"""LARC — Layer-wise Adaptive Rate Clipping/Scaling, parity with
``apex.parallel.LARC`` (apex/parallel/LARC.py:5-107).

The reference wraps any torch optimizer and, before its step, replaces each
param's grad with a trust-ratio-scaled grad:
    ratio = trust_coefficient * |p| / (|g| + wd*|p| + eps)
    clip mode: ratio <- min(ratio/lr, 1) applied to the grad
    scale mode: grad <- grad * ratio
Here the same surgery is a grad transform applied before any
:class:`~apex_tpu.optimizers.base.FusedOptimizer` step.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.optimizers.base import FusedOptimizer, resolve_lr

Tree = Any


def larc_transform_grads(grads: Tree, params: Tree, *, lr: jax.Array,
                         trust_coefficient: float = 0.02, clip: bool = True,
                         eps: float = 1e-8, weight_decay: float = 0.0) -> Tree:
    """The per-tensor grad surgery of LARC.step (LARC.py:78-107)."""
    def per_tensor(g, p):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        p_norm = jnp.sqrt(jnp.sum(p32 * p32))
        g_norm = jnp.sqrt(jnp.sum(g32 * g32))
        ratio = trust_coefficient * p_norm / (
            g_norm + weight_decay * p_norm + eps)
        # reference guards p_norm==0 or g_norm==0 -> ratio 1
        ratio = jnp.where((p_norm > 0) & (g_norm > 0), ratio, 1.0)
        if clip:
            ratio = jnp.minimum(ratio / lr, 1.0)
        out = g32 * ratio
        if weight_decay != 0.0:
            out = out + weight_decay * p32 * ratio
        return out.astype(g.dtype)

    return jax.tree_util.tree_map(per_tensor, grads, params)


class LARC(FusedOptimizer):
    """Optimizer wrapper: ``LARC(FusedSGD(lr=...))`` — same composition shape
    as the reference (`optim = LARC(optim)`)."""

    def __init__(self, inner: FusedOptimizer, *,
                 trust_coefficient: float = 0.02, clip: bool = True,
                 eps: float = 1e-8):
        self.inner = inner
        self.trust_coefficient = trust_coefficient
        self.clip = clip
        self.eps = eps

    def init(self, params: Tree):
        return self.inner.init(params)

    def step(self, grads: Tree, params: Tree, state,
             *, grad_scale: Optional[jax.Array] = None):
        step_no = getattr(state, "step", jnp.zeros((), jnp.int32)) + 1
        lr = resolve_lr(getattr(self.inner, "lr", 1.0), step_no)
        wd = getattr(self.inner, "weight_decay", 0.0)
        grads = larc_transform_grads(
            grads, params, lr=lr,
            trust_coefficient=self.trust_coefficient, clip=self.clip,
            eps=self.eps, weight_decay=wd)
        # weight decay was folded into the LARC-adjusted grad (reference
        # zeroes the optimizer's own wd during its step, LARC.py:88-92)
        saved_wd = getattr(self.inner, "weight_decay", None)
        if saved_wd is not None:
            self.inner.weight_decay = 0.0
        try:
            out = self.inner.step(grads, params, state,
                                  grad_scale=grad_scale)
        finally:
            if saved_wd is not None:
                self.inner.weight_decay = saved_wd
        return out
