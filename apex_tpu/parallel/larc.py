"""LARC — Layer-wise Adaptive Rate Clipping/Scaling, parity with
``apex.parallel.LARC`` (apex/parallel/LARC.py:5-107).

The reference wraps any torch optimizer and, before its step, replaces each
param's grad with a trust-ratio-scaled grad:
    ratio = trust_coefficient * |p| / (|g| + wd*|p| + eps)
    clip mode: ratio <- min(ratio/lr, 1) applied to the grad
    scale mode: grad <- grad * ratio
Here the same surgery is a grad transform applied before any
:class:`~apex_tpu.optimizers.base.FusedOptimizer` step.
"""

from __future__ import annotations

import copy
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.optimizers.base import FusedOptimizer, resolve_lr

Tree = Any


def larc_transform_grads(grads: Tree, params: Tree, *, lr,
                         trust_coefficient: float = 0.02, clip: bool = True,
                         eps: float = 1e-8, weight_decay=0.0) -> Tree:
    """The per-tensor grad surgery of LARC.step (LARC.py:78-107).

    ``weight_decay`` and ``lr`` are scalars, or pytrees of per-leaf scalars
    (the param-group case: each leaf's group decay/lr folds into that
    leaf's LARC ratio — clip divides by the lr the inner step will
    actually apply to that leaf).
    """
    def per_tensor(g, p, wd, lr_):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        p_norm = jnp.sqrt(jnp.sum(p32 * p32))
        g_norm = jnp.sqrt(jnp.sum(g32 * g32))
        ratio = trust_coefficient * p_norm / (g_norm + wd * p_norm + eps)
        # reference guards p_norm==0 or g_norm==0 -> ratio 1
        ratio = jnp.where((p_norm > 0) & (g_norm > 0), ratio, 1.0)
        if clip:
            ratio = jnp.minimum(ratio / lr_, 1.0)
        out = (g32 + wd * p32) * ratio
        return out.astype(g.dtype)

    treedef = jax.tree_util.tree_structure(grads)
    n = treedef.num_leaves

    def full_tree(v):
        # scalar (python number or 0-d array) -> one copy per leaf
        if isinstance(v, (int, float)) or getattr(v, "ndim", None) == 0:
            return jax.tree_util.tree_unflatten(treedef, [v] * n)
        return v

    return jax.tree_util.tree_map(per_tensor, grads, params,
                                  full_tree(weight_decay), full_tree(lr))


class LARC(FusedOptimizer):
    """Optimizer wrapper: ``LARC(FusedSGD(lr=...))`` — same composition shape
    as the reference (`optim = LARC(optim)`)."""

    def __init__(self, inner: FusedOptimizer, *,
                 trust_coefficient: float = 0.02, clip: bool = True,
                 eps: float = 1e-8):
        self.inner = inner
        self.trust_coefficient = trust_coefficient
        self.clip = clip
        self.eps = eps

    def init(self, params: Tree):
        return self.inner.init(params)

    def step(self, grads: Tree, params: Tree, state,
             *, grad_scale: Optional[jax.Array] = None):
        step_no = getattr(state, "step", jnp.zeros((), jnp.int32)) + 1
        lr = resolve_lr(getattr(self.inner, "lr", 1.0), step_no)
        inner = self.inner
        wd = getattr(inner, "weight_decay", 0.0)
        if getattr(inner, "param_groups", None):
            # Per-group weight decay AND lr: resolve each leaf's group
            # values so they fold into that leaf's LARC ratio (clip must
            # divide by the lr the inner step applies to that leaf), and
            # strip decay from the stepped copy so the grouped inner step
            # doesn't re-apply it.
            leaves = jax.tree_util.tree_leaves(params)
            treedef = jax.tree_util.tree_structure(params)
            wd_leaves = [wd] * len(leaves)
            lr_leaves = [lr] * len(leaves)
            for idxs, ov in inner.group_assignments(params):
                for i in idxs:
                    wd_leaves[i] = ov.get("weight_decay", wd)
                    if "lr" in ov:
                        lr_leaves[i] = resolve_lr(ov["lr"], step_no)
            wd = jax.tree_util.tree_unflatten(treedef, wd_leaves)
            lr = jax.tree_util.tree_unflatten(treedef, lr_leaves)
            inner = copy.copy(inner)
            inner.weight_decay = 0.0
            inner.param_groups = [{**g, "weight_decay": 0.0}
                                  for g in inner.param_groups]
        elif wd != 0.0:
            # Weight decay folds into the LARC-adjusted grad (reference
            # zeroes the optimizer's own wd during its step, LARC.py:88-92).
            # Step a shallow copy with wd=0 instead of mutating the inner
            # optimizer — safe across threads and retraces.
            inner = copy.copy(inner)
            inner.weight_decay = 0.0
        grads = larc_transform_grads(
            grads, params, lr=lr,
            trust_coefficient=self.trust_coefficient, clip=self.clip,
            eps=self.eps, weight_decay=wd)
        return inner.step(grads, params, state, grad_scale=grad_scale)
