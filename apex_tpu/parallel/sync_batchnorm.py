"""Synchronized BatchNorm over a mesh axis — the TPU-native redesign of
``apex.parallel.SyncBatchNorm`` (apex/parallel/optimized_sync_batchnorm.py:9-86
+ optimized_sync_batchnorm_kernel.py:7-119 + csrc/welford.cu).

The reference pipeline: local Welford stats -> all_gather(mean,var,count) ->
parallel Welford merge -> normalize; backward all_reduces (sum_dy,
sum_dy_xmu). Here the cross-replica merge is expressed as ``lax.psum`` of
(sum, sum_sq, count) — mathematically identical merged moments, one fused
XLA collective, and the backward collectives fall out of autodiff through
``psum`` automatically (no hand-written backward kernel needed).

Sub-group stat sync (reference ``process_group`` /
``create_syncbn_process_group``, apex/parallel/__init__.py:58-95; groupbn's
CUDA-IPC ``bn_group``) maps to ``axis_index_groups``.

Per-rank batch sizes may differ (reference
two_gpu_test_different_batch_size.py): the count is psum'd alongside the sums.

Conventions match torch BatchNorm for parity: ``momentum`` is the weight of
the *new* observation (running = (1-m)*running + m*batch), and running_var
uses the unbiased estimator while normalization uses the biased one
(optimized_sync_batchnorm_kernel.py:50-58).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import flax.linen as nn

from apex_tpu.ops import pallas_moments as _pallas_moments

Tree = Any


def sync_moments(x: jax.Array, reduce_axes: Sequence[int],
                 axis_name: Optional[str],
                 axis_index_groups=None) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Cross-replica (sum, sum_sq, count) -> (mean, biased var, count).

    The psum of raw moments is the associative form of the reference's
    Welford merge (welford.cu:578 ``welford_parallel``)."""
    local_count = 1.0
    for ax in reduce_axes:
        local_count *= x.shape[ax]
    feature_axis = x.ndim - 1
    c = x.shape[feature_axis]
    if (_pallas_moments.FORCE_PALLAS
            and tuple(reduce_axes) == tuple(range(x.ndim - 1))
            and _pallas_moments.supported(c, int(local_count))):
        # One-pass Pallas two-moment kernel (welford_mean_var_c_last
        # analog). OPT-IN: measured on v5e, XLA's producer-fused
        # convert+reduce beats a standalone stats pass inside a full
        # train step (the kernel forces an extra HBM read and its
        # custom_vjp blocks backward fusion) — kept for workloads where
        # the stats input is already materialized.
        s, ss = _pallas_moments.fused_sum_sumsq(x.reshape(-1, c))
    else:
        x32 = x.astype(jnp.float32)
        s = jnp.sum(x32, axis=tuple(reduce_axes))
        ss = jnp.sum(x32 * x32, axis=tuple(reduce_axes))
    cnt = jnp.asarray(local_count, jnp.float32)
    if axis_name is not None:
        s, ss, cnt = jax.lax.psum(
            (s, ss, cnt), axis_name, axis_index_groups=axis_index_groups)
    mean = s / cnt
    var = ss / cnt - mean * mean
    return mean, var, cnt


class SyncBatchNorm(nn.Module):
    """flax module with torch-BatchNormNd semantics, stats synchronized over
    ``axis_name`` (reference SyncBatchNorm module,
    optimized_sync_batchnorm.py:9-86).

    Input layout: channels last (TPU-native NHWC; the reference's
    ``channel_last=True`` fast path, syncbn kernels ``*_c_last``).
    """

    features: Optional[int] = None   # None: infer from x.shape[-1]
    eps: float = 1e-5
    momentum: float = 0.1            # torch convention: weight of new batch
    affine: bool = True
    track_running_stats: bool = True
    axis_name: Optional[str] = "data"
    axis_index_groups: Optional[Sequence[Sequence[int]]] = None
    use_running_average: Optional[bool] = None
    dtype: Any = jnp.float32
    scale_init: Callable = nn.initializers.ones
    bias_init: Callable = nn.initializers.zeros
    # Opt-in Pallas epilogue: apply the normalize + affine (+ residual
    # add + ReLU, via the call kwargs) as ONE fused pass over x
    # (ops/conv_epilogue.py — the groupbn bn_fwd/bn_addrelu analog).
    # The stats math above the apply is unchanged; with the flag False
    # (default) the module traces bit-identically to the pre-kernel
    # build (pinned by tests/test_kernels.py).
    fused_epilogue: bool = False

    @nn.compact
    def __call__(self, x, use_running_average: Optional[bool] = None,
                 *, residual: Optional[jax.Array] = None,
                 relu: bool = False):
        use_ra = nn.merge_param(
            "use_running_average", self.use_running_average,
            use_running_average)
        feature_axis = x.ndim - 1
        features = (x.shape[feature_axis] if self.features is None
                    else self.features)
        reduce_axes = tuple(i for i in range(x.ndim) if i != feature_axis)

        ra_mean = self.variable(
            "batch_stats", "mean",
            lambda: jnp.zeros((features,), jnp.float32))
        ra_var = self.variable(
            "batch_stats", "var",
            lambda: jnp.ones((features,), jnp.float32))

        if use_ra:
            mean, var = ra_mean.value, ra_var.value
        else:
            # During flax init no mesh axis is bound; compute local stats.
            axis = None if self.is_initializing() else self.axis_name
            mean, var, cnt = sync_moments(
                x, reduce_axes, axis, self.axis_index_groups)
            if self.track_running_stats and not self.is_initializing():
                # unbiased var for running stats (kernel.py:50-58 parity)
                unbiased = var * cnt / jnp.maximum(cnt - 1.0, 1.0)
                m = self.momentum
                ra_mean.value = (1 - m) * ra_mean.value + m * mean
                ra_var.value = (1 - m) * ra_var.value + m * unbiased

        from apex_tpu.ops import conv_epilogue as _conv_epilogue
        if (self.fused_epilogue and not self.is_initializing()
                and _conv_epilogue.supported(features, x.size)):
            # effective per-channel coefficients: the O(C) plain-JAX
            # vectors carry the batch-stat dependence on x for autodiff;
            # the kernel's custom_vjp owns only the elementwise apply
            rstd = jax.lax.rsqrt(var + self.eps)
            if self.affine:
                scale = self.param("scale", self.scale_init,
                                   (features,), jnp.float32)
                bias = self.param("bias", self.bias_init,
                                  (features,), jnp.float32)
                eff_scale = scale * rstd
                eff_shift = bias - mean * eff_scale
            else:
                eff_scale = rstd
                eff_shift = -mean * rstd
            # the kernel writes self.dtype DIRECTLY off its fp32 result —
            # a wider module dtype is not rounded through x.dtype first
            return _conv_epilogue.bn_relu_apply(
                x, eff_scale, eff_shift, residual=residual, relu=relu,
                out_dtype=self.dtype)

        y = (x.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + self.eps)
        if self.affine:
            scale = self.param("scale", self.scale_init,
                               (features,), jnp.float32)
            bias = self.param("bias", self.bias_init,
                              (features,), jnp.float32)
            y = y * scale + bias
        y = y.astype(self.dtype)
        # unfused composition of the epilogue kwargs (the fused path's
        # off-switch twin; a no-op — and an unchanged program — when the
        # kwargs are left at their defaults)
        if residual is not None:
            y = residual + y
        if relu:
            y = nn.relu(y)
        return y


def convert_syncbn_model(module: nn.Module, *, axis_name: str = "data",
                         axis_index_groups=None) -> nn.Module:
    """Analog of ``apex.parallel.convert_syncbn_model``
    (apex/parallel/__init__.py:21-56): rebuild a flax module tree replacing
    ``nn.BatchNorm`` with :class:`SyncBatchNorm`.

    flax modules are immutable dataclasses, so this clones the module with
    substituted definitions. Works for modules whose BatchNorms are direct
    (possibly nested) dataclass fields; for ``@nn.compact`` models, construct
    SyncBatchNorm directly instead (documented limitation).
    """
    if isinstance(module, nn.BatchNorm):
        return SyncBatchNorm(
            features=module.num_features
            if hasattr(module, "num_features") else module.feature_count
            if hasattr(module, "feature_count") else None,
            eps=module.epsilon,
            momentum=1.0 - module.momentum,  # flax momentum is decay
            axis_name=axis_name,
            axis_index_groups=axis_index_groups,
            use_running_average=module.use_running_average,
        )
    changes = {}
    for name, value in vars(module).items():
        if isinstance(value, nn.Module):
            new = convert_syncbn_model(value, axis_name=axis_name,
                                       axis_index_groups=axis_index_groups)
            if new is not value:
                changes[name] = new
    if changes:
        return module.clone(**changes)
    return module


def convert_syncbn_apply(axis_name: str = "data", axis_index_groups=None):
    """Apply-time SyncBN conversion for ANY flax model — including
    ``@nn.compact`` ones whose submodules :func:`convert_syncbn_model`
    cannot reach (they only exist during apply). The other half of the
    reference's ``convert_syncbn_model`` coverage
    (apex/parallel/__init__.py:21-56 walks arbitrary torch module trees).

    Returns a context manager (a flax method interceptor) under which every
    ``nn.BatchNorm.__call__`` syncs its batch statistics over ``axis_name``
    (flax BatchNorm natively understands ``axis_name``/``axis_index_groups``
    — the interceptor just switches them on), keeping the model's own flax
    BN conventions and its exact variable tree (checkpoints stay
    compatible)::

        with parallel.convert_syncbn_apply("data"):
            logits, upd = model.apply(variables, x, mutable=["batch_stats"])

    Use inside shard_map (where ``axis_name`` is bound); init the model
    OUTSIDE the context. Assumes equal per-device batch sizes (flax BN
    pmeans the moments); for differing per-rank batches use
    :class:`SyncBatchNorm`, which psums counts.
    """
    def interceptor(next_fn, args, kwargs, context):
        m = context.module
        if (isinstance(m, nn.BatchNorm)
                and context.method_name == "__call__"
                and getattr(m, "axis_name", None) is None):
            # bound per-apply instance; BatchNorm natively syncs when
            # axis_name is set
            object.__setattr__(m, "axis_name", axis_name)
            object.__setattr__(m, "axis_index_groups", axis_index_groups)
        return next_fn(*args, **kwargs)

    return nn.intercept_methods(interceptor)
