"""Overlap engine: backward/collective pipelining + gradient compression
for the DDP/ZeRO communication paths.

Three legs, composable independently (ROADMAP item 1):

  * **Backward/collective overlap** — :func:`sync_in_backward` wraps the
    parameter tree in per-bucket identity ``custom_vjp``\\ s
    (:mod:`apex_tpu.ops.staged_vjp`) so each bucket's gradient collective
    is an equation *inside the backward graph* that depends only on that
    bucket's cotangents. Bucket *k*'s ``psum`` can therefore be issued
    while bucket *k+1*'s backward compute runs — the reference Apex DDP's
    per-param-hook + side-stream overlap (distributed.py:320-557),
    expressed as dataflow for XLA's latency-hiding scheduler. Bucket
    granularity resolves through ``apex_tpu.tune`` (op ``ddp_overlap``).

  * **Wire compression** — ``reduce_dtype`` (bf16/fp16/int8) casts each
    bucket to a narrow wire format for the collective and returns to the
    original dtype after, halving (16-bit) or quartering (int8)
    ``bytes_wire``. Numerics contract (*pre-scaling*): the full mean
    divide is folded in *before* the cast, so wire-dtype partial sums
    carry mean-gradient magnitude — fp16 wire stays in range even under
    a 2^16 amp loss scale, and a true overflow saturates to Inf which
    the amp scaler's non-finite check catches (the step is skipped and
    the scale backs off — O2/O5 stay loss-scale-correct). bf16 shares
    fp32's exponent range, so bf16 wire is range-safe at any loss scale
    and costs only mantissa (~3 decimal digits on the per-bucket mean).

    The **int8 tier** (ROADMAP item 5) quantizes each predivided bucket
    symmetrically at one per-bucket scale agreed globally pre-collective
    (``pmax`` of the local amax — a scalar, invisible next to the
    payload): ``s = amax * w / (127 - w/2)``, sized so the integer psum
    of ``w`` rounded contributions provably cannot exceed ±127 — XLA
    accumulates s8 collectives IN s8, and wraparound would corrupt
    silently. Accumulation past the wire is fp32 (the dequantize
    multiplies the summed integers by ``s``). The scale is *linear in
    amax*, so a power-of-two loss scale passes through exactly
    (``quantize(L·g)`` returns the same integers with scale ``L·s``) —
    amp's 2^16 scaling and Adasum's scale-invariance both survive the
    wire, pinned by tests/test_lowp.py. Resolution is ~``(127 - w/2)/w``
    levels per replica contribution: honest at 8-replica scale (~15
    levels), marginal past ~64 — the planner's cost model weighs the
    4x wire saving against that, and axis sizes >= 252 (scale bound
    degenerate) are rejected outright.

  * **Adasum** — ``adasum=True`` replaces the mean with adaptive
    summation ("Scaling Distributed Training with Adaptive Summation",
    arXiv:2006.02924): recursive pairwise combination where each pair
    contributes ``(1 - g1·g2/(2|g1|²)) g1 + (1 - g1·g2/(2|g2|²)) g2`` —
    the sum when gradients are orthogonal, the common value (== the mean)
    when they are parallel. Magnitude adapts to gradient agreement, which
    is what lets large-batch data parallel keep per-replica learning
    rates. The operation is scale-invariant (``adasum(S·g) == S·adasum(g)``),
    so amp loss scaling composes: unscaling after reduction is exact.
    Requires a power-of-two axis size; wire cost is ``log2(n) ×
    bytes_in`` (one pair-allreduce per level) vs the ring all-reduce's
    ``2(n-1)/n`` — Adasum trades wire bytes for convergence, and the
    telemetry bill reports it honestly.

Observability: when telemetry is enabled and a step index is supplied,
per-bucket issue/completion host timestamps are recorded around each
staged collective and a ``ddp/overlap_efficiency`` event (fraction of
total per-bucket comm time hidden behind remaining compute) is emitted
per step; ``telemetry summarize`` renders it. Timestamps come from
``jax.debug.callback`` arrival on the host — an estimate of the device
schedule, not a profiler truth, but enough to see overlap collapse when
a config serializes.
"""

from __future__ import annotations

import functools
import math
import threading
import time
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.ops import buckets as _buckets
from apex_tpu.ops import staged_vjp as _staged
from apex_tpu.parallel.mesh import bound_axis_size

Tree = Any

# accepted spellings -> canonical dtype name. The float tiers cast; the
# int8 tier quantizes at a per-bucket symmetric scale agreed globally
# before the collective (see the module numerics contract) — stateless,
# no error feedback, because the scale bound makes the integer psum
# exact. A 32-bit "compression" is the identity and stays rejected.
_WIRE_DTYPES = {
    "bf16": "bfloat16", "bfloat16": "bfloat16",
    "fp16": "float16", "float16": "float16", "half": "float16",
    "int8": "int8",
}

INT8_MAX = 127.0


def resolve_reduce_dtype(reduce_dtype):
    """None, a spelling ('bf16', 'fp16', 'bfloat16', 'float16', 'int8'),
    or a dtype-like -> canonical ``jnp.dtype`` (or None). Anything that
    is not a supported wire format raises."""
    if reduce_dtype is None:
        return None
    name = (reduce_dtype if isinstance(reduce_dtype, str)
            else jnp.dtype(reduce_dtype).name)
    canon = _WIRE_DTYPES.get(name.lower())
    if canon is None:
        raise ValueError(
            f"reduce_dtype must be a wire format "
            f"({sorted(set(_WIRE_DTYPES))}) or None; got {reduce_dtype!r}")
    return jnp.dtype(canon)


def int8_wire_scale(amax, world: int):
    """The int8 tier's per-bucket symmetric scale: ``amax * w /
    (127 - w/2)``.

    Derivation: each replica ships ``q_i = round(y_i / s)`` with
    ``|y_i| <= amax``, so ``|q_i| <= amax/s + 1/2`` and the integer sum
    over ``w`` replicas is bounded by ``w·amax/s + w/2``; solving
    ``= 127`` gives this ``s``. XLA accumulates s8 collectives in s8 —
    the bound is what makes the integer psum exact rather than silently
    wrapped. Linear in amax (loss-scale/Adasum scale-invariance is
    exact under power-of-two multipliers); amax == 0 resolves to 1.0.
    """
    denom = INT8_MAX - 0.5 * world
    if denom < 1.0:
        raise ValueError(
            f"int8 wire: axis size {world} leaves no integer headroom "
            f"(the psum bound 127 - w/2 degenerates past w=252; "
            f"resolution is already marginal past ~64 replicas — use "
            f"bf16 for axes this wide)")
    amax = jnp.asarray(amax, jnp.float32)
    return jnp.where(amax > 0.0, amax * (world / denom),
                     1.0).astype(jnp.float32)


def int8_quantize(y, scale):
    """clip(round(y / s)) in s8 — the clip is belt-and-braces (the scale
    bound already keeps |q| <= 127 - w/2 + 1/2)."""
    q = jnp.round(y.astype(jnp.float32) / scale)
    return jnp.clip(q, -INT8_MAX, INT8_MAX).astype(jnp.int8)


def int8_dequantize(q, scale):
    """Summed integers back to fp32 gradient magnitude — everything past
    the wire accumulates fp32, same as the float tiers."""
    return q.astype(jnp.float32) * scale


def _group_world(axis_name: str, axis_index_groups) -> int:
    """The number of contributions one collective actually sums — the
    GROUP size when axis_index_groups restricts the ring (this is the
    ``w`` in the int8 scale bound; the full axis size would
    over-conservatively shrink the scale)."""
    if axis_index_groups is not None:
        try:
            return len(axis_index_groups[0])
        except Exception:
            pass
    return bound_axis_size(axis_name)


def validate_comm_args(*, reduce_dtype, adasum: bool,
                       allreduce_always_fp32: bool = False,
                       axis_index_groups=None,
                       gradient_average: bool = True) -> None:
    """Shared argument validation for the compressed/adasum paths —
    raised at construction/trace time with the conflict named, not deep
    inside XLA."""
    if reduce_dtype is not None and allreduce_always_fp32:
        raise ValueError(
            "reduce_dtype and allreduce_always_fp32 are contradictory: "
            "one compresses the wire format, the other forces it to "
            "fp32 — pick one")
    if adasum and axis_index_groups is not None:
        raise ValueError(
            "adasum builds its own pairwise axis_index_groups per "
            "recursion level and cannot compose with caller-supplied "
            "groups — run adasum over a dedicated mesh axis instead")
    if adasum and not gradient_average:
        raise ValueError(
            "adasum replaces the gradient combiner entirely — it cannot "
            "honor gradient_average=False sum semantics (shard "
            "contributions would come out ~world x too small with no "
            "diagnostic); use a plain psum for summed contributions")


def wire_multiplier(world: int, *, adasum: bool) -> float:
    """Per-device interconnect bytes per payload byte: ring all-reduce
    ``2(n-1)/n``, Adasum ``log2(n)`` (one pair-allreduce per level)."""
    if world <= 1:
        return 0.0
    if adasum:
        return float(math.log2(world))
    return 2.0 * (world - 1) / world


# ---------------------------------------------------------------------------
# overlap-efficiency tracker (host side)
# ---------------------------------------------------------------------------

def overlap_efficiency(issues: dict, dones: dict) -> Optional[float]:
    """Fraction of per-bucket comm time hidden behind remaining backward
    work, from per-bucket issue/done timestamps (``{bucket: t}``).

    A bucket's in-flight window counts as *hidden* only up to the latest
    OTHER bucket's issue falling inside it — another issue landing while
    this collective is in flight is direct evidence the backward was
    still producing work concurrently. This makes the two failure modes
    read as failures: a serialized schedule (compute blocked on each
    collective, so no issue ever lands inside another's window) scores
    ~0, and the all-comm-after-backward barrier (issues clustered at the
    step tail with nothing left to compute) also scores ~0. Returns
    None when no bucket has a positive window. Clamped to [0, 1]."""
    common = [b for b in dones if b in issues]
    total = sum(dones[b] - issues[b] for b in common)
    if total <= 0.0:
        return None
    issue_times = sorted(issues[b] for b in common)
    hidden = 0.0
    for b in common:
        t0, t1 = issues[b], dones[b]
        inside = [t for t in issue_times if t0 < t <= t1]
        if inside:
            hidden += inside[-1] - t0
    return min(1.0, max(0.0, hidden / total))


class _OverlapTracker:
    """Collects per-bucket issue/done host timestamps and emits one
    ``ddp/overlap_efficiency`` event per step once every bucket reported.

    Under shard_map the callbacks fire once per shard; the first arrival
    per (step, bucket, phase) wins and replicas are ignored, so the
    emitted series needs no downstream dedup. The metric is
    :func:`overlap_efficiency` over the step's bucket timestamps."""

    _MAX_STEPS = 64     # bound memory if done-marks never complete

    def __init__(self):
        self._lock = threading.Lock()
        self._steps: dict = {}

    def mark(self, step: int, bucket: int, n_buckets: int,
             phase: str) -> None:
        now = time.perf_counter()
        emit = None
        with self._lock:
            rec = self._steps.setdefault(step, {"issue": {}, "done": {}})
            d = rec[phase]
            if bucket in d:
                return      # per-shard replica: first arrival wins
            d[bucket] = now
            if (phase == "done" and len(rec["done"]) >= n_buckets
                    and len(rec["issue"]) >= n_buckets):
                emit = rec
                self._steps.pop(step, None)
            elif len(self._steps) > self._MAX_STEPS:
                self._steps.pop(next(iter(self._steps)), None)
        if emit is not None:
            self._emit(step, emit)

    @staticmethod
    def _emit(step: int, rec: dict) -> None:
        eff = overlap_efficiency(rec["issue"], rec["done"])
        if eff is None:
            return
        from apex_tpu import telemetry
        telemetry.record("ddp/overlap_efficiency", eff, step=step,
                         meta={"buckets": len(rec["done"])})

    def reset(self) -> None:
        with self._lock:
            self._steps.clear()


_tracker = _OverlapTracker()


def _mark_cb(_dep, step, *, bucket: int, n_buckets: int,
             phase: str) -> None:
    import numpy as _np
    _tracker.mark(int(_np.asarray(step)), bucket, n_buckets, phase)


def _mark(dep: jax.Array, step, bucket: int, n_buckets: int,
          phase: str) -> None:
    """Record a host timestamp ordered after ``dep`` materializes — the
    issue/done brackets around one bucket's collective."""
    jax.debug.callback(
        functools.partial(_mark_cb, bucket=bucket, n_buckets=n_buckets,
                          phase=phase),
        dep.reshape(-1)[0], step)


# ---------------------------------------------------------------------------
# flat-bucket reductions
# ---------------------------------------------------------------------------

def adasum_flat(flat: jax.Array, axis_name: str, *,
                reduce_dtype=None) -> jax.Array:
    """Adaptive summation of ``flat`` across the mesh axis by recursive
    pairwise combination (arXiv:2006.02924, Alg. 1 lifted onto
    ``axis_index_groups``).

    Level *l* pairs devices whose axis index differs in bit *l*; the pair
    total arrives via a 2-member grouped ``psum`` and the partner's
    contribution is recovered as ``total - own``. Both pair members
    compute the combination from the SAME quantized views (own is read
    back through the wire dtype when compressing), and the formula is
    symmetric, so the result stays replica-consistent bitwise. Dot
    products and the combination always run in fp32.

    int8 wire: each level quantizes at the PAIR's agreed scale
    (``pmax`` of the local amax over the 2-member groups, w=2 in the
    scale bound — so ``s = amax/62.5``, two rounded contributions can
    never overflow the s8 psum) and recovers the partner in exact
    integer arithmetic; no 0.5 pre-halving is needed because the scale
    owns the range. Scale linearity keeps the combination's
    scale-invariance exact under power-of-two loss scales."""
    world = bound_axis_size(axis_name)
    if world == 1:
        return flat
    if world & (world - 1):
        raise ValueError(
            f"adasum requires a power-of-two axis size (recursive "
            f"pairwise halving); axis {axis_name!r} has size {world}")
    wire_dt = resolve_reduce_dtype(reduce_dtype)
    acc = flat.astype(jnp.float32)
    for level in range(world.bit_length() - 1):
        stride = 1 << level
        span = stride * 2
        groups = [[b * span + j, b * span + j + stride]
                  for b in range(world // span) for j in range(stride)]
        if wire_dt == jnp.int8:
            # pair-scoped scale agreement (w=2 bound); own is the
            # dequantized OWN integers, so both members combine the
            # same quantized views — integers <= 127 are exact in f32,
            # making total - own an exact partner recovery
            amax = jax.lax.pmax(jnp.max(jnp.abs(acc)), axis_name,
                                axis_index_groups=groups)
            scale = int8_wire_scale(amax, 2)
            q = int8_quantize(acc, scale)
            total_q = jax.lax.psum(q, axis_name, axis_index_groups=groups)
            own = int8_dequantize(q, scale)
            other = int8_dequantize(total_q, scale) - own
        else:
            if wire_dt is None:
                wire = acc
            else:
                # per-level pre-scaling: halve before the cast so the
                # pair psum of two near-max values stays in the wire
                # dtype's range (fp16: two elements at 40k would sum to
                # Inf raw); the combination is scale-invariant and
                # linear, so doubling the result after restores
                # magnitude exactly (x0.5/x2 are power-of-two exact in
                # every float format)
                wire = (acc * 0.5).astype(wire_dt)
            total = jax.lax.psum(wire, axis_name, axis_index_groups=groups)
            own = wire.astype(jnp.float32)
            other = total.astype(jnp.float32) - own
        dot = jnp.sum(own * other)
        n_own = jnp.sum(own * own)
        n_oth = jnp.sum(other * other)
        a = jnp.where(n_own > 0.0, dot / (2.0 * n_own), 0.0)
        b = jnp.where(n_oth > 0.0, dot / (2.0 * n_oth), 0.0)
        acc = (1.0 - a) * own + (1.0 - b) * other
        if wire_dt is not None and wire_dt != jnp.int8:
            # undo the float tiers' x0.5 pre-halving (int8 never
            # halved: its scale owns the range)
            acc = acc * 2.0
    return acc.astype(flat.dtype)


def compression_divides(*, world: int, reduce_dtype, adasum: bool,
                        gradient_average: bool,
                        gradient_predivide_factor: float,
                        ) -> Tuple[float, float]:
    """(predivide, postdivide) for one bucket reduction.

    Base semantics mirror ``allreduce_gradients``: divide by
    ``gradient_predivide_factor`` before and ``world / factor`` after
    when averaging. With ``reduce_dtype`` the FULL mean folds into the
    pre-cast divide (pre-scaling — see the module numerics contract) so
    postdivide collapses to 1; a pure sum (``gradient_average=False``)
    pre-scales by ``world`` and multiplies it back after. Adasum ignores
    averaging knobs entirely: its magnitude is the adaptive point of the
    algorithm (compression pre-scaling happens per level inside
    :func:`adasum_flat`, scale-invariance makes it neutral)."""
    if adasum:
        return 1.0, 1.0
    predivide = gradient_predivide_factor if gradient_average else 1.0
    postdivide = (world / gradient_predivide_factor
                  if gradient_average else 1.0)
    if reduce_dtype is not None:
        predivide = predivide * postdivide if gradient_average else float(
            world)
        postdivide = 1.0 if gradient_average else 1.0 / world
    return predivide, postdivide


def reduce_bucket(flat: jax.Array, axis_name: str, *,
                  message_size: int = 0,
                  reduce_dtype=None, adasum: bool = False,
                  predivide: float = 1.0, postdivide: float = 1.0,
                  axis_index_groups=None,
                  bucket_index: int = 0, n_buckets: int = 1,
                  telemetry_step=None, track: bool = False,
                  health_name: Optional[str] = None) -> jax.Array:
    """Reduce one flat same-dtype bucket across ``axis_name`` under the
    engine's compression/adasum options. Returns the reduced bucket in
    the input dtype. ``track=True`` brackets the collective with the
    overlap-tracker timestamps (requires a ``telemetry_step``)."""
    orig_dtype = flat.dtype
    wire_dt = resolve_reduce_dtype(reduce_dtype)
    do_track = track and telemetry_step is not None
    if do_track:
        _mark(flat, telemetry_step, bucket_index, n_buckets, "issue")
    if predivide != 1.0:
        flat = flat / predivide
    # named scope: both DDP paths (post-hoc allreduce_gradients and the
    # staged backward) reduce through here, so every bucket collective
    # carries the apex_ddp_allreduce tag in XLA metadata — the join key
    # pyprof.capture attributes comm time by. Metadata only: the traced
    # program (and the defaults' jaxpr-equality contract) is unchanged.
    with jax.named_scope("apex_ddp_allreduce"):
        if adasum:
            red = adasum_flat(flat, axis_name, reduce_dtype=wire_dt)
        else:
            scale = None
            if wire_dt == jnp.int8:
                # int8 tier: agree one per-bucket symmetric scale
                # globally (pmax of a scalar — invisible next to the
                # payload), quantize the predivided bucket, ship s8.
                # The scale bound makes the integer psum exact.
                w = _group_world(axis_name, axis_index_groups)
                amax = jax.lax.pmax(
                    jnp.max(jnp.abs(flat.astype(jnp.float32))),
                    axis_name, axis_index_groups=axis_index_groups)
                scale = int8_wire_scale(amax, w)
                wire = int8_quantize(flat, scale)
            else:
                wire = flat if wire_dt is None or flat.dtype == wire_dt \
                    else flat.astype(wire_dt)
            psum = functools.partial(jax.lax.psum, axis_name=axis_name,
                                     axis_index_groups=axis_index_groups)
            if 0 < message_size < wire.shape[0]:
                # oversize single leaf: chunked psum for message sizing
                red = jnp.concatenate(
                    [psum(wire[i:i + message_size])
                     for i in range(0, wire.shape[0], message_size)])
            else:
                red = psum(wire)
            if scale is not None:
                red = int8_dequantize(red, scale)
            elif wire_dt is not None and red.dtype != jnp.float32:
                # fp32 accumulation of everything downstream of the
                # wire: postdivide, health norms, the caller's
                # unscale/update
                red = red.astype(jnp.float32)
    if postdivide != 1.0:
        red = red / postdivide
    if do_track:
        _mark(red, telemetry_step, bucket_index, n_buckets, "done")
    if health_name is not None:
        from apex_tpu import telemetry
        from apex_tpu.telemetry import health as _health
        if _health.enabled():
            telemetry.record(
                health_name,
                jnp.sqrt(jnp.sum(jnp.square(red.astype(jnp.float32)))),
                step=telemetry_step)
    if red.dtype != orig_dtype:
        red = red.astype(orig_dtype)
    return red


# ---------------------------------------------------------------------------
# the staged-backward entry point
# ---------------------------------------------------------------------------

def record_comm_event(axis_name: str, leaves: Sequence[jax.Array], *,
                      world: int, n_buckets: int, reduce_dtype,
                      adasum: bool, allreduce_always_fp32: bool = False,
                      overlap: bool = False,
                      axis_index_groups=None) -> None:
    """Static telemetry: the per-device bytes this reduction will move
    per step, with the wire bill under the active compression/algorithm.
    Shared by ``allreduce_gradients`` and :func:`sync_in_backward` so the
    two paths bill identically. ``axis_index_groups`` restricts the ring
    to a replica subset: the wire bill uses the GROUP world, matching
    the jaxpr comm walker's grouped accounting."""
    from apex_tpu import telemetry
    if not telemetry.enabled():
        return
    import numpy as _np
    if axis_index_groups is not None:
        try:
            world = len(axis_index_groups[0]) or world
        except Exception:
            pass
    wire_dt = resolve_reduce_dtype(reduce_dtype)
    def itemsize(leaf):
        if wire_dt is not None:
            return wire_dt.itemsize
        if allreduce_always_fp32:
            return 4
        return _np.dtype(leaf.dtype).itemsize
    nbytes = sum(int(_np.prod(leaf.shape) if leaf.shape else 1)
                 * itemsize(leaf) for leaf in leaves)
    meta = {"axis": axis_name, "primitive": "psum", "count": n_buckets,
            "world": world,
            "bytes_wire": round(nbytes * wire_multiplier(world,
                                                         adasum=adasum))}
    if wire_dt is not None:
        meta["reduce_dtype"] = wire_dt.name
    if adasum:
        meta["adasum"] = True
    if overlap:
        meta["overlap"] = True
    telemetry.record_static(
        f"ddp/{axis_name}/allreduce_bytes", nbytes, meta=meta,
        dedup_key=(axis_name, nbytes, n_buckets, world, bool(adasum),
                   None if wire_dt is None else wire_dt.name,
                   bool(overlap)))


def sync_in_backward(params: Tree, axis_name: str = "data", *,
                     message_size: Optional[int] = None,
                     reduce_dtype=None, adasum: bool = False,
                     allreduce_always_fp32: bool = False,
                     gradient_average: bool = True,
                     gradient_predivide_factor: float = 1.0,
                     axis_index_groups=None,
                     telemetry_step=None) -> Tree:
    """Identity on ``params``; their cotangents come back bucket-reduced.

    Call INSIDE the loss function (within the shard_map/pmap context that
    binds ``axis_name``), on the params the model will consume::

        def loss_fn(params, batch):
            params = overlap.sync_in_backward(params, "data")
            return model_loss(params, batch)

        grads = jax.grad(loss_fn)(params, batch)   # already averaged

    Each bucket's collective is staged into the backward at the point its
    gradients finalize (see :mod:`apex_tpu.ops.staged_vjp`), so XLA can
    overlap bucket *k*'s ``psum`` with bucket *k+1*'s backward compute.
    Reduction semantics (bucketing, averaging, predivide, fp32 upcast,
    ``reduce_dtype`` / ``adasum``) match ``allreduce_gradients`` — the
    two paths are interchangeable numerically; this one overlaps.

    ``message_size=None`` resolves through ``apex_tpu.tune`` (op
    ``ddp_overlap``; the frozen 2**23 under the default ``off`` policy).
    """
    leaves, treedef = jax.tree_util.tree_flatten(params)
    if not leaves:
        return params
    world = bound_axis_size(axis_name)
    wire_dt = resolve_reduce_dtype(reduce_dtype)
    validate_comm_args(reduce_dtype=wire_dt, adasum=adasum,
                       allreduce_always_fp32=allreduce_always_fp32,
                       axis_index_groups=axis_index_groups,
                       gradient_average=gradient_average)
    from apex_tpu import tune
    if message_size is None:
        total = sum(int(leaf.size) for leaf in leaves)
        message_size = tune.ddp_overlap_message_size(total=total,
                                                     world=world)
    elif message_size < 0:
        raise ValueError(
            f"sync_in_backward: message_size must be >= 1 (or 0 to "
            f"disable bucketing, or None to resolve via apex_tpu.tune); "
            f"got {message_size}")
    buckets = _buckets.assign_buckets(leaves, message_size)
    tune.warn_bucket_count("ddp", len(buckets), message_size)
    record_comm_event(axis_name, leaves, world=world,
                      n_buckets=len(buckets), reduce_dtype=wire_dt,
                      adasum=adasum,
                      allreduce_always_fp32=allreduce_always_fp32,
                      overlap=True, axis_index_groups=axis_index_groups)
    predivide, postdivide = compression_divides(
        world=world, reduce_dtype=wire_dt, adasum=adasum,
        gradient_average=gradient_average,
        gradient_predivide_factor=gradient_predivide_factor)
    from apex_tpu import telemetry
    track = telemetry.enabled()

    def make_transform(bi: int, n: int):
        def transform(cotangents: Tuple) -> List[jax.Array]:
            flat, spec = _buckets.flatten_tensors(list(cotangents))
            orig_dtype = flat.dtype
            if allreduce_always_fp32 and orig_dtype != jnp.float32:
                flat = flat.astype(jnp.float32)
            flat = reduce_bucket(
                flat, axis_name, message_size=message_size,
                reduce_dtype=wire_dt, adasum=adasum,
                predivide=predivide, postdivide=postdivide,
                axis_index_groups=axis_index_groups,
                bucket_index=bi, n_buckets=n,
                telemetry_step=telemetry_step, track=track,
                health_name=f"health/ddp/bucket{bi}/grad_norm")
            if flat.dtype != orig_dtype:
                flat = flat.astype(orig_dtype)
            return _buckets.unflatten_tensors(flat, spec)
        return transform

    wrapped = _staged.apply_staged(
        leaves, [idxs for _, idxs in buckets], make_transform)
    return jax.tree_util.tree_unflatten(treedef, wrapped)
