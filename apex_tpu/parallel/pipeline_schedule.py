"""Microbatch pipeline schedules — GPipe and 1F1B as ONE shard_map
program over the ``pipe`` mesh axis.

The sibling :mod:`~apex_tpu.parallel.pipeline` module is the
autodiff-scheduled GPipe forward (``pipeline_apply``): hand it the block
stack and let ``jax.grad`` transpose the ticks. That is the right shape
for a loss the caller differentiates, but the schedule it yields is
whatever autodiff emits — it cannot express 1F1B, and its accumulation
order is not the trainer's. This module is the TRAINER-grade tier: the
schedule is an explicit static timetable (which microbatch every stage
forwards/backwards at every tick), baked into a ``lax.scan`` whose body
does one masked forward, one masked recompute-backward (``jax.vjp``),
and two ``ppermute`` hops (activations right, cotangents left). GPipe
and 1F1B are the SAME executor with different tables.

Why a timetable: per-stage gradients accumulate in ascending-microbatch
order on every stage under both schedules (idle slots contribute exact
float zeros — both cotangents are zeroed, so the pulled gradients are
zeros, and ``acc + 0`` is the identity), which makes GPipe, 1F1B, and
the single-stage :func:`accumulate_grads` baseline produce
bitwise-identical sums — the equality tests/test_pipeline_schedule.py
pins. 1F1B's classic win — at most ``stages - rank`` activations live
per stage instead of all M — is a property of the TABLE (pinned by
test); this executor keeps M-slot buffers either way (CI shapes are
small; a ring buffer is a follow-up, the table already proves the
bound).

Inert default: at ``pipe`` axis size 1, :func:`pipelined_grads` does not
build a degenerate one-stage pipeline — it literally calls
:func:`accumulate_grads` on the composed (embed → stage → loss)
function, so a pp=1 layout traces the identical jaxpr to the
non-pipelined trainer (the jaxpr-equality pin, same doctrine as every
other opt-in axis in this repo).

Masking is ``where``, not ``lax.cond``: every tick pays forward +
recompute + backward on every stage (the repo's masked-pipeline idiom —
``pipeline_apply`` does the same). That is the uniform-program price of
SPMD-safe control flow: a ``cond``-gated send is exactly the
schedule-divergence bug lint rule APX209 exists to catch.

Bubble math: both tables run ``T = 2*(M + P - 1)`` ticks and every
stage is busy for exactly ``2*M`` of them, so each stage idles
``2*(P - 1)`` slots and the bubble fraction is ``(P - 1)/(M + P - 1)``
(:func:`bubble_fraction` — the analytic term ``plan.cost`` prices and
``benchmarks/plan_vs_hand.py`` prints).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, List, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.parallel.mesh import bound_axis_size

Tree = Any

SCHEDULES = ("gpipe", "1f1b")


# ---------------------------------------------------------------------------
# timetables (pure Python — unit-testable against the analytic formulas)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Timetable:
    """A static pipeline schedule: ``fwd[t][r]`` / ``bwd[t][r]`` name
    the microbatch stage ``r`` forwards / backwards at tick ``t``
    (``-1`` = idle slot). Forward and backward never share a (tick,
    stage) slot in either shipped schedule (a parity argument the tests
    re-verify exhaustively), so one masked executor tick hosts both."""

    name: str
    stages: int
    microbatches: int
    fwd: Tuple[Tuple[int, ...], ...]    # [ticks][stages]
    bwd: Tuple[Tuple[int, ...], ...]

    @property
    def ticks(self) -> int:
        return len(self.fwd)

    def busy_slots(self, rank: int) -> int:
        """Non-idle ticks for one stage — ``2*M`` in both schedules."""
        return (sum(1 for t in range(self.ticks) if self.fwd[t][rank] >= 0)
                + sum(1 for t in range(self.ticks)
                      if self.bwd[t][rank] >= 0))

    def bubble_slots(self, rank: int) -> int:
        """Idle ticks for one stage: ``ticks - busy`` — analytically
        ``2*(stages - 1)``, independent of the rank and the schedule."""
        return self.ticks - self.busy_slots(rank)

    def max_in_flight(self, rank: int) -> int:
        """Peak microbatches forwarded-but-not-yet-backwarded on one
        stage — the activation high-water mark. GPipe holds all M;
        1F1B holds ``min(stages - rank, M)`` (its whole point)."""
        live = peak = 0
        for t in range(self.ticks):
            if self.fwd[t][rank] >= 0:
                live += 1
                peak = max(peak, live)
            if self.bwd[t][rank] >= 0:
                live -= 1
        return peak


def _empty(stages: int, microbatches: int):
    if stages < 1 or microbatches < 1:
        raise ValueError(
            f"pipeline schedule needs stages >= 1 and microbatches >= 1, "
            f"got stages={stages}, microbatches={microbatches}")
    ticks = 2 * (microbatches + stages - 1)
    return ([[-1] * stages for _ in range(ticks)],
            [[-1] * stages for _ in range(ticks)])


def _freeze(name, stages, microbatches, fwd, bwd) -> Timetable:
    return Timetable(name=name, stages=stages, microbatches=microbatches,
                     fwd=tuple(tuple(r) for r in fwd),
                     bwd=tuple(tuple(r) for r in bwd))


def schedule_gpipe(stages: int, microbatches: int) -> Timetable:
    """All-forward-then-all-backward: stage ``r`` forwards microbatch
    ``j`` at tick ``r + j`` and backwards it at
    ``(M + P - 1) + (P - 1 - r) + j`` (the drain starts at the last
    stage the tick after the last forward arrives there)."""
    P, M = stages, microbatches
    fwd, bwd = _empty(P, M)
    for r in range(P):
        for j in range(M):
            fwd[r + j][r] = j
            bwd[(M + P - 1) + (P - 1 - r) + j][r] = j
    return _freeze("gpipe", P, M, fwd, bwd)


def schedule_1f1b(stages: int, microbatches: int) -> Timetable:
    """One-forward-one-backward: stage ``r`` warms up with
    ``min(P - r, M)`` forwards (microbatch ``j`` at tick ``r + j``),
    then alternates — steady-state forwards land at ``2j + r`` and
    every backward at ``2P - 1 - r + 2j``, so forward/backward slots
    interleave by parity and at most ``P - r`` activations are ever
    live per stage. Same ``2*(M + P - 1)`` ticks as GPipe — 1F1B buys
    memory, not bubble."""
    P, M = stages, microbatches
    fwd, bwd = _empty(P, M)
    for r in range(P):
        for j in range(M):
            fwd[r + j if j < P - r else 2 * j + r][r] = j
            bwd[2 * P - 1 - r + 2 * j][r] = j
    return _freeze("1f1b", P, M, fwd, bwd)


def make_schedule(name: str, stages: int, microbatches: int) -> Timetable:
    """Schedule factory by name (:data:`SCHEDULES`); loud on unknowns."""
    if name == "gpipe":
        return schedule_gpipe(stages, microbatches)
    if name == "1f1b":
        return schedule_1f1b(stages, microbatches)
    raise ValueError(
        f"unknown pipeline schedule {name!r}; known: {SCHEDULES}")


def bubble_fraction(stages: int, microbatches: int) -> float:
    """Idle fraction of the (ticks x stages) grid:
    ``(P - 1) / (M + P - 1)`` — the closed form both timetables realize
    slot-for-slot and ``plan.cost`` prices as ``bubble_s``."""
    return (stages - 1) / (microbatches + stages - 1)


def stage_partition(layers: int, stages: int) -> List[Tuple[int, int]]:
    """Contiguous ``[start, stop)`` block ranges per stage, balanced
    (earlier stages absorb the remainder). The planner only emits
    evenly-divisible partitions (``search._shape_reason``); the general
    form serves hand layouts."""
    if stages < 1 or layers < stages:
        raise ValueError(
            f"cannot split {layers} layers into {stages} stages")
    base, extra = divmod(layers, stages)
    out, start = [], 0
    for r in range(stages):
        stop = start + base + (1 if r < extra else 0)
        out.append((start, stop))
        start = stop
    return out


# ---------------------------------------------------------------------------
# gradient accumulation (the single-stage baseline, ONE definition —
# plan.adapters delegates here so the pp=1 jaxpr pin is by construction)
# ---------------------------------------------------------------------------

def accumulate_grads(loss_of: Callable, params: Tree, toks, mb: int):
    """value-and-grad over ``mb`` sequential microbatches of the local
    batch (the gradient-accumulation no_sync pattern: ONE collective
    per step, issued by the caller on the averaged grads)."""
    if mb == 1:
        return jax.value_and_grad(loss_of)(params, toks)
    b_loc = toks.shape[0]
    chunks = toks.reshape((mb, b_loc // mb) + toks.shape[1:])

    def body(carry, t):
        acc_l, acc_g = carry
        loss, g = jax.value_and_grad(loss_of)(params, t)
        return (acc_l + loss,
                jax.tree_util.tree_map(jnp.add, acc_g, g)), None

    zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
    (loss_sum, grad_sum), _ = jax.lax.scan(
        body, (jnp.zeros((), jnp.float32), zeros), chunks)
    inv = 1.0 / mb
    return loss_sum * inv, jax.tree_util.tree_map(
        lambda g: g * inv, grad_sum)


# ---------------------------------------------------------------------------
# the executor
# ---------------------------------------------------------------------------

def pipelined_grads(embed_fn: Callable, stage_fn: Callable,
                    loss_fn: Callable, stage_params: Tree, rest: Tree,
                    toks, microbatch: int, *, axis_name: str = "pipe",
                    schedule: str = "1f1b"):
    """Pipeline-parallel microbatched value-and-grad, per-device under
    ``shard_map`` over ``axis_name``.

    The model splits into three caller-supplied pieces:

      * ``embed_fn(rest, toks_mb) -> acts`` — the input-side compute
        (embeddings). Runs on every stage each tick (uniform program);
        only stage 0's result enters the pipeline, so its ``rest``
        grads are exact zeros off stage 0 (the ``where`` transpose).
      * ``stage_fn(stage_params, acts) -> acts`` — THIS stage's block
        run (``stage_params`` is the stacked-block shard, leading dim
        = layers/stages — see ``pipeline.lm_stack_blocks``).
      * ``loss_fn(rest, acts, toks_mb) -> scalar`` — the output-side
        compute (final norm + head + loss). Masked to the last stage,
        so head/norm grads are exact zeros everywhere else.

    ``rest`` grads are therefore stage-DISJOINT and one ``psum`` over
    the pipe axis reassembles them exactly (``x + 0``); stage grads stay
    sharded. Returns ``(loss, (stage_grads, rest_grads))`` with the
    same microbatch-mean normalization as :func:`accumulate_grads` —
    and at axis size 1 it IS :func:`accumulate_grads` on the composed
    function (the inert-default jaxpr pin).
    """
    world = bound_axis_size(axis_name)
    mb = int(microbatch)
    if world == 1:
        def loss_of(pr, t):
            p, r = pr
            return loss_fn(r, stage_fn(p, embed_fn(r, t)), t)
        return accumulate_grads(loss_of, (stage_params, rest), toks, mb)

    table = make_schedule(schedule, world, mb)
    fwd_tbl = jnp.asarray(table.fwd, jnp.int32)    # [ticks, stages]
    bwd_tbl = jnp.asarray(table.bwd, jnp.int32)
    rank = jax.lax.axis_index(axis_name)
    is_last = rank == world - 1
    b_loc = toks.shape[0]
    if b_loc % mb:
        raise ValueError(
            f"local batch {b_loc} not divisible by microbatch={mb}")
    chunks = toks.reshape((mb, b_loc // mb) + toks.shape[1:])

    def rank_fwd(p_loc, rst, act_in, t):
        x0 = embed_fn(rst, t)
        h = stage_fn(p_loc, jnp.where(rank == 0, x0, act_in))
        return h, loss_fn(rst, h, t)

    act_sds = jax.eval_shape(embed_fn, rest, chunks[0])
    # zero-initialized M-slot buffers: idle-tick recomputes run on
    # finite inputs (NaN-safe), and their zeroed cotangents pull exact
    # zero gradients — the accumulation identity the bitwise pin needs
    act0 = jnp.zeros((mb,) + act_sds.shape, act_sds.dtype)
    cot0 = jnp.zeros_like(act0)
    right = [(i, i + 1) for i in range(world - 1)]
    left = [(i + 1, i) for i in range(world - 1)]

    def tick(carry, rows):
        gp, gr, loss_acc, act_buf, cot_buf = carry
        row_f, row_b = rows
        jf = jnp.take(row_f, rank, mode="clip")
        jb = jnp.take(row_b, rank, mode="clip")
        is_f, is_b = jf >= 0, jb >= 0
        # -- forward: this stage's scheduled microbatch (idle slots run
        #    the same compute on slot 0 and mask every effect)
        sf = jnp.clip(jf, 0, mb - 1)
        t_f = jax.lax.dynamic_index_in_dim(chunks, sf, keepdims=False)
        a_f = jax.lax.dynamic_index_in_dim(act_buf, sf, keepdims=False)
        h, mb_loss = rank_fwd(stage_params, rest, a_f, t_f)
        loss_acc = loss_acc + jnp.where(is_f & is_last, mb_loss, 0.0)
        send_f = jnp.where(is_f, h, jnp.zeros_like(h))
        # -- backward: recompute-and-transpose of the scheduled
        #    microbatch. Cotangents: the banked downstream cotangent on
        #    interior stages, dL/dL = 1 on the last; both zeroed on
        #    idle slots -> exact zero grads
        sb = jnp.clip(jb, 0, mb - 1)
        t_b = jax.lax.dynamic_index_in_dim(chunks, sb, keepdims=False)
        a_b = jax.lax.dynamic_index_in_dim(act_buf, sb, keepdims=False)
        c_b = jax.lax.dynamic_index_in_dim(cot_buf, sb, keepdims=False)
        (_, l_b), pull = jax.vjp(
            lambda p, r, a: rank_fwd(p, r, a, t_b),
            stage_params, rest, a_b)
        dh = jnp.where(is_b & ~is_last, c_b, jnp.zeros_like(c_b))
        dl = jnp.where(is_b & is_last, jnp.ones_like(l_b),
                       jnp.zeros_like(l_b))
        dp, dr, da = pull((dh, dl))
        gp = jax.tree_util.tree_map(jnp.add, gp, dp)
        gr = jax.tree_util.tree_map(jnp.add, gr, dr)
        send_b = jnp.where(is_b, da, jnp.zeros_like(da))
        # -- wire: activations hop right, cotangents hop left (every
        #    tick, masked — a cond-gated send would be APX209)
        recv_f = jax.lax.ppermute(send_f, axis_name, right)
        recv_b = jax.lax.ppermute(send_b, axis_name, left)
        # bank arrivals into the SENDER's scheduled microbatch slot
        jf_l = jnp.take(row_f, rank - 1, mode="clip")
        sl_f = jnp.clip(jf_l, 0, mb - 1)
        keep_f = jax.lax.dynamic_index_in_dim(act_buf, sl_f,
                                              keepdims=False)
        act_buf = jax.lax.dynamic_update_index_in_dim(
            act_buf,
            jnp.where((rank > 0) & (jf_l >= 0), recv_f, keep_f),
            sl_f, 0)
        jb_r = jnp.take(row_b, rank + 1, mode="clip")
        sl_b = jnp.clip(jb_r, 0, mb - 1)
        keep_b = jax.lax.dynamic_index_in_dim(cot_buf, sl_b,
                                              keepdims=False)
        cot_buf = jax.lax.dynamic_update_index_in_dim(
            cot_buf,
            jnp.where((rank < world - 1) & (jb_r >= 0), recv_b, keep_b),
            sl_b, 0)
        return (gp, gr, loss_acc, act_buf, cot_buf), ()

    carry0 = (jax.tree_util.tree_map(jnp.zeros_like, stage_params),
              jax.tree_util.tree_map(jnp.zeros_like, rest),
              jnp.zeros((), jnp.float32), act0, cot0)
    (gp, gr, loss_sum, _, _), _ = jax.lax.scan(
        tick, carry0, (fwd_tbl, bwd_tbl))
    # stage-disjoint rest grads reassemble exactly; the loss lives on
    # the last stage only (its accumulation mask), so the same psum
    # broadcasts it
    gr = jax.tree_util.tree_map(
        lambda g: jax.lax.psum(g, axis_name), gr)
    loss_sum = jax.lax.psum(loss_sum, axis_name)
    inv = 1.0 / mb
    return loss_sum * inv, (
        jax.tree_util.tree_map(lambda g: g * inv, gp),
        jax.tree_util.tree_map(lambda g: g * inv, gr))
