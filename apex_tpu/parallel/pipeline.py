"""GPipe-style pipeline parallelism over a mesh axis — the fourth
classic parallelism axis alongside data (DDP/ZeRO), tensor
(tensor_parallel.py), and sequence (ring/Ulysses). The reference
framework has none of these beyond data parallelism; this follows the
standard TPU formulation: each device on the ``pipe`` axis owns a STAGE
(a contiguous run of transformer blocks, params stacked on a leading
dim), microbatches tick through the pipeline inside one ``lax.scan``,
and activations hop stage-to-stage via ``ppermute`` — compiler-visible
control flow, no host scheduling. Backward needs no hand-written
schedule: autodiff transposes the ppermute shifts into reverse shifts,
yielding the classic GPipe backward automatically.

Schedule: M microbatches, P stages → M + P - 1 ticks (the standard
fill/drain bubble; efficiency M / (M + P - 1)). Per tick every device
applies its stage to its live slot, results shift one stage right,
stage 0 injects the next microbatch, and the last stage banks finished
microbatches into the output buffer.

Scope: the block stack only. Embeddings run before the pipeline
(replicated compute; only stage 0's result is injected), so their grads
land on stage 0 alone — reassemble with :func:`psum_input_grads`. The
final norm/head run AFTER the pipeline on the psum-broadcast outputs,
so their grads come out replicated already: do NOT psum those (it would
multiply them by the stage count; see the psum_input_grads docstring).

See ``tests/test_pipeline.py`` for the dense-parity harness and
``lm_stack_blocks`` / ``lm_unstack_blocks`` for the TransformerLM param
plumbing.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from apex_tpu.parallel.mesh import bound_axis_size

Tree = Any


def pipeline_apply(stage_apply: Callable[[Tree, jax.Array], jax.Array],
                   stage_params: Tree, microbatches: jax.Array,
                   axis_name: str = "pipe") -> jax.Array:
    """Run ``microbatches`` (leading dim M) through the pipeline.

    ``stage_apply(stage_params, x)`` applies THIS device's stage (e.g. a
    ``lax.scan`` over its stacked blocks) to one microbatch activation.
    ``stage_params`` is the device-local stage slice (shard the stacked
    tree's leading dim over ``axis_name`` before shard_map).

    Returns the last stage's outputs, shape = microbatches.shape, valid
    on EVERY device (psum-broadcast off the last stage so the caller's
    loss runs replicated). Differentiable end-to-end.
    """
    world = bound_axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    m = microbatches.shape[0]
    ticks = m + world - 1
    right = [(i, i + 1) for i in range(world - 1)]

    def body(carry, tick):
        buf, outs = carry
        # 1. stage 0 injects this tick's microbatch (zeros once all M
        #    are in flight; that trailing garbage reaches the last stage
        #    only at tick >= M + world - 1, past the end of the loop)
        in_idx = jnp.clip(tick, 0, m - 1)
        inject = jnp.where(
            tick < m,
            jax.lax.dynamic_index_in_dim(microbatches, in_idx,
                                         keepdims=False),
            jnp.zeros_like(buf))
        buf = jnp.where(rank == 0, inject, buf)
        # 2. every stage processes its live slot (fill-phase zeros
        #    produce garbage that the banking guard below never stores)
        y = stage_apply(stage_params, buf)
        # 3. the last stage banks a finished microbatch once the
        #    pipeline is full: microbatch k arrives at tick k + world - 1
        out_idx = jnp.clip(tick - (world - 1), 0, m - 1)
        bank = jnp.where(
            (rank == world - 1) & (tick >= world - 1),
            y, jax.lax.dynamic_index_in_dim(outs, out_idx,
                                            keepdims=False))
        outs = jax.lax.dynamic_update_index_in_dim(outs, bank, out_idx, 0)
        # 4. shift one stage right (stage 0's next slot is overwritten
        #    by the next injection)
        buf = jax.lax.ppermute(y, axis_name, right)
        return (buf, outs), ()

    buf0 = jnp.zeros_like(microbatches[0])
    outs0 = jnp.zeros_like(microbatches)
    (_, outs), _ = jax.lax.scan(body, (buf0, outs0), jnp.arange(ticks))
    # outputs live on the last stage only — broadcast so every device
    # can run the (replicated) head/loss. psum-forward / IDENTITY-
    # backward (tensor_parallel's g collective): every rank computes the
    # same downstream loss, so a plain psum's transpose would deliver
    # world× the cotangent to the last stage (check_vma=False psum
    # transposes to psum).
    from apex_tpu.parallel.tensor_parallel import tp_region_exit
    return tp_region_exit(
        jnp.where(rank == world - 1, outs, jnp.zeros_like(outs)),
        axis_name)


def psum_input_grads(grads: Tree, axis_name: str = "pipe") -> Tree:
    """Sum INPUT-side param grads (embeddings — anything computed
    BEFORE :func:`pipeline_apply`) across the pipe axis: the inject
    ``where`` zeroes every rank's input path except stage 0's, so the
    psum of (rank-0 grad, zeros, ...) reassembles the full gradient on
    every rank. Do NOT apply this to output-side params (final norm /
    LM head): they run on the psum-broadcast outputs, so their grads
    come out replicated already — summing would multiply them by the
    stage count."""
    return jax.tree_util.tree_map(
        lambda g: jax.lax.psum(g, axis_name), grads)


# ---------------------------------------------------------------------------
# TransformerLM param plumbing
# ---------------------------------------------------------------------------

def lm_stack_blocks(params: Tree) -> tuple[Tree, Tree]:
    """Split a TransformerLM param tree into (stacked_blocks, rest):
    ``block_0..block_{L-1}`` leaves stack on a new leading dim (length
    L), everything else (embeddings, ``ln_f``, ``head``) passes through.
    Shard the stacked tree's leading dim with ``P(axis)`` so each pipe
    rank holds its stage's L/P consecutive blocks."""
    blocks = sorted((k for k in params if k.startswith("block_")),
                    key=lambda k: int(k.split("_")[1]))
    rest = {k: v for k, v in params.items() if not k.startswith("block_")}
    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *[params[k] for k in blocks])
    return stacked, rest


def lm_unstack_blocks(stacked: Tree, rest: Tree) -> Tree:
    """Inverse of :func:`lm_stack_blocks`."""
    n = jax.tree_util.tree_leaves(stacked)[0].shape[0]
    out = dict(rest)
    for i in range(n):
        out[f"block_{i}"] = jax.tree_util.tree_map(
            lambda x: x[i], stacked)
    return out


def stacked_block_pspecs(stacked: Tree, axis: str = "pipe",
                         inner_specs: Tree = None) -> Tree:
    """P(axis) on every stacked-block leaf's leading dim. For 3-D
    composition (pipe × tensor parallelism) pass ``inner_specs`` — a
    ONE-block PartitionSpec tree (e.g. ``lm_tp_pspecs(params)['block_0']``,
    identical across blocks): each stacked leaf gets
    ``P(axis, *inner_spec)``, sharding the stage dim over ``axis`` and
    the original dims over the tensor axis."""
    if inner_specs is None:
        return jax.tree_util.tree_map(lambda _: P(axis), stacked)
    return jax.tree_util.tree_map(
        lambda _, sp: P(axis, *sp), stacked, inner_specs,
        is_leaf=lambda t: isinstance(t, P))
