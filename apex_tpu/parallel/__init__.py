"""apex_tpu.parallel (placeholder — populated incrementally)."""
