"""apex_tpu.parallel — distributed/parallel layer (reference L3:
apex/parallel/). DP gradient sync, SyncBatchNorm, LARC, mesh helpers."""

from apex_tpu.parallel.mesh import (
    make_mesh, data_parallel_mesh, subgroups, init_distributed, hybrid_mesh,
    require_axis, bound_axis_size, reform_mesh,
)
# NOTE: apex_tpu.parallel.multiproc (Rendezvous, elastic_world, the
# --elastic supervisor) is deliberately NOT imported here — it doubles
# as the `python -m apex_tpu.parallel.multiproc` entry point, and an
# eager package import would shadow runpy's __main__ execution of it.
# Import the submodule directly: `from apex_tpu.parallel import
# multiproc`.
from apex_tpu.parallel.distributed import (
    allreduce_gradients,
    DistributedDataParallel,
    Reducer,
    ddp_train_step,
)
from apex_tpu.parallel import overlap
from apex_tpu.parallel.overlap import adasum_flat, sync_in_backward
from apex_tpu.parallel.sync_batchnorm import (
    SyncBatchNorm,
    sync_moments,
    convert_syncbn_model,
    convert_syncbn_apply,
)
from apex_tpu.parallel.larc import LARC, larc_transform_grads

# create_syncbn_process_group analog (apex/parallel/__init__.py:58-95):
# rank subsets are plain axis_index_groups lists on TPU.
create_syncbn_process_group = subgroups
from apex_tpu.parallel import tensor_parallel
from apex_tpu.parallel.tensor_parallel import (
    tp_region_enter,
    tp_region_exit,
    tp_shard_lm_params,
    tp_unshard_lm_params,
    lm_tp_pspecs,
)
from apex_tpu.parallel import expert_parallel
from apex_tpu.parallel.expert_parallel import (
    MoEMLP,
    top_k_routing,
    lm_moe_pspecs,
    moe_sync_grads,
    moe_aux_total,
)
from apex_tpu.parallel import pipeline
from apex_tpu.parallel.pipeline import (
    pipeline_apply,
    psum_input_grads,
    lm_stack_blocks,
    lm_unstack_blocks,
    stacked_block_pspecs,
)
from apex_tpu.parallel import pipeline_schedule
from apex_tpu.parallel.pipeline_schedule import (
    accumulate_grads,
    bubble_fraction,
    make_schedule,
    pipelined_grads,
    schedule_1f1b,
    schedule_gpipe,
    stage_partition,
)
