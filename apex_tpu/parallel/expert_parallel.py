"""Expert parallelism — Switch/GShard-style Mixture-of-Experts mapped to a
``jax.sharding.Mesh`` axis. The reference framework has no MoE (SURVEY.md
§2.4 counts DP / ZeRO / subgroups); this is additive TPU-first capability
like ring/Ulysses sequence parallelism and Megatron tensor parallelism,
completing the dp/sp/tp/pp/ep axis set.

TPU-first design choices:

- **Einsum dispatch** (GShard): routing materializes one-hot
  dispatch/combine tensors and moves tokens with ``nec,nm->ecm`` /
  ``nec,ecm->nm`` einsums — large static-shape matmuls the MXU tiles,
  instead of the CUDA-style gather/scatter with dynamic token counts
  (data-dependent shapes cannot compile under jit).
- **Fixed capacity**: every expert processes exactly ``C`` token slots
  (``ceil(k·N·capacity_factor/E)`` rounded up to a multiple of 8 for
  sublane alignment); overflow tokens are dropped (combine weight 0) and
  their residual path carries them, exactly the Switch Transformer
  contract.
- **all_to_all over the expert axis**: with experts sharded
  ``P('expert', ...)`` and tokens batch-sharded over the same axis, the
  local ``(E, C, M)`` dispatch buffer is exchanged with ONE tiled
  ``lax.all_to_all`` (split experts, concat capacity) so each device
  receives its own experts' slots from every peer — the XLA collective
  rides ICI; the reverse all_to_all is its exact transpose, so expert-
  kernel gradients arrive complete without any extra collective.

Usage (see tests/test_moe.py, ``__graft_entry__.dryrun_multichip`` part 8)::

    mesh   = parallel.make_mesh((ep,), ("expert",))
    dense  = TransformerLM(..., moe_num_experts=E)          # global twin
    params = dense.init(key, tokens)["params"]              # (E, ...) experts
    specs  = lm_moe_pspecs(params, axis="expert")
    local  = dense.clone(expert_parallel_axis="expert",
                         expert_parallel_size=ep)
    # under shard_map(in_specs=(specs, P("expert"))) each device applies
    # `local` with its (E/ep, ...) expert shard; after backward, psum the
    # replicated-param grads only (moe_sync_grads).

Auxiliary losses (Switch §2.2 / ST-MoE z-loss) are sown into the
``intermediates`` collection — pull them with
``model.apply(..., mutable=["intermediates"])`` and add
``moe_aux_total(...)`` to the objective as-is: it already applies the
standard coefficients (balance 1e-2, the Switch default; z-loss 1e-3)
— pass ``balance_coef``/``z_coef`` to override, never scale its result
again.
"""

from __future__ import annotations

import math
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

Tree = Any


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def top_k_routing(probs, k: int, capacity: int):
    """Greedy top-``k`` token→expert assignment with per-expert capacity.

    ``probs``: (N, E) router probabilities (fp32). Returns
    ``(dispatch, combine, fraction)``:

    - ``dispatch`` (N, E, C) 0/1 — token n occupies slot c of expert e.
      Slots fill in choice-priority order (all first choices before any
      second choice, GShard §3.2), tokens beyond ``capacity`` drop out.
    - ``combine`` (N, E, C) — dispatch scaled by the gate weight. For
      k=1 the weight is the raw top-1 probability (Switch); for k>1 the
      selected probabilities renormalize to sum to 1 per token.
    - ``fraction`` (E,) — fraction of tokens whose FIRST choice is each
      expert (the ``f_e`` of the Switch balance loss).
    """
    n, e = probs.shape
    remaining = probs
    onehots, gates = [], []
    for _ in range(k):
        idx = jnp.argmax(remaining, axis=-1)
        oh = jax.nn.one_hot(idx, e, dtype=probs.dtype)       # (N, E)
        gates.append(jnp.sum(probs * oh, axis=-1))           # (N,)
        onehots.append(oh)
        remaining = remaining * (1.0 - oh)

    if k > 1:
        denom = sum(gates) + 1e-9
        gates = [g / denom for g in gates]

    # Slot positions: cumulative count of earlier claims on the same
    # expert, earlier choices (across ALL tokens) before later ones.
    claimed = jnp.zeros((e,), probs.dtype)
    dispatch = jnp.zeros((n, e, capacity), probs.dtype)
    combine = jnp.zeros((n, e, capacity), probs.dtype)
    for oh, gate in zip(onehots, gates):
        pos_in_e = jnp.cumsum(oh, axis=0) - oh + claimed[None, :]  # (N, E)
        pos = jnp.sum(pos_in_e * oh, axis=-1).astype(jnp.int32)  # (N,)
        keep = (pos < capacity).astype(probs.dtype)
        slot = jax.nn.one_hot(pos, capacity, dtype=probs.dtype)  # (N, C)
        d = (oh * keep[:, None])[:, :, None] * slot[:, None, :]
        dispatch = dispatch + d
        combine = combine + gate[:, None, None] * d
        claimed = claimed + jnp.sum(oh, axis=0)

    return dispatch, combine, jnp.mean(onehots[0], axis=0)


class MoEMLP(nn.Module):
    """Drop-in MoE replacement for a transformer block's dense MLP.

    ``num_experts`` is GLOBAL; with ``expert_parallel_size=ep`` this
    module holds the LOCAL ``num_experts/ep`` expert shard (leading
    param dim) and exchanges tokens over ``axis_name`` — init the dense
    twin (``ep=1``) and shard with :func:`lm_moe_pspecs`, the same flow
    as tensor parallelism. The router always computes in fp32 (amp casts
    disabled): top-k selection on half-precision logits is the classic
    MoE instability.
    """

    embed_dim: int
    num_experts: int
    mlp_ratio: int = 4
    num_selected: int = 2
    capacity_factor: float = 1.25
    dtype: Any = None
    axis_name: Optional[str] = None
    expert_parallel_size: int = 1

    @nn.compact
    def __call__(self, x):
        b, s, m = x.shape
        n = b * s
        e = self.num_experts
        ep = self.expert_parallel_size
        if e % ep:
            raise ValueError(
                f"expert_parallel_size ({ep}) must divide "
                f"num_experts ({e})")
        if self.num_selected > e:
            # with k > E the second argmax would re-pick an
            # already-claimed expert at a real gate weight, silently
            # double-filling its capacity
            raise ValueError(
                f"num_selected ({self.num_selected}) must be <= "
                f"num_experts ({e})")
        e_loc = e // ep
        hidden = self.mlp_ratio * m
        capacity = _round_up(
            max(8, math.ceil(self.num_selected * n
                             * self.capacity_factor / e)), 8)

        xf = x.reshape(n, m)
        router = self.param("router", nn.initializers.lecun_normal(),
                            (m, e))

        from apex_tpu.ops._amp_guard import no_amp

        @no_amp
        def route(xf32, r32):
            logits = xf32 @ r32                              # (N, E)
            probs = jax.nn.softmax(logits, axis=-1)
            dispatch, combine, fraction = top_k_routing(
                probs, self.num_selected, capacity)
            # Switch balance loss: E * sum_e f_e * P_e  (==1 balanced);
            # ST-MoE router z-loss: mean(logsumexp(logits)^2)
            aux = e * jnp.sum(fraction * jnp.mean(probs, axis=0))
            z = jnp.mean(jax.nn.logsumexp(logits, axis=-1) ** 2)
            return dispatch, combine, aux, z

        dispatch, combine, aux, z = route(
            xf.astype(jnp.float32), router.astype(jnp.float32))
        if self.axis_name is not None and ep > 1:
            # Sown VALUE is the shard-mean (GShard computes the balance
            # term per routing group and averages); the grad path stays
            # local — the pmean rides behind stop_gradient because under
            # shard_map(check_vma=False) a differentiated psum transposes
            # to another psum, over-counting replicated cotangents by the
            # axis size (same hazard tensor_parallel's f/g guard against).
            # Each device's aux grad is its shard's contribution; the
            # trainer's moe_sync_grads psum completes it, exactly like
            # the CE loss path.
            aux = aux + jax.lax.stop_gradient(
                jax.lax.pmean(aux, self.axis_name) - aux)
            z = z + jax.lax.stop_gradient(
                jax.lax.pmean(z, self.axis_name) - z)
        self.sow("intermediates", "moe_aux_loss", aux)
        self.sow("intermediates", "moe_router_z_loss", z)

        cdt = x.dtype if self.dtype is None else self.dtype
        expert_in = jnp.einsum("nec,nm->ecm", dispatch.astype(cdt),
                               xf.astype(cdt))               # (E, C, M)
        if self.axis_name is not None and ep > 1:
            # (E, C, M) -> (E/ep, ep*C, M): send each peer its experts'
            # slots, receive my experts' slots from every peer
            expert_in = jax.lax.all_to_all(
                expert_in, self.axis_name, split_axis=0, concat_axis=1,
                tiled=True)

        wi = self.param("wi", nn.initializers.lecun_normal(),
                        (e_loc, m, hidden))
        bi = self.param("bi", nn.initializers.zeros_init(),
                        (e_loc, hidden))
        wo = self.param("wo", nn.initializers.lecun_normal(),
                        (e_loc, hidden, m))
        bo = self.param("bo", nn.initializers.zeros_init(),
                        (e_loc, m))
        h = jnp.einsum("ecm,emh->ech", expert_in, wi.astype(cdt))
        h = nn.gelu(h + bi.astype(cdt)[:, None, :])
        out = jnp.einsum("ech,ehm->ecm", h, wo.astype(cdt))
        out = out + bo.astype(cdt)[:, None, :]

        if self.axis_name is not None and ep > 1:
            out = jax.lax.all_to_all(
                out, self.axis_name, split_axis=1, concat_axis=0,
                tiled=True)                                  # (E, C, M)
        y = jnp.einsum("nec,ecm->nm", combine.astype(cdt), out)
        return y.reshape(b, s, m).astype(x.dtype)


# ---------------------------------------------------------------------------
# Param layout + grad sync helpers
# ---------------------------------------------------------------------------

_EXPERT_LEAVES = ("wi", "bi", "wo", "bo")


def lm_moe_pspecs(params: Tree, axis: str = "expert") -> Tree:
    """PartitionSpec tree for a TransformerLM (or bare MoEMLP) param
    tree: expert-stacked leaves (``wi/bi/wo/bo`` under a ``moe`` module)
    shard their leading expert dim over ``axis``; the router and every
    non-MoE param stay replicated."""

    def spec(path_names, leaf):
        names = [str(getattr(p, "key", getattr(p, "name", p)))
                 for p in path_names]
        # "moe" parent inside a TransformerLM tree; a bare MoEMLP tree
        # has the expert leaves at the root
        in_moe = "moe" in names or len(names) == 1
        if in_moe and names[-1] in _EXPERT_LEAVES:
            return P(axis, *([None] * (leaf.ndim - 1)))
        return P()

    return jax.tree_util.tree_map_with_path(spec, params)


def moe_sync_grads(grads: Tree, specs: Tree, axis: str) -> Tree:
    """Cross-device gradient sync for the EP layout: replicated-param
    grads psum over ``axis`` (each device computed only its token
    shard's contribution); expert-sharded grads pass through — the
    backward all_to_all already accumulated every shard's contribution
    into the owning device (its transpose is the forward exchange)."""
    return jax.tree_util.tree_map(
        lambda g, sp: g if (len(sp) > 0 and sp[0] is not None)
        else jax.lax.psum(g, axis),
        grads, specs, is_leaf=lambda t: isinstance(t, P))


def moe_aux_total(intermediates: Tree, *, balance_coef: float = 1e-2,
                  z_coef: float = 1e-3):
    """Weighted sum of every sown MoE auxiliary loss (mean across MoE
    blocks, Switch convention): ``balance_coef * mean(aux) +
    z_coef * mean(z)``. Returns 0.0 when the tree holds none (dense
    model), so trainers can add it unconditionally."""
    aux, z = [], []

    def visit(path_names, leaf):
        names = [str(getattr(p, "key", getattr(p, "name", p)))
                 for p in path_names]
        vals = leaf if isinstance(leaf, (tuple, list)) else (leaf,)
        if any(n == "moe_aux_loss" for n in names):
            aux.extend(vals)
        elif any(n == "moe_router_z_loss" for n in names):
            z.extend(vals)
        return leaf

    jax.tree_util.tree_map_with_path(visit, intermediates)
    total = jnp.zeros((), jnp.float32)
    if aux:
        total = total + balance_coef * sum(aux) / len(aux)
    if z:
        total = total + z_coef * sum(z) / len(z)
    return total
