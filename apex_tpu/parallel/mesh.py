"""Device-mesh helpers — the TPU-native replacement for the reference's
process-group machinery (torch.distributed process groups, NCCL communicators,
apex/parallel/__init__.py:58-95 ``create_syncbn_process_group``).

On TPU, "process groups" are named axes of a ``jax.sharding.Mesh``; rank
subsets become ``axis_index_groups`` on the XLA collective. Collectives ride
ICI within a slice and DCN across slices — laid out by simply ordering mesh
axes so the fastest-varying axis maps to ICI neighbors.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(axis_sizes: Optional[Sequence[int]] = None,
              axis_names: Sequence[str] = ("data",),
              devices=None) -> Mesh:
    """Build a Mesh over all (or given) devices.

    Default: 1-D "data" mesh over every device — the analog of the reference
    DDP's default world process group (apex/parallel/distributed.py:162-254).
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    if axis_sizes is None:
        axis_sizes = [len(devices)]
    arr = np.asarray(devices).reshape(tuple(axis_sizes))
    return Mesh(arr, tuple(axis_names))


def data_parallel_mesh(name: str = "data") -> Mesh:
    return make_mesh(axis_names=(name,))


def subgroups(world_size: int, group_size: int) -> List[List[int]]:
    """Partition ranks into contiguous groups of ``group_size`` — the analog
    of ``create_syncbn_process_group`` (apex/parallel/__init__.py:58-95),
    which requires world_size % group_size == 0."""
    if group_size <= 0 or world_size % group_size != 0:
        raise ValueError(
            f"world_size ({world_size}) must be divisible by group_size "
            f"({group_size}) — same contract as create_syncbn_process_group")
    return [list(range(i, i + group_size))
            for i in range(0, world_size, group_size)]
