"""Device-mesh helpers — the TPU-native replacement for the reference's
process-group machinery (torch.distributed process groups, NCCL communicators,
apex/parallel/__init__.py:58-95 ``create_syncbn_process_group``).

On TPU, "process groups" are named axes of a ``jax.sharding.Mesh``; rank
subsets become ``axis_index_groups`` on the XLA collective. Collectives ride
ICI within a slice and DCN across slices — laid out by simply ordering mesh
axes so the fastest-varying axis maps to ICI neighbors.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def require_axis(mesh: Mesh, *axis_names: str) -> None:
    """Validate that every name in ``axis_names`` is an axis of ``mesh``,
    raising a ``ValueError`` that names the offender and the available
    axes — the runtime twin of the APX103 lint rule. Without this, a
    mistyped axis name surfaces as an opaque unbound-axis failure deep in
    XLA tracing (or, on multi-host, a hang)."""
    available = tuple(getattr(mesh, "axis_names", ()) or ())
    for name in axis_names:
        if name not in available:
            raise ValueError(
                f"axis name {name!r} is not an axis of the mesh; "
                f"available axes: {available}")


def bound_axis_size(axis_name: str) -> int:
    """Size of the named mesh axis bound in the current trace context
    (shard_map / pmap body). Raises ``ValueError`` naming the offending
    axis when it is not bound — the trace-time twin of
    :func:`require_axis` for collective helpers that never see the Mesh
    object, replacing the opaque ``NameError: unbound axis name`` from
    deep inside tracing."""
    try:
        return jax.lax.axis_size(axis_name)
    except NameError as e:
        raise ValueError(
            f"axis name {axis_name!r} is not bound in this trace "
            "context — collectives must run inside shard_map/pmap over "
            "a mesh that names this axis (check the axis_name= argument "
            "against the mesh's axis_names)") from e


def make_mesh(axis_sizes: Optional[Sequence[int]] = None,
              axis_names: Sequence[str] = ("data",),
              devices=None) -> Mesh:
    """Build a Mesh over all (or given) devices.

    Default: 1-D "data" mesh over every device — the analog of the reference
    DDP's default world process group (apex/parallel/distributed.py:162-254).
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    if axis_sizes is None:
        axis_sizes = [len(devices)]
    arr = np.asarray(devices).reshape(tuple(axis_sizes))
    return Mesh(arr, tuple(axis_names))


def data_parallel_mesh(name: str = "data") -> Mesh:
    return make_mesh(axis_names=(name,))


def named_mesh(axes: Sequence[Tuple[str, int]], devices=None) -> Mesh:
    """Build a mesh from ordered ``(name, size)`` pairs over the first
    ``prod(sizes)`` devices — the :mod:`apex_tpu.plan` layout-to-mesh
    hop (a planner candidate is exactly such an ordered axis list).
    Axes of size 1 are dropped (a 1-extent axis adds nothing but spec
    noise); an empty/all-1 list degrades to a 1-axis mesh of the first
    pair's name so collectives still have an axis to bind."""
    axes = [(str(n), int(s)) for n, s in axes]
    if not axes:
        raise ValueError("named_mesh needs at least one (name, size) pair")
    kept = [(n, s) for n, s in axes if s > 1] or [axes[0]]
    names = tuple(n for n, _ in kept)
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate mesh axis names: {names}")
    sizes = [s for _, s in kept]
    total = int(np.prod(sizes))
    devices = list(jax.devices()) if devices is None else list(devices)
    if total > len(devices):
        raise ValueError(
            f"mesh {dict(kept)} needs {total} devices, have "
            f"{len(devices)}")
    return make_mesh(axis_sizes=sizes, axis_names=names,
                     devices=devices[:total])


def reform_mesh(world: Optional[int] = None,
                axis_names: Sequence[str] = ("data",),
                devices=None) -> Mesh:
    """Re-form a 1-D mesh at ``world`` devices after a membership change
    (the :mod:`apex_tpu.parallel.multiproc` rendezvous/elastic arc): a
    fleet that lost members rebuilds its data/ZeRO axis over the FIRST
    ``world`` devices of the (possibly shrunken) pool, so shard ``r`` of
    the re-sharded optimizer state lands on the device at dense rank
    ``r``. ``world=None`` reads the membership env contract
    (``multiproc.elastic_world()``). Raises when the pool holds fewer
    than ``world`` devices — a membership registry claiming more members
    than there are devices is a wiring error, not something to truncate
    silently."""
    if world is None:
        from apex_tpu.parallel.multiproc import elastic_world
        world, _ = elastic_world()
    world = int(world)
    devices = list(jax.devices()) if devices is None else list(devices)
    if world < 1 or world > len(devices):
        raise ValueError(
            f"cannot re-form a mesh at world {world}: device pool holds "
            f"{len(devices)} devices")
    return make_mesh(axis_sizes=[world], axis_names=axis_names,
                     devices=devices[:world])


def subgroups(world_size: int, group_size: int) -> List[List[int]]:
    """Partition ranks into contiguous groups of ``group_size`` — the analog
    of ``create_syncbn_process_group`` (apex/parallel/__init__.py:58-95),
    which requires world_size % group_size == 0."""
    if group_size <= 0 or world_size % group_size != 0:
        raise ValueError(
            f"world_size ({world_size}) must be divisible by group_size "
            f"({group_size}) — same contract as create_syncbn_process_group")
    return [list(range(i, i + group_size))
            for i in range(0, world_size, group_size)]


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     local_device_ids=None) -> None:
    """Multi-host initialization — the analog of the reference's
    ``torch.distributed.init_process_group('nccl', init_method='env://')``
    (examples/imagenet/main_amp.py:122-125).

    Delegates to ``jax.distributed.initialize``, which (like env://) reads
    the coordinator/world/rank from the environment when arguments are None
    (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID, or the
    TPU metadata service on Cloud TPU pods). Safe to call once per process
    before any backend use; a no-op when already initialized or truly
    single-process (no coordinator configured anywhere).
    """
    import os
    configured = bool(coordinator_address or num_processes is not None
                      or process_id is not None
                      or os.environ.get("JAX_COORDINATOR_ADDRESS")
                      or os.environ.get("COORDINATOR_ADDRESS"))
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None:
        already = bool(is_init())
    else:  # older jax: fall back to the private client handle
        already = getattr(jax._src.distributed.global_state, "client",
                          None) is not None
    if already:
        return
    # Do NOT probe the backend/platform here: that would initialize the
    # local backend single-process before initialize() can register the
    # cluster (the exact "must run before any backend use" hazard).
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id,
            local_device_ids=local_device_ids)
    except Exception:
        if configured:
            raise  # explicit configuration must not fail silently
        # unconfigured single-process run (no coordinator anywhere,
        # no cluster auto-detection): nothing to initialize


def hybrid_mesh(ici_axes: Sequence[int], dcn_axes: Sequence[int],
                axis_names: Sequence[str]) -> Mesh:
    """Multi-slice mesh laid out so the LAST axes vary fastest within a
    slice (ICI) and the first axes cross slices (DCN) — put your
    bandwidth-hungry axis (tensor/sequence parallel, ZeRO shard) on ICI and
    the gradient-sync data axis on DCN.

    ``ici_axes``/``dcn_axes`` are per-axis sizes with
    ``prod(ici) = devices per slice`` and ``prod(dcn) = num slices``;
    ``axis_names`` names the concatenated (dcn + ici) axes. Uses
    ``mesh_utils.create_hybrid_device_mesh`` for a physical-topology-aware
    device order on real TPU slices; falls back to a row-major reshape on
    CPU meshes (tests).
    """
    ici_axes, dcn_axes = tuple(ici_axes), tuple(dcn_axes)
    if len(axis_names) != len(dcn_axes) + len(ici_axes):
        raise ValueError("axis_names must name every dcn + ici axis")
    shape = dcn_axes + ici_axes
    # Topology-aware ordering only exists for real TPU slices; CPU/virtual
    # meshes (tests) have no slice structure, so a row-major reshape is the
    # correct layout there. On TPU, configuration errors from
    # create_hybrid_device_mesh must propagate — a silent fallback would
    # put the DCN axis on ICI neighbors, the exact pathology this helper
    # exists to prevent.
    if jax.devices()[0].platform != "tpu":
        arr = np.asarray(jax.devices()).reshape(shape)
    else:
        from jax.experimental import mesh_utils
        # create_hybrid_device_mesh takes parallel per-axis (ici, dcn) size
        # lists of equal length (total per axis = ici[i]*dcn[i]); express
        # "dcn axes first, then ici axes" by padding each side with 1s.
        arr = mesh_utils.create_hybrid_device_mesh(
            (1,) * len(dcn_axes) + ici_axes,
            dcn_axes + (1,) * len(ici_axes))
        arr = arr.reshape(shape)
    return Mesh(arr, tuple(axis_names))
