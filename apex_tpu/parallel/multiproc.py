"""Launcher analog — the reference ships ``python -m apex.parallel.multiproc``
(apex/parallel/multiproc.py:12-35), a pre-torchrun one-process-per-GPU
spawner.

TPU inverts the model: ONE process drives every local chip (SPMD), and
multi-host pods need one process per host, each calling
``jax.distributed.initialize``. This module provides that initialization
hook, so "the launcher" is your scheduler (GKE/xmanager/mpirun) plus::

    python -m apex_tpu.parallel.multiproc train.py --args...

which initializes the distributed runtime from standard env vars
(COORDINATOR_ADDRESS, NUM_PROCESSES, PROCESS_ID) and then execs the script.

Elastic extension (ROADMAP item 4 / docs/resilience.md "Elastic
membership"): the launcher also owns MEMBERSHIP. ::

    python -m apex_tpu.parallel.multiproc --elastic 2 -- \\
        python train.py --resume auto --telemetry tel-p{rank}.jsonl

spawns one member process per rank with ``APEX_TPU_WORLD`` /
``APEX_TPU_RANK`` / ``APEX_TPU_RENDEZVOUS`` set (and ``{rank}`` /
``{world}`` substituted into the command), then supervises: a member
that dies abnormally (an OOM kill, the ``node_loss`` fault) triggers a
membership change — the survivors are SIGTERMed, which is the EXISTING
cooperative-leave contract (each takes a final snapshot and exits 75,
``EX_TEMPFAIL``), and the fleet relaunches at the smaller world with
dense re-ranked members. The relaunched run's ``--resume auto`` then
re-shards the world-``W`` snapshot to world ``W-1`` through
:mod:`apex_tpu.resilience.elastic`.

:class:`Rendezvous` is the file-based membership registry the members
and supervisor share: each member announces itself (atomic file +
heartbeats) and can ask for the agreed ``(world, rank)`` — rank is the
member's DENSE position among current members, so a re-formed fleet
always numbers 0..W'-1 regardless of which original ranks survived.
"""

from __future__ import annotations

import json
import os
import runpy
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence, Tuple

#: env vars of the elastic membership contract (set by the supervisor,
#: read by members via :func:`elastic_world`)
ENV_WORLD = "APEX_TPU_WORLD"
ENV_RANK = "APEX_TPU_RANK"
ENV_RENDEZVOUS = "APEX_TPU_RENDEZVOUS"


def initialize_distributed() -> None:
    """Initialize jax.distributed from env vars when present (multi-host);
    no-op on single host — mirrors the reference's graceful single-GPU path."""
    import jax

    coord = os.environ.get("COORDINATOR_ADDRESS")
    nproc = os.environ.get("NUM_PROCESSES")
    pid = os.environ.get("PROCESS_ID")
    if coord and nproc and pid:
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=int(nproc),
            process_id=int(pid))


def elastic_world() -> Tuple[int, int]:
    """``(world, rank)`` of this process under the elastic launcher
    (``APEX_TPU_WORLD``/``APEX_TPU_RANK``), falling back to the
    jax.distributed env contract (``NUM_PROCESSES``/``PROCESS_ID``),
    else ``(1, 0)`` — the graceful single-member path. A PRESENT but
    malformed value raises (a member silently training at world 1 while
    the operator believes it joined a fleet is the quiet failure this
    env contract exists to prevent); only ABSENT vars degrade."""
    for wvar, rvar in ((ENV_WORLD, ENV_RANK),
                       ("NUM_PROCESSES", "PROCESS_ID")):
        w, r = os.environ.get(wvar), os.environ.get(rvar)
        if w is not None:
            try:
                return max(int(w), 1), int(r or 0)
            except ValueError as e:
                raise ValueError(
                    f"malformed membership env: {wvar}={w!r} "
                    f"{rvar}={r!r} (both must be integers)") from e
    return 1, 0


# ---------------------------------------------------------------------------
# rendezvous: file-based membership registry
# ---------------------------------------------------------------------------

class Rendezvous:
    """Shared-directory membership registry for one training fleet.

    One file per member (``member_<id>``, atomic ``os.replace`` publish,
    mtime refreshed by :meth:`heartbeat`); a member whose heartbeat is
    older than ``ttl_s`` is considered departed. :meth:`world` returns
    the DENSE ``(size, rank)`` over current members sorted by id — the
    re-rank a re-formed mesh uses, so surviving members always number
    ``0..W'-1``. :meth:`wait_world` is the join barrier: block until the
    expected member count is present (mesh formation at the NEW size).

    The heartbeat also carries the member's CAPABILITY/HEALTH PROFILE
    (``heartbeat(profile={...})`` — declared peak FLOPs + measured step
    rate, see :class:`apex_tpu.resilience.rebalance.MemberProfile`);
    :meth:`profiles` reads every live member's latest published profile,
    which is how the degradation supervisor sees the whole fleet's rates
    without any extra channel.

    The registry is advisory bookkeeping, not a lock service: the
    supervisor owns authoritative membership (it holds the child
    handles); members use the registry to observe the agreed world and
    to leave cooperatively (:meth:`leave` on the exit-75 path).
    """

    def __init__(self, directory: str, member: Optional[str] = None, *,
                 ttl_s: float = 60.0):
        self.directory = str(directory)
        self.member = None if member is None else str(member)
        self.ttl_s = float(ttl_s)
        self._profile: Optional[Dict] = None

    def _path(self, member: str) -> str:
        return os.path.join(self.directory, f"member_{member}")

    def announce(self, profile: Optional[Dict] = None) -> None:
        """Publish (or refresh) this member's registration atomically;
        ``profile`` (JSON-able) rides the member file and sticks for
        subsequent profile-less announces/heartbeats."""
        if self.member is None:
            raise ValueError("announce() needs a member id")
        if profile is not None:
            self._profile = dict(profile)
        os.makedirs(self.directory, exist_ok=True)
        tmp = self._path(self.member) + f".tmp.{os.getpid()}"
        payload = {"member": self.member, "pid": os.getpid(),
                   "ts": time.time()}
        if self._profile is not None:
            payload["profile"] = self._profile
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, self._path(self.member))

    def heartbeat(self, profile: Optional[Dict] = None) -> None:
        """Refresh liveness; re-announces if the registration vanished
        (a cleaned-up rendezvous dir must not ghost a live member).
        With ``profile=`` the member file is re-published atomically so
        the fleet sees the updated measurement; without it only the
        mtime moves (the existing cheap path). No-op in observer mode
        (``member=None``), like :meth:`leave`."""
        if self.member is None:
            return
        if profile is not None:
            self.announce(profile=profile)
            return
        try:
            os.utime(self._path(self.member))
        except OSError:
            self.announce()

    def profiles(self) -> Dict[str, Dict]:
        """``{member: profile}`` for every LIVE member (fresh heartbeat),
        ``{}`` for members that never published one. Unparseable files
        (a write raced the read) are skipped — the next heartbeat
        republishes."""
        out: Dict[str, Dict] = {}
        for m in self.members():
            try:
                with open(self._path(m)) as f:
                    payload = json.load(f)
            except (OSError, ValueError):
                continue
            prof = payload.get("profile")
            out[m] = dict(prof) if isinstance(prof, dict) else {}
        return out

    def leave(self) -> None:
        """Cooperative departure (the exit-75 path): drop the
        registration so the next :meth:`world` excludes this member."""
        if self.member is None:
            return
        try:
            os.unlink(self._path(self.member))
        except OSError:
            pass

    def members(self) -> List[str]:
        """Sorted ids of members with a fresh heartbeat."""
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        now = time.time()
        out = []
        for n in names:
            if not n.startswith("member_") or ".tmp." in n:
                continue
            try:
                fresh = now - os.path.getmtime(
                    os.path.join(self.directory, n)) <= self.ttl_s
            except OSError:
                continue   # departed between listdir and stat
            if fresh:
                out.append(n[len("member_"):])
        return sorted(out)

    def world(self) -> Tuple[int, int]:
        """``(size, rank)`` — rank is this member's dense position among
        current members (-1 when not announced/this member departed)."""
        mem = self.members()
        rank = mem.index(self.member) if self.member in mem else -1
        return len(mem), rank

    def wait_world(self, n: int, *, timeout_s: float = 60.0,
                   poll_s: float = 0.05) -> Tuple[int, int]:
        """Join barrier: block until ``n`` members are registered (mesh
        formation at the new world size); returns :meth:`world`. Raises
        ``TimeoutError`` naming who IS present — membership hangs must
        be debuggable from the message alone."""
        deadline = time.monotonic() + timeout_s
        while True:
            size, rank = self.world()
            if size >= n:
                return size, rank
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"rendezvous at {self.directory}: {size}/{n} members "
                    f"after {timeout_s:g}s (present: {self.members()})")
            time.sleep(poll_s)


# ---------------------------------------------------------------------------
# elastic supervisor
# ---------------------------------------------------------------------------

def _substitute(cmd: Sequence[str], rank: int, world: int) -> List[str]:
    return [a.replace("{rank}", str(rank)).replace("{world}", str(world))
            for a in cmd]


def run_elastic(cmd: Sequence[str], *, world: int,
                rendezvous_dir: Optional[str] = None,
                grace_s: float = 30.0, max_rounds: int = 8,
                env: Optional[Dict[str, str]] = None,
                log=print) -> int:
    """Spawn ``world`` member processes of ``cmd`` and supervise
    membership changes (module doc). Returns the exit code for the
    launcher: 0 when a final round's members all complete.

    Round protocol: members run with ``APEX_TPU_WORLD``/``APEX_TPU_RANK``
    (+ ``{rank}``/``{world}`` substitution). When a member exits
    abnormally (not 0, not 75), the round ends: survivors get SIGTERM —
    the cooperative-leave contract; each snapshots and exits 75 — with a
    ``grace_s`` escalation to SIGKILL for members stuck in a collective
    against the dead peer (their last cadence snapshot still resumes).
    The next round relaunches ``world - lost`` dense-ranked members; a
    member's own spontaneous exit 75 (deadline preemption) also counts
    as a cooperative leave. ``--resume auto`` in ``cmd`` is what turns
    the relaunch into an elastic re-shard resume."""
    if world < 1:
        raise ValueError(f"--elastic world must be >= 1, got {world}")
    cmd = list(cmd)
    if cmd and cmd[0].endswith(".py"):
        cmd = [sys.executable] + cmd
    rounds = 0
    rc_last = 1
    while world >= 1 and rounds < max_rounds:
        rounds += 1
        if rendezvous_dir and os.path.isdir(rendezvous_dir):
            # the supervisor owns authoritative membership: clear the
            # previous round's registrations (including a SIGKILLed
            # member's never-unlinked file) so wait_world(n) is a REAL
            # barrier on this round's members, not satisfied by stale
            # still-within-TTL files
            for name in os.listdir(rendezvous_dir):
                if name.startswith("member_"):
                    try:
                        os.unlink(os.path.join(rendezvous_dir, name))
                    except OSError:
                        pass
        procs: Dict[int, subprocess.Popen] = {}
        for rank in range(world):
            child_env = dict(os.environ)
            child_env.update(env or {})
            child_env[ENV_WORLD] = str(world)
            child_env[ENV_RANK] = str(rank)
            if rendezvous_dir:
                child_env[ENV_RENDEZVOUS] = rendezvous_dir
            procs[rank] = subprocess.Popen(
                _substitute(cmd, rank, world), env=child_env)
        log(f"multiproc --elastic: round {rounds} at world {world} "
            f"(pids {[p.pid for p in procs.values()]})")
        lost: List[int] = []
        left: List[int] = []
        done: List[int] = []
        signaled = False
        while len(done) + len(lost) + len(left) < world:
            for rank, p in procs.items():
                rc = p.poll()
                if rc is None or rank in done or rank in lost \
                        or rank in left:
                    continue
                if rc == 0:
                    done.append(rank)
                elif signaled:
                    # leaving at OUR request (75 after the final
                    # snapshot, or the SIGKILL escalation): a staying
                    # member of the next round, not another loss
                    done.append(rank)
                elif rc == 75:
                    # spontaneous cooperative leave (deadline/SIGTERM
                    # from outside): member departs, fleet re-forms
                    left.append(rank)
                else:
                    lost.append(rank)
                    log(f"multiproc --elastic: rank {rank} LOST "
                        f"(rc={rc}) at world {world}")
            if (lost or left) and not signaled:
                signaled = True
                for rank, p in procs.items():
                    if p.poll() is None:
                        try:
                            p.send_signal(signal.SIGTERM)
                        except OSError:
                            pass
                log("multiproc --elastic: membership change — SIGTERMed "
                    "survivors (cooperative leave, exit 75 after final "
                    "snapshot)")
                deadline = time.monotonic() + grace_s
                for rank, p in procs.items():
                    if p.poll() is not None:
                        continue
                    try:
                        p.wait(max(deadline - time.monotonic(), 0.1))
                    except subprocess.TimeoutExpired:
                        # stuck in a collective against the dead peer:
                        # the last cadence snapshot still resumes
                        log(f"multiproc --elastic: rank {rank} did not "
                            f"leave within {grace_s:g}s; SIGKILL")
                        p.kill()
                        p.wait()
            time.sleep(0.05)
        if not lost and not left:
            log(f"multiproc --elastic: world {world} completed")
            return 0
        new_world = world - len(lost) - len(left)
        log(f"multiproc --elastic: re-forming at world {new_world} "
            f"(lost ranks {lost}, left ranks {left})")
        if new_world < 1:
            log("multiproc --elastic: no members left")
            return 1
        world = new_world
        rc_last = 1
    return rc_last


def _elastic_main(argv: List[str]) -> None:
    """``--elastic N [--rendezvous DIR] [--grace S] [--max-rounds R]
    [--] cmd...``"""
    world: Optional[int] = None
    rdzv: Optional[str] = None
    grace = 30.0
    max_rounds = 8
    args = argv[:]
    cmd: List[str] = []
    while args:
        a = args.pop(0)
        if a == "--elastic":
            world = int(args.pop(0))
        elif a == "--rendezvous":
            rdzv = args.pop(0)
        elif a == "--grace":
            grace = float(args.pop(0))
        elif a == "--max-rounds":
            max_rounds = int(args.pop(0))
        elif a == "--":
            cmd = args
            break
        else:
            cmd = [a] + args
            break
    if world is None or not cmd:
        print("usage: python -m apex_tpu.parallel.multiproc --elastic N "
              "[--rendezvous DIR] [--grace S] [--max-rounds R] -- "
              "cmd [args...]", file=sys.stderr)
        sys.exit(1)
    sys.exit(run_elastic(cmd, world=world, rendezvous_dir=rdzv,
                         grace_s=grace, max_rounds=max_rounds))


def main() -> None:
    usage = ("usage: python -m apex_tpu.parallel.multiproc script.py "
             "[args...]\n"
             "       python -m apex_tpu.parallel.multiproc --elastic N "
             "[--rendezvous DIR] [--grace S] -- cmd [args...]")
    if len(sys.argv) < 2 or sys.argv[1] in ("-h", "--help"):
        print(usage, file=sys.stderr)
        sys.exit(0 if len(sys.argv) >= 2 else 1)
    if sys.argv[1] == "--elastic":
        _elastic_main(sys.argv[1:])
        return
    script = sys.argv[1]
    if not os.path.exists(script):
        print(f"multiproc: no such script: {script}\n{usage}",
              file=sys.stderr)
        sys.exit(2)
    initialize_distributed()
    sys.argv = sys.argv[1:]
    runpy.run_path(script, run_name="__main__")


if __name__ == "__main__":
    main()
