"""Launcher analog — the reference ships ``python -m apex.parallel.multiproc``
(apex/parallel/multiproc.py:12-35), a pre-torchrun one-process-per-GPU
spawner.

TPU inverts the model: ONE process drives every local chip (SPMD), and
multi-host pods need one process per host, each calling
``jax.distributed.initialize``. This module provides that initialization
hook, so "the launcher" is your scheduler (GKE/xmanager/mpirun) plus::

    python -m apex_tpu.parallel.multiproc train.py --args...

which initializes the distributed runtime from standard env vars
(COORDINATOR_ADDRESS, NUM_PROCESSES, PROCESS_ID) and then execs the script.
"""

from __future__ import annotations

import os
import runpy
import sys


def initialize_distributed() -> None:
    """Initialize jax.distributed from env vars when present (multi-host);
    no-op on single host — mirrors the reference's graceful single-GPU path."""
    import jax

    coord = os.environ.get("COORDINATOR_ADDRESS")
    nproc = os.environ.get("NUM_PROCESSES")
    pid = os.environ.get("PROCESS_ID")
    if coord and nproc and pid:
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=int(nproc),
            process_id=int(pid))


def main() -> None:
    usage = ("usage: python -m apex_tpu.parallel.multiproc script.py "
             "[args...]")
    if len(sys.argv) < 2 or sys.argv[1] in ("-h", "--help"):
        print(usage, file=sys.stderr)
        sys.exit(0 if len(sys.argv) >= 2 else 1)
    script = sys.argv[1]
    if not os.path.exists(script):
        print(f"multiproc: no such script: {script}\n{usage}",
              file=sys.stderr)
        sys.exit(2)
    initialize_distributed()
    sys.argv = sys.argv[1:]
    runpy.run_path(script, run_name="__main__")


if __name__ == "__main__":
    main()
