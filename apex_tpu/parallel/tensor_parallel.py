"""Megatron-style tensor parallelism for the transformer stack — the
intra-layer model-parallel scheme (Shoeybi et al. 2019) mapped to a
``jax.sharding.Mesh`` axis: attention shards by HEADS, the MLP shards
column-then-row, and each block pays exactly two all-reduces (one after
the attention out-projection, one after fc2), riding ICI as XLA
collectives. The reference framework has no tensor parallelism
(SURVEY.md §2.4 counts DP / ZeRO / subgroups; this is additive TPU-first
capability like ring/Ulysses sequence parallelism) — the design follows
the public scaling-book recipe: pick a mesh, shard the params, let the
two f/g conjugate collectives carry the math.

Usage (composable with a data axis; see tests/test_tensor_parallel.py)::

    mesh = parallel.make_mesh((d_dp, d_tp), ("data", "model"))
    params = model_dense.init(key, tokens)["params"]      # dense twin
    params = tp.tp_shard_lm_params(params, tp=d_tp)       # qkv permute
    specs  = tp.lm_tp_pspecs(params, axis="model")        # P() tree
    local  = model.clone(num_heads=H // d_tp,
                         tensor_parallel_axis="model",
                         tensor_parallel_size=d_tp)
    # under shard_map(in_specs=(specs, ...)) each device applies `local`
    # with its param shards; f/g insert the two per-block collectives.

The f/g pair are CONJUGATE collectives (Megatron's f and g): ``f`` is
identity forward / psum backward (entering a column-parallel region:
activations are replicated, each device's dx is a partial sum over its
kernel columns), ``g`` is psum forward / identity backward (leaving a
row-parallel region: outputs are partial sums, the incoming cotangent is
already replicated). Both are custom_vjp: under ``shard_map(...,
check_vma=False)`` a plain ``lax.psum`` transposes to another psum,
over-counting replicated cotangents by the axis size.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from flax.linen.dtypes import promote_dtype
from jax.sharding import PartitionSpec as P

Tree = Any


# ---------------------------------------------------------------------------
# f / g conjugate collectives
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_region_enter(x, axis_name: str):
    """Megatron ``f``: identity forward, psum backward — marks replicated
    activations entering a column-parallel layer."""
    return x


def _enter_fwd(x, axis_name):
    return x, None


def _enter_bwd(axis_name, _, ct):
    return (jax.lax.psum(ct, axis_name),)


tp_region_enter.defvjp(_enter_fwd, _enter_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_region_exit(x, axis_name: str):
    """Megatron ``g``: psum forward, identity backward — reduces the
    partial sums leaving a row-parallel layer."""
    return jax.lax.psum(x, axis_name)


def _exit_fwd(x, axis_name):
    return jax.lax.psum(x, axis_name), None


def _exit_bwd(axis_name, _, ct):
    return (ct,)


tp_region_exit.defvjp(_exit_fwd, _exit_bwd)


# ---------------------------------------------------------------------------
# Row-parallel linear: matmul -> psum -> bias, param-tree-compatible with
# nn.Dense
# ---------------------------------------------------------------------------

class RowParallelDense(nn.Module):
    """Megatron RowParallelLinear: each device matmuls its INPUT-dim
    shard of the kernel, the partial sums all-reduce (``g``), and the
    bias is added ONCE after the reduction — never scale a replicated
    bias by 1/tp instead: adaptive optimizers (Adam) step the scaled
    bias at full lr, silently diverging from the dense trajectory (r4
    finding, caught by the 2-D train-step parity test).

    Param names/shapes match ``nn.Dense`` (``kernel``, ``bias``), so a
    dense twin's tree shards straight in with no re-mapping.

    NOTE on init: the supported flow inits the DENSE twin and shards via
    :func:`tp_shard_lm_params` (module docstring). A direct
    ``local_model.init`` draws this kernel over the LOCAL fan-in
    (fan/tp), i.e. sqrt(tp) larger init std than the dense layer —
    fine for shape probing, not for dense-equivalent training from
    scratch."""

    features: int
    axis_name: str
    dtype: Any = None
    use_bias: bool = True

    @nn.compact
    def __call__(self, x):
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (x.shape[-1], self.features))
        bias = (self.param("bias", nn.initializers.zeros_init(),
                           (self.features,))
                if self.use_bias else None)
        x, kernel, bias = promote_dtype(x, kernel, bias,
                                        dtype=self.dtype)
        y = x @ kernel
        y = tp_region_exit(y, self.axis_name)
        if bias is not None:
            y = y + bias
        return y


# ---------------------------------------------------------------------------
# Param layout: permutation + PartitionSpecs for the TransformerLM tree
# ---------------------------------------------------------------------------

def _permute_qkv(arr, tp: int, *, inverse: bool = False):
    """The fused in_proj holds columns ``[Q | K | V]`` (each e wide,
    head-major). Sharding that contiguously would hand device 0 all of Q
    and part of K — so permute to per-GROUP ``[Q_p | K_p | V_p]`` blocks:
    device p's contiguous chunk then splits into its own heads' q/k/v
    thirds exactly like the dense module's ``jnp.split(qkv, 3)``."""
    e3 = arr.shape[-1]
    e = e3 // 3
    lead = arr.shape[:-1]
    # forward: (…, 3, tp, e/tp) -> (…, tp, 3, e/tp); inverse swaps back
    a = arr.reshape(*lead, *((3, tp) if not inverse else (tp, 3)),
                    e // tp)
    a = jnp.swapaxes(a, -3, -2)
    return a.reshape(*lead, e3)


def tp_shard_lm_params(params: Tree, tp: int) -> Tree:
    """Re-lay out a DENSE TransformerLM param tree for ``tp``-way head
    sharding: every block's fused qkv kernel/bias columns permute to the
    per-group ``[Q_p|K_p|V_p]`` layout (see :func:`_permute_qkv`).
    Row-parallel layers need no value changes — under TP they run as
    :class:`RowParallelDense`, which adds the (replicated, unscaled)
    bias once after the ``g`` reduction. Inverse:
    :func:`tp_unshard_lm_params` (checkpoint interop). The arrays stay
    GLOBAL; shard them with :func:`lm_tp_pspecs` via device_put or
    shard_map in_specs."""
    return _map_blocks(params, tp, inverse=False)


def tp_unshard_lm_params(params: Tree, tp: int) -> Tree:
    """Undo :func:`tp_shard_lm_params` (gathered params -> dense
    layout)."""
    return _map_blocks(params, tp, inverse=True)


def _map_blocks(params: Tree, tp: int, *, inverse: bool) -> Tree:
    out = {}
    for name, sub in params.items():
        if name.startswith("block_"):
            sub = dict(sub)
            attn = dict(sub["attn"])
            proj = dict(attn["in_proj"])
            proj["kernel"] = _permute_qkv(proj["kernel"], tp,
                                          inverse=inverse)
            if "bias" in proj:
                proj["bias"] = _permute_qkv(proj["bias"], tp,
                                            inverse=inverse)
            attn["in_proj"] = proj
            sub["attn"] = attn
        out[name] = sub
    return out


def lm_tp_pspecs(params: Tree, axis: str = "model") -> Tree:
    """PartitionSpec tree for a (permuted) TransformerLM param tree:
    column-parallel kernels shard their OUTPUT dim (in_proj, fc1),
    row-parallel kernels their INPUT dim (out_proj, fc2 — head-major ctx
    features make out_proj's row blocks contiguous per device, no
    permutation needed); embeddings, layer norms, and the LM head stay
    replicated."""
    col_k, row_k = P(None, axis), P(axis, None)

    def spec(path_names, leaf):
        names = [str(getattr(p, "key", getattr(p, "name", p)))
                 for p in path_names]
        if not any(n.startswith("block_") for n in names):
            return P()
        joined = "/".join(names)
        if "in_proj" in joined or "fc1" in joined:
            return col_k if leaf.ndim == 2 else P(axis)
        if "out_proj" in joined or "fc2" in joined:
            # bias replicated and UNSCALED: RowParallelDense adds it
            # once AFTER the g reduction (never pre-scale by 1/tp — see
            # the RowParallelDense docstring's Adam-divergence warning)
            return row_k if leaf.ndim == 2 else P()
        return P()  # ln1/ln2 scales etc.

    return jax.tree_util.tree_map_with_path(spec, params)
