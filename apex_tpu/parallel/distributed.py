"""Data-parallel gradient synchronization — the TPU-native redesign of
``apex.parallel.DistributedDataParallel`` (apex/parallel/distributed.py:129-640)
and ``Reducer`` (:89-126).

What the reference does with per-param backward hooks, flat buckets, NCCL
all_reduce on side streams, and first-iteration bucket-structure discovery,
XLA does with a single program: gradients are averaged with ``lax.pmean`` over
a named mesh axis, and the latency-hiding scheduler overlaps the collectives
with remaining backward computation automatically. What remains semantically
meaningful from the reference's knob set is kept:

  * ``message_size`` bucketing (distributed.py:177: elements per allreduce) —
    controls collective granularity AND overlap: leaves are packed into
    per-dtype buckets of at most ``message_size`` elements, each bucket
    concatenated from only ITS OWN leaves and psum'd as one unit. Because a
    bucket depends on a subset of backward's gradients instead of all of
    them (the pre-r3 whole-tree concat was a dataflow barrier), XLA's
    latency-hiding scheduler can start each bucket's collective as soon as
    its leaves are ready — the ready-bucket overlap the reference builds
    with per-param hooks + side streams (distributed.py:320-557).
  * ``allreduce_always_fp32`` (:190,241-244): upcast before the collective.
  * ``gradient_average`` / ``gradient_predivide_factor`` (:184-189): divide
    by world size after (or partially before) the reduction.
  * ``delay_allreduce`` (:168): in JAX, synchronization happens where you
    call this function; "delay" = call it once after grad accumulation.

Usage inside a shard_map/pmap step (see parallel.ddp_step for the wrapper):

    grads = jax.grad(loss_fn)(params)
    grads = allreduce_gradients(grads, axis_name="data")
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu.ops import buckets as _buckets
from apex_tpu.parallel.mesh import bound_axis_size, require_axis

Tree = Any


def allreduce_gradients(
    grads: Tree,
    axis_name: str = "data",
    *,
    message_size: Optional[int] = None,
    allreduce_always_fp32: bool = False,
    gradient_average: bool = True,
    gradient_predivide_factor: float = 1.0,
    axis_index_groups=None,
    telemetry_step=None,
    reduce_dtype=None,
    adasum: bool = False,
) -> Tree:
    """Leaf-grouped bucketed gradient allreduce over a mesh axis (the hot
    path of reference DDP: create_hooks/comm_ready_buckets/allreduce_bucket,
    distributed.py:320-557). Must run inside a context where ``axis_name``
    is bound (shard_map / pmap / pjit-with-manual-axes).

    Each bucket concatenates at most ``message_size`` elements from its own
    leaves only, so its psum depends on a *prefix* of backward's gradients
    and XLA can overlap the collective with the rest of backward. A single
    leaf larger than ``message_size`` still gets a chunked psum (slices of
    one leaf keep the same dependency footprint) for DCN message sizing.
    ``message_size=None`` (default) resolves through ``apex_tpu.tune``
    (the frozen 2**23 under the default ``APEX_TPU_TUNE=off`` policy;
    a cached/measured granularity under ``cache``/``auto``);
    ``message_size=0`` disables bucketing (one whole-tree bucket per
    dtype — the pre-r3 barrier form, kept for A/B comparison); negative
    values raise. A config that shatters the step into more than 256
    buckets warns once via ``tune/warn/*`` telemetry — per-collective
    latency serializes such a schedule.

    ``telemetry_step``: optional step index (host int or traced scalar)
    attached to the per-bucket ``health/`` events so replicated per-shard
    emissions collapse in summarize's (name, step) dedup and the series
    lines up with the overflow/loss timelines.

    ``reduce_dtype`` (bf16/fp16) compresses each bucket to a 16-bit wire
    format for the collective with the mean pre-scaled in before the cast
    (fp32 accumulation downstream — the overlap engine's numerics
    contract, docs/overlap.md); ``adasum=True`` replaces the mean with
    adaptive summation (arXiv:2006.02924). Both are implemented by
    :mod:`apex_tpu.parallel.overlap`; with both at their defaults this
    function traces the exact pre-overlap program (pinned by
    tests/test_overlap.py's jaxpr-equality test). For collectives
    overlapped with backward COMPUTE, see ``overlap.sync_in_backward`` /
    ``DistributedDataParallel(overlap=True)``."""
    from apex_tpu.parallel import overlap as _overlap
    reduce_dtype = _overlap.resolve_reduce_dtype(reduce_dtype)
    _overlap.validate_comm_args(
        reduce_dtype=reduce_dtype, adasum=adasum,
        allreduce_always_fp32=allreduce_always_fp32,
        axis_index_groups=axis_index_groups,
        gradient_average=gradient_average)
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    if not leaves:
        return grads
    world = bound_axis_size(axis_name)
    from apex_tpu import tune
    if message_size is None:
        total = sum(int(l.size) for l in leaves)
        message_size = tune.ddp_message_size(total=total, world=world)
    elif message_size < 0:
        raise ValueError(
            f"allreduce_gradients: message_size must be >= 1 (or 0 to "
            f"disable bucketing, or None to resolve via apex_tpu.tune); "
            f"got {message_size}")
    buckets = _buckets.assign_buckets(leaves, message_size)
    tune.warn_bucket_count("ddp", len(buckets), message_size)

    # trace-time static accounting: what this call will move per step,
    # per device (itemsize after the optional fp32 upcast / wire
    # compression), with the wire bill under the active algorithm (ring
    # all-reduce or adasum's pairwise levels). Shared with the staged
    # overlap path so both bill identically; no-op unless telemetry is on.
    _overlap.record_comm_event(
        axis_name, leaves, world=world, n_buckets=len(buckets),
        reduce_dtype=reduce_dtype, adasum=adasum,
        allreduce_always_fp32=allreduce_always_fp32,
        axis_index_groups=axis_index_groups)

    # averaging divides: with compression/adasum off these are exactly
    # the pre-overlap predivide/postdivide pair; compression folds the
    # mean into the pre-cast divide (pre-scaling) and adasum skips both
    predivide, postdivide = _overlap.compression_divides(
        world=world, reduce_dtype=reduce_dtype, adasum=adasum,
        gradient_average=gradient_average,
        gradient_predivide_factor=gradient_predivide_factor)

    out: list = [None] * len(leaves)
    for bi, (_, idxs) in enumerate(buckets):
        flat, spec = _buckets.flatten_tensors([leaves[i] for i in idxs])
        orig_dtype = flat.dtype
        if allreduce_always_fp32 and orig_dtype != jnp.float32:
            flat = flat.astype(jnp.float32)
        # one shared bucket reduction for every config (overlap engine):
        # predivide -> (wire cast) -> chunked psum / adasum -> fp32 ->
        # postdivide -> per-bucket health grad norm. With the knobs at
        # their defaults this traces the exact pre-overlap op sequence
        # (pinned by tests/test_overlap.py's jaxpr-equality tests).
        flat = _overlap.reduce_bucket(
            flat, axis_name, message_size=message_size,
            reduce_dtype=reduce_dtype, adasum=adasum,
            predivide=predivide, postdivide=postdivide,
            axis_index_groups=axis_index_groups,
            bucket_index=bi, n_buckets=len(buckets),
            telemetry_step=telemetry_step,
            health_name=f"health/ddp/bucket{bi}/grad_norm")
        if flat.dtype != orig_dtype:
            flat = flat.astype(orig_dtype)
        for i, t in zip(idxs, _buckets.unflatten_tensors(flat, spec)):
            out[i] = t
    return jax.tree_util.tree_unflatten(treedef, out)


class Reducer:
    """Manual-trigger allreduce helper (reference Reducer,
    distributed.py:89-126): call ``.reduce(grads_or_params)`` yourself where
    the reference user would call ``reducer.reduce()``."""

    def __init__(self, axis_name: str = "data", **kwargs):
        self.axis_name = axis_name
        self.kwargs = kwargs

    def reduce(self, tree: Tree) -> Tree:
        return allreduce_gradients(tree, self.axis_name, **self.kwargs)


class DistributedDataParallel:
    """API-shape analog of reference DDP: wraps a *gradient function* so its
    output gradients are synchronized over the data axis.

    Where the reference wraps an ``nn.Module`` and hooks its backward
    (distributed.py:129-640), here you wrap the function that produces
    grads::

        ddp = DistributedDataParallel(axis_name="data",
                                      allreduce_always_fp32=True)
        grad_fn = ddp.wrap_grad_fn(jax.grad(loss_fn))
        # inside shard_map: grads come back pre-averaged

    Bucket capacity: ``message_size=None`` (the default) resolves through
    ``apex_tpu.tune`` — the frozen ``2**23`` elements under the default
    ``APEX_TPU_TUNE=off`` policy (``tune.heuristics.DDP_MESSAGE_SIZE``),
    a cached/measured granularity under ``cache``/``auto``. An explicit
    ``message_size=`` ALWAYS wins over the tune resolution; ``0``
    disables bucketing (one whole-tree bucket per dtype).

    ``overlap=True`` switches from post-hoc sync to the staged-backward
    schedule: call :meth:`prepare` on the params INSIDE the loss function
    and the gradients come out of ``jax.grad`` already reduced, with each
    bucket's collective overlapping the remaining backward compute
    (:func:`apex_tpu.parallel.overlap.sync_in_backward` — the reference
    DDP's hook/side-stream overlap as dataflow). ``reduce_dtype`` /
    ``adasum`` apply to both paths.

    ``delay_allreduce`` (reference :168) is expressed by calling
    ``ddp.sync(grads)`` explicitly after accumulation instead of wrapping.
    """

    def __init__(self, axis_name: str = "data", *,
                 message_size: Optional[int] = None,
                 allreduce_always_fp32: bool = False,
                 gradient_average: bool = True,
                 gradient_predivide_factor: float = 1.0,
                 axis_index_groups=None, prof: bool = False,
                 overlap: bool = False, reduce_dtype=None,
                 adasum: bool = False):
        from apex_tpu.parallel import overlap as _overlap
        self.axis_name = axis_name
        self.prof = prof
        self.overlap = overlap
        # resolve + validate at construction — a bad wire dtype or a
        # contradictory combination fails here, not at first trace
        reduce_dtype = _overlap.resolve_reduce_dtype(reduce_dtype)
        _overlap.validate_comm_args(
            reduce_dtype=reduce_dtype, adasum=adasum,
            allreduce_always_fp32=allreduce_always_fp32,
            axis_index_groups=axis_index_groups,
            gradient_average=gradient_average)
        self._kw = dict(message_size=message_size,
                        allreduce_always_fp32=allreduce_always_fp32,
                        gradient_average=gradient_average,
                        gradient_predivide_factor=gradient_predivide_factor,
                        axis_index_groups=axis_index_groups,
                        reduce_dtype=reduce_dtype, adasum=adasum)

    def sync(self, grads: Tree, *, telemetry_step=None) -> Tree:
        if self.prof:
            # reference DDP prof=True brackets its hook/bucket logic with
            # NVTX ranges (distributed.py:360-364,517-518); here the named
            # scope tags the collective in XLA metadata/profiler traces
            with jax.named_scope("apex_ddp_allreduce"):
                return allreduce_gradients(grads, self.axis_name,
                                           telemetry_step=telemetry_step,
                                           **self._kw)
        return allreduce_gradients(grads, self.axis_name,
                                   telemetry_step=telemetry_step,
                                   **self._kw)

    def prepare(self, params: Tree, *, telemetry_step=None) -> Tree:
        """Overlap staging: identity on ``params`` whose cotangents come
        back bucket-reduced from the backward itself. Call inside the
        loss function; with ``overlap=False`` this is a plain passthrough
        (use :meth:`sync` on the grads instead)."""
        if not self.overlap:
            return params
        from apex_tpu.parallel import overlap as _overlap
        return _overlap.sync_in_backward(
            params, self.axis_name, telemetry_step=telemetry_step,
            **self._kw)

    def wrap_loss_fn(self, loss_fn: Callable) -> Callable:
        """Wrap ``loss_fn(params, *args)`` so its first argument is
        routed through :meth:`prepare` — differentiate the result and
        the grads arrive pre-synchronized via the overlap schedule."""
        @functools.wraps(loss_fn)
        def wrapped(params, *args, **kwargs):
            return loss_fn(self.prepare(params), *args, **kwargs)
        return wrapped

    def wrap_grad_fn(self, grad_fn: Callable) -> Callable:
        @functools.wraps(grad_fn)
        def wrapped(*args, **kwargs):
            res = grad_fn(*args, **kwargs)
            if isinstance(res, tuple) and len(res) == 2:
                # value_and_grad shape: (value, grads)
                val, grads = res
                return val, self.sync(grads)
            return self.sync(res)
        return wrapped


def ddp_train_step(
    loss_fn: Callable,
    optimizer,
    mesh: Mesh,
    axis_name: str = "data",
    *,
    ddp: Optional[DistributedDataParallel] = None,
    donate: bool = True,
) -> Callable:
    """Build a jitted SPMD train step: per-device loss/grad on the local
    batch shard -> bucketed grad allreduce -> optimizer step (replicated).

    This is the end-to-end analog of the reference's
    amp+DDP loop (SURVEY.md §3.3/§3.6) as one compiled program:
    ``step(params, opt_state, batch) -> (params, opt_state, loss)``.

    ``loss_fn(params, batch) -> scalar loss`` computed on the local shard.
    """
    from jax import shard_map

    require_axis(mesh, axis_name)   # fail here, not deep inside tracing
    ddp = ddp or DistributedDataParallel(axis_name)

    def per_device(params, opt_state, batch):
        if ddp.overlap:
            # staged-backward schedule: grads leave value_and_grad
            # already reduced, each bucket's collective overlapping the
            # remaining backward compute
            loss, grads = jax.value_and_grad(
                ddp.wrap_loss_fn(loss_fn))(params, batch)
        else:
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            grads = ddp.sync(grads)
        loss = jax.lax.pmean(loss, axis_name)
        new_params, new_opt_state = optimizer.step(grads, params, opt_state)
        return new_params, new_opt_state, loss

    pspec_batch = P(axis_name)
    rep = P()
    smapped = shard_map(
        per_device, mesh=mesh,
        in_specs=(rep, rep, pspec_batch),
        out_specs=(rep, rep, rep),
        check_vma=False)
    return jax.jit(smapped, donate_argnums=(0, 1) if donate else ())
