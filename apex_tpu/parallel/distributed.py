"""Data-parallel gradient synchronization — the TPU-native redesign of
``apex.parallel.DistributedDataParallel`` (apex/parallel/distributed.py:129-640)
and ``Reducer`` (:89-126).

What the reference does with per-param backward hooks, flat buckets, NCCL
all_reduce on side streams, and first-iteration bucket-structure discovery,
XLA does with a single program: gradients are averaged with ``lax.pmean`` over
a named mesh axis, and the latency-hiding scheduler overlaps the collectives
with remaining backward computation automatically. What remains semantically
meaningful from the reference's knob set is kept:

  * ``message_size`` bucketing (distributed.py:177: elements per allreduce) —
    controls collective granularity AND overlap: leaves are packed into
    per-dtype buckets of at most ``message_size`` elements, each bucket
    concatenated from only ITS OWN leaves and psum'd as one unit. Because a
    bucket depends on a subset of backward's gradients instead of all of
    them (the pre-r3 whole-tree concat was a dataflow barrier), XLA's
    latency-hiding scheduler can start each bucket's collective as soon as
    its leaves are ready — the ready-bucket overlap the reference builds
    with per-param hooks + side streams (distributed.py:320-557).
  * ``allreduce_always_fp32`` (:190,241-244): upcast before the collective.
  * ``gradient_average`` / ``gradient_predivide_factor`` (:184-189): divide
    by world size after (or partially before) the reduction.
  * ``delay_allreduce`` (:168): in JAX, synchronization happens where you
    call this function; "delay" = call it once after grad accumulation.

Usage inside a shard_map/pmap step (see parallel.ddp_step for the wrapper):

    grads = jax.grad(loss_fn)(params)
    grads = allreduce_gradients(grads, axis_name="data")
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu.ops import buckets as _buckets
from apex_tpu.parallel.mesh import bound_axis_size, require_axis

Tree = Any


def allreduce_gradients(
    grads: Tree,
    axis_name: str = "data",
    *,
    message_size: Optional[int] = None,
    allreduce_always_fp32: bool = False,
    gradient_average: bool = True,
    gradient_predivide_factor: float = 1.0,
    axis_index_groups=None,
    telemetry_step=None,
) -> Tree:
    """Leaf-grouped bucketed gradient allreduce over a mesh axis (the hot
    path of reference DDP: create_hooks/comm_ready_buckets/allreduce_bucket,
    distributed.py:320-557). Must run inside a context where ``axis_name``
    is bound (shard_map / pmap / pjit-with-manual-axes).

    Each bucket concatenates at most ``message_size`` elements from its own
    leaves only, so its psum depends on a *prefix* of backward's gradients
    and XLA can overlap the collective with the rest of backward. A single
    leaf larger than ``message_size`` still gets a chunked psum (slices of
    one leaf keep the same dependency footprint) for DCN message sizing.
    ``message_size=None`` (default) resolves through ``apex_tpu.tune``
    (the frozen 2**23 under the default ``APEX_TPU_TUNE=off`` policy;
    a cached/measured granularity under ``cache``/``auto``);
    ``message_size=0`` disables bucketing (one whole-tree bucket per
    dtype — the pre-r3 barrier form, kept for A/B comparison); negative
    values raise. A config that shatters the step into more than 256
    buckets warns once via ``tune/warn/*`` telemetry — per-collective
    latency serializes such a schedule.

    ``telemetry_step``: optional step index (host int or traced scalar)
    attached to the per-bucket ``health/`` events so replicated per-shard
    emissions collapse in summarize's (name, step) dedup and the series
    lines up with the overflow/loss timelines."""
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    if not leaves:
        return grads
    world = bound_axis_size(axis_name)
    from apex_tpu import tune
    if message_size is None:
        total = sum(int(l.size) for l in leaves)
        message_size = tune.ddp_message_size(total=total, world=world)
    elif message_size < 0:
        raise ValueError(
            f"allreduce_gradients: message_size must be >= 1 (or 0 to "
            f"disable bucketing, or None to resolve via apex_tpu.tune); "
            f"got {message_size}")
    buckets = _buckets.assign_buckets(leaves, message_size)
    tune.warn_bucket_count("ddp", len(buckets), message_size)

    from apex_tpu import telemetry
    if telemetry.enabled():
        # trace-time static accounting: what this call will move per step,
        # per device (itemsize after the optional fp32 upcast). The wire
        # estimate is the ring all-reduce bill; summarize groups it with
        # the other per-axis comm producers.
        import numpy as _np
        nbytes = sum(
            int(_np.prod(l.shape)) * (4 if allreduce_always_fp32
                                      else _np.dtype(l.dtype).itemsize)
            for l in leaves)
        telemetry.record_static(
            f"ddp/{axis_name}/allreduce_bytes", nbytes,
            meta={"axis": axis_name, "primitive": "psum",
                  "count": len(buckets), "world": world,
                  "bytes_wire": round(nbytes * 2 * (world - 1) / world)},
            dedup_key=(axis_name, nbytes, len(buckets), world))

    predivide = gradient_predivide_factor if gradient_average else 1.0
    postdivide = (world / gradient_predivide_factor
                  if gradient_average else 1.0)

    from apex_tpu.telemetry import health as _health
    health_on = _health.enabled()

    out: list = [None] * len(leaves)
    for bi, (_, idxs) in enumerate(buckets):
        flat, spec = _buckets.flatten_tensors([leaves[i] for i in idxs])
        orig_dtype = flat.dtype
        if allreduce_always_fp32 and orig_dtype != jnp.float32:
            flat = flat.astype(jnp.float32)
        if predivide != 1.0:
            flat = flat / predivide
        psum = functools.partial(jax.lax.psum, axis_name=axis_name,
                                 axis_index_groups=axis_index_groups)
        if 0 < message_size < flat.shape[0]:
            # oversize single leaf: chunked psum for message sizing
            chunks = [psum(flat[i:i + message_size])
                      for i in range(0, flat.shape[0], message_size)]
            flat = jnp.concatenate(chunks)
        else:
            flat = psum(flat)
        if postdivide != 1.0:
            flat = flat / postdivide
        if health_on:
            # numerics health: per-bucket grad norm off the already
            # reduced flat view — the synced gradient the optimizer will
            # actually consume. One fused reduction per bucket; nothing
            # traced when health is off.
            telemetry.record(
                f"health/ddp/bucket{bi}/grad_norm",
                jnp.sqrt(jnp.sum(jnp.square(flat.astype(jnp.float32)))),
                step=telemetry_step)
        if flat.dtype != orig_dtype:
            flat = flat.astype(orig_dtype)
        for i, t in zip(idxs, _buckets.unflatten_tensors(flat, spec)):
            out[i] = t
    return jax.tree_util.tree_unflatten(treedef, out)


class Reducer:
    """Manual-trigger allreduce helper (reference Reducer,
    distributed.py:89-126): call ``.reduce(grads_or_params)`` yourself where
    the reference user would call ``reducer.reduce()``."""

    def __init__(self, axis_name: str = "data", **kwargs):
        self.axis_name = axis_name
        self.kwargs = kwargs

    def reduce(self, tree: Tree) -> Tree:
        return allreduce_gradients(tree, self.axis_name, **self.kwargs)


class DistributedDataParallel:
    """API-shape analog of reference DDP: wraps a *gradient function* so its
    output gradients are synchronized over the data axis.

    Where the reference wraps an ``nn.Module`` and hooks its backward
    (distributed.py:129-640), here you wrap the function that produces
    grads::

        ddp = DistributedDataParallel(axis_name="data",
                                      message_size=2**25,
                                      allreduce_always_fp32=True)
        grad_fn = ddp.wrap_grad_fn(jax.grad(loss_fn))
        # inside shard_map: grads come back pre-averaged

    ``delay_allreduce`` (reference :168) is expressed by calling
    ``ddp.sync(grads)`` explicitly after accumulation instead of wrapping.
    """

    # Default bucket capacity (None) resolves through apex_tpu.tune: the
    # frozen 2**23 under APEX_TPU_TUNE=off — mirroring the reference's
    # message_size=1e7 elements (distributed.py:177): big enough that ICI
    # bandwidth is saturated, small enough that several buckets overlap.
    def __init__(self, axis_name: str = "data", *,
                 message_size: Optional[int] = None,
                 allreduce_always_fp32: bool = False,
                 gradient_average: bool = True,
                 gradient_predivide_factor: float = 1.0,
                 axis_index_groups=None, prof: bool = False):
        self.axis_name = axis_name
        self.prof = prof
        self._kw = dict(message_size=message_size,
                        allreduce_always_fp32=allreduce_always_fp32,
                        gradient_average=gradient_average,
                        gradient_predivide_factor=gradient_predivide_factor,
                        axis_index_groups=axis_index_groups)

    def sync(self, grads: Tree) -> Tree:
        if self.prof:
            # reference DDP prof=True brackets its hook/bucket logic with
            # NVTX ranges (distributed.py:360-364,517-518); here the named
            # scope tags the collective in XLA metadata/profiler traces
            with jax.named_scope("apex_ddp_allreduce"):
                return allreduce_gradients(grads, self.axis_name,
                                           **self._kw)
        return allreduce_gradients(grads, self.axis_name, **self._kw)

    def wrap_grad_fn(self, grad_fn: Callable) -> Callable:
        @functools.wraps(grad_fn)
        def wrapped(*args, **kwargs):
            res = grad_fn(*args, **kwargs)
            if isinstance(res, tuple) and len(res) == 2:
                # value_and_grad shape: (value, grads)
                val, grads = res
                return val, self.sync(grads)
            return self.sync(res)
        return wrapped


def ddp_train_step(
    loss_fn: Callable,
    optimizer,
    mesh: Mesh,
    axis_name: str = "data",
    *,
    ddp: Optional[DistributedDataParallel] = None,
    donate: bool = True,
) -> Callable:
    """Build a jitted SPMD train step: per-device loss/grad on the local
    batch shard -> bucketed grad allreduce -> optimizer step (replicated).

    This is the end-to-end analog of the reference's
    amp+DDP loop (SURVEY.md §3.3/§3.6) as one compiled program:
    ``step(params, opt_state, batch) -> (params, opt_state, loss)``.

    ``loss_fn(params, batch) -> scalar loss`` computed on the local shard.
    """
    from jax import shard_map

    require_axis(mesh, axis_name)   # fail here, not deep inside tracing
    ddp = ddp or DistributedDataParallel(axis_name)

    def per_device(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        grads = ddp.sync(grads)
        loss = jax.lax.pmean(loss, axis_name)
        new_params, new_opt_state = optimizer.step(grads, params, opt_state)
        return new_params, new_opt_state, loss

    pspec_batch = P(axis_name)
    rep = P()
    smapped = shard_map(
        per_device, mesh=mesh,
        in_specs=(rep, rep, pspec_batch),
        out_specs=(rep, rep, rep),
        check_vma=False)
    return jax.jit(smapped, donate_argnums=(0, 1) if donate else ())
