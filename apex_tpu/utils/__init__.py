"""apex_tpu.utils (placeholder — populated incrementally)."""
