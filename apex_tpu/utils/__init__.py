"""apex_tpu.utils — shared small utilities."""
