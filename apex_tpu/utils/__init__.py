"""apex_tpu.utils — shared small utilities.

``path_str`` is the canonical pytree-keypath renderer used by the param-group
filters (optimizers/base.py), amp's batchnorm path matching (amp/frontend.py)
and the checkpoint structure fingerprint — one definition so the 'a/b/0/w'
path grammar stays consistent everywhere.
"""

from __future__ import annotations

from typing import Any, Iterable


def path_str(key_path: Iterable[Any]) -> str:
    """Render a jax tree key path (DictKey/SequenceKey/GetAttrKey/...) as
    'a/b/0/w'."""
    parts = []
    for k in key_path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        else:
            parts.append(str(k))
    return "/".join(parts)
