"""Generic jaxpr equation-graph walking, shared by the lint jaxpr pass
(apex_tpu/lint/jaxpr_checks.py), the SPMD verifier
(apex_tpu/lint/spmd_checks.py), and the telemetry comm accounting
(apex_tpu/telemetry/comm.py).

All consumers traverse the same program shape — registered entry points
lowered with ``jax.make_jaxpr`` whose equations nest sub-jaxprs through
pjit / scan / cond / while / custom-vjp / shard_map / pallas_call — so the
sub-jaxpr discovery lives here once. Three precision tiers:

* :func:`walk_jaxpr` — every equation, no context. For consumers that
  only need to see each equation once.
* :func:`subjaxprs` — ``(inner, outer_operands_or_None)`` pairs with the
  *permissive* operand mapping (operands only when arities line up 1:1).
  Consumers threading their own per-var state (lint's low-precision
  provenance env) recurse themselves.
* :func:`subjaxprs_tagged` / :func:`walk_jaxpr_ctx` — role-tagged
  discovery with the *precise* operand mapping (``while`` splits its
  cond/body consts, ``cond`` drops the predicate) plus a threaded
  :class:`WalkContext` carrying mesh axes/sizes from enclosing
  ``shard_map``\\ s (via :func:`mesh_axis_sizes`), static loop
  multipliers, and control-flow nesting. The SPMD verifier's abstract
  interpretation recurses itself over :func:`subjaxprs_tagged` (it
  threads a dataflow env the generic walker can't); telemetry's comm
  accounting consumes :func:`walk_jaxpr_ctx` directly.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

# ---------------------------------------------------------------------------
# byte accounting — ONE definition
# ---------------------------------------------------------------------------
# Every byte count the toolkit derives from a program routes through
# here: the SPMD verifier's replication threshold (spmd_checks), the
# telemetry comm walker's payload sizes (telemetry/comm), the planner's
# pytree sizing (plan/describe.tree_bytes), the mem verifier's buffer
# sizes (lint/liveness), and — via HLO_DTYPE_BYTES — pyprof's HLO-text
# byte estimates (pyprof/hlo). One table, one product, no drift.

# dtype token -> bytes per element (HLO shape prefixes). pyprof's HLO
# parser aliases this as its _DTYPE_BYTES.
HLO_DTYPE_BYTES = {
    "pred": 1, "s2": 1, "u2": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1, "f8e4m3fnuz": 1,
    "f8e5m2fnuz": 1, "token": 0, "opaque": 0,
}


def aval_elements(aval) -> int:
    """Element count of one aval / array / ShapeDtypeStruct (1 for a
    scalar, 0 when the shape is unreadable)."""
    shape = getattr(aval, "shape", None)
    if shape is None:
        return 0
    return int(np.prod(shape, dtype=np.int64)) if shape else 1


def aval_bytes(aval) -> int:
    """Buffer bytes of one aval / array / ShapeDtypeStruct: element
    count x dtype itemsize. 0 when shape or dtype is unreadable (Literal
    scalars, abstract tokens) — sizing must never be the thing that
    crashes an analysis."""
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    try:
        itemsize = np.dtype(dtype).itemsize
    except TypeError:
        return 0
    return aval_elements(aval) * itemsize


def operand_bytes(eqn) -> float:
    """Total bytes of an equation's input operands (the comm walker's
    collective payload measure)."""
    total = 0.0
    for v in eqn.invars:
        total += float(aval_bytes(getattr(v, "aval", None)))
    return total


def subjaxprs(eqn) -> List[Tuple[Any, Optional[tuple]]]:
    """(inner_jaxpr, outer_operands_or_None) pairs for every sub-jaxpr in
    an equation's params — pjit/scan/cond/custom-vjp/shard_map/pallas.

    ``outer_operands`` is the equation's invars when the param shape lets
    them map 1:1 onto the inner jaxpr's invars (``cond`` branches drop the
    predicate), else ``None``; callers propagating per-var state use it to
    seed the inner environment.
    """
    pairs: List[Tuple[Any, Optional[tuple]]] = []

    def add(j, operands):
        if j is None:
            return
        inner = getattr(j, "jaxpr", j)          # ClosedJaxpr -> Jaxpr
        if hasattr(inner, "eqns") and hasattr(inner, "invars"):
            pairs.append((inner, operands))

    for key, val in eqn.params.items():
        if key == "branches" and isinstance(val, (tuple, list)):
            for br in val:
                add(br, tuple(eqn.invars[1:]))
        elif hasattr(val, "eqns") or hasattr(val, "jaxpr"):
            add(val, tuple(eqn.invars))
        elif isinstance(val, (tuple, list)):
            for item in val:
                if hasattr(item, "eqns") or hasattr(item, "jaxpr"):
                    add(item, None)
    return pairs


def walk_jaxpr(jaxpr, visit: Callable[[Any], None]) -> None:
    """Depth-first visit of every equation in ``jaxpr`` and all nested
    sub-jaxprs. ``visit(eqn)`` runs before descending into the equation's
    own sub-jaxprs."""
    for eqn in jaxpr.eqns:
        visit(eqn)
        for inner, _ in subjaxprs(eqn):
            walk_jaxpr(inner, visit)


# ---------------------------------------------------------------------------
# precise tier: role-tagged sub-jaxprs + context threading
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class SubJaxpr:
    """One sub-jaxpr of an equation, with its structural role and the
    outer operands that seed its invars.

    role:
        ``"cond_branch"`` (one per ``lax.cond``/``lax.switch`` branch),
        ``"while_cond"`` / ``"while_body"``, ``"scan_body"``,
        ``"shard_map"``, ``"pallas"``, or ``"call"`` (pjit / closed_call
        / custom-jvp/vjp primal — plain inlined calls).
    operands:
        Outer atoms mapping 1:1 onto ``jaxpr.invars`` — the *precise*
        mapping (``while`` splits cond/body consts and shares the carry;
        ``cond`` drops the predicate; ``scan`` maps consts+carry+xs
        positionally, xs avals differing only in the scanned leading
        dim). ``None`` when no sound mapping exists (pallas operands
        pass through BlockSpec index maps; thunk-shaped params).
    """

    role: str
    jaxpr: Any
    operands: Optional[tuple]


def _inner(j):
    inner = getattr(j, "jaxpr", j)              # ClosedJaxpr -> Jaxpr
    if hasattr(inner, "eqns") and hasattr(inner, "invars"):
        return inner
    return None


def subjaxprs_tagged(eqn) -> List[SubJaxpr]:
    """Role-tagged sub-jaxprs with the precise operand mapping (see
    :class:`SubJaxpr`). Falls back to the permissive :func:`subjaxprs`
    shapes (role ``"call"``/``"pallas"``, operands where arity allows)
    for primitives without bespoke handling."""
    prim = eqn.primitive.name
    params = eqn.params
    out: List[SubJaxpr] = []

    if prim == "cond" and isinstance(params.get("branches"), (tuple, list)):
        ops = tuple(eqn.invars[1:])
        for br in params["branches"]:
            j = _inner(br)
            if j is not None:
                out.append(SubJaxpr("cond_branch", j,
                                    ops if len(ops) == len(j.invars)
                                    else None))
        return out

    if prim == "while":
        cn = int(params.get("cond_nconsts", 0))
        bn = int(params.get("body_nconsts", 0))
        carry = tuple(eqn.invars[cn + bn:])
        cj = _inner(params.get("cond_jaxpr"))
        bj = _inner(params.get("body_jaxpr"))
        if cj is not None:
            ops = tuple(eqn.invars[:cn]) + carry
            out.append(SubJaxpr("while_cond", cj,
                                ops if len(ops) == len(cj.invars) else None))
        if bj is not None:
            ops = tuple(eqn.invars[cn:cn + bn]) + carry
            out.append(SubJaxpr("while_body", bj,
                                ops if len(ops) == len(bj.invars) else None))
        return out

    if prim == "scan":
        j = _inner(params.get("jaxpr"))
        if j is not None:
            ops = tuple(eqn.invars)
            out.append(SubJaxpr("scan_body", j,
                                ops if len(ops) == len(j.invars) else None))
        return out

    if prim == "shard_map":
        j = _inner(params.get("jaxpr"))
        if j is not None:
            ops = tuple(eqn.invars)
            out.append(SubJaxpr("shard_map", j,
                                ops if len(ops) == len(j.invars) else None))
        return out

    role = "pallas" if prim == "pallas_call" else "call"
    for key, val in params.items():
        vals = (val if isinstance(val, (tuple, list))
                else (val,))
        listed = isinstance(val, (tuple, list))
        for item in vals:
            if not (hasattr(item, "eqns") or hasattr(item, "jaxpr")):
                continue
            j = _inner(item)
            if j is None:
                continue
            ops = None
            if not listed and role == "call" \
                    and len(eqn.invars) == len(j.invars):
                ops = tuple(eqn.invars)
            out.append(SubJaxpr(role, j, ops))
    return out


def mesh_axis_sizes(eqn) -> Dict[str, int]:
    """``{axis_name: size}`` for a ``shard_map`` equation's mesh param
    (empty for anything else, or when the mesh hides its shape). The one
    place axis sizes are read off a program — telemetry's comm walker and
    the SPMD verifier both resolve through here."""
    sizes: Dict[str, int] = {}
    mesh = eqn.params.get("mesh") if hasattr(eqn, "params") else None
    shape = getattr(mesh, "shape", None)        # Mapping axis -> size
    for name in getattr(mesh, "axis_names", ()) or ():
        try:
            sizes[name] = int(shape[name])
        except Exception:
            pass
    return sizes


@dataclasses.dataclass(frozen=True)
class WalkContext:
    """Structural context threaded by :func:`walk_jaxpr_ctx`.

    path:
        Role chain from the root (e.g. ``("shard_map", "while_body",
        "scan_body")``) — the equation's control-flow address.
    mesh_axes / axis_sizes:
        Axis names (and sizes, where the mesh exposes them) of every
        enclosing ``shard_map``. ``axis_sizes`` may be pre-seeded by the
        caller for programs whose mesh is not discoverable.
    loop_mult:
        Product of enclosing static ``scan`` trip counts — the factor a
        per-iteration cost is multiplied by per call of the entry.
    in_while / in_cond:
        Inside a ``while`` cond/body (trip count unknowable — any count
        derived under it is a lower bound) / inside a ``cond`` branch
        (both branches are walked — an upper bound).
    """

    path: Tuple[str, ...] = ()
    mesh_axes: Tuple[str, ...] = ()
    axis_sizes: Tuple[Tuple[str, int], ...] = ()
    loop_mult: int = 1
    in_while: bool = False
    in_cond: bool = False

    @property
    def depth(self) -> int:
        return len(self.path)

    def axis_size(self, name: str) -> Optional[int]:
        return dict(self.axis_sizes).get(name)

    def child(self, eqn, role: str) -> "WalkContext":
        """The context for one of ``eqn``'s sub-jaxprs in ``role``."""
        mesh_axes, axis_sizes = self.mesh_axes, self.axis_sizes
        loop_mult, in_while, in_cond = (self.loop_mult, self.in_while,
                                        self.in_cond)
        if role == "shard_map":
            found = mesh_axis_sizes(eqn)
            mesh_axes = mesh_axes + tuple(
                n for n in (getattr(eqn.params.get("mesh"), "axis_names",
                                    ()) or ()) if n not in mesh_axes)
            known = dict(axis_sizes)
            for n, s in found.items():
                known.setdefault(n, s)
            axis_sizes = tuple(sorted(known.items()))
        elif role == "scan_body":
            try:
                loop_mult *= int(eqn.params.get("length", 1))
            except Exception:
                pass
        elif role in ("while_cond", "while_body"):
            in_while = True
        elif role == "cond_branch":
            in_cond = True
        return WalkContext(path=self.path + (role,), mesh_axes=mesh_axes,
                           axis_sizes=axis_sizes, loop_mult=loop_mult,
                           in_while=in_while, in_cond=in_cond)


def walk_jaxpr_ctx(jaxpr, visit: Callable[[Any, WalkContext], None],
                   ctx: Optional[WalkContext] = None) -> None:
    """Depth-first visit of every equation with a threaded
    :class:`WalkContext`. ``visit(eqn, ctx)`` runs before descending; the
    child context is derived per sub-jaxpr role (mesh axes/sizes from
    ``shard_map``, loop multipliers from ``scan``, while/cond flags)."""
    ctx = WalkContext() if ctx is None else ctx
    for eqn in jaxpr.eqns:
        visit(eqn, ctx)
        for sub in subjaxprs_tagged(eqn):
            walk_jaxpr_ctx(sub.jaxpr, visit, ctx.child(eqn, sub.role))
