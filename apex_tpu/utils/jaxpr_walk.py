"""Generic jaxpr equation-graph walking, shared by the lint jaxpr pass
(apex_tpu/lint/jaxpr_checks.py) and the telemetry comm accounting
(apex_tpu/telemetry/comm.py).

Both consumers traverse the same program shape — registered entry points
lowered with ``jax.make_jaxpr`` whose equations nest sub-jaxprs through
pjit / scan / cond / while / custom-vjp / shard_map / pallas_call — so the
sub-jaxpr discovery lives here once. Consumers that need to thread their
own per-subtree state (lint's low-precision provenance env) call
:func:`subjaxprs` and recurse themselves; consumers that just need every
equation call :func:`walk_jaxpr`.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple


def subjaxprs(eqn) -> List[Tuple[Any, Optional[tuple]]]:
    """(inner_jaxpr, outer_operands_or_None) pairs for every sub-jaxpr in
    an equation's params — pjit/scan/cond/custom-vjp/shard_map/pallas.

    ``outer_operands`` is the equation's invars when the param shape lets
    them map 1:1 onto the inner jaxpr's invars (``cond`` branches drop the
    predicate), else ``None``; callers propagating per-var state use it to
    seed the inner environment.
    """
    pairs: List[Tuple[Any, Optional[tuple]]] = []

    def add(j, operands):
        if j is None:
            return
        inner = getattr(j, "jaxpr", j)          # ClosedJaxpr -> Jaxpr
        if hasattr(inner, "eqns") and hasattr(inner, "invars"):
            pairs.append((inner, operands))

    for key, val in eqn.params.items():
        if key == "branches" and isinstance(val, (tuple, list)):
            for br in val:
                add(br, tuple(eqn.invars[1:]))
        elif hasattr(val, "eqns") or hasattr(val, "jaxpr"):
            add(val, tuple(eqn.invars))
        elif isinstance(val, (tuple, list)):
            for item in val:
                if hasattr(item, "eqns") or hasattr(item, "jaxpr"):
                    add(item, None)
    return pairs


def walk_jaxpr(jaxpr, visit: Callable[[Any], None]) -> None:
    """Depth-first visit of every equation in ``jaxpr`` and all nested
    sub-jaxprs. ``visit(eqn)`` runs before descending into the equation's
    own sub-jaxprs."""
    for eqn in jaxpr.eqns:
        visit(eqn)
        for inner, _ in subjaxprs(eqn):
            walk_jaxpr(inner, visit)
