"""Functional fused-optimizer protocol.

The reference optimizers are drop-in ``torch.optim.Optimizer`` subclasses that
mutate ``param.data`` (apex/optimizers/*). The TPU-native shape is a pure
``step``: ``(grads, params, state) -> (new_params, new_state)`` that jit/pjit
can trace, donate, and shard. An optax ``GradientTransformation`` view is
provided for ecosystem interop (``as_optax``).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Tuple, Union

import jax
import jax.numpy as jnp

Tree = Any
Schedule = Union[float, Callable[[jax.Array], jax.Array]]


def resolve_lr(lr: Schedule, step: jax.Array) -> jax.Array:
    return jnp.asarray(lr(step) if callable(lr) else lr, jnp.float32)


class FusedOptimizer:
    """Base class: subclasses implement ``init`` and ``step``."""

    def init(self, params: Tree) -> Any:
        raise NotImplementedError

    def step(self, grads: Tree, params: Tree, state: Any,
             *, grad_scale: Optional[jax.Array] = None,
             ) -> Tuple[Tree, Any]:
        """Apply one update. ``grad_scale`` (if given) divides grads on the
        fly, fused into the update kernel (the reference fused optimizers'
        ``scale`` argument)."""
        raise NotImplementedError

    # -- optax interop -----------------------------------------------------
    def as_optax(self):
        """View as an optax ``GradientTransformationExtraArgs`` computing
        ``updates = new_params - params`` (apply with optax.apply_updates)."""
        import optax

        def init_fn(params):
            return self.init(params)

        def update_fn(updates, state, params=None, **extra):
            if params is None:
                raise ValueError("this transformation requires params")
            new_params, new_state = self.step(updates, params, state)
            deltas = jax.tree_util.tree_map(
                lambda n, p: (n.astype(jnp.float32)
                              - p.astype(jnp.float32)).astype(p.dtype),
                new_params, params)
            return deltas, new_state

        return optax.GradientTransformationExtraArgs(init_fn, update_fn)
