"""Functional fused-optimizer protocol.

The reference optimizers are drop-in ``torch.optim.Optimizer`` subclasses that
mutate ``param.data`` (apex/optimizers/*). The TPU-native shape is a pure
``step``: ``(grads, params, state) -> (new_params, new_state)`` that jit/pjit
can trace, donate, and shard. An optax ``GradientTransformation`` view is
provided for ecosystem interop (``as_optax``).

Param groups: torch optimizers carry per-group hyperparameters
(``optimizer.param_groups``), and apex amp supports adding groups after
``amp.initialize`` (apex/amp/_process_optimizer.py:411-487,
tests/L0/run_amp/test_add_param_group.py). Params live in a pytree here, so a
group is a *predicate over leaf paths* plus hyperparameter overrides::

    opt = FusedAdam(lr=1e-3, weight_decay=0.01, param_groups=[
        {"filter": r"(bias|scale|bn)", "weight_decay": 0.0},   # regex, or
        {"filter": lambda path, leaf: leaf.ndim == 1, "lr": 2e-3},
    ])

Each leaf joins the first matching group (unmatched leaves use the optimizer's
defaults). ``add_param_group`` appends a group post-init —
``extend_init(old_state, new_params)`` then carries existing per-leaf state
over to an enlarged param tree, which is the functional analog of adding new
params to a running optimizer.
"""

from __future__ import annotations

import copy
import re
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple, Union

import jax
import jax.numpy as jnp

Tree = Any
Schedule = Union[float, Callable[[jax.Array], jax.Array]]


def resolve_lr(lr: Schedule, step: jax.Array) -> jax.Array:
    return jnp.asarray(lr(step) if callable(lr) else lr, jnp.float32)


from apex_tpu.utils import path_str  # canonical 'a/b/0/w' keypath renderer


def _match(filt, path: str, leaf) -> bool:
    if callable(filt):
        return bool(filt(path, leaf))
    return re.search(filt, path) is not None


class FusedOptimizer:
    """Base class: subclasses implement ``init`` and ``_step_dense`` (the
    whole-tree update) and list their param-mirroring state fields in
    ``_TREE_FIELDS``; param-group dispatch lives here."""

    # State NamedTuple fields whose pytrees mirror the param tree.
    _TREE_FIELDS: Tuple[str, ...] = ()

    # Class-level default; instances rebind (never mutate) this list.
    param_groups: List[Dict[str, Any]] = []

    def _init_groups(self, param_groups) -> None:
        self.param_groups = [dict(g) for g in (param_groups or [])]
        for g in self.param_groups:
            if "filter" not in g:
                raise ValueError("param group needs a 'filter' (regex or "
                                 "callable(path, leaf) -> bool)")

    def add_param_group(self, group: Dict[str, Any]) -> None:
        """Append a param group (the ``optimizer.add_param_group`` analog,
        apex/amp/_process_optimizer.py:411-487). Takes effect on the next
        traced step; for params not yet covered by the optimizer state, call
        ``extend_init``."""
        group = dict(group)
        if "filter" not in group:
            raise ValueError("param group needs a 'filter'")
        # Rebind rather than mutate: param_groups may be the class default.
        self.param_groups = self.param_groups + [group]

    def group_assignments(self, params: Tree):
        """[(leaf_indices, overrides_dict)] — first matching group wins;
        unmatched leaves form the defaults group (empty overrides)."""
        leaves = jax.tree_util.tree_leaves_with_path(params)
        assigned: List[Tuple[List[int], Dict[str, Any]]] = [
            ([], {k: v for k, v in g.items() if k != "filter"})
            for g in self.param_groups]
        default: List[int] = []
        for i, (kp, leaf) in enumerate(leaves):
            path = path_str(kp)
            for gi, g in enumerate(self.param_groups):
                if _match(g["filter"], path, leaf):
                    assigned[gi][0].append(i)
                    break
            else:
                default.append(i)
        out = [(default, {})] if default else []
        out += [(idxs, ov) for idxs, ov in assigned if idxs]
        return out

    def init(self, params: Tree) -> Any:
        raise NotImplementedError

    def extend_init(self, old_state: Any, new_params: Tree) -> Any:
        """State for ``new_params``, carrying over per-leaf state wherever the
        leaf path already existed in ``old_state`` — the functional analog of
        add_param_group introducing new params mid-training."""
        fresh = self.init(new_params)
        merged = {}
        for f in self._TREE_FIELDS:
            old_map = {path_str(kp): leaf for kp, leaf in
                       jax.tree_util.tree_leaves_with_path(
                           getattr(old_state, f))}
            fresh_field = getattr(fresh, f)
            fresh_leaves = jax.tree_util.tree_leaves_with_path(fresh_field)
            vals = [old_map.get(path_str(kp), leaf)
                    for kp, leaf in fresh_leaves]
            merged[f] = jax.tree_util.tree_unflatten(
                jax.tree_util.tree_structure(fresh_field), vals)
        return fresh._replace(step=old_state.step, **merged)

    # -- the public step: param-group dispatch over _step_dense -------------
    def step(self, grads: Tree, params: Tree, state: Any,
             *, grad_scale: Optional[jax.Array] = None, **kw):
        """Apply one update. ``grad_scale`` (if given) divides grads on the
        fly, fused into the update kernel (the reference fused optimizers'
        ``scale`` argument).

        The ``apex_optimizer_step`` named scope tags every update op in
        XLA metadata so profiler traces attribute optimizer time as its
        own bucket (pyprof.capture) — metadata only, the traced program
        is unchanged."""
        with jax.named_scope("apex_optimizer_step"):
            if not self.param_groups:
                return self._step_dense(grads, params, state,
                                        grad_scale=grad_scale, **kw)
            return self._step_grouped(grads, params, state,
                                      grad_scale=grad_scale, **kw)

    def _step_dense(self, grads: Tree, params: Tree, state: Any,
                    *, grad_scale: Optional[jax.Array] = None, **kw):
        raise NotImplementedError

    def _group_shared(self, grads: Tree, grad_scale) -> Dict[str, Any]:
        """Hook: cross-group quantities forwarded to every group's dense step
        (e.g. LAMB's global grad norm, which spans all groups)."""
        return {}

    def _step_grouped(self, grads, params, state, *, grad_scale=None, **kw):
        groups = self.group_assignments(params)
        shared = self._group_shared(grads, grad_scale)
        treedef = jax.tree_util.tree_structure(params)
        g_leaves = jax.tree_util.tree_leaves(grads)
        p_leaves = jax.tree_util.tree_leaves(params)
        state_leaves = {f: jax.tree_util.tree_leaves(getattr(state, f))
                        for f in self._TREE_FIELDS}
        model_t = kw.pop("model_out_template", None)
        model_leaves = (jax.tree_util.tree_leaves(model_t)
                        if model_t is not None else None)

        new_p: List[Any] = [None] * len(p_leaves)
        new_state_leaves = {f: [None] * len(p_leaves)
                            for f in self._TREE_FIELDS}
        new_model: List[Any] = [None] * len(p_leaves)
        new_step = None
        for idxs, overrides in groups:
            sub = copy.copy(self)
            sub.param_groups = []
            for k, v in overrides.items():
                if not hasattr(sub, k):
                    raise ValueError(f"unknown param-group override {k!r}")
                setattr(sub, k, v)
            sub_state = state._replace(**{
                f: [state_leaves[f][i] for i in idxs]
                for f in self._TREE_FIELDS})
            sub_kw = dict(kw)
            sub_kw.update(shared)
            if model_leaves is not None:
                sub_kw["model_out_template"] = [model_leaves[i] for i in idxs]
            outs = sub._step_dense(
                [g_leaves[i] for i in idxs], [p_leaves[i] for i in idxs],
                sub_state, grad_scale=grad_scale, **sub_kw)
            sub_p, sub_new_state = outs[0], outs[1]
            for j, i in enumerate(idxs):
                new_p[i] = sub_p[j]
                for f in self._TREE_FIELDS:
                    new_state_leaves[f][i] = getattr(sub_new_state, f)[j]
                if model_leaves is not None:
                    new_model[i] = outs[2][j]
            new_step = sub_new_state.step

        unf = lambda ls: jax.tree_util.tree_unflatten(treedef, ls)
        out_state = state._replace(step=new_step, **{
            f: unf(new_state_leaves[f]) for f in self._TREE_FIELDS})
        if model_leaves is not None:
            return unf(new_p), out_state, unf(new_model)
        return unf(new_p), out_state

    # -- optax interop -----------------------------------------------------
    def as_optax(self):
        """View as an optax ``GradientTransformationExtraArgs`` computing
        ``updates = new_params - params`` (apply with optax.apply_updates)."""
        import optax

        def init_fn(params):
            return self.init(params)

        def update_fn(updates, state, params=None, **extra):
            if params is None:
                raise ValueError("this transformation requires params")
            new_params, new_state = self.step(updates, params, state)
            deltas = jax.tree_util.tree_map(
                lambda n, p: (n.astype(jnp.float32)
                              - p.astype(jnp.float32)).astype(p.dtype),
                new_params, params)
            return deltas, new_state

        return optax.GradientTransformationExtraArgs(init_fn, update_fn)
