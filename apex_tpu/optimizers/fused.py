"""Fused optimizers — functional counterparts of apex/optimizers/ (FusedAdam,
FusedLAMB, FusedSGD, FusedNovoGrad, FusedAdagrad). Each step is a single call
into the multi-tensor layer (ops/multi_tensor.py), which on TPU runs Pallas
bucket kernels — the analog of the reference's one-kernel-per-dtype-group
multi_tensor_applier launches (apex/optimizers/fused_adam.py:116-172).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu import ops
from apex_tpu.optimizers.base import FusedOptimizer, Schedule, resolve_lr

Tree = Any


class AdamState(NamedTuple):
    step: jax.Array
    exp_avg: Tree
    exp_avg_sq: Tree


class FusedAdam(FusedOptimizer):
    """Adam/AdamW with the reference's flags (apex/optimizers/fused_adam.py:4-88):
    ``adam_w_mode`` (decoupled decay), ``bias_correction``, ``amsgrad``
    unsupported exactly as in the reference (raises)."""

    _TREE_FIELDS = ("exp_avg", "exp_avg_sq")

    def __init__(self, lr: Schedule = 1e-3, *, bias_correction: bool = True,
                 betas: Tuple[float, float] = (0.9, 0.999), eps: float = 1e-8,
                 adam_w_mode: bool = True, weight_decay: float = 0.0,
                 amsgrad: bool = False, param_groups=None):
        if amsgrad:
            raise RuntimeError("FusedAdam does not support the AMSGrad "
                               "variant (parity with fused_adam.py:77-78).")
        self.lr = lr
        self.bias_correction = bias_correction
        self.betas = betas
        self.eps = eps
        self.adam_w_mode = adam_w_mode
        self.weight_decay = weight_decay
        self._init_groups(param_groups)

    def init(self, params: Tree) -> AdamState:
        zeros = lambda: jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        return AdamState(step=jnp.zeros((), jnp.int32),
                         exp_avg=zeros(), exp_avg_sq=zeros())

    def _step_dense(self, grads: Tree, params: Tree, state: AdamState, *,
             grad_scale: Optional[jax.Array] = None,
             ) -> Tuple[Tree, AdamState]:
        step = state.step + 1
        new_p, new_m, new_v = ops.multi_tensor_adam(
            grads, params, state.exp_avg, state.exp_avg_sq,
            lr=resolve_lr(self.lr, step), beta1=self.betas[0],
            beta2=self.betas[1], eps=self.eps, step=step,
            adam_w_mode=self.adam_w_mode,
            bias_correction=self.bias_correction,
            weight_decay=self.weight_decay, grad_scale=grad_scale)
        return new_p, AdamState(step=step, exp_avg=new_m, exp_avg_sq=new_v)


class SGDState(NamedTuple):
    step: jax.Array
    momentum_buf: Tree


class FusedSGD(FusedOptimizer):
    """SGD with momentum/dampening/nesterov/weight-decay
    (apex/optimizers/fused_sgd.py:6; kernel csrc/multi_tensor_sgd_kernel.cu).

    ``wd_after_momentum`` and ``materialize_master_grads`` mirror the
    reference's knobs; first-run momentum init matches torch's lazy
    initialization (momentum_buffer = d_p on first step).
    """

    _TREE_FIELDS = ("momentum_buf",)

    def __init__(self, lr: Schedule = 1e-3, *, momentum: float = 0.0,
                 dampening: float = 0.0, weight_decay: float = 0.0,
                 nesterov: bool = False, wd_after_momentum: bool = False,
                 materialize_master_grads: bool = True, param_groups=None):
        if nesterov and (momentum <= 0 or dampening != 0):
            raise ValueError("Nesterov momentum requires a momentum and zero "
                             "dampening")
        self.lr = lr
        self.momentum = momentum
        self.dampening = dampening
        self.weight_decay = weight_decay
        self.nesterov = nesterov
        self.wd_after_momentum = wd_after_momentum
        self._init_groups(param_groups)
        # False selects the amp no-materialize fast path: low-precision grads
        # feed the kernel directly with the unscale fused, and the kernel
        # emits the low-precision model copy alongside the fp32 master update
        # (apex/optimizers/fused_sgd.py:79, _process_optimizer.py:258-310).
        self.materialize_master_grads = materialize_master_grads

    def init(self, params: Tree) -> SGDState:
        return SGDState(
            step=jnp.zeros((), jnp.int32),
            momentum_buf=jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params))

    def _step_dense(self, grads: Tree, params: Tree, state: SGDState, *,
             grad_scale: Optional[jax.Array] = None,
             model_out_template: Optional[Tree] = None):
        step = state.step + 1
        scale = 1.0 if grad_scale is None else 1.0 / grad_scale
        # torch-style lazy momentum init: buf = (decayed) grad on step 1,
        # selected branchlessly inside the fused kernel.
        outs = ops.multi_tensor_sgd(
            grads, params, state.momentum_buf,
            lr=resolve_lr(self.lr, step),
            weight_decay=self.weight_decay, momentum=self.momentum,
            dampening=self.dampening, nesterov=self.nesterov,
            first_run=(step == 1),
            wd_after_momentum=self.wd_after_momentum,
            scale=scale, model_out_template=model_out_template)
        if model_out_template is not None:
            new_p, new_m, new_model = outs
            return new_p, SGDState(step=step, momentum_buf=new_m), new_model
        new_p, new_m = outs
        return new_p, SGDState(step=step, momentum_buf=new_m)


class LambState(NamedTuple):
    step: jax.Array
    exp_avg: Tree
    exp_avg_sq: Tree


class FusedLAMB(FusedOptimizer):
    """LAMB (apex/optimizers/fused_lamb.py:4): global grad-norm clip
    (multi_tensor_l2norm, :123-132), Adam moments, per-tensor trust ratio,
    optional NVLamb variant."""

    _TREE_FIELDS = ("exp_avg", "exp_avg_sq")

    def __init__(self, lr: Schedule = 1e-3, *, bias_correction: bool = True,
                 betas: Tuple[float, float] = (0.9, 0.999), eps: float = 1e-6,
                 weight_decay: float = 0.01, amsgrad: bool = False,
                 adam_w_mode: bool = True, grad_averaging: bool = True,
                 max_grad_norm: float = 1.0, use_nvlamb: bool = False,
                 param_groups=None):
        if amsgrad:
            raise RuntimeError("FusedLAMB does not support the AMSGrad "
                               "variant (parity with fused_lamb.py).")
        self.lr = lr
        self.bias_correction = bias_correction
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adam_w_mode = adam_w_mode
        self.grad_averaging = grad_averaging
        self.max_grad_norm = max_grad_norm
        self.use_nvlamb = use_nvlamb
        self._init_groups(param_groups)

    def _group_shared(self, grads, grad_scale):
        # The grad-norm clip is GLOBAL across param groups (the reference
        # computes one norm over all groups' grads, fused_lamb.py:123-132),
        # so compute it once here and forward to every group's step.
        gnorm, _ = ops.multi_tensor_l2norm(grads)
        if grad_scale is not None:
            gnorm = gnorm / grad_scale
        return {"global_grad_norm": gnorm}

    def init(self, params: Tree) -> LambState:
        zeros = lambda: jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        return LambState(step=jnp.zeros((), jnp.int32),
                         exp_avg=zeros(), exp_avg_sq=zeros())

    def _step_dense(self, grads: Tree, params: Tree, state: LambState, *,
             grad_scale: Optional[jax.Array] = None,
             global_grad_norm: Optional[jax.Array] = None,
             ) -> Tuple[Tree, LambState]:
        step = state.step + 1
        scale = 1.0 if grad_scale is None else 1.0 / grad_scale
        new_p, new_m, new_v = ops.multi_tensor_lamb(
            grads, params, state.exp_avg, state.exp_avg_sq,
            lr=resolve_lr(self.lr, step), beta1=self.betas[0],
            beta2=self.betas[1], eps=self.eps, step=step,
            bias_correction=self.bias_correction,
            weight_decay=self.weight_decay,
            grad_averaging=self.grad_averaging,
            adam_w_mode=self.adam_w_mode,
            max_grad_norm=self.max_grad_norm, use_nvlamb=self.use_nvlamb,
            scale=scale, global_grad_norm=global_grad_norm)
        return new_p, LambState(step=step, exp_avg=new_m, exp_avg_sq=new_v)


class NovoGradState(NamedTuple):
    step: jax.Array
    exp_avg: Tree
    v: Tree  # per-tensor scalars


class FusedNovoGrad(FusedOptimizer):
    """NovoGrad (apex/optimizers/fused_novograd.py:4): per-tensor second
    moments from grad norms; ``init_zero`` selects v_0 = 0 vs v_0 = |g_0|^2
    (reference ``init_zero`` arg)."""

    _TREE_FIELDS = ("exp_avg", "v")

    def __init__(self, lr: Schedule = 1e-3, *, bias_correction: bool = True,
                 betas: Tuple[float, float] = (0.95, 0.98), eps: float = 1e-8,
                 weight_decay: float = 0.0, grad_averaging: bool = True,
                 norm_type: int = 2, init_zero: bool = False,
                 param_groups=None):
        if norm_type not in (2,):
            raise ValueError("FusedNovoGrad supports norm_type=2 (the "
                             "reference kernel also only implements L2)")
        self.lr = lr
        self.bias_correction = bias_correction
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.grad_averaging = grad_averaging
        self.norm_type = norm_type
        self.init_zero = init_zero
        self._init_groups(param_groups)

    def init(self, params: Tree) -> NovoGradState:
        return NovoGradState(
            step=jnp.zeros((), jnp.int32),
            exp_avg=jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params),
            v=jax.tree_util.tree_map(
                lambda p: jnp.zeros((), jnp.float32), params))

    def _step_dense(self, grads: Tree, params: Tree, state: NovoGradState, *,
             grad_scale: Optional[jax.Array] = None,
             ) -> Tuple[Tree, NovoGradState]:
        step = state.step + 1
        scale = 1.0 if grad_scale is None else 1.0 / grad_scale
        new_p, new_m, new_v = ops.multi_tensor_novograd(
            grads, params, state.exp_avg, state.v,
            lr=resolve_lr(self.lr, step), beta1=self.betas[0],
            beta2=self.betas[1], eps=self.eps, step=step,
            weight_decay=self.weight_decay,
            bias_correction=self.bias_correction,
            grad_averaging=self.grad_averaging, norm_type=self.norm_type,
            init_zero=self.init_zero, first=(step == 1), scale=scale)
        return new_p, NovoGradState(step=step, exp_avg=new_m, v=new_v)


class AdagradState(NamedTuple):
    step: jax.Array
    sum: Tree


class FusedAdagrad(FusedOptimizer):
    """Adagrad (apex/optimizers/fused_adagrad.py:5,
    kernel csrc/multi_tensor_adagrad.cu)."""

    _TREE_FIELDS = ("sum",)

    def __init__(self, lr: Schedule = 1e-2, *, eps: float = 1e-10,
                 weight_decay: float = 0.0, adagrad_w_mode: bool = False,
                 param_groups=None):
        self.lr = lr
        self.eps = eps
        self.weight_decay = weight_decay
        self.adagrad_w_mode = adagrad_w_mode
        self._init_groups(param_groups)

    def init(self, params: Tree) -> AdagradState:
        return AdagradState(
            step=jnp.zeros((), jnp.int32),
            sum=jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params))

    def _step_dense(self, grads: Tree, params: Tree, state: AdagradState, *,
             grad_scale: Optional[jax.Array] = None,
             ) -> Tuple[Tree, AdagradState]:
        step = state.step + 1
        scale = 1.0 if grad_scale is None else 1.0 / grad_scale
        new_p, new_h = ops.multi_tensor_adagrad(
            grads, params, state.sum, lr=resolve_lr(self.lr, step),
            epsilon=self.eps, weight_decay=self.weight_decay,
            adagrad_w_mode=self.adagrad_w_mode, scale=scale)
        return new_p, AdagradState(step=step, sum=new_h)
