"""Persistent-bucket dense optimizer mode (VERDICT r3 #4) — the ZeRO
state layout without the sharding.

BASELINE.md's r2 analysis attributed most of the Pallas multi-tensor
kernels' 3-13x end-to-end loss to per-step tree<->bucket marshalling
(161 leaves x 7 operand trees for Adam). This wrapper removes the
marshalling from the *steady state*: parameters and optimizer state live
as ONE flat bucket per dtype ACROSS steps (the pointer-list persistence
of csrc/multi_tensor_apply.cuh:16-142, expressed as persistent arrays).
Per step only two tree conversions remain, both unavoidable:

  * ``unflatten(pb)`` — the tree view of the params for the forward;
  * ``flatten(grads)`` — one concat per dtype of the incoming grad tree.

Because a list of flat buckets is itself a pytree, the wrapped fused
optimizer's elementwise math runs on it unchanged — under either
multi-tensor backend (jnp fusion or the Pallas bucket kernels, which see
pre-flattened operands and skip their own packing).

Only elementwise-uniform optimizers can run on buckets: FusedLAMB's
per-tensor trust ratios and FusedNovoGrad's per-tensor second moments
would silently become per-BUCKET quantities, so those raise — use the
ZeRO optimizers (contrib.optimizers), whose segmented reductions keep
per-tensor semantics over flat shards. Param groups likewise need the
per-element segment machinery and raise here.

Usage::

    opt = BucketedOptimizer(FusedAdam(lr=1e-3))
    pb, state = opt.init(params)          # flat per-dtype buckets
    for batch in data:
        grads = jax.grad(loss)(opt.unflatten(pb), batch)
        pb, state = opt.step(opt.flatten(grads), pb, state)
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import jax

from apex_tpu.ops import buckets as _buckets
from apex_tpu.optimizers.fused import (FusedAdagrad, FusedAdam, FusedLAMB,
                                       FusedNovoGrad, FusedSGD)

Tree = Any

# Optimizers whose update is the same elementwise function for every
# element (no per-tensor reductions) — safe to run on concatenated
# buckets.
_ELEMENTWISE = (FusedAdam, FusedSGD, FusedAdagrad)


class BucketedOptimizer:
    """Persistent-bucket wrapper around an elementwise fused optimizer."""

    def __init__(self, inner):
        if isinstance(inner, (FusedLAMB, FusedNovoGrad)):
            raise ValueError(
                f"{type(inner).__name__} computes per-tensor reductions "
                "(trust ratios / per-tensor moments) that would become "
                "per-bucket on flat state; use the ZeRO optimizers "
                "(apex_tpu.contrib.optimizers), whose segmented "
                "reductions keep per-tensor semantics on flat shards")
        if not isinstance(inner, _ELEMENTWISE):
            raise ValueError(
                f"BucketedOptimizer supports {[c.__name__ for c in _ELEMENTWISE]}; "
                f"got {type(inner).__name__}")
        if inner.param_groups:
            raise ValueError(
                "BucketedOptimizer does not support param groups (per-group "
                "hyperparameters need per-element vectors over the bucket; "
                "the ZeRO optimizers implement that)")
        self.inner = inner
        self._tspec: Optional[_buckets.TreeBucketSpec] = None

    # -- layout -------------------------------------------------------------
    def flatten(self, tree: Tree) -> List[jax.Array]:
        """Tree -> per-dtype flat buckets (grads, once per step). The first
        call (via ``init``) fixes the layout; later trees must match it."""
        bs, tspec = _buckets.tree_flatten_buckets(tree)
        if self._tspec is None:
            self._tspec = tspec
        elif (tspec.treedef != self._tspec.treedef
              or tspec.leaf_dtypes != self._tspec.leaf_dtypes
              or tuple(s.shapes for s in tspec.bucket_specs)
              != tuple(s.shapes for s in self._tspec.bucket_specs)):
            raise ValueError(
                "tree structure/dtypes/shapes changed since init — re-init "
                "the BucketedOptimizer (bucket layout is static)")
        return bs

    def unflatten(self, bucket_params: Sequence[jax.Array]) -> Tree:
        """Buckets -> the param tree view (for the forward pass)."""
        if self._tspec is None:
            raise ValueError("call init() first")
        return _buckets.tree_unflatten_buckets(bucket_params, self._tspec)

    # -- optimizer protocol over buckets -------------------------------------
    def init(self, params: Tree) -> Tuple[List[jax.Array], Any]:
        """-> (bucket_params, state); state arrays are flat buckets too.
        Re-initializing establishes a fresh layout."""
        self._tspec = None
        pb = self.flatten(params)
        return pb, self.inner.init(pb)

    def step(self, grad_buckets: Sequence[jax.Array],
             bucket_params: Sequence[jax.Array], state: Any, *,
             grad_scale: Optional[jax.Array] = None, **kw):
        """One update entirely on flat buckets — zero tree marshalling."""
        if self.inner.param_groups:
            # a later inner.add_param_group would otherwise silently route
            # through _step_grouped, whose path filters would match flat-
            # bucket list indices instead of the original leaf names
            raise ValueError(
                "param groups were added to the wrapped optimizer after "
                "BucketedOptimizer construction; group filters cannot "
                "address leaves inside flat buckets")
        return self.inner.step(list(grad_buckets), list(bucket_params),
                               state, grad_scale=grad_scale, **kw)
