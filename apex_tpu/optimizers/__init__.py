"""apex_tpu.optimizers — fused optimizers (reference L4: apex/optimizers/)."""

from apex_tpu.optimizers.base import FusedOptimizer, resolve_lr
from apex_tpu.optimizers.fused import (
    FusedAdam, AdamState,
    FusedSGD, SGDState,
    FusedLAMB, LambState,
    FusedNovoGrad, NovoGradState,
    FusedAdagrad, AdagradState,
)
from apex_tpu.optimizers.bucketed import BucketedOptimizer
