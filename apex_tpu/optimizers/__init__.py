"""apex_tpu.optimizers (placeholder — populated incrementally)."""
