"""Environment gates for tests (reference apex/testing/common_utils.py:1-25:
``TEST_WITH_ROCM`` env flag + ``skipIfRocm`` decorator). Here the axis is
CPU-vs-TPU: ``APEX_TPU_TEST_WITH_TPU=1`` opts tests into requiring real
hardware."""

from __future__ import annotations

import functools
import os
import unittest

import jax

TEST_WITH_TPU = os.environ.get("APEX_TPU_TEST_WITH_TPU",
                               "0").lower() in ("1", "true", "yes")


def on_tpu() -> bool:
    return jax.default_backend() in ("tpu", "axon")


def skipIfNoTpu(fn):
    """Skip unless a TPU backend is present (reference skipIfRocm shape)."""
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        if not on_tpu():
            raise unittest.SkipTest("test requires TPU")
        return fn(*args, **kwargs)
    return wrapper


def skipIfCpu(fn):
    return skipIfNoTpu(fn)
