"""apex_tpu.testing (placeholder — populated incrementally)."""
