"""apex_tpu.testing — test gating utilities (reference apex/testing/
common_utils.py:12-25: TEST_WITH_ROCM / skipIfRocm)."""

from apex_tpu.testing.common_utils import (
    TEST_WITH_TPU,
    skipIfNoTpu,
    skipIfCpu,
    on_tpu,
)
