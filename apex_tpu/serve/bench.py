"""Synthetic load driver for the serving engine — the measurement side
of ``python -m apex_tpu.serve bench`` and benchmarks/serve_bench.py.

Two phases, one report:

  * **steady** (closed loop): ``requests`` synthetic prompts submitted
    up front, the engine drains them at its own pace. Measures the
    headline tokens/s plus p50/p99 TTFT and inter-token latency (from
    per-token host observation times — the same numbers the
    ``serve/ttft`` / ``serve/intertoken`` trace spans carry).
  * **overload** (2x offered load): twice the steady request count is
    thrown at an admission queue sized for HALF of it, with per-request
    SLO deadlines. The point is the shedding contract: rejected > 0
    (queue-full + deadline sheds), while every ADMITTED request still
    completes — goodput degrades by refusing work, never by corrupting
    accepted work. Goodput is completed-within-deadline over ALL
    submissions (shed requests count against it; see
    serve/admission.py).

The report dict is the SERVE_r*.json row schema — keys are stable;
unmeasured values are null, never absent.
"""

from __future__ import annotations

import time
from typing import List, Optional

import numpy as np

from apex_tpu.serve import metrics
from apex_tpu.serve import slo as slo_mod
from apex_tpu.serve.admission import AdmissionController
from apex_tpu.serve.engine import Engine, Request
from apex_tpu.serve.loader import LoadedModel
from apex_tpu.telemetry import ledger as ledger_mod


def _pct(samples: List[float], q: float) -> Optional[float]:
    if not samples:
        return None
    return float(np.percentile(np.asarray(samples, np.float64), q))


def _latency_stats(reqs: List[Request]) -> dict:
    ttft = [r.ttft_s for r in reqs if r.ttft_s is not None]
    inter: List[float] = []
    for r in reqs:
        ts = r.token_times
        inter.extend(b - a for a, b in zip(ts, ts[1:]))
    return {
        "ttft_ms": {"p50": _ms(_pct(ttft, 50)), "p99": _ms(_pct(ttft, 99))},
        "intertoken_ms": {"p50": _ms(_pct(inter, 50)),
                          "p99": _ms(_pct(inter, 99))},
    }


def _ms(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(v * 1e3, 3)


def _goodput(reqs: List[Request]) -> float:
    """Completed-in-deadline over ALL submissions. Requests without a
    deadline count as good when completed — and shed either way."""
    if not reqs:
        return 0.0
    good = 0
    for r in reqs:
        if r.state != "done":
            continue
        ind = r.in_deadline()
        good += 1 if (ind is None or ind) else 0
    return good / len(reqs)


def _prompts(n: int, vocab: int, prompt_len: int, seed: int
             ) -> List[List[int]]:
    rng = np.random.default_rng(seed)
    return [[int(t) for t in rng.integers(0, vocab, prompt_len)]
            for _ in range(n)]


def run_bench(loaded: LoadedModel, *, requests: int = 50,
              prompt_len: int = 8, max_new: int = 8, max_batch: int = 4,
              page: int = 16, max_context: Optional[int] = None,
              max_prompt: Optional[int] = None, in_flight: int = 2,
              overload: bool = True, deadline_s: float = 30.0,
              slo: Optional["slo_mod.SLOSpec"] = None,
              seed: int = 0) -> dict:
    """Run the two-phase synthetic load against ``loaded`` and return
    the SERVE report row (see the module docstring). ``slo`` (an
    :class:`apex_tpu.serve.slo.SLOSpec` or a spec dict) scores the
    run's whole request population; the report's ``slo`` key is null
    when no spec is given — stable schema, never absent."""
    if isinstance(slo, dict):
        slo = slo_mod.SLOSpec.from_dict(slo)
    if requests < 1:
        raise ValueError(f"requests must be >= 1, got {requests}")
    max_prompt = prompt_len if max_prompt is None else max_prompt
    if max_context is None:
        max_context = -(-(max_prompt + max_new) // page) * page
    vocab = loaded.spec.vocab
    prompts = _prompts(requests, vocab, prompt_len, seed)

    # -- steady phase ------------------------------------------------------
    eng = Engine(loaded, max_batch=max_batch, page=page,
                 max_context=max_context, max_prompt=max_prompt,
                 in_flight=in_flight,
                 admission=AdmissionController(max_queue=requests))
    reqs = [eng.request(p, max_new) for p in prompts]
    t0 = time.perf_counter()
    eng.run(reqs)
    elapsed = time.perf_counter() - t0
    tokens = eng.tokens_emitted
    tps = tokens / elapsed if elapsed > 0 else 0.0
    metrics.gauge(metrics.TOKENS_PER_S, tps)
    completed = sum(r.state == "done" for r in reqs)
    steady = {
        "requests": requests,
        "completed": completed,
        "tokens": tokens,
        "tokens_per_s": round(tps, 2),
        "elapsed_s": round(elapsed, 4),
        **_latency_stats(reqs),
    }

    # -- overload phase (2x offered load, queue sized for half) -----------
    over = None
    if overload:
        n_over = 2 * requests
        over_prompts = _prompts(n_over, vocab, prompt_len, seed + 1)
        adm = AdmissionController(max_queue=max(1, requests // 2))
        eng2 = Engine(loaded, max_batch=max_batch, page=page,
                      max_context=max_context, max_prompt=max_prompt,
                      in_flight=in_flight, admission=adm)
        oreqs = [eng2.request(p, max_new, deadline_s=deadline_s)
                 for p in over_prompts]
        t0 = time.perf_counter()
        eng2.run(oreqs)
        oelapsed = time.perf_counter() - t0
        rejected = sum(r.state == "rejected" for r in oreqs)
        expired = sum(1 for rj in adm.rejected
                      if rj.reason == "deadline")
        expired_inflight = len(eng2.expired_inflight)
        over = {
            "requests": n_over,
            "admitted": n_over - rejected,
            "completed": sum(r.state == "done" for r in oreqs),
            "rejected": rejected,
            # the shed-gate reads the SUM of both expiry paths:
            # ``expired`` counts queued requests shed at pop time,
            # ``expired_inflight`` counts deadlines that passed
            # mid-decode (wasted tokens the ledger prices)
            "expired": expired,
            "expired_inflight": expired_inflight,
            "expired_total": expired + expired_inflight,
            "goodput": round(_goodput(oreqs), 4),
            "tokens_per_s": round(
                eng2.tokens_emitted / oelapsed, 2) if oelapsed else 0.0,
            "elapsed_s": round(oelapsed, 4),
        }
        # the shedding contract: admitted requests COMPLETE (or expire
        # mid-decode, which the gate reads separately) — a request that
        # was neither shed, finished, nor expired is an engine bug the
        # bench must surface, not average away
        over["stranded"] = (n_over - over["completed"] - rejected
                            - expired_inflight)

    all_reqs = reqs + (oreqs if overload else [])
    slo_report = None
    if slo is not None:
        slo_report = slo_mod.evaluate(
            slo_mod.records_from_requests(all_reqs), slo)
    led = ledger_mod.serve_ledger_from_requests(all_reqs)
    ledger_mod.emit_serve(led)

    return {
        "metric": "serve_tokens_per_s",
        "value": steady["tokens_per_s"],
        "unit": "tokens/s",
        "model": {"step": loaded.step, "spec": loaded.spec.to_dict(),
                  "quant": (loaded.quant.row() if loaded.quant else None),
                  "pruned": loaded.pruned,
                  "directory": loaded.directory},
        "config": {"max_batch": max_batch, "page": page,
                   "max_context": max_context, "max_prompt": max_prompt,
                   "in_flight": in_flight, "prompt_len": prompt_len,
                   "max_new": max_new, "deadline_s": deadline_s,
                   "seed": seed},
        "steady": steady,
        "overload": over,
        "slo": slo_report,
        "ledger": led,
    }
