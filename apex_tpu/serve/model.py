"""Functional decode forward over ``TransformerLM`` params — the model
side of the paged serving stack.

The training decode path stores K/V in per-module flax ``"cache"``
variables: one dense ``(B, H, max_len, D)`` buffer per layer per batch.
Paging replaces those buffers with the shared pool + block tables of
:mod:`~apex_tpu.serve.kvcache`, which no flax variable can express — so
the serve stack runs the decode step FUNCTIONALLY over the same param
tree, mirroring ``TransformerLM``'s per-token math op for op
(``layer_norm`` is literally the same function the flax module wraps;
the dense/einsum chains reproduce flax's dtype-promotion rules). The
bitwise pin in tests/test_serve_decode.py holds this mirror to the
dense-cache decode path exactly.

Prefill is NOT re-implemented: it runs the model's own fresh-cache
decode apply (which takes the existing causal flash forward — see
``SelfMultiheadAttn.decode``'s fresh-prefill path), and the resulting
dense prompt cache is scattered into pages.

Supported model surface (validated by :meth:`ModelSpec.check_params`):
the dense decoder configuration ``TransformerLM(vocab, layers, embed,
heads)`` with learned absolute positions, tied or untied head. MoE,
relative-bias/ALiBi and tensor/sequence-parallel checkpoints are
rejected loudly at load — serving them is future work, and a silent
wrong-math forward is the one failure mode this module must not have.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Mapping, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.models import TransformerLM
from apex_tpu.normalization.fused_layer_norm import layer_norm
from apex_tpu.serve import kvcache
from apex_tpu.serve.decode import paged_decode_attention


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    """The minimal model description serving needs — written into
    snapshot manifests by examples/gpt/train_lm.py (``extra["model"]``)
    so :func:`serve.load_model` is self-contained."""

    vocab: int
    layers: int
    embed_dim: int
    heads: int
    max_seq: int = 4096
    mlp_ratio: int = 4
    tie_embeddings: bool = False

    @property
    def head_dim(self) -> int:
        return self.embed_dim // self.heads

    def model(self, **overrides) -> TransformerLM:
        return TransformerLM(
            vocab_size=self.vocab, num_layers=self.layers,
            embed_dim=self.embed_dim, num_heads=self.heads,
            max_seq=self.max_seq, mlp_ratio=self.mlp_ratio,
            tie_embeddings=self.tie_embeddings, **overrides)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "ModelSpec":
        """Build from a manifest ``extra["model"]`` dict. Unsupported
        trained-in features recorded there (MoE, attention position
        biases) are rejected here — before any payload materializes."""
        for flag in ("moe", "relative_bias", "alibi"):
            if d.get(flag):
                raise NotImplementedError(
                    f"serve does not support checkpoints trained with "
                    f"{flag!r} yet (the paged decode forward mirrors "
                    f"the dense learned-position configuration only)")
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})

    def check_params(self, params: Mapping[str, Any]) -> None:
        """Loud validation that a param tree is the configuration the
        functional decode mirrors — unsupported trained-in features
        would otherwise silently produce wrong logits."""
        if "pos_emb" not in params:
            raise NotImplementedError(
                "serve decode requires the learned-absolute-position "
                "configuration (no pos_emb table found: relative_bias/"
                "alibi checkpoints are not supported yet)")
        blk = params.get("block_0", {})
        attn = blk.get("attn", {})
        for bad in ("rel_bias", "alibi_slopes"):
            if bad in attn:
                raise NotImplementedError(
                    f"serve decode does not support attention position "
                    f"biases ({bad} present in checkpoint)")
        if "moe" in blk:
            raise NotImplementedError(
                "serve decode does not support MoE checkpoints")
        if self.tie_embeddings != ("head" not in params):
            raise ValueError(
                f"tie_embeddings={self.tie_embeddings} but checkpoint "
                f"{'has no' if 'head' not in params else 'has a'} "
                f"separate head — spec/params mismatch")


# ---------------------------------------------------------------------------
# flax-equivalent primitive ops (dtype promotion mirrored exactly)
# ---------------------------------------------------------------------------

def _dense(x, p):
    """``flax.linen.Dense`` with ``dtype=None``: inputs/kernel/bias
    promote to a common dtype, then dot + bias — the promotion rule is
    what keeps bf16 checkpoints bit-compatible with the flax path."""
    kernel = p["kernel"]
    bias = p.get("bias")
    args = [x, kernel] + ([] if bias is None else [bias])
    dt = jnp.result_type(*(a.dtype for a in args))
    y = jnp.dot(x.astype(dt), kernel.astype(dt))
    if bias is not None:
        y = y + bias.astype(dt)
    return y


def _ln(x, p):
    return layer_norm(x, p["weight"], p["bias"]).astype(x.dtype)


def _split_heads(x, num_heads):
    b, s, e = x.shape
    return x.reshape(b, s, num_heads, e // num_heads).transpose(0, 2, 1, 3)


def _merge_heads(x):
    b, h, s, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b, s, h * d)


def decode_step(params, spec: ModelSpec, pool: kvcache.KVPool,
                tokens: jax.Array, positions: jax.Array,
                block_tables: jax.Array, active: jax.Array
                ) -> Tuple[jax.Array, kvcache.KVPool]:
    """One batched decode step: embed ``tokens`` at ``positions``, write
    each layer's new K/V into the pool, attend over the resident pages,
    and return fp32 logits for the NEXT position.

    ``tokens``: (B,) int32 current input token per slot. ``positions``:
    (B,) int32 global position of that token (== tokens already
    resident). ``block_tables``: (B, pages_per_slot) int32. ``active``:
    (B,) bool — dead slots neither write pages nor produce meaningful
    logits (their rows are garbage by contract; the engine discards
    them). Returns ``(logits (B, vocab) fp32, updated pool)``.

    Every op mirrors ``TransformerLM.__call__`` with ``decode=True`` on
    a 1-token input — pinned bitwise against that path in
    tests/test_serve_decode.py.
    """
    h = spec.heads
    scale = 1.0 / math.sqrt(spec.head_dim)
    page = pool.page
    num_pages = pool.num_pages
    seq_lens = jnp.where(active, positions + 1, 0).astype(jnp.int32)
    # page/row of the incoming token; dead slots route out of range so
    # the page scatter drops them
    pid = jnp.take_along_axis(
        block_tables, (positions[:, None] // page), axis=1)[:, 0]
    pid = jnp.where(active, pid, num_pages).astype(jnp.int32)
    off = (positions % page).astype(jnp.int32)

    emb_table = params["tok_emb"]["embedding"]
    x = jnp.take(emb_table, tokens[:, None], axis=0)      # (B, 1, E)
    pos_table = params["pos_emb"]["embedding"]
    x = x + jnp.take(pos_table, positions[:, None], axis=0)

    new_k, new_v = list(pool.k), list(pool.v)
    for i in range(spec.layers):
        p = params[f"block_{i}"]
        y = _ln(x, p["ln1"])
        qkv = _dense(y, p["attn"]["in_proj"])             # (B, 1, 3E)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = _split_heads(q, h)                            # (B, H, 1, D)
        k = _split_heads(k, h)
        v = _split_heads(v, h)
        kp, vp = kvcache.write_token(
            new_k[i], new_v[i], k[:, :, 0], v[:, :, 0], pid, off)
        new_k[i], new_v[i] = kp, vp
        ctx = paged_decode_attention(q, kp, vp, block_tables, seq_lens,
                                     scale=scale)
        a = _dense(_merge_heads(ctx).astype(x.dtype),
                   p["attn"]["out_proj"])
        x = x + a
        y = _ln(x, p["ln2"])
        m = jax.nn.gelu(_dense(y, p["fc1"]))
        x = x + _dense(m, p["fc2"])

    x = _ln(x, params["ln_f"])
    if spec.tie_embeddings:
        # flax Embed.attend: promote then dot against the table^T
        dt = jnp.result_type(x.dtype, emb_table.dtype)
        logits = jnp.dot(x.astype(dt), emb_table.astype(dt).T)
    else:
        logits = _dense(x, params["head"])
    return logits[:, 0].astype(jnp.float32), kvcache.KVPool(
        k=tuple(new_k), v=tuple(new_v))


def prefill(params, spec: ModelSpec, prompt: jax.Array,
            length: jax.Array, pool: kvcache.KVPool,
            block_row: jax.Array
            ) -> Tuple[jax.Array, jax.Array, kvcache.KVPool]:
    """Prefill ONE request: run the model's own fresh-cache decode apply
    over the padded prompt (this takes the existing causal flash
    forward — see SelfMultiheadAttn's fresh-prefill path), scatter the
    resulting dense prompt K/V into the request's pages, and return
    ``(logits_at_last_valid (vocab,) fp32, first_token, updated pool)``.

    ``prompt``: (S_max,) int32 padded to the engine's static prompt
    width (one compile regardless of true length — trailing padding is
    causally invisible to the valid prefix). ``length``: scalar int32
    true prompt length. ``block_row``: (pages_per_slot,) page list.
    """
    s_max = prompt.shape[0]
    dec = spec.model(decode=True, decode_max_len=s_max, dropout=0.0,
                     decode_impl="einsum")
    logits, vs = dec.apply({"params": params}, prompt[None],
                           mutable=["cache"])
    last = logits[0, length - 1].astype(jnp.float32)      # (vocab,)
    first_token = jnp.argmax(last, axis=-1).astype(jnp.int32)
    new_k, new_v = list(pool.k), list(pool.v)
    cache = vs["cache"]
    for i in range(spec.layers):
        ck = cache[f"block_{i}"]["attn"]["cached_key"][0]    # (H, S, D)
        cv = cache[f"block_{i}"]["attn"]["cached_value"][0]
        new_k[i], new_v[i] = kvcache.write_prompt(
            new_k[i], new_v[i], ck, cv, block_row, length)
    return last, first_token, kvcache.KVPool(k=tuple(new_k),
                                             v=tuple(new_v))
