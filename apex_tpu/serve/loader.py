"""``serve.load_model`` — from a SnapshotManager directory to a
servable model.

Order of operations is the safety story:

  1. ``latest_manifest()`` — the newest READABLE manifest, no payload
     touched yet.
  2. Layout fingerprint check — if the caller expects a layout, it is
     validated against the manifest BEFORE any array materializes (a
     wrong-topology restore is a config error; failing it after loading
     gigabytes is the failure mode ``checkpoint._check_layout`` exists
     to prevent).
  3. Model spec — from the manifest's ``extra["model"]`` (written by
     examples/gpt/train_lm.py) or an explicit ``spec=``; unsupported
     trained-in features (MoE, attention biases) are rejected here,
     still before materialization.
  4. Template build — the exact (params, opt_state) structure the
     trainer saved, rebuilt from the spec + the manifest's recorded
     ``opt_level`` via the same ``amp.initialize`` / ``amp.cast_model``
     recipe train_lm runs (``restore_npz``'s structure fingerprint
     demands an exact match). Shapes only — ``jax.eval_shape``, no
     weights allocated.
  5. Restore, keep ``params``, drop the optimizer state. A params-only
     snapshot (the serve-side re-publish format) restores against the
     params-only template as a fallback.
  6. Opt-in transforms: ``quantize="bf16"|"int8"``
     (:mod:`~apex_tpu.serve.quant`) and ``prune=True``
     (``sparsity.prune_for_serving`` — 2:4 checkpoints load like any
     other; the flag applies one-shot pruning at load).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp

from apex_tpu import amp, checkpoint, optimizers
from apex_tpu.resilience.snapshot import SnapshotManager
from apex_tpu.serve.model import ModelSpec
from apex_tpu.serve.quant import QuantReport, quantize_params


class LoadedModel(NamedTuple):
    """Everything the engine needs, plus provenance for the bench
    report."""

    model: Any                     # TransformerLM (dense decode config)
    params: Any
    spec: ModelSpec
    step: int
    generation: int
    manifest: dict
    directory: str
    quant: Optional[QuantReport] = None
    pruned: bool = False


def _template(spec: ModelSpec, opt_level: str):
    """The (params, opt_state) structure train_lm snapshots — rebuilt
    shape-only. Mirrors train_lm's init exactly: fp32 flax init, amp
    model cast (no-bn policy: transformers have no batchnorm), then the
    amp-wrapped FusedAdam state over the CAST params."""
    model = spec.model()
    init_tokens = jnp.zeros((1, min(spec.max_seq, 128)), jnp.int32)
    _, aopt = amp.initialize(None, optimizers.FusedAdam(lr=1e-3),
                             opt_level=opt_level, verbosity=0)

    def build():
        p32 = model.init(jax.random.PRNGKey(0), init_tokens)["params"]
        p = amp.cast_model(p32, amp.resolve(
            opt_level, keep_batchnorm_fp32=False))
        return p, aopt.init(p)

    return jax.eval_shape(build)


def load_model(directory: str, *, spec: Optional[ModelSpec] = None,
               layout=None, quantize: Optional[str] = None,
               prune: bool = False) -> LoadedModel:
    """Load the newest complete snapshot under ``directory`` for
    serving. See the module docstring for the validation order.

    ``layout``: expected parallelism layout — its fingerprint is
    checked against the manifest before the payload loads (pass the
    layout the checkpoint was TRAINED under; None skips the check, the
    ``checkpoint.restore_npz`` convention). ``quantize``: None |
    ``"bf16"`` | ``"int8"``. ``prune``: apply one-shot 2:4 pruning
    (``sparsity.prune_for_serving``) to the loaded params.
    """
    mgr = SnapshotManager(directory)
    man = mgr.latest_manifest()
    if man is None:
        raise ValueError(
            f"no readable snapshot manifest under {directory!r} — "
            f"train with --snapshot-dir (examples/gpt/train_lm.py) or "
            f"point at an existing SnapshotManager directory")
    if layout is not None:
        # BEFORE materialization: a layout mismatch must cost zero
        # array bytes (restore_latest would also catch it, but only
        # per-generation during the load)
        checkpoint._check_layout(man.get("layout"), layout, directory)
    extra = man.get("extra") or {}
    if spec is None:
        md = extra.get("model")
        if not md:
            raise ValueError(
                f"snapshot manifest under {directory!r} records no "
                f"model dimensions (extra['model']) — it predates the "
                f"serving manifest extension; pass spec=ModelSpec(...) "
                f"matching the training run")
        spec = ModelSpec.from_dict(md)
    opt_level = str(extra.get("opt_level", "O0"))

    template = _template(spec, opt_level)
    try:
        restored = mgr.restore_latest(template, layout=layout)
        params = restored.state[0]
    except ValueError:
        # params-only snapshot (serve re-publish format): retry against
        # the params template alone before giving up
        restored = mgr.restore_latest(template[0], layout=layout)
        params = restored.state
    spec.check_params(params)

    report = None
    if quantize is not None:
        params, report = quantize_params(params, quantize)
    if prune:
        from apex_tpu import sparsity
        params = sparsity.prune_for_serving(params)
    return LoadedModel(
        model=spec.model(), params=params, spec=spec,
        step=restored.step, generation=restored.generation,
        manifest=man, directory=str(directory), quant=report,
        pruned=bool(prune))
