"""Declarative serving SLOs — attainment, burn rates, and violator
attribution over per-request records.

An :class:`SLOSpec` names latency/goodput targets; :func:`evaluate`
scores a set of request records (from ``telemetry.requests.join`` on a
telemetry JSONL, or :func:`records_from_requests` on live ``Request``
objects) against it. The CLI half is ``python -m apex_tpu.serve slo
run.jsonl`` (serve/cli.py) with the repo exit-code contract: 0 = every
target met, 3 = violated, 1 = bad input, 2 = usage.

Scoring is SRE-honest:

  * A latency target is ``<metric>_p<q>_ms``: "the q-th percentile of
    <metric> stays under this many milliseconds". Attainment is the
    fraction of ALL terminal requests under the threshold — a request
    that was shed or expired never produced the metric and counts as a
    MISS (value = +inf), not an exemption.
  * Burn rate is the SRE error-budget form: with target percentile q
    the violation budget is ``1 - q/100``; burn = observed violation
    fraction / budget, reported over three windows of the run (full,
    last half, last quarter by submit time) so a late-run regression
    shows as short-window burn >> long-window burn.
  * ``goodput_min`` prices shed work the same way the bench does:
    completed-in-deadline over ALL submissions.

Violators are ranked by worst relative excess over any target, each
with per-phase time attribution (queued vs prefill vs decode vs shed)
so "which requests missed p99 and where did their time go" is one table.
"""

from __future__ import annotations

import dataclasses
import json
import math
from typing import Any, Dict, List, Optional

import numpy as np

# metric key -> record field holding seconds
_METRIC_FIELDS = {"ttft": "ttft_s", "tpot": "tpot_s", "e2e": "e2e_s"}
_TERMINAL = ("done", "rejected", "expired")


@dataclasses.dataclass
class SLOSpec:
    """Declarative SLO targets. Every field is optional — None means
    "no target on this axis"; at least one must be set for a spec to be
    evaluable. Latency thresholds are milliseconds; ``goodput_min`` is
    a fraction of submissions (0..1)."""

    ttft_p50_ms: Optional[float] = None
    ttft_p99_ms: Optional[float] = None
    tpot_p50_ms: Optional[float] = None
    tpot_p99_ms: Optional[float] = None
    e2e_p50_ms: Optional[float] = None
    e2e_p99_ms: Optional[float] = None
    goodput_min: Optional[float] = None

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SLOSpec":
        fields = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - fields
        if unknown:
            raise ValueError(
                f"unknown SLO spec keys {sorted(unknown)} "
                f"(known: {sorted(fields)})")
        vals = {k: (None if v is None else float(v))
                for k, v in d.items()}
        return cls(**vals)

    @classmethod
    def from_file(cls, path: str) -> "SLOSpec":
        with open(path, "r", encoding="utf-8") as f:
            d = json.load(f)
        if not isinstance(d, dict):
            raise ValueError(f"SLO spec {path} must be a JSON object")
        return cls.from_dict(d)

    def to_dict(self) -> Dict[str, Optional[float]]:
        return dataclasses.asdict(self)

    def latency_targets(self) -> List[Dict[str, float]]:
        """[{metric, percentile, target_ms}] for every set latency
        field."""
        out = []
        for metric in _METRIC_FIELDS:
            for q in (50, 99):
                v = getattr(self, f"{metric}_p{q}_ms")
                if v is not None:
                    out.append({"metric": metric, "percentile": q,
                                "target_ms": float(v)})
        return out

    def empty(self) -> bool:
        return not self.latency_targets() and self.goodput_min is None


def records_from_requests(reqs) -> List[dict]:
    """Build SLO records directly from live ``serve.engine.Request``
    objects — same shape as ``telemetry.requests.join`` produces from a
    JSONL, so the bench can score a run without a telemetry sink."""
    out = []
    for r in reqs:
        queued_s = (None if r.t_admit is None or r.submitted_s is None
                    else r.t_admit - r.submitted_s)
        if queued_s is None and r.state == "rejected":
            queued_s = 0.0
        prefill_s = (None if r.t_first is None or r.t_admit is None
                     else r.t_first - r.t_admit)
        decode_s = (None if r.t_last is None or r.t_first is None
                    else r.t_last - r.t_first)
        end = r.t_done if r.t_done is not None else r.t_last
        e2e_s = (None if end is None or r.submitted_s is None
                 else end - r.submitted_s)
        tokens = len(r.tokens)
        tpot_s = (decode_s / (tokens - 1)
                  if decode_s is not None and tokens > 1 else None)
        out.append({
            "rid": r.rid, "process": 0, "state": r.state,
            "prompt_len": len(r.prompt), "max_new": r.max_new_tokens,
            "deadline_s": r.deadline_s, "ts_submit": r.submitted_s,
            "queued_s": queued_s, "prefill_s": prefill_s,
            "decode_s": decode_s, "e2e_s": e2e_s, "ttft_s": r.ttft_s,
            "tpot_s": tpot_s, "tokens": tokens, "slot": None,
            "reason": r.reject_reason, "in_deadline": r.in_deadline(),
        })
    return out


def _metric_ms(rec: dict, metric: str) -> float:
    """A record's value for one latency metric, in ms. A request that
    never produced the measurement (shed, expired before first token)
    is an SLO miss, not a sampling gap: +inf."""
    v = rec.get(_METRIC_FIELDS[metric])
    if v is None:
        return math.inf
    return float(v) * 1e3


def _pctile(values: List[float], q: float) -> Optional[float]:
    if not values:
        return None
    arr = np.asarray(values, np.float64)
    if np.isinf(arr).any():
        # percentile over a set containing inf: sort-based definition
        arr = np.sort(arr)
        idx = min(len(arr) - 1, int(math.ceil(q / 100.0 * len(arr))) - 1)
        return float(arr[max(0, idx)])
    return float(np.percentile(arr, q))


def _windows(records: List[dict]) -> List[Dict[str, Any]]:
    """(label, record subset) over the submit-time span: full run, last
    half, last quarter. Records without a submit time ride in every
    window (they cannot be placed, and dropping them would hide
    misses)."""
    stamped = [r for r in records if r.get("ts_submit") is not None]
    if not stamped:
        return [{"label": "full", "records": records}]
    t0 = min(r["ts_submit"] for r in stamped)
    t1 = max(r["ts_submit"] for r in stamped)
    span = t1 - t0
    out = [{"label": "full", "records": records}]
    for label, frac in (("half", 0.5), ("quarter", 0.25)):
        cut = t1 - span * frac
        sub = [r for r in records
               if r.get("ts_submit") is None or r["ts_submit"] >= cut]
        out.append({"label": label, "records": sub})
    return out


def _goodput(records: List[dict]) -> Optional[float]:
    if not records:
        return None
    good = 0
    for r in records:
        if r["state"] != "done":
            continue
        ind = r.get("in_deadline")
        good += 1 if (ind is None or ind) else 0
    return good / len(records)


def evaluate(records: List[dict], spec: SLOSpec) -> Dict[str, Any]:
    """Score ``records`` against ``spec``. Returns the SLO report dict
    (JSON-able; the SERVE_r*.json ``slo`` key and the CLI's --json
    output). ``met`` is the exit-code verdict: every set target held."""
    if spec.empty():
        raise ValueError("SLO spec sets no targets")
    terminal = [r for r in records if r["state"] in _TERMINAL]
    windows = _windows(terminal)
    targets = []
    for t in spec.latency_targets():
        metric, q, thr = t["metric"], t["percentile"], t["target_ms"]
        values = [_metric_ms(r, metric) for r in terminal]
        obs = _pctile(values, q)
        met = obs is not None and obs <= thr
        budget = 1.0 - q / 100.0
        burn = {}
        for w in windows:
            wv = [_metric_ms(r, metric) for r in w["records"]]
            viol = (sum(1 for v in wv if v > thr) / len(wv)
                    if wv else 0.0)
            burn[w["label"]] = (round(viol / budget, 3) if budget > 0
                                else (math.inf if viol else 0.0))
        targets.append({
            "metric": metric, "percentile": q, "target_ms": thr,
            "observed_ms": (None if obs is None or math.isinf(obs)
                            else round(obs, 3)),
            "unbounded": obs is not None and math.isinf(obs),
            "attainment": (round(
                sum(1 for v in values if v <= thr) / len(values), 4)
                if values else None),
            "met": bool(met),
            "burn": burn,
        })
    goodput = None
    if spec.goodput_min is not None:
        g = _goodput(terminal)
        goodput = {"min": spec.goodput_min,
                   "observed": (None if g is None else round(g, 4)),
                   "met": g is not None and g >= spec.goodput_min}
    met = all(t["met"] for t in targets) \
        and (goodput is None or goodput["met"])
    return {
        "spec": spec.to_dict(),
        "requests": len(terminal),
        "targets": targets,
        "goodput": goodput,
        "violators": violators(terminal, spec),
        "met": bool(met),
    }


def violators(records: List[dict], spec: Optional[SLOSpec] = None,
              top: int = 5) -> List[dict]:
    """Worst offenders with per-phase attribution. With a spec, a
    violator exceeds at least one latency target (score = worst
    relative excess); without one, ranks by e2e latency with
    deadline-missers and shed/expired requests first."""
    targets = spec.latency_targets() if spec is not None else []
    scored = []
    for r in records:
        if r["state"] not in _TERMINAL:
            continue
        if targets:
            score = 0.0
            for t in targets:
                v = _metric_ms(r, t["metric"])
                if t["target_ms"] > 0:
                    score = max(score, v / t["target_ms"])
            if score <= 1.0:
                continue
        else:
            missed = (r["state"] != "done"
                      or r.get("in_deadline") is False)
            e2e = r.get("e2e_s")
            score = (math.inf if missed
                     else (0.0 if e2e is None else e2e))
            if score == 0.0:
                continue
        scored.append((score, r))
    scored.sort(key=lambda sr: (sr[0], sr[1].get("e2e_s") or 0.0),
                reverse=True)
    out = []
    for score, r in scored[:top]:
        out.append({
            "rid": r["rid"], "process": r.get("process", 0),
            "state": r["state"], "reason": r.get("reason"),
            "score": (None if math.isinf(score) else round(score, 3)),
            "e2e_ms": (None if r.get("e2e_s") is None
                       else round(r["e2e_s"] * 1e3, 3)),
            "queued_ms": (None if r.get("queued_s") is None
                          else round(r["queued_s"] * 1e3, 3)),
            "prefill_ms": (None if r.get("prefill_s") is None
                           else round(r["prefill_s"] * 1e3, 3)),
            "decode_ms": (None if r.get("decode_s") is None
                          else round(r["decode_s"] * 1e3, 3)),
        })
    return out


def describe(records: List[dict]) -> Optional[Dict[str, Any]]:
    """Spec-free per-request summary for ``telemetry summarize``:
    TTFT/TPOT/e2e percentiles over terminal requests, deadline
    attainment, and the top violators (slowest / deadline-missing) with
    phase attribution. None when there are no terminal records."""
    terminal = [r for r in records if r["state"] in _TERMINAL]
    if not terminal:
        return None
    out: Dict[str, Any] = {"requests": len(terminal)}
    states: Dict[str, int] = {}
    for r in terminal:
        states[r["state"]] = states.get(r["state"], 0) + 1
    out["by_state"] = states
    for metric, field in _METRIC_FIELDS.items():
        vals = [r[field] * 1e3 for r in terminal
                if r.get(field) is not None]
        out[f"{metric}_ms"] = (
            None if not vals else
            {"p50": round(_pctile(vals, 50), 3),
             "p99": round(_pctile(vals, 99), 3),
             "max": round(max(vals), 3), "n": len(vals)})
    with_deadline = [r for r in terminal
                     if r.get("deadline_s") is not None]
    out["deadline_attainment"] = (
        None if not with_deadline else
        round(sum(1 for r in with_deadline
                  if r["state"] == "done"
                  and r.get("in_deadline") is not False)
              / len(with_deadline), 4))
    out["goodput"] = (None if _goodput(terminal) is None
                      else round(_goodput(terminal), 4))
    out["top_violators"] = violators(terminal)
    return out


def format_report(report: Dict[str, Any]) -> str:
    """Human rendering of an :func:`evaluate` report (the CLI's default
    output; --json prints the dict instead)."""
    lines = [f"slo: {report['requests']} requests, "
             f"{'MET' if report['met'] else 'VIOLATED'}"]
    for t in report["targets"]:
        obs = ("unbounded (shed/expired in tail)" if t["unbounded"]
               else "n/a" if t["observed_ms"] is None
               else f"{t['observed_ms']:.3f}ms")
        att = ("n/a" if t["attainment"] is None
               else f"{t['attainment'] * 100:.2f}%")
        burn = ", ".join(f"{k}={v}" for k, v in t["burn"].items())
        lines.append(
            f"  {t['metric']} p{t['percentile']} <= "
            f"{t['target_ms']:g}ms: observed {obs} "
            f"[{'ok' if t['met'] else 'VIOLATED'}] "
            f"attainment {att} burn({burn})")
    g = report.get("goodput")
    if g:
        obs = "n/a" if g["observed"] is None else f"{g['observed']:.4f}"
        lines.append(f"  goodput >= {g['min']:g}: observed {obs} "
                     f"[{'ok' if g['met'] else 'VIOLATED'}]")
    if report["violators"]:
        lines.append("  top violators (time attribution):")
        for v in report["violators"]:
            phases = ", ".join(
                f"{k[:-3]}={v[k]:.1f}ms" for k in
                ("queued_ms", "prefill_ms", "decode_ms")
                if v[k] is not None)
            tail = f" shed={v['reason']}" if v["reason"] else ""
            e2e = ("n/a" if v["e2e_ms"] is None
                   else f"{v['e2e_ms']:.1f}ms")
            lines.append(
                f"    r{v['rid']} [{v['state']}{tail}] "
                f"e2e={e2e} ({phases or 'no phases observed'})")
    return "\n".join(lines)
