"""Admission control — the bounded front door of the serving engine.

Overload policy (docs/serve.md "shedding"): a bounded FIFO queue sheds
at SUBMIT when full (``queue_full``), and sheds QUEUED requests whose
SLO deadline has already passed at pop time (``deadline`` — running a
request that cannot possibly meet its deadline burns decode capacity
that on-time requests need; rejecting it at admission is the honest
form of the same failure). Requests carrying a deadline are also
screened at submit against the running TTFT estimate: if the queue wait
already makes the deadline unreachable, shedding NOW beats shedding
after the tokens are half-generated.

Goodput is counted honestly: every submitted request lands in exactly
one of completed-in-deadline / completed-late / shed, and the
denominator is ALL submissions — a shed request is a failure of the
service, not a statistics exemption.
"""

from __future__ import annotations

import collections
import time
from typing import Deque, List, NamedTuple, Optional

from apex_tpu.serve import metrics
# Canonical shed reasons live in metrics.SHED_REASONS (one enum shared
# with the summarize serve section); re-exported here for callers.
from apex_tpu.serve.metrics import (DEADLINE, QUEUE_FULL,  # noqa: F401
                                    SHED_REASONS, TOO_LARGE)


class Rejected(NamedTuple):
    """One shed decision, kept for the bench/goodput report."""

    rid: int
    reason: str
    t: float


class AdmissionController:
    """Bounded queue + SLO-aware shedding.

    ``max_queue``: requests allowed to WAIT (running slots are the
    engine's concern). ``clock``: injectable monotonic clock for the
    deterministic shedding tests.
    """

    def __init__(self, *, max_queue: int = 64, clock=time.monotonic):
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.max_queue = int(max_queue)
        self._clock = clock
        self._queue: Deque = collections.deque()
        self.submitted = 0
        self.rejected: List[Rejected] = []
        # EWMA of observed TTFT — the submit-time reachability screen.
        # Starts at None (no screening until the first observation; an
        # optimistic cold start only delays shedding by one request).
        self._ttft_ewma: Optional[float] = None

    @property
    def depth(self) -> int:
        return len(self._queue)

    def submit(self, req, now: Optional[float] = None) -> bool:
        """True = queued; False = shed (the request's ``state`` /
        ``reject_reason`` are set either way)."""
        now = self._clock() if now is None else now
        self.submitted += 1
        if req.submitted_s is None:
            req.submitted_s = now
        if len(self._queue) >= self.max_queue:
            self._shed(req, QUEUE_FULL, now)
            return False
        if req.deadline_s is not None:
            waited = now - req.submitted_s
            est = self._ttft_ewma or 0.0
            if waited + est > req.deadline_s:
                self._shed(req, DEADLINE, now)
                return False
        req.state = "queued"
        self._queue.append(req)
        return True

    def pop_ready(self, now: Optional[float] = None):
        """Next runnable request, shedding queued requests whose
        deadline already passed. None when the queue is empty."""
        now = self._clock() if now is None else now
        while self._queue:
            req = self._queue.popleft()
            if (req.deadline_s is not None
                    and now - req.submitted_s > req.deadline_s):
                self._shed(req, DEADLINE, now, expired=True)
                continue
            return req
        return None

    def push_back(self, req) -> None:
        """Return a popped request to the queue head (the engine could
        not place it this step — e.g. the page pool is momentarily
        full). Not a shed: the request keeps its submission time."""
        self._queue.appendleft(req)

    def observe_ttft(self, ttft_s: float) -> None:
        if self._ttft_ewma is None:
            self._ttft_ewma = float(ttft_s)
        else:
            self._ttft_ewma = 0.8 * self._ttft_ewma + 0.2 * float(ttft_s)

    def _shed(self, req, reason: str, now: float,
              expired: bool = False) -> None:
        metrics.check_reason(reason)
        req.state = "rejected"
        req.reject_reason = reason
        self.rejected.append(Rejected(req.rid, reason, now))
        metrics.count(metrics.REJECTED, meta={"reason": reason})
        metrics.req_event(
            metrics.REQ_REJECT, req.rid,
            meta={"reason": reason, "expired": bool(expired),
                  "queued_s": (None if req.submitted_s is None
                               else now - req.submitted_s)})
        if expired:
            metrics.count(metrics.EXPIRED)
