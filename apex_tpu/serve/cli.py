"""``python -m apex_tpu.serve`` — the serving command line.

Subcommands:

  * ``bench`` — load the newest snapshot from ``--snapshot-dir`` and
    run the two-phase synthetic load of :mod:`apex_tpu.serve.bench`,
    printing the SERVE report row as ONE JSON line on stdout (progress
    on stderr).

Exit codes follow the repo CLI contract (telemetry/plan CLIs): 0 on a
healthy run, 2 for usage errors (argparse), nonzero for bad input — a
missing/empty snapshot directory or an unloadable checkpoint is exit 1
with the reason on stderr, not a traceback.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m apex_tpu.serve",
        description="apex_tpu serving: paged KV-cache continuous-"
                    "batching inference (docs/serve.md)")
    sub = p.add_subparsers(dest="cmd", required=True)
    b = sub.add_parser(
        "bench",
        help="synthetic closed-loop + 2x-overload load run against the "
             "newest snapshot")
    b.add_argument("--snapshot-dir", required=True, metavar="DIR",
                   help="SnapshotManager directory (train with "
                        "examples/gpt/train_lm.py --snapshot-dir)")
    b.add_argument("--requests", type=int, default=50,
                   help="steady-phase request count (overload phase "
                        "offers 2x this)")
    b.add_argument("--prompt-len", type=int, default=8)
    b.add_argument("--max-new", type=int, default=8,
                   help="tokens generated per request")
    b.add_argument("--max-batch", type=int, default=4,
                   help="decode slots (static batch shape)")
    b.add_argument("--page", type=int, default=16,
                   help="tokens per KV page")
    b.add_argument("--in-flight", type=int, default=2,
                   help="decode dispatches in flight (InflightWindow "
                        "depth; token streams are depth-inert)")
    b.add_argument("--deadline-s", type=float, default=30.0,
                   help="per-request SLO deadline in the overload phase")
    b.add_argument("--no-overload", action="store_true",
                   help="skip the 2x-overload shedding phase")
    b.add_argument("--quantize", choices=["bf16", "int8"], default=None,
                   help="opt-in weight quantization at load "
                        "(serve.quant)")
    b.add_argument("--prune", action="store_true",
                   help="apply one-shot 2:4 pruning at load "
                        "(sparsity.prune_for_serving)")
    b.add_argument("--seed", type=int, default=0)
    b.add_argument("--telemetry", default=None, metavar="PATH",
                   help="also write serve/* telemetry events to a "
                        "JSONL (render: python -m apex_tpu.telemetry "
                        "summarize PATH)")
    return p


def _run_bench(args) -> int:
    if args.telemetry:
        from apex_tpu import telemetry, trace
        telemetry.enable()
        trace.enable()
    from apex_tpu.serve.bench import run_bench
    from apex_tpu.serve.loader import load_model
    try:
        loaded = load_model(args.snapshot_dir, quantize=args.quantize,
                            prune=args.prune)
    except (ValueError, NotImplementedError, OSError) as e:
        print(f"serve bench: {e}", file=sys.stderr)
        return 1
    print(f"serve bench: loaded step {loaded.step} "
          f"(generation {loaded.generation}) from "
          f"{loaded.directory}", file=sys.stderr)
    if loaded.quant:
        print(f"serve bench: quantized {loaded.quant.mode} "
              f"({loaded.quant.quantized_leaves} leaves, max_abs_err "
              f"{loaded.quant.max_abs_err:.3e})", file=sys.stderr)
    try:
        report = run_bench(
            loaded, requests=args.requests, prompt_len=args.prompt_len,
            max_new=args.max_new, max_batch=args.max_batch,
            page=args.page, in_flight=args.in_flight,
            overload=not args.no_overload, deadline_s=args.deadline_s,
            seed=args.seed)
    except ValueError as e:
        print(f"serve bench: {e}", file=sys.stderr)
        return 1
    if args.telemetry:
        from apex_tpu import telemetry
        telemetry.write_jsonl(args.telemetry)
        print(f"serve bench: telemetry -> {args.telemetry}",
              file=sys.stderr)
    print(json.dumps(report))
    return 0


def _run(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)
    if args.cmd == "bench":
        return _run_bench(args)
    raise AssertionError(f"unhandled subcommand {args.cmd!r}")


def main(argv: Optional[List[str]] = None) -> int:
    try:
        return _run(argv)
    except BrokenPipeError:
        # piped into `head -1` / `grep -q`: the reader closing early is
        # normal CLI usage, not a failure. Point stdout at devnull so
        # Python's interpreter-shutdown flush doesn't raise a second
        # time (same guard as telemetry/cli.py).
        import os
        try:
            os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        except OSError:
            pass
        return 0


if __name__ == "__main__":
    sys.exit(main())
