"""``python -m apex_tpu.serve`` — the serving command line.

Subcommands:

  * ``bench`` — load the newest snapshot from ``--snapshot-dir`` and
    run the two-phase synthetic load of :mod:`apex_tpu.serve.bench`,
    printing the SERVE report row as ONE JSON line on stdout (progress
    on stderr). ``--slo SPEC.json`` scores the run in the report's
    ``slo`` key; ``--profile DIR`` wraps the run in a ``jax.profiler``
    capture for ``pyprof report DIR --timeline`` (request lanes).
  * ``slo`` — score a telemetry JSONL (a ``bench --telemetry`` run, or
    any stream carrying ``req/*`` events) against a declarative SLO
    spec (:mod:`apex_tpu.serve.slo`).

Exit codes follow the repo CLI contract (telemetry/plan CLIs): 0 on a
healthy run / every SLO target met, 2 for usage errors (argparse),
3 when an SLO target is VIOLATED (the ``telemetry health`` unhealthy
code), and 1 for bad input — a missing/empty snapshot directory, an
unloadable checkpoint, an unreadable spec, or a stream with no
``req/*`` events is exit 1 with the reason on stderr, not a traceback.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m apex_tpu.serve",
        description="apex_tpu serving: paged KV-cache continuous-"
                    "batching inference (docs/serve.md)")
    sub = p.add_subparsers(dest="cmd", required=True)
    b = sub.add_parser(
        "bench",
        help="synthetic closed-loop + 2x-overload load run against the "
             "newest snapshot")
    b.add_argument("--snapshot-dir", required=True, metavar="DIR",
                   help="SnapshotManager directory (train with "
                        "examples/gpt/train_lm.py --snapshot-dir)")
    b.add_argument("--requests", type=int, default=50,
                   help="steady-phase request count (overload phase "
                        "offers 2x this)")
    b.add_argument("--prompt-len", type=int, default=8)
    b.add_argument("--max-new", type=int, default=8,
                   help="tokens generated per request")
    b.add_argument("--max-batch", type=int, default=4,
                   help="decode slots (static batch shape)")
    b.add_argument("--page", type=int, default=16,
                   help="tokens per KV page")
    b.add_argument("--in-flight", type=int, default=2,
                   help="decode dispatches in flight (InflightWindow "
                        "depth; token streams are depth-inert)")
    b.add_argument("--deadline-s", type=float, default=30.0,
                   help="per-request SLO deadline in the overload phase")
    b.add_argument("--no-overload", action="store_true",
                   help="skip the 2x-overload shedding phase")
    b.add_argument("--quantize", choices=["bf16", "int8"], default=None,
                   help="opt-in weight quantization at load "
                        "(serve.quant)")
    b.add_argument("--prune", action="store_true",
                   help="apply one-shot 2:4 pruning at load "
                        "(sparsity.prune_for_serving)")
    b.add_argument("--seed", type=int, default=0)
    b.add_argument("--telemetry", default=None, metavar="PATH",
                   help="also write serve/* + req/* telemetry events "
                        "to a JSONL (render: python -m "
                        "apex_tpu.telemetry summarize PATH; score: "
                        "python -m apex_tpu.serve slo PATH)")
    b.add_argument("--slo", default=None, metavar="SPEC.json",
                   help="score the run against an SLO spec; the "
                        "report's 'slo' key carries the result (null "
                        "without this flag)")
    b.add_argument("--profile", default=None, metavar="DIR",
                   help="wrap the run in a jax.profiler capture for "
                        "pyprof report DIR --timeline (request lanes)")
    s = sub.add_parser(
        "slo",
        help="score a telemetry JSONL's req/* records against an SLO "
             "spec (exit 0 met / 3 violated / 1 bad input)")
    s.add_argument("jsonl", metavar="RUN.jsonl",
                   help="telemetry JSONL carrying req/* events "
                        "(serve bench --telemetry)")
    s.add_argument("--spec", default=None, metavar="SPEC.json",
                   help="SLO spec file (JSON object of serve.slo."
                        "SLOSpec fields)")
    for metric in ("ttft", "tpot", "e2e"):
        for q in (50, 99):
            s.add_argument(f"--{metric}-p{q}-ms", type=float,
                           default=None, dest=f"{metric}_p{q}_ms",
                           help=f"{metric} p{q} target in ms")
    s.add_argument("--goodput-min", type=float, default=None,
                   help="minimum request goodput (completed-in-"
                        "deadline / all submissions, 0..1)")
    s.add_argument("--json", action="store_true",
                   help="print the full report dict as JSON instead "
                        "of the text rendering")
    return p


def _run_bench(args) -> int:
    if args.telemetry:
        from apex_tpu import telemetry, trace
        telemetry.enable()
        trace.enable()
    from apex_tpu.serve.bench import run_bench
    from apex_tpu.serve.loader import load_model
    try:
        loaded = load_model(args.snapshot_dir, quantize=args.quantize,
                            prune=args.prune)
    except (ValueError, NotImplementedError, OSError) as e:
        print(f"serve bench: {e}", file=sys.stderr)
        return 1
    print(f"serve bench: loaded step {loaded.step} "
          f"(generation {loaded.generation}) from "
          f"{loaded.directory}", file=sys.stderr)
    if loaded.quant:
        print(f"serve bench: quantized {loaded.quant.mode} "
              f"({loaded.quant.quantized_leaves} leaves, max_abs_err "
              f"{loaded.quant.max_abs_err:.3e})", file=sys.stderr)
    spec = None
    if args.slo:
        from apex_tpu.serve.slo import SLOSpec
        try:
            spec = SLOSpec.from_file(args.slo)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"serve bench: bad SLO spec: {e}", file=sys.stderr)
            return 1
    try:
        if args.profile:
            import jax
            jax.profiler.start_trace(args.profile)
        try:
            report = run_bench(
                loaded, requests=args.requests,
                prompt_len=args.prompt_len,
                max_new=args.max_new, max_batch=args.max_batch,
                page=args.page, in_flight=args.in_flight,
                overload=not args.no_overload,
                deadline_s=args.deadline_s, slo=spec, seed=args.seed)
        finally:
            if args.profile:
                import jax
                jax.profiler.stop_trace()
                print(f"serve bench: profile -> {args.profile}",
                      file=sys.stderr)
    except ValueError as e:
        print(f"serve bench: {e}", file=sys.stderr)
        return 1
    if args.telemetry:
        from apex_tpu import telemetry
        telemetry.write_jsonl(args.telemetry)
        print(f"serve bench: telemetry -> {args.telemetry}",
              file=sys.stderr)
    print(json.dumps(report))
    return 0


EXIT_SLO_VIOLATED = 3          # matches telemetry health's unhealthy


def _run_slo(args) -> int:
    from apex_tpu.serve import slo as slo_mod
    from apex_tpu.telemetry import requests as requests_mod
    from apex_tpu.telemetry.export import load
    if args.spec:
        try:
            spec = slo_mod.SLOSpec.from_file(args.spec)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"serve slo: bad spec: {e}", file=sys.stderr)
            return 1
    else:
        spec = slo_mod.SLOSpec(
            ttft_p50_ms=args.ttft_p50_ms, ttft_p99_ms=args.ttft_p99_ms,
            tpot_p50_ms=args.tpot_p50_ms, tpot_p99_ms=args.tpot_p99_ms,
            e2e_p50_ms=args.e2e_p50_ms, e2e_p99_ms=args.e2e_p99_ms,
            goodput_min=args.goodput_min)
    if spec.empty():
        print("serve slo: spec sets no targets (use --spec or "
              "--ttft-p99-ms / --tpot-p99-ms / --e2e-p99-ms / "
              "--goodput-min)", file=sys.stderr)
        return 1
    try:
        events = load(args.jsonl)
    except (OSError, ValueError) as e:
        print(f"serve slo: cannot read {args.jsonl}: {e}",
              file=sys.stderr)
        return 1
    records = requests_mod.join(events)
    if not records:
        print(f"serve slo: {args.jsonl} carries no req/* events "
              "(record a run with serve bench --telemetry)",
              file=sys.stderr)
        return 1
    report = slo_mod.evaluate(records, spec)
    if args.json:
        print(json.dumps(report))
    else:
        print(slo_mod.format_report(report))
    return 0 if report["met"] else EXIT_SLO_VIOLATED


def _run(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)
    if args.cmd == "bench":
        return _run_bench(args)
    if args.cmd == "slo":
        return _run_slo(args)
    raise AssertionError(f"unhandled subcommand {args.cmd!r}")


def main(argv: Optional[List[str]] = None) -> int:
    try:
        return _run(argv)
    except BrokenPipeError:
        # piped into `head -1` / `grep -q`: the reader closing early is
        # normal CLI usage, not a failure. Point stdout at devnull so
        # Python's interpreter-shutdown flush doesn't raise a second
        # time (same guard as telemetry/cli.py).
        import os
        try:
            os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        except OSError:
            pass
        return 0


if __name__ == "__main__":
    sys.exit(main())
