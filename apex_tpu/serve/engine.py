"""Continuous-batching engine — admit/retire between decode steps over
a fixed-shape slot array.

The recompile-free contract: ``max_batch`` slots, one shared page pool,
one block table of static shape. Requests come and go by MUTATING slot
contents (page lists, positions, active masks) — never by changing an
array shape, so the decode step compiles exactly once. Dead slots ride
along masked (their page writes drop, their logits are discarded).

Dispatch pipelining reuses the trainer's ``InflightWindow``: the decode
chain advances on DEVICE state (the pool and the last-token vector feed
the next dispatch directly, so autoregression never waits on the host),
while the host observes tokens only at retirement — detokenization,
EOS/finish bookkeeping, TTFT/inter-token spans all happen off the
critical path. The window changes WHEN the host observes, never what
the device computes: token streams are bit-identical at every depth
(pinned by tests/test_serve_engine.py).

Scheduler states (docs/serve.md): ``queued`` (admission queue) ->
``running`` (slot assigned, prefilled) -> ``done``; or ``rejected``
(shed at admission — queue full, SLO-unreachable, or oversized); or
``expired`` (deadline passed MID-DECODE — the slot is cut off and its
decoded tokens are wasted work, counted by ``serve/expired_inflight``
and priced by the goodput ledger). Finished slots linger as DRAINING
until their in-flight dispatches retire, then their pages return to the
free list.

Every lifecycle transition additionally emits a ``req/*`` event (see
serve/metrics.py) so ``telemetry.requests.join`` can reconstruct one
record per request offline — all host-side Python, never traced.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.serve import kvcache, metrics
from apex_tpu.serve import model as smodel
from apex_tpu.serve.admission import (TOO_LARGE, AdmissionController,
                                      Rejected)
from apex_tpu.serve.loader import LoadedModel
from apex_tpu.trainer.pipeline import InflightWindow

# process-wide request id allocator (see Engine.request)
_RIDS = itertools.count()


@dataclasses.dataclass
class Request:
    """One generation request and its observed lifecycle."""

    rid: int
    prompt: List[int]
    max_new_tokens: int
    deadline_s: Optional[float] = None
    eos_token_id: Optional[int] = None
    # lifecycle (engine/admission-owned)
    state: str = "new"         # new|queued|running|done|rejected|expired
    tokens: List[int] = dataclasses.field(default_factory=list)
    # host observation time of each token — TTFT / inter-token
    # percentiles in the bench report come from diffs of this list
    token_times: List[float] = dataclasses.field(default_factory=list)
    submitted_s: Optional[float] = None
    t_admit: Optional[float] = None
    t_first: Optional[float] = None
    t_last: Optional[float] = None
    t_done: Optional[float] = None
    reject_reason: Optional[str] = None

    @property
    def done(self) -> bool:
        return self.state == "done"

    @property
    def ttft_s(self) -> Optional[float]:
        if self.t_first is None or self.submitted_s is None:
            return None
        return self.t_first - self.submitted_s

    def in_deadline(self) -> Optional[bool]:
        """Completed within its SLO? None when no deadline was set."""
        if self.deadline_s is None:
            return None
        if self.t_done is None or self.submitted_s is None:
            return False
        return (self.t_done - self.submitted_s) <= self.deadline_s


@dataclasses.dataclass
class _Slot:
    req: Request
    pages: List[int]
    prompt_len: int
    outstanding: int = 0       # dispatches not yet retired
    finished: bool = False     # logical completion observed (eos/budget)


class Engine:
    """Continuous-batching decode engine over a :class:`LoadedModel`.

    ``max_batch``: decode slots. ``page``: tokens per KV page.
    ``max_context``: per-request context ceiling (prompt + generated);
    sets ``pages_per_slot``. ``max_prompt``: static prefill width (one
    prefill compile). ``in_flight``: InflightWindow depth — decode
    dispatches the host may run ahead of retirement.
    """

    def __init__(self, loaded: LoadedModel, *, max_batch: int = 4,
                 page: int = 16, max_context: int = 128,
                 max_prompt: int = 32, in_flight: int = 2,
                 admission: Optional[AdmissionController] = None,
                 clock=time.monotonic):
        if max_prompt > max_context:
            raise ValueError(
                f"max_prompt ({max_prompt}) > max_context "
                f"({max_context})")
        if max_context > loaded.spec.max_seq:
            raise ValueError(
                f"max_context ({max_context}) exceeds the model's "
                f"position table (max_seq={loaded.spec.max_seq})")
        self.loaded = loaded
        self.spec = loaded.spec
        self.params = loaded.params
        self.max_batch = int(max_batch)
        self.page = int(page)
        self.max_context = int(max_context)
        self.max_prompt = int(max_prompt)
        self.pages_per_slot = -(-self.max_context // self.page)
        self.num_pages = self.max_batch * self.pages_per_slot
        self._clock = clock
        self.admission = admission or AdmissionController(clock=clock)
        self.window = InflightWindow(in_flight)

        spec = self.spec
        emb = self.params["tok_emb"]["embedding"]
        kernel = self.params["block_0"]["attn"]["in_proj"]["kernel"]
        kv_dtype = jnp.result_type(emb.dtype, kernel.dtype)
        self.pool = kvcache.create_pool(
            layers=spec.layers, num_pages=self.num_pages,
            heads=spec.heads, page=self.page, head_dim=spec.head_dim,
            dtype=kv_dtype)
        self.allocator = kvcache.PageAllocator(self.num_pages)
        # static-shape host mirrors of the device scheduling state
        self.block_tables = np.full(
            (self.max_batch, self.pages_per_slot), self.num_pages,
            np.int32)
        self.positions = np.zeros((self.max_batch,), np.int32)
        self.limits = np.zeros((self.max_batch,), np.int32)
        self.slots: List[Optional[_Slot]] = [None] * self.max_batch
        self.last_tokens = jnp.zeros((self.max_batch,), jnp.int32)
        self.completed: List[Request] = []
        self.expired_inflight: List[Request] = []
        self.tokens_emitted = 0
        self._seq = 0          # dispatch sequence number
        self._meta: Dict[int, Any] = {}

        def _decode(params, pool, last_tokens, block_tables, positions,
                    active):
            logits, pool = smodel.decode_step(
                params, spec, pool, last_tokens, positions,
                block_tables, active)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return pool, jnp.where(active, nxt, last_tokens)

        def _prefill(params, pool, prompt, length, block_row):
            _, first, pool = smodel.prefill(
                params, spec, prompt, length, pool, block_row)
            return pool, first

        self._decode_fn = jax.jit(_decode, donate_argnums=(1,))
        self._prefill_fn = jax.jit(_prefill, donate_argnums=(1,))

    # -- submission ---------------------------------------------------------

    def request(self, prompt, max_new_tokens: int, *,
                deadline_s: Optional[float] = None,
                eos_token_id: Optional[int] = None) -> Request:
        # rids come from a PROCESS-wide counter, not a per-engine one:
        # every engine in a process shares one telemetry collector, and
        # per-engine numbering would alias distinct requests under one
        # (process, rid) key in the offline join (the bench runs two
        # engines — steady and overload — into one JSONL)
        r = Request(rid=next(_RIDS), prompt=list(map(int, prompt)),
                    max_new_tokens=int(max_new_tokens),
                    deadline_s=deadline_s, eos_token_id=eos_token_id)
        return r

    def submit(self, req: Request, now: Optional[float] = None) -> bool:
        """Queue a request through admission control. Oversized
        requests (prompt past the static prefill width, or context past
        the per-slot page budget) shed here — they could never run."""
        now = self._clock() if now is None else now
        metrics.req_event(
            metrics.REQ_SUBMIT, req.rid,
            meta={"prompt_len": len(req.prompt),
                  "max_new": req.max_new_tokens,
                  "deadline_s": req.deadline_s})
        if (len(req.prompt) > self.max_prompt
                or len(req.prompt) + req.max_new_tokens
                > self.max_context):
            self.admission.submitted += 1
            req.submitted_s = req.submitted_s or now
            req.state = "rejected"
            req.reject_reason = TOO_LARGE
            self.admission.rejected.append(
                Rejected(req.rid, TOO_LARGE, now))
            metrics.count(metrics.REJECTED, meta={"reason": TOO_LARGE})
            metrics.req_event(metrics.REQ_REJECT, req.rid,
                              meta={"reason": TOO_LARGE,
                                    "expired": False, "queued_s": 0.0})
            return False
        return self.admission.submit(req, now)

    # -- scheduling ---------------------------------------------------------

    def _free_slot_index(self) -> Optional[int]:
        for i, s in enumerate(self.slots):
            if s is None:
                return i
        return None

    def _admit(self, now: float) -> None:
        while True:
            slot_idx = self._free_slot_index()
            if slot_idx is None:
                return
            req = self.admission.pop_ready(now)
            if req is None:
                return
            plen = len(req.prompt)
            need = -(-(plen + req.max_new_tokens) // self.page)
            try:
                pages = self.allocator.alloc(need)
            except kvcache.PoolFullError:
                # back-pressure, not a shed: retry when pages free up
                self.admission.push_back(req)
                return
            slot = _Slot(req=req, pages=pages, prompt_len=plen)
            self.slots[slot_idx] = slot
            row = np.full((self.pages_per_slot,), self.num_pages,
                          np.int32)
            row[:need] = pages
            self.block_tables[slot_idx] = row
            prompt = np.zeros((self.max_prompt,), np.int32)
            prompt[:plen] = req.prompt
            self.pool, first = self._prefill_fn(
                self.params, self.pool, jnp.asarray(prompt),
                jnp.int32(plen), jnp.asarray(row))
            self.last_tokens = self.last_tokens.at[slot_idx].set(first)
            # next decode step consumes the first generated token at
            # position plen; a request of max_new N needs N-1 steps
            self.positions[slot_idx] = plen
            self.limits[slot_idx] = plen + req.max_new_tokens - 1
            req.state = "running"
            req.t_admit = now
            metrics.count(metrics.ADMITTED)
            metrics.count(metrics.PREFILL_TOKENS, plen)
            queued_s = (None if req.submitted_s is None
                        else now - req.submitted_s)
            metrics.req_event(
                metrics.REQ_ADMIT, req.rid,
                meta={"slot": slot_idx, "pages": need,
                      "queued_s": queued_s})
            if req.submitted_s is not None:
                metrics.span(metrics.REQ_QUEUED, req.submitted_s, now,
                             meta={"rid": req.rid, "slot": slot_idx})
            slot.outstanding += 1
            self._meta[self._seq] = ("prefill", self._clock(), slot_idx)
            for idx, payload in self.window.push(self._seq, first):
                self._retire(idx, payload)
            self._seq += 1

    def _expire_running(self, now: float) -> None:
        """Cut off running slots whose deadline has already passed —
        every further decoded token would be wasted work. The slot
        drains like a completed one (in-flight dispatches retire, pages
        free), but the request ends ``expired``: its decoded tokens are
        counted by ``serve/expired_inflight`` accounting so the goodput
        ledger can price them."""
        for i, slot in enumerate(self.slots):
            if slot is None or slot.finished:
                continue
            req = slot.req
            if (req.deadline_s is None or req.submitted_s is None
                    or now - req.submitted_s <= req.deadline_s):
                continue
            slot.finished = True
            self.limits[i] = self.positions[i]
            req.state = "expired"
            self.expired_inflight.append(req)
            metrics.count(metrics.EXPIRED_INFLIGHT)
            metrics.req_event(
                metrics.REQ_EXPIRE_INFLIGHT, req.rid,
                meta={"slot": i, "tokens": len(req.tokens),
                      "e2e_s": now - req.submitted_s})
            if req.t_first is not None:
                metrics.span(metrics.REQ_DECODE, req.t_first, now,
                             meta={"rid": req.rid, "slot": i,
                                   "tokens": len(req.tokens),
                                   "expired": True})
        self._reap()

    def _active_mask(self) -> np.ndarray:
        act = np.zeros((self.max_batch,), bool)
        for i, s in enumerate(self.slots):
            if s is not None and not s.finished \
                    and self.positions[i] < self.limits[i]:
                act[i] = True
        return act

    def step(self) -> bool:
        """One engine iteration: admit, dispatch one decode step over
        the active slots, process retirements. Returns False when there
        was nothing to do (no queue, no occupied slots, nothing in
        flight)."""
        now = self._clock()
        self._admit(now)
        self._expire_running(now)
        metrics.gauge(metrics.QUEUE_DEPTH, self.admission.depth,
                      step=self._seq)
        occupied = sum(s is not None for s in self.slots)
        metrics.gauge(metrics.OCCUPANCY, occupied / self.max_batch,
                      step=self._seq)
        kv = self.allocator.stats()
        metrics.gauge(metrics.KV_USED_PAGES, kv["used"], step=self._seq)
        metrics.gauge(metrics.KV_FREE_PAGES, kv["free"], step=self._seq)
        metrics.gauge(metrics.KV_OCCUPANCY, kv["occupancy"],
                      step=self._seq)
        metrics.gauge(metrics.KV_FRAGMENTATION, kv["fragmentation"],
                      step=self._seq)
        active = self._active_mask()
        metrics.gauge(metrics.SLOT_ACTIVE,
                      int(active.sum()) / self.max_batch, step=self._seq)
        if active.any():
            # int() the slot indices: np.flatnonzero yields np.int64,
            # which would leak into span/req event metas and break the
            # JSONL writer (json can't serialize numpy scalars)
            snapshot = [(i, self.slots[i].req,
                         int(self.positions[i]) - self.slots[i].prompt_len
                         + 1)
                        for i in map(int, np.flatnonzero(active))]
            t_dispatch = self._clock()
            self.pool, self.last_tokens = self._decode_fn(
                self.params, self.pool, self.last_tokens,
                jnp.asarray(self.block_tables),
                jnp.asarray(self.positions), jnp.asarray(active))
            for i, _, _ in snapshot:
                self.positions[i] += 1
                self.slots[i].outstanding += 1
            metrics.count(metrics.DECODE_TOKENS, len(snapshot))
            self._meta[self._seq] = ("decode", t_dispatch, snapshot)
            metrics.span(metrics.ENGINE_STEP, t_dispatch, self._clock(),
                         step=self._seq)
            for idx, payload in self.window.push(self._seq,
                                                 self.last_tokens):
                self._retire(idx, payload)
            self._seq += 1
            return True
        if self.window.stats()["pending"]:
            for idx, payload in self.window.drain():
                self._retire(idx, payload)
            return True
        # Nothing active, nothing in flight: every finished slot was
        # reaped at retirement, so stepping again cannot make progress
        # (queued work, if any, is waiting on capacity that only a
        # retirement can free — and there are no retirements coming).
        return False

    def run(self, requests: List[Request]) -> List[Request]:
        """Closed-loop driver: submit everything, step until drained."""
        now = self._clock()
        for r in requests:
            self.submit(r, now)
        while self.step():
            pass
        for idx, payload in self.window.drain():
            self._retire(idx, payload)
        return requests

    # -- retirement (host-side, off the dispatch critical path) -------------

    def _retire(self, idx: int, payload) -> None:
        kind, t_dispatch, info = self._meta.pop(idx)
        now = self._clock()
        toks = np.asarray(payload)
        if kind == "prefill":
            slot_idx = info
            slot = self.slots[slot_idx]
            slot.outstanding -= 1
            req = slot.req
            tok = int(toks) if toks.ndim == 0 else int(toks.reshape(-1)[0])
            self._observe_token(slot_idx, slot, req, tok, now,
                                first=True)
        else:
            n = 0
            for slot_idx, req, _gen_idx in info:
                slot = self.slots[slot_idx]
                if slot is None or slot.req is not req:
                    continue   # unreachable: reap waits on outstanding
                slot.outstanding -= 1
                self._observe_token(slot_idx, slot, req,
                                    int(toks[slot_idx]), now,
                                    first=False)
                n += 1
            if n:
                metrics.count(metrics.TOKENS, n)
        self._reap()

    def _observe_token(self, slot_idx: int, slot: _Slot, req: Request,
                       tok: int, now: float, *, first: bool) -> None:
        if slot.finished:
            return                      # post-EOS overrun token
        rid_meta = {"rid": req.rid, "slot": slot_idx}
        if first:
            req.t_first = now
            metrics.span(metrics.TTFT, req.submitted_s, now,
                         meta=rid_meta)
            if req.ttft_s is not None:
                self.admission.observe_ttft(req.ttft_s)
            metrics.count(metrics.TOKENS, 1)
            prefill_s = (None if req.t_admit is None
                         else now - req.t_admit)
            metrics.req_event(
                metrics.REQ_FIRST, req.rid,
                meta={"slot": slot_idx, "ttft_s": req.ttft_s,
                      "prefill_s": prefill_s})
            if req.t_admit is not None:
                metrics.span(metrics.REQ_PREFILL, req.t_admit, now,
                             meta=rid_meta)
        elif req.t_last is not None:
            metrics.span(metrics.INTERTOKEN, req.t_last, now,
                         meta=rid_meta)
        req.t_last = now
        req.tokens.append(tok)
        req.token_times.append(now)
        self.tokens_emitted += 1
        hit_eos = (req.eos_token_id is not None
                   and tok == req.eos_token_id)
        if hit_eos or len(req.tokens) >= req.max_new_tokens:
            slot.finished = True
            # stop any further dispatch of this slot
            self.limits[slot_idx] = self.positions[slot_idx]
            req.state = "done"
            req.t_done = now
            metrics.count(metrics.COMPLETED)
            decode_s = (None if req.t_first is None
                        else now - req.t_first)
            metrics.req_event(
                metrics.REQ_FINISH, req.rid,
                meta={"slot": slot_idx, "tokens": len(req.tokens),
                      "queued_s": (None if req.t_admit is None
                                   or req.submitted_s is None
                                   else req.t_admit - req.submitted_s),
                      "prefill_s": (None if req.t_first is None
                                    or req.t_admit is None
                                    else req.t_first - req.t_admit),
                      "decode_s": decode_s,
                      "ttft_s": req.ttft_s,
                      "e2e_s": (None if req.submitted_s is None
                                else now - req.submitted_s),
                      "deadline_s": req.deadline_s,
                      "in_deadline": req.in_deadline()})
            if req.t_first is not None:
                metrics.span(metrics.REQ_DECODE, req.t_first, now,
                             meta={**rid_meta,
                                   "tokens": len(req.tokens)})
            self.completed.append(req)

    def _reap(self) -> None:
        """Free slots whose request finished and whose in-flight
        dispatches have all retired."""
        for i, slot in enumerate(self.slots):
            if slot is None or not slot.finished or slot.outstanding:
                continue
            self.allocator.free(slot.pages)
            self.block_tables[i] = self.num_pages
            self.positions[i] = 0
            self.limits[i] = 0
            self.slots[i] = None
