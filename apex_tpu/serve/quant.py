"""Opt-in weight quantization for serving — threaded through the amp
cast registry.

Two modes (the ``quantize=`` knob of :func:`serve.load_model`):

  * ``"bf16"``: the whole param tree casts through
    ``amp.cast_model`` with the O5 bf16 Properties — EXACTLY the cast
    the training stack's opt levels use, so serving inherits amp's
    variables-dict handling and batchnorm policy rather than growing a
    second cast implementation.
  * ``"int8"``: per-channel symmetric weight quantization of the matmul
    kernels (scale = amax over the input fan-in per OUTPUT channel /
    127). The int8 payload + fp32 scales are what a TPU deployment
    keeps resident (halving weight HBM vs bf16); this CPU-backed stack
    dequantizes back to the compute dtype at load (simulated storage —
    the forward then exercises the exact dequantized values a fused
    int8 matmul would see, which is what the parity tests pin).
    Quantization error is bounded per element by ``scale/2`` (round to
    nearest), asserted by tests/test_serve_loader.py.

Non-kernel leaves (biases, layer norms, embeddings) stay in their
checkpoint dtype under int8 — the embed table is a gather (no MXU win)
and norms are fp32 by repo convention.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu import amp

MODES = ("bf16", "int8")


@dataclasses.dataclass(frozen=True)
class QuantReport:
    """What the quantization pass did — surfaced by the serve CLI and
    the loader so 'quantized' is never a silent property of a server."""

    mode: str
    quantized_leaves: int
    skipped_leaves: int
    dense_bytes: int
    quant_bytes: int
    max_abs_err: float

    def row(self) -> dict:
        return dataclasses.asdict(self)


def per_channel_int8(w: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Symmetric per-output-channel int8 quantization of a (.., out)
    kernel: scale over every axis but the last. Zero channels get scale
    1 (their quantized values are exactly 0 either way)."""
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)),
                   axis=tuple(range(w.ndim - 1)))
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127)
    return q.astype(jnp.int8), scale


def dequantize_int8(q: jax.Array, scale: jax.Array, dtype) -> jax.Array:
    return (q.astype(jnp.float32) * scale).astype(dtype)


def _is_kernel(path) -> bool:
    keys = [getattr(k, "key", getattr(k, "name", None)) for k in path]
    return bool(keys) and keys[-1] == "kernel"


def quantize_params(params: Any, mode: str
                    ) -> Tuple[Any, QuantReport]:
    """Quantize a serving param tree. Returns ``(params, report)`` —
    under ``"bf16"`` the tree is the amp cast output; under ``"int8"``
    the matmul kernels are round-tripped through per-channel int8 (see
    the module docstring for the storage contract)."""
    if mode not in MODES:
        raise ValueError(
            f"quantize mode must be one of {MODES}, got {mode!r}")
    leaves = jax.tree_util.tree_leaves_with_path(params)
    dense_bytes = sum(v.size * v.dtype.itemsize for _, v in leaves)
    if mode == "bf16":
        props = amp.resolve("O5", keep_batchnorm_fp32=False)
        out = amp.cast_model(params, props)
        out_leaves = jax.tree_util.tree_leaves(out)
        quant_bytes = sum(v.size * v.dtype.itemsize for v in out_leaves)
        n_cast = sum(
            1 for (_, a), b in zip(leaves, out_leaves)
            if b.dtype != a.dtype)
        err = max((float(jnp.max(jnp.abs(
            a.astype(jnp.float32) - b.astype(jnp.float32))))
            for (_, a), b in zip(leaves, out_leaves)), default=0.0)
        return out, QuantReport("bf16", n_cast, len(leaves) - n_cast,
                                dense_bytes, quant_bytes, err)

    quantized = 0
    quant_bytes = 0
    max_err = 0.0

    def one(path, v):
        nonlocal quantized, quant_bytes, max_err
        if v.ndim < 2 or not _is_kernel(path):
            quant_bytes += v.size * v.dtype.itemsize
            return v
        q, scale = per_channel_int8(v)
        dq = dequantize_int8(q, scale, v.dtype)
        quantized += 1
        quant_bytes += q.size + scale.size * scale.dtype.itemsize
        max_err = max(max_err, float(jnp.max(jnp.abs(
            v.astype(jnp.float32) - dq.astype(jnp.float32)))))
        return dq

    out = jax.tree_util.tree_map_with_path(one, params)
    return out, QuantReport("int8", quantized,
                            len(leaves) - quantized, dense_bytes,
                            quant_bytes, max_err)
