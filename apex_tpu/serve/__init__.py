"""``apex_tpu.serve`` — continuous-batching inference on the trained
stack (ROADMAP north star: the serving path for "heavy traffic from
millions of users").

The training side of this repo ends at a snapshot; this package turns
one into a running service:

  * :mod:`~apex_tpu.serve.kvcache` — paged KV cache: fixed-size pages,
    a host-side free-list allocator, per-request page lists in block
    tables. Static shapes everywhere (recompile-free).
  * :mod:`~apex_tpu.serve.decode` — paged decode attention: the jnp
    reference chain (bit-identical to the dense-cache decode path) and
    an opt-in Pallas kernel with block-table-indexed page DMA + dead-
    page elision, behind the same backend-select pattern as
    ``contrib.xentropy``.
  * :mod:`~apex_tpu.serve.model` — the functional decode forward over
    ``TransformerLM`` params (prefill reuses the model's own flash
    forward).
  * :mod:`~apex_tpu.serve.loader` — ``load_model(dir)`` from
    SnapshotManager manifests (layout fingerprint validated BEFORE the
    payload materializes), opt-in bf16/int8 quantization
    (:mod:`~apex_tpu.serve.quant`) and 2:4 pruning
    (``sparsity.prune_for_serving``).
  * :mod:`~apex_tpu.serve.engine` — continuous batching: admit/retire
    between decode steps, fixed-shape slot packing, N decode dispatches
    in flight via the trainer's ``InflightWindow``.
  * :mod:`~apex_tpu.serve.admission` — bounded queue + SLO-aware
    shedding; goodput counted against every submitted request.
  * :mod:`~apex_tpu.serve.bench` / ``python -m apex_tpu.serve bench`` —
    synthetic closed/open-loop load driver emitting ``serve/*`` +
    ``req/*`` telemetry (docs/telemetry.md).
  * :mod:`~apex_tpu.serve.slo` / ``python -m apex_tpu.serve slo`` —
    declarative SLO specs scored over per-request records (attainment,
    multi-window burn rates, violator attribution; exit 0 met / 3
    violated / 1 bad input).

Architecture notes: docs/serve.md ("Observability" covers the request
lifecycle records, the SLO engine, and the goodput ledger).
"""

from apex_tpu.serve import bench
from apex_tpu.serve import slo
from apex_tpu.serve.admission import AdmissionController, Rejected
from apex_tpu.serve.bench import run_bench
from apex_tpu.serve.decode import (backend as decode_backend,
                                   paged_decode_attention,
                                   set_backend as set_decode_backend)
from apex_tpu.serve.engine import Engine, Request
from apex_tpu.serve.kvcache import (KVPool, PageAllocator, PoolFullError,
                                    create_pool)
from apex_tpu.serve.loader import LoadedModel, load_model
from apex_tpu.serve.model import ModelSpec
from apex_tpu.serve.quant import QuantReport, quantize_params
from apex_tpu.serve.slo import SLOSpec

__all__ = [
    "AdmissionController", "Engine", "KVPool", "LoadedModel",
    "ModelSpec", "PageAllocator", "PoolFullError", "QuantReport",
    "Rejected", "Request", "SLOSpec", "bench", "create_pool",
    "decode_backend", "load_model", "paged_decode_attention",
    "quantize_params", "run_bench", "set_decode_backend", "slo",
]
