"""Paged KV cache — the block-table memory layout of the serving stack
(vLLM-style PagedAttention, adapted to the repo's static-shape TPU
doctrine).

The training decode path (``SelfMultiheadAttn.decode``) allocates one
dense ``(B, H, max_len, D)`` cache per layer: every sequence pays for
the WORST-CASE context whether it uses it or not. Under continuous
batching that over-reservation is the capacity ceiling — a mixed pool
of short and long requests wants memory proportional to the tokens
actually resident. Paging fixes it: the cache is a pool of fixed-size
pages (``(num_pages, H, page, D)`` per layer), each request holds an
ordered page list in a block table, and a host-side free-list allocator
recycles pages on retirement.

Static shapes throughout (the recompile-free contract the engine
depends on): the pool, the block tables (``(max_batch,
pages_per_slot)``), and the per-step index vectors never change shape —
only their CONTENTS change as requests come and go. Dead slots are
masked with an out-of-range page id (`=num_pages`), which the scatter
writes drop (``mode='drop'``) and the attention masks by sequence
length, so there is no per-request reshape or recompile anywhere on the
hot path.

Device-side helpers are functional (pool in, pool out) so the engine
can thread the pool through a donated jit chain; the allocator is plain
host Python (page ids are scheduling state, not tensor state).
"""

from __future__ import annotations

import dataclasses
from typing import List, NamedTuple, Sequence

import jax
import jax.numpy as jnp


class PoolFullError(RuntimeError):
    """Raised by :meth:`PageAllocator.alloc` when no free page remains.
    The engine treats this as back-pressure (the request waits in the
    admission queue), never as a fatal error."""


class PageAllocator:
    """Host-side free-list allocator over ``num_pages`` page ids.

    LIFO recycling (a stack): the most recently freed pages are handed
    out first, which keeps the live working set dense at the low end of
    the pool — the same locality argument as a slab allocator, and it
    makes allocator behaviour deterministic for the bitwise replay
    tests."""

    def __init__(self, num_pages: int):
        if num_pages < 1:
            raise ValueError(f"num_pages must be >= 1, got {num_pages}")
        self.num_pages = int(num_pages)
        self._free: List[int] = list(range(self.num_pages - 1, -1, -1))

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return self.num_pages - len(self._free)

    def alloc(self, n: int = 1) -> List[int]:
        """Allocate ``n`` pages atomically — all or nothing (a partial
        grant would leak pages when the caller aborts admission)."""
        if n < 0:
            raise ValueError(f"cannot allocate {n} pages")
        if n > len(self._free):
            raise PoolFullError(
                f"paged KV pool exhausted: need {n} pages, "
                f"{len(self._free)}/{self.num_pages} free")
        return [self._free.pop() for _ in range(n)]

    def free(self, pages: Sequence[int]) -> None:
        for p in pages:
            p = int(p)
            if not 0 <= p < self.num_pages:
                raise ValueError(
                    f"page id {p} out of range [0, {self.num_pages})")
            if p in self._free:
                raise ValueError(f"double free of page {p}")
            self._free.append(p)

    def stats(self) -> dict:
        """Free-list health snapshot for the ``serve/kv_*`` gauges.

        ``fragmentation`` is free-list shatter: ``1 - largest
        contiguous free run / free pages`` — 0.0 when the free space is
        one clean run (or the pool is full/empty), approaching 1.0 when
        it is scattered single pages. Paged attention doesn't need
        contiguity to FUNCTION, but a shattered free list is the
        leading indicator of pathological churn (every retirement
        interleaved with an admission), which is what the gauge exists
        to surface."""
        free = len(self._free)
        used = self.num_pages - free
        frag = 0.0
        if free > 1:
            ordered = sorted(self._free)
            longest = run = 1
            for a, b in zip(ordered, ordered[1:]):
                run = run + 1 if b == a + 1 else 1
                longest = max(longest, run)
            frag = 1.0 - longest / free
        return {"num_pages": self.num_pages, "used": used, "free": free,
                "occupancy": used / self.num_pages,
                "fragmentation": frag}


class KVPool(NamedTuple):
    """Device-side paged K/V storage: one entry per transformer layer,
    each shaped ``(num_pages, heads, page, head_dim)``. A NamedTuple of
    per-layer arrays (not one stacked array) so a jitted step updates
    layers in place without a lifetime-doubling stack/unstack."""

    k: tuple
    v: tuple

    @property
    def num_pages(self) -> int:
        return self.k[0].shape[0]

    @property
    def page(self) -> int:
        return self.k[0].shape[2]

    @property
    def layers(self) -> int:
        return len(self.k)

    def bytes(self) -> int:
        return sum(a.size * a.dtype.itemsize for a in self.k + self.v)


def create_pool(*, layers: int, num_pages: int, heads: int, page: int,
                head_dim: int, dtype=jnp.float32) -> KVPool:
    shape = (num_pages, heads, page, head_dim)
    k = tuple(jnp.zeros(shape, dtype) for _ in range(layers))
    v = tuple(jnp.zeros(shape, dtype) for _ in range(layers))
    return KVPool(k=k, v=v)


# ---------------------------------------------------------------------------
# Device-side page access (functional, jit-friendly)
# ---------------------------------------------------------------------------

def write_token(k_pages: jax.Array, v_pages: jax.Array, k: jax.Array,
                v: jax.Array, page_ids: jax.Array, offsets: jax.Array):
    """Scatter one new token's K/V per sequence into the pool.

    ``k``/``v``: (B, H, D) — this step's projected key/value, one token
    per slot. ``page_ids``: (B,) int32 — the destination page of each
    slot's current position (pass ``num_pages`` for dead slots: the
    out-of-range index makes the scatter a no-op via ``mode='drop'``).
    ``offsets``: (B,) int32 row within the page. Returns the updated
    ``(k_pages, v_pages)``.
    """
    k_pages = k_pages.at[page_ids, :, offsets, :].set(k, mode="drop")
    v_pages = v_pages.at[page_ids, :, offsets, :].set(v, mode="drop")
    return k_pages, v_pages


def write_prompt(k_pages: jax.Array, v_pages: jax.Array, k: jax.Array,
                 v: jax.Array, block_row: jax.Array, length: jax.Array):
    """Scatter a prefilled prompt's K/V (one request, one layer) into
    its pages. ``k``/``v``: (H, S_max, D) — the dense prefill cache,
    rows past ``length`` are padding and are dropped. ``block_row``:
    (pages_per_slot,) int32 page list of the request."""
    h, s_max, d = k.shape
    page = k_pages.shape[2]
    pos = jnp.arange(s_max)
    pid = block_row[pos // page]
    # padding rows route out of range -> dropped by the scatter
    pid = jnp.where(pos < length, pid, k_pages.shape[0])
    off = pos % page
    k_pages = k_pages.at[pid, :, off, :].set(
        k.transpose(1, 0, 2), mode="drop")
    v_pages = v_pages.at[pid, :, off, :].set(
        v.transpose(1, 0, 2), mode="drop")
    return k_pages, v_pages


def gather_pages(pages: jax.Array, block_table: jax.Array) -> jax.Array:
    """Gather each slot's page list into a dense per-slot view:
    ``(num_pages, H, page, D)`` x ``(B, pages_per_slot)`` ->
    ``(B, H, pages_per_slot * page, D)``. Token ``t`` of a slot lands at
    row ``t`` (page lists are position-ordered), so downstream masking
    is a plain ``col < seq_len``. Out-of-range ids (dead slots) clamp —
    the rows they produce are garbage by construction and MUST be
    masked by sequence length."""
    g = pages[block_table]                     # (B, P_s, H, page, D)
    b, ps, h, page, d = g.shape
    return g.transpose(0, 2, 1, 3, 4).reshape(b, h, ps * page, d)


@dataclasses.dataclass
class SlotPages:
    """Host-side bookkeeping for one occupied slot: the ordered page
    list and the number of resident tokens (mirrors the device
    ``seq_lens`` entry; kept host-side for retirement/free)."""

    pages: List[int]
    tokens: int = 0

    def capacity(self, page: int) -> int:
        return len(self.pages) * page
