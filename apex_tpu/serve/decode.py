"""Paged decode attention — the serving variant of
``ops/attention.py``'s fused decode kernel, reading K/V through a block
table instead of a dense per-sequence cache.

One new token per sequence attends over that sequence's resident pages
(``kvcache.gather_pages`` semantics: token ``t`` lives at logical row
``t``). Two execution paths behind the same backend-select pattern as
``contrib.xentropy`` (``APEX_TPU_SERVE_DECODE_BACKEND`` /
:func:`set_backend`):

  * **jnp** (the default): gather the pages dense, then run EXACTLY the
    einsum/softmax chain of ``SelfMultiheadAttn.decode``'s einsum path —
    same einsum strings, same fp32 promotion, same ``-1e30`` mask — so
    paged decode is bit-identical to the dense-cache decode the training
    stack already pins against the full forward.
  * **pallas** (opt-in): one kernel per step, grid ``(B, H, pages)``,
    the block table scalar-prefetched so each grid step's page id feeds
    the BlockSpec index map directly — the pages DMA straight from the
    pool with no host-side gather, and dead grid steps (pages past the
    sequence's live length) clamp to the last live page so consecutive
    identical indices elide the fetch entirely (the same dead-block DMA
    elision as ``ops.attention.decode_attention``, which is the whole
    bandwidth story of a ~0-FLOP decode step). Blockwise online softmax
    in base 2, f32 accumulators.

Prefill never comes through here — it reuses the existing flash forward
(``SelfMultiheadAttn``'s fresh-cache prefill path), per the serving
architecture in docs/serve.md.
"""

from __future__ import annotations

import functools
import math
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops.attention import LOG2E, NEG_INF, _interpret
from apex_tpu.serve.kvcache import gather_pages

_BACKENDS = ("jnp", "pallas")
_FORCE = os.environ.get("APEX_TPU_SERVE_DECODE_BACKEND", "auto")
_OVERRIDE: Optional[str] = None


def set_backend(name: Optional[str] = None) -> Optional[str]:
    """Process-level backend override (None restores the env/default).
    Returns the previous override so callers can save/restore."""
    global _OVERRIDE
    if name is not None and name not in _BACKENDS:
        raise ValueError(
            f"serve decode backend must be one of {_BACKENDS}, "
            f"got {name!r}")
    prev = _OVERRIDE
    _OVERRIDE = name
    return prev


def backend() -> str:
    """The active execution path: ``set_backend`` override, else the
    ``APEX_TPU_SERVE_DECODE_BACKEND`` env value; ``auto`` (the default)
    resolves to ``jnp`` — the gather+einsum chain that is bit-identical
    to the dense-cache decode path. An unrecognized value raises (loud
    failure: a typo'd opt-in must not silently serve the wrong path)."""
    b = _OVERRIDE if _OVERRIDE is not None else _FORCE
    if b in _BACKENDS:
        return b
    if b in ("auto", ""):
        return "jnp"
    raise ValueError(
        f"APEX_TPU_SERVE_DECODE_BACKEND={b!r} — expected one of "
        f"{_BACKENDS} or 'auto'")


def paged_native_shapes(page: int, head_dim: int) -> bool:
    """True when the Pallas path serves this (page, head_dim) without a
    pad copy: the page is the kernel's KV block row count (sublane
    multiple) and the head dim its lane dim (128-multiple, or a
    power-of-two minor Mosaic accepts as block minor == array minor —
    same rule as ``ops.attention.decode_native_head_dim``)."""
    return page % 16 == 0 and (head_dim % 128 == 0
                               or head_dim in (64, 32, 16, 8))


def paged_decode_attention(q: jax.Array, k_pages: jax.Array,
                           v_pages: jax.Array, block_table: jax.Array,
                           seq_lens: jax.Array, *,
                           scale: Optional[float] = None) -> jax.Array:
    """Attention of one new token per sequence over its paged K/V.

    ``q``: (B, H, 1, D) — the current step's queries. ``k_pages`` /
    ``v_pages``: (num_pages, H, page, D) — the shared pool, with the
    step's token ALREADY written at row ``seq_lens[b] - 1`` of each live
    sequence. ``block_table``: (B, pages_per_slot) int32 position-ordered
    page ids. ``seq_lens``: (B,) int32 valid-token counts INCLUDING the
    current token. Returns (B, H, 1, D).

    Dead slots (``seq_lens[b] == 0``) produce a zero context row rather
    than NaN (the all-masked softmax denominator is guarded), so the
    engine can run a partially-occupied batch without poisoning the
    shared batch math.
    """
    if q.ndim != 4 or q.shape[2] != 1:
        raise ValueError(
            f"paged decode is the 1-token step path: q must be "
            f"(B, H, 1, D), got {q.shape}")
    b, h, _, d = q.shape
    if k_pages.shape != v_pages.shape:
        raise ValueError(
            f"k_pages {k_pages.shape} != v_pages {v_pages.shape}")
    if k_pages.shape[1] != h or k_pages.shape[3] != d:
        raise ValueError(
            f"pool {k_pages.shape} does not match q heads/dim {q.shape}")
    scale = (1.0 / math.sqrt(d)) if scale is None else scale
    if backend() == "pallas" and paged_native_shapes(k_pages.shape[2], d):
        return _paged_decode_pallas(q, k_pages, v_pages, block_table,
                                    seq_lens, scale)
    return _paged_decode_jnp(q, k_pages, v_pages, block_table, seq_lens,
                             scale)


def _paged_decode_jnp(q, k_pages, v_pages, block_table, seq_lens, scale):
    """Reference path: gather pages dense, then the exact decode einsum
    chain of ``SelfMultiheadAttn.decode`` (same einsum strings, fp32
    score promotion, -1e30 mask, fp32 softmax) — token ``t`` sits at
    row ``t`` after the gather, so ``col < seq_len`` is precisely the
    dense path's ``col <= idx + row`` at ``row = 0``."""
    k_all = gather_pages(k_pages, block_table)     # (B, H, L, D)
    v_all = gather_pages(v_pages, block_table)
    s_mat = jnp.einsum("bhqd,bhkd->bhqk", q, k_all,
                       preferred_element_type=jnp.float32) * scale
    col = jnp.arange(k_all.shape[2])[None, None, None, :]
    live = col < seq_lens[:, None, None, None]
    s_mat = jnp.where(live, s_mat, NEG_INF)
    # all-masked rows (dead slots): NEG_INF everywhere softmaxes to a
    # uniform distribution over garbage — force the context to zero
    p = jax.nn.softmax(s_mat, axis=-1).astype(v_all.dtype)
    p = jnp.where(live, p, jnp.zeros((), p.dtype))
    return jnp.einsum("bhqk,bhkd->bhqd", p, v_all)


# ---------------------------------------------------------------------------
# Pallas path — block-table-indexed page DMA with dead-page elision
# ---------------------------------------------------------------------------

def _paged_decode_kernel(scale, bq, page, n_pages, *refs):
    """Grid (B, H, ip): one page of one sequence's K/V per step,
    blockwise online softmax in base 2 (the ``_decode_attn_kernel``
    recipe, re-indexed through the block table). The query block is the
    step's single token row-padded to ``bq`` sublanes; every padded row
    computes the same masked softmax and is sliced away outside.
    Validity: logical column ``ip * page + r < seq_lens[b]``. Dead
    pages never DMA: the index map clamps them to the last live page,
    and ``@pl.when`` skips their compute."""
    bt_ref, sl_ref, q_ref, k_ref, v_ref, o_ref, acc, m_scr, l_scr = refs
    ip = pl.program_id(2)
    b_ = pl.program_id(0)
    n = sl_ref[b_]

    @pl.when(ip == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    @pl.when(ip * page < n)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * (scale * LOG2E)  # (bq, d)
        k = k_ref[0, 0].astype(jnp.float32)                    # (page, d)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)                # (bq, page)
        col = ip * page + jax.lax.broadcasted_iota(
            jnp.int32, (bq, page), 1)
        s = jnp.where(col < n, s, NEG_INF)
        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp2(s - m_new)
        corr = jnp.exp2(m_prev - m_new)
        l_scr[:, :1] = corr * l_scr[:, :1] \
            + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0, 0],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc[:] = corr * acc[:] + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(ip == n_pages - 1)
    def _finalize():
        l = l_scr[:, :1]
        o_ref[0, 0] = (acc[:] / jnp.where(l == 0.0, 1.0, l)).astype(
            o_ref.dtype)


def _paged_decode_pallas(q, k_pages, v_pages, block_table, seq_lens,
                         scale):
    b, h, _, d = q.shape
    page = k_pages.shape[2]
    n_pages = block_table.shape[1]
    bq = 8          # minimum sublane tile; rows 1.. are inert padding
    qf = jnp.pad(q.reshape(b, h, 1, d), ((0, 0), (0, 0), (0, bq - 1),
                                         (0, 0)))
    bt = jnp.asarray(block_table, jnp.int32)
    sl = jnp.asarray(seq_lens, jnp.int32)

    def kv_index(b_, h_, ip, bt_ref, sl_ref):
        # dead pages (entirely past the live prefix) clamp to the LAST
        # live page: consecutive identical page ids elide the DMA. A
        # fully-dead slot (n == 0) pins to page 0 of its table.
        last = jnp.maximum(
            jnp.minimum((sl_ref[b_] - 1) // page, n_pages - 1), 0)
        return (bt_ref[b_, jnp.minimum(ip, last)], h_, 0, 0)

    out = pl.pallas_call(
        functools.partial(_paged_decode_kernel, scale, bq, page,
                          n_pages),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, h, n_pages),
            in_specs=[
                pl.BlockSpec((1, 1, bq, d),
                             lambda b_, h_, ip, bt_ref, sl_ref:
                             (b_, h_, 0, 0)),
                pl.BlockSpec((1, 1, page, d),
                             lambda b_, h_, ip, bt_ref, sl_ref:
                             kv_index(b_, h_, ip, bt_ref, sl_ref)),
                pl.BlockSpec((1, 1, page, d),
                             lambda b_, h_, ip, bt_ref, sl_ref:
                             kv_index(b_, h_, ip, bt_ref, sl_ref)),
            ],
            out_specs=pl.BlockSpec((1, 1, bq, d),
                                   lambda b_, h_, ip, bt_ref, sl_ref:
                                   (b_, h_, 0, 0)),
            scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32),
                            pltpu.VMEM((bq, 128), jnp.float32),
                            pltpu.VMEM((bq, 128), jnp.float32)]),
        out_shape=jax.ShapeDtypeStruct((b, h, bq, d), q.dtype),
        interpret=_interpret(),
    )(bt, sl, qf, k_pages, v_pages)[:, :, :1, :]
    return out
