import sys

from apex_tpu.serve.cli import main

if __name__ == "__main__":
    sys.exit(main())
