"""``serve/*`` telemetry event families (documented in
docs/telemetry.md; aggregated by the ``serve`` section of
``telemetry.summarize``).

Gauges (kind=point, per engine step):
  * ``serve/queue_depth``  — admission queue length
  * ``serve/occupancy``    — occupied slots / max_batch (0..1)
  * ``serve/tokens_per_s`` — bench-window decode throughput

Counters (kind=counter):
  * ``serve/admitted`` / ``serve/rejected`` / ``serve/expired`` /
    ``serve/completed`` / ``serve/tokens`` (``rejected`` carries the
    shed reason in ``meta``; ``expired`` counts deadline expiries of
    QUEUED requests, a subset of honest goodput accounting)

Trace spans (aggregated from span rows, like the trainer's step
timing):
  * ``serve/ttft``       — submit -> first token observed on host
  * ``serve/intertoken`` — consecutive host-observed tokens of one
    request

All emission is gated by ``telemetry.enabled()`` inside the collector /
trace layer — a disabled server pays only the no-op call.
"""

from __future__ import annotations

from typing import Optional

from apex_tpu import telemetry, trace

QUEUE_DEPTH = "serve/queue_depth"
OCCUPANCY = "serve/occupancy"
TOKENS_PER_S = "serve/tokens_per_s"
ADMITTED = "serve/admitted"
REJECTED = "serve/rejected"
EXPIRED = "serve/expired"
COMPLETED = "serve/completed"
TOKENS = "serve/tokens"
TTFT = "serve/ttft"
INTERTOKEN = "serve/intertoken"

GAUGES = (QUEUE_DEPTH, OCCUPANCY, TOKENS_PER_S)
COUNTERS = (ADMITTED, REJECTED, EXPIRED, COMPLETED, TOKENS)
SPAN_FAMILIES = (TTFT, INTERTOKEN)


def gauge(name: str, value, *, step: Optional[int] = None) -> None:
    telemetry.record(name, value, step=step, kind="point")


def count(name: str, n: float = 1, *, meta: Optional[dict] = None) -> None:
    telemetry.record(name, n, kind="counter", meta=meta)


def span(name: str, begin: float, end: float, *,
         step: Optional[int] = None, meta: Optional[dict] = None) -> None:
    trace.emit_span(name, begin, end, step=step, meta=meta)
