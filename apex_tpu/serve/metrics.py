"""``serve/*`` and ``req/*`` telemetry event families (documented in
docs/telemetry.md; aggregated by the ``serve`` section of
``telemetry.summarize`` and joined per-request by
``telemetry.requests``).

Gauges (kind=point, per engine step):
  * ``serve/queue_depth``      — admission queue length
  * ``serve/occupancy``        — occupied slots / max_batch (0..1)
  * ``serve/slot_active``      — slots actively decoding / max_batch
    (occupancy counts draining slots too; the gap between the two is
    the drain tax)
  * ``serve/tokens_per_s``     — bench-window decode throughput
  * ``serve/kv_used_pages``    — block-allocator pages in use
  * ``serve/kv_free_pages``    — block-allocator free-list length
  * ``serve/kv_occupancy``     — used pages / total pages (0..1)
  * ``serve/kv_fragmentation`` — 1 - largest contiguous free run /
    free pages (0 = one clean run, ->1 = free list shattered)

Counters (kind=counter):
  * ``serve/admitted`` / ``serve/rejected`` / ``serve/expired`` /
    ``serve/expired_inflight`` / ``serve/completed`` / ``serve/tokens``
    / ``serve/prefill_tokens`` / ``serve/decode_tokens``
    (``rejected`` carries the shed reason in ``meta`` — values come
    from the canonical ``SHED_REASONS`` tuple; ``expired`` counts
    deadline expiries of QUEUED requests, ``expired_inflight`` counts
    deadlines that passed MID-DECODE — their decoded tokens are wasted
    work the goodput ledger prices)

Trace spans (aggregated from span rows, like the trainer's step
timing):
  * ``serve/ttft``       — submit -> first token observed on host
    (meta carries ``rid``/``slot``)
  * ``serve/intertoken`` — consecutive host-observed tokens of one
    request (meta carries ``rid``/``slot``)
  * ``serve/step``       — one decode dispatch interval, ``step`` = the
    engine sequence number (the multi-process clock-join anchor and the
    timeline's engine-step lane)
  * ``req/queued`` / ``req/prefill`` / ``req/decode`` — per-request
    phase intervals (meta ``rid``/``slot``) — the requests pid lanes in
    ``pyprof report --timeline``

Request lifecycle events (kind="req", value = rid; joined offline by
``telemetry.requests.join`` into one record per request):
  * ``req/submit`` / ``req/admit`` / ``req/reject`` /
    ``req/first_token`` / ``req/finish`` / ``req/expire_inflight``

All emission is gated by ``telemetry.enabled()`` inside the collector /
trace layer — a disabled server pays only the no-op call, and the
decode program is jaxpr-identical (every emission here is host-side
Python around the jit, never inside it; pinned by
tests/test_serve_obs.py).
"""

from __future__ import annotations

from typing import Optional

from apex_tpu import telemetry, trace

QUEUE_DEPTH = "serve/queue_depth"
OCCUPANCY = "serve/occupancy"
SLOT_ACTIVE = "serve/slot_active"
TOKENS_PER_S = "serve/tokens_per_s"
KV_USED_PAGES = "serve/kv_used_pages"
KV_FREE_PAGES = "serve/kv_free_pages"
KV_OCCUPANCY = "serve/kv_occupancy"
KV_FRAGMENTATION = "serve/kv_fragmentation"
ADMITTED = "serve/admitted"
REJECTED = "serve/rejected"
EXPIRED = "serve/expired"
EXPIRED_INFLIGHT = "serve/expired_inflight"
COMPLETED = "serve/completed"
TOKENS = "serve/tokens"
PREFILL_TOKENS = "serve/prefill_tokens"
DECODE_TOKENS = "serve/decode_tokens"
TTFT = "serve/ttft"
INTERTOKEN = "serve/intertoken"
ENGINE_STEP = "serve/step"

# per-request phase spans (timeline request lanes / SLO attribution)
REQ_QUEUED = "req/queued"
REQ_PREFILL = "req/prefill"
REQ_DECODE = "req/decode"

# per-request lifecycle events (kind="req")
REQ_SUBMIT = "req/submit"
REQ_ADMIT = "req/admit"
REQ_REJECT = "req/reject"
REQ_FIRST = "req/first_token"
REQ_FINISH = "req/finish"
REQ_EXPIRE_INFLIGHT = "req/expire_inflight"

GAUGES = (QUEUE_DEPTH, OCCUPANCY, SLOT_ACTIVE, TOKENS_PER_S,
          KV_USED_PAGES, KV_FREE_PAGES, KV_OCCUPANCY, KV_FRAGMENTATION)
COUNTERS = (ADMITTED, REJECTED, EXPIRED, EXPIRED_INFLIGHT, COMPLETED,
            TOKENS, PREFILL_TOKENS, DECODE_TOKENS)
SPAN_FAMILIES = (TTFT, INTERTOKEN, ENGINE_STEP)
REQ_SPAN_FAMILIES = (REQ_QUEUED, REQ_PREFILL, REQ_DECODE)
REQ_EVENTS = (REQ_SUBMIT, REQ_ADMIT, REQ_REJECT, REQ_FIRST, REQ_FINISH,
              REQ_EXPIRE_INFLIGHT)

# Canonical shed reasons — the ONLY values ``serve/rejected`` meta may
# carry (and a ``req/reject`` meta ``reason``). admission.py re-exports
# these; the summarize serve section iterates this tuple so the
# breakdown table cannot silently split one reason into two rows.
QUEUE_FULL = "queue_full"
DEADLINE = "deadline"
TOO_LARGE = "too_large"
SHED_REASONS = (QUEUE_FULL, DEADLINE, TOO_LARGE)


def check_reason(reason: str) -> str:
    """Validate a shed reason against the canonical enum — a free-form
    string here would silently split the summarize breakdown table."""
    if reason not in SHED_REASONS:
        raise ValueError(
            f"unknown shed reason {reason!r} (canonical: {SHED_REASONS})")
    return reason


def gauge(name: str, value, *, step: Optional[int] = None) -> None:
    telemetry.record(name, value, step=step, kind="point")


def count(name: str, n: float = 1, *, meta: Optional[dict] = None) -> None:
    telemetry.record(name, n, kind="counter", meta=meta)


def span(name: str, begin: float, end: float, *,
         step: Optional[int] = None, meta: Optional[dict] = None) -> None:
    trace.emit_span(name, begin, end, step=step, meta=meta)


def req_event(name: str, rid: int, *, meta: Optional[dict] = None) -> None:
    """One request-lifecycle fact (kind="req"). value is the rid so the
    event is self-identifying even without meta; structured context
    (slot, reason, phase durations) rides in meta."""
    m = {"rid": int(rid)}
    if meta:
        m.update(meta)
    telemetry.record(name, rid, kind="req", meta=m)
