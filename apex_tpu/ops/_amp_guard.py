"""Kernel-tracing guard against amp interposition.

amp O1/O4 patch ``jax.lax.dot_general`` (and friends) GLOBALLY, and
Pallas kernel bodies are traced at pallas_call time — inside the amp
context of a model forward. Without suspension a kernel's INTERNAL f32
MXU operands get cast to the amp dtype in-kernel: f16 does not even
compile under Mosaic, and bf16 would silently override the kernel's own
precision schedule. Every Pallas module decorates its
pallas_call-invoking entry points with :func:`no_amp` so the hazard is
closed as a CLASS, not per-kernel (r4; surfaced by the convergence
gate's O1 GPT config).

Lives in ops (not amp) so ops modules can import it at module level —
amp.scaler imports ops, so the reverse import must stay lazy.
"""

from __future__ import annotations

import functools


def no_amp(fn):
    """Run ``fn`` (a Pallas kernel-wrapper entry point) with amp
    interposition casting suspended for its dynamic extent."""
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        from apex_tpu.amp.interposition import disable_casts
        with disable_casts():
            return fn(*args, **kwargs)
    return wrapper
