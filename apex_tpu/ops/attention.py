"""Attention kernels — the TPU-native counterpart of the reference's fused
multihead-attention extensions (apex/contrib/csrc/multihead_attn/: CUTLASS
strided-batched GEMMs + fused softmax headers, softmax.h:2003), redesigned as
a Pallas flash-attention kernel (blockwise online softmax, never
materializing the (Sq, Sk) score matrix in HBM), plus:

  * a jnp reference path (the ``impl='default'`` PyTorch path of the
    reference modules) that also returns the per-row logsumexp, and
  * two sequence/context-parallel schemes over a mesh axis — **ring
    attention** (``ppermute`` of K/V shards around the ring with
    numerically-stable partial-softmax merging) and **Ulysses all-to-all**
    (re-shard heads↔sequence so each device runs local flash attention on
    the full sequence). The reference has no distributed attention
    (SURVEY.md §5.7) — this is the long-context capability the TPU
    framework adds, built on the same blockwise math.

Shapes follow (batch, heads, seq, head_dim) throughout.
"""

from __future__ import annotations

import functools
import math
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops._amp_guard import no_amp as _no_amp
# The shared block-preference clamp lives in the tuner's heuristic module
# (it is the seed/fallback policy every block-shaped kernel agrees on);
# re-exported under the historical name for the sweep scripts/tests.
from apex_tpu.tune.heuristics import pick_block as _pick_block

NEG_INF = -1e30
LOG2E = 1.4426950408889634   # log2(e): softmax runs in base-2 (exp2 is the
LN2 = 0.6931471805599453     # VPU-native exponential; exp costs an extra
                             # multiply per element to get there)
# Stable additive-mask magnitude: exp(MASK_BIAS) == 0 in f32 whenever the
# row has any unmasked entry, while f32 still carries ~2e-3 of exponent
# precision at this magnitude so the saved-lse backward reconstruction
# stays faithful (see _prep_bias). Shared by the kernels, the module-level
# mask conversion, and masked_softmax_dropout.
MASK_BIAS = -3e4


def _interpret() -> bool:
    return jax.default_backend() not in ("tpu", "axon")


def _axis_size(axis_name):
    # lazy import: ops loads before parallel in the package __init__
    from apex_tpu.parallel.mesh import bound_axis_size
    return bound_axis_size(axis_name)


def _pad3(x, s_to, d_to):
    """Pad (bh, seq, d) to (bh, s_to, d_to)."""
    return jnp.pad(x, ((0, 0), (0, s_to - x.shape[1]),
                       (0, d_to - x.shape[2])))


def _pad_rowstat(x, s_to, fill=0.0):
    """Pad a (bh, 1, seq) per-row statistic along seq."""
    return jnp.pad(x, ((0, 0), (0, 0), (0, s_to - x.shape[2])),
                   constant_values=fill)


def dropout_keep_mask(seed, bh, row, col, rate: float):
    """Deterministic counter-based dropout mask: a 32-bit integer mix of
    (seed, batch-head index, global row, global col) — the fused-dropout
    counterpart of the reference's Philox-based softmax-dropout kernels
    (apex/contrib/csrc/multihead_attn/dropout.h), chosen over the TPU PRNG
    so the SAME mask is computable in the Pallas kernels, the jnp
    reference, and interpret-mode tests.

    Returns a boolean keep-mask broadcast over ``row``/``col`` (int32
    arrays of equal shape)."""
    x = (seed.astype(jnp.int32) * jnp.int32(-1640531527)     # 0x9E3779B9
         + bh.astype(jnp.int32) * jnp.int32(-2048144789)     # 0x85EBCA6B
         + row * jnp.int32(-1028477387)                      # 0xC2B2AE35
         + col * jnp.int32(741103597))
    x = x ^ (x >> 16)
    x = x * jnp.int32(2135587861)
    x = x ^ (x >> 15)
    x = x * jnp.int32(-1663358717)
    x = x ^ (x >> 16)
    threshold = jnp.int32(int((1.0 - rate) * 2147483647))
    return (x & jnp.int32(0x7FFFFFFF)) < threshold


# ---------------------------------------------------------------------------
# Reference (jnp) attention — also the backward path for the flash kernel
# ---------------------------------------------------------------------------

def attention_reference(q, k, v, *, bias=None, causal=False,
                        scale: Optional[float] = None,
                        return_lse: bool = False,
                        dropout_rate: float = 0.0,
                        dropout_seed=None):
    """Plain attention in fp32 softmax (the ``impl='default'`` path of the
    reference modules, e.g. self_multihead_attn.py:26). With
    ``dropout_rate`` > 0 and a ``dropout_seed``, applies the SAME
    counter-based keep mask as the flash kernels (bit-identical dropout
    pattern across implementations)."""
    d = q.shape[-1]
    b, h, sq = q.shape[0], q.shape[1], q.shape[2]
    sk = k.shape[2]
    scale = (1.0 / math.sqrt(d)) if scale is None else scale
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if causal:
        row = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where(col <= row + (sk - sq), s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    probs = p / l
    if dropout_rate > 0.0:
        bh = jnp.arange(b * h, dtype=jnp.int32).reshape(b, h, 1, 1)
        row = jax.lax.broadcasted_iota(jnp.int32, (1, 1, sq, sk), 2)
        col = jax.lax.broadcasted_iota(jnp.int32, (1, 1, sq, sk), 3)
        keep = dropout_keep_mask(jnp.asarray(dropout_seed, jnp.int32), bh,
                                 row, col, dropout_rate)
        probs = jnp.where(keep, probs / (1.0 - dropout_rate), 0.0)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v,
                     preferred_element_type=jnp.float32).astype(q.dtype)
    if return_lse:
        return out, (m + jnp.log(l))[..., 0]
    return out


# ---------------------------------------------------------------------------
# Flash attention (Pallas forward; recompute backward)
# ---------------------------------------------------------------------------

def _mask_variants(causal, pad_cols, iq, ik, bq, bk, off, nk, compute):
    """Dispatch the masked/unmasked compute variants shared by the forward
    and backward kernels: causal blocks entirely above the diagonal are
    skipped outright (they contribute nothing), and of the live blocks
    only diagonal-straddlers and (for ragged sk) last-column blocks pay
    for mask construction — ``compute(masked)`` must handle both
    variants; exactly one executes per grid step."""
    if not (causal or pad_cols):
        compute(False)
        return
    need_mask = jnp.bool_(False)
    live = None
    if causal:
        live = ik * bk <= iq * bq + bq - 1 + off
        need_mask = need_mask | (ik * bk + bk - 1 > iq * bq + off)
    if pad_cols:
        need_mask = need_mask | (ik == nk - 1)
    masked_pred = need_mask if live is None else live & need_mask
    clear_pred = ~need_mask if live is None else live & ~need_mask
    pl.when(masked_pred)(lambda: compute(True))
    pl.when(clear_pred)(lambda: compute(False))


def _flash_fwd_kernel(scale, causal, rate, s_actual, off, bq, bk, nk,
                      has_bias, pad_cols, *refs):
    """Blockwise online softmax in BASE 2: scores carry a factor of
    log2(e) (folded into ``scale``'s multiply) so the running max /
    probabilities use ``exp2``, the VPU-native exponential — ``exp`` costs
    an extra per-element multiply to reduce to it. The saved lse converts
    back to natural log at finalize (the backward and the ring merge both
    consume natural lse).

    Mask construction (two iotas + compares + select over (bq, bk)) is a
    measurable share of the VPU chain the kernel is bound on, so it is
    elided wherever dataflow proves it redundant: ``pad_cols`` is False
    when sk divides the key block (no padding columns exist), and under
    causal masking the per-step predicate splits blocks into
    diagonal-straddling (masked) and fully-live (unmasked) variants —
    only one variant executes per grid step."""
    if has_bias:
        (q_ref, k_ref, v_ref, b_ref, seed_ref, o_ref, lse_ref,
         acc_scr, m_scr, l_scr) = refs
    else:
        (q_ref, k_ref, v_ref, seed_ref, o_ref, lse_ref,
         acc_scr, m_scr, l_scr) = refs
    bh = pl.program_id(0)
    iq = pl.program_id(1)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    # With no bias the log2(e) factor folds into the score multiply for
    # free. An additive bias can carry MASK_BIAS-magnitude entries, and
    # scaling those by log2e crosses an f32 binade (-3e4 -> -4.3e4, ulp
    # 0.004 -> 0.008), doubling the logit quantization of fully-masked
    # rows AND decorrelating it from the dense reference — so the bias
    # path keeps natural-scale scores and converts at the exp:
    # exp2((s-m)*log2e) is exactly what exp(s-m) computes internally.
    base2 = not has_bias

    def _compute(masked: bool):
        # scale applies to the (bq, d) q block, not the (bq, bk) score
        # matrix: bk/d-fold less VPU work for the same product
        q = q_ref[0].astype(jnp.float32) \
            * (scale * LOG2E if base2 else scale)  # (bq, d)
        k = k_ref[0].astype(jnp.float32)           # (bk, d)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if has_bias:
            # additive score bias (the fused additive-mask / pad-mask of
            # the reference's *_bias_additive_mask kernels); (1, bk) or
            # (bq, bk) block broadcasts over rows
            s = s + b_ref[0].astype(jnp.float32)

        if masked or rate > 0.0:
            row = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            col = ik * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        if masked:
            mask = None
            if pad_cols:
                mask = col < s_actual
            if causal:
                # diagonal anchored at the bottom-right for sq != sk,
                # matching attention_reference's col <= row + (sk - sq)
                cm = col <= row + off
                mask = cm if mask is None else mask & cm
            s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:, :1]                       # (bq, 1)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        if base2:
            p = jnp.exp2(s - m_new)                 # (bq, bk)
            corr = jnp.exp2(m_prev - m_new)         # (bq, 1)
        else:
            p = jnp.exp2((s - m_new) * LOG2E)
            corr = jnp.exp2((m_prev - m_new) * LOG2E)
        # normalizer uses UNdropped p (dropout applies to the normalized
        # probabilities, torch semantics); only the pv accumulation drops
        l_new = corr * l_scr[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        if rate > 0.0:
            keep = dropout_keep_mask(seed_ref[0], bh, row, col, rate)
            p_v = jnp.where(keep, p / (1.0 - rate), 0.0)
        else:
            p_v = p
        pv = jax.lax.dot_general(
            p_v.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_scr[:] = corr * acc_scr[:] + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    _mask_variants(causal, pad_cols, iq, ik, bq, bk, off, nk, _compute)

    @pl.when(ik == nk - 1)
    def _finalize():
        l = l_scr[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows -> zeros
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        # scratch m is base-2 iff no bias: natural lse = m*ln2 + log(l)
        m_nat = m_scr[:, :1] * LN2 if base2 else m_scr[:, :1]
        lse_ref[0, 0] = (m_nat + jnp.log(l))[:, 0]


def _prep_bias(bias, b, h, sq, sk, sqp, skp):
    """Normalize an additive score bias broadcastable to (b, h, sq, sk)
    into a padded (bb*hb, sq-or-1, skp) fp32 operand for the kernels.
    Returns (array, spec_info) — the info drives the BlockSpec index maps
    so broadcast dims NEVER materialize in HBM (a (b, 1, 1, sk) pad mask
    stays O(b·sk): heads broadcast via bh//h index arithmetic, not a
    copy)."""
    bias = jnp.asarray(bias)
    if bias.ndim != 4:
        raise ValueError(
            "flash attention bias must be rank-4, broadcastable to "
            f"(batch, heads, sq, sk); got shape {bias.shape}")
    bb, hb, sqb, skb = bias.shape
    for got, want, name in ((bb, b, "batch"), (hb, h, "heads"),
                            (sqb, sq, "sq"), (skb, sk, "sk")):
        if got not in (1, want):
            raise ValueError(
                f"bias {name} dim is {got}, must be 1 or {want} "
                f"(bias {bias.shape} vs attention ({b}, {h}, {sq}, {sk}))")
    # Clamp huge negative mask values: the backward reconstructs
    # p = exp(s - lse) from the SAVED lse, and at |bias| >~ 1e7 f32 rounds
    # log(l) out of lse entirely (lse = -1e9 + log l == -1e9), breaking
    # the reconstruction. MASK_BIAS is numerically equivalent masking with
    # a stable backward.
    bias = jnp.maximum(bias, MASK_BIAS)
    per_row = sqb != 1
    bias = bias.reshape(bb * hb, sqb, skb)
    if skb == 1:
        bias = jnp.broadcast_to(bias, bias.shape[:2] + (sk,))
    # pad with 0: padded cols are masked by col < s_actual in-kernel
    bias = jnp.pad(bias.astype(jnp.float32),
                   ((0, 0), (0, (sqp - sqb) if per_row else 0),
                    (0, skp - bias.shape[2])))
    return bias, (bb > 1, hb > 1, h, per_row)


def _bias_spec(info, bq, bk, *, row_id, col_id):
    """BlockSpec for a prepared bias over a (bh, i, j) grid where grid dim
    ``row_id``/``col_id`` (1 or 2) indexes query-rows/key-cols. The lead
    coordinate derives from the flat batch-head grid index by static
    arithmetic — broadcast batch/heads dims index block 0 (or bh // h /
    bh % h for half-broadcast biases) instead of materializing copies."""
    per_b, per_h, h, per_row = info

    def lead(bh):
        if per_b and per_h:
            return bh
        if per_b:
            return bh // h
        if per_h:
            return bh % h
        return 0

    def index(bh, i, j):
        g = (bh, i, j)
        return (lead(bh), g[row_id] if per_row else 0, g[col_id])

    return pl.BlockSpec((1, bq if per_row else 1, bk), index)


@_no_amp
def _flash_fwd(q, k, v, *, causal: bool, scale: float,
               dropout_rate: float = 0.0, dropout_seed=None,
               bias=None, block_q: Optional[int] = None,
               block_k: Optional[int] = None):
    # Block preferences resolve through apex_tpu.tune (explicit values
    # always win; None routes to the tuner). Under the default
    # APEX_TPU_TUNE=off policy the resolution returns the frozen (1024,
    # 1024) — re-measured r3 on v5e (s=4096, d=64, bf16) with PROFILER
    # device time (wall-clock over the axon tunnel carries a ~120 ms
    # fixed dispatch cost that poisoned the r2 sweep): (1024, 1024) runs
    # 1.83 ms vs 2.14 for r2's (512, 1024); 2048-wide blocks fail VMEM.
    # The kernel is VPU-bound on the softmax chain, so bigger blocks
    # amortize per-step overhead. (For calibration: this kernel measures
    # 2.7x faster than jax.experimental.pallas.ops.tpu flash_attention
    # on the same shape/chip.)
    b, h, sq, d = q.shape
    sk = k.shape[2]
    dtype = q.dtype
    if block_q is None or block_k is None:
        from apex_tpu import tune
        tq, tk = tune.attention_blocks("attention_fwd", sq=sq, sk=sk,
                                       d=d, dtype=dtype)
        block_q = tq if block_q is None else block_q
        block_k = tk if block_k is None else block_k
    seed = jnp.asarray(
        0 if dropout_seed is None else dropout_seed,
        jnp.int32).reshape((1,))

    # pad head_dim to lane multiple, seq to block multiples
    dp = ((d + 127) // 128) * 128
    bq = _pick_block(block_q, sq)
    bk = _pick_block(block_k, sk)
    sqp = ((sq + bq - 1) // bq) * bq
    skp = ((sk + bk - 1) // bk) * bk

    qf = _pad3(q.reshape(b * h, sq, d), sqp, dp)
    kf = _pad3(k.reshape(b * h, sk, d), skp, dp)
    vf = _pad3(v.reshape(b * h, sk, d), skp, dp)

    nq = sqp // bq
    nk = skp // bk
    grid = (b * h, nq, nk)

    has_bias = bias is not None
    bias_ops, bias_specs = [], []
    if has_bias:
        bf, binfo = _prep_bias(bias, b, h, sq, sk, sqp, skp)
        bias_ops = [bf]
        bias_specs = [_bias_spec(binfo, bq, bk, row_id=1, col_id=2)]

    out, lse = pl.pallas_call(
        functools.partial(_flash_fwd_kernel, scale, causal, dropout_rate,
                          sk, sk - sq, bq, bk, nk, has_bias, skp != sk),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, dp), lambda bh, iq, ik: (bh, iq, 0)),
            pl.BlockSpec((1, bk, dp), lambda bh, iq, ik: (bh, ik, 0)),
            pl.BlockSpec((1, bk, dp), lambda bh, iq, ik: (bh, ik, 0)),
            *bias_specs,
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, dp), lambda bh, iq, ik: (bh, iq, 0)),
            # lse rides as (bh, 1, seq): Mosaic requires the last two block
            # dims be (8k, 128k) or equal to the array dims — (1, bq) over
            # a (bh, seq) array is neither
            pl.BlockSpec((1, 1, bq), lambda bh, iq, ik: (bh, 0, iq)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sqp, dp), dtype),
            jax.ShapeDtypeStruct((b * h, 1, sqp), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, dp), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
        ],
        interpret=_interpret(),
    )(qf, kf, vf, *bias_ops, seed)
    out = out[:, :sq, :d].reshape(b, h, sq, d)
    lse = lse[:, 0, :sq].reshape(b, h, sq)
    return out, lse


def _recompute_p_ds(scale, causal, rate, sq_actual, sk_actual, bq, bk,
                    bh, iq, ik, q_ref, k_ref, v_ref, do_ref, lse_ref,
                    delta_ref, seed_ref, b_ref=None, masked=True,
                    pad_cols=True):
    """Shared backward recompute: softmax probs from the saved lse plus
    ds = p * (dP - delta). Used by both the dK/dV and dQ kernels.

    Exponentials run through exp2 like the forward (pre-folded scale when
    no bias; natural-scale with conversion at the exp otherwise). The ROW
    padding mask is never needed: padded lse rows are filled with +1e30
    (see _flash_bwd) so p is exactly 0 there in both score scales, padded
    dO/delta rows are zero besides, and padded k rows are zero, which
    zeroes dq contributions (outputs at padded positions are cropped).
    The COLUMN mask survives only for ragged sk (``pad_cols``) — zero-
    padded k makes s=0 there, and a fully-bias-masked row's lse ~ -3e4
    would turn exp2(0 - lse2) into inf — and the causal mask only on
    diagonal-straddling blocks (``masked``; the caller's grid predicate
    proves other live blocks fully unmasked).

    With dropout (y_i = sum_j p_ij m_ij/keep v_j / l_i): the returned
    p_drop = p*m/keep feeds dV, and dP picks up the same m/keep factor
    before the delta subtraction — delta itself is unchanged because
    sum_k a_ik dP_ik still telescopes to dO.y (see _flash_bwd)."""
    base2 = b_ref is None   # same binade rationale as _flash_fwd_kernel
    q = q_ref[0].astype(jnp.float32)            # (bq, d)
    k = k_ref[0].astype(jnp.float32)            # (bk, d)
    # scale folds into the (bk, d) k block (q and k return raw for the
    # dk/dq products): d/bk-fold less VPU work than scaling (bq, bk)
    s = jax.lax.dot_general(
        q, k * (scale * LOG2E if base2 else scale),
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
    if b_ref is not None:
        s = s + b_ref[0].astype(jnp.float32)    # fused additive score bias
    if masked or rate > 0.0:
        row = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        col = ik * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    lse = lse_ref[0, 0][:, None]                # (bq, 1)
    e2 = (s - lse * LOG2E) if base2 else (s - lse) * LOG2E
    if masked:
        mask = None
        if pad_cols:
            mask = col < sk_actual
        if causal:
            cm = col <= row + (sk_actual - sq_actual)
            mask = cm if mask is None else mask & cm
        p = jnp.where(mask, jnp.exp2(e2), 0.0)  # (bq, bk)
    else:
        p = jnp.exp2(e2)
    do = do_ref[0].astype(jnp.float32)          # (bq, d)
    dp = jax.lax.dot_general(
        do, v_ref[0].astype(jnp.float32), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)     # (bq, bk)
    if rate > 0.0:
        keep = dropout_keep_mask(seed_ref[0], bh, row, col, rate)
        p_drop = jnp.where(keep, p / (1.0 - rate), 0.0)
        dp = jnp.where(keep, dp / (1.0 - rate), 0.0)
    else:
        p_drop = p
    delta = delta_ref[0, 0][:, None]            # (bq, 1)
    ds = p * (dp - delta)
    return q, k, p_drop, do, ds


def _flash_bwd_kv_kernel(scale, causal, rate, sq_actual, sk_actual, bq, bk,
                         nq, nk, has_bias, pad_cols, bias_grad,
                         db_per_row, *refs):
    """Grid (bh, ik, iq): accumulate dK/dV for key block ik over all query
    blocks. p = exp2(s2 - lse2); dv += p^T dO; ds = p*(dP - delta);
    dk += ds^T q * scale. With ``bias_grad``, ds IS dbias for this
    (iq, ik) block (s = scale·qkᵀ + bias, so ∂L/∂bias = ∂L/∂s): a
    row-varying bias writes it straight out (each block pair is visited
    once); a row-BROADCAST bias (sqb == 1, e.g. a learned column bias)
    accumulates the column sums in a (1, bk) scratch over the inner iq
    sweep — the dk_scr pattern — so only an O(sk) plane ever reaches
    HBM."""
    if has_bias:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, seed_ref, b_ref,
         *rest) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, seed_ref,
         *rest) = refs
        b_ref = None
    db_scr = None
    if bias_grad and not db_per_row:
        dk_ref, dv_ref, db_ref, dk_scr, dv_scr, db_scr = rest
    elif bias_grad:
        dk_ref, dv_ref, db_ref, dk_scr, dv_scr = rest
    else:
        dk_ref, dv_ref, dk_scr, dv_scr = rest
        db_ref = None
    bh = pl.program_id(0)
    ik = pl.program_id(1)
    iq = pl.program_id(2)

    @pl.when(iq == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)
        if db_scr is not None:
            db_scr[:] = jnp.zeros_like(db_scr)

    if bias_grad and db_per_row:
        # causal-skipped blocks never run _compute; their dbias is zero,
        # and a pure-write output must still be written every grid step
        db_ref[0] = jnp.zeros((bq, bk), db_ref.dtype)

    def _compute(masked):
        q, _, p, do, ds = _recompute_p_ds(
            scale, causal, rate, sq_actual, sk_actual, bq, bk, bh, iq, ik,
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, seed_ref,
            b_ref, masked=masked, pad_cols=pad_cols)
        dv_scr[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)     # p^T dO -> (bk, d)
        dk_scr[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # ds^T q
        if bias_grad and db_per_row:
            db_ref[0] = ds.astype(db_ref.dtype)
        elif bias_grad:
            db_scr[:] += jnp.sum(ds, axis=0, keepdims=True)

    _mask_variants(causal, pad_cols, iq, ik, bq, bk,
                   sk_actual - sq_actual, nk, _compute)

    @pl.when(iq == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)
        if db_scr is not None:
            db_ref[0] = db_scr[:].astype(db_ref.dtype)


def _flash_bwd_q_kernel(scale, causal, rate, sq_actual, sk_actual, bq, bk,
                        nk, has_bias, pad_cols, *refs):
    """Grid (bh, iq, ik): accumulate dQ for query block iq over all key
    blocks. dq += ds k * scale."""
    if has_bias:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, seed_ref, b_ref,
         dq_ref, dq_scr) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, seed_ref,
         dq_ref, dq_scr) = refs
        b_ref = None
    bh = pl.program_id(0)
    iq = pl.program_id(1)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _compute(masked):
        _, k, _, _, ds = _recompute_p_ds(
            scale, causal, rate, sq_actual, sk_actual, bq, bk, bh, iq, ik,
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, seed_ref,
            b_ref, masked=masked, pad_cols=pad_cols)
        dq_scr[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    _mask_variants(causal, pad_cols, iq, ik, bq, bk,
                   sk_actual - sq_actual, nk, _compute)

    @pl.when(ik == nk - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _flash_bwd_fused_kernel(scale, causal, rate, sq_actual, sk_actual, bq,
                            bk, nq, nk, has_bias, pad_cols, bias_grad,
                            db_per_row, *refs):
    """Single-sweep backward, grid (bh, ik, iq): the VPU-bound softmax
    recompute (s → p → dP → ds) runs ONCE per (iq, ik) block pair and
    feeds all three gradients — dV/dK accumulate in per-key-block scratch
    (finalized when the inner query sweep ends), dQ accumulates in a
    persistent full-sequence f32 scratch at row offset iq·bq (TPU grids
    execute sequentially, so revisits across the outer ik sweeps are
    ordered) and is written out during the LAST key sweep. Matches the
    reference's one-backward-per-module design
    (apex/contrib/csrc/multihead_attn/self_multihead_attn_cuda.cu) where
    a single backward launch produces all input grads; the two-pass
    variant below recomputed the softmax chain twice."""
    if has_bias:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, seed_ref, b_ref,
         *rest) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, seed_ref,
         *rest) = refs
        b_ref = None
    db_scr = None
    if bias_grad and not db_per_row:
        (dk_ref, dv_ref, dq_ref, db_ref,
         dk_scr, dv_scr, dq_scr, db_scr) = rest
    elif bias_grad:
        dk_ref, dv_ref, dq_ref, db_ref, dk_scr, dv_scr, dq_scr = rest
    else:
        dk_ref, dv_ref, dq_ref, dk_scr, dv_scr, dq_scr = rest
        db_ref = None
    bh = pl.program_id(0)
    ik = pl.program_id(1)
    iq = pl.program_id(2)

    @pl.when(iq == 0)
    def _init_kv():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)
        if db_scr is not None:
            db_scr[:] = jnp.zeros_like(db_scr)

    @pl.when(ik == 0)
    def _init_q():
        dq_scr[pl.ds(iq * bq, bq), :] = jnp.zeros(
            (bq, dq_scr.shape[1]), jnp.float32)

    if bias_grad and db_per_row:
        # see _flash_bwd_kv_kernel: skipped causal blocks still need a
        # written (zero) dbias block
        db_ref[0] = jnp.zeros((bq, bk), db_ref.dtype)

    def _compute(masked):
        q, kblk, p, do, ds = _recompute_p_ds(
            scale, causal, rate, sq_actual, sk_actual, bq, bk, bh, iq, ik,
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, seed_ref,
            b_ref, masked=masked, pad_cols=pad_cols)
        dv_scr[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # p^T dO -> (bk, d)
        dk_scr[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # ds^T q
        dq_scr[pl.ds(iq * bq, bq), :] += jax.lax.dot_general(
            ds, kblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # ds k -> (bq, d)
        if bias_grad and db_per_row:
            db_ref[0] = ds.astype(db_ref.dtype)
        elif bias_grad:
            db_scr[:] += jnp.sum(ds, axis=0, keepdims=True)

    _mask_variants(causal, pad_cols, iq, ik, bq, bk,
                   sk_actual - sq_actual, nk, _compute)

    @pl.when(iq == nq - 1)
    def _finalize_kv():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)
        if db_scr is not None:
            db_ref[0] = db_scr[:].astype(db_ref.dtype)

    @pl.when(ik == nk - 1)
    def _finalize_q():
        dq_ref[0] = dq_scr[pl.ds(iq * bq, bq), :].astype(dq_ref.dtype)


# The fused backward's dQ scratch holds the whole padded query sequence in
# f32 VMEM (sqp × dp × 4 bytes). v5e VMEM is ~16 MB/core and the kernel
# also lives with its block buffers and (bq, bk) f32 score temporaries, so
# beyond this budget the two-pass backward takes over (long-context
# shapes: 131k rides two-pass; 4k–16k ride fused).
_FUSED_BWD_DQ_SCRATCH_BYTES = 8 * 2 ** 20
# Block tunings, overridable for sweeps: fused needs narrower query blocks
# than r3's two-pass (1024, 1024) to leave VMEM room for the dq scratch.
# (The two-pass preference itself now resolves through apex_tpu.tune —
# heuristics.ATTENTION_BLOCK_Q/K carry the frozen (1024, 1024).)
_FUSED_BLOCK_Q = 512
_FUSED_BLOCK_K = 1024


def _fused_bwd_plan(sq: int, d: int) -> Tuple[bool, int]:
    """(fused?, block_q cap) for a backward at this shape — the single
    owner of the fused-vs-two-pass dispatch criterion, shared by
    _flash_bwd and the benchmarks (so achieved-FLOP accounting can't
    drift from the path the kernel actually takes). r4 v5e sweep (d=64):
    scratch <=4 MB runs (512, 1024); larger scratch halves block_q (the
    8 MB s=16384 scratch + 512-wide blocks exceed scoped VMEM).

    Shapes past the scratch cap no longer mean two-pass outright:
    dropout-free, bias-free backwards run the SEGMENTED fused scheme
    (_flash_bwd_segmented) — query rows split into scratch-sized
    segments, one fused sweep each; two-pass remains only for
    dropout/bias at such lengths (their kernels index GLOBAL rows)."""
    dp_ = ((d + 127) // 128) * 128
    scratch_bytes = (((sq + 127) // 128) * 128) * dp_ * 4
    fused = scratch_bytes <= _FUSED_BWD_DQ_SCRATCH_BYTES
    bq_cap = _FUSED_BLOCK_Q if scratch_bytes <= 4 * 2 ** 20 \
        else _FUSED_BLOCK_Q // 2
    return fused, bq_cap


def _segment_rows(d: int) -> int:
    """Largest 128-aligned query-segment length whose dq scratch fits
    the fused kernel's VMEM budget (16,384 rows at d<=128)."""
    dp_ = ((d + 127) // 128) * 128
    return max(128, (_FUSED_BWD_DQ_SCRATCH_BYTES // (dp_ * 4))
               // 128 * 128)


def _flash_bwd_segmented(q, k, v, out, lse, g, *, causal, scale,
                         block_q, block_k):
    """Fused single-sweep backward for sequences whose full-seq dq
    scratch exceeds the VMEM budget (>16k rows at d<=128; VERDICT r4
    next #3): the query rows split into scratch-sized segments, each
    running the fused kernel against only the keys its causal window
    reaches (k/v sliced to q0 + L + sk - sq columns), with the
    per-segment dK/dV partials accumulated in f32 at the JAX level.
    The VPU-bound softmax recompute chain runs ONCE per block pair —
    the whole point of the fused kernel — where the two-pass scheme ran
    it twice; the price is O(n_segments) extra dK/dV HBM read+write
    traffic for the accumulation, a bandwidth cost an order below the
    kernel's own block streaming at these lengths. Dropout / bias /
    dbias shapes keep the two-pass fallback: their in-kernel counter
    and BlockSpecs index GLOBAL query rows, which a row-sliced segment
    call would silently mis-address (dropout masks would decorrelate
    from the forward's)."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    seg = _segment_rows(d)
    dq_parts = []
    dk_acc = jnp.zeros((b, h, sk, d), jnp.float32)
    dv_acc = jnp.zeros((b, h, sk, d), jnp.float32)
    for q0 in range(0, sq, seg):
        n = min(seg, sq - q0)
        # rows q0..q0+n-1 attend cols <= row + (sk - sq) (bottom-right
        # anchored diagonal) -> the slice preserves the offset exactly
        sk_eff = min(sk, q0 + n + sk - sq) if causal else sk
        if sk_eff <= 0:   # fully-masked rows (causal, sk < sq head)
            dq_parts.append(jnp.zeros_like(q[:, :, q0:q0 + n]))
            continue
        dq_i, dk_i, dv_i = _flash_bwd(
            q[:, :, q0:q0 + n], k[:, :, :sk_eff], v[:, :, :sk_eff],
            out[:, :, q0:q0 + n], lse[:, :, q0:q0 + n],
            g[:, :, q0:q0 + n], causal=causal, scale=scale,
            block_q=block_q, block_k=block_k)
        dq_parts.append(dq_i)
        dk_acc = dk_acc.at[:, :, :sk_eff].add(dk_i.astype(jnp.float32))
        dv_acc = dv_acc.at[:, :, :sk_eff].add(dv_i.astype(jnp.float32))
    dq = jnp.concatenate(dq_parts, axis=2)
    return dq, dk_acc.astype(k.dtype), dv_acc.astype(v.dtype)


@_no_amp
def _flash_bwd(q, k, v, out, lse, g, *, causal: bool, scale: float,
               dropout_rate: float = 0.0, dropout_seed=None,
               bias=None, block_q: Optional[int] = None,
               block_k: Optional[int] = None, bias_grad: bool = False):
    """Pallas flash backward: O(S) memory (only lse/delta row stats are
    carried; the (Sq, Sk) score matrix never hits HBM) — the counterpart of
    the reference's fused MHA backward kernels. Default: a single fused
    sweep computing dq+dk+dv with one softmax recompute per block pair
    (_flash_bwd_fused_kernel); sequences whose full-seq dq scratch would
    blow VMEM (_fused_bwd_plan) fall back to the dKdV-then-dQ two-pass
    scheme at r3's (1024, 1024) tuning."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    if block_q is None or block_k is None:
        # tuner resolution (off policy: the frozen (1024, 1024) two-pass
        # tuning); explicit caller values always win
        from apex_tpu import tune
        tq, tk = tune.attention_blocks("attention_bwd", sq=sq, sk=sk,
                                       d=d, dtype=q.dtype)
        block_q = tq if block_q is None else block_q
        block_k = tk if block_k is None else block_k
    if (not _fused_bwd_plan(sq, d)[0] and dropout_rate == 0.0
            and bias is None and sq > _segment_rows(d)):
        # scratch-overflow shapes without dropout/bias: segmented fused
        # sweeps instead of the two-pass recompute-twice scheme
        return _flash_bwd_segmented(q, k, v, out, lse, g, causal=causal,
                                    scale=scale, block_q=block_q,
                                    block_k=block_k)
    dtype = q.dtype
    seed = jnp.asarray(
        0 if dropout_seed is None else dropout_seed,
        jnp.int32).reshape((1,))

    # delta_i = rowsum(dO ⊙ O): the only quantity besides lse the backward
    # needs from the forward. Unchanged under dropout: delta = dO.y =
    # sum_k a_ik (dO.v_k) with a already carrying the keep mask.
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)                     # (b, h, sq)

    if bias_grad and bias is None:
        raise ValueError("bias_grad=True requires a bias")
    dp_ = ((d + 127) // 128) * 128
    # Fused-vs-two-pass decision precedes block choice (each path has its
    # own tuning): fused iff the 128-aligned full-seq dq scratch fits.
    fused, bq_cap = _fused_bwd_plan(sq, d)
    if fused:
        block_q = min(block_q, bq_cap)
        block_k = min(block_k, _FUSED_BLOCK_K)
    db_per_row = bias_grad and bias.shape[2] != 1
    if db_per_row:
        # the (bq, bk) f32 dbias output block shares the same VMEM budget
        # as the score temporaries; 512-wide caps keep it at <=1 MB.
        # Row-broadcast biases skip this: their dbias lives in a (1, bk)
        # scratch, no plane and no cap.
        block_q = min(block_q, 512)
        block_k = min(block_k, 512)
    bq = _pick_block(block_q, sq)
    bk = _pick_block(block_k, sk)
    sqp = ((sq + bq - 1) // bq) * bq
    skp = ((sk + bk - 1) // bk) * bk

    qf = _pad3(q.reshape(b * h, sq, d), sqp, dp_)
    kf = _pad3(k.reshape(b * h, sk, d), skp, dp_)
    vf = _pad3(v.reshape(b * h, sk, d), skp, dp_)
    dof = _pad3(g.reshape(b * h, sq, d), sqp, dp_)
    # lse/delta ride as (bh, 1, seq) for Mosaic block-shape rules (see
    # _flash_fwd). Padded rows fill with a huge POSITIVE lse so the
    # recomputed p = exp2((s - lse)·log2e) is EXACTLY 0 there in both the
    # base-2 and bias paths. (A 0.0 fill relied on zero-padded dO/delta to
    # cancel p≈1 terms — but on the bias path a padded row's s equals the
    # raw bias, and a positive additive bias > ~88 made p overflow to inf,
    # whose inf·0 products NaN'd the whole dk/dv block whenever sq wasn't
    # a block multiple.)
    lsef = _pad_rowstat(lse.reshape(b * h, 1, sq), sqp, fill=-NEG_INF)
    deltaf = _pad_rowstat(delta.reshape(b * h, 1, sq), sqp)

    nq = sqp // bq
    nk = skp // bk

    has_bias = bias is not None
    bias_ops = []
    kv_bias_specs, q_bias_specs = [], []
    if has_bias:
        bf, binfo = _prep_bias(bias, b, h, sq, sk, sqp, skp)
        bias_ops = [bf]
        # kv grid is (bh, ik, iq): rows from grid dim 2, cols from dim 1;
        # q grid is (bh, iq, ik): rows from dim 1, cols from dim 2
        kv_bias_specs = [_bias_spec(binfo, bq, bk, row_id=2, col_id=1)]
        q_bias_specs = [_bias_spec(binfo, bq, bk, row_id=1, col_id=2)]

    q_spec = pl.BlockSpec((1, bq, dp_), lambda bh, i, j: (bh, j, 0))
    k_spec = pl.BlockSpec((1, bk, dp_), lambda bh, i, j: (bh, i, 0))
    row_spec = pl.BlockSpec((1, 1, bq), lambda bh, i, j: (bh, 0, j))

    # dbias output: for a row-varying bias, the (sqp, skp) score-grad
    # plane (rows from the iq grid dim — 2 on the kv/fused grid — cols
    # from ik, dim 1); for a row-broadcast bias, only the in-kernel
    # row-reduced (1, skp) plane (O(sk), not O(sq·sk) — flash's O(S)
    # memory survives a learned column bias). Remaining broadcast dims
    # (batch/head — the bh grid dim is outermost, so its revisits are
    # non-consecutive and cannot accumulate in-kernel) reduce in
    # _reduce_dbias afterwards.
    db_specs, db_shapes, db_scratch = [], [], []
    if bias_grad and db_per_row:
        db_specs = [pl.BlockSpec((1, bq, bk), lambda bh, i, j: (bh, j, i))]
        db_shapes = [jax.ShapeDtypeStruct((b * h, sqp, skp), jnp.float32)]
    elif bias_grad:
        db_specs = [pl.BlockSpec((1, 1, bk), lambda bh, i, j: (bh, 0, i))]
        db_shapes = [jax.ShapeDtypeStruct((b * h, 1, skp), jnp.float32)]
        db_scratch = [pltpu.VMEM((1, bk), jnp.float32)]

    if fused:
        # One sweep, all three grads: the softmax recompute chain (the
        # kernel's VPU bottleneck) runs once per block pair instead of
        # twice. dq rides a persistent (sqp, dp) f32 scratch.
        dk, dv, dq, *db = pl.pallas_call(
            functools.partial(_flash_bwd_fused_kernel, scale, causal,
                              dropout_rate, sq, sk, bq, bk, nq, nk,
                              has_bias, skp != sk, bias_grad, db_per_row),
            grid=(b * h, nk, nq),
            in_specs=[q_spec, k_spec, k_spec, q_spec, row_spec, row_spec,
                      pl.BlockSpec(memory_space=pltpu.SMEM),
                      *kv_bias_specs],
            out_specs=[
                pl.BlockSpec((1, bk, dp_), lambda bh, i, j: (bh, i, 0)),
                pl.BlockSpec((1, bk, dp_), lambda bh, i, j: (bh, i, 0)),
                pl.BlockSpec((1, bq, dp_), lambda bh, i, j: (bh, j, 0)),
                *db_specs,
            ],
            out_shape=[
                jax.ShapeDtypeStruct((b * h, skp, dp_), dtype),
                jax.ShapeDtypeStruct((b * h, skp, dp_), dtype),
                jax.ShapeDtypeStruct((b * h, sqp, dp_), dtype),
                *db_shapes,
            ],
            scratch_shapes=[pltpu.VMEM((bk, dp_), jnp.float32),
                            pltpu.VMEM((bk, dp_), jnp.float32),
                            pltpu.VMEM((sqp, dp_), jnp.float32),
                            *db_scratch],
            interpret=_interpret(),
        )(qf, kf, vf, dof, lsef, deltaf, seed, *bias_ops)
        dq = dq[:, :sq, :d].reshape(b, h, sq, d)
        dk = dk[:, :sk, :d].reshape(b, h, sk, d)
        dv = dv[:, :sk, :d].reshape(b, h, sk, d)
        if bias_grad:
            rows = sq if db_per_row else 1
            return dq, dk, dv, \
                db[0][:, :rows, :sk].reshape(b, h, rows, sk)
        return dq, dk, dv

    dk, dv, *db = pl.pallas_call(
        functools.partial(_flash_bwd_kv_kernel, scale, causal,
                          dropout_rate, sq, sk, bq, bk, nq, nk, has_bias,
                          skp != sk, bias_grad, db_per_row),
        grid=(b * h, nk, nq),
        in_specs=[q_spec, k_spec, k_spec, q_spec, row_spec, row_spec,
                  pl.BlockSpec(memory_space=pltpu.SMEM), *kv_bias_specs],
        out_specs=[pl.BlockSpec((1, bk, dp_), lambda bh, i, j: (bh, i, 0))]
        * 2 + db_specs,
        out_shape=[jax.ShapeDtypeStruct((b * h, skp, dp_), dtype)] * 2
        + db_shapes,
        scratch_shapes=[pltpu.VMEM((bk, dp_), jnp.float32)] * 2
        + db_scratch,
        interpret=_interpret(),
    )(qf, kf, vf, dof, lsef, deltaf, seed, *bias_ops)

    q_spec2 = pl.BlockSpec((1, bq, dp_), lambda bh, i, j: (bh, i, 0))
    k_spec2 = pl.BlockSpec((1, bk, dp_), lambda bh, i, j: (bh, j, 0))
    row_spec2 = pl.BlockSpec((1, 1, bq), lambda bh, i, j: (bh, 0, i))
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_q_kernel, scale, causal,
                          dropout_rate, sq, sk, bq, bk, nk, has_bias,
                          skp != sk),
        grid=(b * h, nq, nk),
        in_specs=[q_spec2, k_spec2, k_spec2, q_spec2, row_spec2, row_spec2,
                  pl.BlockSpec(memory_space=pltpu.SMEM), *q_bias_specs],
        out_specs=pl.BlockSpec((1, bq, dp_), lambda bh, i, j: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sqp, dp_), dtype),
        scratch_shapes=[pltpu.VMEM((bq, dp_), jnp.float32)],
        interpret=_interpret(),
    )(qf, kf, vf, dof, lsef, deltaf, seed, *bias_ops)

    dq = dq[:, :sq, :d].reshape(b, h, sq, d)
    dk = dk[:, :sk, :d].reshape(b, h, sk, d)
    dv = dv[:, :sk, :d].reshape(b, h, sk, d)
    if bias_grad:
        rows = sq if db_per_row else 1
        return dq, dk, dv, db[0][:, :rows, :sk].reshape(b, h, rows, sk)
    return dq, dk, dv


def _reduce_dbias(db_full, bias):
    """Reduce the full-rank (b, h, sq, sk) f32 score grad to the bias's
    broadcast shape (summing over dims the bias broadcast), cast to the
    bias dtype — the cotangent custom_vjp must return."""
    axes = tuple(i for i, (dbd, bd)
                 in enumerate(zip(db_full.shape, bias.shape)) if bd == 1
                 and dbd != 1)
    if axes:
        db_full = jnp.sum(db_full, axis=axes, keepdims=True)
    return db_full.astype(bias.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash_attention_core(q, k, v, bias, seed, causal, scale, rate,
                          has_bias, bias_grad):
    out, _ = _flash_fwd(q, k, v, causal=causal, scale=scale,
                        dropout_rate=rate, dropout_seed=seed,
                        bias=bias if has_bias else None)
    return out


def _flash_vjp_fwd(q, k, v, bias, seed, causal, scale, rate, has_bias,
                   bias_grad):
    out, lse = _flash_fwd(q, k, v, causal=causal, scale=scale,
                          dropout_rate=rate, dropout_seed=seed,
                          bias=bias if has_bias else None)
    return out, (q, k, v, bias, seed, out, lse)


def _flash_vjp_bwd(causal, scale, rate, has_bias, bias_grad, res, g):
    q, k, v, bias, seed, out, lse = res
    grads = _flash_bwd(q, k, v, out, lse, g, causal=causal,
                       scale=scale, dropout_rate=rate,
                       dropout_seed=seed,
                       bias=bias if has_bias else None,
                       bias_grad=bias_grad and has_bias)
    # integer seed: zero-size float0 cotangent
    dseed = np.zeros(np.shape(seed), jax.dtypes.float0)
    if bias_grad and has_bias:
        dq, dk, dv, db = grads
        return dq, dk, dv, _reduce_dbias(db, bias), dseed
    # bias is a mask/additive constant (the public wrapper stop_gradients
    # it unless trainable_bias)
    dq, dk, dv = grads
    return dq, dk, dv, jnp.zeros_like(bias), dseed


_flash_attention_core.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None,
                    dropout_rate: float = 0.0, dropout_seed=None,
                    bias=None, trainable_bias: bool = False):
    """Flash attention: Pallas forward AND backward (blockwise, O(S) HBM —
    the (Sq, Sk) score matrix never materializes in either direction).
    ``dropout_rate`` > 0 fuses dropout into the kernels (the reference's
    fused softmax-dropout, dropout.h) using the deterministic counter mask
    of :func:`dropout_keep_mask` seeded by ``dropout_seed`` (int32 scalar,
    traced — a fresh seed per step does not retrace).

    ``bias`` is an additive score bias broadcastable to (b, h, sq, sk) —
    the fused additive-mask / padding-mask of the reference's
    *_bias_additive_mask and masked_softmax kernels
    (self_multihead_attn_bias_additive_mask_cuda.cu). Broadcast dims stay
    broadcast in HBM (a (b, 1, 1, sk) pad mask costs O(b·sk), not
    O(b·h·sq·sk)). By default the bias is a constant (stop_gradient):
    masks are data. ``trainable_bias=True`` makes it a LEARNED score bias
    (T5 relative bias, learned ALiBi, ...): the backward kernel emits the
    per-block score grad ds = p·(dP − Δ) as a fourth output (each block
    pair is visited once — a pure write, no extra matmuls) and the
    cotangent reduces over the bias's broadcast dims. Cost: O(sq·sk) f32
    HBM traffic for a bias that VARIES over query rows — inherent to a
    full-rank bias grad, the same cost the dense path pays; a
    row-broadcast bias (e.g. a learned column bias, sqb == 1) reduces
    rows in-kernel and writes only an O(sk) plane, keeping flash's O(S)
    memory."""
    scale = (1.0 / math.sqrt(q.shape[-1])) if scale is None else scale
    rate = float(dropout_rate)
    if rate > 0.0 and dropout_seed is None:
        raise ValueError(
            "flash_attention: dropout_rate > 0 requires dropout_seed — "
            "without a per-step seed the same attention entries would be "
            "dropped every step of training")
    seed = jnp.asarray(0 if dropout_seed is None else dropout_seed,
                       jnp.int32)
    has_bias = bias is not None
    bias_grad = bool(trainable_bias) and has_bias
    if has_bias:
        bias_arr = jnp.asarray(bias)
        if not bias_grad:
            bias_arr = jax.lax.stop_gradient(bias_arr)
    else:
        bias_arr = jnp.zeros((1, 1, 1, 1), jnp.float32)
    # Mosaic has no f16 (fp16 amp levels O1/O2 cast q/k/v to float16):
    # run the kernels in bf16 and cast back — the in-kernel softmax/lse
    # chain is f32 either way, so only the MXU operand dtype changes.
    # The cast sits OUTSIDE the custom_vjp, so autodiff casts the f16
    # cotangents the same way (the fp16 analog of multi_tensor's
    # fp16-routes-to-jnp policy; interpret mode runs f16 natively).
    if q.dtype == jnp.float16 and not _interpret():
        # apexlint: the casts below do not BYPASS the amp policy — they
        # IMPLEMENT it for the f16 levels on a backend with no f16 MXU
        # path; the target dtype is fixed by hardware, not a policy knob.
        out = _flash_attention_core(
            q.astype(jnp.bfloat16),  # apexlint: disable=APX005 -- Mosaic f16 shim
            k.astype(jnp.bfloat16),  # apexlint: disable=APX005 -- Mosaic f16 shim
            v.astype(jnp.bfloat16),  # apexlint: disable=APX005 -- Mosaic f16 shim
            bias_arr, seed, causal, scale, rate,
            has_bias, bias_grad)
        return out.astype(jnp.float16)  # apexlint: disable=APX005 -- back to caller dtype
    return _flash_attention_core(q, k, v, bias_arr, seed, causal, scale,
                                 rate, has_bias, bias_grad)


def attention_model_flops(b, h, sq, sk, d, *, causal=False,
                          training=True) -> float:
    """Analytic MODEL FLOPs of one attention call under the standard
    dense-autodiff accounting (MAC=2): forward QK^T + PV = 2 matmuls of
    2·b·h·sq·sk·d each; training adds the 4-matmul backward (dV = P^T dO,
    dP = dO V^T, dQ = dS K, dK = dS^T Q — the softmax backward dS is
    elementwise) for 6 total, the usual backward-is-2x-forward count;
    causal masking halves the useful area.

    This is the MFU numerator for attention-heavy benches: XLA cost
    analysis sees Pallas kernels as ~0-FLOP custom calls, so benches add
    this per flash call to turn "MFU floor" disclaimers into real,
    regression-trackable values. Impl-independent by design — the flash
    backward's in-kernel score recompute is deliberately NOT counted,
    matching the model-FLOPs convention of the cost-analysis numerator
    used for the non-Pallas graph (bench.py)."""
    mm = 2.0 * b * h * sq * sk * d
    f = (6.0 if training else 2.0) * mm
    return f / 2 if causal else f


def self_attention(q, k, v, *, causal=False, scale=None, impl="auto",
                   bias=None, trainable_bias=False):
    """Dispatch: Pallas flash on TPU, jnp reference elsewhere/when asked.
    (The reference path always differentiates ``bias``;
    ``trainable_bias`` controls the flash kernels' dbias emission.)"""
    if impl == "auto":
        impl = "flash" if not _interpret() else "default"
    if impl == "flash":
        return flash_attention(q, k, v, causal, scale, bias=bias,
                               trainable_bias=trainable_bias)
    return attention_reference(q, k, v, causal=causal, scale=scale,
                               bias=bias)


# ---------------------------------------------------------------------------
# Decode attention (KV-cache inference) — fused step kernel
# ---------------------------------------------------------------------------
# History: archived in r4 as a negative result on isolated numbers
# (v5e, b=8 h=12 d=64 bf16, device time per call):
#   L=640:  einsum 24.9 us; fused (128, d) blocks 120.5 us (tiny DMAs
#           + 480 grid steps of overhead); whole-cache block 36.3 us.
#   L=4096: einsum 151 us; fused-as-wrapped 764 us — but that number
#           was the WRAPPER's d=64 -> 128 lane pad copying the 50 MB
#           cache every call, not the kernel.
# r5 re-opened it with three fixes: native-d blocks (no pad copy),
# divisor-only block choice (no row-pad copy), and dead-block DMA
# elision via scalar-prefetched index maps (dead grid steps clamp to
# the last live block; consecutive identical indices skip the fetch,
# so only the LIVE cache prefix moves from HBM). In-model (12-layer
# GPT-small decode scan, batch 8, device clock, BASELINE.md r5 decode
# section): L=4096 caches decode +22% (deep steps, device clock)
# to +54% (full generation, wall A/B) over the einsum path; short
# caches (<~2k rows, where the whole cache is one block and there is
# nothing to elide) stay marginally einsum-favored, so the module's
# 'auto' policy picks by cache length. The r4 "XLA scheduling" theory
# for the in-model gap was wrong — the fused kernel suffered the same
# in-model degradation; the recoverable cost was dead-row bandwidth.
# Parity coverage: tests/test_attention.py (padding fallback + divisor
# shapes) and tpu_kernel_check's decode cases on real hardware.

def _decode_attn_kernel(scale, bq, bl, nl, *refs):
    """Grid (bh, il): one small query block (the current decode step's
    ≤8 tokens, row-padded) against the full KV cache, blockwise online
    softmax in base 2. Validity comes from the scalar-prefetched
    ``index``: query row r may attend cache columns col <= index + r.
    Blocks entirely past index + bq - 1 skip their compute — AND their
    DMAs: the BlockSpec index maps clamp dead steps to the last live
    block, so consecutive same-index fetches are elided by the
    pipeline (r5; only the LIVE prefix of the cache moves from HBM,
    which is the whole bandwidth story of a step that does ~0 FLOPs)."""
    idx_ref, q_ref, k_ref, v_ref, o_ref, acc_scr, m_scr, l_scr = refs
    il = pl.program_id(1)
    idx = idx_ref[0]

    @pl.when(il == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    @pl.when(il * bl <= idx + bq - 1)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * (scale * LOG2E)   # (bq, d)
        k = k_ref[0].astype(jnp.float32)                     # (bl, d)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)              # (bq, bl)
        row = jax.lax.broadcasted_iota(jnp.int32, (bq, bl), 0)
        col = il * bl + jax.lax.broadcasted_iota(jnp.int32, (bq, bl), 1)
        s = jnp.where(col <= idx + row, s, NEG_INF)
        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp2(s - m_new)
        corr = jnp.exp2(m_prev - m_new)
        l_scr[:, :1] = corr * l_scr[:, :1] \
            + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_scr[:] = corr * acc_scr[:] + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(il == nl - 1)
    def _finalize():
        l = l_scr[:, :1]
        o_ref[0] = (acc_scr[:] / jnp.where(l == 0.0, 1.0, l)).astype(
            o_ref.dtype)


def decode_native_head_dim(d: int) -> bool:
    """True when decode_attention moves the caches WITHOUT a pad copy at
    this head dim (128-multiples, or a power-of-two minor dim Mosaic
    accepts as block minor == array minor). The module's fused-impl
    gating consults this — a non-native d (e.g. 96) must ride the
    einsum, or every step would re-pay the full-cache pad copy that
    produced the r4 negative verdict."""
    return d % 128 == 0 or d in (64, 32, 16, 8)


@_no_amp
def decode_attention(q, k_cache, v_cache, index, *,
                     scale: Optional[float] = None,
                     block_l: int = 1024):
    """Fused KV-cache attention for autoregressive decoding: one Pallas
    call computes score+softmax+context over both caches — no XLA
    scheduling boundary between the two reductions (the r4 trace showed
    the einsum pair running ~2.4x slower in-model than isolated; a
    single custom call is opaque to that scheduling). Archived as a
    negative result in r4 — but that verdict was poisoned by the
    wrapper's d=64→128 pad, which COPIED the whole cache every call
    (764 µs at L=4096). r5: the caches pass through at native d
    whenever Mosaic's block rules allow (last block dim equal to the
    array dim), so d=64 runs copy-free; see the r5 decode section of
    BASELINE.md for the re-measure.

    ``q``: (B, H, S_cur, D) — the current step's queries (S_cur ≤ 8:
    single-token decode or a small speculative chunk). ``k_cache`` /
    ``v_cache``: (B, H, L, D) with the step's tokens ALREADY written at
    rows ``index .. index + S_cur - 1``; ``index`` is the scalar int32
    start position (query row r attends cache cols ≤ index + r —
    identical semantics to the einsum path in
    ``SelfMultiheadAttn.decode``). Returns (B, H, S_cur, D)."""
    b, h, sc, d = q.shape
    if sc > 8:
        raise ValueError(
            f"decode_attention is the ≤8-token step kernel (got "
            f"S_cur={sc}); run prefill through flash_attention")
    L = k_cache.shape[2]
    scale = (1.0 / math.sqrt(d)) if scale is None else scale
    # native-d blocks when legal (d a lane multiple, or the whole array
    # minor dim — Mosaic accepts block minor == array minor): the r4
    # archived verdict paid a full-cache pad COPY here at d=64
    dp = d if decode_native_head_dim(d) else ((d + 127) // 128) * 128
    bq = 8
    # block must DIVIDE the cache length or _pad3 below copies both
    # caches every step (the exact cost the native-d fix removed on the
    # other axis): take the LARGEST 128-multiple divisor <= block_l —
    # big blocks matter doubly here (the archived r4 sweep measured
    # 120.5 us at (128, d) blocks vs 36.3 us whole-cache at L=640: tiny
    # DMAs + per-grid-step overhead). Only a non-128-multiple L
    # (callers should allocate rounded; the module does) falls back to
    # the padding path via _pick_block.
    if L % 128 == 0:
        start = max(128, min(block_l, L) // 128 * 128)
        bl = next(b for b in range(start, 127, -128) if L % b == 0)
    else:
        bl = _pick_block(block_l, L)
    lp = ((L + bl - 1) // bl) * bl
    nl = lp // bl

    qf = _pad3(q.reshape(b * h, sc, d), bq, dp)
    kf = _pad3(k_cache.reshape(b * h, L, d), lp, dp)
    vf = _pad3(v_cache.reshape(b * h, L, d), lp, dp)
    idx = jnp.asarray(index, jnp.int32).reshape((1,))

    def kv_index(bh, il, idx_ref):
        # dead blocks (entirely past the live prefix) clamp to the last
        # live block: consecutive identical indices elide the DMA
        last = jnp.minimum((idx_ref[0] + bq - 1) // bl, nl - 1)
        return (bh, jnp.minimum(il, last), 0)

    out = pl.pallas_call(
        functools.partial(_decode_attn_kernel, scale, bq, bl, nl),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b * h, nl),
            in_specs=[
                pl.BlockSpec((1, bq, dp), lambda bh, i, idx_ref:
                             (bh, 0, 0)),
                pl.BlockSpec((1, bl, dp), kv_index),
                pl.BlockSpec((1, bl, dp), kv_index),
            ],
            out_specs=pl.BlockSpec((1, bq, dp), lambda bh, i, idx_ref:
                                   (bh, 0, 0)),
            scratch_shapes=[pltpu.VMEM((bq, dp), jnp.float32),
                            pltpu.VMEM((bq, 128), jnp.float32),
                            pltpu.VMEM((bq, 128), jnp.float32)]),
        out_shape=jax.ShapeDtypeStruct((b * h, bq, dp), q.dtype),
        interpret=_interpret(),
    )(idx, qf, kf, vf)
    return out[:, :sc, :d].reshape(b, h, sc, d)


# ---------------------------------------------------------------------------
# Ring attention (sequence parallelism over a mesh axis)
# ---------------------------------------------------------------------------

def _merge_partials(o1, lse1, o2, lse2):
    """Numerically-stable merge of two partial attention results."""
    m = jnp.maximum(lse1, lse2)
    w1 = jnp.exp(lse1 - m)[..., None]
    w2 = jnp.exp(lse2 - m)[..., None]
    o = (o1.astype(jnp.float32) * w1 + o2.astype(jnp.float32) * w2) / \
        (w1 + w2)
    lse = m + jnp.log(w1[..., 0] + w2[..., 0])
    return o, lse


def _ring_perm(world):
    return [(j, (j + 1) % world) for j in range(world)]


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _psum_cotangent(x, axis_name):
    """Identity whose COTANGENT psums over ``axis_name``: wrapping a
    replicated operand makes its grad the full cross-device sum instead
    of the local contribution — the correct-by-default form for a
    ring-replicated learned bias (ADVICE r4: the local-grad convention
    is a silent-undertraining footgun since the non-ring flash path
    needs no psum). Works for any impl: the wrapper sits OUTSIDE the
    attention computation."""
    return x


def _psum_cot_fwd(x, axis_name):
    return x, None


def _psum_cot_bwd(axis_name, _res, g):
    return (jax.lax.psum(g, axis_name),)


_psum_cotangent.defvjp(_psum_cot_fwd, _psum_cot_bwd)


def _ring_mode(causal, src, rank):
    """0 = full chunk, 1 = causal diagonal chunk, 2 = skip (future)."""
    if causal:
        return jnp.where(src == rank, 1, jnp.where(src < rank, 0, 2))
    return jnp.zeros((), jnp.int32)


def _ring_bias_chunk(bias, src, s_loc):
    if bias is None:
        return None
    return jax.lax.dynamic_slice_in_dim(bias, src * s_loc, s_loc, axis=3)


def _ring_flash_fwd(q, k, v, bias, axis_name, causal, scale):
    """Ring forward over Pallas flash chunks: each arriving K/V chunk runs
    the flash kernel (O(S_loc·d) VMEM/HBM — the (S_loc, S_loc) score matrix
    never materializes), partials merge via stable lse arithmetic. Peak
    per-device memory is O(B·H·S_loc·D), the long-context point of ring
    attention, now without a dense inner step (VERDICT r1 weak #7)."""
    world = _axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    b, h, s_loc, _ = q.shape

    def chunk(kc, vc, mode, bias_c):
        def full(_):
            return _flash_fwd(q, kc, vc, causal=False, scale=scale,
                              bias=bias_c)

        def diag(_):
            return _flash_fwd(q, kc, vc, causal=True, scale=scale,
                              bias=bias_c)

        def skip(_):
            return (jnp.zeros_like(q),
                    jnp.full((b, h, s_loc), NEG_INF, jnp.float32))

        return jax.lax.switch(mode, [full, diag, skip], None)

    def body(i, carry):
        o, lse, kc, vc = carry
        src = (rank - i) % world
        o_i, lse_i = chunk(kc, vc, _ring_mode(causal, src, rank),
                           _ring_bias_chunk(bias, src, s_loc))
        o, lse = _merge_partials(o, lse, o_i, lse_i)
        perm = _ring_perm(world)
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        return (o, lse, kc, vc)

    o0 = jnp.zeros(q.shape, jnp.float32)
    lse0 = jnp.full((b, h, s_loc), NEG_INF, jnp.float32)
    o, lse, _, _ = jax.lax.fori_loop(0, world, body, (o0, lse0, k, v))
    return o.astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _ring_flash_core(q, k, v, bias, axis_name, causal, scale, has_bias,
                     bias_grad):
    o, _ = _ring_flash_fwd(q, k, v, bias if has_bias else None,
                           axis_name, causal, scale)
    return o


def _ring_flash_vjp_fwd(q, k, v, bias, axis_name, causal, scale, has_bias,
                        bias_grad):
    o, lse = _ring_flash_fwd(q, k, v, bias if has_bias else None,
                             axis_name, causal, scale)
    return o, (q, k, v, bias, o, lse)


def _ring_flash_vjp_bwd(axis_name, causal, scale, has_bias, bias_grad,
                        res, g):
    """Ring backward: a second ring pass with the GLOBAL lse (saved) and
    global delta (recomputed per chunk inside _flash_bwd from the global
    out/g rows), so per-chunk p = exp(s - lse_global) sums to the exact
    dense backward. dK/dV accumulators rotate WITH their K/V chunks, so
    after `world` steps each device holds the full gradient for its own
    chunk — one extra ppermute pair per step, still O(S_loc) memory."""
    q, k, v, bias, o, lse = res
    bias_arr = bias
    bias = bias if has_bias else None
    world = _axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    b, h, s_loc, _ = q.shape
    want_db = bias_grad and has_bias
    db_chunk_shape = None
    if want_db:
        bb, hb, sqb, _ = bias_arr.shape
        db_chunk_shape = (bb, hb, sqb, s_loc)

    def chunk_bwd(kc, vc, mode, bias_c):
        def grads(causal_c):
            out = _flash_bwd(q, kc, vc, o, lse, g, causal=causal_c,
                             scale=scale, bias=bias_c,
                             bias_grad=want_db)
            if want_db:
                dq_i, dk_i, dv_i, db_full = out
                # reduce the (b, h, s_loc, s_loc) score grad to this
                # chunk's bias column window at the bias's broadcast
                # shape (rows are this device's local queries)
                axes = tuple(i for i, bd in enumerate(db_chunk_shape)
                             if bd == 1 and db_full.shape[i] != 1)
                db_i = (jnp.sum(db_full, axis=axes, keepdims=True)
                        if axes else db_full)
                return dq_i, dk_i, dv_i, db_i
            return out

        def full(_):
            return grads(False)

        def diag(_):
            return grads(True)

        def skip(_):
            zero = (jnp.zeros_like(q), jnp.zeros_like(kc),
                    jnp.zeros_like(vc))
            if want_db:
                return zero + (jnp.zeros(db_chunk_shape, jnp.float32),)
            return zero

        return jax.lax.switch(mode, [full, diag, skip], None)

    def body(i, carry):
        dq, kc, vc, dkc, dvc, dbb = carry
        src = (rank - i) % world
        out_i = chunk_bwd(
            kc, vc, _ring_mode(causal, src, rank),
            _ring_bias_chunk(bias, src, s_loc))
        dq_i, dk_i, dv_i = out_i[:3]
        dq = dq + dq_i.astype(jnp.float32)
        dkc = dkc + dk_i.astype(jnp.float32)
        dvc = dvc + dv_i.astype(jnp.float32)
        if want_db:
            # each source chunk's column window is visited exactly once
            dbb = jax.lax.dynamic_update_slice_in_dim(
                dbb, out_i[3], src * s_loc, axis=3)
        perm = _ring_perm(world)
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        dkc = jax.lax.ppermute(dkc, axis_name, perm)
        dvc = jax.lax.ppermute(dvc, axis_name, perm)
        return (dq, kc, vc, dkc, dvc, dbb)

    dq0 = jnp.zeros(q.shape, jnp.float32)
    dk0 = jnp.zeros(k.shape, jnp.float32)
    dv0 = jnp.zeros(v.shape, jnp.float32)
    db0 = (jnp.zeros(bias_arr.shape, jnp.float32) if want_db
           else jnp.zeros((1,), jnp.float32))
    dq, _, _, dk, dv, dbb = jax.lax.fori_loop(
        0, world, body, (dq0, k, v, dk0, dv0, db0))
    if want_db:
        # LOCAL contribution (this device's query rows): the public
        # wrapper's replicated_bias option layers the psum on top via
        # _psum_cotangent — this core always stays local
        dbias = dbb.astype(bias_arr.dtype)
    else:
        dbias = (jnp.zeros_like(bias_arr) if has_bias
                 else jnp.zeros((1, 1, 1, 1), jnp.float32))
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            dbias)


_ring_flash_core.defvjp(_ring_flash_vjp_fwd, _ring_flash_vjp_bwd)


def ring_self_attention(q, k, v, axis_name: str, *, causal: bool = False,
                        scale: Optional[float] = None, bias=None,
                        impl: str = "auto", trainable_bias: bool = False,
                        replicated_bias: bool = False):
    """Ring attention: each device holds a sequence shard (B, H, S_local, D);
    K/V shards rotate around the ring via ``lax.ppermute`` while each device
    accumulates its queries' attention over every K/V chunk with blockwise
    stable softmax merging.

    Communication pattern: world-1 ppermute steps over ICI neighbors —
    the sequence-parallel analog of the reference's NCCL ring allreduce,
    except the payload is K/V activations (long-context scaling).

    Causal masking uses global positions: query block ``r`` attends to key
    block ``src`` fully when src < r, diagonally when src == r, not at all
    when src > r.

    ``bias`` is a per-device additive score bias with GLOBAL key columns:
    shape broadcastable to (B, H, S_local, S_global) — e.g. a replicated
    key-padding mask (B, 1, 1, S_global). Each ring step slices the
    arriving chunk's column window. By default the bias is a CONSTANT
    (stop_gradient) on the flash path; ``trainable_bias=True`` makes it
    learned — each ring step's flash backward also emits that chunk's
    score grad, written into the bias's column window (every window is
    visited exactly once). The returned dbias is this device's LOCAL
    contribution (its query rows); for a bias REPLICATED across the
    ring, either pass ``replicated_bias=True`` (the backward psums the
    grad over ``axis_name`` in-place — correct by default for the
    common replicated-param case) or ``psum`` the grad yourself (the
    same contract as every replicated-param grad in this framework; see
    docs/source/advanced.rst "Attention masks vs learned biases").

    ``impl='flash'`` composes the Pallas flash kernels into the ring (each
    chunk runs blockwise, O(S_loc·d) memory, with a global-lse ring
    backward); ``'default'`` runs the dense jnp chunk path; ``'auto'``
    picks flash on TPU.
    """
    world = _axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape
    scale_ = (1.0 / math.sqrt(d)) if scale is None else scale

    if bias is not None:
        bias = jnp.asarray(bias)
        if bias.ndim != 4 or bias.shape[3] != world * s_loc:
            raise ValueError(
                "ring attention bias must be rank-4 (B, H|1, S_local|1, "
                f"S_global={world * s_loc}); got shape "
                f"{getattr(bias, 'shape', None)}")
        if replicated_bias and trainable_bias:
            bias = _psum_cotangent(bias, axis_name)

    if impl == "auto":
        impl = "flash" if not _interpret() else "default"
    if impl == "flash":
        has_bias = bias is not None
        bias_grad = bool(trainable_bias) and has_bias
        if has_bias:
            bias_arr = bias if bias_grad else jax.lax.stop_gradient(bias)
        else:
            bias_arr = jnp.zeros((1, 1, 1, 1), jnp.float32)
        if q.dtype == jnp.float16 and not _interpret():
            # Mosaic has no f16 — bf16 reroute, see flash_attention
            # (hardware-fixed target dtype, not a policy bypass)
            o = _ring_flash_core(
                q.astype(jnp.bfloat16),  # apexlint: disable=APX005 -- Mosaic f16 shim
                k.astype(jnp.bfloat16),  # apexlint: disable=APX005 -- Mosaic f16 shim
                v.astype(jnp.bfloat16),  # apexlint: disable=APX005 -- Mosaic f16 shim
                bias_arr, axis_name, causal,
                scale_, has_bias, bias_grad)
            return o.astype(jnp.float16)  # apexlint: disable=APX005 -- back to caller dtype
        return _ring_flash_core(q, k, v, bias_arr, axis_name, causal,
                                scale_, has_bias, bias_grad)

    def chunk_attn(q_, k_, v_, mode, bias_c):
        # mode: 0 = full, 1 = causal-diagonal, 2 = skip
        def full(_):
            return attention_reference(q_, k_, v_, scale=scale_,
                                       bias=bias_c, return_lse=True)

        def diag(_):
            return attention_reference(q_, k_, v_, causal=True,
                                       scale=scale_, bias=bias_c,
                                       return_lse=True)

        def skip(_):
            return (jnp.zeros_like(q_),
                    jnp.full((b, h, s_loc), NEG_INF, jnp.float32))

        return jax.lax.switch(mode, [full, diag, skip], None)

    def body(i, carry):
        o, lse, kc, vc = carry
        src = (rank - i) % world  # which shard we currently hold
        o_i, lse_i = chunk_attn(q, kc, vc,
                                _ring_mode(causal, src, rank),
                                _ring_bias_chunk(bias, src, s_loc))
        o, lse = _merge_partials(o, lse, o_i, lse_i)
        perm = _ring_perm(world)
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        return (o, lse, kc, vc)

    o0 = jnp.zeros((b, h, s_loc, d), jnp.float32)
    lse0 = jnp.full((b, h, s_loc), NEG_INF, jnp.float32)
    o, lse, _, _ = jax.lax.fori_loop(0, world, body, (o0, lse0, k, v))
    return o.astype(q.dtype)


# ---------------------------------------------------------------------------
# Ulysses attention (all-to-all sequence parallelism over a mesh axis)
# ---------------------------------------------------------------------------

def ulysses_self_attention(q, k, v, axis_name: str, *,
                           causal: bool = False,
                           scale: Optional[float] = None,
                           impl: str = "auto", bias=None,
                           trainable_bias: bool = False):
    """All-to-all (DeepSpeed-Ulysses-style) sequence parallelism: each
    device holds a sequence shard (B, H, S_local, D); one ``all_to_all``
    re-shards to (B, H/P, S_global, D) — heads scattered, sequence gathered
    — so every device runs ordinary *local* attention (the Pallas flash
    kernel) over the full sequence for its head subset, then a second
    ``all_to_all`` restores sequence sharding.

    Complementary to :func:`ring_self_attention`: Ulysses moves Q/K/V/O
    once each (4 all-to-alls per layer, O(B·S·D·H/P) bytes/device) and
    needs ``num_heads % axis_size == 0``; the ring moves K/V world-1 times
    but has no head-count constraint and overlaps transfers with compute.
    On an ICI mesh axis the all-to-all is a single XLA collective.

    Shapes (per device): (B, H, S_local, D) -> (B, H, S_local, D).
    """
    world = _axis_size(axis_name)
    h = q.shape[1]
    if h % world != 0:
        raise ValueError(
            f"ulysses needs num_heads ({h}) % axis_size ({world}) == 0 — "
            f"use ring_self_attention for unconstrained head counts")

    if bias is not None:
        # After the all-to-all each device holds the FULL sequence for a
        # head subset, so a usable bias must not vary over query rows the
        # device doesn't have: require q-dim 1 (key-padding / additive
        # column masks, shape (B|1, H|1, 1, S_global)). Per-head biases
        # are head-sliced to this device's subset.
        bias = jnp.asarray(bias)
        if bias.ndim != 4 or bias.shape[2] != 1:
            raise ValueError(
                "ulysses attention bias must be (B|1, H|1, 1, S_global) — "
                "a column (key-padding) mask; per-query-row biases would "
                f"need their own all-to-all. Got shape "
                f"{getattr(bias, 'shape', None)}")
        if bias.shape[1] not in (1, h):
            raise ValueError(
                f"ulysses bias heads dim must be 1 or {h}, got "
                f"{bias.shape[1]}")
        if bias.shape[1] == h:
            hp = h // world
            bias = jax.lax.dynamic_slice_in_dim(
                bias, jax.lax.axis_index(axis_name) * hp, hp, axis=1)

    # One stacked collective each way (3x fewer launches than per-tensor):
    # (3, B, H, S_loc, D) -> (3, B, H/P, S_glob, D): split heads, concat seq
    qg, kg, vg = jax.lax.all_to_all(
        jnp.stack([q, k, v]), axis_name, split_axis=2, concat_axis=3,
        tiled=True)
    # trainable_bias: the flash dbias flows back through the head slice's
    # autodiff transpose (dynamic_update_slice); a head-broadcast bias's
    # grad is this device's LOCAL (head-subset) contribution — psum over
    # the axis for a replicated bias, as with the ring
    o = self_attention(qg, kg, vg, causal=causal, scale=scale, impl=impl,
                       bias=bias, trainable_bias=trainable_bias)
    # (B, H/P, S_glob, D) -> (B, H, S_loc, D)
    return jax.lax.all_to_all(o, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)
