"""Pallas TPU kernels for BatchNorm channel statistics — the counterpart of
the reference's Welford kernels (csrc/welford.cu:268 ``welford_mean_var``,
:307 ``welford_mean_var_c_last``): one pass over the activation computing
BOTH first and second moments per channel, instead of the two (or three)
convert+reduce sweeps XLA emits for ``sum(x)`` / ``sum(x*x)`` separately.
BN-stat reductions are the dominant non-matmul cost of a ResNet train step
on TPU, so halving their HBM traffic is a direct step-time win.

Layout: channels-last input viewed as (rows, C) with rows = N*H*W. The TPU
grid is sequential, so per-channel fp32 accumulators live in VMEM scratch
across row blocks and are written out at the final block.

Gradients: d(sum)/dx = 1 and d(sum_sq)/dx = 2x are elementwise, so the
custom VJP needs no reduction kernel — XLA fuses the 2x multiply into the
surrounding backward elementwise chain.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops._amp_guard import no_amp as _no_amp

LANES = 128
VMEM_BUDGET = 4 * 1024 * 1024

# Opt-in gate for sync_moments: benchmarked on v5e, XLA's producer-fused
# convert+reduce wins inside a full train step (it fuses the stats read
# into the producing op's output, and autodiff of the jnp form keeps the
# backward fusable). Flip for workloads dominated by standalone stats
# passes over already-materialized activations.
FORCE_PALLAS = False


def _interpret() -> bool:
    return jax.default_backend() not in ("tpu", "axon")


def supported(c: int, rows: int = 0) -> bool:
    """Direct path for lane-multiple C; narrow C (64, 32, ...) folds row
    pairs into the lane dimension (channel c lands in lanes c, c+C, ... —
    summing the folds recovers per-channel moments), needing rows
    divisible by the fold factor."""
    if c % LANES == 0:
        return True
    if c <= LANES and LANES % c == 0:
        return rows % (LANES // c) == 0
    return False


def _rows_per_block(c: int) -> int:
    rows = max(8, min(2048, VMEM_BUDGET // (4 * c)))
    return (rows // 8) * 8


def _moments_kernel(nblocks, rows_actual, br, x_ref, s_ref, ss_ref,
                    acc_s, acc_ss):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        acc_s[:] = jnp.zeros_like(acc_s)
        acc_ss[:] = jnp.zeros_like(acc_ss)

    x = x_ref[:].astype(jnp.float32)            # (br, C)
    # zero the padding rows of the final block
    row = i * br + jax.lax.broadcasted_iota(jnp.int32, x.shape, 0)
    x = jnp.where(row < rows_actual, x, 0.0)
    acc_s[:] += jnp.sum(x, axis=0, keepdims=True)
    acc_ss[:] += jnp.sum(x * x, axis=0, keepdims=True)

    @pl.when(i == nblocks - 1)
    def _finalize():
        s_ref[:] = acc_s[:]
        ss_ref[:] = acc_ss[:]


@_no_amp
def _moments_2d(x2d: jax.Array, rows: Optional[int] = None,
                ) -> Tuple[jax.Array, jax.Array]:
    n, c = x2d.shape
    if c % LANES != 0:  # narrow-C fold (see supported())
        fold = LANES // c
        s, ss = _moments_2d(x2d.reshape(n // fold, c * fold), rows)
        return (s.reshape(fold, c).sum(0), ss.reshape(fold, c).sum(0))
    if rows is None:
        # tuner resolution (off policy: exactly _rows_per_block(c));
        # an explicit caller value always wins
        from apex_tpu import tune
        rows = tune.moments_rows(c=c, dtype=x2d.dtype)
    br = rows
    np_ = ((n + br - 1) // br) * br
    if np_ != n:
        x2d = jnp.pad(x2d, ((0, np_ - n), (0, 0)))
    nblocks = np_ // br

    s, ss = pl.pallas_call(
        functools.partial(_moments_kernel, nblocks, n, br),
        grid=(nblocks,),
        in_specs=[pl.BlockSpec((br, c), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((1, c), lambda i: (0, 0)),
                   pl.BlockSpec((1, c), lambda i: (0, 0))],
        out_shape=[jax.ShapeDtypeStruct((1, c), jnp.float32),
                   jax.ShapeDtypeStruct((1, c), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((1, c), jnp.float32),
                        pltpu.VMEM((1, c), jnp.float32)],
        interpret=_interpret(),
    )(x2d)
    return s[0], ss[0]


@jax.custom_vjp
@_no_amp
def fused_sum_sumsq(x2d: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """One-pass per-channel (sum, sum_sq) over a (rows, C) array, fp32
    accumulation regardless of input dtype. C must be a lane multiple
    (use :func:`supported`); callers fall back to jnp otherwise."""
    return _moments_2d(x2d)


def _fwd(x2d):
    s, ss = _moments_2d(x2d)
    return (s, ss), x2d


def _bwd(x2d, g):
    ds, dss = g
    dx = (ds[None, :] + 2.0 * dss[None, :] * x2d.astype(jnp.float32))
    return (dx.astype(x2d.dtype),)


fused_sum_sumsq.defvjp(_fwd, _bwd)
