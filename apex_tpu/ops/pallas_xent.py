"""Pallas TPU fused softmax-cross-entropy kernels — the counterpart of the
reference ``xentropy_cuda`` extension (apex/contrib/csrc/xentropy/
xentropy_kernel.cu: one-pass fused logsumexp + picked-logit forward saving
``max_log_sum_exp``, and a backward that rebuilds the softmax from the saved
statistic without re-reducing).

Layout: logits viewed as (rows, K); the grid is (row_blocks, k_blocks) with
the K axis innermost, so each row block streams its vocabulary in VMEM-sized
chunks with an online (max, sum) update — the flash-attention logsumexp
recurrence applied to the loss head. One pass produces per-example losses
AND the saved lse; the backward emits ``(softmax - target) * g`` blockwise,
writing straight in the logits dtype so the full fp32 softmax is NEVER
materialized in HBM (at 128k rows x 32k vocab that array alone is ~17 GB).

Constraints: K must be a multiple of 128 (lane width); other widths fall
back to the jnp implementation in ``apex_tpu/contrib/xentropy.py`` (which is
also the default — the Pallas path is opt-in via
``APEX_TPU_XENT_BACKEND=pallas``, see contrib/xentropy.py).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops._amp_guard import no_amp as _no_amp

LANES = 128
VMEM_BUDGET = 4 * 1024 * 1024  # per live (rows, block_k) f32 working array


def _interpret() -> bool:
    return jax.default_backend() not in ("tpu", "axon")


def supported(k: int) -> bool:
    """The kernel path needs the vocab to be lane-aligned."""
    return k % LANES == 0


def _pick_block_k(k: int, pref: int) -> int:
    """Largest 128-multiple DIVISOR of ``k`` that is <= ``pref``. The K
    grid must tile the vocab exactly (no masking pass per block); 128
    always qualifies because callers guarantee ``supported(k)``."""
    pref = max(LANES, min(int(pref), k))
    for cand in range(pref - pref % LANES, LANES - 1, -LANES):
        if k % cand == 0:
            return cand
    return LANES


def _rows_per_block(bk: int, arrays: int = 1) -> int:
    """Row-block height for ``arrays`` live (rows, bk) f32 working arrays
    within the VMEM budget (same arithmetic as the layer-norm kernels)."""
    rows = max(8, min(1024, VMEM_BUDGET // (4 * bk * arrays)))
    return (rows // 8) * 8


def _clamp_rows(rows: int, n: int) -> int:
    """Never pad the row axis past the minimal 8-aligned length (a 127-row
    batch under a 1024-row preference would compute 8x dead rows)."""
    return max(8, min(rows, ((n + 7) // 8) * 8))


def _resolve(op: str, k: int, dtype, rows: Optional[int],
             block_k: Optional[int]) -> Tuple[int, int]:
    if rows is not None and block_k is not None:
        return int(rows), int(block_k)
    from apex_tpu import tune
    t_rows, t_bk = tune.xentropy_blocks(op, k=k, dtype=dtype)
    return (int(rows) if rows is not None else t_rows,
            int(block_k) if block_k is not None else t_bk)


# -- forward ----------------------------------------------------------------

def _xent_fwd_kernel(smoothing, kdim, x_ref, lab_ref, loss_ref, lse_ref,
                     m_ref, s_ref, pick_ref, ksum_ref):
    k = pl.program_id(1)
    nk = pl.num_programs(1)

    @pl.when(k == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        s_ref[:] = jnp.zeros_like(s_ref)
        pick_ref[:] = jnp.zeros_like(pick_ref)
        ksum_ref[:] = jnp.zeros_like(ksum_ref)

    x = x_ref[:].astype(jnp.float32)                    # (rows, bk)
    bm = jnp.max(x, axis=1, keepdims=True)
    m_new = jnp.maximum(m_ref[:], bm)
    # online logsumexp: rescale the running sum to the new max
    s_ref[:] = s_ref[:] * jnp.exp(m_ref[:] - m_new) \
        + jnp.sum(jnp.exp(x - m_new), axis=1, keepdims=True)
    m_ref[:] = m_new
    cols = k * x.shape[1] + jax.lax.broadcasted_iota(
        jnp.int32, x.shape, 1)
    onehot = (cols == lab_ref[:]).astype(jnp.float32)
    pick_ref[:] += jnp.sum(x * onehot, axis=1, keepdims=True)
    if smoothing:                                       # static python float
        ksum_ref[:] += jnp.sum(x, axis=1, keepdims=True)

    @pl.when(k == nk - 1)
    def _fin():
        lse = jnp.log(s_ref[:]) + m_ref[:]
        loss = lse - (1.0 - smoothing) * pick_ref[:]
        if smoothing:
            loss = loss - smoothing * (ksum_ref[:] / kdim)
        loss_ref[:] = loss
        lse_ref[:] = lse


@_no_amp
def xent_fwd(logits2d: jax.Array, labels: jax.Array, smoothing: float = 0.0,
             *, rows: Optional[int] = None, block_k: Optional[int] = None,
             ) -> Tuple[jax.Array, jax.Array]:
    """One-pass fused loss forward on (n, K) logits + (n,) int labels.

    Returns ``(losses, lse)``, both fp32 (n,) — the ``max_log_sum_exp``
    save contract of the reference kernel. ``rows``/``block_k`` resolve
    through ``apex_tpu.tune`` when None (explicit values win).
    """
    n, k = logits2d.shape
    if not supported(k):
        raise ValueError(f"fused xentropy needs K % {LANES} == 0, got {k}")
    rows, block_k = _resolve("xentropy_fwd", k, logits2d.dtype,
                             rows, block_k)
    bk = _pick_block_k(k, block_k)
    rows = _clamp_rows(rows, n)
    padded = ((n + rows - 1) // rows) * rows
    lab2 = labels.astype(jnp.int32).reshape(n, 1)
    if padded != n:
        # at most rows-1 dead rows, but jnp.pad copies the operand —
        # Mosaic reads past the array end are undefined, so the pad is
        # the safe route (ln_fwd precedent); row-aligned workloads (or a
        # tune-picked `rows` dividing n) skip it entirely
        logits2d = jnp.pad(logits2d, ((0, padded - n), (0, 0)))
        lab2 = jnp.pad(lab2, ((0, padded - n), (0, 0)))
    grid = (padded // rows, k // bk)
    with jax.named_scope("apex_xentropy"):
        losses, lse = pl.pallas_call(
            functools.partial(_xent_fwd_kernel, float(smoothing), float(k)),
            grid=grid,
            in_specs=[
                pl.BlockSpec((rows, bk), lambda i, j: (i, j)),
                pl.BlockSpec((rows, 1), lambda i, j: (i, 0)),
            ],
            out_specs=[
                pl.BlockSpec((rows, 1), lambda i, j: (i, 0)),
                pl.BlockSpec((rows, 1), lambda i, j: (i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((padded, 1), jnp.float32),
                jax.ShapeDtypeStruct((padded, 1), jnp.float32),
            ],
            scratch_shapes=[pltpu.VMEM((rows, 1), jnp.float32)
                            for _ in range(4)],
            interpret=_interpret(),
        )(logits2d, lab2)
    return losses[:n, 0], lse[:n, 0]


# -- backward ---------------------------------------------------------------

def _xent_bwd_kernel(smoothing, inv_k, x_ref, lab_ref, lse_ref, g_ref,
                     dx_ref):
    k = pl.program_id(1)
    x = x_ref[:].astype(jnp.float32)
    # softmax rebuilt from the saved max_log_sum_exp — no re-reduction
    probs = jnp.exp(x - lse_ref[:])
    cols = k * x.shape[1] + jax.lax.broadcasted_iota(
        jnp.int32, x.shape, 1)
    onehot = (cols == lab_ref[:]).astype(jnp.float32)
    grad = probs - (1.0 - smoothing) * onehot
    if smoothing:
        grad = grad - smoothing * inv_k
    dx_ref[:] = (grad * g_ref[:]).astype(dx_ref.dtype)


@_no_amp
def xent_bwd(logits2d: jax.Array, labels: jax.Array, lse: jax.Array,
             g: jax.Array, smoothing: float = 0.0, *,
             rows: Optional[int] = None, block_k: Optional[int] = None,
             ) -> jax.Array:
    """Blockwise ``(softmax - target) * g`` from the saved ``lse``.

    ``g`` is the per-example loss cotangent (n,). The gradient is written
    directly in the logits dtype, block by block — the fp32 softmax never
    exists as a whole array.
    """
    n, k = logits2d.shape
    if not supported(k):
        raise ValueError(f"fused xentropy needs K % {LANES} == 0, got {k}")
    rows, block_k = _resolve("xentropy_bwd", k, logits2d.dtype,
                             rows, block_k)
    bk = _pick_block_k(k, block_k)
    rows = _clamp_rows(rows, n)
    padded = ((n + rows - 1) // rows) * rows
    lab2 = labels.astype(jnp.int32).reshape(n, 1)
    lse2 = lse.astype(jnp.float32).reshape(n, 1)
    g2 = g.astype(jnp.float32).reshape(n, 1)
    if padded != n:
        logits2d = jnp.pad(logits2d, ((0, padded - n), (0, 0)))
        lab2 = jnp.pad(lab2, ((0, padded - n), (0, 0)))
        lse2 = jnp.pad(lse2, ((0, padded - n), (0, 0)))
        g2 = jnp.pad(g2, ((0, padded - n), (0, 0)))   # zero g: zero dx rows
    grid = (padded // rows, k // bk)
    with jax.named_scope("apex_xentropy"):
        dx = pl.pallas_call(
            functools.partial(_xent_bwd_kernel, float(smoothing), 1.0 / k),
            grid=grid,
            in_specs=[
                pl.BlockSpec((rows, bk), lambda i, j: (i, j)),
                pl.BlockSpec((rows, 1), lambda i, j: (i, 0)),
                pl.BlockSpec((rows, 1), lambda i, j: (i, 0)),
                pl.BlockSpec((rows, 1), lambda i, j: (i, 0)),
            ],
            out_specs=pl.BlockSpec((rows, bk), lambda i, j: (i, j)),
            out_shape=jax.ShapeDtypeStruct((padded, k), logits2d.dtype),
            interpret=_interpret(),
        )(logits2d, lab2, lse2, g2)
    return dx[:n]
