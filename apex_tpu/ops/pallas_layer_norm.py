"""Pallas TPU LayerNorm kernels — the counterpart of the reference
``fused_layer_norm_cuda`` extension (csrc/layer_norm_cuda.cpp +
csrc/layer_norm_cuda_kernel.cu:285-528: Welford row stats, affine fwd, and the
two-stage backward producing dx plus dgamma/dbeta cross-row reductions).

Layout: input viewed as (rows, D); one grid step processes a block of rows
with the full feature dim resident in VMEM. dgamma/dbeta accumulate across
the sequential TPU grid into a (1, D) fp32 output block.

Constraints: D must be a multiple of 128 (lane width) to take this path;
other shapes fall back to the jnp implementation in
apex_tpu/normalization/fused_layer_norm.py.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops._amp_guard import no_amp as _no_amp

LANES = 128
VMEM_BUDGET = 4 * 1024 * 1024  # per operand block


def _interpret() -> bool:
    return jax.default_backend() not in ("tpu", "axon")


def _rows_per_block(d: int, arrays: int = 1) -> int:
    """Row-block height for a VMEM budget of ``VMEM_BUDGET`` bytes per
    ``arrays`` live (rows, d) f32 working arrays. The BACKWARD passes
    ``arrays=2``: its kernel keeps ~6 live row-blocks (x, dy, xhat, wdy,
    dx + casts) vs the forward's ~2, and at d=768 the shared 1024-row
    block blew the 16 MB scoped VMEM limit by 3.3 MB (r4, surfaced by a
    GPT-small 16k run)."""
    rows = max(8, min(1024, VMEM_BUDGET // (4 * d * arrays)))
    return (rows // 8) * 8


def supported(d: int) -> bool:
    return d % LANES == 0


# -- forward ----------------------------------------------------------------

def _ln_fwd_kernel(eps, x_ref, w_ref, b_ref, y_ref, mu_ref, rstd_ref):
    x = x_ref[:].astype(jnp.float32)
    mu = jnp.mean(x, axis=1, keepdims=True)
    xc = x - mu
    var = jnp.mean(xc * xc, axis=1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = xc * rstd
    w = w_ref[:].astype(jnp.float32)
    b = b_ref[:].astype(jnp.float32)
    y_ref[:] = (xhat * w + b).astype(y_ref.dtype)
    mu_ref[:] = mu
    rstd_ref[:] = rstd


@_no_amp
def ln_fwd(x2d: jax.Array, w: jax.Array, b: jax.Array, eps: float,
           rows: Optional[int] = None,
           ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    n, d = x2d.shape
    if rows is None:
        # tuner resolution (off policy: exactly _rows_per_block(d));
        # an explicit caller value always wins
        from apex_tpu import tune
        rows = tune.layer_norm_rows(d=d, dtype=x2d.dtype)
    padded = ((n + rows - 1) // rows) * rows
    if padded != n:
        x2d = jnp.pad(x2d, ((0, padded - n), (0, 0)))
    grid = padded // rows
    y, mu, rstd = pl.pallas_call(
        functools.partial(_ln_fwd_kernel, eps),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((rows, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((rows, d), lambda i: (i, 0)),
            pl.BlockSpec((rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((rows, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((padded, d), x2d.dtype),
            jax.ShapeDtypeStruct((padded, 1), jnp.float32),
            jax.ShapeDtypeStruct((padded, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(x2d, w.reshape(1, d), b.reshape(1, d))
    return y[:n], mu[:n], rstd[:n]


# -- backward ---------------------------------------------------------------

def _ln_bwd_kernel(x_ref, w_ref, mu_ref, rstd_ref, dy_ref,
                   dx_ref, dw_ref, db_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        dw_ref[:] = jnp.zeros_like(dw_ref)
        db_ref[:] = jnp.zeros_like(db_ref)

    x = x_ref[:].astype(jnp.float32)
    dy = dy_ref[:].astype(jnp.float32)
    w = w_ref[:].astype(jnp.float32)
    mu = mu_ref[:]
    rstd = rstd_ref[:]
    xhat = (x - mu) * rstd
    wdy = dy * w
    c1 = jnp.mean(wdy, axis=1, keepdims=True)
    c2 = jnp.mean(wdy * xhat, axis=1, keepdims=True)
    dx_ref[:] = ((wdy - c1 - xhat * c2) * rstd).astype(dx_ref.dtype)
    dw_ref[:] += jnp.sum(dy * xhat, axis=0, keepdims=True)
    db_ref[:] += jnp.sum(dy, axis=0, keepdims=True)


@_no_amp
def ln_bwd(x2d, w, mu, rstd, dy2d, rows: Optional[int] = None):
    n, d = x2d.shape
    if rows is None:
        from apex_tpu import tune
        rows = tune.layer_norm_rows(d=d, dtype=x2d.dtype, bwd=True)
    padded = ((n + rows - 1) // rows) * rows
    if padded != n:
        x2d = jnp.pad(x2d, ((0, padded - n), (0, 0)))
        dy2d = jnp.pad(dy2d, ((0, padded - n), (0, 0)))
        mu = jnp.pad(mu, ((0, padded - n), (0, 0)))
        # rstd padding must be finite; zeros keep padded dx rows at 0
        rstd = jnp.pad(rstd, ((0, padded - n), (0, 0)))
    grid = padded // rows
    dx, dw, db = pl.pallas_call(
        _ln_bwd_kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((rows, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((rows, d), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((rows, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((padded, d), dy2d.dtype),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(x2d, w.reshape(1, d), mu, rstd, dy2d)
    return dx[:n], dw.reshape(-1), db.reshape(-1)
