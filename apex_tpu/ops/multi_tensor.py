"""Fused multi-tensor elementwise ops — the TPU-native counterpart of the
reference's ``amp_C`` extension (csrc/amp_C_frontend.cpp:115-136 and the
``csrc/multi_tensor_*`` kernels).

Three execution paths, selected by :func:`backend`
(``APEX_TPU_MT_BACKEND`` / :func:`set_backend` / the ``mt_apply`` tune
sweep under ``auto``):

  * **jnp path** (the default everywhere): pure ``jax.numpy`` tree maps.
    Under ``jit`` XLA fuses the whole-model elementwise update into a few
    fusions, which captures what multi_tensor_apply buys on CUDA (batching
    thousands of tiny kernels, csrc/multi_tensor_apply.cuh:12) *without* any
    marshalling.
  * **flat path** (``APEX_TPU_MT_BACKEND=flat``): the whole tree packs into
    ONE flat bucket per dtype group (ops/buckets.py) and the update applies
    as O(1) fused jnp ops over the flat buffers — multi-tensor BATCHING
    without hand-written kernels, collapsing a 593-leaf step's per-leaf op
    soup into a handful of big fusions. Covers the hot ops (scale, adam,
    sgd); the rest degrade to jnp.
  * **Pallas path** (``APEX_TPU_MT_BACKEND=pallas``): the same buckets fed
    to a single Pallas kernel per bucket, mirroring the reference's chunked
    launches (csrc/multi_tensor_apply.cuh:41-142).

The default is **jnp on TPU too**, by measurement: on a v5e chip over a
ResNet-50-sized tree, XLA's fusion beats the Pallas bucket kernels on every
one of the eight ops — 3-13x with per-step tree<->bucket marshalling, and
still 1.4-1.9x in the Pallas kernels' best case, persistent-bucket state
with zero marshalling (r3, ``optimizers.BucketedOptimizer``; full table in
BASELINE.md). The Pallas mt layer is therefore an ARCHIVED
documented-negative-result: complete, parity-tested
(tests/test_multi_tensor.py, benchmarks/tpu_kernel_check.py), selectable
via ``APEX_TPU_MT_BACKEND=pallas``, and in no shipped default path. The
CUDA reference needs hand-written multi-tensor kernels because eager torch
launches one kernel per tensor; XLA's whole-graph fusion is the TPU-native
answer to the same problem.

Overflow contract: the reference kernels set a device-side ``noop_flag`` when
they see inf/nan (e.g. ScaleFunctor, csrc/multi_tensor_scale_kernel.cu:30).
Being functional, these ops instead *return* a boolean ``overflow`` scalar that
stays on device; callers thread it into ``lax.cond``-guarded updates
(amp/scaler.py) so no host sync is ever required — an improvement over the
per-step D2H ``.item()`` at apex/amp/scaler.py:209.
"""

from __future__ import annotations

import functools
import os
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.ops import buckets as _buckets

Tree = Any


# ---------------------------------------------------------------------------
# Dispatch control
# ---------------------------------------------------------------------------

# auto | jnp | flat | pallas. "flat" is the multi-tensor BATCHING path:
# the whole tree flattens into one bucket per dtype group and the update
# applies as O(1) fused jnp ops over the flat buffers (instead of one
# fused op per leaf) — the marshalling of the Pallas path without its
# kernels. "auto" resolves through apex_tpu.tune's mt_apply sweep (off
# policy: "jnp", the measured default).
_FORCE = os.environ.get("APEX_TPU_MT_BACKEND", "auto")
_BACKEND_NAMES = ("jnp", "flat", "pallas")
_OVERRIDE: Optional[str] = None

# Backends whose devices are TPU chips. "axon" is a PJRT tunnel to a TPU.
_TPU_BACKENDS = ("tpu", "axon")


def on_tpu() -> bool:
    return jax.default_backend() in _TPU_BACKENDS


def set_backend(name: Optional[str] = None) -> Optional[str]:
    """Process-level backend override (None restores the env/default).
    Returns the previous override so callers can save/restore — the
    mt_apply sweep runner and the lint entries trace under it."""
    global _OVERRIDE
    if name is not None and name not in _BACKEND_NAMES:
        raise ValueError(f"mt backend must be one of {_BACKEND_NAMES}, "
                         f"got {name!r}")
    prev = _OVERRIDE
    _OVERRIDE = name
    return prev


def backend(*trees: Tree) -> str:
    """The execution backend for a multi-tensor op over ``trees``:
    ``set_backend`` override, else ``APEX_TPU_MT_BACKEND``, else (auto)
    the ``mt_apply`` tune resolution — which under the default ``off``
    policy returns the frozen ``"jnp"`` (measured: XLA fusion wins on
    TPU — see module docstring), keeping default programs bit-identical.

    fp16 demotes ``pallas`` to ``jnp``: Mosaic (the Pallas TPU compiler)
    has no f16 type, while plain XLA handles f16 storage fine.
    """
    b = _OVERRIDE if _OVERRIDE is not None else _FORCE
    if b not in _BACKEND_NAMES:
        if b not in ("auto", ""):
            # loud-failure doctrine: a typo'd env value must not
            # silently measure-under-auto or quietly skip the kernels
            raise ValueError(
                f"APEX_TPU_MT_BACKEND={b!r} — expected one of "
                f"{_BACKEND_NAMES} or 'auto'")
        from apex_tpu import tune
        leaves = [l for t in trees for l in jax.tree_util.tree_leaves(t)]
        total = sum(int(l.size) for l in leaves) or 1
        dtype = leaves[0].dtype if leaves else jnp.float32
        b = tune.mt_apply_backend(n=total, dtype=dtype)
    if b == "pallas":
        for t in trees:
            for l in jax.tree_util.tree_leaves(t):
                if l.dtype == jnp.float16:
                    return "jnp"
    return b


def use_pallas(*trees: Tree) -> bool:
    """True when the fused Pallas bucket kernels should be used for
    ``trees`` (see :func:`backend`)."""
    return backend(*trees) == "pallas"


def _flat_map(trees, fn, out_spec_idx):
    """Whole-tree flat-buffer application: pack each tree's leaves into
    ONE flat bucket per dtype-signature group (the ops/pallas_mt
    marshalling), apply ``fn`` to the flat operands — a single fused
    elementwise update per group instead of one per leaf — and unflatten.
    ``out_spec_idx[o]`` names the input tree whose layout unflattens
    output ``o``."""
    from apex_tpu.ops import pallas_mt

    def runner(flats, specs, idxs):
        out = fn(*flats)
        return out if isinstance(out, tuple) else (out,)

    return pallas_mt._run_grouped(trees, runner, out_spec_idx)


def _nonfinite(x: jax.Array) -> jax.Array:
    """Any-nonfinite reduction in fp32 (bool scalar on device)."""
    if not jnp.issubdtype(x.dtype, jnp.floating):
        return jnp.asarray(False)
    return jnp.logical_not(jnp.all(jnp.isfinite(x.astype(jnp.float32))))


def _tree_overflow(tree: Tree) -> jax.Array:
    leaves = jax.tree_util.tree_leaves(tree)
    flags = [_nonfinite(l) for l in leaves]
    return functools.reduce(jnp.logical_or, flags, jnp.asarray(False))


# ---------------------------------------------------------------------------
# Tree-level public ops (the multi_tensor_applier surface,
# apex/multi_tensor_apply/multi_tensor_apply.py:3-30)
# ---------------------------------------------------------------------------

def multi_tensor_scale(tree: Tree, scale: jax.Array) -> Tuple[Tree, jax.Array]:
    """out = in * scale, with nonfinite detection on the inputs.

    Analog of ``amp_C.multi_tensor_scale`` (csrc/multi_tensor_scale_kernel.cu:30);
    this is the grad-unscale primitive used by the amp loss scaler
    (apex/amp/scaler.py:103-128).
    Returns ``(scaled_tree, overflow)``.
    """
    b = backend(tree)
    if b == "pallas":
        from apex_tpu.ops import pallas_mt
        return pallas_mt.scale_tree(tree, scale)
    if b == "flat":
        return _scale_tree_flat(tree, scale)
    overflow = _tree_overflow(tree)
    out = jax.tree_util.tree_map(
        lambda x: (x.astype(jnp.float32) * scale).astype(x.dtype), tree)
    return out, overflow


def _scale_tree_flat(tree: Tree, scale) -> Tuple[Tree, jax.Array]:
    """Flat-bucket scale + nonfinite detect: ONE fused multiply and ONE
    isfinite reduction per dtype group, whatever the leaf count."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    groups = _buckets.group_by_dtype(leaves)
    out_leaves = [None] * len(leaves)
    overflow = jnp.asarray(False)
    with jax.named_scope("apex_mt_apply"):
        for _, idxs in groups.items():
            flat, spec = _buckets.flatten_tensors([leaves[i] for i in idxs])
            overflow = jnp.logical_or(overflow, _nonfinite(flat))
            y = (flat.astype(jnp.float32) * scale).astype(flat.dtype)
            for i, t in zip(idxs, _buckets.unflatten_tensors(y, spec)):
                out_leaves[i] = t
    return jax.tree_util.tree_unflatten(treedef, out_leaves), overflow


def multi_tensor_axpby(a: jax.Array, x: Tree, b: jax.Array, y: Tree,
                       ) -> Tuple[Tree, jax.Array]:
    """out = a*x + b*y with nonfinite detection (csrc/multi_tensor_axpby_kernel.cu).

    Used for merging stashed and freshly-computed grads under grad accumulation
    (apex/amp/scaler.py:161-193 ``unscale_with_stashed``).
    """
    if use_pallas(x, y):
        from apex_tpu.ops import pallas_mt
        return pallas_mt.axpby_tree(a, x, b, y)
    overflow = jnp.logical_or(_tree_overflow(x), _tree_overflow(y))
    out = jax.tree_util.tree_map(
        lambda xe, ye: (a * xe.astype(jnp.float32)
                        + b * ye.astype(jnp.float32)).astype(ye.dtype), x, y)
    return out, overflow


def multi_tensor_l2norm(tree: Tree, per_tensor: bool = False,
                        ) -> Tuple[jax.Array, Optional[Tree]]:
    """Global (and optionally per-tensor) L2 norm of a pytree, computed in fp32.

    Analog of ``amp_C.multi_tensor_l2norm``
    (csrc/multi_tensor_l2norm_kernel.cu:28,197-280 — the two-stage cleanup
    reduction maps to XLA's reduction + a final psum-free scalar add tree).
    Returns ``(global_norm, per_tensor_norms_or_None)`` as fp32.
    """
    if use_pallas(tree):
        from apex_tpu.ops import pallas_mt
        if not per_tensor:
            return pallas_mt.l2norm_tree(tree), None
        return pallas_mt.l2norm_tree_per_tensor(tree)
    leaves = jax.tree_util.tree_leaves(tree)
    sq = [jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves]
    gnorm = jnp.sqrt(functools.reduce(jnp.add, sq, jnp.asarray(0.0, jnp.float32)))
    if not per_tensor:
        return gnorm, None
    norms = jax.tree_util.tree_map(
        lambda l: jnp.sqrt(jnp.sum(jnp.square(l.astype(jnp.float32)))), tree)
    return gnorm, norms


def multi_tensor_adam(
    grads: Tree, params: Tree, exp_avg: Tree, exp_avg_sq: Tree, *,
    lr: jax.Array, beta1: float, beta2: float, eps: float,
    step: jax.Array, adam_w_mode: bool = True, bias_correction: bool = True,
    weight_decay: float = 0.0, grad_scale: Optional[jax.Array] = None,
) -> Tuple[Tree, Tree, Tree]:
    """Fused Adam/AdamW step over a whole pytree.

    Math parity with ``amp_C.multi_tensor_adam`` (csrc/multi_tensor_adam.cu:171,
    signature csrc/amp_C_frontend.cpp:58-69): ``adam_w_mode`` selects decoupled
    weight decay (AdamW) vs L2-regularization-style decay folded into the grad.
    ``grad_scale`` optionally divides grads on the fly (fused unscale).
    Returns ``(new_params, new_exp_avg, new_exp_avg_sq)``.
    """
    step = jnp.asarray(step, jnp.float32)
    if bias_correction:
        bc1 = 1.0 - jnp.power(jnp.asarray(beta1, jnp.float32), step)
        bc2 = 1.0 - jnp.power(jnp.asarray(beta2, jnp.float32), step)
    else:
        bc1 = jnp.asarray(1.0, jnp.float32)
        bc2 = jnp.asarray(1.0, jnp.float32)
    inv_scale = (1.0 / grad_scale) if grad_scale is not None else None

    b = backend(grads, params)
    if b == "pallas":
        from apex_tpu.ops import pallas_mt
        return pallas_mt.adam_tree(
            grads, params, exp_avg, exp_avg_sq,
            lr=jnp.asarray(lr, jnp.float32), beta1=beta1, beta2=beta2, eps=eps,
            bc1=bc1, bc2=bc2, adam_w_mode=adam_w_mode,
            weight_decay=weight_decay, inv_scale=inv_scale)

    def upd(g, p, m, v):
        g32 = g.astype(jnp.float32)
        if inv_scale is not None:
            g32 = g32 * inv_scale
        p32 = p.astype(jnp.float32)
        if not adam_w_mode and weight_decay != 0.0:
            g32 = g32 + weight_decay * p32
        m32 = beta1 * m.astype(jnp.float32) + (1.0 - beta1) * g32
        v32 = beta2 * v.astype(jnp.float32) + (1.0 - beta2) * g32 * g32
        update = (m32 / bc1) / (jnp.sqrt(v32 / bc2) + eps)
        if adam_w_mode and weight_decay != 0.0:
            update = update + weight_decay * p32
        p32 = p32 - lr * update
        return p32.astype(p.dtype), m32.astype(m.dtype), v32.astype(v.dtype)

    if b == "flat":
        # the SAME elementwise update applied once per flat dtype-group
        # bucket — O(1) fused ops for the whole tree
        with jax.named_scope("apex_mt_apply"):
            return _flat_map([grads, params, exp_avg, exp_avg_sq], upd,
                             (1, 2, 3))

    out = jax.tree_util.tree_map(
        lambda g, p, m, v: upd(g, p, m, v), grads, params, exp_avg, exp_avg_sq)
    new_p = jax.tree_util.tree_map(lambda t: t[0], out,
                                   is_leaf=lambda t: isinstance(t, tuple))
    new_m = jax.tree_util.tree_map(lambda t: t[1], out,
                                   is_leaf=lambda t: isinstance(t, tuple))
    new_v = jax.tree_util.tree_map(lambda t: t[2], out,
                                   is_leaf=lambda t: isinstance(t, tuple))
    return new_p, new_m, new_v


def multi_tensor_sgd(
    grads: Tree, params: Tree, momentum_buf: Optional[Tree], *,
    lr: jax.Array, weight_decay: float = 0.0, momentum: float = 0.0,
    dampening: float = 0.0, nesterov: bool = False, first_run: bool = False,
    wd_after_momentum: bool = False, scale: float = 1.0,
    model_out_template: Optional[Tree] = None,
):
    """Fused SGD with momentum/nesterov/weight-decay over a pytree.

    Math parity with ``amp_C.multi_tensor_sgd``
    (csrc/multi_tensor_sgd_kernel.cu:320). ``first_run`` (Python bool or
    traced bool scalar) initializes the momentum buffer to the (decayed) grad
    like torch SGD's lazy init. ``model_out_template`` (a pytree giving
    per-leaf dtypes) requests a fused low-precision model-param copy — the
    reference kernel's 4-list [grads, master, momentum, fp16 model] variant
    used by amp FusedSGD with ``materialize_master_grads=False``.
    Returns ``(new_params, new_momentum_buf[, new_model_copy])``.
    """
    if momentum_buf is None:
        momentum_buf = jax.tree_util.tree_map(
            lambda g: jnp.zeros_like(g, dtype=jnp.float32), grads)

    b = backend(grads, params, momentum_buf)
    if b == "pallas":
        from apex_tpu.ops import pallas_mt
        return pallas_mt.sgd_tree(
            grads, params, momentum_buf, lr=lr, weight_decay=weight_decay,
            momentum=momentum, dampening=dampening, nesterov=nesterov,
            wd_after_momentum=wd_after_momentum, first=first_run, scale=scale,
            model_out_template=model_out_template)

    def upd(g, p, m):
        g32 = g.astype(jnp.float32) * scale
        p32 = p.astype(jnp.float32)
        if weight_decay != 0.0 and not wd_after_momentum:
            g32 = g32 + weight_decay * p32
        if momentum != 0.0:
            m_steady = momentum * m.astype(jnp.float32) \
                + (1.0 - dampening) * g32
            m32 = jnp.where(jnp.asarray(first_run), g32, m_steady)
            d = g32 + momentum * m32 if nesterov else m32
        else:
            m32 = m.astype(jnp.float32)
            d = g32
        if weight_decay != 0.0 and wd_after_momentum:
            d = d + weight_decay * p32
        p32 = p32 - lr * d
        return p32.astype(p.dtype), m32.astype(m.dtype)

    if b == "flat":
        with jax.named_scope("apex_mt_apply"):
            if model_out_template is not None:
                # fused low-precision model copy off the flat master
                # update (the reference kernel's 4-list variant)
                def upd4(g, p, m, t):
                    p2, m2 = upd(g, p, m)
                    return p2, m2, p2.astype(t.dtype)
                return _flat_map(
                    [grads, params, momentum_buf, model_out_template],
                    upd4, (1, 2, 3))
            return _flat_map([grads, params, momentum_buf], upd, (1, 2))

    out = jax.tree_util.tree_map(upd, grads, params, momentum_buf)
    new_p = jax.tree_util.tree_map(lambda t: t[0], out,
                                   is_leaf=lambda t: isinstance(t, tuple))
    new_m = jax.tree_util.tree_map(lambda t: t[1], out,
                                   is_leaf=lambda t: isinstance(t, tuple))
    if model_out_template is not None:
        new_model = jax.tree_util.tree_map(
            lambda p, t: p.astype(t.dtype), new_p, model_out_template)
        return new_p, new_m, new_model
    return new_p, new_m


def multi_tensor_check_overflow(tree: Tree) -> jax.Array:
    """Reduction-only nonfinite check over a pytree (no output write).

    The amp no-materialize FusedSGD path uses this in place of a full
    materializing unscale (apex/amp/_process_optimizer.py:258-310 skips master
    grad creation; the overflow check still runs via multi_tensor_scale's
    noop flag).
    """
    return _tree_overflow(tree)


def multi_tensor_adagrad(
    grads: Tree, params: Tree, state_sum: Tree, *,
    lr: jax.Array, epsilon: float = 1e-10, weight_decay: float = 0.0,
    adagrad_w_mode: bool = False, scale: float = 1.0,
) -> Tuple[Tree, Tree]:
    """Fused Adagrad step (csrc/multi_tensor_adagrad.cu; the ``adagrad_w_mode``
    decoupled-decay flag mirrors apex/optimizers/fused_adagrad.py:5).

    Returns ``(new_params, new_state_sum)``.
    """
    if use_pallas(grads, params, state_sum):
        from apex_tpu.ops import pallas_mt
        return pallas_mt.adagrad_tree(
            grads, params, state_sum, lr=lr, eps=epsilon,
            weight_decay=weight_decay, adagrad_w_mode=adagrad_w_mode,
            scale=scale)

    def upd(g, p, h):
        g32 = g.astype(jnp.float32) * scale
        p32 = p.astype(jnp.float32)
        if weight_decay != 0.0 and not adagrad_w_mode:
            g32 = g32 + weight_decay * p32
        h32 = h.astype(jnp.float32) + g32 * g32
        u = g32 / (jnp.sqrt(h32) + epsilon)
        if weight_decay != 0.0 and adagrad_w_mode:
            u = u + weight_decay * p32
        p32 = p32 - lr * u
        return p32.astype(p.dtype), h32.astype(h.dtype)

    out = jax.tree_util.tree_map(upd, grads, params, state_sum)
    new_p = jax.tree_util.tree_map(lambda t: t[0], out,
                                   is_leaf=lambda t: isinstance(t, tuple))
    new_h = jax.tree_util.tree_map(lambda t: t[1], out,
                                   is_leaf=lambda t: isinstance(t, tuple))
    return new_p, new_h


def multi_tensor_novograd(
    grads: Tree, params: Tree, exp_avg: Tree, v_per_tensor: Tree, *,
    lr: jax.Array, beta1: float, beta2: float, eps: float, step: jax.Array,
    weight_decay: float = 0.0, bias_correction: bool = True,
    grad_averaging: bool = True, norm_type: int = 2,
    init_zero: bool = False, first=None, scale: float = 1.0,
) -> Tuple[Tree, Tree, Tree]:
    """Fused NovoGrad step (csrc/multi_tensor_novograd.cu,
    signature csrc/amp_C_frontend.cpp:82-96).

    NovoGrad's second moment ``v`` is a *per-tensor scalar* tracking the grad
    norm, not an elementwise buffer. ``v_per_tensor`` is a pytree of scalars.
    ``first`` (bool or traced scalar; defaults to ``step == 1``) selects the
    v initialization: 0 when ``init_zero`` else the first grad-norm^2 — the
    reference's ``init_zero`` knob (apex/optimizers/fused_novograd.py).
    Returns ``(new_params, new_exp_avg, new_v)``.
    """
    step = jnp.asarray(step, jnp.float32)
    if first is None:
        first = step == 1
    if bias_correction:
        bc1 = 1.0 - jnp.power(jnp.asarray(beta1, jnp.float32), step)
        bc2 = 1.0 - jnp.power(jnp.asarray(beta2, jnp.float32), step)
    else:
        bc1 = jnp.asarray(1.0, jnp.float32)
        bc2 = jnp.asarray(1.0, jnp.float32)
    beta3 = (1.0 - beta1) if grad_averaging else 1.0

    if norm_type == 2 and use_pallas(grads, params, exp_avg):
        from apex_tpu.ops import pallas_mt
        return pallas_mt.novograd_tree(
            grads, params, exp_avg, v_per_tensor, lr=lr, beta1=beta1,
            beta2=beta2, beta3=beta3, eps=eps, bc1=bc1, bc2=bc2,
            weight_decay=weight_decay, init_zero=init_zero, first=first,
            scale=scale)

    def upd(g, p, m, v):
        g32 = g.astype(jnp.float32) * scale
        p32 = p.astype(jnp.float32)
        if norm_type == 2:
            gn_sq = jnp.sum(g32 * g32)
        else:
            gn_sq = jnp.max(jnp.abs(g32))
        v32 = jnp.where(jnp.asarray(first),
                        0.0 if init_zero else gn_sq,
                        beta2 * v.astype(jnp.float32) + (1.0 - beta2) * gn_sq)
        denom = jnp.sqrt(v32 / bc2) + eps
        gn = g32 / denom
        if weight_decay != 0.0:
            gn = gn + weight_decay * p32
        m32 = beta1 * m.astype(jnp.float32) + beta3 * gn
        p32 = p32 - lr * (m32 / bc1)
        return p32.astype(p.dtype), m32.astype(m.dtype), v32.astype(jnp.float32)

    out = jax.tree_util.tree_map(upd, grads, params, exp_avg, v_per_tensor)
    new_p = jax.tree_util.tree_map(lambda t: t[0], out,
                                   is_leaf=lambda t: isinstance(t, tuple))
    new_m = jax.tree_util.tree_map(lambda t: t[1], out,
                                   is_leaf=lambda t: isinstance(t, tuple))
    new_v = jax.tree_util.tree_map(lambda t: t[2], out,
                                   is_leaf=lambda t: isinstance(t, tuple))
    return new_p, new_m, new_v


def multi_tensor_lamb(
    grads: Tree, params: Tree, exp_avg: Tree, exp_avg_sq: Tree, *,
    lr: jax.Array, beta1: float, beta2: float, eps: float, step: jax.Array,
    bias_correction: bool = True, weight_decay: float = 0.0,
    grad_averaging: bool = True, adam_w_mode: bool = True,
    global_grad_norm: Optional[jax.Array] = None,
    max_grad_norm: float = 0.0, use_nvlamb: bool = False,
    scale: float = 1.0,
) -> Tuple[Tree, Tree, Tree]:
    """Fused one-shot LAMB step (csrc/multi_tensor_lamb.cu:413, signature
    csrc/amp_C_frontend.cpp:98-113): global grad-norm clip, Adam moments, then a
    per-tensor trust ratio ``|p| / |update|`` scaling the learning rate.

    ``use_nvlamb`` keeps the trust ratio even for zero-weight-decay tensors
    (NVLamb variant, apex/optimizers/fused_lamb.py docs). ``scale`` multiplies
    grads on the fly (fused amp unscale); a caller-supplied
    ``global_grad_norm`` must already refer to the scaled grads.
    Returns ``(new_params, new_exp_avg, new_exp_avg_sq)``.
    """
    step = jnp.asarray(step, jnp.float32)
    if bias_correction:
        bc1 = 1.0 - jnp.power(jnp.asarray(beta1, jnp.float32), step)
        bc2 = 1.0 - jnp.power(jnp.asarray(beta2, jnp.float32), step)
    else:
        bc1 = jnp.asarray(1.0, jnp.float32)
        bc2 = jnp.asarray(1.0, jnp.float32)
    beta3 = (1.0 - beta1) if grad_averaging else 1.0

    # Global grad-norm clipping (stage 1 of csrc/multi_tensor_lamb.cu).
    if global_grad_norm is None:
        gnorm_raw, _ = multi_tensor_l2norm(grads)
        global_grad_norm = gnorm_raw * scale
    if max_grad_norm > 0.0:
        clip = jnp.where(global_grad_norm > max_grad_norm,
                         global_grad_norm / max_grad_norm, 1.0)
    else:
        clip = jnp.asarray(1.0, jnp.float32)

    if use_pallas(grads, params, exp_avg, exp_avg_sq):
        from apex_tpu.ops import pallas_mt
        return pallas_mt.lamb_tree(
            grads, params, exp_avg, exp_avg_sq,
            lr=lr, beta1=beta1, beta2=beta2, beta3=beta3, eps=eps,
            bc1=bc1, bc2=bc2, adam_w_mode=adam_w_mode,
            weight_decay=weight_decay, inv_clip=scale / clip,
            use_ratio=(weight_decay != 0.0) or use_nvlamb)

    def upd(g, p, m, v):
        g32 = g.astype(jnp.float32) * scale / clip
        p32 = p.astype(jnp.float32)
        if not adam_w_mode and weight_decay != 0.0:
            g32 = g32 + weight_decay * p32
        m32 = beta1 * m.astype(jnp.float32) + beta3 * g32
        v32 = beta2 * v.astype(jnp.float32) + (1.0 - beta2) * g32 * g32
        update = (m32 / bc1) / (jnp.sqrt(v32 / bc2) + eps)
        if adam_w_mode and weight_decay != 0.0:
            update = update + weight_decay * p32
        # Per-tensor trust ratio (stage 2, csrc/multi_tensor_lamb.cu).
        p_norm = jnp.sqrt(jnp.sum(p32 * p32))
        u_norm = jnp.sqrt(jnp.sum(update * update))
        use_ratio = (weight_decay != 0.0) or use_nvlamb
        ratio = jnp.where(
            (p_norm > 0.0) & (u_norm > 0.0), p_norm / u_norm, 1.0
        ) if use_ratio else jnp.asarray(1.0, jnp.float32)
        p32 = p32 - lr * ratio * update
        return p32.astype(p.dtype), m32.astype(m.dtype), v32.astype(v.dtype)

    out = jax.tree_util.tree_map(upd, grads, params, exp_avg, exp_avg_sq)
    new_p = jax.tree_util.tree_map(lambda t: t[0], out,
                                   is_leaf=lambda t: isinstance(t, tuple))
    new_m = jax.tree_util.tree_map(lambda t: t[1], out,
                                   is_leaf=lambda t: isinstance(t, tuple))
    new_v = jax.tree_util.tree_map(lambda t: t[2], out,
                                   is_leaf=lambda t: isinstance(t, tuple))
    return new_p, new_m, new_v
