"""Bucket-staged cotangent transforms — the dataflow primitive under the
overlap engine (:mod:`apex_tpu.parallel.overlap`).

The reference Apex DDP overlaps gradient all-reduce with backward compute
by registering per-parameter backward *hooks* that fire as each grad is
produced (apex/parallel/distributed.py:320-557). JAX has no hooks — but it
has ``jax.custom_vjp``: wrapping a group of parameters in an identity
whose VJP applies a transform to the cotangents places that transform
*inside the backward graph*, at exactly the point where those parameters'
gradients are finalized. Split the parameters into buckets, give each
bucket its own identity-with-transform, and each bucket's collective
becomes an equation that depends only on *its* cotangents — bucket *k*'s
``psum`` can be issued while bucket *k+1*'s backward compute is still
running, which is the latency-hiding schedule XLA's scheduler needs to
see in the dataflow before it can exploit it.

This module is deliberately communication-agnostic: it knows nothing
about meshes or collectives, only "identity forward, transformed
cotangents backward". The overlap engine supplies reducers.
"""

from __future__ import annotations

from typing import Callable, Sequence, Tuple

import jax


def cotangent_transform(transform: Callable[[Tuple], Sequence],
                        ) -> Callable:
    """Build an identity function over ``*arrays`` whose backward maps the
    cotangent tuple through ``transform``.

    ``transform(cotangents: tuple) -> sequence`` must return one cotangent
    per primal operand, matching shapes and dtypes (custom_vjp enforces
    this at trace time). The forward saves no residuals, so the wrapper
    adds zero memory pressure to the backward.
    """

    @jax.custom_vjp
    def ident(*arrays):
        return arrays

    def fwd(*arrays):
        return arrays, None

    def bwd(_, cotangents):
        return tuple(transform(tuple(cotangents)))

    ident.defvjp(fwd, bwd)
    return ident


def apply_staged(leaves: Sequence, bucket_indices: Sequence[Sequence[int]],
                 make_transform: Callable[[int, int], Callable],
                 ) -> list:
    """Route ``leaves`` through one :func:`cotangent_transform` per bucket.

    ``bucket_indices``: leaf indices per bucket (e.g. from
    ``ops.buckets.assign_buckets``). ``make_transform(bucket_index,
    n_buckets)`` returns that bucket's cotangent transform. Returns the
    wrapped leaves in original order — an identity on values, with the
    backward staged per bucket.
    """
    out: list = list(leaves)
    n = len(bucket_indices)
    for bi, idxs in enumerate(bucket_indices):
        wrapped = cotangent_transform(make_transform(bi, n))(
            *[leaves[i] for i in idxs])
        for i, t in zip(idxs, wrapped):
            out[i] = t
    return out
