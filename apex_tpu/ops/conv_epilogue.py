"""Pallas TPU fused conv epilogue — BatchNorm scale/shift + ReLU (and the
residual add on block exits) folded into ONE pass over the conv output.

The reference fuses this chain on the CUDA side as ``apex.contrib.groupbn``
(bn_fwd_nhwc / bn_addrelu kernels over cudnn's BN workspace); on TPU the
analogous memory-bound chain is the separate normalize / relu / add HBM
passes trailing every conv. This kernel applies

    y = relu(x * scale + shift [+ residual])

with per-channel fp32 ``scale = gamma * rsqrt(var + eps)`` and
``shift = beta - mean * scale`` computed OUTSIDE the kernel in plain JAX
(they are O(C) vectors; autodiff through them carries the batch-stat
dependence on ``x``, so the custom_vjp below only owns the elementwise
apply — the math stays exactly BatchNorm's).

Layout: the (..., C) activation is viewed as (rows, C) when C is
lane-aligned, or — for narrow stems like C=64 — as (rows, 128) with the
channel vectors tiled ``128 // C`` times (the per-channel affine is
periodic in C, so a lane-tiled view is exact). The backward is one pass
too: dx and the optional residual cotangent stream out blockwise while
dscale/dshift accumulate across the sequential grid into (1, C) fp32
outputs (the dgamma/dbeta reduction shape of the layer-norm kernels).

Opt-in: ``models.ResNet*(fused_epilogue=True)`` /
``SyncBatchNorm(fused_epilogue=True)``; the default path is untouched
(jaxpr-equality pinned by tests/test_kernels.py).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from apex_tpu.ops._amp_guard import no_amp as _no_amp

LANES = 128
VMEM_BUDGET = 4 * 1024 * 1024  # per live (rows, d) f32 working array


def _interpret() -> bool:
    return jax.default_backend() not in ("tpu", "axon")


def supported(c: int, n_elems: int) -> bool:
    """True when the (rows, lanes) view exists: lane-aligned channels, or
    a channel count that tiles the 128-lane row exactly (stem C=64)."""
    if c % LANES == 0:
        return True
    return LANES % c == 0 and n_elems % LANES == 0


def _rows_per_block(d: int, arrays: int = 3) -> int:
    """Row-block height for ``arrays`` live (rows, d) f32 working arrays
    (x, y, residual) within the VMEM budget."""
    rows = max(8, min(1024, VMEM_BUDGET // (4 * d * arrays)))
    return (rows // 8) * 8


def _resolve_rows(d: int, dtype, rows: Optional[int]) -> int:
    if rows is not None:
        return int(rows)
    from apex_tpu import tune
    return tune.conv_epilogue_rows(c=d, dtype=dtype)


def _as2d(x: jax.Array, scale: jax.Array, shift: jax.Array):
    """(x2, scale2, shift2): the lane-aligned 2-D view plus matching
    (possibly lane-tiled) fp32 channel vectors."""
    c = x.shape[-1]
    if c % LANES == 0:
        d = c
        x2 = x.reshape(-1, d)
        s2 = scale.astype(jnp.float32)
        b2 = shift.astype(jnp.float32)
    else:
        rep = LANES // c
        d = LANES
        x2 = x.reshape(-1, d)
        s2 = jnp.tile(scale.astype(jnp.float32), rep)
        b2 = jnp.tile(shift.astype(jnp.float32), rep)
    return x2, s2, b2, d


# -- kernels ----------------------------------------------------------------

def _epi_fwd_kernel(relu, has_res, x_ref, s_ref, b_ref, *rest):
    if has_res:
        r_ref, y_ref = rest
    else:
        (y_ref,) = rest
    y = x_ref[:].astype(jnp.float32) * s_ref[:] + b_ref[:]
    if has_res:
        y = y + r_ref[:].astype(jnp.float32)
    if relu:
        y = jnp.maximum(y, 0.0)
    y_ref[:] = y.astype(y_ref.dtype)


def _epi_bwd_kernel(relu, has_res, g_ref, y_ref, x_ref, s_ref, *out_refs):
    if has_res:
        dx_ref, dr_ref, ds_ref, db_ref = out_refs
    else:
        dx_ref, ds_ref, db_ref = out_refs
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        ds_ref[:] = jnp.zeros_like(ds_ref)
        db_ref[:] = jnp.zeros_like(db_ref)

    g = g_ref[:].astype(jnp.float32)
    if relu:
        # the saved OUTPUT is the relu mask (y > 0 <=> pre-relu > 0)
        g = g * (y_ref[:] > 0).astype(jnp.float32)
    dx_ref[:] = (g * s_ref[:]).astype(dx_ref.dtype)
    if has_res:
        dr_ref[:] = g.astype(dr_ref.dtype)
    ds_ref[:] += jnp.sum(g * x_ref[:].astype(jnp.float32), axis=0,
                         keepdims=True)
    db_ref[:] += jnp.sum(g, axis=0, keepdims=True)


def _pad_rows(a: jax.Array, padded: int) -> jax.Array:
    n = a.shape[0]
    return a if padded == n else jnp.pad(a, ((0, padded - n), (0, 0)))


@_no_amp
def _epi_fwd_call(x2, s2, b2, r2, relu, rows, out_dtype):
    # Row padding (at most rows-1 dead rows, rows clamped to the minimal
    # 8-aligned length) is load-bearing for the BACKWARD's cross-row
    # dscale/dshift reductions — Mosaic reads past the array end are
    # undefined, so a partial last block could corrupt the accumulators.
    # The pad does copy the operand (the ln_fwd precedent); row blocks
    # are tune-picked, so pick `rows` dividing the workload to avoid it.
    n, d = x2.shape
    rows = max(8, min(rows, ((n + 7) // 8) * 8))
    padded = ((n + rows - 1) // rows) * rows
    has_res = r2 is not None
    operands = [_pad_rows(x2, padded), s2.reshape(1, d), b2.reshape(1, d)]
    if has_res:
        operands.append(_pad_rows(r2, padded))
    blk = lambda: pl.BlockSpec((rows, d), lambda i: (i, 0))
    vec = lambda: pl.BlockSpec((1, d), lambda i: (0, 0))
    y2 = pl.pallas_call(
        functools.partial(_epi_fwd_kernel, bool(relu), has_res),
        grid=(padded // rows,),
        in_specs=[blk(), vec(), vec()] + ([blk()] if has_res else []),
        out_specs=blk(),
        out_shape=jax.ShapeDtypeStruct((padded, d), out_dtype),
        interpret=_interpret(),
    )(*operands)
    return y2[:n]


@_no_amp
def _epi_bwd_call(g2, y2, x2, s2, res_dtype, relu, rows):
    n, d = x2.shape
    rows = max(8, min(rows, ((n + 7) // 8) * 8))
    padded = ((n + rows - 1) // rows) * rows
    has_res = res_dtype is not None
    blk = lambda dt: pl.BlockSpec((rows, d), lambda i: (i, 0))
    vec = lambda: pl.BlockSpec((1, d), lambda i: (0, 0))
    out_specs = [pl.BlockSpec((rows, d), lambda i: (i, 0))]
    out_shape = [jax.ShapeDtypeStruct((padded, d), x2.dtype)]
    if has_res:
        out_specs.append(pl.BlockSpec((rows, d), lambda i: (i, 0)))
        out_shape.append(jax.ShapeDtypeStruct((padded, d), res_dtype))
    out_specs += [vec(), vec()]
    out_shape += [jax.ShapeDtypeStruct((1, d), jnp.float32),
                  jax.ShapeDtypeStruct((1, d), jnp.float32)]
    outs = pl.pallas_call(
        functools.partial(_epi_bwd_kernel, bool(relu), has_res),
        grid=(padded // rows,),
        in_specs=[blk(None), blk(None), blk(None), vec()],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=_interpret(),
        # zero cotangent on the padded rows: their dx/accumulator
        # contribution vanishes
    )(_pad_rows(g2, padded), _pad_rows(y2, padded), _pad_rows(x2, padded),
      s2.reshape(1, d))
    if has_res:
        dx2, dr2, ds, db = outs
        return dx2[:n], dr2[:n], ds.reshape(-1), db.reshape(-1)
    dx2, ds, db = outs
    return dx2[:n], None, ds.reshape(-1), db.reshape(-1)


# -- custom_vjp over the 2-D apply ------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _apply2d(x2, s2, b2, relu, rows, out_dtype):
    return _epi_fwd_call(x2, s2, b2, None, relu, rows, out_dtype)


def _apply2d_fwd(x2, s2, b2, relu, rows, out_dtype):
    y2 = _epi_fwd_call(x2, s2, b2, None, relu, rows, out_dtype)
    return y2, (x2, s2, y2)


def _apply2d_bwd(relu, rows, out_dtype, res, g2):
    x2, s2, y2 = res
    dx2, _, ds, db = _epi_bwd_call(g2, y2, x2, s2, None, relu, rows)
    return dx2, ds, db


_apply2d.defvjp(_apply2d_fwd, _apply2d_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _apply2d_res(x2, s2, b2, r2, relu, rows, out_dtype):
    return _epi_fwd_call(x2, s2, b2, r2, relu, rows, out_dtype)


def _apply2d_res_fwd(x2, s2, b2, r2, relu, rows, out_dtype):
    y2 = _epi_fwd_call(x2, s2, b2, r2, relu, rows, out_dtype)
    # zero-size marker carries the residual DTYPE to the backward (a bare
    # dtype object is not a pytree leaf) — no residual data is saved
    return y2, (x2, s2, y2, jnp.zeros((0,), r2.dtype))


def _apply2d_res_bwd(relu, rows, out_dtype, res, g2):
    x2, s2, y2, r_marker = res
    dx2, dr2, ds, db = _epi_bwd_call(g2, y2, x2, s2, r_marker.dtype,
                                     relu, rows)
    return dx2, ds, db, dr2


_apply2d_res.defvjp(_apply2d_res_fwd, _apply2d_res_bwd)


# -- public entry -----------------------------------------------------------

def bn_relu_apply(x: jax.Array, scale: jax.Array, shift: jax.Array,
                  residual: Optional[jax.Array] = None, *,
                  relu: bool = True, out_dtype=None,
                  rows: Optional[int] = None) -> jax.Array:
    """``relu(x * scale + shift [+ residual])`` in one Pallas pass.

    ``x``: (..., C) conv output; ``scale``/``shift``: (C,) fp32 effective
    BatchNorm coefficients; ``residual``: same shape as ``x``. The fp32
    in-kernel result is written in ``out_dtype`` (default ``x.dtype``) —
    pass a wider dtype to keep the full normalize precision instead of
    rounding through the input dtype. ``rows`` resolves through
    ``apex_tpu.tune`` when None (explicit values win). Differentiable
    via a one-pass custom_vjp backward producing dx, d(residual), and
    the per-channel dscale/dshift reductions.
    """
    c = x.shape[-1]
    if not supported(c, x.size):
        raise ValueError(
            f"fused conv epilogue needs C % {LANES} == 0 or a row-tiling "
            f"channel count (128 % C == 0, lane-aligned total); got "
            f"C={c}, {x.size} elements")
    out_dtype = jnp.dtype(x.dtype if out_dtype is None else out_dtype)
    x2, s2, b2, d = _as2d(x, scale, shift)
    rows = _resolve_rows(d, x.dtype, rows)
    with jax.named_scope("apex_conv_epilogue"):
        if residual is None:
            y2 = _apply2d(x2, s2, b2, bool(relu), rows, out_dtype)
        else:
            y2 = _apply2d_res(x2, s2, b2, residual.reshape(x2.shape),
                              bool(relu), rows, out_dtype)
    return y2.reshape(x.shape)
