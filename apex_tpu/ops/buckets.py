"""Flat bucket management: the TPU-native analog of ``apex_C.flatten/unflatten``
(reference: csrc/flatten_unflatten.cpp:5-18) and of the dtype bucketing used by
the reference DDP (apex/parallel/distributed.py:51-58) and fused optimizers
(apex/optimizers/fused_adam.py:116-144).

A *bucket* is a single contiguous 1-D array holding many tensors of the same
dtype. Fused multi-tensor ops (Pallas kernels) run over buckets so that a whole
model's elementwise update is a handful of kernel launches instead of one per
parameter — the same motivation as csrc/multi_tensor_apply.cuh:12.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class BucketSpec:
    """Static (trace-time) description of how tensors pack into one flat bucket."""

    shapes: Tuple[Tuple[int, ...], ...]
    dtype: Any
    offsets: Tuple[int, ...]  # start offset of each tensor in the flat bucket
    sizes: Tuple[int, ...]
    total: int

    @property
    def num_tensors(self) -> int:
        return len(self.shapes)


def flatten_tensors(tensors: Sequence[jax.Array], align: int = 1,
                    ) -> Tuple[jax.Array, BucketSpec]:
    """Pack a list of same-dtype arrays into one contiguous 1-D bucket.

    Analog of ``apex_C.flatten`` (csrc/flatten_unflatten.cpp:5-10).

    ``align > 1`` starts every tensor at a multiple of ``align`` elements
    (zero-padded gaps). Segmented Pallas reductions (per-tensor norms, LAMB
    trust ratios) use lane-aligned buckets so each (sublane, lane) row belongs
    to exactly one tensor — the TPU layout counterpart of the reference's
    per-chunk ``tensor_loc`` bookkeeping (csrc/multi_tensor_apply.cuh:72-106).
    """
    if not tensors:
        raise ValueError("flatten_tensors: empty tensor list")
    dtype = tensors[0].dtype
    for t in tensors:
        if t.dtype != dtype:
            raise ValueError(
                f"flatten_tensors: mixed dtypes {t.dtype} vs {dtype}; "
                "group by dtype first (see group_by_dtype)"
            )
    shapes = tuple(tuple(t.shape) for t in tensors)
    sizes = tuple(int(np.prod(s)) if s else 1 for s in shapes)
    if align <= 1:
        offsets = tuple(int(x) for x in np.cumsum((0,) + sizes[:-1]))
        flat = jnp.concatenate([t.reshape(-1) for t in tensors])
        total = int(sum(sizes))
    else:
        offsets_l, parts, pos = [], [], 0
        for t, size in zip(tensors, sizes):
            start = ((pos + align - 1) // align) * align
            if start > pos:
                parts.append(jnp.zeros((start - pos,), dtype))
            offsets_l.append(start)
            parts.append(t.reshape(-1))
            pos = start + size
        offsets = tuple(offsets_l)
        flat = jnp.concatenate(parts)
        total = pos
    spec = BucketSpec(shapes=shapes, dtype=dtype, offsets=offsets, sizes=sizes,
                      total=total)
    return flat, spec


def unflatten_tensors(flat: jax.Array, spec: BucketSpec) -> List[jax.Array]:
    """Split a flat bucket back into the original tensor list.

    Analog of ``apex_C.unflatten`` (csrc/flatten_unflatten.cpp:12-18).
    """
    out = []
    for off, size, shape in zip(spec.offsets, spec.sizes, spec.shapes):
        out.append(jax.lax.dynamic_slice_in_dim(flat, off, size).reshape(shape))
    return out


def group_by_dtype(
    tensors: Sequence[jax.Array],
) -> Dict[str, List[int]]:
    """Return {canonical dtype name: indices} preserving order.

    Mirrors the dtype split in the reference fused optimizers
    (apex/optimizers/fused_adam.py:116-144: fp16 vs bf16 vs fp32 lists) and DDP
    bucketing (apex/parallel/distributed.py:51-58).
    """
    groups: Dict[str, List[int]] = {}
    for i, t in enumerate(tensors):
        groups.setdefault(jnp.dtype(t.dtype).name, []).append(i)
    return groups


def partition_by_capacity(sizes: Sequence[int], capacity: int,
                          ) -> List[List[int]]:
    """Greedy partition of positions 0..len(sizes)-1 into contiguous runs
    whose total size is at most ``capacity`` (<=0: one run). A single item
    larger than ``capacity`` forms its own run (items are never split).
    Shared by DDP bucketing (:func:`assign_buckets`) and the ZeRO bucket
    layout so the two comm paths keep identical boundary semantics."""
    runs: List[List[int]] = []
    cur: List[int] = []
    fill = 0
    for i, sz in enumerate(sizes):
        if cur and capacity > 0 and fill + sz > capacity:
            runs.append(cur)
            cur, fill = [], 0
        cur.append(i)
        fill += sz
    if cur:
        runs.append(cur)
    return runs


def assign_buckets(leaves: Sequence[jax.Array], capacity: int,
                   ) -> List[Tuple[str, Tuple[int, ...]]]:
    """Partition leaf indices into same-dtype buckets of at most ``capacity``
    elements, preserving leaf order within each dtype stream.

    This is the TPU analog of the reference DDP's ready-bucket scheme
    (apex/parallel/distributed.py:320-557): because each bucket is built from
    only ITS OWN leaves, a collective over the bucket depends on a subset of
    backward's outputs instead of all of them, and XLA's latency-hiding
    scheduler can overlap per-bucket collectives with the remaining backward
    compute. (The pre-r3 design concatenated the whole tree first — a
    dataflow barrier no scheduler can hide.)

    ``capacity <= 0`` means unbounded: one bucket per dtype. A single leaf
    larger than ``capacity`` forms its own bucket (leaves are never split
    across buckets, matching the reference's per-param bucket assignment).
    Returns ``[(dtype_name, leaf_indices), ...]``.
    """
    streams: Dict[str, List[int]] = {}
    for i, t in enumerate(leaves):
        streams.setdefault(jnp.dtype(t.dtype).name, []).append(i)
    out: List[Tuple[str, Tuple[int, ...]]] = []
    for name, idxs in streams.items():
        sizes = [int(np.prod(leaves[i].shape)) if leaves[i].shape else 1
                 for i in idxs]
        for run in partition_by_capacity(sizes, capacity):
            out.append((name, tuple(idxs[j] for j in run)))
    return out


# ---------------------------------------------------------------------------
# Pytree-level helpers (the JAX-idiomatic surface used by optimizers/DDP)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class TreeBucketSpec:
    """Static description of a pytree packed into per-dtype buckets."""

    treedef: Any
    leaf_dtypes: Tuple[str, ...]
    group_order: Tuple[str, ...]           # dtype name per bucket
    group_indices: Tuple[Tuple[int, ...], ...]  # leaf indices per bucket
    bucket_specs: Tuple[BucketSpec, ...]


def tree_flatten_buckets(tree: Any) -> Tuple[List[jax.Array], TreeBucketSpec]:
    """Flatten an arbitrary pytree into one flat 1-D bucket per dtype."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    groups = group_by_dtype(leaves)
    buckets, bucket_specs, group_order, group_indices = [], [], [], []
    for name, idxs in groups.items():
        flat, spec = flatten_tensors([leaves[i] for i in idxs])
        buckets.append(flat)
        bucket_specs.append(spec)
        group_order.append(name)
        group_indices.append(tuple(idxs))
    tspec = TreeBucketSpec(
        treedef=treedef,
        leaf_dtypes=tuple(jnp.dtype(l.dtype).name for l in leaves),
        group_order=tuple(group_order),
        group_indices=tuple(group_indices),
        bucket_specs=tuple(bucket_specs),
    )
    return buckets, tspec


def tree_unflatten_buckets(buckets: Sequence[jax.Array], tspec: TreeBucketSpec) -> Any:
    """Inverse of :func:`tree_flatten_buckets`."""
    n_leaves = len(tspec.leaf_dtypes)
    leaves: List[Any] = [None] * n_leaves
    for flat, idxs, spec in zip(buckets, tspec.group_indices, tspec.bucket_specs):
        parts = unflatten_tensors(flat, spec)
        for i, p in zip(idxs, parts):
            leaves[i] = p
    return jax.tree_util.tree_unflatten(tspec.treedef, leaves)
