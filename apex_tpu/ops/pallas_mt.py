"""Pallas TPU kernels for the multi-tensor bucket ops.

These are the TPU-native re-design of the reference CUDA kernels in
``csrc/multi_tensor_*_kernel.cu`` driven by the chunked launcher
``csrc/multi_tensor_apply.cuh:41-142``. Instead of packing up to 110 tensor
pointers into pinned-host metadata per launch (multi_tensor_apply.cuh:72-118),
we pack the tensors themselves into one flat per-dtype bucket (ops/buckets.py)
and run a single Pallas kernel with a 1-D grid of VMEM-sized chunks — the grid
on TPU is sequential, so the overflow flag and norm accumulators live in
SMEM/VMEM outputs that persist across grid steps.

Layout: a flat bucket of N elements is zero-padded to a multiple of
``BLOCK_ROWS * 128`` and viewed as (rows, 128) so the VPU sees full
(sublane, lane) tiles.
"""

from __future__ import annotations

import functools
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops import buckets as _buckets

Tree = Any

LANES = 128
BLOCK_ROWS = 512  # 512x128 fp32 = 256 KiB per operand block in VMEM


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _as_blocked(flat: jax.Array) -> Tuple[jax.Array, int]:
    """Zero-pad a 1-D array to a multiple of BLOCK_ROWS*LANES and reshape to
    (rows, LANES). Returns (blocked, original_length)."""
    n = flat.shape[0]
    chunk = BLOCK_ROWS * LANES
    padded = ((n + chunk - 1) // chunk) * chunk
    if padded != n:
        flat = jnp.pad(flat, (0, padded - n))
    return flat.reshape(-1, LANES), n


def _unblocked(blocked: jax.Array, n: int) -> jax.Array:
    return blocked.reshape(-1)[:n]


# ---------------------------------------------------------------------------
# scale
# ---------------------------------------------------------------------------

def _scale_kernel(scale_ref, x_ref, y_ref, of_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        of_ref[0, 0] = 0

    x = x_ref[:].astype(jnp.float32)
    y_ref[:] = (x * scale_ref[0]).astype(y_ref.dtype)
    bad = jnp.logical_not(jnp.all(jnp.isfinite(x)))
    of_ref[0, 0] = jnp.maximum(of_ref[0, 0], bad.astype(jnp.int32))


def scale_flat(x: jax.Array, scale: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Fused out = x*scale + nonfinite detect on one flat bucket."""
    xb, n = _as_blocked(x)
    rows = xb.shape[0]
    grid = rows // BLOCK_ROWS
    y, of = pl.pallas_call(
        _scale_kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((BLOCK_ROWS, LANES), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((BLOCK_ROWS, LANES), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(xb.shape, x.dtype),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
        ],
        interpret=_interpret(),
    )(jnp.asarray(scale, jnp.float32).reshape(1), xb)
    return _unblocked(y, n), of[0, 0] > 0


# ---------------------------------------------------------------------------
# axpby
# ---------------------------------------------------------------------------

def _axpby_kernel(ab_ref, x_ref, y_ref, out_ref, of_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        of_ref[0, 0] = 0

    x = x_ref[:].astype(jnp.float32)
    y = y_ref[:].astype(jnp.float32)
    out_ref[:] = (ab_ref[0] * x + ab_ref[1] * y).astype(out_ref.dtype)
    bad = jnp.logical_not(jnp.all(jnp.isfinite(x)) & jnp.all(jnp.isfinite(y)))
    of_ref[0, 0] = jnp.maximum(of_ref[0, 0], bad.astype(jnp.int32))


def axpby_flat(a, x: jax.Array, b, y: jax.Array) -> Tuple[jax.Array, jax.Array]:
    xb, n = _as_blocked(x)
    yb, _ = _as_blocked(y)
    grid = xb.shape[0] // BLOCK_ROWS
    ab = jnp.stack([jnp.asarray(a, jnp.float32), jnp.asarray(b, jnp.float32)])
    out, of = pl.pallas_call(
        _axpby_kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((BLOCK_ROWS, LANES), lambda i: (i, 0)),
            pl.BlockSpec((BLOCK_ROWS, LANES), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((BLOCK_ROWS, LANES), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(yb.shape, y.dtype),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
        ],
        interpret=_interpret(),
    )(ab, xb, yb)
    return _unblocked(out, n), of[0, 0] > 0


# ---------------------------------------------------------------------------
# l2norm
# ---------------------------------------------------------------------------

def _l2norm_kernel(x_ref, acc_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        acc_ref[0, 0] = 0.0

    x = x_ref[:].astype(jnp.float32)
    acc_ref[0, 0] += jnp.sum(x * x)


def l2norm_sq_flat(x: jax.Array) -> jax.Array:
    """Sum of squares of one flat bucket (fp32 scalar)."""
    xb, _ = _as_blocked(x)
    grid = xb.shape[0] // BLOCK_ROWS
    acc = pl.pallas_call(
        _l2norm_kernel,
        grid=(grid,),
        in_specs=[pl.BlockSpec((BLOCK_ROWS, LANES), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((1, 1), lambda i: (0, 0),
                               memory_space=pltpu.SMEM),
        out_shape=jax.ShapeDtypeStruct((1, 1), jnp.float32),
        interpret=_interpret(),
    )(xb)
    return acc[0, 0]


# ---------------------------------------------------------------------------
# adam
# ---------------------------------------------------------------------------

def _adam_kernel(adam_w_mode, c_ref, g_ref, p_ref, m_ref, v_ref,
                 p_out, m_out, v_out):
    # c = [lr, beta1, beta2, eps, bc1, bc2, weight_decay, inv_scale]
    lr, b1, b2, eps = c_ref[0], c_ref[1], c_ref[2], c_ref[3]
    bc1, bc2, wd, inv_scale = c_ref[4], c_ref[5], c_ref[6], c_ref[7]
    g = g_ref[:].astype(jnp.float32) * inv_scale
    p = p_ref[:].astype(jnp.float32)
    if not adam_w_mode:
        g = g + wd * p
    m = b1 * m_ref[:].astype(jnp.float32) + (1.0 - b1) * g
    v = b2 * v_ref[:].astype(jnp.float32) + (1.0 - b2) * g * g
    update = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
    if adam_w_mode:
        update = update + wd * p
    p = p - lr * update
    p_out[:] = p.astype(p_out.dtype)
    m_out[:] = m.astype(m_out.dtype)
    v_out[:] = v.astype(v_out.dtype)


def adam_flat(g: jax.Array, p: jax.Array, m: jax.Array, v: jax.Array, *,
              lr, beta1, beta2, eps, bc1, bc2, adam_w_mode, weight_decay,
              inv_scale=None) -> Tuple[jax.Array, jax.Array, jax.Array]:
    gb, n = _as_blocked(g)
    pb, _ = _as_blocked(p)
    mb, _ = _as_blocked(m)
    vb, _ = _as_blocked(v)
    grid = gb.shape[0] // BLOCK_ROWS
    c = jnp.stack([
        jnp.asarray(lr, jnp.float32), jnp.asarray(beta1, jnp.float32),
        jnp.asarray(beta2, jnp.float32), jnp.asarray(eps, jnp.float32),
        jnp.asarray(bc1, jnp.float32), jnp.asarray(bc2, jnp.float32),
        jnp.asarray(weight_decay, jnp.float32),
        jnp.asarray(1.0 if inv_scale is None else inv_scale, jnp.float32),
    ])
    blk = lambda: pl.BlockSpec((BLOCK_ROWS, LANES), lambda i: (i, 0))
    p2, m2, v2 = pl.pallas_call(
        functools.partial(_adam_kernel, bool(adam_w_mode)),
        grid=(grid,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  blk(), blk(), blk(), blk()],
        out_specs=[blk(), blk(), blk()],
        out_shape=[
            jax.ShapeDtypeStruct(pb.shape, p.dtype),
            jax.ShapeDtypeStruct(mb.shape, m.dtype),
            jax.ShapeDtypeStruct(vb.shape, v.dtype),
        ],
        input_output_aliases={2: 0, 3: 1, 4: 2},
        interpret=_interpret(),
    )(c, gb, pb, mb, vb)
    return _unblocked(p2, n), _unblocked(m2, n), _unblocked(v2, n)


# ---------------------------------------------------------------------------
# Tree-level wrappers: group leaves by dtype signature, bucket, run kernel.
# ---------------------------------------------------------------------------

def _grouped(trees: Sequence[Tree]):
    """Align leaves across trees and group indices by their dtype signature."""
    all_leaves = [jax.tree_util.tree_leaves(t) for t in trees]
    n = len(all_leaves[0])
    sig_groups = {}
    for i in range(n):
        sig = tuple(jnp.dtype(l[i].dtype).name for l in all_leaves)
        sig_groups.setdefault(sig, []).append(i)
    return all_leaves, sig_groups


def scale_tree(tree: Tree, scale) -> Tuple[Tree, jax.Array]:
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    groups = _buckets.group_by_dtype(leaves)
    out_leaves: List[Any] = [None] * len(leaves)
    overflow = jnp.asarray(False)
    for _, idxs in groups.items():
        flat, spec = _buckets.flatten_tensors([leaves[i] for i in idxs])
        y, of = scale_flat(flat, scale)
        overflow = jnp.logical_or(overflow, of)
        for i, t in zip(idxs, _buckets.unflatten_tensors(y, spec)):
            out_leaves[i] = t
    return jax.tree_util.tree_unflatten(treedef, out_leaves), overflow


def axpby_tree(a, x: Tree, b, y: Tree) -> Tuple[Tree, jax.Array]:
    (x_leaves, y_leaves), sig_groups = _grouped([x, y])
    treedef = jax.tree_util.tree_structure(x)
    out_leaves: List[Any] = [None] * len(x_leaves)
    overflow = jnp.asarray(False)
    for _, idxs in sig_groups.items():
        fx, sx = _buckets.flatten_tensors([x_leaves[i] for i in idxs])
        fy, _ = _buckets.flatten_tensors([y_leaves[i] for i in idxs])
        out, of = axpby_flat(a, fx, b, fy)
        overflow = jnp.logical_or(overflow, of)
        for i, t in zip(idxs, _buckets.unflatten_tensors(out, sx)):
            out_leaves[i] = t
    return jax.tree_util.tree_unflatten(treedef, out_leaves), overflow


def l2norm_tree(tree: Tree) -> jax.Array:
    leaves = jax.tree_util.tree_leaves(tree)
    groups = _buckets.group_by_dtype(leaves)
    total = jnp.asarray(0.0, jnp.float32)
    for _, idxs in groups.items():
        flat, _ = _buckets.flatten_tensors([leaves[i] for i in idxs])
        total = total + l2norm_sq_flat(flat)
    return jnp.sqrt(total)


def adam_tree(grads: Tree, params: Tree, exp_avg: Tree, exp_avg_sq: Tree, *,
              lr, beta1, beta2, eps, bc1, bc2, adam_w_mode, weight_decay,
              inv_scale=None) -> Tuple[Tree, Tree, Tree]:
    (g_l, p_l, m_l, v_l), sig_groups = _grouped(
        [grads, params, exp_avg, exp_avg_sq])
    treedef = jax.tree_util.tree_structure(params)
    new_p: List[Any] = [None] * len(p_l)
    new_m: List[Any] = [None] * len(p_l)
    new_v: List[Any] = [None] * len(p_l)
    for _, idxs in sig_groups.items():
        fg, _ = _buckets.flatten_tensors([g_l[i] for i in idxs])
        fp, sp = _buckets.flatten_tensors([p_l[i] for i in idxs])
        fm, sm = _buckets.flatten_tensors([m_l[i] for i in idxs])
        fv, sv = _buckets.flatten_tensors([v_l[i] for i in idxs])
        p2, m2, v2 = adam_flat(
            fg, fp, fm, fv, lr=lr, beta1=beta1, beta2=beta2, eps=eps,
            bc1=bc1, bc2=bc2, adam_w_mode=adam_w_mode,
            weight_decay=weight_decay, inv_scale=inv_scale)
        for i, t in zip(idxs, _buckets.unflatten_tensors(p2, sp)):
            new_p[i] = t
        for i, t in zip(idxs, _buckets.unflatten_tensors(m2, sm)):
            new_m[i] = t
        for i, t in zip(idxs, _buckets.unflatten_tensors(v2, sv)):
            new_v[i] = t
    unf = lambda ls: jax.tree_util.tree_unflatten(treedef, ls)
    return unf(new_p), unf(new_m), unf(new_v)
