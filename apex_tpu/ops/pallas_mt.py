"""Pallas TPU kernels for the multi-tensor bucket ops.

These are the TPU-native re-design of the reference CUDA kernels in
``csrc/multi_tensor_*_kernel.cu`` driven by the chunked launcher
``csrc/multi_tensor_apply.cuh:41-142``. Instead of packing up to 110 tensor
pointers into pinned-host metadata per launch (multi_tensor_apply.cuh:72-118),
we pack the tensors themselves into one flat per-dtype bucket (ops/buckets.py)
and run a single Pallas kernel with a 1-D grid of VMEM-sized chunks — the grid
on TPU is sequential, so the overflow flag and norm accumulators live in
SMEM/VMEM outputs that persist across grid steps.

Layout: a flat bucket of N elements is zero-padded to a multiple of
``BLOCK_ROWS * 128`` and viewed as (rows, 128) so the VPU sees full
(sublane, lane) tiles.

STATUS (r3): ARCHIVED — documented negative result. Measured on v5e these
kernels lose to XLA's whole-graph elementwise fusion by 1.4-1.9x even in
their best case (persistent-bucket operands, zero marshalling; BASELINE.md
table). They remain complete, parity-tested, and selectable via
``APEX_TPU_MT_BACKEND=pallas``, but no shipped default path runs them.
"""

from __future__ import annotations

import functools
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops._amp_guard import no_amp as _no_amp

from apex_tpu.ops import buckets as _buckets

Tree = Any

LANES = 128
# 512x128 fp32 = 256 KiB per operand block in VMEM. ONE definition: the
# tuner's heuristic module owns the frozen default (a retune edits it
# there, and the off-policy resolution can never silently diverge from
# this in-file name); the per-call value resolves through apex_tpu.tune
# — see _block_rows — with explicit caller values winning.
from apex_tpu.tune.heuristics import MT_BLOCK_ROWS as BLOCK_ROWS


def _interpret() -> bool:
    # Must agree with multi_tensor._TPU_BACKENDS: an axon-tunneled chip is a
    # real TPU and must get Mosaic compilation, not interpret mode.
    from apex_tpu.ops.multi_tensor import _TPU_BACKENDS
    return jax.default_backend() not in _TPU_BACKENDS


def _block_rows(n: int, dtype, block_rows: Optional[int]) -> int:
    """Grid-block row count for an n-element bucket: the explicit caller
    value when given, else the tuner's resolution (BLOCK_ROWS under the
    default off policy)."""
    if block_rows is not None:
        return int(block_rows)
    from apex_tpu import tune
    return tune.mt_block_rows(n=n, dtype=dtype)


def _as_blocked(flat: jax.Array, br: int) -> Tuple[jax.Array, int]:
    """Zero-pad a 1-D array to a multiple of br*LANES and reshape to
    (rows, LANES). Returns (blocked, original_length)."""
    n = flat.shape[0]
    chunk = br * LANES
    padded = ((n + chunk - 1) // chunk) * chunk
    if padded != n:
        flat = jnp.pad(flat, (0, padded - n))
    return flat.reshape(-1, LANES), n


def _unblocked(blocked: jax.Array, n: int) -> jax.Array:
    return blocked.reshape(-1)[:n]


# ---------------------------------------------------------------------------
# scale
# ---------------------------------------------------------------------------

def _scale_kernel(scale_ref, x_ref, y_ref, of_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        of_ref[0, 0] = 0

    x = x_ref[:].astype(jnp.float32)
    y_ref[:] = (x * scale_ref[0]).astype(y_ref.dtype)
    bad = jnp.logical_not(jnp.all(jnp.isfinite(x)))
    of_ref[0, 0] = jnp.maximum(of_ref[0, 0], bad.astype(jnp.int32))


@_no_amp
def scale_flat(x: jax.Array, scale: jax.Array, *,
               block_rows: Optional[int] = None,
               ) -> Tuple[jax.Array, jax.Array]:
    """Fused out = x*scale + nonfinite detect on one flat bucket."""
    br = _block_rows(x.shape[0], x.dtype, block_rows)
    xb, n = _as_blocked(x, br)
    rows = xb.shape[0]
    grid = rows // br
    y, of = pl.pallas_call(
        _scale_kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((br, LANES), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, LANES), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(xb.shape, x.dtype),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
        ],
        interpret=_interpret(),
    )(jnp.asarray(scale, jnp.float32).reshape(1), xb)
    return _unblocked(y, n), of[0, 0] > 0


# ---------------------------------------------------------------------------
# axpby
# ---------------------------------------------------------------------------

def _axpby_kernel(ab_ref, x_ref, y_ref, out_ref, of_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        of_ref[0, 0] = 0

    x = x_ref[:].astype(jnp.float32)
    y = y_ref[:].astype(jnp.float32)
    out_ref[:] = (ab_ref[0] * x + ab_ref[1] * y).astype(out_ref.dtype)
    bad = jnp.logical_not(jnp.all(jnp.isfinite(x)) & jnp.all(jnp.isfinite(y)))
    of_ref[0, 0] = jnp.maximum(of_ref[0, 0], bad.astype(jnp.int32))


@_no_amp
def axpby_flat(a, x: jax.Array, b, y: jax.Array, *,
               block_rows: Optional[int] = None,
               ) -> Tuple[jax.Array, jax.Array]:
    br = _block_rows(x.shape[0], x.dtype, block_rows)
    xb, n = _as_blocked(x, br)
    yb, _ = _as_blocked(y, br)
    grid = xb.shape[0] // br
    ab = jnp.stack([jnp.asarray(a, jnp.float32), jnp.asarray(b, jnp.float32)])
    out, of = pl.pallas_call(
        _axpby_kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((br, LANES), lambda i: (i, 0)),
            pl.BlockSpec((br, LANES), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, LANES), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(yb.shape, y.dtype),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
        ],
        interpret=_interpret(),
    )(ab, xb, yb)
    return _unblocked(out, n), of[0, 0] > 0


# ---------------------------------------------------------------------------
# l2norm
# ---------------------------------------------------------------------------

def _l2norm_kernel(x_ref, acc_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        acc_ref[0, 0] = 0.0

    x = x_ref[:].astype(jnp.float32)
    acc_ref[0, 0] += jnp.sum(x * x)


@_no_amp
def l2norm_sq_flat(x: jax.Array, *,
                   block_rows: Optional[int] = None) -> jax.Array:
    """Sum of squares of one flat bucket (fp32 scalar)."""
    br = _block_rows(x.shape[0], x.dtype, block_rows)
    xb, _ = _as_blocked(x, br)
    grid = xb.shape[0] // br
    acc = pl.pallas_call(
        _l2norm_kernel,
        grid=(grid,),
        in_specs=[pl.BlockSpec((br, LANES), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((1, 1), lambda i: (0, 0),
                               memory_space=pltpu.SMEM),
        out_shape=jax.ShapeDtypeStruct((1, 1), jnp.float32),
        interpret=_interpret(),
    )(xb)
    return acc[0, 0]


# ---------------------------------------------------------------------------
# adam
# ---------------------------------------------------------------------------

def _adam_kernel(adam_w_mode, c_ref, g_ref, p_ref, m_ref, v_ref,
                 p_out, m_out, v_out):
    # c = [lr, beta1, beta2, eps, bc1, bc2, weight_decay, inv_scale]
    lr, b1, b2, eps = c_ref[0], c_ref[1], c_ref[2], c_ref[3]
    bc1, bc2, wd, inv_scale = c_ref[4], c_ref[5], c_ref[6], c_ref[7]
    g = g_ref[:].astype(jnp.float32) * inv_scale
    p = p_ref[:].astype(jnp.float32)
    if not adam_w_mode:
        g = g + wd * p
    m = b1 * m_ref[:].astype(jnp.float32) + (1.0 - b1) * g
    v = b2 * v_ref[:].astype(jnp.float32) + (1.0 - b2) * g * g
    update = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
    if adam_w_mode:
        update = update + wd * p
    p = p - lr * update
    p_out[:] = p.astype(p_out.dtype)
    m_out[:] = m.astype(m_out.dtype)
    v_out[:] = v.astype(v_out.dtype)


@_no_amp
def adam_flat(g: jax.Array, p: jax.Array, m: jax.Array, v: jax.Array, *,
              lr, beta1, beta2, eps, bc1, bc2, adam_w_mode, weight_decay,
              inv_scale=None, block_rows: Optional[int] = None,
              ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    br = _block_rows(g.shape[0], g.dtype, block_rows)
    gb, n = _as_blocked(g, br)
    pb, _ = _as_blocked(p, br)
    mb, _ = _as_blocked(m, br)
    vb, _ = _as_blocked(v, br)
    grid = gb.shape[0] // br
    c = jnp.stack([
        jnp.asarray(lr, jnp.float32), jnp.asarray(beta1, jnp.float32),
        jnp.asarray(beta2, jnp.float32), jnp.asarray(eps, jnp.float32),
        jnp.asarray(bc1, jnp.float32), jnp.asarray(bc2, jnp.float32),
        jnp.asarray(weight_decay, jnp.float32),
        jnp.asarray(1.0 if inv_scale is None else inv_scale, jnp.float32),
    ])
    blk = lambda: pl.BlockSpec((br, LANES), lambda i: (i, 0))
    p2, m2, v2 = pl.pallas_call(
        functools.partial(_adam_kernel, bool(adam_w_mode)),
        grid=(grid,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  blk(), blk(), blk(), blk()],
        out_specs=[blk(), blk(), blk()],
        out_shape=[
            jax.ShapeDtypeStruct(pb.shape, p.dtype),
            jax.ShapeDtypeStruct(mb.shape, m.dtype),
            jax.ShapeDtypeStruct(vb.shape, v.dtype),
        ],
        input_output_aliases={2: 0, 3: 1, 4: 2},
        interpret=_interpret(),
    )(c, gb, pb, mb, vb)
    return _unblocked(p2, n), _unblocked(m2, n), _unblocked(v2, n)


# ---------------------------------------------------------------------------
# Segmented (per-tensor) reductions over lane-aligned buckets.
#
# The reference computes per-tensor norms from a flat bucket with a two-stage
# kernel: per-chunk partial sums into (tensor, chunk) scratch, then a cleanup
# reduction (csrc/multi_tensor_l2norm_kernel.cu:197-280). The TPU one-pass
# equivalent: tensors are packed at LANES-aligned offsets so every (sublane,
# lane) row of the blocked view belongs to exactly one tensor; the kernel
# reduces each row (lane axis), then scatters row sums into a (1, T_pad)
# accumulator via a row->tensor one-hot built from start/end row bounds. The
# grid is sequential on TPU so the accumulator persists across grid steps, and
# the O(T) cleanup (sqrt, trust ratios) runs on scalars outside the kernel.
# ---------------------------------------------------------------------------

def _pad_t(t: int) -> int:
    return max(LANES, ((t + LANES - 1) // LANES) * LANES)


def _seg_bounds(spec) -> Tuple[jax.Array, jax.Array, int]:
    """Per-tensor [start, end) row bounds of a LANES-aligned bucket, padded to
    (1, T_pad) int32 for VMEM."""
    import numpy as np
    offs = np.asarray(spec.offsets, np.int64)
    sizes = np.asarray(spec.sizes, np.int64)
    if (offs % LANES).any():
        raise ValueError("segmented reduction needs LANES-aligned offsets; "
                         "flatten with align=LANES")
    t = len(spec.sizes)
    t_pad = _pad_t(t)
    starts = np.zeros((1, t_pad), np.int32)
    ends = np.zeros((1, t_pad), np.int32)
    starts[0, :t] = offs // LANES
    ends[0, :t] = (offs + sizes + LANES - 1) // LANES
    return jnp.asarray(starts), jnp.asarray(ends), t_pad


def _row_onehot(i, br, starts, ends):
    """(br, T_pad) {0,1} map of block-local rows to tensors (``br`` = the
    grid block's row count, read off the kernel's block shape)."""
    r = i * br + jax.lax.broadcasted_iota(jnp.int32, (br, 1), 0)
    return jnp.logical_and(r >= starts, r < ends).astype(jnp.float32)


def _l2norm_seg_kernel(x_ref, starts_ref, ends_ref, acc_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    x = x_ref[:].astype(jnp.float32)
    rowsq = jnp.sum(x * x, axis=1, keepdims=True)          # (rows, 1)
    onehot = _row_onehot(i, x.shape[0], starts_ref[:], ends_ref[:])
    acc_ref[:] += jnp.sum(rowsq * onehot, axis=0, keepdims=True)


@_no_amp
def l2norm_sq_seg_flat(x: jax.Array, spec, *,
                       block_rows: Optional[int] = None) -> jax.Array:
    """Per-tensor sums of squares of one LANES-aligned bucket -> (T,) fp32."""
    starts, ends, t_pad = _seg_bounds(spec)
    br = _block_rows(x.shape[0], x.dtype, block_rows)
    xb, _ = _as_blocked(x, br)
    grid = xb.shape[0] // br
    acc = pl.pallas_call(
        _l2norm_seg_kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((br, LANES), lambda i: (i, 0)),
            pl.BlockSpec((1, t_pad), lambda i: (0, 0)),
            pl.BlockSpec((1, t_pad), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, t_pad), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((1, t_pad), jnp.float32),
        interpret=_interpret(),
    )(xb, starts, ends)
    return acc[0, :len(spec.sizes)]


# ---------------------------------------------------------------------------
# sgd
# ---------------------------------------------------------------------------

def _sgd_kernel(use_momentum, nesterov, wd_after_momentum, n_out,
                c_ref, g_ref, p_ref, m_ref, *out_refs):
    # c = [lr, weight_decay, momentum, dampening, scale, first]
    p_out, m_out = out_refs[0], out_refs[1]
    lr, wd, mom = c_ref[0], c_ref[1], c_ref[2]
    damp, scale, first = c_ref[3], c_ref[4], c_ref[5]
    g = g_ref[:].astype(jnp.float32) * scale
    p = p_ref[:].astype(jnp.float32)
    if not wd_after_momentum:
        g = g + wd * p
    if use_momentum:
        m_steady = mom * m_ref[:].astype(jnp.float32) + (1.0 - damp) * g
        m = jnp.where(first > 0, g, m_steady)
        d = g + mom * m if nesterov else m
        m_out[:] = m.astype(m_out.dtype)
    else:
        m_out[:] = m_ref[:]
        d = g
    if wd_after_momentum:
        d = d + wd * p
    p_new = p - lr * d
    p_out[:] = p_new.astype(p_out.dtype)
    if n_out == 3:
        out_refs[2][:] = p_new.astype(out_refs[2].dtype)


@_no_amp
def sgd_flat(g: jax.Array, p: jax.Array, m: jax.Array, *, lr, weight_decay,
             momentum, dampening, nesterov, wd_after_momentum, first,
             scale=1.0, model_dtype=None, block_rows: Optional[int] = None):
    """Fused SGD on one flat bucket (csrc/multi_tensor_sgd_kernel.cu:320).

    ``model_dtype`` adds a fused low-precision model-param copy output — the
    reference's 4-list variant used by amp FusedSGD with
    ``materialize_master_grads=False`` (multi_tensor_sgd_kernel.cu N=4 case).
    Returns ``(new_p, new_m[, new_model])``.
    """
    br = _block_rows(g.shape[0], g.dtype, block_rows)
    gb, n = _as_blocked(g, br)
    pb, _ = _as_blocked(p, br)
    mb, _ = _as_blocked(m, br)
    grid = gb.shape[0] // br
    c = jnp.stack([
        jnp.asarray(lr, jnp.float32), jnp.asarray(weight_decay, jnp.float32),
        jnp.asarray(momentum, jnp.float32),
        jnp.asarray(dampening, jnp.float32),
        jnp.asarray(scale, jnp.float32),
        jnp.asarray(first, jnp.float32),
    ])
    blk = lambda: pl.BlockSpec((br, LANES), lambda i: (i, 0))
    n_out = 3 if model_dtype is not None else 2
    out_specs = [blk() for _ in range(n_out)]
    out_shape = [jax.ShapeDtypeStruct(pb.shape, p.dtype),
                 jax.ShapeDtypeStruct(mb.shape, m.dtype)]
    if model_dtype is not None:
        out_shape.append(jax.ShapeDtypeStruct(pb.shape, model_dtype))
    # Momentum structure is static when momentum is a Python number (the
    # optimizer hyperparameter case); a traced momentum keeps the buffer live.
    use_momentum = not (isinstance(momentum, (int, float)) and momentum == 0)
    outs = pl.pallas_call(
        functools.partial(_sgd_kernel, use_momentum, bool(nesterov),
                          bool(wd_after_momentum), n_out),
        grid=(grid,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  blk(), blk(), blk()],
        out_specs=out_specs,
        out_shape=out_shape,
        input_output_aliases={2: 0, 3: 1},
        interpret=_interpret(),
    )(c, gb, pb, mb)
    res = tuple(_unblocked(o, n) for o in outs)
    return res


# ---------------------------------------------------------------------------
# adagrad
# ---------------------------------------------------------------------------

def _adagrad_kernel(adagrad_w_mode, c_ref, g_ref, p_ref, h_ref, p_out, h_out):
    # c = [lr, eps, weight_decay, scale]
    lr, eps, wd, scale = c_ref[0], c_ref[1], c_ref[2], c_ref[3]
    g = g_ref[:].astype(jnp.float32) * scale
    p = p_ref[:].astype(jnp.float32)
    if not adagrad_w_mode:
        g = g + wd * p
    h = h_ref[:].astype(jnp.float32) + g * g
    u = g / (jnp.sqrt(h) + eps)
    if adagrad_w_mode:
        u = u + wd * p
    p_out[:] = (p - lr * u).astype(p_out.dtype)
    h_out[:] = h.astype(h_out.dtype)


@_no_amp
def adagrad_flat(g: jax.Array, p: jax.Array, h: jax.Array, *, lr, eps,
                 weight_decay, adagrad_w_mode=False, scale=1.0,
                 block_rows: Optional[int] = None):
    """Fused Adagrad on one flat bucket (csrc/multi_tensor_adagrad.cu)."""
    br = _block_rows(g.shape[0], g.dtype, block_rows)
    gb, n = _as_blocked(g, br)
    pb, _ = _as_blocked(p, br)
    hb, _ = _as_blocked(h, br)
    grid = gb.shape[0] // br
    c = jnp.stack([
        jnp.asarray(lr, jnp.float32), jnp.asarray(eps, jnp.float32),
        jnp.asarray(weight_decay, jnp.float32),
        jnp.asarray(scale, jnp.float32),
    ])
    blk = lambda: pl.BlockSpec((br, LANES), lambda i: (i, 0))
    p2, h2 = pl.pallas_call(
        functools.partial(_adagrad_kernel, bool(adagrad_w_mode)),
        grid=(grid,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM), blk(), blk(), blk()],
        out_specs=[blk(), blk()],
        out_shape=[jax.ShapeDtypeStruct(pb.shape, p.dtype),
                   jax.ShapeDtypeStruct(hb.shape, h.dtype)],
        input_output_aliases={2: 0, 3: 1},
        interpret=_interpret(),
    )(c, gb, pb, hb)
    return _unblocked(p2, n), _unblocked(h2, n)


# ---------------------------------------------------------------------------
# lamb — two Pallas passes + scalar cleanup, mirroring the reference's
# stage structure (csrc/multi_tensor_lamb.cu: moments+update with fused
# per-chunk norms, cleanup, then ratio apply).
# ---------------------------------------------------------------------------

def _lamb_stage1_kernel(adam_w_mode, c_ref, g_ref, p_ref, m_ref, v_ref,
                        starts_ref, ends_ref,
                        m_out, v_out, u_out, pn_acc, un_acc):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        pn_acc[:] = jnp.zeros_like(pn_acc)
        un_acc[:] = jnp.zeros_like(un_acc)

    # c = [beta1, beta3, beta2, eps, bc1, bc2, weight_decay, inv_clip]
    b1, beta3, b2, eps = c_ref[0], c_ref[1], c_ref[2], c_ref[3]
    bc1, bc2, wd, inv_clip = c_ref[4], c_ref[5], c_ref[6], c_ref[7]
    g = g_ref[:].astype(jnp.float32) * inv_clip
    p = p_ref[:].astype(jnp.float32)
    if not adam_w_mode:
        g = g + wd * p
    m = b1 * m_ref[:].astype(jnp.float32) + beta3 * g
    v = b2 * v_ref[:].astype(jnp.float32) + (1.0 - b2) * g * g
    u = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
    if adam_w_mode:
        u = u + wd * p
    m_out[:] = m.astype(m_out.dtype)
    v_out[:] = v.astype(v_out.dtype)
    u_out[:] = u.astype(u_out.dtype)
    onehot = _row_onehot(i, g.shape[0], starts_ref[:], ends_ref[:])
    pn_acc[:] += jnp.sum(jnp.sum(p * p, axis=1, keepdims=True) * onehot,
                         axis=0, keepdims=True)
    un_acc[:] += jnp.sum(jnp.sum(u * u, axis=1, keepdims=True) * onehot,
                         axis=0, keepdims=True)


def _lamb_stage2_kernel(c_ref, p_ref, u_ref, ratios_ref, starts_ref, ends_ref,
                        p_out):
    i = pl.program_id(0)
    onehot = _row_onehot(i, p_ref.shape[0], starts_ref[:], ends_ref[:])
    ratio_row = jnp.sum(onehot * ratios_ref[:], axis=1, keepdims=True)
    p = p_ref[:].astype(jnp.float32)
    u = u_ref[:].astype(jnp.float32)
    p_out[:] = (p - c_ref[0] * ratio_row * u).astype(p_out.dtype)


@_no_amp
def lamb_flat(g: jax.Array, p: jax.Array, m: jax.Array, v: jax.Array, spec, *,
              lr, beta1, beta2, beta3, eps, bc1, bc2, adam_w_mode,
              weight_decay, inv_clip, use_ratio,
              block_rows: Optional[int] = None,
              ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Fused LAMB on one LANES-aligned bucket. Stage 1 computes Adam moments,
    the raw update, and one-pass segmented p/update norms; scalar cleanup forms
    per-tensor trust ratios; stage 2 applies ``p -= lr * ratio * u``."""
    starts, ends, t_pad = _seg_bounds(spec)
    t = len(spec.sizes)
    br = _block_rows(g.shape[0], g.dtype, block_rows)
    gb, n = _as_blocked(g, br)
    pb, _ = _as_blocked(p, br)
    mb, _ = _as_blocked(m, br)
    vb, _ = _as_blocked(v, br)
    grid = gb.shape[0] // br
    c1 = jnp.stack([
        jnp.asarray(beta1, jnp.float32), jnp.asarray(beta3, jnp.float32),
        jnp.asarray(beta2, jnp.float32), jnp.asarray(eps, jnp.float32),
        jnp.asarray(bc1, jnp.float32), jnp.asarray(bc2, jnp.float32),
        jnp.asarray(weight_decay, jnp.float32),
        jnp.asarray(inv_clip, jnp.float32),
    ])
    blk = lambda: pl.BlockSpec((br, LANES), lambda i: (i, 0))
    seg = lambda: pl.BlockSpec((1, t_pad), lambda i: (0, 0))
    m2, v2, u, pn_sq, un_sq = pl.pallas_call(
        functools.partial(_lamb_stage1_kernel, bool(adam_w_mode)),
        grid=(grid,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  blk(), blk(), blk(), blk(), seg(), seg()],
        out_specs=[blk(), blk(), blk(), seg(), seg()],
        out_shape=[
            jax.ShapeDtypeStruct(mb.shape, m.dtype),
            jax.ShapeDtypeStruct(vb.shape, v.dtype),
            jax.ShapeDtypeStruct(gb.shape, jnp.float32),
            jax.ShapeDtypeStruct((1, t_pad), jnp.float32),
            jax.ShapeDtypeStruct((1, t_pad), jnp.float32),
        ],
        input_output_aliases={3: 0, 4: 1},
        interpret=_interpret(),
    )(c1, gb, pb, mb, vb, starts, ends)

    # Scalar cleanup (the reference's cleanup_v2 + per-tensor ratio logic).
    p_norms = jnp.sqrt(pn_sq[0, :t])
    u_norms = jnp.sqrt(un_sq[0, :t])
    if use_ratio:
        ratios = jnp.where((p_norms > 0.0) & (u_norms > 0.0),
                           p_norms / u_norms, 1.0)
    else:
        ratios = jnp.ones((t,), jnp.float32)
    ratios_pad = jnp.zeros((1, t_pad), jnp.float32).at[0, :t].set(ratios)

    p2 = pl.pallas_call(
        _lamb_stage2_kernel,
        grid=(grid,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  blk(), blk(), seg(), seg(), seg()],
        out_specs=blk(),
        out_shape=jax.ShapeDtypeStruct(pb.shape, p.dtype),
        input_output_aliases={1: 0},
        interpret=_interpret(),
    )(jnp.asarray(lr, jnp.float32).reshape(1), pb, u, ratios_pad, starts,
      ends)
    return _unblocked(p2, n), _unblocked(m2, n), _unblocked(v2, n)


# ---------------------------------------------------------------------------
# novograd — per-tensor grad-norm pass + fused update pass, mirroring the
# reference flow (fused_novograd.py: multi_tensor_l2norm per tensor, then
# csrc/multi_tensor_novograd.cu update with per-tensor denominators).
# ---------------------------------------------------------------------------

def _novograd_kernel(c_ref, g_ref, p_ref, m_ref, denom_ref, starts_ref,
                     ends_ref, p_out, m_out):
    i = pl.program_id(0)
    # c = [lr, beta1, beta3, bc1, weight_decay, scale]
    lr, b1, beta3 = c_ref[0], c_ref[1], c_ref[2]
    bc1, wd, scale = c_ref[3], c_ref[4], c_ref[5]
    onehot = _row_onehot(i, g_ref.shape[0], starts_ref[:], ends_ref[:])
    denom_row = jnp.sum(onehot * denom_ref[:], axis=1, keepdims=True)
    denom_row = jnp.where(denom_row > 0.0, denom_row, 1.0)  # padding rows
    g = g_ref[:].astype(jnp.float32) * scale
    p = p_ref[:].astype(jnp.float32)
    gn = g / denom_row + wd * p
    m = b1 * m_ref[:].astype(jnp.float32) + beta3 * gn
    p_out[:] = (p - lr * (m / bc1)).astype(p_out.dtype)
    m_out[:] = m.astype(m_out.dtype)


@_no_amp
def novograd_flat(g: jax.Array, p: jax.Array, m: jax.Array, denoms: jax.Array,
                  spec, *, lr, beta1, beta3, bc1, weight_decay, scale=1.0,
                  block_rows: Optional[int] = None,
                  ) -> Tuple[jax.Array, jax.Array]:
    """Fused NovoGrad update on one LANES-aligned bucket given per-tensor
    denominators ``denoms`` (T,). Returns ``(new_p, new_m)``."""
    starts, ends, t_pad = _seg_bounds(spec)
    t = len(spec.sizes)
    br = _block_rows(g.shape[0], g.dtype, block_rows)
    gb, n = _as_blocked(g, br)
    pb, _ = _as_blocked(p, br)
    mb, _ = _as_blocked(m, br)
    grid = gb.shape[0] // br
    c = jnp.stack([
        jnp.asarray(lr, jnp.float32), jnp.asarray(beta1, jnp.float32),
        jnp.asarray(beta3, jnp.float32), jnp.asarray(bc1, jnp.float32),
        jnp.asarray(weight_decay, jnp.float32),
        jnp.asarray(scale, jnp.float32),
    ])
    denoms_pad = jnp.zeros((1, t_pad), jnp.float32).at[0, :t].set(denoms)
    blk = lambda: pl.BlockSpec((br, LANES), lambda i: (i, 0))
    seg = lambda: pl.BlockSpec((1, t_pad), lambda i: (0, 0))
    p2, m2 = pl.pallas_call(
        _novograd_kernel,
        grid=(grid,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  blk(), blk(), blk(), seg(), seg(), seg()],
        out_specs=[blk(), blk()],
        out_shape=[jax.ShapeDtypeStruct(pb.shape, p.dtype),
                   jax.ShapeDtypeStruct(mb.shape, m.dtype)],
        input_output_aliases={2: 0, 3: 1},
        interpret=_interpret(),
    )(c, gb, pb, mb, denoms_pad, starts, ends)
    return _unblocked(p2, n), _unblocked(m2, n)


# ---------------------------------------------------------------------------
# Tree-level wrappers: group leaves by dtype signature, bucket, run kernel.
# ---------------------------------------------------------------------------

def _grouped(trees: Sequence[Tree]):
    """Align leaves across trees and group indices by their dtype signature."""
    all_leaves = [jax.tree_util.tree_leaves(t) for t in trees]
    n = len(all_leaves[0])
    sig_groups = {}
    for i in range(n):
        sig = tuple(jnp.dtype(l[i].dtype).name for l in all_leaves)
        sig_groups.setdefault(sig, []).append(i)
    return all_leaves, sig_groups


def scale_tree(tree: Tree, scale) -> Tuple[Tree, jax.Array]:
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    groups = _buckets.group_by_dtype(leaves)
    out_leaves: List[Any] = [None] * len(leaves)
    overflow = jnp.asarray(False)
    for _, idxs in groups.items():
        flat, spec = _buckets.flatten_tensors([leaves[i] for i in idxs])
        y, of = scale_flat(flat, scale)
        overflow = jnp.logical_or(overflow, of)
        for i, t in zip(idxs, _buckets.unflatten_tensors(y, spec)):
            out_leaves[i] = t
    return jax.tree_util.tree_unflatten(treedef, out_leaves), overflow


def axpby_tree(a, x: Tree, b, y: Tree) -> Tuple[Tree, jax.Array]:
    (x_leaves, y_leaves), sig_groups = _grouped([x, y])
    treedef = jax.tree_util.tree_structure(x)
    out_leaves: List[Any] = [None] * len(x_leaves)
    overflow = jnp.asarray(False)
    for _, idxs in sig_groups.items():
        fx, sx = _buckets.flatten_tensors([x_leaves[i] for i in idxs])
        fy, _ = _buckets.flatten_tensors([y_leaves[i] for i in idxs])
        out, of = axpby_flat(a, fx, b, fy)
        overflow = jnp.logical_or(overflow, of)
        for i, t in zip(idxs, _buckets.unflatten_tensors(out, sx)):
            out_leaves[i] = t
    return jax.tree_util.tree_unflatten(treedef, out_leaves), overflow


def l2norm_tree(tree: Tree) -> jax.Array:
    leaves = jax.tree_util.tree_leaves(tree)
    groups = _buckets.group_by_dtype(leaves)
    total = jnp.asarray(0.0, jnp.float32)
    for _, idxs in groups.items():
        flat, _ = _buckets.flatten_tensors([leaves[i] for i in idxs])
        total = total + l2norm_sq_flat(flat)
    return jnp.sqrt(total)


def adam_tree(grads: Tree, params: Tree, exp_avg: Tree, exp_avg_sq: Tree, *,
              lr, beta1, beta2, eps, bc1, bc2, adam_w_mode, weight_decay,
              inv_scale=None) -> Tuple[Tree, Tree, Tree]:
    (g_l, p_l, m_l, v_l), sig_groups = _grouped(
        [grads, params, exp_avg, exp_avg_sq])
    treedef = jax.tree_util.tree_structure(params)
    new_p: List[Any] = [None] * len(p_l)
    new_m: List[Any] = [None] * len(p_l)
    new_v: List[Any] = [None] * len(p_l)
    for _, idxs in sig_groups.items():
        fg, _ = _buckets.flatten_tensors([g_l[i] for i in idxs])
        fp, sp = _buckets.flatten_tensors([p_l[i] for i in idxs])
        fm, sm = _buckets.flatten_tensors([m_l[i] for i in idxs])
        fv, sv = _buckets.flatten_tensors([v_l[i] for i in idxs])
        p2, m2, v2 = adam_flat(
            fg, fp, fm, fv, lr=lr, beta1=beta1, beta2=beta2, eps=eps,
            bc1=bc1, bc2=bc2, adam_w_mode=adam_w_mode,
            weight_decay=weight_decay, inv_scale=inv_scale)
        for i, t in zip(idxs, _buckets.unflatten_tensors(p2, sp)):
            new_p[i] = t
        for i, t in zip(idxs, _buckets.unflatten_tensors(m2, sm)):
            new_m[i] = t
        for i, t in zip(idxs, _buckets.unflatten_tensors(v2, sv)):
            new_v[i] = t
    unf = lambda ls: jax.tree_util.tree_unflatten(treedef, ls)
    return unf(new_p), unf(new_m), unf(new_v)


def _run_grouped(trees: Sequence[Tree], fn, out_spec_idx: Sequence[int],
                 align: int = 1):
    """Bucket aligned leaves of ``trees`` per dtype signature, run
    ``fn(flat_arrays, specs, idxs) -> tuple of flat outputs`` per group, and
    unflatten back to trees. Output o is unflattened with the spec of input
    tree ``out_spec_idx[o]``."""
    all_leaves, sig_groups = _grouped(trees)
    treedef = jax.tree_util.tree_structure(trees[0])
    outs: List[List[Any]] = [[None] * len(all_leaves[0])
                             for _ in out_spec_idx]
    for _, idxs in sig_groups.items():
        flats, specs = [], []
        for leaves in all_leaves:
            f, s = _buckets.flatten_tensors([leaves[i] for i in idxs],
                                            align=align)
            flats.append(f)
            specs.append(s)
        results = fn(flats, specs, idxs)
        for o, (res, si) in enumerate(zip(results, out_spec_idx)):
            for i, t in zip(idxs, _buckets.unflatten_tensors(res, specs[si])):
                outs[o][i] = t
    unf = lambda ls: jax.tree_util.tree_unflatten(treedef, ls)
    return tuple(unf(o) for o in outs)


def sgd_tree(grads: Tree, params: Tree, momentum_buf: Tree, *, lr,
             weight_decay, momentum, dampening, nesterov, wd_after_momentum,
             first, scale=1.0, model_out_template: Optional[Tree] = None):
    with_model = model_out_template is not None

    def fn(flats, specs, idxs):
        model_dtype = flats[3].dtype if with_model else None
        return sgd_flat(
            flats[0], flats[1], flats[2], lr=lr, weight_decay=weight_decay,
            momentum=momentum, dampening=dampening, nesterov=nesterov,
            wd_after_momentum=wd_after_momentum, first=first, scale=scale,
            model_dtype=model_dtype)

    trees = [grads, params, momentum_buf]
    if with_model:
        trees.append(model_out_template)
        new_p, new_m, new_model = _run_grouped(trees, fn, (1, 2, 3))
        return new_p, new_m, new_model
    new_p, new_m = _run_grouped(trees, fn, (1, 2))
    return new_p, new_m


def adagrad_tree(grads: Tree, params: Tree, state_sum: Tree, *, lr, eps,
                 weight_decay, adagrad_w_mode=False, scale=1.0,
                 ) -> Tuple[Tree, Tree]:
    def fn(flats, specs, idxs):
        return adagrad_flat(
            flats[0], flats[1], flats[2], lr=lr, eps=eps,
            weight_decay=weight_decay, adagrad_w_mode=adagrad_w_mode,
            scale=scale)

    new_p, new_h = _run_grouped([grads, params, state_sum], fn, (1, 2))
    return new_p, new_h


def lamb_tree(grads: Tree, params: Tree, exp_avg: Tree, exp_avg_sq: Tree, *,
              lr, beta1, beta2, beta3, eps, bc1, bc2, adam_w_mode,
              weight_decay, inv_clip, use_ratio,
              ) -> Tuple[Tree, Tree, Tree]:
    def fn(flats, specs, idxs):
        return lamb_flat(
            flats[0], flats[1], flats[2], flats[3], specs[1], lr=lr,
            beta1=beta1, beta2=beta2, beta3=beta3, eps=eps, bc1=bc1, bc2=bc2,
            adam_w_mode=adam_w_mode, weight_decay=weight_decay,
            inv_clip=inv_clip, use_ratio=use_ratio)

    new_p, new_m, new_v = _run_grouped(
        [grads, params, exp_avg, exp_avg_sq], fn, (1, 2, 3), align=LANES)
    return new_p, new_m, new_v


def novograd_tree(grads: Tree, params: Tree, exp_avg: Tree,
                  v_per_tensor: Tree, *, lr, beta1, beta2, beta3, eps, bc1,
                  bc2, weight_decay, init_zero, first, scale=1.0,
                  ) -> Tuple[Tree, Tree, Tree]:
    """NovoGrad: per-tensor grad-norm kernel pass, scalar v/denominator
    cleanup, then the fused update kernel. ``v_per_tensor`` is a pytree of
    fp32 scalars (one per leaf)."""
    v_leaves = jax.tree_util.tree_leaves(v_per_tensor)
    new_v_leaves: List[Any] = [None] * len(v_leaves)

    def fn(flats, specs, idxs):
        g, p, m = flats[0], flats[1], flats[2]
        gnorm_sq = l2norm_sq_seg_flat(g, specs[0]) * (
            jnp.asarray(scale, jnp.float32) ** 2)
        v_arr = jnp.stack([v_leaves[i] for i in idxs]).astype(jnp.float32)
        v_new = jnp.where(
            jnp.asarray(first),
            0.0 if init_zero else gnorm_sq,
            beta2 * v_arr + (1.0 - beta2) * gnorm_sq)
        denoms = jnp.sqrt(v_new / bc2) + eps
        p2, m2 = novograd_flat(
            g, p, m, denoms, specs[0], lr=lr, beta1=beta1, beta3=beta3,
            bc1=bc1, weight_decay=weight_decay, scale=scale)
        for j, i in enumerate(idxs):
            new_v_leaves[i] = v_new[j]
        return p2, m2

    new_p, new_m = _run_grouped(
        [grads, params, exp_avg], fn, (1, 2), align=LANES)
    new_v = jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(v_per_tensor), new_v_leaves)
    return new_p, new_m, new_v


def l2norm_tree_per_tensor(tree: Tree) -> Tuple[jax.Array, Tree]:
    """Global + per-tensor L2 norms via the one-pass segmented kernel."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    groups = _buckets.group_by_dtype(leaves)
    per_leaf: List[Any] = [None] * len(leaves)
    total = jnp.asarray(0.0, jnp.float32)
    for _, idxs in groups.items():
        flat, spec = _buckets.flatten_tensors([leaves[i] for i in idxs],
                                              align=LANES)
        sumsq = l2norm_sq_seg_flat(flat, spec)
        total = total + jnp.sum(sumsq)
        norms = jnp.sqrt(sumsq)
        for j, i in enumerate(idxs):
            per_leaf[i] = norms[j]
    return jnp.sqrt(total), jax.tree_util.tree_unflatten(treedef, per_leaf)
