"""apex_tpu.ops — fused kernels and bucket plumbing (reference L0/L1 layers:
csrc/ + apex/multi_tensor_apply/)."""

from apex_tpu.ops.buckets import (
    BucketSpec,
    TreeBucketSpec,
    flatten_tensors,
    unflatten_tensors,
    group_by_dtype,
    tree_flatten_buckets,
    tree_unflatten_buckets,
)
from apex_tpu.ops.staged_vjp import apply_staged, cotangent_transform
from apex_tpu.ops.conv_epilogue import bn_relu_apply
from apex_tpu.ops.multi_tensor import (
    multi_tensor_scale,
    multi_tensor_axpby,
    multi_tensor_l2norm,
    multi_tensor_adam,
    multi_tensor_sgd,
    multi_tensor_adagrad,
    multi_tensor_novograd,
    multi_tensor_lamb,
    multi_tensor_check_overflow,
    use_pallas,
)
from apex_tpu.ops.attention import (
    attention_reference,
    flash_attention,
    ring_self_attention,
    self_attention,
    ulysses_self_attention,
)
