"""apex_tpu.sparsity (placeholder — populated incrementally)."""
