"""apex_tpu.sparsity — ASP (Automatic SParsity): 2:4 structured sparsity,
parity with apex/contrib/sparsity (``ASP`` at asp.py:21,
``init_model_for_pruning`` at asp.py:28, mask patterns in
sparse_masklib.py).

Functional recast: masks are a pytree mirroring the params; pruning is
``params * masks``; the reference's "re-apply masks inside optimizer.step"
hook becomes a :class:`SparseOptimizer` wrapper whose step re-masks — the
same invariant (weights stay 2:4 sparse through training) without monkey
patching.

TPU note: 2:4 sparsity has no TPU hardware acceleration (it targets NVIDIA
sparse tensor cores); the value preserved here is the *workflow* — train
dense, prune 2:4, finetune sparse, checkpoint continuity — which is
hardware-independent.
"""

from __future__ import annotations

import functools
import re
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Tree = Any


def mn_mask_1d(w: jax.Array, m: int = 4, n: int = 2) -> jax.Array:
    """Keep the ``n`` largest-|w| of every contiguous group of ``m`` along
    the last axis — sparse_masklib's ``mn_1d_best`` (its exhaustive
    pattern-argmax over all C(m,n) patterns is exactly top-n by magnitude,
    so the TPU form is a vectorized rank test). Last axis must be
    % ``m`` == 0."""
    shape = w.shape
    g = w.reshape(-1, m)
    mag = jnp.abs(g)
    order = jnp.argsort(mag, axis=1)  # ascending
    ranks = jnp.zeros_like(order).at[
        jnp.arange(g.shape[0])[:, None], order].set(
        jnp.broadcast_to(jnp.arange(m), order.shape))
    mask = (ranks >= m - n).astype(w.dtype)
    return mask.reshape(shape)


def m4n2_mask_1d(w: jax.Array) -> jax.Array:
    """sparse_masklib's ``m4n2_1d``: 2-of-4 along the last axis."""
    return mn_mask_1d(w, 4, 2)


@functools.lru_cache(maxsize=None)
def _valid_2d_patterns(m: int, n: int):
    """All m x m 0/1 patterns with exactly n per row AND <= n per column
    (reference compute_valid_2d_patterns; for 4:2 there are 90). Built in
    numpy once — a static (P, m*m) table baked into the jitted program."""
    import itertools
    base = sorted(set(itertools.permutations([1] * n + [0] * (m - n))))
    valid = [p for p in itertools.product(base, repeat=m)
             if all(sum(col) <= n for col in zip(*p))]
    return np.asarray(valid, np.float32).reshape(len(valid), m * m)


def _to_2d_blocks(w: jax.Array, m: int):
    r, c = w.shape
    if r % m or c % m:
        raise ValueError(
            f"2d m:n masking needs both dims % {m} == 0; got {w.shape}")
    # (r//m, m, c//m, m) -> (r//m, c//m, m, m) -> (B, m*m)
    return (w.reshape(r // m, m, c // m, m).transpose(0, 2, 1, 3)
            .reshape(-1, m * m))


def _from_2d_blocks(blocks: jax.Array, shape, m: int):
    r, c = shape
    return (blocks.reshape(r // m, c // m, m, m).transpose(0, 2, 1, 3)
            .reshape(r, c))


def mn_mask_2d_best(w: jax.Array, m: int = 4, n: int = 2) -> jax.Array:
    """Exhaustive 2d m:n mask (sparse_masklib ``mn_2d_best``): every m x m
    block gets the valid pattern (n per row AND per column — so the
    TRANSPOSED weight is also m:n sparse, the DGRAD property) maximizing
    the kept |w| sum. One (B, m²) x (m², P) matmul + argmax — MXU-friendly,
    no per-block loops."""
    patterns = jnp.asarray(_valid_2d_patterns(m, n))      # (P, m*m)
    blocks = _to_2d_blocks(jnp.abs(w.astype(jnp.float32)), m)
    scores = blocks @ patterns.T                          # (B, P)
    best = jnp.argmax(scores, axis=1)
    mask = patterns[best]                                 # (B, m*m)
    return _from_2d_blocks(mask, w.shape, m).astype(w.dtype)


def m4n2_mask_2d_best(w: jax.Array) -> jax.Array:
    return mn_mask_2d_best(w, 4, 2)


def mn_mask_2d_greedy(w: jax.Array, m: int = 4, n: int = 2) -> jax.Array:
    """Greedy 2d m:n mask (sparse_masklib ``mn_2d_greedy``): visit each
    block's entries in descending |w| order, keep while the row/column
    quotas allow. The per-block sequential scan becomes a fori_loop over
    the m² ranked positions, vectorized across all blocks."""
    blocks = _to_2d_blocks(jnp.abs(w.astype(jnp.float32)), m)  # (B, m*m)
    nb = blocks.shape[0]
    order = jnp.argsort(-blocks, axis=1)                  # descending
    bidx = jnp.arange(nb)

    def body(t, carry):
        mask, rows, cols = carry
        idx = order[:, t]                                 # (B,)
        r = idx // m
        c = idx % m
        ok = (rows[bidx, r] < n) & (cols[bidx, c] < n)
        mask = mask.at[bidx, idx].set(ok.astype(mask.dtype))
        rows = rows.at[bidx, r].add(ok.astype(jnp.int32))
        cols = cols.at[bidx, c].add(ok.astype(jnp.int32))
        return mask, rows, cols

    mask0 = jnp.zeros((nb, m * m), jnp.float32)
    quota = jnp.zeros((nb, m), jnp.int32)
    mask, _, _ = jax.lax.fori_loop(0, m * m, body, (mask0, quota, quota))
    return _from_2d_blocks(mask, w.shape, m).astype(w.dtype)


def m4n2_mask_2d_greedy(w: jax.Array) -> jax.Array:
    return mn_mask_2d_greedy(w, 4, 2)


_PATTERNS = {
    "m4n2_1d": m4n2_mask_1d,
    "m4n2_2d_best": m4n2_mask_2d_best,
    "m4n2_2d_greedy": m4n2_mask_2d_greedy,
}


def dispatch_ranks(fn: Callable, w: jax.Array) -> jax.Array:
    """Apply a 2d mask pattern to a rank-1..4 tensor (the rank-dispatch of
    sparse_masklib ``create_mask``): 1d masks as one row; 2d as-is; 3d
    (batch, in, out) flattens leading dims and prunes the last dim (the
    reference's bmm branch); 4d convs — flax layout (h, w, in, out) —
    prune along the INPUT-channel dim, matching the reference's permute of
    torch's (out, in, h, w) to put the reduction dim last."""
    shape = w.shape
    if w.ndim == 1:
        return fn(w.reshape(1, -1)).reshape(shape)
    if w.ndim == 2:
        return fn(w)
    if w.ndim == 3:
        return fn(w.reshape(shape[0] * shape[1], shape[2])).reshape(shape)
    if w.ndim == 4:
        t = w.transpose(0, 1, 3, 2).reshape(-1, shape[2])
        m = fn(t).reshape(shape[0], shape[1], shape[3], shape[2])
        return m.transpose(0, 1, 3, 2)
    raise ValueError(f"sparsity masks support rank 1-4, got shape {shape}")


def create_mask(w: jax.Array, pattern: str = "m4n2_1d",
                density: float = 0.5) -> jax.Array:
    """Rank-dispatching mask construction (sparse_masklib ``create_mask``).
    ``density`` is accepted for signature parity (2:4 is the hardware
    pattern)."""
    del density
    fn = _PATTERNS.get(pattern)
    if fn is None:
        raise ValueError(
            f"unknown sparsity pattern {pattern!r}; options: "
            f"{sorted(_PATTERNS)}")
    return dispatch_ranks(fn, w)


def _default_allowed(path, p) -> bool:
    """Prune 2-D+ kernels whose pruned axis is a multiple of 4 and that are
    not norm/bias params (the reference whitelists Linear/Conv weights).
    The pruned axis is the last dim for ranks 2-3 and the input-channel
    dim (axis 2, flax conv layout) for rank 4 — see dispatch_ranks."""
    if p.ndim < 2:
        return False
    prune_axis = 2 if p.ndim == 4 else -1
    if p.shape[prune_axis] % 4 != 0:
        return False
    name = "/".join(str(getattr(x, "key", getattr(x, "name", x)))
                    for x in path).lower()
    return not any(t in name for t in ("norm", "bn", "bias", "embed"))


def compute_sparse_masks(params: Tree,
                         allowed: Callable = _default_allowed,
                         pattern: Callable = m4n2_mask_1d) -> Tree:
    """Masks for every prunable leaf; ones elsewhere (ASP.compute_sparse_masks).
    Leaves route through :func:`dispatch_ranks`, so any pattern —
    including the 2d block calculators — applies to rank-1..4 leaves
    (conv kernels prune along the input-channel dim)."""
    def mk(path, p):
        if jnp.issubdtype(p.dtype, jnp.floating) and allowed(path, p):
            return dispatch_ranks(pattern, p)
        return jnp.ones_like(p)
    return jax.tree_util.tree_map_with_path(mk, params)


def apply_masks(params: Tree, masks: Tree) -> Tree:
    return jax.tree_util.tree_map(lambda p, m: p * m.astype(p.dtype),
                                  params, masks)


def sparsity_ratio(params: Tree, masks: Tree) -> float:
    """Fraction of masked (zeroed) weights across prunable leaves."""
    zeros = total = 0
    for m in jax.tree_util.tree_leaves(masks):
        total += m.size
        zeros += int(m.size - jnp.sum(m))
    return zeros / max(total, 1)


def prune_for_serving(params: Tree,
                      pattern: Callable = m4n2_mask_1d,
                      allowed: Callable = _default_allowed) -> Tree:
    """One-shot dense -> 2:4 pruning for inference (the serve-loader
    entry point, ``serve.load_model(..., prune=True)``): compute masks
    and apply them, no optimizer wrapper — there is no training step to
    re-mask. Non-prunable leaves (norms, biases, embeddings, dims not %
    4) pass through untouched; every pruned kernel is exactly 2:4 along
    its reduction dim (structure asserted by tests/test_sparsity.py's
    serving test). TPU note per the module docstring: no hardware
    speedup on TPU — this preserves the prune-then-serve WORKFLOW
    (checkpoint continuity with GPU sparse deployments), not FLOPs."""
    return apply_masks(params, compute_sparse_masks(
        params, allowed, pattern))


class SparseOptimizer:
    """Wraps a FusedOptimizer so each step re-applies the masks — the
    reference patches ``optimizer.step`` (asp.py hooks); here the wrapper's
    step composes purely."""

    def __init__(self, inner, masks: Tree):
        self.inner = inner
        self.masks = masks

    def init(self, params):
        return self.inner.init(apply_masks(params, self.masks))

    def step(self, grads, params, state, **kw):
        # mask grads too so momentum doesn't resurrect pruned weights
        grads = apply_masks(grads, self.masks)
        new_p, new_s = self.inner.step(grads, params, state, **kw)
        return apply_masks(new_p, self.masks), new_s


class ASP:
    """API-shape parity with the reference ASP workflow (asp.py:21-…):

        asp = ASP()
        params, opt = asp.init_model_for_pruning(params, optimizer)
        ... train; masks persist via asp.state_dict() ...
    """

    def __init__(self, mask_calculator: Callable = m4n2_mask_1d,
                 allowed_layer_names: Optional[str] = None):
        self.pattern = mask_calculator
        self._name_re = (re.compile(allowed_layer_names)
                         if allowed_layer_names else None)
        self.masks: Optional[Tree] = None

    def _allowed(self, path, p):
        if self._name_re is not None:
            name = "/".join(str(getattr(x, "key", getattr(x, "name", x)))
                            for x in path)
            if not self._name_re.search(name):
                return False
        return _default_allowed(path, p)

    def init_model_for_pruning(self, params: Tree, optimizer=None):
        self.masks = compute_sparse_masks(params, self._allowed,
                                          self.pattern)
        pruned = apply_masks(params, self.masks)
        if optimizer is None:
            return pruned
        return pruned, SparseOptimizer(optimizer, self.masks)

    # checkpoint continuity (reference checkpointing_test_part1/2)
    def state_dict(self) -> dict:
        return {"masks": jax.device_get(self.masks)}

    def load_state_dict(self, d: dict) -> None:
        self.masks = jax.tree_util.tree_map(jnp.asarray, d["masks"])
