"""apex_tpu.sparsity — ASP (Automatic SParsity): 2:4 structured sparsity,
parity with apex/contrib/sparsity (``ASP`` at asp.py:21,
``init_model_for_pruning`` at asp.py:28, mask patterns in
sparse_masklib.py).

Functional recast: masks are a pytree mirroring the params; pruning is
``params * masks``; the reference's "re-apply masks inside optimizer.step"
hook becomes a :class:`SparseOptimizer` wrapper whose step re-masks — the
same invariant (weights stay 2:4 sparse through training) without monkey
patching.

TPU note: 2:4 sparsity has no TPU hardware acceleration (it targets NVIDIA
sparse tensor cores); the value preserved here is the *workflow* — train
dense, prune 2:4, finetune sparse, checkpoint continuity — which is
hardware-independent.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp

Tree = Any


def m4n2_mask_1d(w: jax.Array) -> jax.Array:
    """Keep the 2 largest-|w| of every contiguous group of 4 along the last
    axis (sparse_masklib's m4n2_1d pattern). Last axis must be % 4 == 0."""
    shape = w.shape
    g = w.reshape(-1, 4)
    mag = jnp.abs(g)
    # rank within each group; keep top-2
    order = jnp.argsort(mag, axis=1)  # ascending
    ranks = jnp.zeros_like(order).at[
        jnp.arange(g.shape[0])[:, None], order].set(
        jnp.broadcast_to(jnp.arange(4), order.shape))
    mask = (ranks >= 2).astype(w.dtype)
    return mask.reshape(shape)


def _default_allowed(path, p) -> bool:
    """Prune 2-D+ kernels whose last dim is a multiple of 4 and that are not
    norm/bias params (the reference whitelists Linear/Conv weights)."""
    if p.ndim < 2 or p.shape[-1] % 4 != 0:
        return False
    name = "/".join(str(getattr(x, "key", getattr(x, "name", x)))
                    for x in path).lower()
    return not any(t in name for t in ("norm", "bn", "bias", "embed"))


def compute_sparse_masks(params: Tree,
                         allowed: Callable = _default_allowed,
                         pattern: Callable = m4n2_mask_1d) -> Tree:
    """Masks for every prunable leaf; ones elsewhere (ASP.compute_sparse_masks)."""
    def mk(path, p):
        if jnp.issubdtype(p.dtype, jnp.floating) and allowed(path, p):
            return pattern(p)
        return jnp.ones_like(p)
    return jax.tree_util.tree_map_with_path(mk, params)


def apply_masks(params: Tree, masks: Tree) -> Tree:
    return jax.tree_util.tree_map(lambda p, m: p * m.astype(p.dtype),
                                  params, masks)


def sparsity_ratio(params: Tree, masks: Tree) -> float:
    """Fraction of masked (zeroed) weights across prunable leaves."""
    zeros = total = 0
    for m in jax.tree_util.tree_leaves(masks):
        total += m.size
        zeros += int(m.size - jnp.sum(m))
    return zeros / max(total, 1)


class SparseOptimizer:
    """Wraps a FusedOptimizer so each step re-applies the masks — the
    reference patches ``optimizer.step`` (asp.py hooks); here the wrapper's
    step composes purely."""

    def __init__(self, inner, masks: Tree):
        self.inner = inner
        self.masks = masks

    def init(self, params):
        return self.inner.init(apply_masks(params, self.masks))

    def step(self, grads, params, state, **kw):
        # mask grads too so momentum doesn't resurrect pruned weights
        grads = apply_masks(grads, self.masks)
        new_p, new_s = self.inner.step(grads, params, state, **kw)
        return apply_masks(new_p, self.masks), new_s


class ASP:
    """API-shape parity with the reference ASP workflow (asp.py:21-…):

        asp = ASP()
        params, opt = asp.init_model_for_pruning(params, optimizer)
        ... train; masks persist via asp.state_dict() ...
    """

    def __init__(self, mask_calculator: Callable = m4n2_mask_1d,
                 allowed_layer_names: Optional[str] = None):
        self.pattern = mask_calculator
        self._name_re = (re.compile(allowed_layer_names)
                         if allowed_layer_names else None)
        self.masks: Optional[Tree] = None

    def _allowed(self, path, p):
        if self._name_re is not None:
            name = "/".join(str(getattr(x, "key", getattr(x, "name", x)))
                            for x in path)
            if not self._name_re.search(name):
                return False
        return _default_allowed(path, p)

    def init_model_for_pruning(self, params: Tree, optimizer=None):
        self.masks = compute_sparse_masks(params, self._allowed,
                                          self.pattern)
        pruned = apply_masks(params, self.masks)
        if optimizer is None:
            return pruned
        return pruned, SparseOptimizer(optimizer, self.masks)

    # checkpoint continuity (reference checkpointing_test_part1/2)
    def state_dict(self) -> dict:
        return {"masks": jax.device_get(self.masks)}

    def load_state_dict(self, d: dict) -> None:
        self.masks = jax.tree_util.tree_map(jnp.asarray, d["masks"])
