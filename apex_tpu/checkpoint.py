"""Checkpoint / resume — the TPU-native version of the reference's
checkpointing recipe (SURVEY.md §5.4).

The reference's documented flow (README.md "Checkpointing";
apex/amp/frontend.py:428-467) is::

    checkpoint = {'model': model.state_dict(),
                  'optimizer': optimizer.state_dict(),
                  'amp': amp.state_dict()}
    torch.save(checkpoint, 'amp_checkpoint.pt')
    # resume: amp.initialize with the SAME opt_level, then load all three

with two transparency guarantees: (1) O2/O5 checkpoints hold fp32 weights
even though the live model is half/bf16 (the ``O2StateDictHook`` recast,
apex/amp/_initialize.py:133-142), and (2) loss-scaler state
(``loss_scale``/``unskipped``) round-trips so resume is bitwise.

Here the whole training state is one pytree — params + AmpOptimizerState
(master fp32 weights, fused-optimizer moments, scaler state) + step — so a
single save captures everything, sharded arrays included:

  * :func:`save` / :func:`restore` — orbax-backed, async-capable, works for
    arrays sharded over a ``jax.sharding.Mesh`` (each host writes its
    addressable shards; the TPU analog of rank-0 torch.save).
  * :func:`save_npz` / :func:`restore_npz` — dependency-light single-host
    fallback mirroring the reference's optional-extension degradation.

The O2/O5 fp32 guarantee holds structurally: the master weights *are* the
fp32 copy inside ``AmpOptimizerState.master``, so checkpoints always carry
fp32 state with no recast hook needed. Exercised by
tests/test_checkpoint.py (the analog of tests/L0/run_amp/test_checkpointing.py).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import jax
import numpy as np

Tree = Any

#: npz member / orbax sidecar name carrying the optional layout
#: fingerprint (e.g. ``DistributedFusedAdam.layout_fingerprint``) — a plain
#: JSON dict of the facts that shaped any flat/sharded state in the tree.
LAYOUT_KEY = "__layout__"
_LAYOUT_SIDECAR = "apex_layout.json"

# orbax (and its tensorstore dependency) costs ~2s to import; load it only
# when an orbax-backed save/restore is actually requested so plain
# `import apex_tpu` stays fast.
_ocp = None


def _orbax():
    global _ocp
    if _ocp is None:
        try:
            import orbax.checkpoint as ocp
        except ImportError as e:
            raise ImportError(
                "orbax-checkpoint is not installed; use save_npz/restore_npz"
            ) from e
        _ocp = ocp
    return _ocp


def _checkpointer():
    return _orbax().PyTreeCheckpointer()


def _check_layout(saved: Optional[Dict[str, Any]],
                  expected: Dict[str, Any], path: str) -> None:
    """Fail fast — BEFORE any array is materialized — when a checkpoint's
    recorded layout fingerprint differs from the one the caller's live
    configuration would produce (different mesh size, ZeRO chunk
    resolution, leaf order...). Without this guard the failure surfaces as
    a shape mismatch deep in the restore machinery or, worse, a silently
    scrambled flat master."""
    if saved == expected:
        return
    if saved is None:
        hint = ("The checkpoint predates layout recording (no "
                "fingerprint saved); re-save it with layout=, or pass "
                "expected_layout=None to skip the check at your own "
                "risk.")
    else:
        # distinguish "re-shardable world mismatch" (same param tree,
        # different shard_count/chunk resolution — the state re-maps
        # deterministically) from "structurally incompatible tree"
        # (re-sharding cannot help) and print the RECIPE, not just the
        # fingerprints. Layouts that are not ZeRO fingerprints at all
        # (layout= accepts any JSON-able dict) keep the generic
        # message — claiming "different param tree" about them would
        # be a misdiagnosis.
        try:
            from apex_tpu.resilience import elastic as _elastic
            kind, reason = _elastic.classify_reshard(saved, expected)
            ok = kind == _elastic.RESHARDABLE
            structural = kind == _elastic.STRUCTURAL
        except Exception:   # never mask the mismatch with a helper bug
            ok, reason, structural = False, "", False
        if ok:
            src = saved.get("shard_count")
            dst = expected.get("shard_count")
            hint = (
                f"RE-SHARDABLE world mismatch: saved at world {src} "
                f"(chunk_elements {saved.get('chunk_elements')}), live "
                f"configuration expects world {dst} (chunk_elements "
                f"{expected.get('chunk_elements')}) over the SAME param "
                "tree. The state re-maps deterministically — resume "
                "with resilient_loop(..., elastic=resilience.Elastic("
                "opt, params)), or materialize it once with "
                "resilience.elastic.reshard_restore(manager, template, "
                "params=params, optimizer=opt). `python -m "
                f"apex_tpu.resilience inspect DIR --check {dst}` "
                "reports feasibility per generation.")
        elif structural:
            hint = (
                "STRUCTURALLY INCOMPATIBLE tree — " + reason + " — "
                "the checkpoint was written for a different param "
                "tree (not just a different world size), so an elastic "
                "re-shard cannot help. Re-create the optimizer/mesh "
                "with the saved configuration, or re-initialize state "
                "from params.")
        else:
            hint = (
                "The checkpoint was written under a different "
                "sharded-state layout (mesh size / chunk resolution / "
                "param tree) and would restore scrambled. Re-create "
                "the optimizer/mesh with the saved configuration, or "
                "re-initialize state from params.")
    raise ValueError(
        f"checkpoint layout fingerprint mismatch for {path}:\n"
        f"  expected: {expected}\n  found:    {saved}\n" + hint)


def save(path: str, train_state: Tree, *, force: bool = True,
         layout: Optional[Dict[str, Any]] = None) -> None:
    """Save a full training-state pytree (params, AmpOptimizerState, step,
    ...) to ``path``. Sharded ``jax.Array`` leaves are written distributed:
    every host persists its addressable shards.

    ``layout``: optional JSON-able layout fingerprint (e.g.
    ``zero_opt.layout_fingerprint(params)``) written as a sidecar inside
    the checkpoint directory; :func:`restore` validates it against
    ``expected_layout`` before materializing any array."""
    path = os.path.abspath(path)
    _checkpointer().save(path, train_state, force=force)
    if layout is not None:
        with open(os.path.join(path, _LAYOUT_SIDECAR), "w") as f:
            json.dump(layout, f, indent=1, sort_keys=True)


def restore(path: str, template: Optional[Tree] = None, *,
            expected_layout: Optional[Dict[str, Any]] = None) -> Tree:
    """Restore a pytree saved by :func:`save`.

    ``template`` (a pytree of like-structured arrays or
    ``jax.ShapeDtypeStruct`` with shardings) restores arrays directly onto
    their mesh shardings — resume does not need to fit the whole state on
    one host. Without it, leaves restore as host numpy arrays.

    ``expected_layout``: when given, the checkpoint's recorded layout
    sidecar must match it exactly — checked BEFORE any array bytes move,
    so restoring a checkpoint from a different mesh / ZeRO chunk
    resolution fails fast with both fingerprints in the message.
    """
    path = os.path.abspath(path)
    if expected_layout is not None:
        # distinguish "no checkpoint here at all" from "checkpoint with
        # no recorded layout" — the latter's fail-fast message would send
        # a user with a typo'd path off to debug layout recording
        if not os.path.isdir(path):
            raise FileNotFoundError(f"no checkpoint directory at {path}")
        saved = None
        sidecar = os.path.join(path, _LAYOUT_SIDECAR)
        if os.path.exists(sidecar):
            with open(sidecar) as f:
                saved = json.load(f)
        _check_layout(saved, expected_layout, path)
    if template is not None:
        ocp = _orbax()
        restore_args = jax.tree_util.tree_map(
            lambda x: ocp.ArrayRestoreArgs(sharding=x.sharding)
            if hasattr(x, "sharding") else ocp.RestoreArgs(), template)
        return _checkpointer().restore(
            path, args=ocp.args.PyTreeRestore(
                item=template,
                restore_args=restore_args))
    return _checkpointer().restore(path)


# ---------------------------------------------------------------------------
# npz fallback (single host, replicated state)
# ---------------------------------------------------------------------------

def _npz_path(path: str) -> str:
    # np.savez appends ".npz" to bare filenames; do it ourselves so the
    # tmp-write/replace and the reader agree on one final name.
    return path if str(path).endswith(".npz") else str(path) + ".npz"


def save_npz(path: str, train_state: Tree, *,
             layout: Optional[Dict[str, Any]] = None) -> None:
    """Single-host fallback: flatten the pytree to host numpy and write one
    ``.npz`` (the moral equivalent of the reference's ``torch.save``).

    Extension dtypes (bfloat16, fp8 — numpy kind 'V') don't survive the npz
    format, so they are widened to fp32 on disk; :func:`restore_npz` casts
    back to the template dtype. Widening is exact, so the round trip stays
    bitwise — the same fp32-on-disk convention as the reference's O2 hook.

    The write is atomic: bytes go to a same-directory temp file that is
    fsync'd and ``os.replace``'d onto the target, so a crash mid-write
    leaves either the previous complete checkpoint or nothing — never a
    truncated ``.npz`` that :func:`restore_npz` trips over later.

    ``layout``: optional JSON-able layout fingerprint stored inside the
    archive (see :data:`LAYOUT_KEY`); validated by ``restore_npz``'s
    ``expected_layout`` before arrays are materialized.
    """
    leaves, treedef = jax.tree_util.tree_flatten(train_state)
    arrays = {}
    for i, leaf in enumerate(leaves):
        arr = np.asarray(leaf)
        if arr.dtype.kind == "V":
            arr = arr.astype(np.float32)
        arrays[f"leaf_{i}"] = arr
    if layout is not None:
        arrays[LAYOUT_KEY] = np.frombuffer(
            json.dumps(layout, sort_keys=True).encode(), dtype=np.uint8)
    final = _npz_path(path)
    tmp = f"{final}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            np.savez(f, __structure__=np.frombuffer(
                _structure_key(train_state).encode(), dtype=np.uint8),
                **arrays)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, final)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _structure_key(tree: Tree) -> str:
    """Version-stable structure fingerprint: the flattened key paths (one
    per leaf, jax.tree_util.keystr) — unlike ``repr(PyTreeDef)``, this does
    not change with JAX's internal PyTreeDef rendering across releases."""
    paths = jax.tree_util.tree_flatten_with_path(tree)[0]
    return "\n".join(jax.tree_util.keystr(p) for p, _ in paths)


def _corrupt(path: str, what: str, e: Exception) -> ValueError:
    return ValueError(
        f"truncated or corrupt checkpoint: {path} ({what}: {e}). The file "
        "was most likely interrupted mid-write (pre-atomic-save era) or "
        "damaged on disk — fall back to an older snapshot generation or "
        "re-save; it cannot be loaded.")


def restore_npz(path: str, template: Tree, *,
                expected_layout: Optional[Dict[str, Any]] = None) -> Tree:
    """Restore an ``.npz`` checkpoint into the structure (and dtypes) of
    ``template`` — the same "re-initialize then load" contract as the
    reference's resume recipe.

    A truncated or otherwise unreadable file raises a clear
    "truncated or corrupt checkpoint" ``ValueError`` naming the file (not
    a bare zipfile/pickle error); ``expected_layout`` is validated
    against the archive's recorded fingerprint (see :func:`save_npz`)
    BEFORE any array is materialized."""
    final = _npz_path(path)
    try:
        data = np.load(final)
        members = set(data.files)  # forces the zip central directory read
    except Exception as e:  # BadZipFile / OSError / EOFError / ValueError
        if isinstance(e, FileNotFoundError):
            raise
        raise _corrupt(final, "unreadable archive", e) from e

    def member(name):
        try:
            return data[name]
        except KeyError:
            raise
        except Exception as e:  # truncated/corrupt member payload
            raise _corrupt(final, f"member {name!r} unreadable", e) from e

    if expected_layout is not None:
        saved_layout = (json.loads(bytes(member(LAYOUT_KEY)).decode())
                        if LAYOUT_KEY in members else None)
        _check_layout(saved_layout, expected_layout, final)
    leaves, treedef = jax.tree_util.tree_flatten(template)
    if "__structure__" not in members and "__treedef__" not in members:
        raise ValueError(
            f"{final} is a readable .npz but not an apex_tpu checkpoint "
            f"(no structure key; members: {sorted(members)[:8]})")
    key = "__structure__" if "__structure__" in members else "__treedef__"
    saved = bytes(member(key)).decode()
    expected = (_structure_key(template) if key == "__structure__"
                else repr(treedef))  # pre-rename checkpoints
    if saved != expected:
        raise ValueError(
            "checkpoint structure does not match the template (was it saved "
            "at a different opt level or with different param groups?):\n"
            f"  saved:    {saved}\n  template: {expected}\n"
            "Re-initialize with the same configuration before loading — the "
            "same contract as the reference's resume recipe.")
    new_leaves = []
    for i, leaf in enumerate(leaves):
        arr = member(f"leaf_{i}")
        if (hasattr(leaf, "shape")
                and tuple(arr.shape) != tuple(leaf.shape)):
            # The keystr fingerprint doesn't encode leaf shapes, so a
            # same-paths/different-shapes checkpoint must fail here, not
            # later at use.
            raise ValueError(
                f"checkpoint leaf {i} has shape {tuple(arr.shape)} but the "
                f"template expects {tuple(leaf.shape)} — the checkpoint was "
                "saved for a differently-shaped model.")
        if hasattr(leaf, "dtype"):
            arr = arr.astype(leaf.dtype)
        new_leaves.append(arr)
    return jax.tree_util.tree_unflatten(treedef, new_leaves)
