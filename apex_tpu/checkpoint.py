"""Checkpoint / resume — the TPU-native version of the reference's
checkpointing recipe (SURVEY.md §5.4).

The reference's documented flow (README.md "Checkpointing";
apex/amp/frontend.py:428-467) is::

    checkpoint = {'model': model.state_dict(),
                  'optimizer': optimizer.state_dict(),
                  'amp': amp.state_dict()}
    torch.save(checkpoint, 'amp_checkpoint.pt')
    # resume: amp.initialize with the SAME opt_level, then load all three

with two transparency guarantees: (1) O2/O5 checkpoints hold fp32 weights
even though the live model is half/bf16 (the ``O2StateDictHook`` recast,
apex/amp/_initialize.py:133-142), and (2) loss-scaler state
(``loss_scale``/``unskipped``) round-trips so resume is bitwise.

Here the whole training state is one pytree — params + AmpOptimizerState
(master fp32 weights, fused-optimizer moments, scaler state) + step — so a
single save captures everything, sharded arrays included:

  * :func:`save` / :func:`restore` — orbax-backed, async-capable, works for
    arrays sharded over a ``jax.sharding.Mesh`` (each host writes its
    addressable shards; the TPU analog of rank-0 torch.save).
  * :func:`save_npz` / :func:`restore_npz` — dependency-light single-host
    fallback mirroring the reference's optional-extension degradation.

The O2/O5 fp32 guarantee holds structurally: the master weights *are* the
fp32 copy inside ``AmpOptimizerState.master``, so checkpoints always carry
fp32 state with no recast hook needed. Exercised by
tests/test_checkpoint.py (the analog of tests/L0/run_amp/test_checkpointing.py).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np

Tree = Any

# orbax (and its tensorstore dependency) costs ~2s to import; load it only
# when an orbax-backed save/restore is actually requested so plain
# `import apex_tpu` stays fast.
_ocp = None


def _orbax():
    global _ocp
    if _ocp is None:
        try:
            import orbax.checkpoint as ocp
        except ImportError as e:
            raise ImportError(
                "orbax-checkpoint is not installed; use save_npz/restore_npz"
            ) from e
        _ocp = ocp
    return _ocp


def _checkpointer():
    return _orbax().PyTreeCheckpointer()


def save(path: str, train_state: Tree, *, force: bool = True) -> None:
    """Save a full training-state pytree (params, AmpOptimizerState, step,
    ...) to ``path``. Sharded ``jax.Array`` leaves are written distributed:
    every host persists its addressable shards."""
    _checkpointer().save(os.path.abspath(path), train_state, force=force)


def restore(path: str, template: Optional[Tree] = None) -> Tree:
    """Restore a pytree saved by :func:`save`.

    ``template`` (a pytree of like-structured arrays or
    ``jax.ShapeDtypeStruct`` with shardings) restores arrays directly onto
    their mesh shardings — resume does not need to fit the whole state on
    one host. Without it, leaves restore as host numpy arrays.
    """
    path = os.path.abspath(path)
    if template is not None:
        ocp = _orbax()
        restore_args = jax.tree_util.tree_map(
            lambda x: ocp.ArrayRestoreArgs(sharding=x.sharding)
            if hasattr(x, "sharding") else ocp.RestoreArgs(), template)
        return _checkpointer().restore(
            path, args=ocp.args.PyTreeRestore(
                item=template,
                restore_args=restore_args))
    return _checkpointer().restore(path)


# ---------------------------------------------------------------------------
# npz fallback (single host, replicated state)
# ---------------------------------------------------------------------------

def save_npz(path: str, train_state: Tree) -> None:
    """Single-host fallback: flatten the pytree to host numpy and write one
    ``.npz`` (the moral equivalent of the reference's ``torch.save``).

    Extension dtypes (bfloat16, fp8 — numpy kind 'V') don't survive the npz
    format, so they are widened to fp32 on disk; :func:`restore_npz` casts
    back to the template dtype. Widening is exact, so the round trip stays
    bitwise — the same fp32-on-disk convention as the reference's O2 hook.
    """
    leaves, treedef = jax.tree_util.tree_flatten(train_state)
    arrays = {}
    for i, leaf in enumerate(leaves):
        arr = np.asarray(leaf)
        if arr.dtype.kind == "V":
            arr = arr.astype(np.float32)
        arrays[f"leaf_{i}"] = arr
    np.savez(path, __structure__=np.frombuffer(
        _structure_key(train_state).encode(), dtype=np.uint8), **arrays)


def _structure_key(tree: Tree) -> str:
    """Version-stable structure fingerprint: the flattened key paths (one
    per leaf, jax.tree_util.keystr) — unlike ``repr(PyTreeDef)``, this does
    not change with JAX's internal PyTreeDef rendering across releases."""
    paths = jax.tree_util.tree_flatten_with_path(tree)[0]
    return "\n".join(jax.tree_util.keystr(p) for p, _ in paths)


def restore_npz(path: str, template: Tree) -> Tree:
    """Restore an ``.npz`` checkpoint into the structure (and dtypes) of
    ``template`` — the same "re-initialize then load" contract as the
    reference's resume recipe."""
    data = np.load(path if str(path).endswith(".npz") else str(path) + ".npz")
    leaves, treedef = jax.tree_util.tree_flatten(template)
    key = "__structure__" if "__structure__" in data else "__treedef__"
    saved = bytes(data[key]).decode()
    expected = (_structure_key(template) if key == "__structure__"
                else repr(treedef))  # pre-rename checkpoints
    if saved != expected:
        raise ValueError(
            "checkpoint structure does not match the template (was it saved "
            "at a different opt level or with different param groups?):\n"
            f"  saved:    {saved}\n  template: {expected}\n"
            "Re-initialize with the same configuration before loading — the "
            "same contract as the reference's resume recipe.")
    new_leaves = []
    for i, leaf in enumerate(leaves):
        arr = data[f"leaf_{i}"]
        if (hasattr(leaf, "shape")
                and tuple(arr.shape) != tuple(leaf.shape)):
            # The keystr fingerprint doesn't encode leaf shapes, so a
            # same-paths/different-shapes checkpoint must fail here, not
            # later at use.
            raise ValueError(
                f"checkpoint leaf {i} has shape {tuple(arr.shape)} but the "
                f"template expects {tuple(leaf.shape)} — the checkpoint was "
                "saved for a differently-shaped model.")
        if hasattr(leaf, "dtype"):
            arr = arr.astype(leaf.dtype)
        new_leaves.append(arr)
    return jax.tree_util.tree_unflatten(treedef, new_leaves)
