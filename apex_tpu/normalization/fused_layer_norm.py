"""FusedLayerNorm — parity with ``apex.normalization.FusedLayerNorm``
(apex/normalization/fused_layer_norm.py:12-165): a LayerNorm whose fwd/bwd
run as single fused kernels (Pallas on TPU; the reference used
``fused_layer_norm_cuda``), with a plain-XLA fallback exactly like the
reference's CPU fallback to ``F.layer_norm`` (:154-156).

``layer_norm`` is a ``jax.custom_vjp``: the Pallas backward consumes the
saved (mean, rstd) row statistics — same contract as the reference autograd
bridge (:12-62).
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import flax.linen as nn
import numpy as np

from apex_tpu.ops import pallas_layer_norm as _plln

Shape = Union[int, Sequence[int]]


def _norm_size(normalized_shape: Shape) -> int:
    if isinstance(normalized_shape, int):
        return normalized_shape
    return int(np.prod(tuple(normalized_shape)))


def _use_pallas(d: int, dtype=None) -> bool:
    import os
    # Mosaic has no f16: fp16 activations (amp O1/O2 interposition) ride
    # the XLA fallback, which is f32 internally anyway — the same policy
    # as ops/multi_tensor's fp16-routes-to-jnp (r4: surfaced by the
    # convergence gate's O1 GPT run; overrides APEX_TPU_MT_BACKEND=pallas)
    if dtype is not None and jnp.dtype(dtype) == jnp.float16 \
            and jax.default_backend() in ("tpu", "axon"):
        return False
    force = os.environ.get("APEX_TPU_MT_BACKEND", "auto")
    if force == "jnp":
        return False
    if not _plln.supported(d):
        return False
    if force == "pallas":
        return True
    return jax.default_backend() in ("tpu", "axon")


# -- functional, differentiable --------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _layer_norm_pallas(x2d, weight, bias, eps):
    y, _, _ = _plln.ln_fwd(x2d, weight, bias, eps)
    return y


def _ln_fwd_rule(x2d, weight, bias, eps):
    y, mu, rstd = _plln.ln_fwd(x2d, weight, bias, eps)
    return y, (x2d, weight, mu, rstd)


def _ln_bwd_rule(eps, res, dy):
    x2d, weight, mu, rstd = res
    dx, dw, db = _plln.ln_bwd(x2d, weight, mu, rstd, dy)
    return dx, dw.astype(weight.dtype), db.astype(weight.dtype)


_layer_norm_pallas.defvjp(_ln_fwd_rule, _ln_bwd_rule)


def layer_norm(x: jax.Array, weight: Optional[jax.Array] = None,
               bias: Optional[jax.Array] = None, *,
               normalized_shape: Optional[Shape] = None,
               eps: float = 1e-5) -> jax.Array:
    """Functional fused layer norm over the trailing ``normalized_shape``
    dims (defaults to the last dim). Affine params optional (the reference's
    non-affine variant, layer_norm_cuda.cpp)."""
    if normalized_shape is None:
        normalized_shape = x.shape[-1]
    d = _norm_size(normalized_shape)
    lead = x.shape[:x.ndim - (1 if isinstance(normalized_shape, int)
                              else len(tuple(normalized_shape)))]
    x2d = x.reshape(-1, d)
    w = (jnp.ones((d,), jnp.float32) if weight is None
         else weight.reshape(-1).astype(jnp.float32))
    b = (jnp.zeros((d,), jnp.float32) if bias is None
         else bias.reshape(-1).astype(jnp.float32))

    if _use_pallas(d, x2d.dtype):
        y2d = _layer_norm_pallas(x2d, w, b, eps)
    else:
        x32 = x2d.astype(jnp.float32)
        mu = jnp.mean(x32, axis=1, keepdims=True)
        xc = x32 - mu
        var = jnp.mean(xc * xc, axis=1, keepdims=True)
        y2d = (xc * jax.lax.rsqrt(var + eps) * w + b).astype(x2d.dtype)
    return y2d.reshape(x.shape)


class FusedLayerNorm(nn.Module):
    """Module parity with ``apex.normalization.FusedLayerNorm(normalized_
    shape, eps, elementwise_affine)``."""

    normalized_shape: Shape
    eps: float = 1e-5
    elementwise_affine: bool = True
    dtype: Any = None   # output dtype; None = input dtype

    @nn.compact
    def __call__(self, x):
        d = _norm_size(self.normalized_shape)
        if self.elementwise_affine:
            weight = self.param("weight", nn.initializers.ones, (d,),
                                jnp.float32)
            bias = self.param("bias", nn.initializers.zeros, (d,),
                              jnp.float32)
        else:
            weight = bias = None
        y = layer_norm(x, weight, bias,
                       normalized_shape=self.normalized_shape, eps=self.eps)
        return y.astype(self.dtype) if self.dtype is not None else y


class FusedRMSNorm(nn.Module):
    """RMSNorm sibling (no mean subtraction) — the modern LN variant; kept
    alongside for transformer models. Not in the reference (additive)."""

    normalized_shape: Shape
    eps: float = 1e-6
    elementwise_affine: bool = True

    @nn.compact
    def __call__(self, x):
        d = _norm_size(self.normalized_shape)
        x2d = x.reshape(-1, d).astype(jnp.float32)
        ms = jnp.mean(x2d * x2d, axis=1, keepdims=True)
        y = x2d * jax.lax.rsqrt(ms + self.eps)
        if self.elementwise_affine:
            weight = self.param("weight", nn.initializers.ones, (d,),
                                jnp.float32)
            y = y * weight
        return y.reshape(x.shape).astype(x.dtype)
