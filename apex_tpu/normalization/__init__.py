"""apex_tpu.normalization — fused normalization layers (reference
apex/normalization/)."""

from apex_tpu.normalization.fused_layer_norm import (
    FusedLayerNorm,
    FusedRMSNorm,
    layer_norm,
)
