"""apex_tpu.normalization (placeholder — populated incrementally)."""
