"""JAX version-compat shims.

The framework targets the current JAX surface (``jax.shard_map`` with
``check_vma=``, ``jax.lax.axis_size``); older releases (<= 0.4.x) ship the
same machinery under earlier names (``jax.experimental.shard_map`` with
``check_rep=``, no ``axis_size``). :func:`install` bridges the gap by
adding the modern names onto the ``jax`` namespace when — and only when —
they are missing, so the package, the graft entry, and the test suite run
unchanged on both. On a current JAX this is a no-op.

Installed automatically on ``import apex_tpu`` (and importable standalone
for scripts that touch ``jax.shard_map`` before the package: put
``import apex_tpu`` above ``from jax import shard_map``).
"""

from __future__ import annotations

import functools

import jax


def _axis_size(axis_name):
    """``jax.lax.axis_size`` for releases that predate it: the size of a
    bound named mesh axis is the concrete value of ``psum(1, axis)``."""
    try:
        return jax.lax.psum(1, axis_name)
    except NameError as e:
        # keep the modern API's error shape: unbound name -> NameError
        raise NameError(f"unbound axis name: {axis_name}") from e


def cost_analysis_value(cost, key: str, default=None):
    """Look up an XLA cost-analysis key accepting BOTH spellings.

    jax/jaxlib versions disagree on whether compiled cost-analysis keys
    use spaces or underscores ("bytes accessed" vs "bytes_accessed",
    "optimal_seconds" vs "optimal seconds"); a caller keying on one
    spelling silently reads None on the other. Returns whichever
    variant is present, else ``default``."""
    if not cost:
        return default
    if key in cost:
        return cost[key]
    alt = key.replace(" ", "_") if " " in key else key.replace("_", " ")
    return cost.get(alt, default)


def install() -> None:
    """Idempotently add missing modern-JAX names. Safe to call many times."""
    if not hasattr(jax.lax, "axis_size"):
        jax.lax.axis_size = _axis_size

    try:
        jax.enable_x64
    except AttributeError:
        # modern jax.enable_x64 is the old experimental context manager
        from jax.experimental import enable_x64
        jax.enable_x64 = enable_x64

    if not hasattr(jax, "shard_map"):
        from jax.experimental.shard_map import shard_map as _shard_map

        @functools.wraps(_shard_map)
        def shard_map(f, *args, **kwargs):
            # modern spelling of the replication check is check_vma;
            # 0.4.x calls it check_rep
            if "check_vma" in kwargs:
                kwargs["check_rep"] = kwargs.pop("check_vma")
            return _shard_map(f, *args, **kwargs)

        jax.shard_map = shard_map


install()
