"""Model description — everything the analytic cost model needs to
price a layout WITHOUT building it.

A :class:`ModelDesc` is produced once per ``plan.auto`` call by the
model adapter (:mod:`apex_tpu.plan.adapters`): parameter counts come
from ``jax.eval_shape`` over ``model.init`` (nothing executes), and the
whole-step FLOP/byte totals come from XLA's own cost analysis of a
single-device reference step (:func:`apex_tpu.pyprof.prof.analyze` —
the same numbers pyprof's roofline verdicts use). Every candidate's
compute/memory floor is then a scaling of these totals; the exact
per-layout communication bill comes from the :mod:`telemetry.comm`
jaxpr walker when the candidate is actually traced (the validate tier).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np

Tree = Any


def tree_bytes(tree: Tree) -> int:
    """Total bytes of a pytree of arrays/ShapeDtypeStructs."""
    from apex_tpu.utils.jaxpr_walk import aval_bytes
    return sum(aval_bytes(leaf)
               for leaf in jax.tree_util.tree_leaves(tree))


def tree_count(tree: Tree) -> int:
    """Total element count of a pytree of arrays/ShapeDtypeStructs."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = getattr(leaf, "shape", ())
        total += int(np.prod(shape, dtype=np.int64) if shape else 1)
    return total


@dataclasses.dataclass
class ModelDesc:
    """The cost model's view of one (model, workload) pair.

    flops_per_step / bytes_per_step:
        Whole-step totals (fwd + bwd + optimizer) for the GLOBAL batch
        on ONE device, from XLA cost analysis of the adapter's
        single-device reference step. A candidate's per-device floor is
        these totals divided by its model-parallel degree and batch
        shards (documented approximation: tensor/sequence/pipeline
        parallelism divide the matmul-dominated totals near-linearly;
        the traced tier re-checks the winner's program for real).
    act_bytes_per_sample:
        Activation footprint per sample at the FULL sequence length, in
        the compute dtype — the HBM-feasibility term that microbatching
        divides. A documented estimate (transformer: ~12 activations of
        (S, E) per block + logits; resnet: stage feature maps), not a
        compiled-program claim.
    opt_state_bytes:
        Unsharded fp32 optimizer footprint (master + both Adam
        moments); ZeRO divides it by the shard count.
    dims:
        Model-family dimensions for the pruner's divisibility checks
        (``batch``, ``seq``, ``heads``, ``embed``, ``layers``,
        ``vocab``, ``mlp_width`` for GPT; ``batch``, ``image``,
        ``classes`` for resnet).
    """

    name: str
    param_count: int
    param_bytes: int
    flops_per_step: float
    bytes_per_step: float
    act_bytes_per_sample: float
    opt_state_bytes: int
    dims: Dict[str, int]
    grad_itemsize: int = 4        # fp32 gradients everywhere today

    def to_meta(self) -> Dict[str, Any]:
        return {"name": self.name, "param_count": int(self.param_count),
                "flops_per_step": float(self.flops_per_step),
                "dims": dict(self.dims)}


def reference_cost(step_fn: Callable, *args) -> Dict[str, Optional[float]]:
    """XLA cost analysis of the adapter's single-device reference step
    (one compile; avals suffice — nothing executes). Returns the
    :func:`~apex_tpu.pyprof.prof.analyze` dict; ``flops``/
    ``bytes_accessed`` may be None on backends whose cost analysis is
    silent — the adapter then falls back to its analytic formula."""
    from apex_tpu.pyprof import prof
    return prof.analyze(step_fn, *args)


def transformer_flops(*, batch: int, seq: int, embed: int, layers: int,
                      vocab: int, mlp_ratio: int = 4) -> float:
    """Analytic fwd+bwd FLOPs for one decoder-LM step (the standard
    6·N·T estimate plus the quadratic attention term and the LM head)
    — the fallback when XLA cost analysis reports nothing."""
    tokens = batch * seq
    block_params = 12 * embed * embed * (1 + mlp_ratio) / 5  # qkv+o+mlp
    n_block = layers * block_params * 5
    matmul = 6.0 * tokens * (n_block + embed * vocab)
    attn = 6.0 * layers * batch * seq * seq * embed * 2 / 2
    return matmul + attn


def resnet_flops(*, batch: int, image: int) -> float:
    """Analytic fwd+bwd FLOPs for a ResNet-18-family step at ``image``
    resolution (scaled from the canonical 1.8 GFLOP @224 forward)."""
    fwd = 1.8e9 * (image / 224.0) ** 2
    return 3.0 * batch * fwd
