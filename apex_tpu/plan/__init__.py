"""``apex_tpu.plan`` — cost-model-driven automatic parallelism planner
(ROADMAP item 2; AMP-style strategy search, arXiv 2210.07297; automatic
cross-replica weight-update sharding, arXiv 2004.13336).

The multichip dryrun proves the layout FAMILIES work; this package
picks one — plus microbatch, ZeRO stage, bucket capacities, and
reduce_dtype — and emits a ready-to-train package::

    from apex_tpu import plan
    p = plan.auto(plan.GPTAdapter(batch=16, seq=256))
    tr = p.build_trainer()                  # PR 9 compiled trainer
    state = tr.run(p.init_state(), p.batch_fn, steps=100)

Three tiers (see the module docs):

  * :mod:`~apex_tpu.plan.cost` — the analytic cost model: wire bytes
    (telemetry.comm's jaxpr walker for traced candidates, matching
    closed forms for the full space), compute/memory floors (pyprof
    roofline peaks + XLA cost analysis), HBM footprint, PR 6 overlap
    credit.
  * :mod:`~apex_tpu.plan.search` — enumerate/prune/rank over (dp, tp,
    pp, seq, zero, microbatch, buckets, reduce_dtype); top-k validated
    by tracing (and on-device measurement on TPU — policy-gated,
    hermetic off-TPU).
  * :mod:`~apex_tpu.plan.emit` — TrainerConfig + shard_map layout +
    tune cache entries (``"planner"`` provenance), every emission
    verified by ``lint.spmd`` (APX201-208) first.

CLI: ``python -m apex_tpu.plan auto|explain`` (docs/plan.md).
"""

from apex_tpu.plan.adapters import (ADAPTERS, Built, GPTAdapter,
                                    ResNetAdapter, get_adapter)
from apex_tpu.plan.cost import (CostBreakdown, HeteroCost, WireItem,
                                analytic_wire, estimate, hbm_footprint,
                                heterogeneous_step_s, member_speeds,
                                optimal_weights, plan_hbm_tolerance_pct,
                                traced_wire)
from apex_tpu.plan.describe import ModelDesc
from apex_tpu.plan.emit import Plan, PlanRejected, emit, format_table, \
    verify_built
from apex_tpu.plan.layout import Layout, parse_layout_id
from apex_tpu.plan.search import (Constraints, PlanError, Verdict, auto,
                                  enumerate_candidates, estimate_layout,
                                  prune, rank, replanner)

__all__ = [
    "auto", "estimate", "estimate_layout", "enumerate_candidates",
    "prune", "rank", "replanner", "analytic_wire", "traced_wire",
    "hbm_footprint", "plan_hbm_tolerance_pct", "emit", "verify_built",
    "format_table",
    "Layout", "parse_layout_id", "Constraints", "Verdict", "Plan",
    "PlanError", "PlanRejected", "CostBreakdown", "HeteroCost",
    "WireItem", "heterogeneous_step_s", "member_speeds",
    "optimal_weights", "ModelDesc", "Built", "GPTAdapter",
    "ResNetAdapter", "get_adapter", "ADAPTERS",
]
