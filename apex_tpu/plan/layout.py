"""Candidate parallelism layouts — the planner's search coordinates.

A :class:`Layout` names one point in the space the ROADMAP item-2 search
covers: the mesh factorization (dp x tp x pp x seq), the ZeRO stage, the
microbatch (gradient-accumulation) count, the gradient-collective bucket
capacities, and the wire dtype. It is deliberately a frozen value type:
the cost model prices it, the pruner vetoes it, the emitter builds a
real step from it — none of them mutate it.

The mesh axis names follow the multichip dryrun conventions
(``__graft_entry__.py``): ``data`` (batch shards / ZeRO shards),
``model`` (Megatron tensor parallel), ``pipe`` (GPipe stages), ``seq``
(ring/Ulysses sequence shards).
"""

from __future__ import annotations

import dataclasses
import re
from typing import List, Optional, Tuple

REDUCE_DTYPES = (None, "bf16", "fp16", "int8")
SEQ_IMPLS = ("ring", "ulysses")

# ZeRO stages the toolkit implements: 0 = replicated optimizer state
# (DDP + FusedAdam), 2 = DistributedFusedAdam (fp32 master + both Adam
# moments sharded over the data axis, grads reduce-scattered). Stages
# 1/3 are not built; the enumerator never emits them.
ZERO_STAGES = (0, 2)


@dataclasses.dataclass(frozen=True)
class Layout:
    """One parallelism candidate. ``dp*tp*pp*seq`` must equal the device
    count; knobs that do not apply to a family stay at their defaults
    (the enumerator only produces meaningful combinations, and
    :meth:`validate` rejects contradictory ones loudly)."""

    dp: int = 1
    tp: int = 1
    pp: int = 1
    seq: int = 1
    zero: int = 0                        # ZERO_STAGES
    microbatch: int = 1                  # grad-accumulation chunks
    reduce_dtype: Optional[str] = None   # wire dtype for grad collectives
    fp8: bool = False                    # lowp O6 fp8 compute tier
    overlap: bool = True                 # stage dp collectives in backward
    seq_impl: str = "ring"               # when seq > 1
    # planner-resolved bucket capacities (elements); None = the tune
    # heuristic. These are what the emitter writes into the tune cache
    # with "planner" provenance.
    ddp_bucket: Optional[int] = None
    zero_chunk: Optional[int] = None

    def __post_init__(self):
        self.validate()

    # -- identity ----------------------------------------------------------
    @property
    def world(self) -> int:
        return self.dp * self.tp * self.pp * self.seq

    def family(self) -> str:
        """Human name of the layout family (the dryrun part names)."""
        parts = []
        if self.zero:
            parts.append(f"zero{self.zero}")
        elif self.dp > 1 or not parts:
            parts.append("dp")
        if self.tp > 1:
            parts.append("tp")
        if self.seq > 1:
            parts.append(self.seq_impl)
        if self.pp > 1:
            # schedule-agnostic: the pp axis runs 1F1B by default
            # (APEX_TPU_PP_SCHEDULE=gpipe flips), same wire/bubble bill
            parts.append("pipe")
        return "x".join(parts)

    def layout_id(self) -> str:
        """Stable parseable id, e.g. ``dp4-tp2``, ``dp8-zero2-mb2-bf16``.
        Round-trips through :func:`parse_layout_id`."""
        bits = [f"dp{self.dp}"]
        if self.tp > 1:
            bits.append(f"tp{self.tp}")
        if self.pp > 1:
            bits.append(f"pp{self.pp}")
        if self.seq > 1:
            tag = "sq" if self.seq_impl == "ring" else "uly"
            bits.append(f"{tag}{self.seq}")
        if self.zero:
            bits.append(f"zero{self.zero}")
        if self.microbatch > 1:
            bits.append(f"mb{self.microbatch}")
        if self.reduce_dtype:
            bits.append(self.reduce_dtype)
        if self.fp8:
            bits.append("fp8")
        if not self.overlap:
            bits.append("noov")
        return "-".join(bits)

    # -- mesh --------------------------------------------------------------
    def mesh_axes(self) -> List[Tuple[str, int]]:
        """Ordered (name, size) pairs for :func:`apex_tpu.parallel.mesh.
        named_mesh` — slower-varying (DCN-friendly) axes first, the
        bandwidth-hungry tp/seq axes last (ICI neighbors), matching
        :func:`~apex_tpu.parallel.mesh.hybrid_mesh` guidance."""
        axes: List[Tuple[str, int]] = [("data", self.dp)]
        if self.pp > 1:
            axes.append(("pipe", self.pp))
        if self.seq > 1:
            axes.append(("seq", self.seq))
        if self.tp > 1:
            axes.append(("model", self.tp))
        return axes

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["id"] = self.layout_id()
        d["family"] = self.family()
        return d

    # -- validation --------------------------------------------------------
    def validate(self) -> None:
        """Structural sanity — raises ``ValueError`` naming the offending
        knob. Model-shape feasibility (divisibility, HBM) is the
        pruner's job (:func:`apex_tpu.plan.search.prune`); this catches
        layouts that are contradictory for EVERY model."""
        for name in ("dp", "tp", "pp", "seq", "microbatch"):
            v = getattr(self, name)
            if not isinstance(v, int) or v < 1:
                raise ValueError(f"Layout.{name} must be an int >= 1, "
                                 f"got {v!r}")
        if self.zero not in ZERO_STAGES:
            raise ValueError(
                f"Layout.zero must be one of {ZERO_STAGES} (the stages "
                f"the toolkit implements), got {self.zero!r}")
        if self.reduce_dtype not in REDUCE_DTYPES:
            raise ValueError(
                f"Layout.reduce_dtype must be one of {REDUCE_DTYPES}, "
                f"got {self.reduce_dtype!r}")
        if self.seq_impl not in SEQ_IMPLS:
            raise ValueError(
                f"Layout.seq_impl must be one of {SEQ_IMPLS}, "
                f"got {self.seq_impl!r}")
        if not isinstance(self.fp8, bool):
            raise ValueError(
                f"Layout.fp8 must be a bool (the lowp O6 compute "
                f"tier), got {self.fp8!r}")
        if self.zero and self.dp < 2:
            raise ValueError(
                "ZeRO shards optimizer state over the data axis — "
                f"zero={self.zero} requires dp >= 2, got dp={self.dp}")
        if self.zero and self.tp > 1:
            raise ValueError(
                "zero + tensor parallelism is not a supported "
                "composition (ZeRO's flat layout assumes replicated "
                "params over the data axis; TP shards them)")
        if self.tp > 1 and self.seq > 1:
            raise ValueError(
                "tp + sequence parallelism in one layout is not a "
                "supported composition (attention cannot shard heads "
                "over two axes at once)")
        for cap_name in ("ddp_bucket", "zero_chunk"):
            cap = getattr(self, cap_name)
            if cap is not None and (not isinstance(cap, int) or cap < 1):
                raise ValueError(
                    f"Layout.{cap_name} must be a positive element "
                    f"count or None (tune heuristic), got {cap!r}")


_ID_RE = re.compile(
    r"^dp(?P<dp>\d+)"
    r"(?:-tp(?P<tp>\d+))?"
    r"(?:-pp(?P<pp>\d+))?"
    r"(?:-(?P<seqtag>sq|uly)(?P<seq>\d+))?"
    r"(?:-zero(?P<zero>\d+))?"
    r"(?:-mb(?P<mb>\d+))?"
    r"(?:-(?P<rd>bf16|fp16|int8))?"
    r"(?:-(?P<fp8>fp8))?"
    r"(?:-(?P<noov>noov))?$")


def parse_layout_id(s: str) -> Layout:
    """Inverse of :meth:`Layout.layout_id` (the CLI's ``explain <pick>``
    argument). Raises ``ValueError`` with the grammar on mismatch."""
    m = _ID_RE.match(s.strip())
    if m is None:
        raise ValueError(
            f"unparseable layout id {s!r}; expected e.g. 'dp8', "
            "'dp4-tp2', 'dp8-zero2-mb2-bf16', 'dp2-sq4' "
            "(grammar: dpN[-tpN][-ppN][-sqN|-ulyN][-zeroN][-mbN]"
            "[-bf16|-fp16|-int8][-fp8][-noov])")
    g = m.groupdict()
    return Layout(
        dp=int(g["dp"]), tp=int(g["tp"] or 1), pp=int(g["pp"] or 1),
        seq=int(g["seq"] or 1), zero=int(g["zero"] or 0),
        microbatch=int(g["mb"] or 1), reduce_dtype=g["rd"],
        fp8=g["fp8"] is not None,
        overlap=g["noov"] is None,
        seq_impl=("ulysses" if g["seqtag"] == "uly" else "ring"))
