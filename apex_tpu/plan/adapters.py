"""Model adapters — the hop from a :class:`~apex_tpu.plan.layout.Layout`
to a REAL per-device step function plus everything the trainer builder,
the lint SPMD verifier, and the comm walker need to consume it.

Each adapter owns one model family and knows (a) how to describe it to
the analytic cost model (:meth:`describe`), (b) which layouts it can
actually build (:meth:`veto` — a named reason, never a silent skip), and
(c) how to build the candidate step (:meth:`build` → :class:`Built`).

The built step follows the PR 9 trainer convention — ``(state, batch) ->
(new_state, aux)`` with per-device semantics under ``shard_map`` — so
``Plan.build_trainer`` can hand it straight to ``trainer.build`` and the
3-step CI train is the same code path a user gets.

``build`` itself touches ONLY avals (``jax.eval_shape`` over the model
init): the planner traces/verifies every top_k candidate, and at real
sizes a concrete seeded param init per candidate is real memory + time
the search never uses. Concrete materialization is deferred to
``Built.init_state`` — the winner's, called once by
``Plan.build_trainer`` — which also makes every ``init_state()`` call
donation-safe by construction (fresh buffers each time).

Supported families (the ones the multichip dryrun proves AND the step
builder can emit end to end):

  * GPT:    dp, dp+ZeRO-2, dp x tp (Megatron), dp x seq (ring/Ulysses),
            dp x pp (GPipe/1F1B timetable pipeline)
  * ResNet: dp (SyncBN), dp+ZeRO-2

Pipeline (pp>1) layouts BUILD for GPT: the block stack shards its stage
dim over ``pipe`` and the step runs the
:mod:`apex_tpu.parallel.pipeline_schedule` timetable executor — 1F1B by
default, ``APEX_TPU_PP_SCHEDULE=gpipe`` flips, both bitwise-equal to
the single-stage accumulation baseline. pp composes with dp only; the
unbuilt compositions (pp x tp/seq, pp + ZeRO, pp + reduce_dtype) keep
named vetoes below (loud-failure doctrine — the emitter never pretends
to build what it cannot).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

import apex_tpu._compat  # noqa: F401  (jax.shard_map shim)
from jax import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu.plan.describe import (ModelDesc, reference_cost,
                                    resnet_flops, transformer_flops,
                                    tree_bytes, tree_count)
from apex_tpu.plan.layout import Layout

Tree = Any

# activation-footprint factor per transformer block: ~the count of
# (tokens, embed)-sized intermediates the backward keeps live without
# remat (qkv, attn out, 2 LN, 2 residual, mlp hidden at ratio 4 counts
# as 4, gelu). An estimate for HBM feasibility, not a compiled claim.
GPT_ACT_FACTOR = 14


@dataclasses.dataclass
class Built:
    """One buildable candidate: the per-device step plus its mesh/spec
    wiring and example avals. ``wrapped`` is the shard_map-wrapped form
    of ``step`` — the single program the SPMD verifier and the comm
    walker analyze (trace-only; nothing executes until
    ``Plan.build_trainer`` compiles it)."""

    layout: Layout
    mesh: Any
    step: Callable                   # per-device (state, batch) -> ...
    wrapped: Callable                # shard_map(step) — analysis target
    state_spec: Any
    batch_spec: Any
    state_avals: Tree
    batch_avals: Tree
    init_state: Callable[[], Tree]   # real arrays, device_put sharded
    batch_fn: Callable[[int], Tree]  # deterministic host batches
    axis_sizes: dict                 # {"data": 4, "model": 2, ...}

    @property
    def mesh_axis_names(self) -> Tuple[str, ...]:
        return tuple(self.axis_sizes)


def _wrap(step: Callable, mesh, state_spec, batch_spec) -> Callable:
    return shard_map(step, mesh=mesh,
                     in_specs=(state_spec, batch_spec),
                     out_specs=(state_spec, P()), check_vma=False)


# ONE definition of microbatch gradient accumulation: the pipeline
# module owns it (its pp=1 fallback IS this function — the jaxpr-
# equality pin that makes pp an inert-default axis holds by
# construction), the step builders here delegate.
from apex_tpu.parallel.pipeline_schedule import (  # noqa: E402
    accumulate_grads as _accumulate)


class GPTAdapter:
    """Decoder-LM adapter over :class:`apex_tpu.models.TransformerLM`.

    ``batch`` is the GLOBAL batch (a workload constant the search never
    changes — dp shards it, microbatch accumulates it); ``seq`` is the
    global sequence length (the seq axis shards it)."""

    name = "gpt"

    def __init__(self, *, vocab: int = 256, layers: int = 2,
                 embed: int = 128, heads: int = 4, batch: int = 16,
                 seq: int = 128, mlp_ratio: int = 4, lr: float = 1e-3,
                 seed: int = 0):
        self.vocab, self.layers, self.embed = vocab, layers, embed
        self.heads, self.batch, self.seq = heads, batch, seq
        self.mlp_ratio, self.lr, self.seed = mlp_ratio, lr, seed

    # -- model building blocks --------------------------------------------
    def _dense_model(self, **over):
        from apex_tpu.models import TransformerLM
        kw = dict(vocab_size=self.vocab, num_layers=self.layers,
                  embed_dim=self.embed, num_heads=self.heads,
                  max_seq=self.seq, mlp_ratio=self.mlp_ratio)
        kw.update(over)
        return TransformerLM(**kw)

    def _dense_params_sds(self):
        # per-instance memo (an lru_cache on the method would pin every
        # adapter instance in a class-global cache for the process
        # lifetime — shape sweeps construct many)
        if not hasattr(self, "_params_sds_memo"):
            model = self._dense_model()
            toks = jax.ShapeDtypeStruct((1, self.seq), jnp.int32)
            vs = jax.eval_shape(
                lambda t: model.init(jax.random.PRNGKey(0), t), toks)
            self._params_sds_memo = vs["params"]
        return self._params_sds_memo

    def _dense_params(self):
        model = self._dense_model()
        toks = jnp.zeros((1, self.seq), jnp.int32)
        return model.init(jax.random.PRNGKey(self.seed), toks)["params"]

    # -- describe ----------------------------------------------------------
    def describe(self, *, compile_reference: bool = True) -> ModelDesc:
        """One :class:`ModelDesc` per auto() call. ``compile_reference``
        prices the whole step with XLA cost analysis (one single-device
        compile); False falls back to the analytic transformer formula
        (the CLI's --no-compile fast path and the replan seam, where a
        compile per membership change would be a regression)."""
        p_sds = self._dense_params_sds()
        n_params = tree_count(p_sds)
        p_bytes = tree_bytes(p_sds)
        flops = nbytes = None
        if compile_reference:
            from apex_tpu import optimizers
            model = self._dense_model()
            opt = optimizers.FusedAdam(lr=self.lr)

            def ref_step(params, opt_state, toks):
                from apex_tpu.models.gpt import next_token_loss

                def loss_of(p):
                    return next_token_loss(
                        model.apply({"params": p}, toks), toks)

                loss, g = jax.value_and_grad(loss_of)(params)
                new_p, new_s = opt.step(g, params, opt_state)
                return new_p, new_s, loss

            st_sds = jax.eval_shape(opt.init, p_sds)
            toks = jax.ShapeDtypeStruct((self.batch, self.seq), jnp.int32)
            cost = reference_cost(ref_step, p_sds, st_sds, toks)
            flops, nbytes = cost.get("flops"), cost.get("bytes_accessed")
        if not flops:
            flops = transformer_flops(
                batch=self.batch, seq=self.seq, embed=self.embed,
                layers=self.layers, vocab=self.vocab,
                mlp_ratio=self.mlp_ratio)
        if not nbytes:
            # every param read+written thrice (grad, moments, update)
            # plus one activation sweep — bandwidth floor fallback
            nbytes = 6.0 * p_bytes + 2.0 * self.batch * (
                self._act_bytes_per_sample())
        return ModelDesc(
            name=self.name, param_count=n_params, param_bytes=p_bytes,
            flops_per_step=float(flops), bytes_per_step=float(nbytes),
            act_bytes_per_sample=self._act_bytes_per_sample(),
            opt_state_bytes=8 * n_params,
            dims={"batch": self.batch, "seq": self.seq,
                  "heads": self.heads, "embed": self.embed,
                  "layers": self.layers, "vocab": self.vocab,
                  "mlp_width": self.mlp_ratio * self.embed,
                  # params tensor parallelism CANNOT shard (embeddings,
                  # LM head, LayerNorms, row-parallel biases) — the part
                  # of the dp grad psum that stays full-size under tp
                  # (cost.analytic_wire; within 0.1% of the traced bill)
                  "tp_replicated": (2 * self.vocab * self.embed
                                    + self.seq * self.embed + self.vocab
                                    + 6 * self.embed * self.layers
                                    + 2 * self.embed),
                  # params the pipeline CANNOT stage (embeddings, final
                  # norm, LM head) — the stage-disjoint "rest" tree
                  # that psums over pipe and stays full-size in the dp
                  # grad sync (unlike tp_replicated this EXCLUDES the
                  # per-block LN/bias leaves: those ride the stacked
                  # stage shard under pp)
                  "pp_rest": (2 * self.vocab * self.embed
                              + self.seq * self.embed + self.vocab
                              + 2 * self.embed)})

    def _act_bytes_per_sample(self) -> float:
        per_block = GPT_ACT_FACTOR * self.seq * self.embed * 4
        logits = self.seq * self.vocab * 4
        return float(self.layers * per_block + logits
                     + self.seq * self.embed * 4)

    # -- feasibility -------------------------------------------------------
    def veto(self, layout: Layout) -> Optional[str]:
        """Build-capability veto — a named reason, or None when
        :meth:`build` can emit this layout. Shape divisibility is the
        pruner's job; this is about what the step builder implements."""
        if layout.pp > 1:
            if layout.tp > 1 or layout.seq > 1:
                return ("pipeline composes with dp only — pp x tp / "
                        "pp x seq would need the per-block tp/seq "
                        "collectives rescoped under the stage scan; "
                        "not built")
            if layout.zero:
                return ("ZeRO's flat optimizer layout shards over "
                        "data and assumes replicated params; the "
                        "pipeline's stage-sharded stack would need a "
                        "pipe-aware flat layout — not built")
            if layout.reduce_dtype:
                return ("reduce_dtype rides the DDP bucketed-allreduce "
                        "seam; pipeline layouts sync grads with plain "
                        "collectives")
        if layout.microbatch > 1 and (layout.tp > 1 or layout.seq > 1):
            return ("microbatch accumulation is built for dp/zero "
                    "layouts only")
        if layout.reduce_dtype and (layout.tp > 1 or layout.seq > 1):
            # tp/seq steps use scope-free plain collectives (arming the
            # apex_ddp_allreduce seam would make every per-layer tp/seq
            # collective an APX206 finding); the compressed wire rides
            # that seam, so it is not available here — loudly.
            return ("reduce_dtype rides the DDP bucketed-allreduce "
                    "seam; tp/seq layouts use plain collectives")
        if layout.fp8:
            # the cost model prices the tier (Constraints.fp8_modes),
            # but emitting it needs lowp.fp8_autocast + delayed-scaling
            # state threaded through the reference step — not built;
            # pricing a layout we would then build WITHOUT fp8 would
            # make the traced tier dishonest
            return ("fp8 compute tier (amp O6) is not threaded through "
                    "the reference step builder — rank it analytically "
                    "or wire lowp.fp8_autocast into your own step")
        return None

    # -- build -------------------------------------------------------------
    def build(self, layout: Layout, devices=None) -> Built:
        veto = self.veto(layout)
        if veto is not None:
            raise ValueError(
                f"cannot build layout {layout.layout_id()}: {veto}")
        from apex_tpu.parallel.mesh import named_mesh
        mesh = named_mesh(layout.mesh_axes(), devices=devices)
        axis_sizes = dict(zip(mesh.axis_names,
                              (int(s) for s in mesh.devices.shape)))
        if layout.pp > 1:
            return self._build_pp(layout, mesh, axis_sizes)
        if layout.tp > 1:
            return self._build_tp(layout, mesh, axis_sizes)
        if layout.seq > 1:
            return self._build_seq(layout, mesh, axis_sizes)
        return self._build_dp(layout, mesh, axis_sizes)

    def _batch_fn(self, shape):
        vocab = self.vocab

        def make(i: int):
            rng = np.random.default_rng(10_000 + i)
            return jnp.asarray(
                rng.integers(0, vocab, shape, dtype=np.int32))
        return make

    def _build_dp(self, layout: Layout, mesh, axis_sizes) -> Built:
        """dp / dp+ZeRO-2: batch shards over ``data``; grads sync via the
        bucketed allreduce (post-hoc, or staged into backward when
        ``layout.overlap`` and mb==1) or via ZeRO's reduce-scatter."""
        from apex_tpu import optimizers, parallel
        from apex_tpu.models.gpt import next_token_loss
        from apex_tpu.tune import heuristics as _h

        model = self._dense_model()
        mb = layout.microbatch
        bucket = layout.ddp_bucket or _h.DDP_MESSAGE_SIZE
        staged = (layout.zero == 0 and layout.overlap and mb == 1)
        ddp = None
        if staged or (layout.reduce_dtype and not layout.zero):
            # zero layouts compress on their own reduce-scatter path
            # (DistributedFusedAdam gets reduce_dtype below) — a DDP
            # object would be dead weight there
            ddp = parallel.DistributedDataParallel(
                "data", overlap=staged, message_size=bucket,
                reduce_dtype=layout.reduce_dtype)
        if layout.zero:
            from apex_tpu.contrib.optimizers import DistributedFusedAdam
            opt = DistributedFusedAdam(
                lr=self.lr, axis_name="data", shard_count=layout.dp,
                chunk_elements=layout.zero_chunk
                or _h.ZERO_CHUNK_ELEMENTS,
                reduce_dtype=layout.reduce_dtype)
        else:
            opt = optimizers.FusedAdam(lr=self.lr)

        def step(state, batch):
            params, opt_state = state

            def loss_of(p, t):
                if ddp is not None and ddp.overlap:
                    p = ddp.prepare(p)
                return next_token_loss(
                    model.apply({"params": p}, t), t)

            loss, grads = _accumulate(loss_of, params, batch, mb)
            if layout.zero:
                # no pre-reduction: the ZeRO step's psum_scatter IS the
                # cross-device mean+shard (dryrun part 1 convention)
                new_p, new_o = opt.step(grads, params, opt_state)
            else:
                if ddp is None:
                    grads = parallel.allreduce_gradients(
                        grads, "data", message_size=bucket)
                elif not ddp.overlap:
                    grads = ddp.sync(grads)
                new_p, new_o = opt.step(grads, params, opt_state)
            return (new_p, new_o), jax.lax.pmean(loss, "data")

        # avals only — build() is called for every top_k candidate; the
        # concrete (seeded) param init is DEFERRED to the winner's
        # init_state (ROADMAP item 2: the trace tier must not pay
        # top_k full param inits it never uses)
        params_sds = self._dense_params_sds()
        if layout.zero:
            state_spec = (P(), opt.state_pspec())
        else:
            state_spec = (P(), type(jax.eval_shape(
                opt.init, params_sds))(
                step=P(), exp_avg=P(), exp_avg_sq=P()))
        batch_spec = P("data")

        def init_state():
            p = self._dense_params()   # fresh buffers every call
            opt_state = opt.init(p)
            if layout.zero:
                opt_state = jax.device_put(
                    opt_state, jax.tree_util.tree_map(
                        lambda sp: NamedSharding(mesh, sp),
                        opt.state_pspec()))
            return (p, opt_state)

        st_avals = (params_sds, jax.eval_shape(opt.init, params_sds))
        toks_shape = (self.batch, self.seq)
        batch_avals = jax.ShapeDtypeStruct(toks_shape, jnp.int32)
        return Built(
            layout=layout, mesh=mesh, step=step,
            wrapped=_wrap(step, mesh, state_spec, batch_spec),
            state_spec=state_spec, batch_spec=batch_spec,
            state_avals=st_avals, batch_avals=batch_avals,
            init_state=init_state, batch_fn=self._batch_fn(toks_shape),
            axis_sizes=axis_sizes)

    def _build_tp(self, layout: Layout, mesh, axis_sizes) -> Built:
        """dp x tp: Megatron head/column/row sharding inside every block
        (dryrun part 6), grads averaged over ``data``."""
        from apex_tpu import optimizers
        from apex_tpu.models.gpt import next_token_loss
        from apex_tpu.parallel import lm_tp_pspecs, tp_shard_lm_params

        tp = layout.tp
        dense = self._dense_model()
        local = dense.clone(num_heads=self.heads // tp,
                            tensor_parallel_axis="model",
                            tensor_parallel_size=tp)
        opt = optimizers.FusedAdam(lr=self.lr)

        # avals only (winner's init_state materializes — see _build_dp)
        params_sds = jax.eval_shape(
            lambda: tp_shard_lm_params(self._dense_params(), tp))
        tp_specs = lm_tp_pspecs(params_sds)
        st_sds = jax.eval_shape(opt.init, params_sds)
        st_specs = type(st_sds)(step=P(), exp_avg=tp_specs,
                                exp_avg_sq=tp_specs)
        state_spec = (tp_specs, st_specs)
        batch_spec = P("data") if layout.dp > 1 else P()

        # plain (scope-free) collectives, dryrun part 6 convention: the
        # apex_ddp_allreduce seam would turn every in-block tp psum
        # into an APX206 finding, and bucketing a tp-sharded tree buys
        # nothing the per-layer collectives don't already dominate
        def step(state, batch):
            p, opt_state = state

            def loss_of(pp, t):
                return next_token_loss(
                    local.apply({"params": pp}, t), t)

            loss, grads = _accumulate(loss_of, p, batch,
                                      layout.microbatch)
            if layout.dp > 1:
                grads = jax.lax.pmean(grads, "data")
            new_p, new_o = opt.step(grads, p, opt_state)
            loss = (jax.lax.pmean(loss, "data") if layout.dp > 1
                    else loss)
            return (new_p, new_o), loss

        def init_state():
            sharded = jax.device_put(
                tp_shard_lm_params(self._dense_params(), tp),
                jax.tree_util.tree_map(
                    lambda sp: NamedSharding(mesh, sp), tp_specs))
            return (sharded, opt.init(sharded))

        toks_shape = (self.batch, self.seq)
        return Built(
            layout=layout, mesh=mesh, step=step,
            wrapped=_wrap(step, mesh, state_spec, batch_spec),
            state_spec=state_spec, batch_spec=batch_spec,
            state_avals=(params_sds, st_sds),
            batch_avals=jax.ShapeDtypeStruct(toks_shape, jnp.int32),
            init_state=init_state, batch_fn=self._batch_fn(toks_shape),
            axis_sizes=axis_sizes)

    def _build_seq(self, layout: Layout, mesh, axis_sizes) -> Built:
        """dp x seq: ring/Ulysses sequence-parallel attention (dryrun
        parts 2-4); grads are shard CONTRIBUTIONS over ``seq`` (summed)
        and replica means over ``data``."""
        from apex_tpu import optimizers
        from apex_tpu.models.gpt import next_token_loss

        model = self._dense_model(seq_parallel=layout.seq_impl,
                                  axis_name="seq")
        opt = optimizers.FusedAdam(lr=self.lr)

        # plain (scope-free) collectives — see _build_tp: the DDP seam
        # would flag the ring/Ulysses attention collectives (APX206)
        def step(state, batch):
            p, opt_state = state
            toks = batch
            off = jax.lax.axis_index("seq") * toks.shape[1]

            def loss_of(pp, t):
                return next_token_loss(
                    model.apply({"params": pp}, t, pos_offset=off),
                    t, "seq")

            loss, grads = _accumulate(loss_of, p, toks, 1)
            # globally-normalized loss: each device holds its shard's
            # contribution — SUM over seq, then replica-mean over data
            grads = jax.lax.psum(grads, "seq")
            if layout.dp > 1:
                grads = jax.lax.pmean(grads, "data")
            new_p, new_o = opt.step(grads, p, opt_state)
            loss = jax.lax.pmean(loss, "seq")
            if layout.dp > 1:
                loss = jax.lax.pmean(loss, "data")
            return (new_p, new_o), loss

        # avals only (winner's init_state materializes — see _build_dp)
        params_sds = self._dense_params_sds()
        st_sds = jax.eval_shape(opt.init, params_sds)
        state_spec = (P(), type(st_sds)(step=P(), exp_avg=P(),
                                        exp_avg_sq=P()))
        batch_spec = (P("data", "seq") if layout.dp > 1
                      else P(None, "seq"))

        def init_state():
            p = self._dense_params()
            return (p, opt.init(p))

        toks_shape = (self.batch, self.seq)
        return Built(
            layout=layout, mesh=mesh, step=step,
            wrapped=_wrap(step, mesh, state_spec, batch_spec),
            state_spec=state_spec, batch_spec=batch_spec,
            state_avals=(params_sds, st_sds),
            batch_avals=jax.ShapeDtypeStruct(toks_shape, jnp.int32),
            init_state=init_state, batch_fn=self._batch_fn(toks_shape),
            axis_sizes=axis_sizes)

    def _build_pp(self, layout: Layout, mesh, axis_sizes) -> Built:
        """dp x pp: the block stack shards into contiguous stages over
        ``pipe`` (stacked leading dim, ``layers/pp`` blocks per rank)
        and each step runs the :mod:`~apex_tpu.parallel.
        pipeline_schedule` timetable executor — 1F1B by default,
        ``APEX_TPU_PP_SCHEDULE=gpipe`` flips. Both schedules are
        bitwise-equal to the single-stage accumulation baseline, so
        the knob is a memory-shape choice, not a numerics one. Stage
        grads stay pipe-sharded; the stage-disjoint rest grads psum
        over pipe inside ``pipelined_grads``; dp replicas pmean over
        ``data`` (plain collectives — see the _build_tp APX206 note)."""
        import os

        from apex_tpu import optimizers
        from apex_tpu.models.gpt import Block, next_token_loss
        from apex_tpu.normalization import layer_norm
        from apex_tpu.parallel.pipeline import (lm_stack_blocks,
                                                stacked_block_pspecs)
        from apex_tpu.parallel.pipeline_schedule import pipelined_grads

        e, heads = self.embed, self.heads
        mb = layout.microbatch
        schedule = os.environ.get("APEX_TPU_PP_SCHEDULE", "1f1b")
        opt = optimizers.FusedAdam(lr=self.lr)

        def embed_fn(rest, t):
            return (rest["tok_emb"]["embedding"][t]
                    + rest["pos_emb"]["embedding"][
                        jnp.arange(t.shape[1])][None])

        def stage_fn(p_loc, h):
            def body(hh, p):
                return Block(e, heads, name="b").apply(
                    {"params": p}, hh), ()
            return jax.lax.scan(body, h, p_loc)[0]

        def loss_fn(rest, h, t):
            hid = layer_norm(h.reshape(-1, e), rest["ln_f"]["weight"],
                             rest["ln_f"]["bias"]).reshape(h.shape)
            logits = hid @ rest["head"]["kernel"] + rest["head"]["bias"]
            return next_token_loss(logits.astype(jnp.float32), t)

        # params ride as {"stacked", "rest"} (a dict root — the fused
        # optimizer's tuple-is-leaf convention must not see a tuple at
        # the tree root)
        def step(state, batch):
            params, opt_state = state
            loss, (g_stk, g_rest) = pipelined_grads(
                embed_fn, stage_fn, loss_fn, params["stacked"],
                params["rest"], batch, mb,
                axis_name="pipe", schedule=schedule)
            grads = {"stacked": g_stk, "rest": g_rest}
            if layout.dp > 1:
                grads = jax.lax.pmean(grads, "data")
                loss = jax.lax.pmean(loss, "data")
            new_p, new_o = opt.step(grads, params, opt_state)
            return (new_p, new_o), loss

        # avals only (winner's init_state materializes — see _build_dp)
        stacked_sds, rest_sds = jax.eval_shape(
            lm_stack_blocks, self._dense_params_sds())
        params_sds = {"stacked": stacked_sds, "rest": rest_sds}
        sspecs = stacked_block_pspecs(stacked_sds)
        p_specs = {"stacked": sspecs,
                   "rest": jax.tree_util.tree_map(lambda _: P(),
                                                  rest_sds)}
        st_sds = jax.eval_shape(opt.init, params_sds)
        st_specs = type(st_sds)(step=P(), exp_avg=p_specs,
                                exp_avg_sq=p_specs)
        state_spec = (p_specs, st_specs)
        batch_spec = P("data") if layout.dp > 1 else P()

        def init_state():
            stacked, rest = lm_stack_blocks(self._dense_params())
            stacked = jax.device_put(stacked, jax.tree_util.tree_map(
                lambda sp: NamedSharding(mesh, sp), sspecs))
            params = {"stacked": stacked, "rest": rest}
            return (params, opt.init(params))

        toks_shape = (self.batch, self.seq)
        return Built(
            layout=layout, mesh=mesh, step=step,
            wrapped=_wrap(step, mesh, state_spec, batch_spec),
            state_spec=state_spec, batch_spec=batch_spec,
            state_avals=(params_sds, st_sds),
            batch_avals=jax.ShapeDtypeStruct(toks_shape, jnp.int32),
            init_state=init_state, batch_fn=self._batch_fn(toks_shape),
            axis_sizes=axis_sizes)


class ResNetAdapter:
    """ResNet-18-family adapter (the bench shape): dp with SyncBatchNorm
    stat sync, optionally ZeRO-2 sharded Adam (dryrun part 1)."""

    name = "resnet"

    def __init__(self, *, image: int = 32, classes: int = 10,
                 batch: int = 64, lr: float = 1e-3, seed: int = 0):
        self.image, self.classes = image, classes
        self.batch, self.lr, self.seed = batch, lr, seed

    def _model(self, axis_name: Optional[str]):
        from apex_tpu import models
        return models.ResNet18(num_classes=self.classes,
                               axis_name=axis_name)

    def _init_vars(self, axis_name: Optional[str]):
        model = self._model(axis_name)
        x = jnp.ones((2, self.image, self.image, 3), jnp.float32)
        return model.init(jax.random.PRNGKey(self.seed), x, train=False)

    def describe(self, *, compile_reference: bool = True) -> ModelDesc:
        vs = jax.eval_shape(
            lambda: self._init_vars(None))
        p_sds = vs["params"]
        n_params = tree_count(p_sds)
        p_bytes = tree_bytes(p_sds)
        flops = nbytes = None
        if compile_reference:
            from apex_tpu import optimizers
            from apex_tpu.contrib.xentropy import (
                softmax_cross_entropy_loss)
            model = self._model(None)
            opt = optimizers.FusedAdam(lr=self.lr)

            def ref_step(params, bs, opt_state, x, y):
                def loss_of(p):
                    logits, upd = model.apply(
                        {"params": p, "batch_stats": bs}, x, train=True,
                        mutable=["batch_stats"])
                    return jnp.mean(
                        softmax_cross_entropy_loss(logits, y)), upd

                (loss, upd), g = jax.value_and_grad(
                    loss_of, has_aux=True)(params)
                new_p, new_s = opt.step(g, params, opt_state)
                return new_p, upd["batch_stats"], new_s, loss

            st_sds = jax.eval_shape(opt.init, p_sds)
            x = jax.ShapeDtypeStruct(
                (self.batch, self.image, self.image, 3), jnp.float32)
            y = jax.ShapeDtypeStruct((self.batch,), jnp.int32)
            cost = reference_cost(ref_step, p_sds, vs["batch_stats"],
                                  st_sds, x, y)
            flops, nbytes = cost.get("flops"), cost.get("bytes_accessed")
        if not flops:
            flops = resnet_flops(batch=self.batch, image=self.image)
        act = self._act_bytes_per_sample()
        if not nbytes:
            nbytes = 6.0 * p_bytes + 2.0 * self.batch * act
        return ModelDesc(
            name=self.name, param_count=n_params, param_bytes=p_bytes,
            flops_per_step=float(flops), bytes_per_step=float(nbytes),
            act_bytes_per_sample=act, opt_state_bytes=8 * n_params,
            dims={"batch": self.batch, "image": self.image,
                  "classes": self.classes})

    def _act_bytes_per_sample(self) -> float:
        # stagewise feature maps: 64@S/2 + 64@S/4 + 128@S/8 + 256@S/16 +
        # 512@S/32, ~2 tensors per block alive in backward, fp32
        s = self.image
        maps = (64 * (s // 2) ** 2 + 2 * 64 * (s // 4) ** 2
                + 2 * 128 * (s // 8) ** 2 + 2 * 256 * (s // 16) ** 2
                + 2 * 512 * (max(s // 32, 1)) ** 2)
        return float(2 * maps * 4)

    def veto(self, layout: Layout) -> Optional[str]:
        if layout.tp > 1 or layout.seq > 1 or layout.pp > 1:
            return ("resnet builds dp/zero layouts only (tensor/"
                    "sequence/pipeline parallelism do not apply to the "
                    "conv trunk)")
        if layout.microbatch > 1:
            return ("microbatch accumulation changes SyncBatchNorm "
                    "statistics semantics — not built for resnet")
        if layout.fp8:
            return ("fp8 compute tier (amp O6) is not threaded through "
                    "the resnet reference step — rank it analytically")
        return None

    def build(self, layout: Layout, devices=None) -> Built:
        veto = self.veto(layout)
        if veto is not None:
            raise ValueError(
                f"cannot build layout {layout.layout_id()}: {veto}")
        from apex_tpu import optimizers, parallel
        from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss
        from apex_tpu.parallel.mesh import named_mesh
        from apex_tpu.tune import heuristics as _h

        mesh = named_mesh(layout.mesh_axes(), devices=devices)
        axis_sizes = dict(zip(mesh.axis_names,
                              (int(s) for s in mesh.devices.shape)))
        axis = "data" if layout.dp > 1 else None
        model = self._model(axis)
        # avals only — the concrete init is deferred to the winner's
        # init_state (see GPTAdapter._build_dp)
        vars_sds = jax.eval_shape(lambda: self._init_vars(axis))
        params, batch_stats = vars_sds["params"], \
            vars_sds["batch_stats"]
        bucket = layout.ddp_bucket or _h.DDP_MESSAGE_SIZE
        if layout.zero:
            from apex_tpu.contrib.optimizers import DistributedFusedAdam
            opt = DistributedFusedAdam(
                lr=self.lr, axis_name="data", shard_count=layout.dp,
                chunk_elements=layout.zero_chunk
                or _h.ZERO_CHUNK_ELEMENTS,
                reduce_dtype=layout.reduce_dtype)
        else:
            opt = optimizers.FusedAdam(lr=self.lr)

        def step(state, batch):
            p, bs, opt_state = state
            x, y = batch

            def loss_of(pp):
                logits, upd = model.apply(
                    {"params": pp, "batch_stats": bs}, x, train=True,
                    mutable=["batch_stats"])
                return jnp.mean(
                    softmax_cross_entropy_loss(logits, y)), upd

            (loss, upd), grads = jax.value_and_grad(
                loss_of, has_aux=True)(p)
            if layout.zero:
                new_p, new_o = opt.step(grads, p, opt_state)
            else:
                if layout.dp > 1:
                    grads = parallel.allreduce_gradients(
                        grads, "data", message_size=bucket,
                        reduce_dtype=layout.reduce_dtype)
                new_p, new_o = opt.step(grads, p, opt_state)
            loss = (jax.lax.pmean(loss, "data") if layout.dp > 1
                    else loss)
            return (new_p, upd["batch_stats"], new_o), loss

        if layout.zero:
            st_spec = opt.state_pspec()
        else:
            st = jax.eval_shape(opt.init, params)
            st_spec = type(st)(step=P(), exp_avg=P(), exp_avg_sq=P())
        state_spec = (P(), P(), st_spec)
        batch_spec = ((P("data"), P("data")) if layout.dp > 1
                      else (P(), P()))

        def init_state():
            variables = self._init_vars(axis)
            p, bs = variables["params"], variables["batch_stats"]
            opt_state = opt.init(p)
            if layout.zero:
                opt_state = jax.device_put(
                    opt_state, jax.tree_util.tree_map(
                        lambda sp: NamedSharding(mesh, sp),
                        opt.state_pspec()))
            return (p, bs, opt_state)

        x_shape = (self.batch, self.image, self.image, 3)
        classes = self.classes

        def batch_fn(i: int):
            rng = np.random.default_rng(20_000 + i)
            x = jnp.asarray(rng.standard_normal(x_shape, np.float32))
            y = jnp.asarray(rng.integers(0, classes, (x_shape[0],),
                                         dtype=np.int32))
            return (x, y)

        st_avals = (params, batch_stats,
                    jax.eval_shape(opt.init, params))
        batch_avals = (jax.ShapeDtypeStruct(x_shape, jnp.float32),
                       jax.ShapeDtypeStruct((x_shape[0],), jnp.int32))
        return Built(
            layout=layout, mesh=mesh, step=step,
            wrapped=_wrap(step, mesh, state_spec, batch_spec),
            state_spec=state_spec, batch_spec=batch_spec,
            state_avals=st_avals, batch_avals=batch_avals,
            init_state=init_state, batch_fn=batch_fn,
            axis_sizes=axis_sizes)


ADAPTERS = {"gpt": GPTAdapter, "resnet": ResNetAdapter}


def get_adapter(name: str, **kwargs):
    """CLI/bench factory: adapter by family name with shape kwargs."""
    try:
        cls = ADAPTERS[name]
    except KeyError:
        raise ValueError(
            f"unknown model family {name!r}; known: {sorted(ADAPTERS)}")
    return cls(**kwargs)
