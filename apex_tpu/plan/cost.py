"""The analytic cost model: price one layout in seconds and bytes.

Three ingredient families, per the ROADMAP item-2 recipe:

  * **Wire bytes** — the per-step collective bill. The exact number
    comes from :func:`apex_tpu.telemetry.comm.comm_stats` run over the
    candidate's traced program (:func:`traced_wire` — axis-size- and
    ring-algorithm-aware, grouped-collective-correct); the closed-form
    :func:`analytic_wire` mirrors the same ring multipliers per layout
    family so the full candidate space can be ranked without tracing
    hundreds of programs. ``plan.auto`` traces the survivors and
    reports the analytic-vs-traced drift honestly
    (``CostBreakdown.wire_drift_pct``).
  * **Compute/memory floors** — the model's whole-step FLOP/byte totals
    (XLA cost analysis via the adapter's :meth:`describe`) divided by
    the layout's parallel degree, against the
    :func:`apex_tpu.pyprof.roofline.device_peaks` ceilings. The step
    can never beat ``max(compute_floor, memory_floor)``.
  * **HBM footprint** — params + optimizer state under the ZeRO stage +
    the activation estimate; the pruner's feasibility ceiling.

Overlap credit follows the PR 6 staged-backward model: the dp-axis
gradient collective issues inside the backward graph, so up to
``OVERLAP_EFFICIENCY`` of it hides behind the backward's compute time
(the live bench measured ~0.8; ``ddp/overlap_efficiency`` telemetry).
Pipeline layouts pay the GPipe bubble ``(pp-1)/microbatch``.

All constants that are NOT device-measured (ICI bandwidth, the
per-collective latency) are env-overridable and recorded in the
breakdown — the bench's ``plan`` key tracks modeled-vs-measured error
across rounds so cost-model drift is visible, never silent.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, List, Optional, Sequence

from apex_tpu.plan.describe import ModelDesc
from apex_tpu.plan.layout import Layout

__all__ = ["CostBreakdown", "HeteroCost", "WireItem", "estimate",
           "analytic_wire", "traced_wire", "hbm_footprint",
           "decode_step_s", "heterogeneous_step_s", "member_speeds",
           "optimal_weights", "OVERLAP_EFFICIENCY", "ici_bytes_per_s",
           "collective_latency_s", "WIRE_ITEMSIZE", "fp8_flop_scale",
           "fp8_bytes_scale"]

# Fraction of a staged dp-collective's time that hides behind backward
# compute (PR 6 overlap engine; pyprof measured 79.6% on the live GPT
# profile, ddp/overlap_efficiency). Env-overridable for new fabrics.
OVERLAP_EFFICIENCY = 0.8

# Backward's share of total step compute (fwd 1x, bwd 2x of the fwd
# cost for matmul-dominated models) — the window a staged collective
# can hide in.
BACKWARD_FRACTION = 2.0 / 3.0

# Interconnect bandwidth per device (bytes/s) the wire bill divides by.
# ~one v4 ICI link direction; like the roofline CPU constants this is a
# RELATIVE ranking signal on CPU meshes, not a hardware claim.
ICI_BW_DEFAULT = 9e10

# Fixed per-collective cost (dispatch + link latency) — prices bucket
#-count trade-offs so a 10k-bucket schedule ranks worse than 8 buckets.
COLLECTIVE_LATENCY_S = 8e-6

# Per-element wire bytes of each reduce_dtype tier (grad collectives
# pre-cast to the wire format; fp32 accumulation after). None falls
# back to the model's grad itemsize.
WIRE_ITEMSIZE = {"bf16": 2, "fp16": 2, "int8": 1}

# fp8 compute-tier pricing (Layout.fp8 / amp O6): the MXU runs fp8
# matmuls at ~2x the bf16 rate and the forward stash moves 1-byte
# activations where bf16 moved 2 — relative ranking multipliers like
# the roofline CPU constants, env-overridable for new silicon.
FP8_FLOP_SCALE_DEFAULT = 0.5
FP8_BYTES_SCALE_DEFAULT = 0.75


def fp8_flop_scale() -> float:
    env = os.environ.get("APEX_TPU_PLAN_FP8_FLOP_SCALE")
    return float(env) if env else FP8_FLOP_SCALE_DEFAULT


def fp8_bytes_scale() -> float:
    env = os.environ.get("APEX_TPU_PLAN_FP8_BYTES_SCALE")
    return float(env) if env else FP8_BYTES_SCALE_DEFAULT


def ici_bytes_per_s() -> float:
    env = os.environ.get("APEX_TPU_PLAN_ICI_BW")
    return float(env) if env else ICI_BW_DEFAULT


def collective_latency_s() -> float:
    env = os.environ.get("APEX_TPU_PLAN_COLL_LAT")
    return float(env) if env else COLLECTIVE_LATENCY_S


def _ring(prim: str, n: int) -> float:
    """The telemetry.comm wire multipliers — ONE definition, imported."""
    from apex_tpu.telemetry.comm import _WIRE
    return _WIRE[prim](n)


@dataclasses.dataclass
class WireItem:
    """One (axis, primitive) line of the communication bill — the same
    shape as :class:`~apex_tpu.telemetry.comm.CommRecord`, plus whether
    the overlap engine can hide it (dp grad sync) or it sits on the
    critical path (per-layer tp/seq collectives)."""

    axis: str
    primitive: str
    bytes_in: float
    bytes_wire: float
    count: float = 1.0
    hideable: bool = False

    def to_meta(self) -> Dict[str, Any]:
        return {"axis": self.axis, "primitive": self.primitive,
                "bytes_in": round(self.bytes_in),
                "bytes_wire": round(self.bytes_wire),
                "count": round(self.count, 2),
                "hideable": self.hideable}


@dataclasses.dataclass
class CostBreakdown:
    """Every term of one candidate's modeled step, auditable by the CLI
    ``explain`` command. Seconds unless suffixed otherwise."""

    layout_id: str
    compute_s: float
    memory_s: float
    roofline_s: float            # max(compute, memory)
    wire: List[WireItem]
    wire_bytes: float            # sum of bytes_wire
    comm_s: float                # wire over the interconnect + latency
    hidden_s: float              # overlap credit actually granted
    exposed_comm_s: float
    bubble_s: float              # GPipe bubble overhead
    latency_s: float             # per-collective fixed costs
    step_s: float                # the ranking total
    hbm: Dict[str, float]        # params/grads/opt/act/total/capacity
    wire_source: str = "analytic"
    wire_drift_pct: Optional[float] = None
    notes: List[str] = dataclasses.field(default_factory=list)

    def to_meta(self) -> Dict[str, Any]:
        return {
            "layout": self.layout_id, "step_s": self.step_s,
            "compute_s": self.compute_s, "memory_s": self.memory_s,
            "roofline_s": self.roofline_s,
            "wire_bytes": round(self.wire_bytes),
            "comm_s": self.comm_s, "hidden_s": self.hidden_s,
            "exposed_comm_s": self.exposed_comm_s,
            "bubble_s": self.bubble_s, "latency_s": self.latency_s,
            "hbm_total": round(self.hbm.get("total", 0.0)),
            "wire_source": self.wire_source,
            "wire_drift_pct": self.wire_drift_pct,
        }

    def explain(self) -> str:
        """Per-term audit table (the CLI ``explain`` body)."""
        ms = 1e3
        mb = 1 / (1 << 20)
        lines = [f"layout {self.layout_id}  (modeled step "
                 f"{self.step_s * ms:.3f} ms)",
                 f"  compute floor      {self.compute_s * ms:10.3f} ms",
                 f"  memory floor       {self.memory_s * ms:10.3f} ms",
                 f"  roofline max       {self.roofline_s * ms:10.3f} ms",
                 f"  comm ({self.wire_source:>8})   "
                 f"{self.comm_s * ms:10.3f} ms  "
                 f"({self.wire_bytes * mb:.2f} MiB wire)"]
        for w in self.wire:
            hide = " [hideable]" if w.hideable else ""
            lines.append(
                f"    {w.axis:<8}{w.primitive:<14}"
                f"{w.bytes_wire * mb:10.2f} MiB wire  "
                f"x{w.count:.0f}{hide}")
        lines += [
            f"  overlap hidden     {-self.hidden_s * ms:10.3f} ms  "
            f"(eff {OVERLAP_EFFICIENCY})",
            f"  exposed comm       {self.exposed_comm_s * ms:10.3f} ms",
            f"  collective latency {self.latency_s * ms:10.3f} ms",
            f"  pipeline bubble    {self.bubble_s * ms:10.3f} ms",
            "  HBM: " + ", ".join(
                f"{k} {v * mb:.1f}" for k, v in self.hbm.items()
                if k != "capacity") + " MiB"
            + (f" (cap {self.hbm['capacity'] * mb:.0f} MiB)"
               if "capacity" in self.hbm else ""),
        ]
        if self.wire_drift_pct is not None:
            lines.append(f"  analytic-vs-traced wire drift "
                         f"{self.wire_drift_pct:+.1f}%")
        for n in self.notes:
            lines.append(f"  note: {n}")
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# wire bills
# ---------------------------------------------------------------------------

def analytic_wire(desc: ModelDesc, layout: Layout) -> List[WireItem]:
    """Closed-form per-step communication bill for one layout family —
    the same ring multipliers the jaxpr walker applies, over payload
    sizes derived from the model description. Sub-KiB payloads (loss
    pmeans, scalar counters) are omitted: they never move a ranking and
    the traced tier accounts them exactly."""
    items: List[WireItem] = []
    dims = desc.dims
    grad_b = desc.param_count * desc.grad_itemsize
    wire_item = WIRE_ITEMSIZE.get(layout.reduce_dtype,
                                  desc.grad_itemsize)
    wire_b = desc.param_count * wire_item
    n_buckets = max(1, -(-desc.param_count
                         // (layout.ddp_bucket or 2 ** 23)))
    if layout.tp > 1:
        # under tp the dp grad psum carries the LOCAL tree: sharded
        # params at 1/tp plus the replicated remainder (embeddings,
        # head, LNs — the adapter's tp_replicated dim)
        repl = dims.get("tp_replicated", 0)
        local_count = (desc.param_count - repl) / layout.tp + repl
        grad_b = local_count * desc.grad_itemsize
        wire_b = local_count * wire_item
    if layout.pp > 1:
        # under pp the dp grad psum likewise carries the LOCAL tree:
        # the stacked block shard at 1/pp plus the stage-disjoint rest
        # (embeddings, final norm, head — the adapter's pp_rest dim)
        rest_n = dims.get("pp_rest", 0)
        local_count = (desc.param_count - rest_n) / layout.pp + rest_n
        grad_b = local_count * desc.grad_itemsize
        wire_b = local_count * wire_item
    if layout.dp > 1:
        if layout.zero:
            n = layout.dp
            chunk = layout.zero_chunk or 2 ** 23
            n_chunks = max(1, -(-desc.param_count // chunk))
            # reduce-scatter of the flat grads at the wire dtype, then
            # all-gather of each shard's updated fp32 params
            items.append(WireItem(
                "data", "reduce_scatter", wire_b,
                wire_b * _ring("reduce_scatter", n), n_chunks,
                hideable=False))
            gather_in = grad_b / n
            items.append(WireItem(
                "data", "all_gather", gather_in,
                gather_in * _ring("all_gather", n), n_chunks,
                hideable=False))
        else:
            n = layout.dp
            # overlap credit applies to PURE-dp layouts only: the tp/seq
            # builders sync grads with a plain post-backward pmean (no
            # staged seam — see adapters' APX206 note), so their dp
            # collective sits fully on the critical path
            items.append(WireItem(
                "data", "psum", wire_b, wire_b * _ring("psum", n),
                n_buckets,
                hideable=(layout.overlap and layout.microbatch == 1
                          and layout.tp == 1 and layout.seq == 1
                          and layout.pp == 1)))
    if layout.tp > 1:
        # Megatron f/g: 2 fwd psums per block (attention out, fc2) plus
        # their backward transposes — 4 activation-sized psums per block
        act = (dims["batch"] // layout.dp) * dims["seq"] \
            * dims["embed"] * 4
        count = 4 * dims["layers"]
        items.append(WireItem(
            "model", "psum", act * count,
            act * count * _ring("psum", layout.tp), count))
    if layout.seq > 1:
        n = layout.seq
        b_loc = dims["batch"] // layout.dp
        s_loc = dims["seq"] // n
        act = b_loc * s_loc * dims["embed"] * 4   # one (tokens, E) shard
        if layout.seq_impl == "ring":
            # ring attention rotates the FULL K and V past every device
            # once forward and once backward: per layer each device
            # moves 2 x (K+V) = 4 full (tokens, E) activations —
            # INDEPENDENT of n; as shard-sized ppermutes that is 4n
            # payloads of one KV shard (matches the traced bill at
            # n=2 and n=4 exactly)
            count = 4 * n * dims["layers"]
            items.append(WireItem(
                "seq", "ppermute", act * count,
                act * count * _ring("ppermute", n), count))
        else:
            # Ulysses: head<->sequence all_to_all around attention,
            # 2-shard payloads x (qkv pack + out) x fwd+bwd = 8 act per
            # layer (exactly the traced count)
            count = 4 * dims["layers"]
            items.append(WireItem(
                "seq", "all_to_all", 2 * act * count,
                2 * act * count * _ring("all_to_all", n), count))
        # the globally-normalized loss leaves shard CONTRIBUTIONS:
        # every step psums the FULL grad tree over the seq axis
        items.append(WireItem(
            "seq", "psum", grad_b, grad_b * _ring("psum", n), 1))
    if layout.pp > 1:
        # the timetable executor's wire, closed-form: the scan runs
        # T = 2*(mb + pp - 1) ticks and EVERY tick issues one
        # microbatch-sized activation ppermute right and one cotangent
        # ppermute left — idle slots send zeros, which move bytes all
        # the same (the walker bills the aval; honesty over optimism)
        b_loc = dims["batch"] // max(layout.dp, 1)
        act = (b_loc // max(layout.microbatch, 1)) \
            * dims.get("seq", 1) * dims.get("embed", 1) * 4
        count = 2 * 2 * (layout.microbatch + layout.pp - 1)
        items.append(WireItem(
            "pipe", "ppermute", act * count,
            act * count * _ring("ppermute", layout.pp), count))
        # the stage-disjoint rest grads (embeddings on stage 0, final
        # norm + head on the last) reassemble with ONE full-size psum
        # over pipe; the scalar loss broadcast rides the sub-KiB
        # omission rule above
        rest_n = dims.get("pp_rest", 0)
        if rest_n:
            rest_b = rest_n * desc.grad_itemsize
            items.append(WireItem(
                "pipe", "psum", rest_b,
                rest_b * _ring("psum", layout.pp), 1))
    return items


def traced_wire(built) -> List[WireItem]:
    """The EXACT wire bill: run the telemetry.comm jaxpr walker over the
    candidate's shard_map-wrapped program (trace only — avals in,
    nothing executes). Collectives on the data axis tagged hideable
    when the layout stages them into backward."""
    from apex_tpu.telemetry.comm import comm_stats
    records = comm_stats(built.wrapped, built.state_avals,
                         built.batch_avals,
                         axis_sizes=built.axis_sizes)
    layout = built.layout
    hide = (layout.overlap and layout.microbatch == 1
            and not layout.zero and layout.tp == 1 and layout.seq == 1
            and layout.pp == 1)
    items = []
    for r in records:
        if r.bytes_wire is None:
            raise ValueError(
                f"comm walker could not resolve axis size for "
                f"{r.axis}/{r.primitive} — planner candidates must "
                f"carry a fully-sized mesh")
        items.append(WireItem(
            r.axis, r.primitive, r.bytes_in, float(r.bytes_wire),
            r.count,
            hideable=(hide and r.axis == "data"
                      and r.primitive == "psum")))
    return items


# ---------------------------------------------------------------------------
# HBM footprint
# ---------------------------------------------------------------------------

def hbm_footprint(desc: ModelDesc, layout: Layout,
                  capacity: Optional[float] = None) -> Dict[str, float]:
    """Per-device HBM need: params + grads + optimizer state under the
    ZeRO stage + activation estimate. ``capacity`` (when given) rides
    along for the pruner's verdict message.

    Microbatch accumulation changes BOTH memory terms, in opposite
    directions: only one microbatch's activations are live at a time
    (the scan body re-stashes per chunk — ``act`` divides by
    ``microbatch``), but the accumulation CARRIES a full gradient-sized
    accumulator through the scan, live simultaneously with each chunk's
    fresh gradients at the combine — the ``grads`` term doubles. The
    static analyzer (:func:`apex_tpu.lint.verified_peak_bytes`)
    confirms both movements on the adapters' scan-mode builds; the
    residual level gap is the activation estimate's documented
    structural underestimate (see :func:`plan_hbm_tolerance_pct`)."""
    shard = layout.tp * layout.pp            # axes that SHARD params
    params = desc.param_bytes / shard
    grads = desc.param_count * desc.grad_itemsize / shard
    if layout.microbatch > 1:
        grads *= 2.0                         # accumulator + chunk grads
    if layout.zero:
        # fp32 master + both moments, sharded over dp; fp32 compute
        # params stay replicated (they ARE the dense copy here)
        opt = 12.0 * desc.param_count / layout.dp / shard
    else:
        opt = 8.0 * desc.param_count / shard  # two fp32 Adam moments
    local_batch = desc.dims.get("batch", 1) / (layout.dp
                                               * layout.microbatch)
    act = desc.act_bytes_per_sample * local_batch \
        / (layout.seq * layout.pp)
    if layout.fp8:
        # fp8 compute tier: the forward stash holds 1-byte e4m3
        # activations where bf16 held 2 (weights/grads/opt unchanged —
        # O6 keeps bf16 weights, O7 fp32 masters ride the opt term)
        act *= 0.5
    out = {"params": params, "grads": grads, "opt": opt, "act": act,
           "total": params + grads + opt + act}
    if capacity is not None:
        out["capacity"] = float(capacity)
    return out


def plan_hbm_tolerance_pct() -> float:
    """How far the lint mem analyzer's verified peak may sit ABOVE the
    analytic ``hbm_footprint`` before the planner demotes a candidate
    (``APEX_TPU_PLAN_HBM_TOL_PCT`` overrides; default 600).

    The default is deliberately wide and deliberately named: the
    analytic activation term is a forward-stash scaling model — it does
    not price backward temporaries or the quadratic attention
    matrices, so the compiled program's true peak runs ~1.2-2.2x the
    formula on the shipped adapters (worst ~5.5x on toy configs; pinned
    in tests/test_plan.py). The tolerance exists to pass that
    structural band while still demoting pathological blow-ups (an
    accidental full replication or O(steps^2) accumulation is 10-50x,
    not 2x). The hard edge is separate and un-tolerated: a verified
    peak above device capacity demotes regardless."""
    import os
    try:
        return float(os.environ.get("APEX_TPU_PLAN_HBM_TOL_PCT", "600"))
    except ValueError:
        return 600.0


# ---------------------------------------------------------------------------
# the estimate
# ---------------------------------------------------------------------------

def estimate(desc: ModelDesc, layout: Layout, *,
             peaks: Optional[Dict[str, float]] = None,
             wire: Optional[List[WireItem]] = None,
             hbm_capacity: Optional[float] = None) -> CostBreakdown:
    """Price ``layout`` for ``desc``. ``wire`` (from :func:`traced_wire`)
    replaces the analytic bill and records the drift between the two;
    ``peaks`` defaults to :func:`apex_tpu.pyprof.roofline.device_peaks`
    of the local device."""
    if peaks is None:
        from apex_tpu.pyprof.roofline import device_peaks
        peaks = device_peaks()
    world = layout.world
    mb = layout.microbatch

    compute_s = desc.flops_per_step / world / peaks["flops"]
    memory_s = desc.bytes_per_step / world / peaks["bytes_per_s"]
    if layout.fp8:
        # the lowp compute tier: fp8 matmuls at ~2x MXU rate, narrower
        # activation traffic (constants above; env-overridable)
        compute_s *= fp8_flop_scale()
        memory_s *= fp8_bytes_scale()
    roofline_s = max(compute_s, memory_s)

    analytic = analytic_wire(desc, layout)
    drift = None
    source = "analytic"
    if wire is not None:
        a_total = sum(w.bytes_wire for w in analytic)
        t_total = sum(w.bytes_wire for w in wire)
        if t_total > 0:
            drift = 100.0 * (a_total - t_total) / t_total
        source = "traced"
    else:
        wire = analytic

    bw = ici_bytes_per_s()
    lat = collective_latency_s()
    wire_bytes = sum(w.bytes_wire for w in wire)
    latency_s = lat * sum(w.count for w in wire)
    comm_s = wire_bytes / bw
    hideable_s = sum(w.bytes_wire for w in wire if w.hideable) / bw
    window = BACKWARD_FRACTION * compute_s
    hidden_s = min(hideable_s, window) * OVERLAP_EFFICIENCY
    exposed_s = comm_s - hidden_s

    bubble_s = roofline_s * (layout.pp - 1) / mb if layout.pp > 1 \
        else 0.0

    step_s = roofline_s + exposed_s + latency_s + bubble_s
    notes = []
    if layout.zero == 0 and layout.dp > 1 and not layout.overlap:
        notes.append("overlap off: dp grad sync fully exposed")
    if layout.reduce_dtype:
        notes.append(f"{layout.reduce_dtype} wire compression "
                     "(pre-scaled, fp32 accumulation)")
    if layout.fp8:
        notes.append(
            f"fp8 compute tier (amp O6: e4m3 fwd / e5m2 bwd QDQ; "
            f"flops x{fp8_flop_scale()}, hbm x{fp8_bytes_scale()})")
    return CostBreakdown(
        layout_id=layout.layout_id(),
        compute_s=compute_s, memory_s=memory_s, roofline_s=roofline_s,
        wire=list(wire), wire_bytes=wire_bytes, comm_s=comm_s + latency_s,
        hidden_s=hidden_s, exposed_comm_s=exposed_s, bubble_s=bubble_s,
        latency_s=latency_s, step_s=step_s,
        hbm=hbm_footprint(desc, layout, capacity=hbm_capacity
                          if hbm_capacity is not None
                          else peaks.get("hbm_bytes")),
        wire_source=source, wire_drift_pct=drift, notes=notes)


# ---------------------------------------------------------------------------
# decode latency (the serving objective, plan.auto(objective="p99_decode"))
# ---------------------------------------------------------------------------

def decode_step_s(desc: ModelDesc, layout: Layout, *,
                  peaks: Optional[Dict[str, float]] = None) -> float:
    """Modeled per-token decode step latency for one layout — the
    ranking currency of ``objective="p99_decode"``.

    Decode flips the training roofline: one token's forward is ~0
    FLOPs against the bytes it must move, so the step is MEMORY-BOUND —
    every resident weight is read once per token, plus the live KV
    history. The parallel-axis algebra is therefore different from
    :func:`estimate`'s throughput model, which is the whole reason this
    is a separate objective and not a re-weighting:

      * **tp** divides the critical-path weight AND KV reads (each rank
        reads only its head/mlp shard) but buys that with 2 per-layer
        psums on the token's critical path — pure latency at one
        token's payload, priced via :func:`collective_latency_s`.
      * **pp** shards weights per DEVICE but not per TOKEN: the token
        still traverses every stage serially, so pipeline parallelism
        does NOT reduce the bytes on its critical path — it only adds
        stage-boundary hops. (Great for training throughput, useless
        for p99 decode — the objective flip the test pins.)
      * **dp** replicates weights (no read reduction); it divides the
        batch, shrinking only the KV term.
      * **seq** has nothing to shard at s=1 — no benefit, and its
        layouts keep their per-layer collectives on the path.
    """
    if peaks is None:
        from apex_tpu.pyprof.roofline import device_peaks
        peaks = device_peaks()
    d = desc.dims
    itemsize = (desc.param_bytes / desc.param_count
                if desc.param_count else 4.0)
    # critical-path weight bytes: tp shards the reads; pp does not
    # (serial stage traversal reads every stage's shard in sequence)
    weight_b = desc.param_bytes / layout.tp
    local_batch = max(1.0, d.get("batch", 1) / layout.dp
                      / max(1, layout.microbatch))
    kv_b = (2.0 * d.get("layers", 1) * local_batch * d.get("seq", 1)
            * d.get("embed", 0) * itemsize / layout.tp)
    mem_s = (weight_b + kv_b) / peaks["bytes_per_s"]
    lat = collective_latency_s()
    coll_s = 0.0
    if layout.tp > 1:
        # Megatron forward: 2 psums per block (attention out, fc2) on
        # the token's critical path; payloads are one token's
        # activations — latency-dominated, plus their (tiny) wire time
        n_ps = 2 * d.get("layers", 1)
        act_b = local_batch * d.get("embed", 0) * 4.0
        coll_s += n_ps * (lat + act_b * _ring("psum", layout.tp)
                          / ici_bytes_per_s())
    if layout.seq > 1:
        # per-layer seq collectives stay on the path even with nothing
        # to shard (the builders' all-to-all/ppermute structure)
        coll_s += 2 * d.get("layers", 1) * lat
    if layout.pp > 1:
        coll_s += 2.0 * (layout.pp - 1) * lat
    return mem_s + coll_s


# ---------------------------------------------------------------------------
# heterogeneous members (the AMP arc, arXiv 2210.07297)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class HeteroCost:
    """One weighted-fleet pricing: the step is the SLOWEST member's
    bill (a lock-step fleet advances at the straggler's pace — the
    whole point of rebalancing is to shrink that max)."""

    step_s: float                 # max over members
    per_member_s: List[float]     # each member's modeled bill
    speeds: List[float]           # relative speeds (fleet median = 1)
    weights: Optional[List[int]]  # canonical vector (None = equal)

    def to_meta(self) -> Dict[str, Any]:
        return {"step_s": self.step_s,
                "per_member_ms": [round(s * 1e3, 4)
                                  for s in self.per_member_s],
                "speeds": [round(s, 4) for s in self.speeds],
                "weights": self.weights}


def member_speeds(rates: Dict[str, float]) -> List[float]:
    """Measured per-member step rates -> relative speeds normalized to
    the fleet MEDIAN (= 1.0), in dense sorted-member order — the same
    member ordering the rendezvous rank assignment uses, so index i is
    member rank i."""
    members = sorted(rates)
    if not members:
        raise ValueError("member_speeds needs at least one rate")
    vals = [float(rates[m]) for m in members]
    if any(v <= 0 for v in vals):
        raise ValueError(f"rates must be positive, got {rates}")
    med = sorted(vals)[len(vals) // 2]
    return [v / med for v in vals]


def optimal_weights(speeds: Sequence[float], *,
                    granularity: int = 8) -> Optional[List[int]]:
    """Speed-proportional canonical weight vector: the fixed
    (replicated-compute) term of the heterogeneous bill scales with
    ``1/speed_i`` no matter the assignment, so the minimizing move for
    the shard-proportional term is to give each member work in
    proportion to its speed. Quantized to ``granularity`` levels of the
    fastest member and floored at 1; an all-equal result canonicalizes
    to None (equal shards) — one definition of canonical weights,
    shared with :mod:`apex_tpu.resilience.elastic`."""
    from apex_tpu.resilience.elastic import normalize_weights
    top = max(speeds)
    if top <= 0:
        raise ValueError(f"speeds must be positive, got {speeds}")
    ws = [max(1, round(granularity * s / top)) for s in speeds]
    return normalize_weights(ws)


def heterogeneous_step_s(cost: CostBreakdown,
                         speeds: Sequence[float], *,
                         weights: Optional[Sequence[int]] = None
                         ) -> HeteroCost:
    """Price one layout on a fleet of UNEQUAL members: the step time is
    ``max`` over members of that member's compute+comm bill.

    Member ``i``'s bill splits into the REPLICATED term — the roofline
    floor plus any pipeline bubble, paid by every member over its own
    silicon, so it scales with ``1/speed_i`` — and the
    SHARD-PROPORTIONAL term — the exposed collective bill plus
    per-collective latency, whose per-member share follows its shard
    fraction (ZeRO scatter/gather payloads and the optimizer's flat
    update are both linear in the member's span), normalized so the
    equal split reproduces ``cost.step_s`` exactly on a homogeneous
    fleet. ``weights=None`` prices the equal assignment (what the fleet
    pays BEFORE rebalancing)."""
    speeds = [float(s) for s in speeds]
    n = len(speeds)
    if n < 1:
        raise ValueError("heterogeneous_step_s needs >= 1 member")
    if weights is None:
        fractions = [1.0 / n] * n
        canon = None
    else:
        from apex_tpu.resilience.elastic import normalize_weights
        canon = normalize_weights(weights, n)
        ws = canon if canon is not None else [1] * n
        total = float(sum(ws))
        fractions = [w / total for w in ws]
    fixed = cost.roofline_s + cost.bubble_s
    shardable = cost.exposed_comm_s + cost.latency_s
    per_member = [fixed / s + shardable * f * n
                  for s, f in zip(speeds, fractions)]
    return HeteroCost(step_s=max(per_member), per_member_s=per_member,
                      speeds=speeds, weights=canon)
