"""The emitter: turn the winning candidate into a ready-to-train
package — ``TrainerConfig`` + shard_map layout (mesh/in_specs) + tune
cache entries — delivered through the PR 9 trainer plugin seam.

The non-negotiable gate: EVERY emitted layout passes the lint SPMD
verifier (APX201-APX209) over the exact shard_map-wrapped program the
trainer will compile. A candidate the verifier flags raises
:class:`PlanRejected` carrying the findings — the planner never hands a
caller a layout it knows deadlocks or diverges.

Tune cache entries are schema-v1 compatible with ``"planner"``
provenance: a subsequent ``APEX_TPU_TUNE=cache`` run resolves the
planner's bucket/chunk choices with zero re-measurement, and
``python -m apex_tpu.tune show`` renders where they came from.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence

from apex_tpu.plan.adapters import Built
from apex_tpu.plan.cost import CostBreakdown
from apex_tpu.plan.describe import ModelDesc
from apex_tpu.plan.layout import Layout

__all__ = ["Plan", "PlanRejected", "verify_built", "emit",
           "format_table"]


class PlanRejected(RuntimeError):
    """An emit-path candidate failed the SPMD verifier. Carries the
    findings so callers (and the CI gate) can name the rules."""

    def __init__(self, layout: Layout, findings: Sequence[Any]):
        self.layout = layout
        self.findings = list(findings)
        rules = ", ".join(sorted({f.rule_id for f in self.findings}))
        super().__init__(
            f"planner refuses to emit layout {layout.layout_id()}: "
            f"lint.spmd flagged {rules} — "
            + "; ".join(f.message for f in self.findings[:3]))


def verify_built(built: Built, *,
                 threshold_bytes: Optional[int] = None) -> List[Any]:
    """Run APX201-APX209 over the candidate's shard_map-wrapped program
    (trace-only; the same entry ``Plan.build_trainer`` compiles, with
    the trainer's donation declaration armed). Returns the findings
    list — empty means verified."""
    from apex_tpu import lint
    if threshold_bytes is None and built.layout.zero:
        # ZeRO re-materializes the updated params in bucketed
        # all_gathers BY DESIGN (sharded optimizer state, gathered
        # params is the zero-2 trade) — at real model sizes those
        # designed gathers cross APX204's default 1 MiB replication
        # threshold. Raise it to the step state's own size: no designed
        # zero gather can exceed the state it re-materializes, so the
        # param gathers pass while an activation-sized accidental
        # replication (batch x features dwarfs the state) still fires.
        from apex_tpu.lint.spmd_checks import replication_threshold_bytes
        from apex_tpu.plan.describe import tree_bytes
        threshold_bytes = max(replication_threshold_bytes(),
                              int(tree_bytes(built.state_avals)) + 1)
    return lint.check_entry_spmd(
        built.wrapped, (built.state_avals, built.batch_avals),
        name=f"plan:{built.layout.layout_id()}",
        path="apex_tpu/plan/emit.py",
        mesh_axes=built.mesh_axis_names,
        axis_sizes=built.axis_sizes,
        donate_argnums=(0,),
        threshold_bytes=threshold_bytes)


def _cache_entries(desc: ModelDesc, layout: Layout,
                   est: CostBreakdown) -> List[Dict[str, Any]]:
    """The schema-v1 tune entries this layout pins: the exact
    (op, key) pairs the runtime call sites will look up (``total`` goes
    through ``tune.shape_bucket`` exactly like ``allreduce_gradients``
    / ``_ZeroBase._pack`` compute it)."""
    from apex_tpu.tune import shape_bucket
    from apex_tpu.tune.tuner import cache_key
    out: List[Dict[str, Any]] = []
    total = shape_bucket(desc.param_count)

    def _entry(op: str, key: Dict[str, int], config: Dict[str, int]):
        out.append({
            "op": op, "key": key, "cache_key": cache_key(op, key),
            "entry": {"config": dict(config), "provenance": "planner",
                      "planned_s": est.step_s,
                      "layout": layout.layout_id()}})

    if layout.dp > 1 and not layout.zero and layout.ddp_bucket:
        key = {"total": total, "world": layout.dp}
        cfg = {"message_size": int(layout.ddp_bucket)}
        _entry("ddp_message_size", key, cfg)
        if layout.overlap:
            _entry("ddp_overlap", key, cfg)
    if layout.zero and layout.zero_chunk:
        _entry("zero_chunk_elements",
               {"total": total, "world": layout.dp},
               {"chunk_elements": int(layout.zero_chunk)})
    return out


def _write_cache(entries: List[Dict[str, Any]]) -> int:
    from apex_tpu.tune import cache as _cache
    store = _cache.get_cache()
    written = 0
    for e in entries:
        if store.put(e["cache_key"], dict(e["entry"])):
            written += 1
    return written


@dataclasses.dataclass
class Plan:
    """A ready-to-train emission. ``build_trainer()`` compiles the
    winning step through :func:`apex_tpu.trainer.build` with the plan's
    own TrainerConfig and a :class:`~apex_tpu.trainer.plugins.
    PlanPlugin` attached (the pick lands in the run's telemetry as
    ``plan/pick``); ``init_state()`` materializes the sharded initial
    state; the verdict ``table`` keeps every candidate's fate for the
    CLI/CI."""

    layout: Layout
    cost: CostBreakdown
    desc: ModelDesc
    built: Built
    table: List[Dict[str, Any]]
    cache_entries: List[Dict[str, Any]]
    cache_written: int
    measured_s: Optional[float] = None

    @property
    def layout_id(self) -> str:
        return self.layout.layout_id()

    def trainer_config(self, **overrides):
        from apex_tpu.trainer import TrainerConfig
        kw = dict(mode="per_step", in_flight=2, donate=True)
        kw.update(overrides)
        return TrainerConfig(**kw)

    def init_state(self):
        return self.built.init_state()

    def batch_fn(self, i: int):
        return self.built.batch_fn(i)

    def build_trainer(self, *, config=None, plugins: Sequence[Any] = (),
                      name: Optional[str] = None):
        """The delivery point: the PR 9 compiled-step builder over the
        emitted layout (mesh + in_specs + donation + dispatch window),
        plan attribution plugin attached exactly once."""
        from apex_tpu import trainer as _trainer
        from apex_tpu.trainer.plugins import PlanPlugin
        cfg = config or self.trainer_config()
        return _trainer.build(
            self.built.step, self.built.state_avals,
            self.built.batch_avals, mesh=self.built.mesh,
            state_spec=self.built.state_spec,
            batch_spec=self.built.batch_spec,
            config=cfg, plugins=list(plugins) + [PlanPlugin(self)],
            name=name or f"plan:{self.layout_id}")

    def explain(self, layout_id: Optional[str] = None) -> str:
        """Per-term cost audit of the pick (or any candidate in the
        table by id) — the CLI ``explain`` body."""
        if layout_id is None or layout_id == self.layout_id:
            return self.cost.explain()
        for row in self.table:
            if row.get("layout") == layout_id:
                return "\n".join(f"{k}: {v}" for k, v in row.items())
        raise KeyError(f"layout {layout_id!r} not in this plan's table; "
                       f"known: {[r['layout'] for r in self.table]}")

    def to_json(self) -> Dict[str, Any]:
        return {
            "pick": self.layout.to_dict(),
            "modeled_step_s": self.cost.step_s,
            "measured_step_s": self.measured_s,
            "wire_bytes": self.cost.wire_bytes,
            "wire_source": self.cost.wire_source,
            "wire_drift_pct": self.cost.wire_drift_pct,
            "hbm_bytes": self.cost.hbm.get("total"),
            "model": self.desc.to_meta(),
            "mesh": dict(self.built.axis_sizes),
            "cache_entries": [
                {"cache_key": e["cache_key"], **e["entry"]}
                for e in self.cache_entries],
            "table": list(self.table),
        }


def format_table(table: List[Dict[str, Any]]) -> str:
    """The ranked candidate table (CLI ``auto`` body): layout, modeled
    step ms, wire bytes, HBM, feasibility verdict — parseable (fixed
    columns, one row per candidate)."""
    hdr = (f"{'rank':<5}{'layout':<26}{'family':<14}{'step_ms':>10}"
           f"{'wire_MiB':>10}{'hbm_MiB':>9}  verdict")
    lines = [hdr, "-" * len(hdr)]
    rank_i = 0
    for row in table:
        feas = row["feasible"]
        rank_i = rank_i + 1 if feas else rank_i
        rank = str(rank_i) if feas else "-"
        step = (f"{row['step_ms']:.3f}" if "step_ms" in row else "-")
        wire = (f"{row['wire_mib']:.2f}" if "wire_mib" in row else "-")
        hbm = (f"{row['hbm_mib']:.0f}" if "hbm_mib" in row else "-")
        verdict = "OK" if feas else f"infeasible: {row['reason']}"
        if feas and "measured_ms" in row:
            verdict += f" (measured {row['measured_ms']:.3f} ms)"
        if feas and row.get("wire_source") == "traced":
            verdict += " [traced]"
        lines.append(f"{rank:<5}{row['layout']:<26}{row['family']:<14}"
                     f"{step:>10}{wire:>10}{hbm:>9}  {verdict}")
    return "\n".join(lines)


def emit(built: Built, est: CostBreakdown, *, desc: ModelDesc,
         verdicts: Sequence[Any] = (), measured_s: Optional[float] = None,
         write_cache: bool = True, preverified: bool = False) -> Plan:
    """Gate + package: verify the candidate (APX201-209), write the tune
    cache entries, record the ``plan/*`` telemetry statics, return the
    :class:`Plan`. Raises :class:`PlanRejected` on findings — this is
    the one door every emitted layout walks through. ``preverified``
    skips the (expensive, whole-program) re-verification ONLY for the
    in-process ``plan.auto`` path, which has already run
    :func:`verify_built` over this exact built program and rejected on
    findings; every external caller keeps the default gate."""
    from apex_tpu import telemetry
    if not preverified:
        findings = verify_built(built)
        if findings:
            raise PlanRejected(built.layout, findings)
    entries = _cache_entries(desc, built.layout, est)
    written = _write_cache(entries) if write_cache else 0
    table = [v.row() for v in verdicts] if verdicts else []
    plan = Plan(layout=built.layout, cost=est, desc=desc, built=built,
                table=table, cache_entries=entries,
                cache_written=written, measured_s=measured_s)
    if telemetry.enabled():
        telemetry.record_static(
            "plan/pick", est.step_s,
            meta={**est.to_meta(), "mesh": dict(built.axis_sizes),
                  "model": desc.to_meta(),
                  "measured_s": measured_s,
                  "cache_entries": len(entries),
                  "cache_written": written},
            dedup_key=("plan/pick", built.layout.layout_id(),
                       desc.name))
        telemetry.record_static(
            "plan/candidates", float(len(table)),
            meta={"feasible": sum(1 for r in table if r["feasible"]),
                  "total": len(table)},
            dedup_key=("plan/candidates", desc.name, len(table)))
    return plan
