"""The strategy search: enumerate -> prune -> rank -> validate -> pick.

The space is the ROADMAP item-2 tuple (dp, tp, pp, seq, zero stage,
microbatch, bucket capacities, reduce_dtype), in the spirit of AMP's
heterogeneity-aware strategy search (arXiv 2210.07297): an ANALYTIC
first pass prices every structurally-feasible candidate (no tracing),
then the ``top_k`` survivors are traced for their exact comm bill
(:func:`~apex_tpu.plan.cost.traced_wire` — the telemetry.comm jaxpr
walker) and verified by the lint SPMD rules before any of them can be
emitted; a verifier-rejected candidate is disqualified LOUDLY, never
silently skipped. On a real TPU (``validate="measure"``) the survivors
are additionally timed through :mod:`apex_tpu.tune.measure` — on
CPU/interpret that tier reports "not measurable" and the ranking stays
analytic, exactly like existing tune sweeps (hermetic CI).
"""

from __future__ import annotations

import dataclasses
import warnings
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from apex_tpu.plan import cost as _cost
from apex_tpu.plan.adapters import Built
from apex_tpu.plan.describe import ModelDesc
from apex_tpu.plan.layout import Layout

__all__ = ["Constraints", "Verdict", "PlanError", "enumerate_candidates",
           "prune", "rank", "auto", "replanner"]


class PlanError(ValueError):
    """A planner-level contract violation (estimating an infeasible
    layout, an empty feasible set, ...) — loud by design."""


@dataclasses.dataclass(frozen=True)
class Constraints:
    """Search-space bounds + validation policy for one ``auto`` call.

    hbm_bytes:
        Per-device capacity the footprint model prunes against; None =
        :func:`apex_tpu.pyprof.roofline.device_hbm_bytes` of the local
        device.
    zero_stages / microbatches / reduce_dtypes:
        The knob values enumerated (defaults cover the proven set;
        ``reduce_dtypes`` additionally accepts ``"fp16"``/``"int8"`` —
        the int8 wire tier competes only when asked for).
    fp8_modes:
        Whether pure-dp candidates additionally enumerate the lowp fp8
        compute tier (``Layout.fp8`` / amp O6). Default ``(False,)``
        keeps the search space identical to the pre-fp8 build; pass
        ``(False, True)`` to let O6 candidates compete.
    allow_seq / allow_tp / allow_pp:
        Family gates, all True: every axis the adapters can build
        competes by default. ``allow_pp`` flipped True in PR 19 when
        the GPT adapter learned to emit the pipeline_schedule executor
        (pp candidates additionally enumerate microbatch counts of
        ``pp`` and ``2*pp`` — a 1-microbatch pipeline is all bubble,
        so the schedule's natural operating points must be in the
        table for the bubble term to rank honestly).
    top_k:
        Survivors that get the traced comm bill + lint verification
        (and measurement under ``validate="measure"``).
    validate:
        ``"none"`` (analytic only — the replan/bench fast path),
        ``"trace"`` (default), ``"measure"`` (trace + on-device timing
        when the backend is measurable; measured candidates then rank
        by MEASURED step time — the AMP arc: the analytic model's job
        is to shortlist the true best into the top_k, the device clock
        settles the pick).
    measure_force:
        Time ``validate="measure"`` candidates even on a backend
        ``tune.measure.measurable()`` declines (CPU/interpret). The
        hermetic-CI doctrine stays the default — this is the explicit
        opt-in ``benchmarks/plan_vs_hand.py`` uses, where wall clock IS
        the ground truth being compared against.
    objective:
        The ranking currency. ``"throughput"`` (default) ranks by the
        modeled TRAINING step time; ``"p99_decode"`` ranks by the
        modeled per-token decode latency
        (:func:`apex_tpu.plan.cost.decode_step_s` — memory-bound, so
        the parallel-axis algebra flips: pp stops helping, tp starts).
        Every verdict row carries both numbers either way.
    """

    hbm_bytes: Optional[float] = None
    zero_stages: Tuple[int, ...] = (0, 2)
    microbatches: Tuple[int, ...] = (1, 2)
    reduce_dtypes: Tuple[Optional[str], ...] = (None, "bf16")
    fp8_modes: Tuple[bool, ...] = (False,)
    allow_seq: bool = True
    allow_tp: bool = True
    allow_pp: bool = True
    seq_impls: Tuple[str, ...] = ("ring", "ulysses")
    top_k: int = 4
    validate: str = "trace"
    measure_force: bool = False
    target_buckets: int = 8
    objective: str = "throughput"

    def __post_init__(self):
        if self.validate not in ("none", "trace", "measure"):
            raise ValueError(
                f"Constraints.validate must be none|trace|measure, "
                f"got {self.validate!r}")
        if self.top_k < 1:
            raise ValueError("Constraints.top_k must be >= 1")
        if self.objective not in ("throughput", "p99_decode"):
            raise ValueError(
                f"Constraints.objective must be throughput|p99_decode, "
                f"got {self.objective!r}")


@dataclasses.dataclass
class Verdict:
    """One row of the ranked table: a candidate plus its fate."""

    layout: Layout
    feasible: bool
    reason: str = ""                     # why infeasible ("" when ok)
    cost: Optional[_cost.CostBreakdown] = None
    measured_s: Optional[float] = None   # validate="measure" only
    # modeled per-token decode latency (cost.decode_step_s) — the
    # p99_decode objective's ranking currency, carried on every
    # feasible row so both objectives' tables are comparable
    decode_s: Optional[float] = None
    lint_findings: List[Any] = dataclasses.field(default_factory=list)
    # lint.mem analyzer cross-check (traced candidates only): the
    # verified per-device peak and the analytic formula's drift from it
    # (positive = formula overestimates), the HBM twin of wire drift
    hbm_verified_bytes: Optional[int] = None
    hbm_error_pct: Optional[float] = None

    @property
    def step_s(self) -> float:
        return self.cost.step_s if self.cost else float("inf")

    def row(self) -> Dict[str, Any]:
        out = {"layout": self.layout.layout_id(),
               "family": self.layout.family(),
               "feasible": self.feasible, "reason": self.reason}
        if self.cost is not None:
            out.update({
                "step_ms": round(self.cost.step_s * 1e3, 4),
                "wire_mib": round(self.cost.wire_bytes / (1 << 20), 3),
                "hbm_mib": round(self.cost.hbm["total"] / (1 << 20), 1),
                "wire_source": self.cost.wire_source})
        if self.decode_s is not None:
            out["decode_ms"] = round(self.decode_s * 1e3, 4)
        if self.hbm_verified_bytes is not None:
            out["hbm_verified_mib"] = round(
                self.hbm_verified_bytes / (1 << 20), 1)
        if self.hbm_error_pct is not None:
            out["hbm_error_pct"] = round(self.hbm_error_pct, 1)
        if self.measured_s is not None:
            out["measured_ms"] = round(self.measured_s * 1e3, 4)
        if self.lint_findings:
            out["lint"] = [f.rule_id for f in self.lint_findings]
        return out


def _pow2_at_most(n: int) -> int:
    b = 1
    while b * 2 <= n:
        b *= 2
    return b


def _divisors(n: int) -> List[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


def resolve_buckets(desc: ModelDesc, layout: Layout, *,
                    target_buckets: int = 8) -> Layout:
    """Planner-resolved bucket capacities: split the flat gradient into
    ~``target_buckets`` power-of-two-sized buckets (enough pieces for
    the staged-backward schedule to pipeline, few enough that
    per-collective latency stays negligible), clamped to the tune
    heuristics' sane range [2^20, 2^25]."""
    total = desc.param_count
    cap = max(1 << 20, min(1 << 25,
                           _pow2_at_most(max(1, total // target_buckets))))
    kw = {}
    pure_dp = layout.tp == 1 and layout.seq == 1 and layout.pp == 1
    if layout.dp > 1 and not layout.zero and pure_dp:
        # tp/seq layouts sync grads with plain collectives (adapter
        # APX206 note) — a bucket capacity would configure nothing
        kw["ddp_bucket"] = cap
    if layout.zero:
        kw["zero_chunk"] = cap
    return dataclasses.replace(layout, **kw) if kw else layout


def enumerate_candidates(n_devices: int, desc: ModelDesc,
                         constraints: Constraints) -> List[Layout]:
    """Every structurally-plausible layout over ``n_devices`` — mesh
    factorizations x zero stages x microbatches x wire dtypes, with the
    planner's bucket resolution applied. Model-shape feasibility is
    :func:`prune`'s job."""
    cands: List[Layout] = []
    is_lm = "seq" in desc.dims

    def _add(**kw):
        try:
            layout = Layout(**kw)
        except ValueError:
            return
        cands.append(resolve_buckets(
            desc, layout, target_buckets=constraints.target_buckets))

    for dp in _divisors(n_devices):
        rest = n_devices // dp
        if rest == 1:
            # pure data parallelism (dp may be 1 = single device)
            for zero in constraints.zero_stages:
                if zero and dp < 2:
                    continue
                for mb in constraints.microbatches:
                    for rd in constraints.reduce_dtypes:
                        if dp == 1 and (rd or zero):
                            continue
                        for f8 in constraints.fp8_modes:
                            _add(dp=dp, zero=zero, microbatch=mb,
                                 reduce_dtype=rd, fp8=f8)
            continue
        # one extra axis: tp, seq, or pp takes the remainder (no
        # reduce_dtype variants: compression rides the DDP seam the
        # tp/seq steps deliberately avoid — adapters.veto)
        if constraints.allow_tp and is_lm:
            _add(dp=dp, tp=rest)
        if constraints.allow_seq and is_lm:
            for impl in constraints.seq_impls:
                _add(dp=dp, seq=rest, seq_impl=impl)
        if constraints.allow_pp and is_lm:
            # the pipeline's economics live in the microbatch count
            # (bubble = (pp-1)/(mb+pp-1)): beyond the constraint set,
            # enumerate the schedule's natural operating points mb=pp
            # and mb=2*pp so a bubble-starved mb=1 row is never the
            # only pp candidate in the table
            for mb in sorted(set(constraints.microbatches)
                             | {rest, 2 * rest}):
                _add(dp=dp, pp=rest, microbatch=mb)
    # dedup (the dp==1 branches can collide)
    seen, out = set(), []
    for c in cands:
        key = c.layout_id()
        if key not in seen:
            seen.add(key)
            out.append(c)
    return out


def _shape_reason(desc: ModelDesc, layout: Layout) -> Optional[str]:
    """Divisibility feasibility from the model dims — a named reason or
    None. These are the non-negotiable vetoes (a non-divisible axis is
    not a slower layout, it is not a layout)."""
    d = desc.dims
    batch = d.get("batch", 1)
    if batch % layout.dp:
        return (f"global batch {batch} not divisible by dp={layout.dp}")
    if (batch // layout.dp) % layout.microbatch:
        return (f"local batch {batch // layout.dp} not divisible by "
                f"microbatch={layout.microbatch}")
    if layout.tp > 1:
        if d.get("heads", 1) % layout.tp:
            return f"heads {d.get('heads')} not divisible by tp={layout.tp}"
        if d.get("mlp_width", 1) % layout.tp:
            return (f"mlp width {d.get('mlp_width')} not divisible by "
                    f"tp={layout.tp}")
    if layout.seq > 1:
        if d.get("seq", 1) % layout.seq:
            return (f"sequence {d.get('seq')} not divisible by "
                    f"seq={layout.seq}")
        if layout.seq_impl == "ulysses" \
                and d.get("heads", 1) % layout.seq:
            return (f"ulysses shards heads: {d.get('heads')} not "
                    f"divisible by seq={layout.seq}")
    if layout.pp > 1 and d.get("layers", 1) % layout.pp:
        return (f"layers {d.get('layers')} not divisible by "
                f"pp={layout.pp}")
    return None


def prune(candidates: Sequence[Layout], desc: ModelDesc, *,
          adapter=None, constraints: Optional[Constraints] = None,
          peaks: Optional[Dict[str, float]] = None) -> List[Verdict]:
    """Classify every candidate: infeasible ones keep their named reason
    (non-divisible axis, adapter veto, HBM overflow) and NO cost;
    feasible ones carry the analytic :class:`CostBreakdown`."""
    constraints = constraints or Constraints()
    if peaks is None:
        from apex_tpu.pyprof.roofline import device_peaks
        peaks = device_peaks()
    cap = constraints.hbm_bytes if constraints.hbm_bytes is not None \
        else peaks.get("hbm_bytes")
    out: List[Verdict] = []
    for layout in candidates:
        reason = _shape_reason(desc, layout)
        if reason is None and adapter is not None:
            reason = adapter.veto(layout)
        if reason is not None:
            out.append(Verdict(layout, False, reason))
            continue
        est = _cost.estimate(desc, layout, peaks=peaks,
                             hbm_capacity=cap)
        if cap is not None and est.hbm["total"] > cap:
            out.append(Verdict(
                layout, False,
                f"HBM overflow: need "
                f"{est.hbm['total'] / (1 << 20):.0f} MiB > "
                f"{cap / (1 << 20):.0f} MiB", est))
            continue
        out.append(Verdict(
            layout, True, "", est,
            decode_s=_cost.decode_step_s(desc, layout, peaks=peaks)))
    return out


def _objective_s(v: Verdict, objective: str) -> float:
    if objective == "p99_decode":
        return v.decode_s if v.decode_s is not None else float("inf")
    return v.step_s


def rank(verdicts: Sequence[Verdict],
         objective: str = "throughput") -> List[Verdict]:
    """Feasible candidates by the objective's modeled time — training
    step seconds for ``"throughput"``, per-token decode latency for
    ``"p99_decode"`` (infeasible ones keep their enumeration order at
    the tail — the table shows everything)."""
    feas = sorted((v for v in verdicts if v.feasible),
                  key=lambda v: _objective_s(v, objective))
    return feas + [v for v in verdicts if not v.feasible]


def estimate_layout(desc: ModelDesc, layout: Layout, *,
                    constraints: Optional[Constraints] = None,
                    peaks: Optional[Dict[str, float]] = None
                    ) -> _cost.CostBreakdown:
    """Single-layout estimate with the pruner's contract: an infeasible
    layout RAISES :class:`PlanError` naming the reason (the satellite
    'raises/filters loudly' requirement), it never returns a price for
    a layout that cannot exist."""
    verdicts = prune([layout], desc, constraints=constraints,
                     peaks=peaks)
    v = verdicts[0]
    if not v.feasible:
        raise PlanError(
            f"layout {layout.layout_id()} is infeasible: {v.reason}")
    assert v.cost is not None
    return v.cost


def _measure_built(built: Built, *, force: bool = False,
                   chain: int = 4) -> Optional[float]:
    """On-device median step seconds of a built candidate — the
    tune.measure pathway (policy-gated by the caller; hermetic off-TPU:
    returns None without touching a clock unless ``force``). Each
    sample is a ``chain``-step state-threaded run, not an isolated
    step: sustained throughput is what a training loop pays (isolated
    single-step timing hid ZeRO's smaller-working-set advantage on the
    live comparison — the layouts differ exactly in what stays
    resident between steps)."""
    from apex_tpu.tune import measure as _measure
    if not force and not _measure.measurable():
        return None
    import jax
    fn = jax.jit(built.wrapped, donate_argnums=())
    state = built.init_state()
    batch = built.batch_fn(0)

    def sample():
        s = state
        for _ in range(max(1, chain)):
            s, _ = fn(s, batch)
        return s

    try:
        return _measure.time_fn(sample) / max(1, chain)
    except Exception as e:
        warnings.warn(f"apex_tpu.plan: measuring "
                      f"{built.layout.layout_id()} failed ({e}); "
                      "keeping the modeled ranking for it")
        return None


def validate_top(verdicts: List[Verdict], adapter, desc: ModelDesc, *,
                 constraints: Constraints,
                 peaks: Optional[Dict[str, float]] = None,
                 devices=None) -> Dict[str, Built]:
    """Trace + verify (and optionally measure) the top_k feasible
    candidates IN PLACE: each survivor's cost is re-estimated with the
    walker's exact wire bill; a candidate the SPMD verifier flags is
    marked infeasible with its rule ids (disqualified before emission —
    the planner must never emit a layout the verifier rejects).
    Returns the Built programs keyed by layout id (the emitter reuses
    the winner's instead of re-building)."""
    from apex_tpu.plan.emit import verify_built
    built_map: Dict[str, Built] = {}
    if constraints.validate == "none":
        return built_map
    # the same capacity prune judged feasibility against — traced rows
    # must carry the identical hbm["capacity"] annotation the analytic
    # rows show
    cap = constraints.hbm_bytes
    if cap is None and peaks is not None:
        cap = peaks.get("hbm_bytes")
    checked = 0
    for v in verdicts:
        if not v.feasible or checked >= constraints.top_k:
            continue
        checked += 1
        lid = v.layout.layout_id()
        try:
            built = adapter.build(v.layout, devices=devices)
        except Exception as e:
            v.feasible = False
            v.reason = f"build failed: {e}"
            continue
        findings = verify_built(built)
        if findings:
            v.feasible = False
            v.lint_findings = list(findings)
            v.reason = ("rejected by lint.spmd: "
                        + ", ".join(sorted({f.rule_id for f in findings})))
            continue
        wire = _cost.traced_wire(built)
        v.cost = _cost.estimate(desc, v.layout, peaks=peaks, wire=wire,
                                hbm_capacity=cap)
        # the HBM honesty cross-check: the lint mem analyzer's verified
        # per-device peak vs the analytic formula that pruned on HBM.
        # Drift is always REPORTED (the bench tracks it across rounds
        # like wire drift); a verified peak above capacity demotes
        # unconditionally — the formula admitted a layout the program
        # does not fit — and a peak beyond the named structural
        # tolerance above the formula demotes too (a pathological
        # blow-up the scaling model cannot see)
        from apex_tpu.lint.mem_checks import verified_peak_bytes
        verified = verified_peak_bytes(
            built.wrapped, (built.state_avals, built.batch_avals),
            donate_argnums=(0,), axis_sizes=built.axis_sizes)
        analytic_hbm = v.cost.hbm["total"]
        v.hbm_verified_bytes = verified
        v.hbm_error_pct = (100.0 * (analytic_hbm - verified) / verified
                           if verified else None)
        tol = _cost.plan_hbm_tolerance_pct()
        if cap is not None and verified > cap:
            v.feasible = False
            v.reason = (
                f"verified HBM overflow: analyzer peak "
                f"{verified / (1 << 20):.0f} MiB > capacity "
                f"{cap / (1 << 20):.0f} MiB (analytic footprint said "
                f"{analytic_hbm / (1 << 20):.0f} MiB)")
            continue
        if verified > analytic_hbm * (1.0 + tol / 100.0):
            v.feasible = False
            v.reason = (
                f"HBM model disagreement: analyzer peak "
                f"{verified / (1 << 20):.0f} MiB exceeds the analytic "
                f"footprint {analytic_hbm / (1 << 20):.0f} MiB by more "
                f"than the structural tolerance ({tol:.0f}%; "
                f"APEX_TPU_PLAN_HBM_TOL_PCT overrides)")
            continue
        built_map[lid] = built
        if constraints.validate == "measure":
            v.measured_s = _measure_built(
                built, force=constraints.measure_force)
    return built_map


def auto(adapter, *, n_devices: Optional[int] = None,
         constraints: Optional[Constraints] = None, devices=None,
         write_cache: bool = True, compile_reference: bool = True):
    """The planner entry point: describe -> enumerate -> prune -> rank
    -> validate top_k -> emit the winner as a ready
    :class:`~apex_tpu.plan.emit.Plan` (TrainerConfig + shard_map layout
    + tune cache entries, lint-verified). Raises :class:`PlanError`
    when nothing survives."""
    import jax
    # NOTE: the package re-exports the emit() FUNCTION under the same
    # name as the submodule, so attribute-style module imports resolve
    # to the function — import the names straight from the submodule
    from apex_tpu.plan.emit import PlanRejected
    from apex_tpu.plan.emit import emit as _emit_plan
    from apex_tpu.plan.emit import verify_built as _verify_built
    from apex_tpu.pyprof.roofline import device_peaks
    constraints = constraints or Constraints()
    if devices is None:
        devices = list(jax.devices())
    n = int(n_devices) if n_devices else len(devices)
    devices = devices[:n]
    if len(devices) < n:
        raise PlanError(f"need {n} devices, have {len(devices)}")
    peaks = device_peaks(devices[0])
    cap = constraints.hbm_bytes if constraints.hbm_bytes is not None \
        else peaks.get("hbm_bytes")
    desc = adapter.describe(compile_reference=compile_reference)
    cands = enumerate_candidates(n, desc, constraints)
    verdicts = rank(prune(cands, desc, adapter=adapter,
                          constraints=constraints, peaks=peaks),
                    constraints.objective)
    built_map = validate_top(verdicts, adapter, desc,
                             constraints=constraints, peaks=peaks,
                             devices=devices)
    # the pick competes in ONE currency, highest fidelity first: a
    # MEASURED candidate outranks a traced one (the AMP arc — the
    # analytic model shortlists, the device clock settles), a traced
    # one outranks an analytic rival (a traced bill counts every scalar
    # psum the closed form rounds away — comparing across the two hands
    # sub-percent artifacts the decision). The table's rank 1 IS the
    # pick; wire_source / measured_ms name each row's fidelity tier.
    # Under objective="p99_decode" the currency is the modeled decode
    # latency on EVERY tier — tracing/measuring verify the candidate's
    # program and price its training step, but the decode model is the
    # only decode clock there is (nothing measures a serving step here).
    def _fidelity_key(v):
        if constraints.objective == "p99_decode":
            return (0, _objective_s(v, constraints.objective))
        if v.measured_s is not None:
            return (0, v.measured_s)
        if built_map and v.layout.layout_id() in built_map:
            return (1, v.step_s)
        return (2, v.step_s)

    feas = sorted((v for v in verdicts if v.feasible),
                  key=_fidelity_key)
    verdicts = feas + [v for v in verdicts if not v.feasible]
    winners = feas
    if not winners:
        raise PlanError(
            "no feasible layout survived; reasons: "
            + "; ".join(f"{v.layout.layout_id()}: {v.reason}"
                        for v in verdicts[:8]))
    pick = winners[0]
    built = built_map.get(pick.layout.layout_id())
    if built is None:
        built = adapter.build(pick.layout, devices=devices)
        # the analytic tier never traced this program — verify + price
        # it now (the emit gate would catch lint anyway; doing it here
        # keeps ONE code path producing the emitted numbers)
        findings = _verify_built(built)
        if findings:
            raise PlanRejected(pick.layout, findings)
        # re-price with the traced bill; no re-sort — this branch is
        # only reachable when NOTHING was traced (a traced feasible
        # rival would be fidelity tier 1 and already outrank the
        # untraced pick), so the pick stays at rank 1 regardless of
        # how the traced price moves: "the table's rank 1 IS the pick"
        # is an invariant the CI gate parses
        pick.cost = _cost.estimate(
            desc, pick.layout, peaks=peaks,
            wire=_cost.traced_wire(built),
            hbm_capacity=cap)
    return _emit_plan(built, pick.cost, desc=desc, verdicts=verdicts,
                      measured_s=pick.measured_s,
                      write_cache=write_cache, preverified=True)


# ---------------------------------------------------------------------------
# elastic replanning seam (ROADMAP item 4 — now heterogeneity-aware)
# ---------------------------------------------------------------------------

def replanner(adapter, *, constraints: Optional[Constraints] = None,
              heterogeneous: bool = True,
              granularity: int = 8
              ) -> Callable[..., Dict[str, Any]]:
    """The membership-change re-plan hook for
    :class:`apex_tpu.resilience.elastic.Elastic` — an ACTING
    incremental re-plan: the returned callable re-runs the ANALYTIC
    cost model at the old and new world sizes (no tracing, no
    compiling — a membership change must not pay a search) and, when
    the caller passes measured per-member ``rates`` (the rendezvous
    profile feed, ``Elastic(rates=...)``), prices the pick with the
    heterogeneous-member term (:func:`apex_tpu.plan.cost.
    heterogeneous_step_s` — step time = max over members of that
    member's compute+comm bill) and emits the canonical ``weights``
    vector the pick wants. That vector is what the rebalance
    supervisor's weighted re-shard consumes
    (``Elastic.planned_weights`` → ``rebalance.apply_rebalance``): the
    cost model's choice is CARRIED into the state re-map, not just
    logged.

    Returns ``{"old", "new", "old_step_s", "new_step_s",
    "equal_shard"}`` plus — with usable rates —
    ``{"weights", "speeds", "hetero_step_s", "equal_step_s"}``.
    ``heterogeneous=False`` restores the PR 14 equal-shard re-rank.
    """
    base = constraints or Constraints()
    cons = dataclasses.replace(base, validate="none")
    desc = adapter.describe(compile_reference=False)

    def _best(world: int) -> Verdict:
        cands = enumerate_candidates(world, desc, cons)
        ranked = rank(prune(cands, desc, adapter=adapter,
                            constraints=cons))
        feas = [v for v in ranked if v.feasible]
        if not feas:
            raise PlanError(
                f"replan: no feasible layout at world {world}")
        return feas[0]

    def replan(old_world: int, new_world: int,
               rates: Optional[Dict[str, float]] = None
               ) -> Dict[str, Any]:
        old, new = _best(int(old_world)), _best(int(new_world))
        out = {"old": old.layout.layout_id(),
               "new": new.layout.layout_id(),
               "old_step_s": old.step_s, "new_step_s": new.step_s,
               "equal_shard": True}
        if not heterogeneous or not rates:
            return out
        if len(rates) != int(new_world):
            # stale/partial profiles (a member died between the
            # heartbeat and this replan): weighted pricing would
            # assign weights to the wrong membership — stay equal
            out["weights_skipped"] = (
                f"{len(rates)} rates for world {new_world}")
            return out
        speeds = _cost.member_speeds(rates)
        weights = _cost.optimal_weights(speeds,
                                        granularity=granularity)
        hetero = _cost.heterogeneous_step_s(new.cost, speeds,
                                            weights=weights)
        equal = _cost.heterogeneous_step_s(new.cost, speeds)
        out.update({
            "weights": hetero.weights,
            "speeds": [round(s, 4) for s in speeds],
            "hetero_step_s": hetero.step_s,
            "equal_step_s": equal.step_s,
            "equal_shard": hetero.weights is None})
        return out

    return replan
