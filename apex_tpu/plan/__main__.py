import sys

from apex_tpu.plan.cli import main

sys.exit(main())
