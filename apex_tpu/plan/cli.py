"""``python -m apex_tpu.plan`` — the planner CLI.

``auto``     print the ranked candidate table (layout, modeled step ms,
             wire bytes, HBM, feasibility verdict), emit the winner
             (tune cache entries + lint gate), optionally train N steps
             through the emitted TrainerConfig (the CI gate's arc).
``explain``  per-term cost breakdown of one layout id, so a human can
             audit WHY the planner ranked it where it did.

Exit codes: 0 ok; 1 planner error (nothing feasible / rejected by the
SPMD verifier); 2 usage.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def _add_model_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("--model", default="gpt", choices=["gpt", "resnet"],
                   help="model family (adapter) to plan for")
    p.add_argument("--devices", type=int, default=0,
                   help="mesh size (0 = all local devices)")
    # gpt shape
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--embed-dim", type=int, default=128)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--batch", type=int, default=16,
                   help="GLOBAL batch size")
    p.add_argument("--seq-len", type=int, default=128)
    # resnet shape
    p.add_argument("--image", type=int, default=32)
    p.add_argument("--classes", type=int, default=10)
    p.add_argument("--hbm-bytes", type=float, default=None,
                   help="override the per-device HBM capacity the "
                        "pruner checks against (default: the device "
                        "table / APEX_TPU_HBM_BYTES)")
    p.add_argument("--no-compile", action="store_true",
                   help="skip the XLA cost-analysis reference compile; "
                        "use the analytic FLOP formulas")


def _adapter(args):
    from apex_tpu.plan import get_adapter
    if args.model == "gpt":
        return get_adapter("gpt", vocab=args.vocab, layers=args.layers,
                           embed=args.embed_dim, heads=args.heads,
                           batch=args.batch, seq=args.seq_len)
    return get_adapter("resnet", image=args.image,
                       classes=args.classes, batch=args.batch)


def _constraints(args):
    from apex_tpu.plan import Constraints
    kw = {}
    if args.hbm_bytes is not None:
        kw["hbm_bytes"] = float(args.hbm_bytes)
    if getattr(args, "top_k", None) is not None:
        kw["top_k"] = args.top_k     # 0 reaches Constraints' loud raise
    if getattr(args, "validate", None):
        kw["validate"] = args.validate
    if getattr(args, "objective", None):
        kw["objective"] = args.objective
    return Constraints(**kw)


def cmd_auto(args) -> int:
    from apex_tpu import plan as _plan
    from apex_tpu import telemetry
    if args.telemetry:
        telemetry.enable()
    try:
        constraints = _constraints(args)
    except ValueError as e:           # e.g. --top-k 0
        print(f"plan: {e}", file=sys.stderr)
        return 2
    try:
        p = _plan.auto(_adapter(args),
                       n_devices=args.devices or None,
                       constraints=constraints,
                       write_cache=not args.no_cache,
                       compile_reference=not args.no_compile)
    except (_plan.PlanError, _plan.PlanRejected) as e:
        print(f"plan: {e}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(p.to_json(), indent=1, sort_keys=True))
    else:
        print(_plan.format_table(p.table))
        print(f"\npick: {p.layout_id}  "
              f"(modeled {p.cost.step_s * 1e3:.3f} ms/step, "
              f"wire {p.cost.wire_bytes / (1 << 20):.2f} MiB "
              f"[{p.cost.wire_source}], lint.spmd clean)")
        if p.cache_entries:
            state = ("written" if p.cache_written else
                     "computed (--no-cache or unwritable cache)")
            print(f"tune cache entries ({state}): "
                  + ", ".join(e["cache_key"] for e in p.cache_entries))
    if args.train_steps:
        return _train(p, args)       # writes --telemetry after training
    if args.telemetry:
        # no train requested: the plan/pick + plan/candidates statics
        # recorded during emission still land in the promised JSONL
        telemetry.write_jsonl(args.telemetry)
        print(f"telemetry: {args.telemetry}")
    return 0


def _train(p, args) -> int:
    """Train --train-steps through the emitted TrainerConfig — the CI
    gate's end-to-end arc (telemetry JSONL written when --telemetry)."""
    import jax
    from apex_tpu import telemetry
    tr = p.build_trainer()
    state = p.init_state()
    losses: List[float] = []
    tr.set_user_on_step(lambda i, aux: losses.append(float(aux)))
    state = tr.run(state, p.batch_fn, args.train_steps)
    jax.block_until_ready(state)
    print(f"trained {args.train_steps} steps through {p.layout_id}: "
          f"losses {['%.4f' % l for l in losses]}")
    if args.telemetry:
        telemetry.write_jsonl(args.telemetry)
        print(f"telemetry: {args.telemetry}")
    return 0


def cmd_explain(args) -> int:
    from apex_tpu import plan as _plan
    try:
        layout = _plan.parse_layout_id(args.layout)
    except ValueError as e:
        print(f"plan: {e}", file=sys.stderr)
        return 2
    adapter = _adapter(args)
    desc = adapter.describe(compile_reference=not args.no_compile)
    try:
        est = _plan.estimate_layout(desc, layout,
                                    constraints=_constraints(args))
    except _plan.PlanError as e:
        print(f"plan: {e}", file=sys.stderr)
        return 1
    if args.traced:
        veto = adapter.veto(layout)
        if veto:
            print(f"plan: cannot trace {args.layout}: {veto}",
                  file=sys.stderr)
            return 1
        import jax
        devs = list(jax.devices())
        if args.devices:
            devs = devs[:args.devices]
        try:
            built = adapter.build(layout, devices=devs)
        except ValueError as e:      # e.g. more devices than local
            print(f"plan: {e}", file=sys.stderr)
            return 1
        est = _plan.estimate(desc, layout,
                             wire=_plan.traced_wire(built),
                             hbm_capacity=args.hbm_bytes)
    print(est.explain())
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m apex_tpu.plan",
        description="cost-model-driven automatic parallelism planner")
    sub = p.add_subparsers(dest="cmd", required=True)

    pa = sub.add_parser("auto", help="rank candidates, emit the winner")
    _add_model_args(pa)
    pa.add_argument("--top-k", type=int, default=4,
                    help="candidates to trace/verify (and measure on "
                         "TPU)")
    pa.add_argument("--validate", default="trace",
                    choices=["none", "trace", "measure"])
    pa.add_argument("--objective", default="throughput",
                    choices=["throughput", "p99_decode"],
                    help="ranking currency: training step time, or "
                         "modeled per-token decode latency (the serving "
                         "objective — memory-bound, so the axis algebra "
                         "flips; see plan.cost.decode_step_s)")
    pa.add_argument("--json", action="store_true")
    pa.add_argument("--no-cache", action="store_true",
                    help="do not write tune cache entries")
    pa.add_argument("--train-steps", type=int, default=0,
                    help="after emitting, train this many steps through "
                         "the emitted TrainerConfig")
    pa.add_argument("--telemetry", default=None, metavar="PATH",
                    help="enable telemetry and write the JSONL here "
                         "(plan/* statics + step series)")
    pa.set_defaults(fn=cmd_auto)

    pe = sub.add_parser("explain",
                        help="per-term cost breakdown of one layout id")
    pe.add_argument("layout", help="layout id, e.g. dp8 or dp4-tp2")
    _add_model_args(pe)
    pe.add_argument("--traced", action="store_true",
                    help="build + trace the layout for the exact wire "
                         "bill (default: analytic)")
    pe.set_defaults(fn=cmd_explain)
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
