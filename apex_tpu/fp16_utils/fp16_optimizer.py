"""FP16_Optimizer — the legacy manual-mixed-precision wrapper (reference
apex/fp16_utils/fp16_optimizer.py:13: fp32 master copies, loss scaling,
``backward``/``update_master_grads``/``clip_master_grads`` surface).

Functional recast: a host-driven eager wrapper around any
:class:`~apex_tpu.optimizers.base.FusedOptimizer`. For jitted training loops
use :class:`apex_tpu.amp.AmpOptimizer` — this class exists for users porting
reference fp16_utils code verbatim.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from apex_tpu import ops
from apex_tpu.fp16_utils.loss_scaler import DynamicLossScaler, LossScaler
from apex_tpu.fp16_utils.fp16util import (clip_grad_norm,
                                          master_params_to_model_params)

Tree = Any


class FP16_Optimizer:
    """``FP16_Optimizer(init_optimizer, static_loss_scale=1.0,
    dynamic_loss_scale=False)`` (fp16_optimizer.py:13-80)."""

    def __init__(self, init_optimizer, model_params: Tree,
                 static_loss_scale: float = 1.0,
                 dynamic_loss_scale: bool = False,
                 dynamic_loss_args: Optional[dict] = None,
                 verbose: bool = False):
        self.optimizer = init_optimizer
        self.model_params = model_params
        self.master_params = jax.tree_util.tree_map(
            lambda p: p.astype(jnp.float32), model_params)
        self.opt_state = init_optimizer.init(self.master_params)
        if dynamic_loss_scale:
            self.loss_scaler = DynamicLossScaler(**(dynamic_loss_args or {}))
        else:
            self.loss_scaler = LossScaler(static_loss_scale)
        self.overflow = False
        self._master_grads: Optional[Tree] = None
        self.verbose = verbose

    @property
    def loss_scale(self) -> float:
        return self.loss_scaler.loss_scale

    # -- reference API -----------------------------------------------------
    def scale_loss(self, loss):
        """Use as ``grads = jax.grad(lambda p: opt.scale_loss(loss_fn(p)))``
        — the explicit counterpart of ``optimizer.backward(loss)``
        (fp16_optimizer.py:373)."""
        return loss * self.loss_scale

    def backward(self, loss_fn, *args):
        """Eager convenience: computes scaled grads of ``loss_fn(model_params,
        *args)`` and stashes them (reference ``backward`` :373)."""
        grads = jax.grad(
            lambda p: loss_fn(p, *args) * self.loss_scale)(self.model_params)
        self.update_master_grads(grads)

    def update_master_grads(self, scaled_grads: Tree) -> None:
        """Unscale model grads into fp32 master grads + overflow check
        (reference :436)."""
        g32 = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32), scaled_grads)
        unscaled, overflow = ops.multi_tensor_scale(g32, 1.0 / self.loss_scale)
        self.overflow = bool(overflow)
        self._master_grads = unscaled

    def clip_master_grads(self, max_norm: float) -> float:
        """Global-norm clip on the master grads (reference :185)."""
        if self._master_grads is None:
            return 0.0
        self._master_grads, total = clip_grad_norm(self._master_grads,
                                                   max_norm)
        return float(total)

    def step(self) -> None:
        """Skip on overflow, else fused step on masters + copy back
        (reference step + _master_params_to_model_params)."""
        self.loss_scaler.update_scale(self.overflow)
        if self.overflow:
            if self.verbose:
                print(f"OVERFLOW! Skipping step, loss scale -> "
                      f"{self.loss_scale}")
            self._master_grads = None
            return
        assert self._master_grads is not None, \
            "call update_master_grads (or backward) before step"
        self.master_params, self.opt_state = self.optimizer.step(
            self._master_grads, self.master_params, self.opt_state)
        self.model_params = master_params_to_model_params(
            self.model_params, self.master_params)
        self._master_grads = None

    def zero_grad(self) -> None:
        self._master_grads = None

    # -- checkpointing (reference state_dict/load_state_dict) --------------
    def state_dict(self) -> dict:
        return {
            "loss_scaler": self.loss_scaler.state_dict(),
            "overflow": self.overflow,
            "master_params": jax.device_get(self.master_params),
            "opt_state": jax.device_get(self.opt_state),
        }

    def load_state_dict(self, d: dict) -> None:
        self.loss_scaler.load_state_dict(d["loss_scaler"])
        self.overflow = d["overflow"]
        self.master_params = jax.tree_util.tree_map(
            jnp.asarray, d["master_params"])
        self.opt_state = jax.tree_util.tree_map(jnp.asarray, d["opt_state"])
        self.model_params = master_params_to_model_params(
            self.model_params, self.master_params)
