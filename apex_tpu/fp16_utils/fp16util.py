"""Conversion helpers — parity with apex/fp16_utils/fp16util.py:22-173
(``network_to_half``, ``convert_network``, ``prep_param_lists``,
``master_params_to_model_params``, ``clip_grad_norm``), recast for pytrees:
a "network" is a params pytree; BN params are identified by path (the
reference checks module classes)."""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu import ops
from apex_tpu.amp.frontend import is_batchnorm_path

Tree = Any


def convert_network(params: Tree, dtype, *,
                    keep_batchnorm_fp32: bool = True,
                    bn_predicate: Callable = is_batchnorm_path) -> Tree:
    """Cast floating leaves to ``dtype``, keeping batchnorm-ish params fp32
    (fp16util.convert_network/BN_convert_float semantics)."""
    def cast(path, p):
        if not jnp.issubdtype(p.dtype, jnp.floating):
            return p
        if keep_batchnorm_fp32 and bn_predicate(path):
            return p.astype(jnp.float32)
        return p.astype(dtype)
    return jax.tree_util.tree_map_with_path(cast, params)


def network_to_half(params: Tree) -> Tree:
    """fp16util.network_to_half (:22)."""
    return convert_network(params, jnp.float16)


def network_to_bfloat16(params: Tree) -> Tree:
    """The fork's bf16 sibling."""
    return convert_network(params, jnp.bfloat16)


def prep_param_lists(params: Tree, flat_master: bool = False,
                     ) -> Tuple[Tree, Tree]:
    """(model_params, fp32 master copy); with ``flat_master`` the master is a
    single flat fp32 bucket (fp16util.prep_param_lists:81-120)."""
    master = jax.tree_util.tree_map(
        lambda p: p.astype(jnp.float32), params)
    if flat_master:
        buckets, spec = ops.tree_flatten_buckets(master)
        return params, (buckets, spec)
    return params, master


def master_params_to_model_params(model_params: Tree, master: Tree) -> Tree:
    """Copy master values into the model dtype (fp16util:129-143). Returns
    the new model params (functional)."""
    if isinstance(master, tuple) and len(master) == 2 and \
            hasattr(master[1], "bucket_specs"):
        master = ops.tree_unflatten_buckets(*master)
    return jax.tree_util.tree_map(
        lambda mp, p: mp.astype(p.dtype), master, model_params)


def model_grads_to_master_grads(grads: Tree) -> Tree:
    """fp32 copies of model grads (fp16util:122-127)."""
    return jax.tree_util.tree_map(lambda g: g.astype(jnp.float32), grads)


def clip_grad_norm(grads: Tree, max_norm: float,
                   ) -> Tuple[Tree, jax.Array]:
    """Global-norm clip (fp16util.clip_grad_norm:146-173). Returns
    (clipped_grads, total_norm)."""
    total, _ = ops.multi_tensor_l2norm(grads)
    coef = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    clipped = jax.tree_util.tree_map(
        lambda g: (g.astype(jnp.float32) * coef).astype(g.dtype), grads)
    return clipped, total


def to_python_float(x) -> float:
    """fp16util.to_python_float (host sync — use outside jit only)."""
    return float(jax.device_get(x))
