"""apex_tpu.fp16_utils (placeholder — populated incrementally)."""
