"""apex_tpu.fp16_utils — legacy manual mixed precision (reference L5,
apex/fp16_utils/: FP16_Optimizer, static/dynamic loss scalers, conversion
helpers). Deprecated-but-shipped in the reference; provided here for API
parity. New code should use apex_tpu.amp."""

from apex_tpu.fp16_utils.fp16util import (
    network_to_half,
    network_to_bfloat16,
    convert_network,
    prep_param_lists,
    master_params_to_model_params,
    model_grads_to_master_grads,
    clip_grad_norm,
    to_python_float,
)
from apex_tpu.fp16_utils.loss_scaler import LossScaler, DynamicLossScaler
from apex_tpu.fp16_utils.fp16_optimizer import FP16_Optimizer
