"""Legacy loss scalers — parity with apex/fp16_utils/loss_scaler.py
(``LossScaler`` static at :10, ``DynamicLossScaler`` at :47). These are thin
stateful shells over the functional scaler in apex_tpu.amp.scaler, kept for
the FP16_Optimizer legacy API. Host-side state; not for use inside jit
(use amp.LossScaler there)."""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from apex_tpu import ops


class LossScaler:
    """Static scale (reference loss_scaler.py:10-44)."""

    def __init__(self, scale: float = 1.0):
        self.cur_scale = float(scale)

    @property
    def loss_scale(self) -> float:
        return self.cur_scale

    def scale_gradient(self, grads):
        out, _ = ops.multi_tensor_scale(grads, self.cur_scale)
        return out

    def unscale(self, grads):
        out, overflow = ops.multi_tensor_scale(grads, 1.0 / self.cur_scale)
        return out, bool(overflow)

    def update_scale(self, overflow: bool) -> None:
        pass  # static

    def state_dict(self):
        return {"cur_scale": self.cur_scale}

    def load_state_dict(self, d):
        self.cur_scale = d["cur_scale"]


class DynamicLossScaler(LossScaler):
    """Dynamic scale (reference loss_scaler.py:47-…): x2 growth every
    ``scale_window`` clean iters, /2 backoff on overflow."""

    def __init__(self, init_scale: float = 2.0 ** 32,
                 scale_factor: float = 2.0, scale_window: int = 1000,
                 min_scale: float = 1.0):
        super().__init__(init_scale)
        self.scale_factor = scale_factor
        self.scale_window = scale_window
        self.min_scale = min_scale
        self.last_overflow_iter = -1
        self.cur_iter = 0

    def has_overflow(self, grads) -> bool:
        leaves = jax.tree_util.tree_leaves(grads)
        for l in leaves:
            if not bool(jnp.all(jnp.isfinite(l.astype(jnp.float32)))):
                return True
        return False

    def update_scale(self, overflow: bool) -> None:
        if overflow:
            self.cur_scale = max(self.cur_scale / self.scale_factor,
                                 self.min_scale)
            self.last_overflow_iter = self.cur_iter
        elif (self.cur_iter - self.last_overflow_iter) % \
                self.scale_window == 0 and self.cur_iter > 0:
            self.cur_scale *= self.scale_factor
        self.cur_iter += 1

    def state_dict(self):
        return {"cur_scale": self.cur_scale, "cur_iter": self.cur_iter,
                "last_overflow_iter": self.last_overflow_iter}

    def load_state_dict(self, d):
        self.cur_scale = d["cur_scale"]
        self.cur_iter = d["cur_iter"]
        self.last_overflow_iter = d["last_overflow_iter"]
