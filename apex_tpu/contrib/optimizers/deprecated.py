"""Deprecated contrib fused optimizers — parity with
apex/contrib/optimizers/{fused_adam,fused_sgd,fused_lamb}.py (the older API
taking explicit ``grads``/``output_params``/``scale`` step arguments, kept in
the reference for backward compatibility) and their bundled
``FP16_Optimizer`` (fp16_optimizer.py:4-243).

These shims delegate to the modern apex_tpu.optimizers implementations while
honoring the old call signature: ``step(grads=..., output_params=...,
scale=...)`` where output_params receive the low-precision copy of the
updated master params (the "fp16 model copy" the old kernels wrote)."""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu import optimizers as _opt
from apex_tpu.fp16_utils.fp16_optimizer import FP16_Optimizer  # re-export

Tree = Any


class _DeprecatedShim:
    _inner_cls = None

    def __init__(self, params: Tree, *args, **kwargs):
        kwargs.pop("use_mt", None)
        kwargs.pop("amp_scale_adjustment", None)
        self.inner = self._inner_cls(*args, **kwargs)
        self.params = params
        self.state = self.inner.init(params)

    def step(self, closure=None, grads: Optional[Tree] = None,
             output_params: Optional[Tree] = None,
             scale: float = 1.0, grad_norms=None):
        """Old-style step: explicit grads, optional fused 1/scale, optional
        low-precision output copy (contrib fused_adam.py's signature)."""
        if grads is None:
            raise ValueError("deprecated contrib optimizers require "
                             "explicit grads= (as in the reference)")
        self.params, self.state = self.inner.step(
            grads, self.params, self.state,
            grad_scale=jnp.asarray(scale, jnp.float32)
            if scale != 1.0 else None)
        if output_params is not None:
            out = jax.tree_util.tree_map(
                lambda mp, op: mp.astype(op.dtype), self.params,
                output_params)
            return self.params, out
        return self.params


class FusedAdam(_DeprecatedShim):
    """apex/contrib/optimizers/fused_adam.py (206 LoC) shim."""
    _inner_cls = _opt.FusedAdam


class FusedSGD(_DeprecatedShim):
    """apex/contrib/optimizers/fused_sgd.py (211 LoC) shim."""
    _inner_cls = _opt.FusedSGD


class FusedLAMB(_DeprecatedShim):
    """apex/contrib/optimizers/fused_lamb.py (208 LoC) shim."""
    _inner_cls = _opt.FusedLAMB
