"""apex_tpu.contrib.optimizers (placeholder — populated incrementally)."""
