"""apex_tpu.contrib.optimizers — ZeRO-style sharded distributed optimizers +
deprecated legacy shims (reference apex/contrib/optimizers/)."""

from apex_tpu.contrib.optimizers.zero import (
    DistributedFusedAdam,
    DistributedFusedLAMB,
    ZeroState,
)
from apex_tpu.contrib.optimizers import deprecated
from apex_tpu.contrib.optimizers.deprecated import (
    FP16_Optimizer,
    FusedAdam,
    FusedSGD,
    FusedLAMB,
)
