"""apex_tpu.contrib.optimizers — ZeRO-style sharded distributed optimizers
(reference apex/contrib/optimizers/)."""

from apex_tpu.contrib.optimizers.zero import (
    DistributedFusedAdam,
    DistributedFusedLAMB,
    ZeroState,
)
