"""ZeRO-style sharded-state distributed optimizers — the TPU-native redesign
of ``apex.contrib.optimizers.DistributedFusedAdam`` (v1/v2/v3,
apex/contrib/optimizers/distributed_fused_adam.py:43-407) and
``DistributedFusedLAMB`` (distributed_fused_lamb.py:7-607).

Reference pipeline (SURVEY.md §2.3): flatten all grads into blocks/chunks/
shards -> chunked async ``reduce_scatter`` overlapped with backward -> each
rank steps Adam on its shard (fp32 master + moments sharded dwu_group_size
ways) -> ``all_gather`` updated params -> optional compressed allgather;
separate process groups per communication role; GPU L2-norm; step-revert for
late overflow.

TPU-native mapping:
  * reduce_scatter       -> ``lax.psum_scatter(..., tiled=True)`` over a mesh
                            axis (rides ICI; XLA pipelines it with backward)
  * sharded step         -> the same Pallas/jnp fused update, on the local
                            flat shard (state arrays are sharded over the
                            axis: use ``state_sharding()``)
  * all_gather params    -> ``lax.all_gather(..., tiled=True)``
  * multiple comm PGs / streams -> XLA latency-hiding scheduler
  * compressed allgather (e5m2 flag) -> ``allgather_dtype=jnp.bfloat16``
  * step-revert on overflow (revert_method 1-3) -> free: the functional step
    returns the previous state under ``lax.cond`` — nothing to undo.

Usage: ``step`` must run inside shard_map with the flat state sharded::

    opt = DistributedFusedAdam(lr=1e-3, axis_name="data")
    state = opt.init(params)                       # flat fp32 arrays
    # in_specs: params replicated P(), state opt.state_pspec()
    new_params, new_state = opt.step(grads, params, state)
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu.ops import buckets as _buckets
from apex_tpu.optimizers.base import FusedOptimizer, Schedule, resolve_lr

Tree = Any


class ZeroState(NamedTuple):
    step: jax.Array        # i32 scalar (replicated)
    master: jax.Array      # (padded_total,) f32 — shard over axis
    exp_avg: jax.Array     # (padded_total,) f32 — shard over axis
    exp_avg_sq: jax.Array  # (padded_total,) f32 — shard over axis


def _flatten_f32(tree: Tree, pad_to: int) -> Tuple[jax.Array, Any]:
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    flat = jnp.concatenate(
        [l.astype(jnp.float32).reshape(-1) for l in leaves])
    n = flat.shape[0]
    if pad_to > n:
        flat = jnp.pad(flat, (0, pad_to - n))
    return flat, treedef


class _ZeroBase(FusedOptimizer):
    """Shared flatten/scatter/gather plumbing."""

    def __init__(self, *, axis_name: str = "data",
                 shard_count: Optional[int] = None,
                 allgather_dtype=None):
        self.axis_name = axis_name
        self._shard_count = shard_count  # resolved lazily from the mesh
        self.allgather_dtype = allgather_dtype
        self._spec_cache = None

    # -- static packing metadata ------------------------------------------
    def _pack(self, params: Tree):
        leaves, treedef = jax.tree_util.tree_flatten(params)
        shapes = [tuple(l.shape) for l in leaves]
        sizes = [int(np.prod(s)) if s else 1 for s in shapes]
        offsets = np.cumsum([0] + sizes[:-1])
        total = int(sum(sizes))
        n = self.shard_count
        padded = ((total + n - 1) // n) * n
        self._spec_cache = dict(
            treedef=treedef, shapes=shapes, sizes=sizes,
            offsets=offsets, total=total, padded=padded,
            dtypes=[l.dtype for l in leaves])
        return self._spec_cache

    @property
    def shard_count(self) -> int:
        if self._shard_count is not None:
            return self._shard_count
        return len(jax.devices())

    def state_pspec(self) -> ZeroState:
        """PartitionSpecs for shard_map in_specs/out_specs of the state."""
        ax = self.axis_name
        return ZeroState(step=P(), master=P(ax), exp_avg=P(ax),
                         exp_avg_sq=P(ax))

    # -- state -------------------------------------------------------------
    def init(self, params: Tree) -> ZeroState:
        spec = self._pack(params)
        flat, _ = _flatten_f32(params, spec["padded"])
        return ZeroState(
            step=jnp.zeros((), jnp.int32),
            master=flat,
            exp_avg=jnp.zeros((spec["padded"],), jnp.float32),
            exp_avg_sq=jnp.zeros((spec["padded"],), jnp.float32),
        )

    # -- collectives -------------------------------------------------------
    def _scatter_grads(self, grads: Tree, spec) -> jax.Array:
        """Replicated grad tree -> reduced local shard (mean over axis).

        The analog of the chunked async reduce_scatter at
        distributed_fused_adam.py:297-331.
        """
        flat, _ = _flatten_f32(grads, spec["padded"])
        world = jax.lax.axis_size(self.axis_name)
        return jax.lax.psum_scatter(
            flat, self.axis_name, scatter_dimension=0, tiled=True) / world

    def _gather_params(self, master_shard: jax.Array, spec,
                       params: Tree) -> Tree:
        """Local updated shard -> replicated param tree (the parameter
        all_gather at distributed_fused_adam.py:392-407; optionally in a
        compressed dtype like the e5m2 allgather flag)."""
        send = master_shard
        if self.allgather_dtype is not None:
            send = send.astype(self.allgather_dtype)
        flat = jax.lax.all_gather(send, self.axis_name, tiled=True)
        leaves = []
        for off, size, shape, dt in zip(spec["offsets"], spec["sizes"],
                                        spec["shapes"], spec["dtypes"]):
            leaves.append(
                jax.lax.dynamic_slice_in_dim(flat, int(off), size)
                .reshape(shape).astype(dt))
        return jax.tree_util.tree_unflatten(spec["treedef"], leaves)

    def _shard_positions(self, spec) -> jax.Array:
        """Global flat indices covered by this device's shard."""
        k = spec["padded"] // jax.lax.axis_size(self.axis_name)
        r = jax.lax.axis_index(self.axis_name)
        return r * k + jnp.arange(k)

    def global_grad_norm(self, g_shard: jax.Array) -> jax.Array:
        """Sharded L2 norm -> psum (the l2-grad-norm process group,
        distributed_fused_adam.py:352)."""
        return jnp.sqrt(jax.lax.psum(jnp.sum(g_shard * g_shard),
                                     self.axis_name))


class DistributedFusedAdam(_ZeroBase):
    """ZeRO sharded Adam/AdamW (reference distributed_fused_adam.py).

    Hyperparameter surface mirrors FusedAdam; overflow handling ("revert")
    is expressed by the caller via lax.cond (AmpOptimizer composes cleanly:
    the step is pure, so skipping == keeping the old state).
    """

    def __init__(self, lr: Schedule = 1e-3, *, bias_correction: bool = True,
                 betas: Tuple[float, float] = (0.9, 0.999), eps: float = 1e-8,
                 adam_w_mode: bool = True, weight_decay: float = 0.0,
                 axis_name: str = "data", shard_count: Optional[int] = None,
                 allgather_dtype=None):
        super().__init__(axis_name=axis_name, shard_count=shard_count,
                         allgather_dtype=allgather_dtype)
        self.lr = lr
        self.bias_correction = bias_correction
        self.betas = betas
        self.eps = eps
        self.adam_w_mode = adam_w_mode
        self.weight_decay = weight_decay

    def step(self, grads: Tree, params: Tree, state: ZeroState, *,
             grad_scale: Optional[jax.Array] = None,
             ) -> Tuple[Tree, ZeroState]:
        spec = self._spec_cache or self._pack(params)
        step = state.step + 1
        g = self._scatter_grads(grads, spec)
        if grad_scale is not None:
            g = g / grad_scale

        b1, b2 = self.betas
        stepf = step.astype(jnp.float32)
        bc1 = 1.0 - b1 ** stepf if self.bias_correction else 1.0
        bc2 = 1.0 - b2 ** stepf if self.bias_correction else 1.0

        p = state.master
        if not self.adam_w_mode and self.weight_decay != 0.0:
            g = g + self.weight_decay * p
        m = b1 * state.exp_avg + (1.0 - b1) * g
        v = b2 * state.exp_avg_sq + (1.0 - b2) * g * g
        update = (m / bc1) / (jnp.sqrt(v / bc2) + self.eps)
        if self.adam_w_mode and self.weight_decay != 0.0:
            update = update + self.weight_decay * p
        new_master = p - resolve_lr(self.lr, step) * update

        new_params = self._gather_params(new_master, spec, params)
        return new_params, ZeroState(step=step, master=new_master,
                                     exp_avg=m, exp_avg_sq=v)


class DistributedFusedLAMB(_ZeroBase):
    """ZeRO sharded LAMB (reference distributed_fused_lamb.py:7-607):
    global grad-norm clip, sharded Adam moments, per-tensor trust ratios
    computed via segmented reductions over the flat shards + psum — the
    TPU analog of the distributed_lamb_cuda segmented-norm kernels."""

    def __init__(self, lr: Schedule = 1e-3, *, bias_correction: bool = True,
                 betas: Tuple[float, float] = (0.9, 0.999), eps: float = 1e-6,
                 weight_decay: float = 0.01, adam_w_mode: bool = True,
                 grad_averaging: bool = True, max_grad_norm: float = 1.0,
                 use_nvlamb: bool = False, axis_name: str = "data",
                 shard_count: Optional[int] = None, allgather_dtype=None):
        super().__init__(axis_name=axis_name, shard_count=shard_count,
                         allgather_dtype=allgather_dtype)
        self.lr = lr
        self.bias_correction = bias_correction
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adam_w_mode = adam_w_mode
        self.grad_averaging = grad_averaging
        self.max_grad_norm = max_grad_norm
        self.use_nvlamb = use_nvlamb

    def step(self, grads: Tree, params: Tree, state: ZeroState, *,
             grad_scale: Optional[jax.Array] = None,
             ) -> Tuple[Tree, ZeroState]:
        spec = self._spec_cache or self._pack(params)
        num_tensors = len(spec["sizes"])
        step = state.step + 1
        g = self._scatter_grads(grads, spec)
        if grad_scale is not None:
            g = g / grad_scale

        # Global grad-norm clip (stage 1).
        gnorm = self.global_grad_norm(g)
        if self.max_grad_norm > 0:
            clip = jnp.where(gnorm > self.max_grad_norm,
                             gnorm / self.max_grad_norm, 1.0)
            g = g / clip

        b1, b2 = self.betas
        stepf = step.astype(jnp.float32)
        bc1 = 1.0 - b1 ** stepf if self.bias_correction else 1.0
        bc2 = 1.0 - b2 ** stepf if self.bias_correction else 1.0
        beta3 = (1.0 - b1) if self.grad_averaging else 1.0

        p = state.master
        if not self.adam_w_mode and self.weight_decay != 0.0:
            g = g + self.weight_decay * p
        m = b1 * state.exp_avg + beta3 * g
        v = b2 * state.exp_avg_sq + (1.0 - b2) * g * g
        update = (m / bc1) / (jnp.sqrt(v / bc2) + self.eps)
        if self.adam_w_mode and self.weight_decay != 0.0:
            update = update + self.weight_decay * p

        # Per-tensor norms across shard boundaries: segment ids from static
        # tensor offsets, psum'd partial sums (distributed_lamb's two-stage
        # segmented reduction).
        pos = self._shard_positions(spec)
        bounds = jnp.asarray(
            np.cumsum(spec["sizes"]), jnp.int32)  # tensor end offsets
        seg = jnp.searchsorted(bounds, pos, side="right")
        seg = jnp.minimum(seg, num_tensors - 1)  # padding -> last segment
        in_range = pos < spec["total"]
        p_sq = jnp.where(in_range, p * p, 0.0)
        u_sq = jnp.where(in_range, update * update, 0.0)
        p_norms = jnp.sqrt(jax.lax.psum(
            jax.ops.segment_sum(p_sq, seg, num_segments=num_tensors),
            self.axis_name))
        u_norms = jnp.sqrt(jax.lax.psum(
            jax.ops.segment_sum(u_sq, seg, num_segments=num_tensors),
            self.axis_name))

        use_ratio = (self.weight_decay != 0.0) or self.use_nvlamb
        if use_ratio:
            ratios = jnp.where((p_norms > 0) & (u_norms > 0),
                               p_norms / u_norms, 1.0)
        else:
            ratios = jnp.ones((num_tensors,), jnp.float32)
        new_master = p - resolve_lr(self.lr, step) * ratios[seg] * update

        new_params = self._gather_params(new_master, spec, params)
        return new_params, ZeroState(step=step, master=new_master,
                                     exp_avg=m, exp_avg_sq=v)
