"""ZeRO-style sharded-state distributed optimizers — the TPU-native redesign
of ``apex.contrib.optimizers.DistributedFusedAdam`` (v1/v2/v3,
apex/contrib/optimizers/distributed_fused_adam.py:43-407) and
``DistributedFusedLAMB`` (distributed_fused_lamb.py:7-607).

Reference pipeline (SURVEY.md §2.3): flatten all grads into blocks/chunks/
shards -> chunked async ``reduce_scatter`` overlapped with backward -> each
rank steps Adam on its shard (fp32 master + moments sharded dwu_group_size
ways) -> ``all_gather`` updated params -> optional compressed allgather;
separate process groups per communication role; GPU L2-norm; step-revert for
late overflow.

TPU-native mapping:
  * reduce_scatter       -> ``lax.psum_scatter(..., tiled=True)`` over a mesh
                            axis (rides ICI; XLA pipelines it with backward)
  * sharded step         -> the same Pallas/jnp fused update, on the local
                            flat shard (state arrays are sharded over the
                            axis: use ``state_sharding()``)
  * all_gather params    -> ``lax.all_gather(..., tiled=True)``
  * multiple comm PGs / streams -> XLA latency-hiding scheduler
  * compressed allgather (e5m2 flag) -> ``allgather_dtype=jnp.bfloat16``
  * compressed grad reduction -> ``reduce_dtype="bf16"`` (16-bit wire for
    the reduce-scatter, fp32 accumulation — docs/overlap.md contract);
    ``"int8"`` steps down to the integer tier: per-bucket symmetric
    scale agreed via pmax pre-collective, s8 psum_scatter (the scale
    bound makes the integer sum exact), fp32 dequantize after
  * step-revert on overflow (revert_method 1-3) -> free: the functional step
    returns the previous state under ``lax.cond`` — nothing to undo.
  * ``dwu_group_size`` subgroup sharding (state sharded over a subgroup,
    gradients allreduced across subgroups,
    distributed_fused_adam.py:251-289) -> a 2-D mesh: state shards over
    ``axis_name`` (the subgroup) and replicates over ``group_axis`` (the
    cross-group reduction axis). ``shard_count`` must equal the size of
    ``axis_name`` and is validated at trace time (a mismatch raises rather
    than silently mis-sharding).

Usage: ``step`` must run inside shard_map with the flat state sharded::

    opt = DistributedFusedAdam(lr=1e-3, axis_name="data")
    state = opt.init(params)                       # flat fp32 arrays
    # in_specs: params replicated P(), state opt.state_pspec()
    new_params, new_state = opt.step(grads, params, state)

Subgroup (dwu_group_size) form on a 2-D mesh ``('replica', 'data')``::

    opt = DistributedFusedAdam(lr=1e-3, axis_name="data",
                               group_axis="replica", shard_count=4)
    # state shards over 'data' within each replica group; grads are
    # reduce-scattered over 'data' then allreduced over 'replica'.

Per-group hyperparameters (``param_groups``, optimizers/base.py) are
supported for ``lr`` and ``weight_decay``: per-leaf overrides become
per-element vectors over the flat shard via the same static segment map used
for the LAMB per-tensor norms. Other overrides raise (no per-element form).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu.ops import buckets as _buckets
from apex_tpu.optimizers.base import FusedOptimizer, Schedule, resolve_lr
from apex_tpu.parallel.mesh import bound_axis_size

Tree = Any


class ZeroState(NamedTuple):
    step: jax.Array        # i32 scalar (replicated)
    master: jax.Array      # (padded_total,) f32 — shard over axis
    exp_avg: jax.Array     # (padded_total,) f32 — shard over axis
    exp_avg_sq: jax.Array  # (padded_total,) f32 — shard over axis


def pack_layout(params: Tree, *, chunk_elements: int,
                shard_count: int) -> dict:
    """Deterministic flat-layout spec for ``(params, chunk_elements,
    shard_count)`` — the pure function underneath :meth:`_ZeroBase._pack`
    (which adds tune resolution and param-group maps on top).

    Standalone because the layout must be reconstructible from a
    checkpoint's :meth:`~_ZeroBase.layout_fingerprint` alone: the elastic
    re-shard path (:mod:`apex_tpu.resilience.elastic`) rebuilds the
    SOURCE world's spec from the saved fingerprint and the live params
    tree, then re-maps every flat element into the target world's spec.
    """
    if chunk_elements < 0:
        raise ValueError(
            f"chunk_elements must be >= 0, got {chunk_elements}")
    if shard_count < 1:
        raise ValueError(f"shard_count must be >= 1, got {shard_count}")
    leaves, treedef = jax.tree_util.tree_flatten(params)
    shapes = [tuple(l.shape) for l in leaves]
    sizes = [int(np.prod(s)) if s else 1 for s in shapes]
    offsets = np.cumsum([0] + sizes[:-1])
    total = int(sum(sizes))
    n = int(shard_count)
    # Contiguous-leaf buckets of at most chunk_elements each; a single
    # oversize leaf forms its own bucket (leaves never split).
    runs = _buckets.partition_by_capacity(sizes, chunk_elements)
    buckets = []
    for idxs in runs:
        size_b = int(sum(sizes[i] for i in idxs))
        padded_b = ((size_b + n - 1) // n) * n
        buckets.append(dict(
            idxs=tuple(idxs),
            start=int(offsets[idxs[0]]),   # canonical flat offset
            size=size_b,
            padded=padded_b,
            k=padded_b // n))              # local shard elements
    padded = int(sum(b["padded"] for b in buckets))
    return dict(
        treedef=treedef, shapes=shapes, sizes=sizes, offsets=offsets,
        total=total, padded=padded, buckets=buckets,
        chunk_elements=int(chunk_elements), shard_count=n,
        dtypes=[l.dtype for l in leaves])


def structure_crc(params: Tree) -> int:
    """Canonical (path, shape) crc32 of a param tree — the fingerprint
    field that distinguishes "same tree, different world" (re-shardable)
    from "different tree" (structurally incompatible). Leaf ORDER and
    shapes determine the interleaved layout even when the aggregate
    counts coincide (two equal-size layers swapped, a transposed
    kernel, ...); PyTreeDef repr is deliberately NOT hashed — its format
    is not stable across jax versions."""
    import zlib

    from apex_tpu.utils import path_str
    pairs = [(path_str(p), tuple(l.shape)) for p, l in
             jax.tree_util.tree_flatten_with_path(params)[0]]
    return int(zlib.crc32(repr(pairs).encode()))


def _bucket_flat(leaves, idxs, pad_to: int) -> jax.Array:
    """Concat ONLY the given leaves (f32, raveled) and zero-pad to pad_to.
    Keeping the concat per bucket — not per tree — is what lets each
    bucket's reduce-scatter depend on a prefix of backward instead of all
    of it (the reference's chunked async reduce_scatter overlap,
    distributed_fused_adam.py:297-331)."""
    flat = jnp.concatenate(
        [leaves[i].astype(jnp.float32).reshape(-1) for i in idxs])
    n = flat.shape[0]
    if pad_to > n:
        flat = jnp.pad(flat, (0, pad_to - n))
    return flat


class _ZeroBase(FusedOptimizer):
    """Shared flatten/scatter/gather plumbing.

    State layout: params partition into contiguous-leaf *buckets* of at
    most ``chunk_elements`` elements; each bucket pads to a multiple of
    ``shard_count`` and shards over ``axis_name``. A device's local state
    is the concatenation of its shard of every bucket, so the global flat
    array (what ``P(axis_name)`` sees) is bucket-shard-interleaved — init,
    scatter, gather, and the position/segment maps all speak this layout.
    """

    def __init__(self, *, axis_name: str = "data",
                 shard_count: Optional[int] = None,
                 group_axis: Optional[str] = None,
                 allgather_dtype=None, param_groups=None,
                 chunk_elements: Optional[int] = None,
                 reduce_dtype=None):
        from apex_tpu.parallel import overlap as _overlap
        self.axis_name = axis_name
        self._shard_count = shard_count  # resolved lazily from the mesh
        # Narrow wire format for the gradient reduce-scatter (the inbound
        # analog of the compressed allgather): each bucket is pre-scaled
        # by the full data-parallel world and cast before psum_scatter,
        # and the local shard returns to fp32 immediately after — master
        # weights and moments always accumulate fp32
        # (apex_tpu.parallel.overlap numerics contract, docs/overlap.md).
        # Does NOT participate in the flat state layout: fingerprints and
        # checkpoints are compatible across reduce_dtype changes.
        self.reduce_dtype = _overlap.resolve_reduce_dtype(reduce_dtype)
        # Mesh axis ACROSS which optimizer state is replicated (the
        # dwu_group_size analog): grads are reduce-scattered over axis_name
        # (within the subgroup) and allreduced over group_axis.
        self.group_axis = group_axis
        self.allgather_dtype = allgather_dtype
        # Bucket capacity (elements) for the overlap-friendly chunked
        # reduce-scatter/all-gather (reference dwu chunking,
        # distributed_fused_adam.py:297-331). None (default): resolved
        # through apex_tpu.tune at first _pack (the frozen 2**23 under
        # APEX_TPU_TUNE=off). 0: one whole-tree bucket. The RESOLVED
        # value participates in the ZeroState flat layout and is recorded
        # by layout_fingerprint. Negative values raise here, not at some
        # deep trace site.
        if chunk_elements is not None and chunk_elements < 0:
            raise ValueError(
                f"chunk_elements must be >= 1 (or 0 for one whole-tree "
                f"bucket, or None to resolve via apex_tpu.tune); got "
                f"{chunk_elements}")
        self.chunk_elements = chunk_elements
        self._spec_cache = None
        self._init_groups(param_groups)

    # Overrides the ZeRO flat-shard math supports per element; anything else
    # must fail loudly rather than silently using the default.
    _GROUP_OVERRIDES_SUPPORTED = ("lr", "weight_decay")

    def add_param_group(self, group) -> None:
        super().add_param_group(group)
        self._spec_cache = None  # re-pack: the group->tensor map changed

    def extend_init(self, old_state, new_params):
        # The base-class carry-over walks per-leaf _TREE_FIELDS; ZeRO state
        # is flat sharded arrays (no per-leaf paths), so the inherited
        # version would silently ZERO the moments and rebuild the master
        # from the passed params. Fail loudly instead of corrupting
        # mid-training state.
        raise NotImplementedError(
            "extend_init is not supported for ZeRO optimizers: their state "
            "is flat sharded buffers, not per-leaf trees, so carrying state "
            "over a param-tree change would require resharding. Re-init "
            "the optimizer state, or add params before training starts.")

    # -- static packing metadata ------------------------------------------
    def _pack(self, params: Tree):
        n = self.shard_count
        from apex_tpu import tune
        chunk_elements = self.chunk_elements
        if chunk_elements is None:
            leaves = jax.tree_util.tree_leaves(params)
            total = int(sum(int(np.prod(l.shape)) if l.shape else 1
                            for l in leaves))
            chunk_elements = tune.zero_chunk_elements(total=total, world=n)
        spec = pack_layout(params, chunk_elements=chunk_elements,
                           shard_count=n)
        tune.warn_bucket_count("zero", len(spec["buckets"]),
                               chunk_elements)
        # Per-tensor param-group assignment (index into override table).
        group_of_tensor = np.zeros((len(spec["sizes"]),), np.int32)
        overrides: list = [{}]
        if self.param_groups:
            for g in self.param_groups:
                unsupported = [k for k in g
                               if k != "filter"
                               and k not in self._GROUP_OVERRIDES_SUPPORTED]
                if unsupported:
                    raise ValueError(
                        f"ZeRO param groups support only "
                        f"{self._GROUP_OVERRIDES_SUPPORTED} overrides; got "
                        f"{unsupported} (per-element vectors exist only for "
                        "lr/weight_decay)")
            for idxs, ov in self.group_assignments(params):
                gi = 0 if not ov else len(overrides)
                if ov:
                    overrides.append(ov)
                for i in idxs:
                    group_of_tensor[i] = gi
        spec["group_of_tensor"] = group_of_tensor
        spec["group_overrides"] = overrides
        self._spec_cache = spec
        return self._spec_cache

    @property
    def shard_count(self) -> int:
        if self._shard_count is not None:
            return self._shard_count
        return len(jax.devices())

    def _check_axes(self):
        """Trace-time validation: shard_count must equal the axis size (the
        silent-mis-shard hazard the reference's dwu_group_size avoids by
        construction)."""
        n = bound_axis_size(self.axis_name)
        if n != self.shard_count:
            raise ValueError(
                f"shard_count={self.shard_count} != size({self.axis_name})="
                f"{n}. State shards over the full '{self.axis_name}' axis; "
                "for subgroup sharding (dwu_group_size) put the subgroup on "
                "its own mesh axis and pass group_axis for the cross-group "
                "reduction axis.")

    def layout_fingerprint(self, params: Tree) -> dict:
        """The facts that determine ZeroState's flat layout (r3 ADVICE:
        the bucket-shard-interleaved layout depends on chunk_elements /
        shard_count / the leaf structure, and a checkpoint saved under a
        DIFFERENT layout restores into a scrambled master with no error —
        nothing in the arrays records the layout). Save this next to the
        state (plain dict of ints — any checkpointer can carry it) and
        call :meth:`check_layout` after restore."""
        # Always pack THESE params — the cache may hold an earlier tree's
        # spec, and a fingerprint of the wrong tree defeats the guard —
        # but restore the cache afterwards: _pack overwrites it, and
        # fingerprinting a CANDIDATE tree must not poison the spec a live
        # step() will reuse for the training tree.
        prev = self._spec_cache
        try:
            spec = self._pack(params)
        finally:
            self._spec_cache = prev
        return {
            # the RESOLVED capacity (chunk_elements=None routes through
            # apex_tpu.tune): the layout guard must record what actually
            # shaped the flat arrays, not the constructor sentinel
            "chunk_elements": int(spec["chunk_elements"]),
            "shard_count": int(self.shard_count),
            "total": int(spec["total"]),
            "padded": int(spec["padded"]),
            "n_buckets": len(spec["buckets"]),
            "structure_crc32": structure_crc(params),
        }

    def layout_mismatch(self, saved: Optional[dict],
                        params: Tree) -> dict:
        """``{field: (saved, current)}`` for every fingerprint field on
        which a recorded layout disagrees with the one THIS optimizer
        would use for ``params`` (empty = compatible). ``saved=None`` —
        a checkpoint that never recorded a layout — mismatches on every
        field. Shared by :meth:`check_layout` and the resilience
        manifest validation (``resilience.SnapshotManager`` stores
        :meth:`layout_fingerprint` under the manifest's ``layout`` key
        and refuses to restore across a mismatch). Keys present ONLY in
        the saved fingerprint mismatch too: a WEIGHTED snapshot
        (``weights`` key, apex_tpu.resilience.rebalance) restored by an
        equal-shard optimizer would otherwise pass every current-key
        compare and load member-scrambled state."""
        current = self.layout_fingerprint(params)
        saved = saved if isinstance(saved, dict) else {}
        out = {k: (saved.get(k), v) for k, v in current.items()
               if saved.get(k) != v}
        for k, v in saved.items():
            if k not in current:
                out[k] = (v, None)
        return out

    def check_layout(self, saved: dict, params: Tree) -> None:
        """Raise if a restored ZeroState's recorded layout differs from
        the layout THIS optimizer would use for ``params`` — the loud
        failure that replaces silent master/moment scrambling when
        chunk_elements / shard_count changed between save and load."""
        bad = self.layout_mismatch(saved, params)
        if bad:
            # one classifier for saved-vs-live layout pairs (elastic
            # module doc) — lazy import keeps the optimizer importable
            # without the resilience package in degraded environments
            from apex_tpu.resilience import elastic as _elastic
            kind, reason = _elastic.classify_reshard(
                saved, self.layout_fingerprint(params))
            if kind == _elastic.RESHARDABLE:
                hint = (
                    "Same param tree, different world/chunk resolution "
                    f"({reason}): the state re-maps deterministically — "
                    "use apex_tpu.resilience.elastic (reshard_restore / "
                    "resilient_loop(..., elastic=...)) to materialize "
                    "it at this layout.")
            elif kind == _elastic.STRUCTURAL:
                hint = (f"{reason}; re-create the optimizer with the "
                        "saved configuration, or re-initialize the "
                        "state from params.")
            else:
                hint = ("The saved layout is not a complete ZeRO "
                        f"fingerprint ({reason}), so it cannot be "
                        "re-shard-restored; re-initialize the state "
                        "from params.")
            raise ValueError(
                "ZeroState layout mismatch — the checkpoint was saved "
                "under a different flat layout and would restore "
                f"scrambled. saved vs current: {bad}. {hint}")

    def state_pspec(self) -> ZeroState:
        """PartitionSpecs for shard_map in_specs/out_specs of the state.

        With ``group_axis`` the state is sharded over ``axis_name`` and
        replicated over ``group_axis`` — exactly what P(axis_name) means on
        a 2-D mesh."""
        ax = self.axis_name
        return ZeroState(step=P(), master=P(ax), exp_avg=P(ax),
                         exp_avg_sq=P(ax))

    # -- state -------------------------------------------------------------
    def init(self, params: Tree) -> ZeroState:
        """Build the GLOBAL state arrays in the bucket-shard-interleaved
        layout: global[r*K : (r+1)*K] is device r's shard, itself the
        concat of that device's slice of every bucket. Sharding the result
        with ``P(axis_name)`` therefore hands each device exactly the
        slices ``step`` expects."""
        spec = self._pack(params)
        leaves = jax.tree_util.tree_leaves(params)
        n = self.shard_count
        cols = [_bucket_flat(leaves, b["idxs"], b["padded"])
                .reshape(n, b["k"]) for b in spec["buckets"]]
        master = (cols[0] if len(cols) == 1
                  else jnp.concatenate(cols, axis=1)).reshape(-1)
        return ZeroState(
            step=jnp.zeros((), jnp.int32),
            master=master,
            exp_avg=jnp.zeros((spec["padded"],), jnp.float32),
            exp_avg_sq=jnp.zeros((spec["padded"],), jnp.float32),
        )

    # -- collectives -------------------------------------------------------
    def _scatter_grads(self, grads: Tree, spec,
                       telemetry_step=None) -> jax.Array:
        """Replicated grad tree -> reduced local shard (mean over the full
        data-parallel world).

        The analog of the chunked async reduce_scatter at
        distributed_fused_adam.py:297-331 — and, as of r3, with the same
        overlap property: each bucket's psum_scatter consumes a concat of
        only that bucket's leaves, so XLA can issue it as soon as those
        gradients exist. With ``group_axis`` set this is reduce-scatter
        within the subgroup + allreduce across subgroups (the
        dwu_group_size two-level scheme, :251-289)."""
        self._check_axes()
        leaves = jax.tree_util.tree_leaves(grads)
        world = bound_axis_size(self.axis_name)
        if self.group_axis is not None:
            world = world * bound_axis_size(self.group_axis)

        from apex_tpu import telemetry
        if telemetry.enabled():
            # trace-time static accounting: per-device bytes entering
            # the chunked reduce-scatter each step at the WIRE dtype
            # (f32, or reduce_dtype when compressed; + the cross-group
            # psum when subgrouped); (n-1)/n ring wire bill per axis.
            n = bound_axis_size(self.axis_name)
            item = 4 if self.reduce_dtype is None \
                else self.reduce_dtype.itemsize
            nbytes = item * int(sum(b["padded"] for b in spec["buckets"]))
            meta = {"axis": self.axis_name, "primitive": "psum_scatter",
                    "count": len(spec["buckets"]), "world": n,
                    "bytes_wire": round(nbytes * (n - 1) / n)}
            if self.reduce_dtype is not None:
                meta["reduce_dtype"] = self.reduce_dtype.name
            telemetry.record_static(
                f"zero/{self.axis_name}/reduce_scatter_bytes", nbytes,
                meta=meta,
                dedup_key=(self.axis_name, nbytes, len(spec["buckets"]),
                           item))
            if self.group_axis is not None:
                gn = bound_axis_size(self.group_axis)
                # the cross-subgroup psum deliberately stays fp32 even
                # when the scatter is compressed (see below), so bill it
                # at 4 bytes/element, not the scatter's wire itemsize
                gbytes = 4 * int(sum(b["padded"]
                                     for b in spec["buckets"])) // n
                telemetry.record_static(
                    f"zero/{self.group_axis}/allreduce_bytes", gbytes,
                    meta={"axis": self.group_axis, "primitive": "psum",
                          "count": len(spec["buckets"]), "world": gn,
                          "bytes_wire": round(gbytes * 2 * (gn - 1) / gn)},
                    dedup_key=(self.group_axis, gbytes,
                               len(spec["buckets"])))
        # the named scope tags every bucket's psum_scatter (and the
        # cross-subgroup psum) in XLA metadata, so profiler traces
        # attribute this comm to ZeRO (pyprof.capture's collective/zero
        # bucket) — metadata only, the traced program is unchanged
        shards = []
        with jax.named_scope("apex_zero_reduce_scatter"):
            for b in spec["buckets"]:
                flat = _bucket_flat(leaves, b["idxs"], b["padded"])
                if self.reduce_dtype == jnp.int8:
                    # int8 tier: mean-predivide, then quantize at the
                    # axis-agreed per-bucket scale (pmax of a scalar).
                    # The w-aware scale bound keeps the s8 psum_scatter's
                    # integer accumulation exact; dequantize lands fp32.
                    # Cross-group psum (below) stays fp32 as for the
                    # float tiers.
                    from apex_tpu.parallel import overlap as _ov
                    y = (flat / world).astype(jnp.float32)
                    a = jax.lax.pmax(jnp.max(jnp.abs(y)), self.axis_name)
                    s = _ov.int8_wire_scale(
                        a, bound_axis_size(self.axis_name))
                    sh = _ov.int8_dequantize(
                        jax.lax.psum_scatter(
                            _ov.int8_quantize(y, s), self.axis_name,
                            scatter_dimension=0, tiled=True), s)
                elif self.reduce_dtype is not None:
                    # pre-scaling compression: the full-world mean divide
                    # lands BEFORE the cast so wire-dtype partial sums
                    # carry mean-gradient magnitude (loss-scale-safe;
                    # overflow saturates to Inf for the amp non-finite
                    # check); the shard returns to fp32 immediately —
                    # everything past the wire accumulates fp32
                    wire = (flat / world).astype(self.reduce_dtype)
                    sh = jax.lax.psum_scatter(
                        wire, self.axis_name, scatter_dimension=0,
                        tiled=True).astype(jnp.float32)
                else:
                    sh = jax.lax.psum_scatter(
                        flat, self.axis_name, scatter_dimension=0,
                        tiled=True)
                if self.group_axis is not None:
                    # cross-subgroup reduction stays fp32: it moves 1/n
                    # of the bytes and compressing it would square the
                    # quantization error for no meaningful wire saving
                    sh = jax.lax.psum(sh, self.group_axis)
                shards.append(sh)
        from apex_tpu.telemetry import health as _health
        if _health.enabled():
            # numerics health: per-bucket grad norms off the ALREADY
            # reduced shards (each device holds a distinct slice of the
            # summed bucket, so psum of local sum-of-squares over the
            # shard axis is the full bucket's norm²; / world reports the
            # MEAN-gradient norm the optimizer actually steps on).
            # Cardinality is bounded by the bucket count.
            from apex_tpu import telemetry
            for i, sh in enumerate(shards):
                n2 = jax.lax.psum(jnp.sum(jnp.square(sh)), self.axis_name)
                norm = (jnp.sqrt(n2) if self.reduce_dtype is not None
                        else jnp.sqrt(n2) / world)
                telemetry.record(
                    f"health/zero/bucket{i}/grad_norm",
                    norm, step=telemetry_step)
        shard = shards[0] if len(shards) == 1 else jnp.concatenate(shards)
        # compressed shards were pre-divided by the full world before the
        # wire cast (pre-scaling) — they are already the mean
        return shard if self.reduce_dtype is not None else shard / world

    def _gather_params(self, master_shard: jax.Array, spec,
                       params: Tree) -> Tree:
        """Local updated shard -> replicated param tree (the parameter
        all_gather at distributed_fused_adam.py:392-407; optionally in a
        compressed dtype like the e5m2 allgather flag). One all_gather per
        bucket: XLA can overlap a bucket's gather with the unflatten (and
        the next step's forward) of previously gathered buckets. Gathers
        over ``axis_name`` only — with group_axis, every subgroup already
        holds identical shards."""
        from apex_tpu import telemetry
        if telemetry.enabled():
            # per-device shard bytes contributed to the parameter
            # all_gather each step (post-compression dtype); ring wire
            # bill is (n-1) x the contributed shard.
            n = bound_axis_size(self.axis_name)
            item = np.dtype(self.allgather_dtype or np.float32).itemsize
            nbytes = item * int(sum(b["k"] for b in spec["buckets"]))
            telemetry.record_static(
                f"zero/{self.axis_name}/all_gather_bytes", nbytes,
                meta={"axis": self.axis_name, "primitive": "all_gather",
                      "count": len(spec["buckets"]), "world": n,
                      "bytes_wire": round(nbytes * (n - 1))},
                dedup_key=(self.axis_name, nbytes, len(spec["buckets"]),
                           "gather"))

        leaves: list = [None] * len(spec["sizes"])
        off = 0
        # profiler attribution scope (see _scatter_grads)
        with jax.named_scope("apex_zero_allgather"):
            for b in spec["buckets"]:
                piece = jax.lax.slice_in_dim(master_shard, off,
                                             off + b["k"])
                off += b["k"]
                if self.allgather_dtype is not None:
                    piece = piece.astype(self.allgather_dtype)
                flat = jax.lax.all_gather(piece, self.axis_name,
                                          tiled=True)
                for i in b["idxs"]:
                    rel = int(spec["offsets"][i]) - b["start"]
                    leaves[i] = (
                        jax.lax.slice_in_dim(flat, rel,
                                             rel + spec["sizes"][i])
                        .reshape(spec["shapes"][i])
                        .astype(spec["dtypes"][i]))
        return jax.tree_util.tree_unflatten(spec["treedef"], leaves)

    def _shard_positions(self, spec) -> jax.Array:
        """CANONICAL flat index (tensor-order concat, no padding) of each
        element of this device's shard; bucket-padding elements map to the
        out-of-range sentinel ``total`` so ``pos < total`` masks them."""
        r = jax.lax.axis_index(self.axis_name)
        parts = []
        for b in spec["buckets"]:
            q = r * b["k"] + jnp.arange(b["k"])
            parts.append(jnp.where(q < b["size"], b["start"] + q,
                                   spec["total"]))
        return parts[0] if len(parts) == 1 else jnp.concatenate(parts)

    def _shard_segments(self, spec) -> jax.Array:
        """Per-element tensor index over this device's shard (static tensor
        offsets -> segment ids; padding tail maps to the last tensor)."""
        pos = self._shard_positions(spec)
        bounds = jnp.asarray(np.cumsum(spec["sizes"]), jnp.int32)
        seg = jnp.searchsorted(bounds, pos, side="right")
        return jnp.minimum(seg, len(spec["sizes"]) - 1)

    def _hp_elem(self, spec, name: str, default, seg: Optional[jax.Array],
                 resolve=None):
        """Per-element hyperparameter over the flat shard: the optimizer
        default unless param groups override it, in which case a (shard,)
        vector is gathered through the static tensor->group map."""
        overrides = spec["group_overrides"]
        if len(overrides) <= 1 or not any(name in ov for ov in overrides[1:]):
            return resolve(default) if resolve else default
        vals = [ov.get(name, default) for ov in overrides]
        if resolve is not None:
            vals = [resolve(v) for v in vals]
        table = jnp.stack([jnp.asarray(v, jnp.float32) for v in vals])
        group_elem = jnp.asarray(spec["group_of_tensor"])[seg]
        return table[group_elem]

    def global_grad_norm(self, g_shard: jax.Array) -> jax.Array:
        """Sharded L2 norm -> psum (the l2-grad-norm process group,
        distributed_fused_adam.py:352). psum over ``axis_name`` only: with
        group_axis the shards are replicated across subgroups."""
        return jnp.sqrt(jax.lax.psum(jnp.sum(g_shard * g_shard),
                                     self.axis_name))


class DistributedFusedAdam(_ZeroBase):
    """ZeRO sharded Adam/AdamW (reference distributed_fused_adam.py).

    Hyperparameter surface mirrors FusedAdam; overflow handling ("revert")
    is expressed by the caller via lax.cond (AmpOptimizer composes cleanly:
    the step is pure, so skipping == keeping the old state).
    """

    def __init__(self, lr: Schedule = 1e-3, *, bias_correction: bool = True,
                 betas: Tuple[float, float] = (0.9, 0.999), eps: float = 1e-8,
                 adam_w_mode: bool = True, weight_decay: float = 0.0,
                 axis_name: str = "data", shard_count: Optional[int] = None,
                 group_axis: Optional[str] = None, allgather_dtype=None,
                 param_groups=None, chunk_elements: Optional[int] = None,
                 reduce_dtype=None):
        super().__init__(axis_name=axis_name, shard_count=shard_count,
                         group_axis=group_axis,
                         allgather_dtype=allgather_dtype,
                         param_groups=param_groups,
                         chunk_elements=chunk_elements,
                         reduce_dtype=reduce_dtype)
        self.lr = lr
        self.bias_correction = bias_correction
        self.betas = betas
        self.eps = eps
        self.adam_w_mode = adam_w_mode
        self.weight_decay = weight_decay

    def step(self, grads: Tree, params: Tree, state: ZeroState, *,
             grad_scale: Optional[jax.Array] = None,
             ) -> Tuple[Tree, ZeroState]:
        spec = self._spec_cache or self._pack(params)
        step = state.step + 1
        g = self._scatter_grads(grads, spec, telemetry_step=step)
        if grad_scale is not None:
            g = g / grad_scale

        b1, b2 = self.betas
        stepf = step.astype(jnp.float32)
        bc1 = 1.0 - b1 ** stepf if self.bias_correction else 1.0
        bc2 = 1.0 - b2 ** stepf if self.bias_correction else 1.0

        seg = self._shard_segments(spec) if self.param_groups else None
        lr = self._hp_elem(spec, "lr", self.lr, seg,
                           resolve=lambda l: resolve_lr(l, step))
        wd = self._hp_elem(spec, "weight_decay", self.weight_decay, seg)
        wd_active = isinstance(wd, jax.Array) or wd != 0.0

        p = state.master
        if not self.adam_w_mode and wd_active:
            g = g + wd * p
        m = b1 * state.exp_avg + (1.0 - b1) * g
        v = b2 * state.exp_avg_sq + (1.0 - b2) * g * g
        update = (m / bc1) / (jnp.sqrt(v / bc2) + self.eps)
        if self.adam_w_mode and wd_active:
            update = update + wd * p
        new_master = p - lr * update

        new_params = self._gather_params(new_master, spec, params)
        return new_params, ZeroState(step=step, master=new_master,
                                     exp_avg=m, exp_avg_sq=v)


class DistributedFusedLAMB(_ZeroBase):
    """ZeRO sharded LAMB (reference distributed_fused_lamb.py:7-607):
    global grad-norm clip, sharded Adam moments, per-tensor trust ratios
    computed via segmented reductions over the flat shards + psum — the
    TPU analog of the distributed_lamb_cuda segmented-norm kernels."""

    def __init__(self, lr: Schedule = 1e-3, *, bias_correction: bool = True,
                 betas: Tuple[float, float] = (0.9, 0.999), eps: float = 1e-6,
                 weight_decay: float = 0.01, adam_w_mode: bool = True,
                 grad_averaging: bool = True, max_grad_norm: float = 1.0,
                 use_nvlamb: bool = False, axis_name: str = "data",
                 shard_count: Optional[int] = None,
                 group_axis: Optional[str] = None, allgather_dtype=None,
                 param_groups=None, chunk_elements: Optional[int] = None,
                 reduce_dtype=None):
        super().__init__(axis_name=axis_name, shard_count=shard_count,
                         group_axis=group_axis,
                         allgather_dtype=allgather_dtype,
                         param_groups=param_groups,
                         chunk_elements=chunk_elements,
                         reduce_dtype=reduce_dtype)
        self.lr = lr
        self.bias_correction = bias_correction
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adam_w_mode = adam_w_mode
        self.grad_averaging = grad_averaging
        self.max_grad_norm = max_grad_norm
        self.use_nvlamb = use_nvlamb

    def step(self, grads: Tree, params: Tree, state: ZeroState, *,
             grad_scale: Optional[jax.Array] = None,
             ) -> Tuple[Tree, ZeroState]:
        spec = self._spec_cache or self._pack(params)
        num_tensors = len(spec["sizes"])
        step = state.step + 1
        g = self._scatter_grads(grads, spec, telemetry_step=step)
        if grad_scale is not None:
            g = g / grad_scale

        # Global grad-norm clip (stage 1).
        gnorm = self.global_grad_norm(g)
        if self.max_grad_norm > 0:
            clip = jnp.where(gnorm > self.max_grad_norm,
                             gnorm / self.max_grad_norm, 1.0)
            g = g / clip

        b1, b2 = self.betas
        stepf = step.astype(jnp.float32)
        bc1 = 1.0 - b1 ** stepf if self.bias_correction else 1.0
        bc2 = 1.0 - b2 ** stepf if self.bias_correction else 1.0
        beta3 = (1.0 - b1) if self.grad_averaging else 1.0

        # Segment ids also drive per-element param-group hyperparameters.
        pos = self._shard_positions(spec)
        seg = self._shard_segments(spec)
        lr = self._hp_elem(spec, "lr", self.lr, seg,
                           resolve=lambda l: resolve_lr(l, step))
        wd = self._hp_elem(spec, "weight_decay", self.weight_decay, seg)
        wd_active = isinstance(wd, jax.Array) or wd != 0.0

        p = state.master
        if not self.adam_w_mode and wd_active:
            g = g + wd * p
        m = b1 * state.exp_avg + beta3 * g
        v = b2 * state.exp_avg_sq + (1.0 - b2) * g * g
        update = (m / bc1) / (jnp.sqrt(v / bc2) + self.eps)
        if self.adam_w_mode and wd_active:
            update = update + wd * p

        # Per-tensor norms across shard boundaries: segment ids from static
        # tensor offsets, psum'd partial sums (distributed_lamb's two-stage
        # segmented reduction).
        in_range = pos < spec["total"]
        p_sq = jnp.where(in_range, p * p, 0.0)
        u_sq = jnp.where(in_range, update * update, 0.0)
        p_norms = jnp.sqrt(jax.lax.psum(
            jax.ops.segment_sum(p_sq, seg, num_segments=num_tensors),
            self.axis_name))
        u_norms = jnp.sqrt(jax.lax.psum(
            jax.ops.segment_sum(u_sq, seg, num_segments=num_tensors),
            self.axis_name))

        # Trust-ratio applicability is per tensor: a group with
        # weight_decay=0 skips the ratio unless NVLamb (fused_lamb.py docs).
        wd_t = np.array([spec["group_overrides"][gi].get(
            "weight_decay", self.weight_decay)
            for gi in spec["group_of_tensor"]], np.float32)
        use_ratio_t = jnp.asarray((wd_t != 0.0) | self.use_nvlamb)
        ratios = jnp.where(
            use_ratio_t & (p_norms > 0) & (u_norms > 0),
            p_norms / jnp.maximum(u_norms, 1e-38), 1.0)
        new_master = p - lr * ratios[seg] * update

        new_params = self._gather_params(new_master, spec, params)
        return new_params, ZeroState(step=step, master=new_master,
                                     exp_avg=m, exp_avg_sq=v)
