"""Fused softmax cross-entropy with label smoothing — parity with
``apex.contrib.xentropy.SoftmaxCrossEntropyLoss``
(apex/contrib/xentropy/softmax_xentropy.py:4-28 over the xentropy_cuda
extension, apex/contrib/csrc/xentropy/xentropy_kernel.cu).

The reference kernel's trick: forward returns (losses, max_log_sum_exp) so
backward can rebuild the softmax as ``exp(logits - lse)`` without recomputing
the max/sum reductions. The custom_vjp below keeps exactly that contract;
XLA fuses the bwd expression into one pass over the logits.

loss_i = logsumexp(x_i) - (1-smoothing) * x_i[y_i] - smoothing * mean_k(x_i[k])
grad_i = softmax(x_i) - (1-smoothing) * onehot(y_i) - smoothing / K
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def softmax_cross_entropy_loss(logits: jax.Array, labels: jax.Array,
                               smoothing: float = 0.0,
                               half_to_float: bool = False) -> jax.Array:
    """Per-example losses, shape (batch,). ``half_to_float`` mirrors the
    reference flag: compute/return losses in fp32 even for low-prec logits
    (always true here — TPU reductions want fp32 anyway)."""
    losses, _ = _xent_fwd_impl(logits, labels, smoothing)
    return losses


def _xent_fwd_impl(logits, labels, smoothing):
    x = logits.astype(jnp.float32)
    mx = jnp.max(x, axis=-1, keepdims=True)
    lse = jnp.log(jnp.sum(jnp.exp(x - mx), axis=-1, keepdims=True)) + mx
    picked = jnp.take_along_axis(x, labels[..., None], axis=-1)
    mean_all = jnp.mean(x, axis=-1, keepdims=True)
    losses = (lse - (1.0 - smoothing) * picked - smoothing * mean_all)
    return losses[..., 0], lse[..., 0]


def _xent_fwd(logits, labels, smoothing, half_to_float):
    losses, lse = _xent_fwd_impl(logits, labels, smoothing)
    return losses, (logits, labels, lse)


def _xent_bwd(smoothing, half_to_float, res, g):
    logits, labels, lse = res
    k = logits.shape[-1]
    x = logits.astype(jnp.float32)
    # softmax rebuilt from the saved max_log_sum_exp (no re-reduction)
    probs = jnp.exp(x - lse[..., None])
    onehot = jax.nn.one_hot(labels, k, dtype=jnp.float32)
    grad = probs - (1.0 - smoothing) * onehot - smoothing / k
    grad = grad * g[..., None]
    return grad.astype(logits.dtype), None


softmax_cross_entropy_loss.defvjp(_xent_fwd, _xent_bwd)


class SoftmaxCrossEntropyLoss:
    """Class shim matching the reference module surface."""

    def __init__(self, smoothing: float = 0.0, reduction: str = "mean"):
        self.smoothing = smoothing
        self.reduction = reduction

    def __call__(self, logits, labels):
        losses = softmax_cross_entropy_loss(logits, labels, self.smoothing)
        if self.reduction == "mean":
            return jnp.mean(losses)
        if self.reduction == "sum":
            return jnp.sum(losses)
        return losses
