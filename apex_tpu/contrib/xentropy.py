"""Fused softmax cross-entropy with label smoothing — parity with
``apex.contrib.xentropy.SoftmaxCrossEntropyLoss``
(apex/contrib/xentropy/softmax_xentropy.py:4-28 over the xentropy_cuda
extension, apex/contrib/csrc/xentropy/xentropy_kernel.cu).

The reference kernel's trick: forward returns (losses, max_log_sum_exp) so
backward can rebuild the softmax as ``exp(logits - lse)`` without recomputing
the max/sum reductions. The custom_vjp below keeps exactly that contract.

loss_i = logsumexp(x_i) - (1-smoothing) * x_i[y_i] - smoothing * mean_k(x_i[k])
grad_i = softmax(x_i) - (1-smoothing) * onehot(y_i) - smoothing / K

Two execution paths, selected by :func:`backend`:

  * **jnp** (the default): the plain math below; XLA fuses the bwd
    expression into one pass over the logits. The default is provably
    inert — compiled programs are bit-identical to the pre-Pallas build
    (pinned by tests/test_kernels.py jaxpr equality).
  * **pallas** (opt-in, ``APEX_TPU_XENT_BACKEND=pallas`` or
    :func:`set_backend`): the ``ops/pallas_xent`` kernels — one K-blocked
    online-logsumexp pass producing loss + saved lse, and a backward that
    writes the gradient blockwise in the logits dtype so the full fp32
    softmax is never materialized. Falls back to jnp when the vocab is
    not lane-aligned (K % 128 != 0).

``half_to_float`` mirrors the reference flag: False (default) returns the
losses in the LOGITS dtype; True computes/returns them in fp32 even for
low-precision logits. The backward always computes in fp32 (the incoming
cotangent is upcast first) and returns cotangents in the logits' original
dtype either way.
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

_BACKENDS = ("jnp", "pallas")
_FORCE = os.environ.get("APEX_TPU_XENT_BACKEND", "auto")  # auto|jnp|pallas
_OVERRIDE: Optional[str] = None


def set_backend(name: Optional[str] = None) -> Optional[str]:
    """Process-level backend override (None restores the env/default).
    Returns the previous override so callers can save/restore."""
    global _OVERRIDE
    if name is not None and name not in _BACKENDS:
        raise ValueError(f"xentropy backend must be one of {_BACKENDS}, "
                         f"got {name!r}")
    prev = _OVERRIDE
    _OVERRIDE = name
    return prev


def backend() -> str:
    """The active execution path: ``set_backend`` override, else the
    ``APEX_TPU_XENT_BACKEND`` env value; ``auto`` (the default) resolves
    to ``jnp`` — XLA's fused plain math, bit-identical to the pre-kernel
    build. An unrecognized env value raises (loud-failure doctrine: a
    typo'd opt-in must not silently measure the unfused path)."""
    b = _OVERRIDE if _OVERRIDE is not None else _FORCE
    if b in _BACKENDS:
        return b
    if b in ("auto", ""):
        return "jnp"
    raise ValueError(f"APEX_TPU_XENT_BACKEND={b!r} — expected one of "
                     f"{_BACKENDS} or 'auto'")


def _use_pallas(logits) -> bool:
    if backend() != "pallas":
        return False
    from apex_tpu.ops import pallas_xent
    return pallas_xent.supported(logits.shape[-1])


def _loss_out_dtype(logits_dtype, half_to_float: bool):
    return jnp.float32 if half_to_float else jnp.dtype(logits_dtype)


def _cast_loss(losses, logits_dtype, half_to_float: bool):
    out = _loss_out_dtype(logits_dtype, half_to_float)
    # python-level guard: fp32 logits (every shipped call site) trace the
    # exact pre-fix program — no convert op is ever added for them
    return losses if losses.dtype == out else losses.astype(out)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def softmax_cross_entropy_loss(logits: jax.Array, labels: jax.Array,
                               smoothing: float = 0.0,
                               half_to_float: bool = False) -> jax.Array:
    """Per-example losses, shape ``logits.shape[:-1]``. ``half_to_float``
    mirrors the reference flag: the losses come back in the logits dtype
    unless it is set, in which case they stay fp32 (reductions on TPU
    want fp32 — pass True for low-precision logits feeding a mean)."""
    losses, _ = _xent_fwd_impl(logits, labels, smoothing)
    return _cast_loss(losses, logits.dtype, half_to_float)


def _xent_fwd_impl(logits, labels, smoothing):
    if _use_pallas(logits):
        from apex_tpu.ops import pallas_xent
        shp = logits.shape[:-1]
        losses, lse = pallas_xent.xent_fwd(
            logits.reshape(-1, logits.shape[-1]),
            labels.reshape(-1), smoothing)
        return losses.reshape(shp), lse.reshape(shp)
    x = logits.astype(jnp.float32)
    mx = jnp.max(x, axis=-1, keepdims=True)
    lse = jnp.log(jnp.sum(jnp.exp(x - mx), axis=-1, keepdims=True)) + mx
    picked = jnp.take_along_axis(x, labels[..., None], axis=-1)
    mean_all = jnp.mean(x, axis=-1, keepdims=True)
    losses = (lse - (1.0 - smoothing) * picked - smoothing * mean_all)
    return losses[..., 0], lse[..., 0]


def _xent_fwd(logits, labels, smoothing, half_to_float):
    losses, lse = _xent_fwd_impl(logits, labels, smoothing)
    return (_cast_loss(losses, logits.dtype, half_to_float),
            (logits, labels, lse))


def _xent_bwd(smoothing, half_to_float, res, g):
    logits, labels, lse = res
    k = logits.shape[-1]
    # the cotangent arrives in the LOSS dtype (logits dtype unless
    # half_to_float) — upcast before the fp32 softmax math so a bf16 g
    # cannot poison the rebuild
    g32 = g if g.dtype == jnp.float32 else g.astype(jnp.float32)
    if _use_pallas(logits):
        from apex_tpu.ops import pallas_xent
        dx = pallas_xent.xent_bwd(
            logits.reshape(-1, k), labels.reshape(-1),
            lse.reshape(-1), g32.reshape(-1), smoothing)
        return dx.reshape(logits.shape), None
    x = logits.astype(jnp.float32)
    # softmax rebuilt from the saved max_log_sum_exp (no re-reduction)
    probs = jnp.exp(x - lse[..., None])
    onehot = jax.nn.one_hot(labels, k, dtype=jnp.float32)
    grad = probs - (1.0 - smoothing) * onehot - smoothing / k
    grad = grad * g32[..., None]
    return grad.astype(logits.dtype), None


softmax_cross_entropy_loss.defvjp(_xent_fwd, _xent_bwd)


class SoftmaxCrossEntropyLoss:
    """Class shim matching the reference module surface."""

    def __init__(self, smoothing: float = 0.0, reduction: str = "mean",
                 half_to_float: bool = False):
        self.smoothing = smoothing
        self.reduction = reduction
        self.half_to_float = half_to_float

    def __call__(self, logits, labels):
        losses = softmax_cross_entropy_loss(logits, labels, self.smoothing,
                                            self.half_to_float)
        if self.reduction == "mean":
            return jnp.mean(losses)
        if self.reduction == "sum":
            return jnp.sum(losses)
        return losses
