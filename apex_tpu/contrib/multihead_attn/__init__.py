"""Fused multihead attention modules — parity with
``apex.contrib.multihead_attn`` (SelfMultiheadAttn at
self_multihead_attn.py:26, EncdecMultiheadAttn, and the fast_* autograd
functions over the CUTLASS/CUDA kernels). Variant matrix reproduced
(SURVEY.md §2.2): self/enc-dec x {plain, bias, additive-mask, norm-add
residual}, plus the standalone masked-softmax-dropout.

``impl='fast'`` runs the Pallas flash kernel (ops/attention.py);
``impl='default'`` is the plain jnp path — the same two-impl switch as the
reference modules. On the fast path, attention-prob dropout fuses into the
flash kernels via the deterministic counter mask (the reference fuses
dropout into its softmax kernel the same way,
csrc/multihead_attn/dropout.h); each module folds its flax path into the
seed so stacked layers sharing one dropout_rng still draw distinct masks.
"""

from __future__ import annotations

import math
from typing import Any, Optional

import jax
import jax.numpy as jnp
import flax.linen as nn

from apex_tpu.normalization import FusedLayerNorm
from apex_tpu.parallel.mesh import bound_axis_size
from apex_tpu.ops.attention import (
    MASK_BIAS,
    attention_reference,
    flash_attention,
    ring_self_attention,
    self_attention,
    ulysses_self_attention,
)

__all__ = [
    "SelfMultiheadAttn", "EncdecMultiheadAttn", "masked_softmax_dropout",
    "self_attention", "flash_attention", "attention_reference",
    "ring_self_attention", "ulysses_self_attention",
    "RelativePositionBias", "relative_position_bucket",
    "alibi_bias", "alibi_slopes",
]


def masked_softmax_dropout(scores: jax.Array, *, mask: Optional[jax.Array]
                           = None, dropout_rate: float = 0.0,
                           rng: Optional[jax.Array] = None,
                           deterministic: bool = True) -> jax.Array:
    """Standalone fused masked-softmax-dropout (the reference's
    ``fast_mask_softmax_dropout`` module): additive mask -> fp32 softmax ->
    dropout. XLA fuses this chain into one pass. Boolean masks (True =
    masked out) convert to MASK_BIAS additive entries, same as the fast
    path."""
    s = scores.astype(jnp.float32)
    if mask is not None:
        mask = jnp.asarray(mask)
        if mask.dtype == jnp.bool_:
            mask = jnp.where(mask, MASK_BIAS, 0.0)
        s = s + mask.astype(jnp.float32)
    p = jax.nn.softmax(s, axis=-1)
    if dropout_rate > 0.0 and not deterministic:
        keep = jax.random.bernoulli(rng, 1.0 - dropout_rate, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
    return p.astype(scores.dtype)


def _mask_to_bias(attn_mask):
    """Normalize a module-level ``attn_mask`` (additive, matching
    masked_softmax_dropout semantics) to the rank-4 (B|1, H|1, Sq|1, Sk)
    additive bias the attention kernels take. Boolean masks (True = masked
    out) convert to MASK_BIAS additive entries (the flash kernels' stable
    mask magnitude; exp(MASK_BIAS) == 0)."""
    if attn_mask is None:
        return None
    m = jnp.asarray(attn_mask)
    if m.dtype == jnp.bool_:
        m = jnp.where(m, MASK_BIAS, 0.0)
    if m.ndim == 1:            # (sk,) key-padding -> broadcast everywhere
        return m[None, None, None]
    if m.ndim == 2:            # (sq, sk)
        return m[None, None]
    if m.ndim == 3:            # (b, sq, sk) -> broadcast over heads
        return m[:, None]
    if m.ndim == 4:
        return m
    raise ValueError(f"attn_mask must be rank 1-4, got shape {m.shape}")


def relative_position_bucket(rel_pos, *, bidirectional: bool,
                             num_buckets: int, max_distance: int):
    """T5-style log-spaced relative-position bucketing (Raffel et al.
    2020 §2.1): exact buckets up to ``num_buckets//2`` positions back,
    then logarithmically coarser out to ``max_distance``, everything
    further sharing the last bucket. ``rel_pos = k_pos - q_pos``
    (negative = key in the past). Unidirectional (causal) variants give
    future positions bucket 0 — pair with a causal mask so they never
    contribute."""
    n = -rel_pos                      # positive = distance into the past
    off = jnp.zeros_like(n)
    if bidirectional:
        num_buckets //= 2
        off = jnp.where(n < 0, num_buckets, 0)
        n = jnp.abs(n)
    else:
        n = jnp.maximum(n, 0)
    max_exact = num_buckets // 2
    # log-spaced tail: bucket grows with log(distance), clamped to last
    big = max_exact + (
        jnp.log(jnp.maximum(n, 1).astype(jnp.float32) / max_exact)
        / math.log(max_distance / max_exact)
        * (num_buckets - max_exact)).astype(jnp.int32)
    big = jnp.minimum(big, num_buckets - 1)
    return off + jnp.where(n < max_exact, n, big)


class RelativePositionBias(nn.Module):
    """Learned T5-style relative position bias: a (num_buckets, heads)
    embedding table indexed by the bucketed (sq, sk) relative-position
    matrix → additive score bias (1, heads, sq, sk). Trains through the
    flash kernels via ``trainable_bias=True`` (the bucket gather's
    transpose is a segment-sum, so the O(sk)-or-O(sq·sk) kernel dbias
    reduces onto the tiny table). The reference has no relative-bias
    module (its *_bias_* kernels take constant masks); this consumes the
    r4 dbias emission the way T5/ALiBi-family models need."""

    num_heads: int
    num_buckets: int = 32
    max_distance: int = 128
    bidirectional: bool = False
    dtype: Any = None

    @nn.compact
    def __call__(self, sq: int, sk: int, *, q_offset=0, k_offset=0):
        table = self.param("rel_bias", nn.initializers.normal(0.02),
                           (self.num_buckets, self.num_heads))
        rel = (k_offset + jnp.arange(sk))[None, :] \
            - (q_offset + jnp.arange(sq))[:, None]
        buckets = relative_position_bucket(
            rel, bidirectional=self.bidirectional,
            num_buckets=self.num_buckets, max_distance=self.max_distance)
        bias = table[buckets]                       # (sq, sk, h)
        return bias.transpose(2, 0, 1)[None].astype(
            self.dtype or jnp.float32)              # (1, h, sq, sk)


def alibi_slopes(num_heads: int):
    """ALiBi head slopes (Press et al. 2022): for power-of-two head
    counts, the geometric sequence 2^(-8/n), 2^(-16/n), ...; otherwise
    the published interleaved recipe — the closest lower power's slopes
    plus every other slope of the doubled sequence — so weights match
    externally-trained ALiBi checkpoints (e.g. BLOOM) at any head count
    (ADVICE r4: the plain geometric form diverged from the standard at
    non-power-of-two counts)."""
    def geometric(n):
        return [2.0 ** (-8.0 * (i + 1) / n) for i in range(n)]

    if num_heads & (num_heads - 1) == 0:          # power of two
        s = geometric(num_heads)
    else:
        closest = 1 << (num_heads.bit_length() - 1)
        s = geometric(closest) \
            + geometric(2 * closest)[0::2][:num_heads - closest]
    return jnp.asarray(s, jnp.float32)


def alibi_bias(num_heads: int, sk: int, *, slopes=None):
    """ALiBi attention bias in COLUMN form, shape (1, H, 1, sk).

    ALiBi's score penalty -slope·(i-j) is row-shift-equivalent to
    +slope·j under softmax (each query row's shift -slope·i cancels in
    the row normalization), so for CAUSAL attention the bias collapses
    from a (sq, sk) plane to one broadcast column vector — which rides
    the flash kernels' cheap row-broadcast path (and, with
    ``trainable_bias=True`` for learned slopes, the in-kernel-reduced
    O(sk) dbias; see BASELINE.md's dbias price table). Only valid with
    causal masking: a non-causal row would see rewarded FUTURE columns
    instead of masked ones. Pass learned ``slopes`` (H,) to
    differentiate through them."""
    s = alibi_slopes(num_heads) if slopes is None else slopes
    cols = jnp.arange(sk, dtype=jnp.float32)
    return (s[:, None] * cols[None, :])[None, :, None, :]


def _derive_seed(rng, module_path):
    """Per-module dropout seed: fold the flax module path into the rng so
    stacked attention layers sharing one dropout_rng draw distinct masks."""
    import zlib
    tag = zlib.crc32("/".join(map(str, module_path)).encode()) & 0x7FFFFFFF
    return jax.random.randint(jax.random.fold_in(rng, tag), (),
                              0, 2**31 - 1)


def _tp_dropout_rng(rng, axis_name):
    """Fold the tensor-parallel rank into the dropout rng. Without this
    every TP rank draws the SAME mask for its head shard (same rng, same
    module path, same local shape), correlating dropout across the head
    groups — the per-rank masks must be independent draws. No-op outside
    TP or without an rng."""
    if axis_name is None or rng is None:
        return rng
    return jax.random.fold_in(rng, jax.lax.axis_index(axis_name))


def _split_heads(x, num_heads):
    b, s, e = x.shape
    return x.reshape(b, s, num_heads, e // num_heads).transpose(0, 2, 1, 3)


def _merge_heads(x):
    b, h, s, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b, s, h * d)


class SelfMultiheadAttn(nn.Module):
    """``SelfMultiheadAttn(embed_dim, num_heads, dropout, bias,
    include_norm_add, impl)`` (self_multihead_attn.py:26).

    Input layout: (batch, seq, embed) — batch-first, the TPU-friendly layout
    (the reference uses seq-first torch convention).
    ``include_norm_add``: pre-LayerNorm + residual add around attention
    (the *_norm_add_* kernel variants).
    """

    embed_dim: int
    num_heads: int
    dropout: float = 0.0
    bias: bool = False
    include_norm_add: bool = False
    impl: str = "fast"          # 'fast' (Pallas flash) | 'default' (jnp)
    causal: bool = False
    dtype: Any = None
    # Sequence parallelism: run the attention itself over a mesh axis while
    # every projection stays local to the sequence shard. 'ring' permutes
    # K/V around the axis (no head constraint); 'ulysses' all-to-alls
    # heads<->sequence (num_heads % axis size == 0). The module must be
    # called under shard_map with the sequence dim sharded on `axis_name`.
    seq_parallel: Optional[str] = None    # None | 'ring' | 'ulysses'
    axis_name: Optional[str] = None
    # Megatron-style tensor parallelism (parallel/tensor_parallel.py):
    # constructed with num_heads = H // tp and head-sharded params, the
    # module brackets its column->row parallel region with the f/g
    # conjugate collectives over this axis. Mutually exclusive with
    # seq_parallel (which shards the SEQUENCE, not the heads).
    tensor_parallel_axis: Optional[str] = None
    # tp degree: column-parallel layer widths are divided by this (flax
    # validates param shapes at apply, so the local module must declare
    # the LOCAL feature sizes). num_heads must also be the local count.
    tensor_parallel_size: int = 1
    # Learned T5-style relative position bias (RelativePositionBias):
    # trains through the flash kernels via trainable_bias=True (r4 dbias
    # emission). Composes additively with attn_mask.
    relative_bias: bool = False
    relative_bias_buckets: int = 32
    relative_bias_max_distance: int = 128
    # ALiBi (Press et al. 2022) in COLUMN form (alibi_bias): a per-head
    # linear score penalty riding the flash kernels' cheap row-broadcast
    # bias path (O(sk) dbias when learned). Requires causal=True — the
    # column form is only softmax-equivalent under causal masking.
    # ``alibi_learned`` makes the slopes a trained (H,) param
    # ("alibi_slopes", initialized to the published geometric values)
    # whose grad flows through the in-kernel-reduced dbias. Composes
    # additively with attn_mask and relative_bias.
    alibi: bool = False
    alibi_learned: bool = False
    # Autoregressive KV-cache decoding (models.gpt.generate): K/V land
    # in a ("cache", ...) variable collection sized decode_max_len, the
    # causal mask offsets by the running cache index, and attention is a
    # plain einsum against the cache (a 1-token query has no use for the
    # flash kernels; the read of the cache is the cost). Static shapes
    # throughout: every step attends over the full decode_max_len
    # window, masked — the TPU-native decode formulation.
    decode: bool = False
    decode_max_len: int = 0
    # Step-attention backend for decode mode: 'einsum' (XLA chain),
    # 'fused' (ops.attention.decode_attention — one Pallas call per
    # step with dead-block DMA elision, so only the live cache prefix
    # moves from HBM), or 'auto' (default): fused for caches >= 2048
    # rows — measured +22% on deep-cache steps / +54% over a full
    # 4096-token-cache generation (BASELINE.md r5 decode section) —
    # einsum below, where the whole cache is one block and elision has
    # nothing to skip. 'fused' serves plain-config steps (S_cur <= 8,
    # no bias, not fp16); bias-config steps ride the einsum, and a
    # FRESH-cache prefill (idx provably 0) runs blockwise flash over
    # the local k/v when impl='fast' (einsum otherwise).
    decode_impl: str = "auto"

    def _alibi_column_bias(self, h, sk):
        """(1, h, 1, sk) ALiBi column bias; learned slopes become the
        "alibi_slopes" param (init = the published geometric/interleaved
        values, so training starts AT standard ALiBi)."""
        slopes = None
        if self.alibi_learned:
            slopes = self.param("alibi_slopes",
                                lambda _key: alibi_slopes(h))
        return alibi_bias(h, sk, slopes=slopes)

    @nn.compact
    def __call__(self, x, *, attn_mask: Optional[jax.Array] = None,
                 deterministic: bool = True,
                 dropout_rng: Optional[jax.Array] = None):
        e, h = self.embed_dim, self.num_heads
        assert e % h == 0, "embed_dim must divide num_heads"
        if self.relative_bias and self.seq_parallel == "ulysses":
            raise NotImplementedError(
                "relative_bias under ulysses: the all-to-all re-shards "
                "to full-sequence/head-subset, where only column "
                "(q-broadcast) biases apply — use seq_parallel='ring' "
                "(supported: the bias is built per-shard with global "
                "query offsets) or alibi (column form)")
        if self.alibi_learned and not self.alibi:
            # a dead flag would silently train WITHOUT ALiBi (no slopes
            # param, absolute embeddings instead) — same loud-failure
            # contract as generate()'s top_k/top_p validation
            raise ValueError(
                "alibi_learned=True requires alibi=True (alone it "
                "does nothing — no slopes param would be created)")
        if self.alibi and not self.causal:
            raise ValueError(
                "alibi=True requires causal=True: the column-form bias "
                "is only softmax-equivalent to the (i-j) penalty under "
                "causal masking (future columns would be REWARDED)")
        if self.alibi and self.tensor_parallel_axis:
            raise NotImplementedError(
                "alibi under tensor parallelism needs the GLOBAL-head "
                "slope sequence sliced per rank (the local init would "
                "re-derive slopes for the local head count) — pass "
                "alibi_bias(H_global, sk)[:, rank*h_loc:(rank+1)*h_loc] "
                "as attn_mask instead")
        if self.tensor_parallel_axis and self.seq_parallel:
            raise NotImplementedError(
                "tensor_parallel_axis and seq_parallel are mutually "
                "exclusive on one module — put them on different mesh "
                "axes via separate modules/layers")
        if self.tensor_parallel_size > 1:
            if e % self.tensor_parallel_size:
                raise ValueError(
                    f"tensor_parallel_size ({self.tensor_parallel_size}) "
                    f"must divide embed_dim ({e}) — silent floor "
                    "division would mis-size the local projections")
            # dropout under TP folds the rank into the rng below —
            # otherwise every rank would draw the SAME mask for its
            # head shard (per-rank masks are independent, like any
            # re-seeded dropout; the dense-parity tests use dropout=0)
        residual = x
        if self.include_norm_add:
            x = FusedLayerNorm(normalized_shape=e)(x)

        if self.tensor_parallel_axis:
            from apex_tpu.parallel.tensor_parallel import tp_region_enter
            x = tp_region_enter(x, self.tensor_parallel_axis)
        qkv = nn.Dense(3 * e // self.tensor_parallel_size,
                       use_bias=self.bias, name="in_proj",
                       dtype=self.dtype)(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = _split_heads(q, h)
        k = _split_heads(k, h)
        v = _split_heads(v, h)

        if self.decode:
            # tensor parallelism composes: heads (and the KV cache) are
            # already sharded by the local in_proj above; only the
            # out_proj changes to its row-parallel form below
            if (self.seq_parallel or attn_mask is not None
                    or not self.causal
                    or (self.dropout > 0.0 and not deterministic)):
                # causal=False would silently decode causally anyway,
                # and active dropout would silently be dropped — loud
                # failure beats quiet divergence from the train path
                raise NotImplementedError(
                    "decode mode supports the causal deterministic "
                    "self-attention configuration (+ tensor "
                    "parallelism, relative_bias, alibi); attn_mask / "
                    "non-causal / active dropout are rejected")
            if self.decode_max_len <= 0:
                raise ValueError(
                    "decode=True needs decode_max_len (cache size)")
            # Before the cache variables are created: a FRESH cache
            # proves this is the first (prefill) call with idx == 0 —
            # attention then only spans the tokens in hand, so it can
            # run the blockwise flash kernel on the LOCAL k/v instead
            # of the einsum over the full cache window (which
            # materializes an (s_p, max_len) score matrix and reads
            # max_len-s_p rows of zeros; at prompt 3584 / cache 4096
            # that plane alone is ~5.6 GB f32 at batch 8). Gated on
            # impl == 'fast' — 'default' remains the zero-Pallas
            # escape hatch at every call. Caveat: callers following
            # the init-then-apply recipe (passing init()'s zero cache
            # into the prefill apply) present a cache collection, so
            # fresh is False and prefill takes the einsum — start the
            # prefill WITHOUT a "cache" collection (as gpt.generate
            # does) to get the flash path; idx is traced, so the
            # module cannot branch on it being 0.
            fresh = (not self.has_variable("cache", "cached_key")
                     and self.impl == "fast")
            if self.decode_impl not in ("auto", "einsum", "fused"):
                raise ValueError(
                    f"decode_impl must be 'auto', 'einsum' or 'fused', "
                    f"got {self.decode_impl!r}")
            impl = self.decode_impl
            if impl == "auto":
                # measured crossover (BASELINE.md r5 decode section):
                # elision pays once the cache spans multiple blocks
                impl = ("fused" if self.decode_max_len >= 2048
                        else "einsum")
            b_, _, s_cur, hd = q.shape
            from apex_tpu.ops.attention import decode_native_head_dim
            if impl == "fused" and (
                    not decode_native_head_dim(hd)
                    or self.relative_bias or self.alibi
                    or q.dtype == jnp.float16):
                # configs the kernel can't serve demote HERE, before
                # the cache is sized: a non-native head dim (e.g. 96)
                # would re-pay the full-cache pad copy every step (the
                # exact r4 pathology), and bias/fp16 steps would ride
                # the einsum anyway — over a cache rounded up for a
                # kernel that never runs (~25% dead-row bandwidth at
                # decode_max_len=2050)
                impl = "einsum"
            # fused kernel: cache rows round up to the kernel's block
            # grid so it never pads (a pad would COPY the cache every
            # step); 512-multiples past 1024 rows keep the divisor-only
            # block search away from the measured-worst tiny blocks
            # (a bare 128-multiple like 2176 = 128*17 would force
            # bl=128: 120.5 us vs 36.3 us whole-cache at L=640, r4
            # sweep). Masking makes the extra rows inert.
            if impl == "fused":
                unit = 512 if self.decode_max_len > 1024 else 128
                max_len = -(-self.decode_max_len // unit) * unit
            else:
                max_len = self.decode_max_len
            ck = self.variable(
                "cache", "cached_key", jnp.zeros,
                (b_, h, max_len, hd), k.dtype)
            cv = self.variable(
                "cache", "cached_value", jnp.zeros,
                (b_, h, max_len, hd), v.dtype)
            ci = self.variable(
                "cache", "cache_index",
                lambda: jnp.zeros((), jnp.int32))
            idx = ci.value
            # Overflow contract (ADVICE r4): callers must keep
            # cache_index + s_cur <= decode_max_len — past the end,
            # dynamic_update_slice CLAMPS the start index and silently
            # overwrites the tail cache rows (XLA semantics; a traced
            # index cannot raise). models.gpt.generate() enforces this
            # at its level; direct users of decode=True own the check.
            k_all = jax.lax.dynamic_update_slice(
                ck.value, k, (0, 0, idx, 0))
            v_all = jax.lax.dynamic_update_slice(
                cv.value, v, (0, 0, idx, 0))
            ck.value, cv.value = k_all, v_all
            ci.value = idx + s_cur
            scale = 1.0 / math.sqrt(hd)
            # 'einsum': XLA's chain runs within ~1.25x of the cache-read
            # bandwidth floor IN ISOLATION (24.9 us at L=640, 151 us at
            # L=4096, b=8 h=12 d=64) but ~2.4x slower inside the decode
            # scan (r4 trace). 'fused': one pad-free Pallas call for the
            # whole step attention — no scheduling boundary between the
            # two cache reductions (r5; measured in BASELINE.md's decode
            # section). Non-fresh prefill-width calls (s_cur > 8 with an
            # existing cache), bias-config steps, and fp16 (no Mosaic
            # f16) take the einsum; fresh prefill takes flash above.
            # bias/fp16/odd-head-dim configs were demoted to einsum at
            # impl resolution above; only prefill-width calls remain
            use_fused = impl == "fused" and s_cur <= 8
            if fresh:
                # prefill: plain causal flash over the local k/v (the
                # cache above was just written from exactly these
                # tokens at idx=0); biases are the train-path form at
                # sq = sk = s_cur — constants here, nothing trains in
                # decode. fp16 rides flash's bf16 reroute.
                bias0 = None
                if self.relative_bias:
                    bias0 = RelativePositionBias(
                        num_heads=h,
                        num_buckets=self.relative_bias_buckets,
                        max_distance=self.relative_bias_max_distance,
                        bidirectional=False, dtype=jnp.float32,
                        name="rel_bias")(s_cur, s_cur)
                if self.alibi:
                    ab = self._alibi_column_bias(h, s_cur)
                    bias0 = ab if bias0 is None else bias0 + ab
                ctx = flash_attention(q, k, v, True, bias=bias0)
            elif use_fused:
                from apex_tpu.ops.attention import decode_attention
                # default 1024-row blocks; a cache/4 block (512 at the
                # L=2048 crossover, for finer dead-prefix elision)
                # measured WORSE in-model — 5,437 vs 5,777 tok/s at
                # L=2048 batch 8 — the smaller DMAs and extra grid
                # steps cost more than the finer skipping saves
                # (recorded negative result, r5)
                ctx = decode_attention(q, k_all, v_all, idx, scale=scale)
            else:
                s_mat = jnp.einsum(
                    "bhqd,bhkd->bhqk", q, k_all,
                    preferred_element_type=jnp.float32) * scale
                # Additive score biases run the SAME math as the
                # train-path flash kernels, sliced to the cache window:
                # query rows sit at global positions idx..idx+s_cur-1,
                # key columns at 0..max_len-1 (future columns are
                # causally masked below, so bias values there never
                # contribute) — this is what lets a model TRAINED with
                # relative_bias/alibi generate through the cache path
                # (VERDICT r4 missing #1).
                if self.relative_bias:
                    rel = RelativePositionBias(
                        num_heads=h,
                        num_buckets=self.relative_bias_buckets,
                        max_distance=self.relative_bias_max_distance,
                        bidirectional=False, dtype=jnp.float32,
                        name="rel_bias")(s_cur, max_len, q_offset=idx)
                    s_mat = s_mat + rel.astype(jnp.float32)
                if self.alibi:
                    s_mat = s_mat + self._alibi_column_bias(
                        h, max_len).astype(jnp.float32)
                col = jnp.arange(max_len)[None, :]
                row = idx + jnp.arange(s_cur)[:, None]
                s_mat = jnp.where(col <= row, s_mat, -1e30)
                p = jax.nn.softmax(s_mat, axis=-1).astype(v_all.dtype)
                ctx = jnp.einsum("bhqk,bhkd->bhqd", p, v_all)
            ctx2 = _merge_heads(ctx).astype(x.dtype)
            if self.tensor_parallel_axis:
                from apex_tpu.parallel.tensor_parallel import \
                    RowParallelDense
                out = RowParallelDense(
                    e, self.tensor_parallel_axis, use_bias=self.bias,
                    dtype=self.dtype, name="out_proj")(ctx2)
            else:
                out = nn.Dense(e, use_bias=self.bias, name="out_proj",
                               dtype=self.dtype)(ctx2)
            if self.include_norm_add:
                out = out + residual
            return out

        if self.seq_parallel is not None:
            if self.dropout > 0.0 and not deterministic:
                raise NotImplementedError(
                    "seq_parallel attention does not fuse dropout")
            # attn_mask (if any) must address GLOBAL key columns:
            # (B|1, H|1, S_local|1, S_global) for ring,
            # (B|1, H|1, 1, S_global) for ulysses
            bias = _mask_to_bias(attn_mask)
            # Learned position biases compose with sequence parallelism
            # (r5): the bias is built per-shard with GLOBAL positions —
            # this device's query rows sit at rank*s_loc, key columns
            # are global. The table/slopes params are replicated across
            # the axis, and each device's dbias is its LOCAL (query
            # rows' / head subset's) contribution — exactly the
            # framework's replicated-param grad convention, so the
            # trainer's existing cross-axis grad psum finishes the job
            # (no replicated_bias psum here: it would double-count).
            world = bound_axis_size(self.axis_name)
            s_glob = world * q.shape[2]
            learned = False
            if self.relative_bias:     # ring-only (validated above)
                rel = RelativePositionBias(
                    num_heads=h, num_buckets=self.relative_bias_buckets,
                    max_distance=self.relative_bias_max_distance,
                    bidirectional=not self.causal, dtype=self.dtype,
                    name="rel_bias")(
                    q.shape[2], s_glob,
                    q_offset=jax.lax.axis_index(self.axis_name)
                    * q.shape[2])
                bias = rel if bias is None else bias + rel
                learned = True
            if self.alibi:             # column form: ring AND ulysses
                ab = self._alibi_column_bias(h, s_glob)
                bias = ab if bias is None else bias + ab
                learned = learned or self.alibi_learned
            if self.seq_parallel == "ring":
                ctx = ring_self_attention(q, k, v, self.axis_name,
                                          causal=self.causal, bias=bias,
                                          trainable_bias=learned)
            elif self.seq_parallel == "ulysses":
                ctx = ulysses_self_attention(q, k, v, self.axis_name,
                                             causal=self.causal,
                                             bias=bias,
                                             trainable_bias=learned)
            else:
                raise ValueError(
                    f"seq_parallel must be 'ring' or 'ulysses', got "
                    f"{self.seq_parallel!r}")
            out = nn.Dense(e, use_bias=self.bias, name="out_proj",
                           dtype=self.dtype)(
                _merge_heads(ctx).astype(x.dtype))
            if self.include_norm_add:
                out = out + residual
            return out

        bias = _mask_to_bias(attn_mask)
        if self.relative_bias:
            # TP note: the table is per-LOCAL-head (h is the local count
            # under tensor parallelism), so it shards with the heads
            rel = RelativePositionBias(
                num_heads=h, num_buckets=self.relative_bias_buckets,
                max_distance=self.relative_bias_max_distance,
                bidirectional=not self.causal, dtype=self.dtype,
                name="rel_bias")(q.shape[2], k.shape[2])
            bias = rel if bias is None else bias + rel
        if self.alibi:
            ab = self._alibi_column_bias(h, k.shape[2])
            bias = ab if bias is None else bias + ab
        learned_bias = self.relative_bias or (self.alibi
                                              and self.alibi_learned)

        if self.impl == "fast":
            # dropout AND the additive mask fuse into the flash kernels
            # (reference dropout.h + *_bias_additive_mask kernels); the
            # seed derives from the module's dropout rng per call
            rate, seed = 0.0, None
            if self.dropout > 0.0 and not deterministic:
                rate = self.dropout
                seed = _derive_seed(
                    _tp_dropout_rng(dropout_rng,
                                    self.tensor_parallel_axis),
                    self.path)
            ctx = flash_attention(q, k, v, self.causal,
                                  dropout_rate=rate, dropout_seed=seed,
                                  bias=bias,
                                  trainable_bias=learned_bias)
        else:
            # per-head dim from the ACTUAL q shape: under tensor
            # parallelism the local projection width is 3e/tp, and
            # e // num_heads_local would over-count the head dim
            scale = 1.0 / math.sqrt(q.shape[-1])
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                           preferred_element_type=jnp.float32) * scale
            if self.causal:
                sq, sk = s.shape[-2], s.shape[-1]
                row = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
                col = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
                s = jnp.where(col <= row, s, -1e30)
            # Same rank normalization as the fast path: a rank-3 (b, sq, sk)
            # mask gains the head axis instead of broadcasting against it
            # (ADVICE r2: the raw add raised or silently misaligned b vs h).
            p = masked_softmax_dropout(
                s, mask=bias, dropout_rate=self.dropout,
                rng=_tp_dropout_rng(dropout_rng,
                                    self.tensor_parallel_axis),
                deterministic=deterministic)
            ctx = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)

        ctx2d = _merge_heads(ctx).astype(x.dtype)
        if self.tensor_parallel_axis:
            # row-parallel out projection: partial matmul -> g psum ->
            # bias added once (RowParallelDense; same param tree as Dense)
            from apex_tpu.parallel.tensor_parallel import RowParallelDense
            out = RowParallelDense(e, self.tensor_parallel_axis,
                                   use_bias=self.bias, dtype=self.dtype,
                                   name="out_proj")(ctx2d)
        else:
            out = nn.Dense(e, use_bias=self.bias, name="out_proj",
                           dtype=self.dtype)(ctx2d)
        if self.include_norm_add:
            out = out + residual
        return out


class EncdecMultiheadAttn(nn.Module):
    """Encoder-decoder attention (encdec_multihead_attn.py): queries from the
    decoder stream, keys/values projected jointly from the encoder stream.

    ``decode=True`` (seq2seq inference): the PROJECTED encoder K/V are
    computed once — on the first call, which must pass ``key`` — and
    cached in the ``"cache"`` collection; every later decoder step may
    pass ``key=None`` and attends its (typically 1-token) query against
    the cached heads. Cross-attention needs no causal mask or index:
    the cache is static for the whole generation."""

    embed_dim: int
    num_heads: int
    dropout: float = 0.0
    bias: bool = False
    include_norm_add: bool = False
    impl: str = "fast"
    dtype: Any = None
    decode: bool = False

    @nn.compact
    def __call__(self, query, key=None, *,
                 attn_mask: Optional[jax.Array] = None,
                 deterministic: bool = True,
                 dropout_rng: Optional[jax.Array] = None):
        e, h = self.embed_dim, self.num_heads
        residual = query
        if self.include_norm_add:
            query = FusedLayerNorm(normalized_shape=e)(query)

        q = nn.Dense(e, use_bias=self.bias, name="q_proj",
                     dtype=self.dtype)(query)
        q = _split_heads(q, h)
        kv_proj = nn.Dense(2 * e, use_bias=self.bias, name="kv_proj",
                           dtype=self.dtype)
        if self.decode:
            have = self.has_variable("cache", "encdec_key")
            if not have and key is None:
                raise ValueError(
                    "EncdecMultiheadAttn(decode=True): the first call "
                    "must pass the encoder stream (key=...) to fill "
                    "the cross-attention cache")
            if have and key is not None:
                # silently attending a STALE cache while the caller
                # hands over a fresh encoder stream would be quiet
                # garbage — switching source sequences needs a fresh
                # cache dict
                raise ValueError(
                    "EncdecMultiheadAttn(decode=True): the "
                    "cross-attention cache is already filled; pass "
                    "key=None for decode steps (re-initialize the "
                    "cache to switch encoder streams)")
            if key is not None:
                kv = kv_proj(key)
                k0, v0 = (  # noqa: F841 — captured by the init lambdas
                    _split_heads(x_, h) for x_ in jnp.split(kv, 2, -1))
            else:
                k0 = v0 = None
            ck = self.variable("cache", "encdec_key", lambda: k0)
            cv = self.variable("cache", "encdec_value", lambda: v0)
            k, v = ck.value, cv.value
        else:
            if key is None:
                raise ValueError("key (encoder stream) is required")
            kv = kv_proj(key)
            k, v = jnp.split(kv, 2, axis=-1)
            k = _split_heads(k, h)
            v = _split_heads(v, h)

        # decode always takes the dense path: a 1-token query pads to a
        # full 128-row flash block for nothing
        if self.impl == "fast" and not self.decode:
            rate, seed = 0.0, None
            if self.dropout > 0.0 and not deterministic:
                rate = self.dropout
                seed = _derive_seed(dropout_rng, self.path)
            ctx = flash_attention(q, k, v, False,
                                  dropout_rate=rate, dropout_seed=seed,
                                  bias=_mask_to_bias(attn_mask))
        else:
            # per-head dim from the ACTUAL q shape (no tensor-parallel
            # support in this class — see SelfMultiheadAttn)
            scale = 1.0 / math.sqrt(q.shape[-1])
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                           preferred_element_type=jnp.float32) * scale
            p = masked_softmax_dropout(
                s, mask=_mask_to_bias(attn_mask), dropout_rate=self.dropout,
                rng=dropout_rng, deterministic=deterministic)
            ctx = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)

        out = nn.Dense(e, use_bias=self.bias, name="out_proj",
                       dtype=self.dtype)(_merge_heads(ctx).astype(query.dtype))
        if self.include_norm_add:
            out = out + residual
        return out


def fast_mask_softmax_dropout_func(is_training, heads, inputs, pad_mask,
                                   mask_additive, dropout_prob, rng=None):
    """Call-signature parity with the reference's standalone fused
    masked-softmax-dropout (mask_softmax_dropout_func.py:8:
    ``forward(is_training, heads, inputs, pad_mask, mask_additive,
    dropout_prob)``).

    ``inputs`` are attention scores shaped (..., q_len, k_len); ``pad_mask``
    is added to the scores when ``mask_additive`` else treated as a boolean
    padding mask (True = masked out). ``rng`` is required when
    ``is_training`` with nonzero dropout (JAX randomness is explicit).
    ``heads`` is accepted for signature parity; the array layout already
    carries the head dimension.
    """
    del heads
    mask = None
    if pad_mask is not None:
        if mask_additive:
            mask = pad_mask
        else:
            mask = jnp.where(pad_mask.astype(bool), -jnp.inf, 0.0)
    return masked_softmax_dropout(inputs, mask=mask,
                                  dropout_rate=float(dropout_prob), rng=rng,
                                  deterministic=not is_training)


__all__.append("fast_mask_softmax_dropout_func")
