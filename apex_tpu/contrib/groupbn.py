"""Group BatchNorm — parity with ``apex.contrib.groupbn.BatchNorm2d_NHWC``
(apex/contrib/groupbn/batch_norm.py:7-225 over the ``bnp`` extension):
NHWC batchnorm whose statistics are exchanged across a small group of
devices (``bn_group``), built in the reference on CUDA IPC peer memory
(apex/contrib/csrc/groupbn/ipc.cu:50-132) with occupancy-tuned persistent
kernels for small per-GPU batches.

On TPU the entire IPC machinery disappears: group stat exchange is a psum
with ``axis_index_groups`` over ICI — :class:`apex_tpu.parallel.
SyncBatchNorm` already implements it. This module provides the reference's
constructor surface (``bn_group``, fused add+relu variants) on top of it.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import flax.linen as nn

from apex_tpu.parallel.mesh import subgroups
from apex_tpu.parallel.sync_batchnorm import SyncBatchNorm


class BatchNorm2d_NHWC(nn.Module):
    """``BatchNorm2d_NHWC(planes, fuse_relu=False, bn_group=1)``
    (batch_norm.py:7). ``bn_group > 1`` syncs stats over contiguous groups of
    that many devices on the ``axis_name`` mesh axis; ``world_size`` only
    needs to be set when bn_group > 1.

    The fused add+relu variant (``bn_addrelu``, batch_norm.py:55) is the
    ``residual`` argument + ``fuse_relu`` flag: out = relu(bn(x) + residual)
    — XLA fuses the chain exactly as the bnp kernels hand-fused it.
    """

    planes: int
    fuse_relu: bool = False
    bn_group: int = 1
    world_size: Optional[int] = None
    axis_name: Optional[str] = "data"
    momentum: float = 0.1
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x, residual: Optional[jax.Array] = None,
                 use_running_average: Optional[bool] = None):
        groups = None
        axis = None
        if self.bn_group > 1:
            world = self.world_size or jax.device_count()
            groups = subgroups(world, self.bn_group)
            axis = self.axis_name
        y = SyncBatchNorm(
            features=self.planes, eps=self.eps, momentum=self.momentum,
            axis_name=axis, axis_index_groups=groups,
            name="bn")(x, use_running_average=use_running_average)
        if residual is not None:
            y = y + residual
        if self.fuse_relu:
            y = jax.nn.relu(y)
        return y
