"""apex_tpu.contrib — contrib components (reference apex/contrib/)."""

from apex_tpu.contrib import optimizers
from apex_tpu.contrib import xentropy
from apex_tpu.contrib import groupbn
from apex_tpu.contrib import multihead_attn
