"""apex_tpu.contrib (placeholder — populated incrementally)."""
