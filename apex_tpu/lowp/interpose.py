"""``fp8_autocast`` — the trace-time context that routes the amp cast
registry's whitelisted ops through fp8 QDQ pairs.

The amp interposition wrappers (amp/interposition.py) check this module
first: while a context is active, every float operand of a whitelisted
op (dot_general, matmul, einsum, conv, ...) is passed through
:func:`apex_tpu.lowp.qdq.fake_quant` — e4m3 QDQ forward, e5m2 QDQ on
the cotangent backward — instead of a plain dtype cast. With no context
active the wrappers call the original function untouched, which is what
keeps O0–O5 programs jaxpr-identical to the pre-fp8 build.

Delayed-scaling state threads through like optimizer state::

    with lowp.fp8_autocast(fp8_state, telemetry_step=step) as ctx:
        loss = model.apply(params, batch)          # casts consume scales
    new_fp8_state = ctx.new_state()                # amaxes -> next scales

Inside ``jax.value_and_grad`` the context wraps the *forward* trace;
the backward e5m2 scales are just-in-time (see qdq.py). Tensor count
discovery: trace once with ``state=None`` (just-in-time scales
throughout) — ``warmup_state`` does it via ``jax.eval_shape`` at zero
FLOPs — then ``scaling.init_state(ctx.num_tensors)``.

Ops are matched to state slots by TRACE ORDER, so the step structure
must match the warmup trace (same model, same intercepted ops); a
mismatch raises at ``new_state`` rather than silently mispairing
scales.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, List, Optional

import jax
import jax.numpy as jnp

from apex_tpu.lowp import qdq as _qdq
from apex_tpu.lowp import scaling

# dtypes the fp8 cast applies to; anything else (ints, bools, fp8
# itself, f64 accumulators) passes through untouched
_CASTABLE = (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16),
             jnp.dtype(jnp.float16))

_state = threading.local()


def current() -> Optional["Fp8Context"]:
    """The active context (None outside ``fp8_autocast`` — the hot-path
    check the amp wrappers make on every call)."""
    return getattr(_state, "ctx", None)


@contextlib.contextmanager
def suspend():
    """Temporarily deactivate the context. The amp wrappers hold this
    around the original function call: whitelisted ops nest (jnp.matmul
    dispatches to the also-patched lax.dot_general), and without the
    guard each operand would be QDQ'd once per nesting level — burning
    state slots and double-quantizing."""
    prev = current()
    _state.ctx = None
    try:
        yield
    finally:
        _state.ctx = prev


class Fp8Context:
    """Collects per-tensor amaxes and hands out quantization scales in
    trace order. Created by :func:`fp8_autocast`; not constructed
    directly."""

    def __init__(self, state: Optional[dict], *, margin: int,
                 telemetry_step: Any = None, track: bool = True):
        if state is not None:
            n = state["scale"].shape[0]
            if state["amax_history"].shape[0] != n:
                raise ValueError("fp8 state scale/amax_history tensor "
                                 "counts disagree")
        self.state = state
        self.margin = margin
        self.telemetry_step = telemetry_step
        self.track = track
        self._amaxes: List[Any] = []
        self._scales: List[Any] = []
        self._labels: List[str] = []

    # -- wrapper-facing ----------------------------------------------------
    def cast(self, x, dt, label: str = "op"):
        """The registry's fp8 cast: QDQ ``x`` at this tensor slot's scale
        (delayed from state, or just-in-time when tracing stateless).
        Non-castable dtypes pass through."""
        if jnp.dtype(dt) not in _CASTABLE:
            return x
        i = len(self._amaxes)
        amax = jnp.max(jnp.abs(x.astype(jnp.float32)))
        if self.state is not None and i < self.state["scale"].shape[0]:
            scale = self.state["scale"][i]
        else:
            scale = scaling.pow2_scale(amax, scaling.E4M3_MAX, self.margin)
        self._amaxes.append(amax)
        self._scales.append(scale)
        self._labels.append(f"t{i}:{label.rsplit('.', 1)[-1]}")
        return _qdq.fake_quant(x, scale)

    # -- step-state machine ------------------------------------------------
    @property
    def num_tensors(self) -> int:
        """Tensors intercepted so far in this trace (sizes init_state)."""
        return len(self._amaxes)

    def amaxes(self):
        """Stacked f32[T] of this trace's observed amaxes."""
        if not self._amaxes:
            return jnp.zeros((0,), jnp.float32)
        return jnp.stack(self._amaxes)

    def new_state(self, history: int = scaling.DEFAULT_HISTORY,
                  axis_name=None) -> dict:
        """Next step's delayed-scaling state from this trace's amaxes
        (fresh-initialized from them when the context ran stateless).
        Also emits the ``lowp/*`` health series for this step — per-
        tensor amax/scale timelines plus saturation provenance — when
        numerics health is enabled.

        ``axis_name``: inside ``shard_map``, pmax the per-tensor amaxes
        over that mesh axis first. Data-parallel shards each observe
        only their batch shard's activations; without the sync the
        threaded state (and therefore next step's scales) would diverge
        across replicas. The health series then carry the synced,
        replica-consistent amaxes too."""
        if self.state is not None and \
                self.num_tensors != self.state["scale"].shape[0]:
            raise ValueError(
                f"fp8_autocast intercepted {self.num_tensors} tensors but "
                f"the threaded state holds {self.state['scale'].shape[0]} "
                f"— the traced step no longer matches the warmup trace; "
                f"re-run lowp.warmup_state")
        # amaxes are monitoring state, not a differentiable path (the
        # QDQ's custom_vjp already owns the gradient); without the stop,
        # new_state() inside a value_and_grad aux would drag tangents
        # into pmax, which has no differentiation rule
        amaxes = jax.lax.stop_gradient(self.amaxes())
        if axis_name is not None and self.num_tensors:
            amaxes = jax.lax.pmax(amaxes, axis_name)
        self._emit_health(amaxes)
        if self.state is None:
            fresh = scaling.init_state(self.num_tensors, history)
            return scaling.update_state(fresh, amaxes, margin=self.margin)
        return scaling.update_state(self.state, amaxes, margin=self.margin)

    def _emit_health(self, amaxes=None) -> None:
        if not self.track or self.num_tensors == 0:
            return
        from apex_tpu.telemetry import health as _health
        if not _health.enabled():
            return
        _health.lowp_stats(amaxes if amaxes is not None else self.amaxes(),
                           jnp.stack(self._scales),
                           labels=tuple(self._labels),
                           step=self.telemetry_step)


@contextlib.contextmanager
def fp8_autocast(state: Optional[dict] = None, *,
                 margin: int = scaling.DEFAULT_MARGIN,
                 telemetry_step: Any = None, track: bool = True):
    """Scoped fp8 compute: whitelisted amp-registry ops inside the block
    run on e4m3-QDQ operands (e5m2 cotangents in backward).

    ``state`` is the delayed-scaling pytree (``scaling.init_state`` /
    ``warmup_state``); None traces with just-in-time scales. Trace-time
    scope, same contract as ``amp.autocast``. Requires the amp
    interposition to be installed (``amp.initialize`` at O6/O7 does it;
    so does ``amp.interposition.install()``).
    """
    ctx = Fp8Context(state, margin=margin, telemetry_step=telemetry_step,
                     track=track)
    prev = current()
    _state.ctx = ctx
    try:
        yield ctx
    finally:
        _state.ctx = prev


def warmup_state(fn, *args, history: int = scaling.DEFAULT_HISTORY,
                 margin: int = scaling.DEFAULT_MARGIN, **kwargs) -> dict:
    """Size a fresh delayed-scaling state by abstractly tracing ``fn``
    (``jax.eval_shape`` — zero FLOPs, zero memory) under a stateless
    context and counting the intercepted tensors."""
    from apex_tpu.amp import interposition as _interp
    _interp.install()
    with fp8_autocast(None, margin=margin, track=False) as ctx:
        jax.eval_shape(lambda *a: fn(*a, **kwargs), *args)
    return scaling.init_state(ctx.num_tensors, history)
