"""Quantize→dequantize cast pairs with the fp8 training vjp contract.

:func:`fake_quant` is the cast the amp registry applies to operands of
whitelisted ops under ``lowp.fp8_autocast``: forward runs the value
through **e4m3** (activations/weights — more mantissa), backward runs
the incoming cotangent through **e5m2** (gradients — more exponent
range). Both directions are QDQ (quantize, immediately dequantize), so
the surrounding op executes on values carrying exact fp8 precision
while the program stays in the compute dtype — the hermetic reference
semantics; ``lowp.matmul`` holds the true fp8-input kernel.

The forward scale is the delayed-scaling state's (threaded in by the
caller); the backward scale is derived just-in-time from the
cotangent's own amax. Cotangent amaxes cannot flow back into forward-
threaded state through ``custom_vjp`` without mutable collections, and
JIT scaling is the numerically stronger choice there anyway (the scale
is never stale, for one extra backward reduction).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.lowp import scaling


def qdq(x, scale, dtype=scaling.E4M3):
    """Plain quantize→dequantize round trip in ``x``'s dtype (no custom
    gradient — differentiating through it sees the clip's gradient)."""
    q = scaling.quantize(x, scale, dtype)
    return scaling.dequantize(q, scale, x.dtype)


@jax.custom_vjp
def fake_quant(x, scale):
    """fp8 cast pair: e4m3 QDQ forward, e5m2 QDQ on the cotangent
    backward (straight-through: the cotangent of the clip/round is the
    quantized cotangent itself). ``scale`` gets a zero cotangent — it is
    state, not a trained parameter."""
    return qdq(x, scale, scaling.E4M3)


def _fake_quant_fwd(x, scale):
    # residual: only the zero scale-cotangent (the output is in x's
    # dtype, so backward recovers the input dtype from g itself)
    return qdq(x, scale, scaling.E4M3), jnp.zeros_like(scale)


def _fake_quant_bwd(res, g):
    g32 = g.astype(jnp.float32)
    gscale = scaling.pow2_scale(jnp.max(jnp.abs(g32)), scaling.E5M2_MAX,
                                margin=0)
    gq = qdq(g32, gscale, scaling.E5M2)
    return gq.astype(g.dtype), res


fake_quant.defvjp(_fake_quant_fwd, _fake_quant_bwd)
