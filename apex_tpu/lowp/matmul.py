"""fp8-input, fp32-accumulate matmul behind the xentropy-style backend
select (``APEX_TPU_FP8_BACKEND=jnp|pallas``).

Two execution paths, selected by :func:`backend`:

  * **jnp** (the default, CPU/CI hermetic): quantize both operands to
    e4m3 at their (delayed or just-in-time) scales, then a plain
    ``lax.dot_general`` **on the fp8 arrays** with
    ``preferred_element_type=float32`` — XLA widens in-register, so the
    accumulation is fp32 and the operands carry exact fp8 precision.
    This is the reference semantics the Pallas path is parity-tested
    against, and what CI runs on the CPU mesh.
  * **pallas** (opt-in): a blocked Mosaic kernel taking the e4m3 tiles
    directly — grid (M/bm, N/bn, K/bk) with K innermost, one fp32 VMEM
    accumulator tile per (i, j), dequantized by the combined scale once
    at the end.  fp8 operand tiles want (32, 128) minimum Mosaic tiling,
    so the path requires 128-aligned shapes and **declines off-TPU**
    (no interpret-mode fallback: an fp8 candidate must not crash — or
    silently masquerade — on a host backend; see
    ``tune.measure.supports_fp8``).  Block sizes come from the tune
    registry (``tune.fp8_matmul_blocks``) and are sweepable.

Both paths return ``(x @ w)`` computed through the fp8 quantization of
the inputs — NOT the exact product; parity between the two paths is the
contract (tests/test_lowp.py), exactness vs fp32 is bounded by e4m3.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.lowp import scaling

_BACKENDS = ("jnp", "pallas")
_FORCE = os.environ.get("APEX_TPU_FP8_BACKEND", "auto")  # auto|jnp|pallas
_OVERRIDE: Optional[str] = None

# test hook: lets the CPU suite drive the Mosaic kernel through the
# Pallas interpreter. NEVER set on the production path — off-TPU the
# kernel path declines instead (satellite: decline, don't crash).
_ALLOW_INTERPRET = False

LANES = 128
SUBLANES = 32  # fp8 min sublane tile


def set_backend(name: Optional[str] = None) -> Optional[str]:
    """Process-level backend override (None restores the env/default).
    Returns the previous override so callers can save/restore."""
    global _OVERRIDE
    if name is not None and name not in _BACKENDS:
        raise ValueError(f"fp8 matmul backend must be one of {_BACKENDS}, "
                         f"got {name!r}")
    prev = _OVERRIDE
    _OVERRIDE = name
    return prev


def backend() -> str:
    """Active execution path: ``set_backend`` override, else the
    ``APEX_TPU_FP8_BACKEND`` env value; ``auto`` resolves to ``jnp``.
    An unrecognized value raises (loud-failure doctrine: a typo'd opt-in
    must not silently measure the reference path)."""
    b = _OVERRIDE if _OVERRIDE is not None else _FORCE
    if b in _BACKENDS:
        return b
    if b in ("auto", ""):
        return "jnp"
    raise ValueError(f"APEX_TPU_FP8_BACKEND={b!r} — expected one of "
                     f"{_BACKENDS} or 'auto'")


def supported(m: int, k: int, n: int) -> bool:
    """Shape gate for the kernel path: fp8 operand tiles are (32, 128)
    minimum, and the default blocking tiles all three dims by 128."""
    return m % LANES == 0 and k % LANES == 0 and n % LANES == 0


def _on_device() -> bool:
    return jax.default_backend() in ("tpu", "axon") or _ALLOW_INTERPRET


def _use_pallas(m: int, k: int, n: int) -> bool:
    return backend() == "pallas" and supported(m, k, n) and _on_device()


def _resolve_blocks(m, k, n, block_m, block_n, block_k):
    if block_m is not None and block_n is not None and block_k is not None:
        return int(block_m), int(block_n), int(block_k)
    from apex_tpu import tune
    bm, bn, bk = tune.fp8_matmul_blocks(m=m, k=k, n=n)
    return (int(block_m) if block_m is not None else bm,
            int(block_n) if block_n is not None else bn,
            int(block_k) if block_k is not None else bk)


def _jit_scale(x):
    return scaling.pow2_scale(jnp.max(jnp.abs(x.astype(jnp.float32))),
                              scaling.E4M3_MAX)


def _mm_kernel(x_ref, w_ref, o_ref):
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(2) == 0)
    def _init():
        o_ref[...] = jnp.zeros_like(o_ref)

    # fp8 tiles straight into the dot; fp32 accumulation is forced by
    # preferred_element_type — the entire point of the kernel
    o_ref[...] += jax.lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _pallas_mm(x8, w8, block_m, block_n, block_k):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, k = x8.shape
    n = w8.shape[1]
    bm = min(block_m, m)
    bn = min(block_n, n)
    bk = min(block_k, k)
    grid = (m // bm, n // bn, k // bk)
    return pl.pallas_call(
        _mm_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        interpret=jax.default_backend() not in ("tpu", "axon"),
    )(x8, w8)


def fp8_matmul(x, w, *, scale_x=None, scale_w=None,
               block_m: Optional[int] = None, block_n: Optional[int] = None,
               block_k: Optional[int] = None, out_dtype=None):
    """``x @ w`` through e4m3-quantized operands with fp32 accumulation.

    ``x``: (M, K), ``w``: (K, N), any float dtype. ``scale_x`` /
    ``scale_w`` are the quantization scales (fp32 scalars, typically the
    delayed-scaling state's); None derives them just-in-time from the
    operand's own amax. Output is dequantized by ``1/(scale_x*scale_w)``
    and returned in ``out_dtype`` (default: the promoted input dtype).
    """
    if x.ndim != 2 or w.ndim != 2 or x.shape[1] != w.shape[0]:
        raise ValueError(f"fp8_matmul wants (M,K)@(K,N), got "
                         f"{x.shape} @ {w.shape}")
    out = jnp.dtype(out_dtype) if out_dtype is not None \
        else jnp.result_type(x.dtype, w.dtype)
    sx = _jit_scale(x) if scale_x is None else \
        jnp.asarray(scale_x, jnp.float32)
    sw = _jit_scale(w) if scale_w is None else \
        jnp.asarray(scale_w, jnp.float32)
    x8 = scaling.quantize(x, sx, scaling.E4M3)
    w8 = scaling.quantize(w, sw, scaling.E4M3)
    m, k = x.shape
    n = w.shape[1]
    if _use_pallas(m, k, n):
        bm, bn, bk = _resolve_blocks(m, k, n, block_m, block_n, block_k)
        acc = _pallas_mm(x8, w8, bm, bn, bk)
    else:
        acc = jax.lax.dot_general(
            x8, w8, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    return (acc / (sx * sw)).astype(out)
