"""Per-tensor delayed scaling for fp8 compute (ROADMAP item 5).

fp8 has ~2 decimal digits of dynamic headroom per format (e4m3 tops out
at 448, e5m2 at 57344), so every tensor must be rescaled into the
representable band before the cast and rescaled back after.  The scheme
here is *delayed scaling*: each fp8 tensor keeps a bounded history of
its recent absolute maxima, and the quantization scale for step N is
derived from the history as of step N-1.  That keeps the scale a
trace-time-threaded fp32 array (no data-dependent recompilation, no
host sync) at the cost of one-step staleness — a tensor whose amax
jumps past its history saturates for one step (clipped to ±fp8_max, a
finite value, so the amp overflow check is NOT tripped; the saturation
event is what ``telemetry.health``'s ``lowp/*`` series records).

State layout (a plain pytree, so it threads through jit/donation/
checkpoints like any optimizer state)::

    {"amax_history": f32[T, H],   # ring of the last H amaxes per tensor
     "scale":        f32[T]}      # quantization scale derived from it

Scales are powers of two: ``scale = 2^(floor(log2(fp8_max / amax)) -
margin)``.  A pow2 scale multiplies mantissas exactly, so quantize →
dequantize round-trips bit-exactly for values already representable in
fp8, and the scale composes exactly with amp's pow2 loss scale.

``T`` (the tensor count) is discovered by tracing: run one step inside
``lowp.fp8_autocast(None)`` (or call :func:`apex_tpu.lowp.warmup_state`
which does it via ``jax.eval_shape`` — zero FLOPs) and size the state
from the context's ``num_tensors``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# fp8 wire formats (jax ships both ml_dtypes variants; e4m3fn is the
# "no infinities, saturating" variant every fp8 training recipe uses)
E4M3 = jnp.float8_e4m3fn
E5M2 = jnp.float8_e5m2

E4M3_MAX = 448.0
E5M2_MAX = 57344.0

DEFAULT_HISTORY = 16
# one binade of headroom below fp8_max: the delayed scale is one step
# stale, so leave room for the amax to grow 2x before saturating
DEFAULT_MARGIN = 1

_FP8_MAX = {jnp.dtype(E4M3): E4M3_MAX, jnp.dtype(E5M2): E5M2_MAX}


def fp8_max(dtype) -> float:
    """Largest finite magnitude of an fp8 dtype."""
    return _FP8_MAX[jnp.dtype(dtype)]


def pow2_scale(amax, max_val: float, margin: int = DEFAULT_MARGIN):
    """Power-of-two scale mapping ``amax`` just under ``max_val``.

    ``x * scale`` is guaranteed <= max_val for |x| <= amax (floor keeps
    the exponent conservative); margin subtracts extra binades of
    headroom. amax == 0 (a dead tensor) resolves to scale 1.0, and the
    exponent is clamped to ±30 so a denormal amax cannot produce an
    inf/0 scale.
    """
    amax = jnp.asarray(amax, jnp.float32)
    exp = jnp.floor(jnp.log2(max_val / jnp.maximum(amax, 1e-30))) - margin
    exp = jnp.clip(exp, -30.0, 30.0)
    # ldexp, not exp2: XLA's f32 exp2 is off by an ulp for some integer
    # exponents (e.g. exp2(21) -> 2097153 on CPU), which would break the
    # exact-pow2 contract everything downstream composes on
    pow2 = jnp.ldexp(jnp.float32(1.0), exp.astype(jnp.int32))
    return jnp.where(amax > 0.0, pow2, 1.0).astype(jnp.float32)


def init_state(num_tensors: int, history: int = DEFAULT_HISTORY) -> dict:
    """Fresh delayed-scaling state: empty history, unit scales (the
    first step quantizes at scale 1.0 and seeds the history)."""
    if num_tensors < 0:
        raise ValueError(f"num_tensors must be >= 0, got {num_tensors}")
    if history < 1:
        raise ValueError(f"history must be >= 1, got {history}")
    return {"amax_history": jnp.zeros((num_tensors, history), jnp.float32),
            "scale": jnp.ones((num_tensors,), jnp.float32)}


def update_state(state: dict, amaxes, *, max_val: float = E4M3_MAX,
                 margin: int = DEFAULT_MARGIN) -> dict:
    """One state-machine step: push this step's observed amaxes into the
    ring, derive next step's scales from the history max.

    Pure function of (state, amaxes) — call it inside the jitted step
    with the amaxes collected by ``fp8_autocast`` and carry the result
    forward, exactly like optimizer state.
    """
    hist = jnp.asarray(state["amax_history"], jnp.float32)
    amaxes = jnp.asarray(amaxes, jnp.float32)
    if amaxes.shape != (hist.shape[0],):
        raise ValueError(
            f"amaxes shape {amaxes.shape} does not match state with "
            f"{hist.shape[0]} tensors — re-init the state (warmup_state) "
            f"after changing the model or the set of intercepted ops")
    hist = jnp.roll(hist, 1, axis=1).at[:, 0].set(amaxes)
    amax = jnp.max(hist, axis=1)
    return {"amax_history": hist,
            "scale": pow2_scale(amax, max_val, margin)}


def quantize(x, scale, dtype=E4M3):
    """Scale, saturate, cast: the raw fp8 array (``dequantize`` undoes
    it). Saturation is explicit so e5m2 (which HAS inf) clips instead of
    overflowing — a saturated fp8 tensor stays finite and is reported
    through the lowp/* health series, not the amp overflow check."""
    m = fp8_max(dtype)
    y = x.astype(jnp.float32) * jnp.asarray(scale, jnp.float32)
    return jnp.clip(y, -m, m).astype(dtype)


def dequantize(q, scale, dtype=jnp.float32):
    return (q.astype(jnp.float32) / jnp.asarray(scale, jnp.float32)) \
        .astype(dtype)
