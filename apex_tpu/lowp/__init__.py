"""apex_tpu.lowp — the fp8 compute tier (amp opt levels O6/O7).

The reference fork's signature move was stretching Apex's opt levels to
bf16 (O4/O5); this package takes the next step down (ROADMAP item 5):

  * :mod:`scaling`   — per-tensor delayed scaling: bounded amax history
    → power-of-two scales, a plain fp32 pytree threaded through the
    train step like optimizer state.
  * :mod:`qdq`       — quantize/dequantize cast pairs via ``custom_vjp``:
    e4m3 for activations/weights forward, e5m2 for cotangents backward.
  * :mod:`interpose` — ``fp8_autocast``, the trace-time context the amp
    cast registry consults: whitelisted ops' operands run through the
    QDQ pairs while it is active, untouched otherwise (O0–O5 stay
    jaxpr-identical).
  * :mod:`matmul`    — ``fp8_matmul``: fp8-input fp32-accumulate, jnp
    reference path by default (CPU/CI hermetic), blocked Pallas kernel
    behind ``APEX_TPU_FP8_BACKEND=pallas`` (declines off-TPU), block
    sizes in the tune sweep registry.

Opt-level surface (amp/frontend.py): **O6** = fp8 compute over bf16
weights, **O7** = fp8 compute + fp32 master weights. The int8 *wire*
tier (gradient collectives, ``reduce_dtype="int8"``) lives in
``parallel.overlap`` — wire compression is a collectives property, not
a compute one; docs/lowp.md has the full table.

Recipe::

    model, opt = amp.initialize(model, opt, opt_level="O6")
    fp8_state = lowp.warmup_state(
        lambda p, b: model.apply(p, b), params, batch)

    def step(params, fp8_state, batch):
        def loss_fn(p):
            with lowp.fp8_autocast(fp8_state) as ctx:
                loss = model.apply(p, batch)
            return loss, ctx.new_state()
        (loss, new_state), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        ...
        return loss, new_state
"""

from apex_tpu.lowp.interpose import (Fp8Context, current, fp8_autocast,
                                     warmup_state)
from apex_tpu.lowp.matmul import backend, fp8_matmul, set_backend, supported
from apex_tpu.lowp.qdq import fake_quant, qdq
from apex_tpu.lowp.scaling import (DEFAULT_HISTORY, DEFAULT_MARGIN, E4M3,
                                   E4M3_MAX, E5M2, E5M2_MAX, dequantize,
                                   fp8_max, init_state, pow2_scale, quantize,
                                   update_state)

__all__ = [
    "Fp8Context", "current", "fp8_autocast", "warmup_state",
    "backend", "fp8_matmul", "set_backend", "supported",
    "fake_quant", "qdq",
    "DEFAULT_HISTORY", "DEFAULT_MARGIN", "E4M3", "E4M3_MAX", "E5M2",
    "E5M2_MAX", "dequantize", "fp8_max", "init_state", "pow2_scale",
    "quantize", "update_state",
]
