"""apex_tpu.mlp (placeholder — populated incrementally)."""
