"""Fused MLP — parity with ``apex.mlp.MLP`` (apex/mlp/mlp.py:8-79 over
``mlp_cuda``, csrc/mlp.cpp:53-171 + csrc/mlp_cuda.cu: chained cuBLAS GEMMs
with fused bias/ReLU/sigmoid epilogues).

On TPU no hand-written chain is needed: a jitted sequence of
``dot_general + bias + activation`` is fused by XLA into MXU matmuls with
epilogue fusion — the very thing mlp_cuda hand-built. The module keeps the
reference's constructor surface (``mlp_sizes``, ``bias``, ``activation``,
amp registration via ``amp.low_prec_function``).
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp
import flax.linen as nn

from apex_tpu.amp.interposition import low_prec_function

_ACTS = {
    "none": lambda x: x,
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
}


@low_prec_function
def mlp_function(x: jax.Array, weights: Sequence[jax.Array],
                 biases: Sequence[jax.Array], activation: str = "relu",
                 ) -> jax.Array:
    """Functional fused MLP: y = act(...act(x W1 + b1)... W_n + b_n).
    Amp-registered low-precision (the reference registers mlp via
    ``amp.half_function``, apex/mlp/mlp.py:24). Final layer has no
    activation, matching mlp_cuda semantics."""
    act = _ACTS[activation]
    h = x
    for i, w in enumerate(weights):
        h = h @ w.T
        if biases:
            h = h + biases[i]
        if i < len(weights) - 1:
            h = act(h)
    return h


class MLP(nn.Module):
    """``MLP(mlp_sizes, bias=True, activation='relu')`` (apex/mlp/mlp.py:30).
    ``mlp_sizes[0]`` is the input features; weights are stored transposed
    (out, in) like the reference's torch Linear layout."""

    mlp_sizes: Sequence[int]
    bias: bool = True
    activation: str = "relu"
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        sizes = tuple(self.mlp_sizes)
        if len(sizes) < 2:
            raise ValueError("mlp_sizes needs at least (in, out)")
        weights, biases = [], []
        for i in range(len(sizes) - 1):
            w = self.param(f"weight_{i}",
                           nn.initializers.lecun_normal(),
                           (sizes[i + 1], sizes[i]), jnp.float32)
            weights.append(w)
            if self.bias:
                biases.append(self.param(
                    f"bias_{i}", nn.initializers.zeros, (sizes[i + 1],),
                    jnp.float32))
        y = mlp_function(x, weights, biases if self.bias else [],
                         self.activation)
        return y.astype(self.dtype) if self.dtype is not None else y
