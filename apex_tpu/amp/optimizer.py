"""AmpOptimizer: the functional replacement for the reference's optimizer
surgery (apex/amp/_process_optimizer.py:321-489) — master-weight management,
fused unscale, and overflow step-skipping, all inside one jittable update.

Reference flow it reproduces (call stack SURVEY.md §3.3):
  scale_loss -> backward -> [post_backward] unscale grads w/ overflow check ->
  update_scale -> step or skip.

Improvements inherent to the design:
  * ``lax.cond`` selects stepped vs un-stepped state on device — no host sync
    (the reference does a D2H ``.item()`` per step, scaler.py:209, and patches
    ``optimizer.step`` to a no-op on overflow, handle.py:127-154).
  * Master fp32 weights live in the optimizer state pytree; the master->model
    copy (``_process_optimizer.py:14-25``) is a fused cast that XLA schedules
    with the update.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.amp.policy import Properties
from apex_tpu.amp.scaler import LossScaler, ScalerState

Tree = Any


class AmpOptimizerState(NamedTuple):
    inner: Any             # fused optimizer state (over master or model params)
    master: Any            # fp32 master params, or () when not used
    scaler: ScalerState


class AmpOptimizer:
    """Wraps a :class:`~apex_tpu.optimizers.base.FusedOptimizer` with amp
    semantics per the resolved ``Properties``."""

    def __init__(self, inner, properties: Properties, *, num_losses: int = 1,
                 **scaler_kwargs):
        self.inner = inner
        self.properties = properties
        self.scaler = LossScaler(properties.loss_scale, num_losses=num_losses,
                                 **scaler_kwargs)
        self.num_losses = num_losses

    # -- state -------------------------------------------------------------
    def init(self, model_params: Tree) -> AmpOptimizerState:
        if self.properties.master_weights:
            # copy=True: leaves that are already fp32 (keep_batchnorm_fp32)
            # must still get their own buffer — astype would alias them with
            # the model params, breaking buffer donation of (params, state).
            master = jax.tree_util.tree_map(
                lambda p: jnp.array(p, dtype=jnp.float32, copy=True),
                model_params)
            inner = self.inner.init(master)
        else:
            master = ()
            inner = self.inner.init(model_params)
        return AmpOptimizerState(inner=inner, master=master,
                                 scaler=self.scaler.init())

    # -- loss scaling ------------------------------------------------------
    def scale_loss(self, loss: jax.Array, state: AmpOptimizerState,
                   loss_id: int = 0) -> jax.Array:
        """``with amp.scale_loss(loss, optimizer)`` equivalent: returns the
        scaled loss to differentiate (handle.py:81-113)."""
        if not self.properties.enabled:
            return loss
        return self.scaler.scale_loss(loss, state.scaler, loss_id)

    def execution_index(self, state: AmpOptimizerState,
                        loss_id: int = 0):
        """Monotone per-CALL step index for telemetry attribution.

        ``inner.step`` counts only successful (non-overflow) applies —
        it freezes while the dynamic scaler skips — so successes +
        cumulative overflows advances exactly once per ``step()`` call.
        ONE definition shared by every health/telemetry producer
        (overflow attribution in :meth:`step`, grad_stats / ddp bucket
        norms in trainers): series recorded against it join the scaler's
        ``amp/overflow`` / ``amp/loss_scale`` timelines in summarize's
        (name, step) dedup, and a drifting copy would silently mis-join
        them. Returns None when the inner optimizer keeps no ``step``;
        trace-safe (a traced scalar inside jit)."""
        step = getattr(state.inner, "step", None)
        if step is None:
            return None
        return step + state.scaler.overflows[loss_id]

    # -- the step ----------------------------------------------------------
    def step(self, scaled_grads: Tree, model_params: Tree,
             state: AmpOptimizerState, loss_id: int = 0,
             ) -> Tuple[Tree, AmpOptimizerState, dict]:
        """Unscale, check overflow, conditionally step, update the scaler.

        Returns ``(new_model_params, new_state, info)`` where info carries
        ``overflow`` and ``loss_scale`` as device scalars.
        """
        props = self.properties
        use_master = props.master_weights
        # FusedSGD's materialize_master_grads=False fast path
        # (apex/amp/_process_optimizer.py:258-310): no fp32 master-grad
        # materialization — the low-precision grads feed the kernel directly
        # with the unscale fused via grad_scale, and the kernel emits the
        # low-precision model copy alongside the fp32 master update (the
        # reference's 4-list multi_tensor_sgd variant).
        no_materialize = use_master and not getattr(
            self.inner, "materialize_master_grads", True)

        # Static loss scale never skips a step (reference update_scale
        # gates every overflow consequence on self.dynamic,
        # scaler.py:206-226) — so don't pay for the nonfinite reductions
        # or the lax.cond at all on the O0/O3/O4/O5 static levels.
        dynamic = self.scaler.dynamic
        if no_materialize:
            from apex_tpu import ops
            if dynamic:
                overflow = ops.multi_tensor_check_overflow(scaled_grads)
            else:
                overflow = jnp.zeros((), jnp.bool_)
            grads32 = scaled_grads
        else:
            grads32, overflow = self.scaler.unscale(
                scaled_grads, state.scaler, loss_id,
                out_dtype=jnp.float32 if use_master else None,
                check_overflow=dynamic)

        def do_step(_):
            if no_materialize:
                new_master, new_inner, new_model = self.inner.step(
                    grads32, state.master, state.inner,
                    grad_scale=state.scaler.loss_scale[loss_id],
                    model_out_template=model_params)
                return new_model, new_master, new_inner
            target = state.master if use_master else model_params
            new_target, new_inner = self.inner.step(grads32, target,
                                                    state.inner)
            if use_master:
                new_model = jax.tree_util.tree_map(
                    lambda mp, p: mp.astype(p.dtype), new_target, model_params)
                return new_model, new_target, new_inner
            return new_target, (), new_inner

        def skip(_):
            return model_params, state.master, state.inner

        if props.enabled and dynamic:
            new_model, new_master, new_inner = jax.lax.cond(
                overflow, skip, do_step, None)
        else:
            new_model, new_master, new_inner = do_step(None)

        # telemetry step attribution: the EXECUTION index, not the inner
        # optimizer step — skipped (overflowed) steps leave inner.step
        # frozen, but successes + cumulative overflows advances once per
        # call, so per-step event series stay per-step under skips.
        # Built only when telemetry is on: the disabled program must be
        # identical to the uninstrumented one.
        from apex_tpu import telemetry
        step_idx = None
        if telemetry.enabled():
            step_idx = self.execution_index(state, loss_id)
        # non-finite provenance (telemetry.health): when the overflow
        # flag fires, count NaN/Inf per named param group over the
        # SCALED grads (that is where the non-finites live) and name the
        # first offending group. The per-group reduction runs only on
        # the overflow branch (lax.cond inside attribute_overflow); with
        # health disabled nothing is traced.
        if props.enabled and dynamic:
            from apex_tpu.telemetry import health as _health
            if _health.enabled():
                _health.attribute_overflow(overflow, scaled_grads,
                                           step=step_idx)
        new_scaler = self.scaler.update(state.scaler, overflow, loss_id,
                                        step=step_idx)
        new_state = AmpOptimizerState(inner=new_inner, master=new_master,
                                      scaler=new_scaler)
        info = {"overflow": overflow,
                "loss_scale": new_scaler.loss_scale[loss_id]}
        return new_model, new_state, info

    # -- param groups (add_param_group analog, _process_optimizer.py:411-487)
    def add_param_group(self, group: dict) -> None:
        """Append a param group on the wrapped optimizer. For params not yet
        in the state, follow with ``extend_init``."""
        self.inner.add_param_group(group)

    def extend_init(self, state: AmpOptimizerState, model_params: Tree,
                    ) -> AmpOptimizerState:
        """Grow the state to cover an enlarged ``model_params`` tree,
        preserving existing master weights and inner state (the reference's
        add_param_group-with-new-params flow,
        tests/L0/run_amp/test_add_param_group.py)."""
        if self.properties.master_weights:
            fresh_master = jax.tree_util.tree_map(
                lambda p: jnp.array(p, dtype=jnp.float32, copy=True),
                model_params)
            from apex_tpu.utils import path_str
            old = {path_str(kp): leaf for kp, leaf in
                   jax.tree_util.tree_leaves_with_path(state.master)}
            leaves = jax.tree_util.tree_leaves_with_path(fresh_master)
            master = jax.tree_util.tree_unflatten(
                jax.tree_util.tree_structure(fresh_master),
                [old.get(path_str(kp), leaf) for kp, leaf in leaves])
            inner = self.inner.extend_init(state.inner, master)
        else:
            master = ()
            inner = self.inner.extend_init(state.inner, model_params)
        return AmpOptimizerState(inner=inner, master=master,
                                 scaler=state.scaler)

    # -- introspection / checkpointing ------------------------------------
    def master_params(self, state: AmpOptimizerState) -> Tree:
        """``amp.master_params(optimizer)`` analog (_amp_state.py:59-68)."""
        return state.master if self.properties.master_weights else None

    def state_dict(self, state: AmpOptimizerState) -> dict:
        return self.scaler.state_dict(state.scaler)

    def load_state_dict(self, state: AmpOptimizerState, d: dict,
                        ) -> AmpOptimizerState:
        return state._replace(scaler=self.scaler.load_state_dict(d))
