"""Dynamic/static loss scaling — behavioral parity with the reference
``LossScaler`` (apex/amp/scaler.py:42-226), designed so the whole
scale → backward → unscale → maybe-skip → rescale cycle lives INSIDE one
jitted step:

  * The overflow flag is a device scalar returned by the fused unscale
    (ops.multi_tensor_scale), never synced to host — the reference pays one
    D2H ``item()`` per step (scaler.py:209); here ``lax.cond`` selects between
    stepped and un-stepped state on device.
  * Scaler state is a pytree (``ScalerState``) carried in the train state and
    checkpointable (the reference serializes (loss_scale, unskipped) per loss,
    frontend.py:428-467).

Defaults match scaler.py:47-61: init 2**16, growth/backoff factor 2, growth
window 2000 steps, max scale 2**24.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu import ops


class ScalerState(NamedTuple):
    """Per-loss scaler state; fields have shape (num_losses,)."""

    loss_scale: jax.Array  # f32
    unskipped: jax.Array   # i32 — steps since last overflow (growth tracker)
    overflows: jax.Array   # i32 — total overflow count (observability)


class LossScaler:
    """Static config for loss scaling; all methods are pure and jittable."""

    def __init__(self, loss_scale="dynamic", *,
                 init_scale: float = 2.0 ** 16,
                 scale_factor: float = 2.0,
                 scale_window: int = 2000,
                 min_loss_scale: Optional[float] = None,
                 max_loss_scale: float = 2.0 ** 24,
                 num_losses: int = 1):
        self.dynamic = (loss_scale == "dynamic")
        self._static_scale = 1.0 if self.dynamic else float(loss_scale)
        self.init_scale = init_scale if self.dynamic else self._static_scale
        self.scale_factor = scale_factor
        self.scale_window = scale_window
        self.min_loss_scale = min_loss_scale
        self.max_loss_scale = max_loss_scale
        self.num_losses = num_losses

    # -- state ------------------------------------------------------------
    def init(self) -> ScalerState:
        n = self.num_losses
        return ScalerState(
            loss_scale=jnp.full((n,), self.init_scale, jnp.float32),
            unskipped=jnp.zeros((n,), jnp.int32),
            overflows=jnp.zeros((n,), jnp.int32),
        )

    # -- hot path ----------------------------------------------------------
    def scale_loss(self, loss: jax.Array, state: ScalerState,
                   loss_id: int = 0) -> jax.Array:
        """loss * current scale (the ``amp.scale_loss`` __enter__ product,
        apex/amp/handle.py:81-113)."""
        return loss.astype(jnp.float32) * state.loss_scale[loss_id]

    def unscale(self, scaled_grads: Any, state: ScalerState,
                loss_id: int = 0, *, out_dtype=None,
                check_overflow: bool = True) -> Tuple[Any, jax.Array]:
        """Fused grads/scale with nonfinite detection (scaler.py:103-128).

        Returns ``(unscaled_grads, overflow)``. ``out_dtype`` optionally casts
        grads (e.g. to fp32 for master-weight steps) before unscaling.

        ``check_overflow=False`` skips the nonfinite reduction entirely and
        returns a constant-False overflow — the static-scale path, where the
        reference never consults the overflow buffer (scaler.py:206-226
        gates on ``self.dynamic``) and a scale of 1.0 skips the multiply
        too (scaler.py:111-112).
        """
        if out_dtype is not None:
            scaled_grads = jax.tree_util.tree_map(
                lambda g: g.astype(out_dtype), scaled_grads)
        inv = 1.0 / state.loss_scale[loss_id]
        if check_overflow:
            return ops.multi_tensor_scale(scaled_grads, inv)
        if self.dynamic or self._static_scale != 1.0:
            scaled_grads = jax.tree_util.tree_map(
                lambda g: (g * inv).astype(g.dtype), scaled_grads)
        return scaled_grads, jnp.zeros((), jnp.bool_)

    def update(self, state: ScalerState, overflow: jax.Array,
               loss_id: int = 0, *, step=None) -> ScalerState:
        """Post-step scale adjustment (scaler.py:206-226): overflow halves the
        scale and resets the window; ``scale_window`` clean steps double it.

        With telemetry enabled (apex_tpu.telemetry.enable() BEFORE jitting
        the step), emits per-step ``amp/overflow`` and ``amp/loss_scale``
        events through a trace-safe host callback; ``step`` optionally
        attributes them to a step counter (AmpOptimizer passes its
        execution index — successes + overflows — so the series stays
        per-step even when overflow skips freeze the inner optimizer
        step). Disabled: zero cost, nothing traced.

        The scaler sees only the flag, not the grads, so WHICH param
        group went non-finite is attributed one level up:
        AmpOptimizer.step calls ``telemetry.health.attribute_overflow``
        on the scaled grad tree when ``telemetry.health`` is enabled."""
        new_state = self._update(state, overflow, loss_id)
        from apex_tpu import telemetry
        if telemetry.enabled():
            # secondary losses get their own series — merging per-loss
            # scalers under one name would average unrelated scales in
            # summarize's (name, step) dedup
            suffix = "" if loss_id == 0 else f"/loss{loss_id}"
            telemetry.record(f"amp/overflow{suffix}",
                             overflow.astype(jnp.float32), step=step)
            telemetry.record(f"amp/loss_scale{suffix}",
                             new_state.loss_scale[loss_id], step=step)
        return new_state

    def _update(self, state: ScalerState, overflow: jax.Array,
                loss_id: int = 0) -> ScalerState:
        if not self.dynamic:
            return state._replace(
                overflows=state.overflows.at[loss_id].add(
                    overflow.astype(jnp.int32)))
        scale = state.loss_scale[loss_id]
        unskipped = state.unskipped[loss_id]

        shrunk = scale / self.scale_factor
        if self.min_loss_scale is not None:
            shrunk = jnp.maximum(shrunk, self.min_loss_scale)
        grown = jnp.minimum(scale * self.scale_factor, self.max_loss_scale)

        new_unskipped = jnp.where(overflow, 0, unskipped + 1)
        should_grow = new_unskipped >= self.scale_window
        new_scale = jnp.where(overflow, shrunk,
                              jnp.where(should_grow, grown, scale))
        new_unskipped = jnp.where(should_grow, 0, new_unskipped)
        return ScalerState(
            loss_scale=state.loss_scale.at[loss_id].set(new_scale),
            unskipped=state.unskipped.at[loss_id].set(new_unskipped),
            overflows=state.overflows.at[loss_id].add(
                overflow.astype(jnp.int32)),
        )

    # -- checkpointing (amp.state_dict parity, frontend.py:428-467) --------
    def state_dict(self, state: ScalerState) -> dict:
        return {
            "loss_scale": jax.device_get(state.loss_scale),
            "unskipped": jax.device_get(state.unskipped),
            "overflows": jax.device_get(state.overflows),
        }

    def load_state_dict(self, d: dict) -> ScalerState:
        return ScalerState(
            loss_scale=jnp.asarray(d["loss_scale"], jnp.float32),
            unskipped=jnp.asarray(d["unskipped"], jnp.int32),
            overflows=jnp.asarray(d["overflows"], jnp.int32),
        )
