"""Legacy amp handle API — parity with apex/amp/handle.py:170-281
(``AmpHandle``/``NoOpHandle`` from the pre-``initialize`` era ``amp.init()``)
and apex/amp/opt.py:9-103 (``OptimWrapper``). The reference keeps these for
compatibility and hard-errors old flows toward the new API; we do the same.
"""

from __future__ import annotations

import contextlib
from typing import Optional

import jax.numpy as jnp

from apex_tpu.amp import interposition
from apex_tpu.amp.scaler import LossScaler


class AmpHandle:
    """Returned by the legacy ``amp.init()`` (reference handle.py:170).

    Scoped wrapper over the interposition engine + a host-side loss scaler.
    Prefer ``amp.initialize``.
    """

    def __init__(self, loss_scale="dynamic", enable_caching: bool = True,
                 verbose: bool = False, dtype=jnp.float16):
        self._enabled = True
        self._dtype = dtype
        self._cache_enabled = enable_caching
        self._scaler = LossScaler(loss_scale)
        self._scaler_state = self._scaler.init()
        interposition.enable(dtype)

    def is_active(self) -> bool:
        return self._enabled

    @property
    def has_cache(self) -> bool:
        # trace-time casting is CSE'd by XLA; the cache exists implicitly
        return self._cache_enabled

    @contextlib.contextmanager
    def scale_loss(self, loss, optimizer):
        """Legacy context manager. In JAX the backward pass is explicit, so
        this hard-errors with migration guidance — exactly how the reference
        directs old flows to the new API (handle.py:17-28)."""
        raise RuntimeError(
            "The legacy amp.init()/handle.scale_loss API cannot express a "
            "JAX backward pass. Use amp.initialize(...) and "
            "AmpOptimizer.scale_loss/step instead.")

    def _deactivate(self):
        self._enabled = False
        interposition.disable()


class NoOpHandle:
    """reference handle.py:263-281."""

    def is_active(self) -> bool:
        return False

    def _deactivate(self):
        pass


def init(enabled: bool = True, loss_scale="dynamic",
         enable_caching: bool = True, verbose: bool = False):
    """Legacy ``amp.init()`` (reference amp.py:75). Returns a handle that
    activates O1-style interposition globally."""
    if not enabled:
        return NoOpHandle()
    return AmpHandle(loss_scale, enable_caching, verbose)
