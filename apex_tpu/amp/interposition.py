"""O1/O4 function interposition: trace-time autocasting by patching the
``jax.numpy`` / ``jax.lax`` / ``jax.nn`` namespaces.

This is the TPU-native equivalent of the reference's eager monkey-patching
engine (apex/amp/amp.py:75-198 ``init`` + apex/amp/wrap.py:10-29
``make_cast_wrapper``). Differences, by design:

  * The wrappers run at *trace* time, so each cast is staged once per jitted
    step and then CSE'd/fused by XLA — the reference needed a per-call weight
    cast cache (apex/amp/utils.py:101-133) to avoid re-casting weights every
    op; under jit that caching is free, preserving the "one cast per weight
    per step" contract.
  * There is no Tensor-method table to patch; everything funnels through the
    jnp/lax function namespaces.

Casting rules (wrap.py:54-55,107-108 incl. the fork's bf16 threading):
low-prec wrapper casts fp32 floating args down; fp32 wrapper casts
fp16/bf16 args up. Non-floating args pass through untouched.
"""

from __future__ import annotations

import contextlib
import functools
import importlib
import threading
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.amp import lists as _lists

# The registry's low-precision dtype SET — everything the fp32
# (blacklist) wrapper promotes back up. Set-driven rather than a
# hardcoded {fp16, bf16} pair: a low dtype missing here silently falls
# through promote-on-mismatch and runs blacklisted ops (softmax, norms,
# losses) at reduced precision — exactly what happened to the fp8
# formats before the lowp tier registered them.
LOW_PRECISION_DTYPES = {
    jnp.dtype(jnp.float16), jnp.dtype(jnp.bfloat16),
    jnp.dtype(jnp.float8_e4m3fn), jnp.dtype(jnp.float8_e5m2),
}


def register_low_precision_dtype(dtype) -> None:
    """Add a dtype to the promote-on-mismatch set (for out-of-tree
    narrow formats; the in-tree fp16/bf16/fp8 set is pre-registered)."""
    LOW_PRECISION_DTYPES.add(jnp.dtype(dtype))


_state = threading.local()


def _active_dtype():
    return getattr(_state, "cast_dtype", None)


def _fp8_ctx():
    """The active ``lowp.fp8_autocast`` context, if any (lazy import:
    amp must stay importable without pulling the lowp tier in)."""
    from apex_tpu.lowp import interpose as _lowp_interpose
    return _lowp_interpose.current()




def _cast_tree(args, kwargs, convert):
    def conv(x):
        # NOT dtype objects: np scalar TYPES expose a .dtype class attr,
        # so a dtype argument (e.g. preferred_element_type=jnp.float32)
        # would otherwise be "cast" — x.astype on a class raises (r4 fix,
        # surfaced by the convergence gate's O1 ResNet run)
        if isinstance(x, (type, jnp.dtype)):
            return x
        if isinstance(x, (jax.Array, jnp.ndarray)) or hasattr(x, "dtype"):
            try:
                dt = jnp.dtype(x.dtype)
            except TypeError:
                return x
            return convert(x, dt)
        return x
    args = jax.tree_util.tree_map(conv, args)
    kwargs = jax.tree_util.tree_map(conv, kwargs)
    return args, kwargs


def _to_low(x, dt, target):
    if dt == jnp.float32:
        return x.astype(target)
    return x


def _to_fp32(x, dt):
    if dt in LOW_PRECISION_DTYPES:
        return x.astype(jnp.float32)
    return x


def make_low_prec_wrapper(orig, name: str):
    """Whitelist wrapper (reference ``make_cast_wrapper`` + ``maybe_half`` /
    ``maybe_bfloat16``, wrap.py:10-29). Checks the fp8 context first:
    under ``lowp.fp8_autocast`` the operands run through the e4m3/e5m2
    QDQ pairs instead of a plain dtype cast. With neither the fp8
    context nor an autocast dtype active the original function is called
    untouched — the O0-O5 jaxpr-identity guarantee."""
    @functools.wraps(orig)
    def wrapper(*args, **kwargs):
        ctx = _fp8_ctx()
        if ctx is not None:
            from apex_tpu.lowp import interpose as _lowp_interpose
            args, kwargs = _cast_tree(
                args, kwargs, lambda x, dt: ctx.cast(x, dt, name))
            with _lowp_interpose.suspend():
                return orig(*args, **kwargs)
        target = _active_dtype()
        if target is None:
            return orig(*args, **kwargs)
        args, kwargs = _cast_tree(
            args, kwargs, lambda x, dt: _to_low(x, dt, target))
        return orig(*args, **kwargs)
    wrapper.__apex_tpu_orig__ = orig
    return wrapper


def make_fp32_wrapper(orig, name: str):
    """Blacklist wrapper (``maybe_float``)."""
    @functools.wraps(orig)
    def wrapper(*args, **kwargs):
        if _active_dtype() is None:
            return orig(*args, **kwargs)
        args, kwargs = _cast_tree(args, kwargs, _to_fp32)
        return orig(*args, **kwargs)
    wrapper.__apex_tpu_orig__ = orig
    return wrapper


# (module, attr) -> original function, for restore.
_patched: Dict[Tuple[str, str], Any] = {}

# User-registered extras (amp.py:29-71 half_function/float_function parity).
_user_low: List[Tuple[str, str]] = []
_user_fp32: List[Tuple[str, str]] = []


def _patch(module_path: str, attr: str, factory) -> None:
    try:
        mod = importlib.import_module(module_path)
        orig = getattr(mod, attr)
    except (ImportError, AttributeError):
        return  # tolerate version drift in the jax namespace
    if getattr(orig, "__apex_tpu_orig__", None) is not None:
        return  # already patched
    setattr(mod, attr, factory(orig, f"{module_path}.{attr}"))
    _patched[(module_path, attr)] = orig


def install() -> None:
    """Patch the namespaces (reference amp.init, amp.py:75-198). Idempotent.

    Patching installs inert wrappers; casting only happens while an
    opt-level context has set the active dtype (``enable``/``autocast``).
    """
    for module_path, attr in _lists.LOW_PREC_FUNCS + _user_low:
        _patch(module_path, attr, make_low_prec_wrapper)
    for module_path, attr in _lists.FP32_FUNCS + _user_fp32:
        _patch(module_path, attr, make_fp32_wrapper)


def uninstall() -> None:
    """Restore every patched function."""
    for (module_path, attr), orig in list(_patched.items()):
        mod = importlib.import_module(module_path)
        setattr(mod, attr, orig)
        del _patched[(module_path, attr)]


def enable(dtype) -> None:
    """Turn casting on globally (per thread) with the given low dtype."""
    install()
    _state.cast_dtype = dtype


def disable() -> None:
    _state.cast_dtype = None


@contextlib.contextmanager
def autocast(dtype=jnp.bfloat16):
    """Scoped O1/O4-style casting: ``with amp.autocast(jnp.bfloat16): ...``.

    Trace-time scope: wrap the region of your step function (or the whole
    jitted call) whose ops should autocast.
    """
    prev = _active_dtype()
    enable(dtype)
    try:
        yield
    finally:
        _state.cast_dtype = prev


@contextlib.contextmanager
def disable_casts():
    """Parity with ``amp.disable_casts`` (apex/amp/handle.py:48-56).

    Also the kernel-tracing guard: the Pallas ops wrap their
    pallas_call-invoking entry points in this (ops/_amp_guard.no_amp) —
    the patched jax.lax.dot_general is GLOBAL, so without it an amp-O1
    model would have its flash kernels' INTERNAL f32 operands cast to
    f16 inside the Mosaic kernel body (Mosaic has no f16 → compile
    error; under O4 the same path silently degrades in-kernel precision
    to bf16). Kernels own their precision schedule; amp governs the
    graph around them (r4 fix, surfaced by the convergence gate's O1
    GPT config).

    Also suspends any active ``lowp.fp8_autocast`` context for the same
    reason: a Pallas kernel's internal dots must not get QDQ pairs
    spliced into the Mosaic body (fp8 sim inside a kernel that owns its
    own precision schedule), and the context's tensor-slot ordering must
    not be perturbed by kernel-internal ops."""
    from apex_tpu.lowp import interpose as _lowp_interpose
    prev = _active_dtype()
    prev_fp8 = _lowp_interpose.current()
    _state.cast_dtype = None
    _lowp_interpose._state.ctx = None
    try:
        yield
    finally:
        _state.cast_dtype = prev
        _lowp_interpose._state.ctx = prev_fp8


# -- registration API (amp.py:29-71) ---------------------------------------

def register_low_prec_function(module, name: str) -> None:
    """``amp.register_half_function`` / ``register_bfloat16_function`` analog."""
    _user_low.append((module if isinstance(module, str) else module.__name__,
                      name))
    if _patched:
        install()


def register_float_function(module, name: str) -> None:
    _user_fp32.append((module if isinstance(module, str) else module.__name__,
                       name))
    if _patched:
        install()


def low_prec_function(fn):
    """Decorator marking a user function to run in the active low dtype
    (``amp.half_function`` / ``bfloat16_function`` analog, amp.py:29-44)."""
    return make_low_prec_wrapper(fn, getattr(fn, "__name__", "user_fn"))


def float_function(fn):
    return make_fp32_wrapper(fn, getattr(fn, "__name__", "user_fn"))
