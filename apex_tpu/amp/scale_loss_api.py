"""Module-level ``amp.scale_loss`` — parity with the reference's central
training-loop API (apex/amp/handle.py:16-158)::

    with amp.scale_loss(loss, optimizer, state) as scaled_loss:
        grads = jax.grad(...)   # differentiate scaled_loss

In the reference, ``scale_loss`` is a context manager whose ``__enter__``
yields ``loss * loss_scale`` and whose ``__exit__`` unscales gradients,
updates the dynamic scale, and patches ``optimizer.step`` to skip on overflow
(handle.py:115-158). In JAX the backward pass is an explicit ``jax.grad``
call and the unscale/skip logic lives inside the jittable
:meth:`AmpOptimizer.step <apex_tpu.amp.optimizer.AmpOptimizer.step>`
(a ``lax.cond``-guarded update — no host sync). So here ``__enter__`` yields
the scaled loss and ``__exit__`` is a no-op; the exit-time work happens when
the caller invokes ``optimizer.step`` on the scaled grads.

Usable both as a context manager (reference idiom) and as a plain function
returning the scaled loss (idiomatic JAX — it is safe to call inside jit).
"""

from __future__ import annotations

from typing import Optional

import jax

from apex_tpu.amp.optimizer import AmpOptimizer, AmpOptimizerState


class _ScaleLoss:
    """Dual-use return value: context manager AND array-like."""

    def __init__(self, scaled: jax.Array):
        self.value = scaled

    # -- context-manager protocol (reference idiom) ------------------------
    def __enter__(self) -> jax.Array:
        return self.value

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False

    # -- array-like delegation so the bare return also works ---------------
    def __jax_array__(self) -> jax.Array:
        return self.value

    def __mul__(self, other):
        return self.value * other

    __rmul__ = __mul__

    def __add__(self, other):
        return self.value + other

    __radd__ = __add__

    def __sub__(self, other):
        return self.value - other

    def __rsub__(self, other):
        return other - self.value

    def __truediv__(self, other):
        return self.value / other

    def __rtruediv__(self, other):
        return other / self.value

    def __neg__(self):
        return -self.value

    def __float__(self) -> float:
        return float(self.value)  # concrete arrays only (not under trace)

    def __repr__(self) -> str:
        return f"_ScaleLoss({self.value!r})"


def scale_loss(loss: jax.Array, optimizer: AmpOptimizer,
               state: Optional[AmpOptimizerState] = None,
               *, loss_id: int = 0, model=None, delay_unscale: bool = False,
               ) -> _ScaleLoss:
    """Scale ``loss`` by the current loss scale of ``optimizer``.

    ``state`` is the :class:`AmpOptimizerState` carried through the training
    step (functional analog of the mutable ``_amp_state``). ``model`` and
    ``delay_unscale`` are accepted for reference-signature parity
    (handle.py:16-21); unscaling is always deferred to ``optimizer.step``.
    """
    if not isinstance(state, AmpOptimizerState):
        # Catches both the missing-state case and reference-style positional
        # calls where the third argument was loss_id (apex handle.py:16).
        raise TypeError(
            "amp.scale_loss requires the AmpOptimizerState as its third "
            "argument: amp.scale_loss(loss, optimizer, state[, loss_id=n]). "
            "JAX state is explicit — there is no global _amp_state to "
            "consult.")
    return _ScaleLoss(optimizer.scale_loss(loss, state, loss_id))
