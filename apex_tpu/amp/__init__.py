"""apex_tpu.amp (placeholder — populated incrementally)."""
