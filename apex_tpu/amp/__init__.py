"""apex_tpu.amp — automatic mixed precision for TPU (reference L2 layer,
apex/amp/). Public surface mirrors apex.amp: ``initialize``, ``scale_loss``
(via AmpOptimizer), opt levels O0-O5, autocast interposition, checkpointing.
"""

from apex_tpu.amp.policy import Properties, opt_levels, resolve
from apex_tpu.amp.scaler import LossScaler, ScalerState
from apex_tpu.amp.optimizer import AmpOptimizer, AmpOptimizerState
from apex_tpu.amp.frontend import (
    initialize,
    cast_model,
    cast_inputs,
    wrap_apply,
    state_dict,
    load_state_dict,
    master_params,
    is_batchnorm_path,
    bn_predicate_from_model,
    bn_predicate_from_batch_stats,
)
from apex_tpu.amp.handle import init, AmpHandle, NoOpHandle
from apex_tpu.amp.interposition import (
    autocast,
    disable_casts,
    register_low_prec_function,
    register_float_function,
    low_prec_function,
    float_function,
)
from apex_tpu.amp.scale_loss_api import scale_loss

# Apex-compatible aliases (apex/amp/amp.py:29-71).
half_function = low_prec_function
bfloat16_function = low_prec_function
register_half_function = register_low_prec_function
register_bfloat16_function = register_low_prec_function


def promote_function(fn):
    """Parity with ``amp.promote_function`` (apex/amp/amp.py:63-66). The
    reference casts mixed fp16/fp32 args to the widest type because torch
    errors on mixed-dtype ops (wrap.py:66-92); jnp's binary-op promotion
    already implements widest-wins, so this is the identity."""
    return fn


def register_promote_function(module, name: str) -> None:
    """Parity with ``amp.register_promote_function`` (amp.py:67-71): a no-op
    — see :func:`promote_function`."""
    return None
