"""apex_tpu.amp — automatic mixed precision for TPU (reference L2 layer,
apex/amp/). Public surface mirrors apex.amp: ``initialize``, ``scale_loss``
(via AmpOptimizer), opt levels O0-O5, autocast interposition, checkpointing.
"""

from apex_tpu.amp.policy import Properties, opt_levels, resolve
from apex_tpu.amp.scaler import LossScaler, ScalerState
from apex_tpu.amp.optimizer import AmpOptimizer, AmpOptimizerState
from apex_tpu.amp.frontend import (
    initialize,
    cast_model,
    cast_inputs,
    wrap_apply,
    state_dict,
    load_state_dict,
    master_params,
    is_batchnorm_path,
)
from apex_tpu.amp.handle import init, AmpHandle, NoOpHandle
from apex_tpu.amp.interposition import (
    autocast,
    disable_casts,
    register_low_prec_function,
    register_float_function,
    low_prec_function,
    float_function,
)

# Apex-compatible aliases (apex/amp/amp.py:29-71).
half_function = low_prec_function
bfloat16_function = low_prec_function
register_half_function = register_low_prec_function
register_bfloat16_function = register_low_prec_function
