"""Casting policy tables for O1/O4 function interposition — the JAX analog of
the reference's whitelist/blacklist (apex/amp/lists/functional_overrides.py:18-91
and lists/torch_overrides.py:7-136).

Each entry is ``(module_path, attr_name)``. The semantics mirror the
reference:

  * LOW_PREC (reference FP16/BF16 whitelist): MXU-friendly ops — inputs cast
    to the policy's low-precision dtype. On TPU these are the ops that hit the
    128x128 systolic array; everything convolution/matmul-shaped belongs here.
  * FP32 (reference blacklist): reductions/transcendentals/losses that want
    fp32 stability — low-precision inputs are cast up.
  * Promote lists are unnecessary in JAX: jnp's binary-op type promotion
    already implements "widest input type wins" (the reference needed
    ``wrap.promote`` only because torch errors on mixed-dtype ops).

Patching ``jax.lax.dot_general`` / ``conv_general_dilated`` covers every
library built on them (flax Dense/Conv, haiku Linear, jnp.matmul, einsum...)
— the single-funnel analog of patching ``torch.nn.functional``.
"""

# MXU-friendly -> low precision (fp16 for O1, bf16 for O4).
LOW_PREC_FUNCS = [
    ("jax.lax", "dot_general"),
    ("jax.lax", "dot"),
    ("jax.lax", "conv_general_dilated"),
    ("jax.lax", "conv_with_general_padding"),
    ("jax.lax", "conv"),
    ("jax.numpy", "matmul"),
    ("jax.numpy", "dot"),
    ("jax.numpy", "vdot"),
    ("jax.numpy", "inner"),
    ("jax.numpy", "tensordot"),
    ("jax.numpy", "einsum"),
]

# Stability-hungry -> fp32 (reference blacklist: softmax/norms/losses/
# pointwise transcendentals, torch_overrides.py:21-45).
FP32_FUNCS = [
    ("jax.nn", "softmax"),
    ("jax.nn", "log_softmax"),
    ("jax.nn", "logsumexp"),
    ("jax.scipy.special", "logsumexp"),
    ("jax.numpy", "exp"),
    ("jax.numpy", "expm1"),
    ("jax.numpy", "log"),
    ("jax.numpy", "log10"),
    ("jax.numpy", "log1p"),
    ("jax.numpy", "log2"),
    ("jax.numpy", "power"),
    ("jax.numpy", "float_power"),
    ("jax.numpy", "cosh"),
    ("jax.numpy", "sinh"),
    ("jax.numpy", "tan"),
    ("jax.numpy", "reciprocal"),
    ("jax.lax", "erf_inv"),
    ("jax.lax", "rsqrt"),
    # Wide reductions accumulate error in low precision
    # (torch_overrides blacklists sum/prod/cumsum/cumprod).
    ("jax.numpy", "sum"),
    ("jax.numpy", "prod"),
    ("jax.numpy", "cumsum"),
    ("jax.numpy", "cumprod"),
    ("jax.numpy", "mean"),
    ("jax.numpy", "var"),
    ("jax.numpy", "std"),
]
