"""Opt-level policy tables — behavioral parity with the reference amp frontend
``Properties`` / ``O0``-``O5`` classes (apex/amp/frontend.py:7-254), re-cast as
an immutable dataclass (JAX configs are trace-time constants, not mutable
global state).

Opt levels:
  O0: pure fp32.
  O1: function interposition — whitelisted ops run in fp16 (dynamic scaling).
  O2: fp16 model (batchnorm kept fp32) + fp32 master weights (dynamic scaling).
  O3: pure fp16.
  O4: function interposition with bf16, no loss scaling (bf16 has fp32 range).
  O5: bf16 model (batchnorm fp32) + fp32 master weights, no loss scaling.
  O6: fp8 compute over bf16 weights — whitelisted ops run on e4m3-QDQ
      operands inside ``lowp.fp8_autocast`` (e5m2 cotangents backward),
      per-tensor delayed scaling threaded through the step; no loss
      scaling (e5m2 carries fp16-class exponent range and the per-tensor
      scales do the range management).
  O7: O6 + fp32 master weights (the O2:O1 :: O7:O6 relation).

O4/O5 are the reference fork's signature bf16 additions
(apex/amp/frontend.py:207-246). On TPU the bf16 levels are the natural ones;
fp16 levels are kept for API/behavior parity (XLA supports f16 storage).
O6/O7 take the next step down (ROADMAP item 5, ``apex_tpu.lowp``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Union

import jax.numpy as jnp

LossScaleSpec = Union[str, float, int]  # "dynamic" or a static scale


@dataclasses.dataclass(frozen=True)
class Properties:
    """Resolved amp options (reference ``Properties``, frontend.py:7-113).

    ``None`` means "defer to the opt-level default" during override
    resolution, mirroring the reference's ``_amp_state`` deferral.
    """

    enabled: bool = True
    opt_level: str = "O1"
    cast_model_type: Optional[Any] = None       # jnp dtype or None
    patch_functions: bool = False               # = patch_torch_functions
    patch_functions_type: Optional[Any] = None  # fp16 (O1) or bf16 (O4)
    keep_batchnorm_fp32: Optional[bool] = None
    master_weights: bool = False
    loss_scale: LossScaleSpec = 1.0
    # O6/O7: whitelisted ops run through the lowp fp8 QDQ pairs when a
    # lowp.fp8_autocast context is active (initialize installs the
    # interposition wrappers so the context has a seam to hook)
    fp8: bool = False
    # True when the USER passed keep_batchnorm_fp32 (vs the opt-level
    # default): gates the zero-BN-matches warning in cast_model so BN-free
    # models under plain O2/O5 don't warn on every run.
    keep_batchnorm_fp32_explicit: bool = False

    @property
    def compute_dtype(self):
        """The low-precision dtype this level computes in (None for O0)."""
        if self.patch_functions:
            return self.patch_functions_type
        if self.cast_model_type is not None and self.cast_model_type != jnp.float32:
            return self.cast_model_type
        return None

    @property
    def dynamic_loss_scale(self) -> bool:
        return self.loss_scale == "dynamic"


def _mk(opt_level, cast_model_type, patch, patch_type, keep_bn, master, scale,
        fp8=False):
    return Properties(
        enabled=True, opt_level=opt_level, cast_model_type=cast_model_type,
        patch_functions=patch, patch_functions_type=patch_type,
        keep_batchnorm_fp32=keep_bn, master_weights=master, loss_scale=scale,
        fp8=fp8)


# Defaults exactly as the reference tables (frontend.py:118-254); O6/O7
# extend the fork's ladder into fp8 (apex_tpu.lowp, ROADMAP item 5).
opt_levels = {
    "O0": _mk("O0", jnp.float32, False, None, None, False, 1.0),
    "O1": _mk("O1", None, True, jnp.float16, None, False, "dynamic"),
    "O2": _mk("O2", jnp.float16, False, None, True, True, "dynamic"),
    "O3": _mk("O3", jnp.float16, False, None, False, False, 1.0),
    "O4": _mk("O4", None, True, jnp.bfloat16, None, False, 1.0),
    "O5": _mk("O5", jnp.bfloat16, False, None, True, True, 1.0),
    "O6": _mk("O6", jnp.bfloat16, False, None, True, False, 1.0, fp8=True),
    "O7": _mk("O7", jnp.bfloat16, False, None, True, True, 1.0, fp8=True),
}


def resolve(opt_level: str = "O1", *,
            cast_model_type=None, patch_functions=None,
            keep_batchnorm_fp32=None, master_weights=None,
            loss_scale=None, enabled: bool = True) -> Properties:
    """Apply per-kwarg user overrides on top of an opt level, with the
    reference's consistency checks (frontend.py:249-254,404-419)."""
    if opt_level not in opt_levels:
        raise ValueError(
            f"Unexpected optimization level {opt_level!r}; options are "
            "'O0', 'O1', 'O2', 'O3', 'O4', 'O5', 'O6', 'O7' (the letter O "
            "+ a digit, not zero).")
    base = opt_levels[opt_level]
    props = dataclasses.replace(
        base,
        enabled=enabled,
        cast_model_type=(base.cast_model_type if cast_model_type is None
                         else cast_model_type),
        patch_functions=(base.patch_functions if patch_functions is None
                         else patch_functions),
        keep_batchnorm_fp32=(base.keep_batchnorm_fp32
                             if keep_batchnorm_fp32 is None
                             else keep_batchnorm_fp32),
        keep_batchnorm_fp32_explicit=keep_batchnorm_fp32 is not None,
        master_weights=(base.master_weights if master_weights is None
                        else master_weights),
        loss_scale=base.loss_scale if loss_scale is None else loss_scale,
    )
    # Consistency checks mirroring Properties.__setattr__ (frontend.py:60-100).
    if props.keep_batchnorm_fp32 and props.cast_model_type is None:
        raise ValueError(
            "keep_batchnorm_fp32 only makes sense with a cast_model_type "
            "(O2/O3/O5-style levels).")
    if props.master_weights and props.cast_model_type is None:
        raise ValueError("master_weights requires cast_model_type "
                         "(O2/O5-style levels).")
    return props
