"""amp.initialize and friends — the user-facing entry point, with the API
shape of the reference frontend (apex/amp/frontend.py:258-425) recast for a
functional JAX world.

Reference:                         apex_tpu:
  model, opt = amp.initialize(      apply_fn, amp_opt = amp.initialize(
      model, opt, opt_level="O2")       apply_fn, opt, opt_level="O2")
  ...                               params = amp.cast_model(params, "O2")
  with amp.scale_loss(l, opt) as sl:scaled = amp_opt.scale_loss(l, opt_state)
      sl.backward()                 grads = jax.grad(...)(params)
  opt.step()                        params, opt_state, info = amp_opt.step(
                                        grads, params, opt_state)

``initialize`` wires: model-apply input casting (O2/O3/O5,
_initialize.py:194-201), namespace interposition (O1/O4, amp.py:75-198),
optimizer wrapping with master weights + loss scaling
(_process_optimizer.py:321-489), and per-loss scalers (num_losses,
_initialize.py:227-231).
"""

from __future__ import annotations

import re
import warnings
from typing import Any, Callable, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from apex_tpu.amp import interposition
from apex_tpu.amp import policy as _policy
from apex_tpu.amp.optimizer import AmpOptimizer, AmpOptimizerState
from apex_tpu.amp.scaler import LossScaler, ScalerState

Tree = Any

# Default param-path pattern identifying batch-norm-like params kept fp32
# under keep_batchnorm_fp32 (the reference checks module types in
# fp16util.convert_network, fp16util.py:60; with pytrees we match path names).
_BN_PATH_RE = re.compile(r"(batch[_]?norm|(^|[/_.])bn(\d|$|[/_.])|batchstats)",
                         re.IGNORECASE)


from apex_tpu.utils import path_str as _path_str


def is_batchnorm_path(path) -> bool:
    return bool(_BN_PATH_RE.search(_path_str(path)))


def _is_bn_module(m) -> bool:
    import flax.linen as nn
    from apex_tpu.parallel.sync_batchnorm import SyncBatchNorm
    # isinstance covers flax BN / SyncBatchNorm and subclasses; the name
    # check catches third-party BN types but must match the WHOLE class
    # name (BatchNorm, SyncBatchNorm2d, ...) — a substring test would pin
    # composite blocks like ConvBatchNormAct, whose subtree holds non-BN
    # params, entirely fp32
    return (isinstance(m, (nn.BatchNorm, SyncBatchNorm))
            or re.fullmatch(r"(?i)(sync)?batch_?norm\w{0,4}",
                            type(m).__name__) is not None)


def bn_predicate_from_model(module, *init_args, **init_kwargs) -> Callable:
    """TYPE-keyed batchnorm detection (VERDICT r2 weak #7) — the
    reference converts by module type (fp16util.convert_network,
    _initialize.py:176-182), which the path regex can only approximate.

    Traces ``module.init(*init_args, **init_kwargs)`` under
    ``jax.eval_shape`` (no compute) with a flax method interceptor that
    records the module path of every BatchNorm-typed submodule —
    ``flax.linen.BatchNorm``, :class:`~apex_tpu.parallel.SyncBatchNorm`,
    subclasses, or any module whose class name IS a batchnorm name
    (fullmatch of ``(Sync)?Batch[_]?Norm`` plus up to 4 trailing chars,
    e.g. ``BatchNorm2d`` — deliberately NOT substring containment, which
    would pin composite blocks like ``ConvBatchNormAct``, whose subtree
    holds non-BN params, entirely fp32; subclass any flax BN type, or use
    :func:`bn_predicate_from_batch_stats`, for exotic names). The
    returned predicate matches param paths under those modules (falling
    back to the name regex for safety) and plugs into
    :func:`cast_model`'s ``bn_predicate``::

        pred = amp.bn_predicate_from_model(model, jax.random.PRNGKey(0), x)
        params = amp.cast_model(params32, "O2", bn_predicate=pred)

    A model whose BN params carry unconventional names now keeps fp32 BN
    under O2/O5 instead of a warning-and-miss.
    """
    import flax.linen as nn

    prefixes: set = set()

    root_is_bn = _is_bn_module(module)

    def interceptor(next_fn, args, kwargs, context):
        m = context.module
        if _is_bn_module(m) and m.path:
            prefixes.add("/".join(str(p) for p in m.path))
        return next_fn(*args, **kwargs)

    with nn.intercept_methods(interceptor):
        jax.eval_shape(module.init, *init_args, **init_kwargs)

    return _prefix_predicate(prefixes, root_is_bn=root_is_bn)


def _prefix_predicate(prefixes, *, root_is_bn: bool = False) -> Callable:
    """Shared predicate over param paths for the typed BN detectors:
    true under any recorded module-path prefix (segment containment, not
    pure prefix — the casted tree may be rooted above 'params', shifting
    every path one level deeper), with the name regex as fallback;
    ``root_is_bn`` means the whole model IS a batchnorm (every param is
    BN state)."""
    prefixes = frozenset(prefixes)

    def predicate(path) -> bool:
        if root_is_bn:
            return True
        p = "/" + _path_str(path) + "/"
        return any("/" + pre + "/" in p for pre in prefixes) \
            or is_batchnorm_path(path)

    predicate.bn_module_paths = prefixes  # introspection/tests
    return predicate


def bn_predicate_from_batch_stats(batch_stats: Tree) -> Callable:
    """TYPE-equivalent batchnorm detection from the ``batch_stats``
    collection — no trace, no model object needed (VERDICT r3 next #8).
    Every module path holding running statistics IS a batchnorm-like
    module (flax ``BatchNorm``/:class:`~apex_tpu.parallel.SyncBatchNorm`
    and anything else sowing the ``batch_stats`` collection), regardless
    of what the module is named — the same information the reference
    reads from module types (fp16util.convert_network, fp16util.py:60).
    Returns a predicate over PARAM paths: true for params living under
    any stats-holding module path, with the name regex kept as a
    fallback."""
    prefixes: set = set()
    root_stats = False

    def record(path, _leaf):
        nonlocal root_stats
        parts = _path_str(path).split("/")
        if len(parts) > 1:  # drop the stat leaf (mean/var)
            prefixes.add("/".join(parts[:-1]))
        else:
            # single-segment stat path: the ROOT module is the batchnorm
            # (nn.BatchNorm(...).init gives batch_stats = {mean, var})
            root_stats = True

    jax.tree_util.tree_map_with_path(record, batch_stats)
    return _prefix_predicate(prefixes,
                             root_is_bn=root_stats and not prefixes)


def cast_model(params: Tree,
               opt_level_or_props: Union[str, _policy.Properties],
               *, bn_predicate: Optional[Callable] = None) -> Tree:
    """Cast model params per the opt level (the ``.half()`` / ``.bfloat16()``
    conversion of O2/O3/O5, _initialize.py:176-182), keeping batchnorm-like
    params fp32 when the policy says so.

    BN detection defaults to TYPE-equivalent auto-detection whenever the
    model is in hand: pass the FULL ``variables`` dict
    (``{"params": ..., "batch_stats": ...}``) and every param under a
    module that holds running stats stays fp32 — no naming convention
    required (``batch_stats`` itself is returned unconverted; stats are
    always fp32). Passing a bare params tree falls back to the
    ``is_batchnorm_path`` name regex; ``bn_predicate=`` overrides
    either."""
    # variables-dict form: auto-derive the typed predicate and recurse on
    # the params subtree. Mapping, not dict: flax FrozenDict variables
    # (flax.core.freeze / older flax) must take this path too — treating
    # them as a bare params tree would cast batch_stats to low precision
    # and miss the typed BN detection entirely. ANY top-level "params"
    # key selects this path (so {'params', 'cache'} returns cache
    # unconverted rather than casting it); the pathological bare params
    # tree containing a top-level MODULE literally named "params" must
    # cast its subtrees separately.
    import collections.abc
    if (isinstance(params, collections.abc.Mapping)
            and not isinstance(params, jnp.ndarray)
            and "params" in params):
        pred = bn_predicate
        if pred is None and "batch_stats" in params:
            pred = bn_predicate_from_batch_stats(params["batch_stats"])
        out = {k: v for k, v in params.items()}
        out["params"] = cast_model(params["params"], opt_level_or_props,
                                   bn_predicate=pred)
        if not isinstance(params, dict):  # restore FrozenDict-ness
            try:
                import flax
                out = flax.core.freeze(out)
            except Exception:
                pass
        return out
    if bn_predicate is None:
        bn_predicate = is_batchnorm_path

    props = (opt_level_or_props if isinstance(opt_level_or_props,
                                              _policy.Properties)
             else _policy.resolve(opt_level_or_props))
    target = props.cast_model_type
    if target is None:
        return params
    keep_bn = bool(props.keep_batchnorm_fp32)
    n_bn = 0

    def cast(path, p):
        nonlocal n_bn
        if not jnp.issubdtype(p.dtype, jnp.floating):
            return p
        if keep_bn and bn_predicate(path):
            n_bn += 1
            return p.astype(jnp.float32)
        return p.astype(target)

    out = jax.tree_util.tree_map_with_path(cast, params)
    if (keep_bn and n_bn == 0
            and getattr(props, "keep_batchnorm_fp32_explicit", False)):
        # Name-based matching can silently miss models whose BN params don't
        # look like BN (the reference keys on module types instead,
        # fp16util.convert_network) — surface that rather than quietly
        # running BN in low precision. Only when the user asked for
        # keep_batchnorm_fp32 explicitly: BN-free models under the plain
        # O2/O5 defaults should not warn.
        warnings.warn(
            "keep_batchnorm_fp32 is set but no batchnorm-like param paths "
            "matched; if this model has batch norm under different names, "
            "pass bn_predicate= to amp.cast_model.", stacklevel=2)
    return out


def cast_inputs(tree: Tree, dtype) -> Tree:
    """Cast floating leaves of inputs to ``dtype`` (the patched
    ``model.forward`` input caster, _initialize.py:194-201)."""
    def cast(x):
        if hasattr(x, "dtype") and jnp.issubdtype(
                jnp.dtype(x.dtype), jnp.floating):
            return x.astype(dtype)
        return x
    return jax.tree_util.tree_map(cast, tree)


def wrap_apply(apply_fn: Callable, props: _policy.Properties) -> Callable:
    """Wrap a model apply function with policy behavior:

    * O2/O3/O5: cast floating inputs to the model compute dtype.
    * O1/O4: run the body under :func:`interposition.autocast`.
    * O6/O7: inputs cast to bf16 like O5 (``cast_model_type``); the fp8
      QDQ itself activates only inside the caller's
      ``lowp.fp8_autocast`` scope, which threads the delayed-scaling
      state the wrapper cannot own (state flows through the train step).
    """
    if not props.enabled:
        return apply_fn

    if props.patch_functions:
        dtype = props.patch_functions_type

        def patched(*args, **kwargs):
            with interposition.autocast(dtype):
                return apply_fn(*args, **kwargs)
        return patched

    if props.cast_model_type is not None and \
            props.cast_model_type != jnp.float32:
        dtype = props.cast_model_type

        def casting(params, *args, **kwargs):
            args, kwargs = cast_inputs((args, kwargs), dtype)
            return apply_fn(params, *args, **kwargs)
        return casting

    return apply_fn


def initialize(
    models: Union[Callable, Sequence[Callable], None],
    optimizers=None,
    opt_level: str = "O1",
    *,
    cast_model_type=None,
    patch_functions: Optional[bool] = None,
    keep_batchnorm_fp32: Optional[bool] = None,
    master_weights: Optional[bool] = None,
    loss_scale=None,
    num_losses: int = 1,
    min_loss_scale: Optional[float] = None,
    max_loss_scale: float = 2.0 ** 24,
    enabled: bool = True,
    verbosity: int = 1,
):
    """Resolve an opt level (+ overrides) and wrap model apply fns and
    optimizers (frontend.py:258-425).

    ``models``: a model apply callable (or list of them) — e.g.
    ``functools.partial(module.apply)`` — or None.
    ``optimizers``: a :class:`~apex_tpu.optimizers.base.FusedOptimizer`
    (or list). Returns the same shapes the reference returns: single objects
    when single inputs were given, lists otherwise.
    """
    props = _policy.resolve(
        opt_level, cast_model_type=cast_model_type,
        patch_functions=patch_functions,
        keep_batchnorm_fp32=keep_batchnorm_fp32,
        master_weights=master_weights, loss_scale=loss_scale,
        enabled=enabled)

    if verbosity > 0 and jax.process_index() == 0:
        fp8_note = ", fp8=True (e4m3 fwd / e5m2 bwd QDQ via " \
            "lowp.fp8_autocast)" if props.fp8 else ""
        print(f"apex_tpu.amp: opt_level={props.opt_level}, "
              f"cast_model_type={props.cast_model_type}, "
              f"patch_functions={props.patch_functions}, "
              f"keep_batchnorm_fp32={props.keep_batchnorm_fp32}, "
              f"master_weights={props.master_weights}, "
              f"loss_scale={props.loss_scale}{fp8_note}")

    # O1/O4 cast through the wrappers directly; O6/O7 need the same
    # wrappers installed as the seam lowp.fp8_autocast hooks (inert
    # until a context is active — the O0-O5 jaxpr-identity pin)
    if props.enabled and (props.patch_functions or props.fp8):
        interposition.install()

    models_was_seq = isinstance(models, (list, tuple))
    opts_was_seq = isinstance(optimizers, (list, tuple))
    model_list = (list(models) if models_was_seq
                  else ([] if models is None else [models]))
    opt_list = (list(optimizers) if opts_was_seq
                else ([] if optimizers is None else [optimizers]))

    wrapped_models = [wrap_apply(m, props) for m in model_list]
    wrapped_opts = [
        AmpOptimizer(o, props, num_losses=num_losses,
                     min_loss_scale=min_loss_scale,
                     max_loss_scale=max_loss_scale)
        for o in opt_list
    ]

    out_models = (wrapped_models if models_was_seq
                  else (wrapped_models[0] if wrapped_models else None))
    out_opts = (wrapped_opts if opts_was_seq
                else (wrapped_opts[0] if wrapped_opts else None))
    if optimizers is None:
        return out_models
    return out_models, out_opts


# -- module-level checkpoint helpers (frontend.py:428-467 parity) ----------

def state_dict(amp_optimizers, amp_states) -> dict:
    """Serialize every loss scaler (reference amp.state_dict serializes
    ``loss_scale``/``unskipped`` per scaler)."""
    if not isinstance(amp_optimizers, (list, tuple)):
        amp_optimizers = [amp_optimizers]
        amp_states = [amp_states]
    return {f"optimizer{i}": opt.state_dict(st)
            for i, (opt, st) in enumerate(zip(amp_optimizers, amp_states))}


def load_state_dict(amp_optimizers, amp_states, d: dict):
    single = not isinstance(amp_optimizers, (list, tuple))
    if single:
        amp_optimizers = [amp_optimizers]
        amp_states = [amp_states]
    out = [opt.load_state_dict(st, d[f"optimizer{i}"])
           for i, (opt, st) in enumerate(zip(amp_optimizers, amp_states))]
    return out[0] if single else out


def master_params(amp_optimizer: AmpOptimizer, state: AmpOptimizerState):
    """Generator-free analog of ``amp.master_params`` (_amp_state.py:59-68)."""
    return amp_optimizer.master_params(state)
