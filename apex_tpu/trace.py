"""apex_tpu.trace — host-side span tracing.

The reference Apex's pyprof rides NVTX *ranges*: host-side begin/end
markers are what join framework intent to device activity
(apex/pyprof/nvtx). Our device half exists (``apex_tpu.pyprof``); this
module is the host half — a low-overhead span API whose events land in
the SAME ``telemetry.Collector``/JSONL stream as every other runtime
fact, as a new ``span/*`` event family:

  * ``span("name")`` — context manager AND decorator. Thread-aware
    (each event records its thread), nestable (depth is tracked
    per-thread), re-entrant (state lives in thread-local storage, so one
    decorator instance is safe under concurrency and recursion).
  * ``emit_span(name, begin, end)`` — record an already-timed interval
    (producers that hold their own ``perf_counter`` brackets, e.g.
    ``instrument_step``'s dispatch/wait split).

Every span emits a begin/end *pair*: the begin event (value 0) is crash
forensics — a JSONL whose last span has no end names the host activity
the process died inside — and the end event carries the duration as its
``value`` plus the monotonic end timestamp in ``meta`` (aggregation and
the timeline export consume end events only). Span events use
``kind="span"`` so summarize's point/counter aggregations ignore them by
construction.

Enabling is process-global, separate from telemetry's flag (the pattern
of ``telemetry.health``): ``trace.enable()``. Spans are pure host code —
they never trace anything into a jitted program, so flipping the flag
cannot change a compiled step (pinned by a jaxpr-equality test); the
disabled cost is one module-global bool check per span.

Span naming convention: ``<family>/<point>`` — ``data/produce``,
``data/wait``, ``step/dispatch``, ``step/device_wait``,
``snapshot/serialize``, ``callback/record``, ``tune/measure``,
``profile/step``. :func:`family_of` returns that two-component id; the
wall-reconciliation and straggler reports aggregate by it.
"""

from __future__ import annotations

import functools
import itertools
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional

from apex_tpu.telemetry import events as _ev

__all__ = ["span", "emit_span", "enable", "disable", "enabled",
           "family_of", "span_rows", "family_totals", "PREFIX",
           "CONCURRENT_FAMILIES", "DEVICE_WAIT_FAMILIES"]

PREFIX = "span/"

# Span families that run CONCURRENTLY with the train loop by design
# (worker threads, async writer threads, XLA callback threads): real
# host work — always visible in the spans table — but never a component
# of the per-step wall, so neither summarize's reconciliation nor
# bench's wall_gap may bill them (one definition, both consumers).
CONCURRENT_FAMILIES = frozenset((
    "data/produce", "data/put", "callback/record", "snapshot/serialize",
    "snapshot/publish"))

# Span families that are the host BLOCKED ON THE DEVICE — device time
# wearing a host span, not host overhead: instrument_step's per-call
# block_until_ready, and the trainer's in-flight window retiring a
# pipelined dispatch. The reconciliation and bench's wall_gap must not
# bill them as host components (step/device_wait doubles as the busy
# proxy instead).
DEVICE_WAIT_FAMILIES = frozenset((
    "step/device_wait", "trainer/retire"))

_enabled = False
_ids = itertools.count(1)        # CPython: count.__next__ is atomic
_tls = threading.local()

# pushed for spans entered while tracing was OFF, so a flag flip between
# __enter__ and __exit__ can never mispair the per-thread stack
_OFF = (False, 0, 0.0)


def enable() -> None:
    """Turn span emission on (host-side only: unlike telemetry's flag,
    this is NOT trace-time — no compiled program changes either way)."""
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def enabled() -> bool:
    return _enabled


def family_of(name: str) -> str:
    """``span/data/wait`` (or ``data/wait``) -> ``data/wait``: the
    two-component producer id the reports aggregate by."""
    if name.startswith(PREFIX):
        name = name[len(PREFIX):]
    parts = name.split("/")
    return "/".join(parts[:2])


def _stack() -> list:
    s = getattr(_tls, "stack", None)
    if s is None:
        s = _tls.stack = []
    return s


def _depth() -> int:
    return getattr(_tls, "depth", 0)


def _emit(name: str, value: float, *, ph: str, sid: int, depth: int,
          mono: float, ts: float, step: Optional[int],
          meta: Optional[dict]) -> None:
    t = threading.current_thread()
    m: Dict[str, Any] = {"ph": ph, "id": sid, "tid": t.ident or 0,
                         "thread": t.name, "depth": depth, "mono": mono}
    if meta:
        m.update(meta)
    _ev.get_collector().add(_ev.Event(
        name=PREFIX + name, value=value, ts=ts, step=step, kind="span",
        meta=m))


class span:
    """``with trace.span("data/produce"): ...`` or ``@trace.span(...)``.

    ``step=`` attaches the step index (the merge CLI's cross-process
    anchor and the reconciliation's per-step join); ``meta=`` rides extra
    JSON-able context on both events."""

    __slots__ = ("name", "step", "meta")

    def __init__(self, name: str, *, step: Optional[int] = None,
                 meta: Optional[dict] = None):
        self.name = name
        self.step = step
        self.meta = meta

    def __enter__(self) -> "span":
        st = _stack()
        if not _enabled:
            st.append(_OFF)
            return self
        sid = next(_ids)
        depth = _depth()
        _tls.depth = depth + 1
        t0 = time.perf_counter()
        st.append((True, sid, t0))
        _emit(self.name, 0.0, ph="B", sid=sid, depth=depth, mono=t0,
              ts=time.time(), step=self.step, meta=self.meta)
        return self

    def __exit__(self, *exc) -> bool:
        st = _stack()
        if not st:          # defensive: unbalanced exit
            return False
        on, sid, t0 = st.pop()
        if not on:
            return False
        _tls.depth = max(_depth() - 1, 0)
        t1 = time.perf_counter()
        _emit(self.name, t1 - t0, ph="E", sid=sid, depth=_depth(),
              mono=t1, ts=time.time(), step=self.step, meta=self.meta)
        return False

    def __call__(self, fn: Callable) -> Callable:
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with self:       # re-entrant: state lives on the tls stack
                return fn(*args, **kwargs)
        return wrapper


def emit_span(name: str, begin: float, end: float, *,
              step: Optional[int] = None,
              meta: Optional[dict] = None) -> None:
    """Record an already-timed ``perf_counter`` interval as a span pair.
    No-op while disabled — producers can bracket unconditionally and pay
    only the two clock reads.

    Wall timestamps are DERIVED from the monotonic brackets (one paired
    wall/mono reading at emission, shifted back by ``now_mono − end``),
    so emission may lag the interval arbitrarily without displacing the
    recorded times — ``instrument_step`` emits the dispatch span only
    after ``block_until_ready``, and that span's begin is the merge
    CLI's cross-process clock anchor: displacing it by the device wait
    would bias every recovered offset by exactly the straggler signal
    being measured."""
    if not _enabled:
        return
    sid = next(_ids)
    dur = max(end - begin, 0.0)
    now_wall = time.time()
    now_mono = time.perf_counter()
    ts_end = now_wall - max(now_mono - end, 0.0)
    depth = _depth()
    _emit(name, 0.0, ph="B", sid=sid, depth=depth, mono=begin,
          ts=ts_end - dur, step=step, meta=meta)
    _emit(name, dur, ph="E", sid=sid, depth=depth, mono=end, ts=ts_end,
          step=step, meta=meta)


# ---------------------------------------------------------------------------
# offline helpers (consumed by export.summarize, bench, pyprof timeline)
# ---------------------------------------------------------------------------

def span_rows(events: Iterable) -> List[Dict[str, Any]]:
    """Completed spans from an event stream (dicts or Events): one row
    per END event — ``{name, family, dur_s, begin_mono, end_mono, ts,
    step, tid, thread, depth, process, rid, slot}``. Begin events
    (crash forensics) are skipped; a span that never ended therefore
    never shows a bogus duration. ``rid``/``slot`` are the serving
    request attribution (None on trainer spans) — the pyprof timeline's
    request lanes key on them."""
    rows: List[Dict[str, Any]] = []
    for e in events:
        d = e.to_dict() if isinstance(e, _ev.Event) else e
        if d.get("kind") != "span":
            continue
        meta = d.get("meta") or {}
        if meta.get("ph") != "E":
            continue
        dur = float(d.get("value", 0.0))
        mono = meta.get("mono")
        rows.append({
            "name": d["name"],
            "family": family_of(d["name"]),
            "dur_s": dur,
            "begin_mono": None if mono is None else float(mono) - dur,
            "end_mono": None if mono is None else float(mono),
            "ts": float(d.get("ts", 0.0)),
            "step": d.get("step"),
            "tid": meta.get("tid", 0),
            "thread": meta.get("thread", ""),
            "depth": meta.get("depth", 0),
            "process": meta.get("process"),
            "rid": meta.get("rid"),
            "slot": meta.get("slot"),
        })
    return rows


def family_totals(events: Iterable, *, exclude: Iterable[str] = (),
                  window: Optional[tuple] = None) -> Dict[str, float]:
    """Total seconds per span family over a stream (bench's ``wall_gap``
    bill). ``window=(mono_t0, mono_t1)`` keeps only spans intersecting
    that ``perf_counter`` interval — the same rule capture's sidecar
    uses, so startup work (an autotuner sweep) is not billed to a
    measured loop that never paid it. Nested spans double into their
    parents by design — each family answers "how much time did THIS
    activity take", not "how does the wall partition". (The
    reconciliation report approximates partitioning: it skips
    :data:`CONCURRENT_FAMILIES` and stack-nested spans, but spans that
    merely overlap in TIME on one thread — an ``emit_span`` interval
    inside another — can still double-bill; its residual goes negative
    rather than hiding that.)"""
    exclude = frozenset(exclude)
    out: Dict[str, float] = {}
    for r in span_rows(events):
        if r["family"] in exclude:
            continue
        if window is not None:
            if r["end_mono"] is None or r["end_mono"] < window[0] \
                    or r["begin_mono"] > window[1]:
                continue
        out[r["family"]] = out.get(r["family"], 0.0) + r["dur_s"]
    return out
