"""The multi-tensor dispatch funnel (reference
apex/multi_tensor_apply/multi_tensor_apply.py:3-30).

The reference's ``multi_tensor_applier(op, noop_flag_buffer, tensor_lists,
*args)`` chunks a list of CUDA tensors into ``TensorListMetadata`` launches
(csrc/multi_tensor_apply.cuh:41-142, chunk size 2048*32 set in
apex/multi_tensor_apply/__init__.py). On TPU the ops are functional
(apex_tpu/ops/multi_tensor.py): a whole pytree goes in, updated pytrees and a
device-side ``overflow`` scalar come out, and XLA/Pallas does the batching the
CUDA chunker did by hand — so the applier is a thin invocation funnel kept for
API parity and as the single seam where dispatch policy (jnp vs Pallas,
ops/multi_tensor.py:48-67) is centralized.

Calling convention::

    multi_tensor_applier(op, noop_flag, tensor_lists, *args, **kwargs)

``op`` is any functional multi-tensor op following the package convention
``op(*trees, *args) -> (*out_trees[, overflow])``; ``tensor_lists`` is the
sequence of input pytrees (positionally matching the reference's
``tensor_lists`` argument, minus the output lists — outputs are returned,
not written in place). ``noop_flag`` may be ``None`` or a boolean device
scalar; when the op reports overflow the applier ORs it into the returned
flag, preserving the reference's noop-flag accumulation contract
(csrc/multi_tensor_scale_kernel.cu:30) without a host sync.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp


class MultiTensorApply:
    """Reference multi_tensor_apply.py:3-30. ``available`` is always True on
    TPU: there is no optional native extension to probe for (the Pallas/jnp
    paths are part of the package)."""

    available: bool = True
    warned: bool = False

    def __init__(self, chunk_size: int = 2048 * 32):
        # Kept for signature parity; XLA picks its own tiling. The Pallas
        # bucket path sizes its (rows, 128) grid blocks through
        # apex_tpu.tune (ops/pallas_mt._block_rows: the frozen BLOCK_ROWS
        # under APEX_TPU_TUNE=off, cached/measured values under
        # cache/auto); per-op ``block_rows=`` kwargs forwarded through
        # this funnel always win over the tuner.
        self.chunk_size = chunk_size

    def __call__(self, op, noop_flag: Optional[jax.Array],
                 tensor_lists: Sequence[Any], *args, **kwargs):
        out = op(*tensor_lists, *args, **kwargs)
        if not isinstance(out, tuple):
            return out
        # Ops that report overflow return it as a trailing 0-d bool scalar;
        # fold it into the caller's noop flag (reference kernels set
        # *noop_flag=1 on inf/nan and the caller reads it later).
        last = out[-1]
        if (noop_flag is not None and hasattr(last, "dtype")
                and getattr(last, "ndim", None) == 0
                and jnp.issubdtype(last.dtype, jnp.bool_)):
            return out[:-1] + (jnp.logical_or(noop_flag, last),)
        return out


multi_tensor_applier = MultiTensorApply(2048 * 32)
