"""apex_tpu.multi_tensor_apply — the L1 kernel-dispatch funnel.

API parity with ``apex.multi_tensor_apply`` (reference
apex/multi_tensor_apply/__init__.py and multi_tensor_apply.py:3-30): a
``multi_tensor_applier`` singleton through which the amp scaler, fused
optimizers, and the parallel layer invoke batched whole-model elementwise
ops.
"""

from apex_tpu.multi_tensor_apply.multi_tensor_apply import (
    MultiTensorApply,
    multi_tensor_applier,
)

__all__ = ["MultiTensorApply", "multi_tensor_applier"]
