"""apex_tpu.reparameterization — weight normalization (reference
apex/reparameterization/: ``apply_weight_norm`` via module hooks,
WeightNorm/Reparameterization classes).

Functional recast: a params-pytree transform. ``weight_norm_init`` splits
selected kernels into (g, v); ``reparameterize`` reconstitutes
w = g * v / ||v|| before apply — the same math as the reference's pre-forward
hook (weight_norm.py), expressed as a pure function the optimizer
differentiates through.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp

Tree = Any

_DEFAULT_PAT = re.compile(r"(kernel|weight)", re.IGNORECASE)


def _path_str(path) -> str:
    return "/".join(str(getattr(p, "key", getattr(p, "name", p)))
                    for p in path)


def _norm(v):
    # norm over all axes except the last (output features) — matching
    # torch weight_norm's default dim=0 on (out, in) == last-dim features
    # for flax (in, out) kernels.
    axes = tuple(range(v.ndim - 1))
    return jnp.sqrt(jnp.sum(jnp.square(v), axis=axes, keepdims=True))


def apply_weight_norm(params: Tree, name_pattern: str = None) -> Tree:
    """Split matching kernels w into {g: ||w||, v: w} (reference
    apply_weight_norm, __init__.py:3-49). Returns the reparameterized
    params tree where each matched leaf becomes {"wn_g", "wn_v"}."""
    pat = re.compile(name_pattern) if name_pattern else _DEFAULT_PAT

    def split(path, p):
        if (jnp.issubdtype(p.dtype, jnp.floating) and p.ndim >= 2
                and pat.search(_path_str(path))):
            return {"wn_g": _norm(p), "wn_v": p}
        return p

    return jax.tree_util.tree_map_with_path(split, params)


def _is_wn(x):
    return isinstance(x, dict) and set(x.keys()) == {"wn_g", "wn_v"}


def remove_weight_norm(params: Tree) -> Tree:
    """Collapse (g, v) back to w (reference remove_weight_norm)."""
    def join(x):
        if _is_wn(x):
            return x["wn_g"] * x["wn_v"] / (_norm(x["wn_v"]) + 1e-12)
        return x
    return jax.tree_util.tree_map(join, params, is_leaf=_is_wn)


def reparameterize(params: Tree) -> Tree:
    """Reconstitute effective weights for the forward pass — compose as
    ``model.apply({"params": reparameterize(p)}, x)``; gradients flow to
    (g, v) (the reference's pre-forward hook, reparameterization.py)."""
    return remove_weight_norm(params)


class WeightNorm:
    """Class shim mirroring the reference WeightNorm surface."""

    apply = staticmethod(apply_weight_norm)
    remove = staticmethod(remove_weight_norm)
    reparameterize = staticmethod(reparameterize)
