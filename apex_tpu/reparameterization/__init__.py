"""apex_tpu.reparameterization (placeholder — populated incrementally)."""
