"""apex_tpu.models (placeholder — populated incrementally)."""
