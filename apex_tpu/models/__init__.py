"""apex_tpu.models — model zoo for the BASELINE workloads (ResNet imagenet,
DCGAN multi-model, BERT pretrain) plus the long-context decoder LM."""

from apex_tpu.models.resnet import (ResNet, ResNet18, ResNet34, ResNet50,
                                    ResNet101, ResNet152)
from apex_tpu.models.dcgan import Generator, Discriminator
from apex_tpu.models.bert import BertEncoder, bert_base, bert_large
from apex_tpu.models.gpt import TransformerLM, GPTSmall, GPTTiny
