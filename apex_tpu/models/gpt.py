"""Decoder-only Transformer LM — the long-context flagship for the
framework's sequence-parallel stack (the reference has no model zoo or
distributed attention, SURVEY.md §5.7; this model exists so ring/Ulysses
attention, flash kernels, FusedLayerNorm, and fused softmax-xentropy have
an end-to-end consumer, the way examples/imagenet consumes amp+DDP).

Pre-LN blocks: x + Attn(LN(x)), x + MLP(LN(x)). Attention is
``contrib.multihead_attn.SelfMultiheadAttn`` (Pallas flash, fused
dropout); with ``seq_parallel='ring'|'ulysses'`` the model runs on
sequence shards under shard_map — every projection/LN/MLP is per-token
and stays local, only the attention communicates. Pass ``pos_offset``
(rank * local_seq) so learned position embeddings see global positions.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
import flax.linen as nn

from apex_tpu.contrib.multihead_attn import SelfMultiheadAttn
from apex_tpu.normalization import FusedLayerNorm
from apex_tpu.parallel.mesh import bound_axis_size


class Block(nn.Module):
    embed_dim: int
    num_heads: int
    mlp_ratio: int = 4
    dropout: float = 0.0
    dtype: Any = None
    seq_parallel: Optional[str] = None
    axis_name: Optional[str] = None
    # Megatron-style tensor parallelism over a mesh axis: heads shard in
    # attention, the MLP runs column(fc1)->row(fc2) parallel, and the
    # block pays exactly two psums (after out_proj, after fc2) — see
    # parallel/tensor_parallel.py for the param layout helpers.
    tensor_parallel_axis: Optional[str] = None
    tensor_parallel_size: int = 1
    # Mixture-of-Experts MLP (Switch/GShard; parallel/expert_parallel.py):
    # moe_num_experts > 0 replaces this block's dense MLP with MoEMLP;
    # experts optionally shard over an expert_parallel mesh axis.
    moe_num_experts: int = 0
    moe_num_selected: int = 2
    moe_capacity_factor: float = 1.25
    expert_parallel_axis: Optional[str] = None
    expert_parallel_size: int = 1
    # KV-cache decode (see SelfMultiheadAttn.decode / gpt.generate)
    decode: bool = False
    decode_max_len: int = 0
    decode_impl: str = "auto"
    # Learned attention position biases (SelfMultiheadAttn): T5-style
    # relative_bias and/or ALiBi — both train through the flash kernels'
    # dbias emission and decode through the cache path (the bias columns
    # are sliced at the running cache index).
    relative_bias: bool = False
    relative_bias_buckets: int = 32
    relative_bias_max_distance: int = 128
    alibi: bool = False
    alibi_learned: bool = False
    # ``deterministic`` can be fixed at construction time so that under
    # ``nn.remat`` it never becomes a traced argument (a traced bool cannot
    # drive the Python-level dropout branch in SelfMultiheadAttn). The
    # call-time kwarg still works for the non-remat path and wins when given.
    deterministic: Optional[bool] = None

    @nn.compact
    def __call__(self, x, *, deterministic: Optional[bool] = None,
                 dropout_rng=None):
        det = self.deterministic if deterministic is None else deterministic
        if det is None:
            det = True
        e = self.embed_dim
        h = SelfMultiheadAttn(
            embed_dim=e, num_heads=self.num_heads, dropout=self.dropout,
            causal=True, dtype=self.dtype, seq_parallel=self.seq_parallel,
            axis_name=self.axis_name,
            tensor_parallel_axis=self.tensor_parallel_axis,
            tensor_parallel_size=self.tensor_parallel_size,
            decode=self.decode, decode_max_len=self.decode_max_len,
            decode_impl=self.decode_impl,
            relative_bias=self.relative_bias,
            relative_bias_buckets=self.relative_bias_buckets,
            relative_bias_max_distance=self.relative_bias_max_distance,
            alibi=self.alibi, alibi_learned=self.alibi_learned,
            name="attn")(
            FusedLayerNorm(normalized_shape=e, name="ln1")(x)
            .astype(x.dtype),
            deterministic=det, dropout_rng=dropout_rng)
        x = x + h
        y = FusedLayerNorm(normalized_shape=e, name="ln2")(x).astype(x.dtype)
        if self.moe_num_experts:
            from apex_tpu.parallel.expert_parallel import MoEMLP
            if (self.tensor_parallel_axis is not None
                    and self.tensor_parallel_axis
                    == self.expert_parallel_axis):
                raise ValueError(
                    "tensor_parallel_axis and expert_parallel_axis must "
                    "be DIFFERENT mesh axes: EP assumes tokens are "
                    "sharded over its axis, but inside a TP region "
                    "activations are replicated over the model axis")
            # TP attention composes with an MoE MLP: the attn half above
            # already sharded heads over the model axis; the expert
            # exchange runs over its own axis
            y = MoEMLP(embed_dim=e, num_experts=self.moe_num_experts,
                       mlp_ratio=self.mlp_ratio,
                       num_selected=self.moe_num_selected,
                       capacity_factor=self.moe_capacity_factor,
                       dtype=self.dtype,
                       axis_name=self.expert_parallel_axis,
                       expert_parallel_size=self.expert_parallel_size,
                       name="moe")(y)
        elif self.tensor_parallel_axis:
            from apex_tpu.parallel.tensor_parallel import (
                RowParallelDense, tp_region_enter)
            if (self.mlp_ratio * e) % self.tensor_parallel_size:
                raise ValueError(
                    f"tensor_parallel_size ({self.tensor_parallel_size}) "
                    f"must divide the mlp width ({self.mlp_ratio * e})")
            # named scope for profiler attribution (pyprof.capture joins
            # trace kernels on it); flax module names already tag
            # attn/ln1/ln2/moe the same way
            with jax.named_scope("mlp"):
                y = tp_region_enter(y, self.tensor_parallel_axis)
                y = nn.Dense(
                    self.mlp_ratio * e // self.tensor_parallel_size,
                    dtype=self.dtype, name="fc1")(y)
                y = nn.gelu(y)
                # row-parallel: partial matmul -> g psum -> bias once
                y = RowParallelDense(e, self.tensor_parallel_axis,
                                     dtype=self.dtype, name="fc2")(y)
        else:
            with jax.named_scope("mlp"):
                y = nn.Dense(self.mlp_ratio * e, dtype=self.dtype,
                             name="fc1")(y)
                y = nn.gelu(y)
                y = nn.Dense(e, dtype=self.dtype, name="fc2")(y)
        return x + y


class TransformerLM(nn.Module):
    """``TransformerLM(vocab, layers, embed_dim, heads)``; __call__ maps
    (B, S) int tokens -> (B, S, vocab) fp32 logits."""

    vocab_size: int
    num_layers: int
    embed_dim: int
    num_heads: int
    max_seq: int = 4096
    mlp_ratio: int = 4
    dropout: float = 0.0
    dtype: Any = None
    seq_parallel: Optional[str] = None
    axis_name: Optional[str] = None
    tensor_parallel_axis: Optional[str] = None
    tensor_parallel_size: int = 1
    # KV-cache autoregressive decoding: clone the trained model with
    # ``decode=True`` (``decode_max_len`` defaults to max_seq) and drive
    # it with :func:`generate` — the prompt prefills the cache in ONE
    # forward (chunked write at the running index), then each new token
    # is a 1-token step attending over the cache. ``decode_impl``:
    # 'auto' (default: by cache length) | 'einsum' (XLA chain) |
    # 'fused' (one Pallas call per step with dead-block DMA elision —
    # see SelfMultiheadAttn.decode_impl).
    decode: bool = False
    decode_max_len: int = 0
    decode_impl: str = "auto"
    # MoE: every ``moe_every``-th block swaps its dense MLP for a
    # moe_num_experts-way MoEMLP (Switch places MoE in alternating
    # blocks; moe_every=1 makes every block sparse)
    moe_num_experts: int = 0
    moe_every: int = 2
    moe_num_selected: int = 2
    moe_capacity_factor: float = 1.25
    expert_parallel_axis: Optional[str] = None
    expert_parallel_size: int = 1
    # Learned attention position biases, every block (see Block). With
    # either on, the learned ABSOLUTE position embedding defaults off
    # (T5 / ALiBi convention: position information lives entirely in
    # the attention bias; override with learned_pos_emb=True).
    relative_bias: bool = False
    relative_bias_buckets: int = 32
    relative_bias_max_distance: int = 128
    alibi: bool = False
    alibi_learned: bool = False
    learned_pos_emb: Optional[bool] = None
    # Tie the LM head to the token embedding (logits = h @ E^T, no
    # separate head kernel/bias) — the standard weight-tying lever:
    # at 32k vocab x 768 it removes a 25M-param matrix
    tie_embeddings: bool = False
    # Rematerialize each block in the backward (jax.checkpoint): activation
    # memory drops from O(layers * S * D) to O(S * D), trading one extra
    # forward per block — the standard long-context lever (SURVEY.md §7:
    # "use jax.checkpoint / rematerialisation to trade FLOPs for memory").
    remat: bool = False

    @nn.compact
    def __call__(self, tokens, *, pos_offset=0, deterministic: bool = True,
                 dropout_rng=None, return_hidden: bool = False):
        if (self.moe_num_experts and self.tensor_parallel_axis is not None
                and self.tensor_parallel_axis == self.expert_parallel_axis):
            # checked here (before any block) so the error beats the
            # attention TP psum's unbound-axis failure under init
            raise ValueError(
                "tensor_parallel_axis and expert_parallel_axis must be "
                "DIFFERENT mesh axes: EP assumes tokens are sharded over "
                "its axis, but inside a TP region activations are "
                "replicated over the model axis")
        b, s = tokens.shape
        tok_emb = nn.Embed(self.vocab_size, self.embed_dim,
                           dtype=self.dtype, name="tok_emb")
        emb = tok_emb(tokens)
        pos_emb = (not (self.relative_bias or self.alibi)
                   if self.learned_pos_emb is None
                   else self.learned_pos_emb)
        if pos_emb:
            pos = pos_offset + jnp.arange(s)
            emb = emb + nn.Embed(self.max_seq, self.embed_dim,
                                 dtype=self.dtype,
                                 name="pos_emb")(pos)[None]
        x = emb
        # deterministic is baked into the module (static) rather than passed
        # per call: under nn.remat a call kwarg is traced, and a traced bool
        # cannot select the dropout branch (ADVICE r2: remat+dropout crash).
        block_cls = nn.remat(Block) if self.remat else Block
        for i in range(self.num_layers):
            moe = (self.moe_num_experts
                   if self.moe_num_experts
                   and i % self.moe_every == self.moe_every - 1 else 0)
            x = block_cls(self.embed_dim, self.num_heads, self.mlp_ratio,
                          self.dropout, self.dtype, self.seq_parallel,
                          self.axis_name,
                          tensor_parallel_axis=self.tensor_parallel_axis,
                          tensor_parallel_size=self.tensor_parallel_size,
                          decode=self.decode,
                          decode_max_len=(self.decode_max_len
                                          or self.max_seq),
                          decode_impl=self.decode_impl,
                          relative_bias=self.relative_bias,
                          relative_bias_buckets=self.relative_bias_buckets,
                          relative_bias_max_distance=(
                              self.relative_bias_max_distance),
                          alibi=self.alibi,
                          alibi_learned=self.alibi_learned,
                          moe_num_experts=moe,
                          moe_num_selected=self.moe_num_selected,
                          moe_capacity_factor=self.moe_capacity_factor,
                          expert_parallel_axis=self.expert_parallel_axis,
                          expert_parallel_size=self.expert_parallel_size,
                          deterministic=deterministic,
                          name=f"block_{i}")(x, dropout_rng=dropout_rng)
        x = FusedLayerNorm(normalized_shape=self.embed_dim,
                           name="ln_f")(x).astype(x.dtype)
        if return_hidden:
            # final hidden states for chunked_next_token_loss: the LM head
            # runs per sequence chunk there, so the full (S, vocab) logits
            # never materialize (at 128k x 32k-vocab, fp32 logits alone
            # are ~17 GB — the single-chip context cap without chunking).
            # Tied models pass {"kernel": params["tok_emb"]["embedding"].T}
            # as the chunked head params.
            return x
        if self.tie_embeddings:
            logits = tok_emb.attend(x)     # h @ E^T, shared table
        else:
            logits = nn.Dense(self.vocab_size, dtype=self.dtype,
                              name="head")(x)
        return logits.astype(jnp.float32)


def _shifted_targets(tokens, axis_name: Optional[str]):
    """(targets, valid, den): next-token targets with the shard-boundary
    shift, the validity mask (the last GLOBAL position has no target), and
    the global target count. Dense: targets[:, i] = tokens[:, i+1], last
    column invalid. Seq-parallel: each shard's final position predicts the
    FIRST token of the NEXT shard (ppermuted in)."""
    b, s_loc = tokens.shape
    if axis_name is None:
        targets = jnp.concatenate(
            [tokens[:, 1:], tokens[:, :1]], axis=1)
        col = jnp.arange(s_loc)
        valid = jnp.broadcast_to(
            jnp.where(col == s_loc - 1, 0.0, 1.0)[None, :], (b, s_loc))
        return targets, valid, jnp.asarray(b * (s_loc - 1), jnp.float32)
    world = bound_axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    # device r receives the first token of shard r+1 (source r+1 -> dest r)
    perm = [((j + 1) % world, j) for j in range(world)]
    nxt = jax.lax.ppermute(tokens[:, :1], axis_name, perm)
    targets = jnp.concatenate([tokens[:, 1:], nxt], axis=1)   # (B, S_loc)
    col = jnp.arange(s_loc)
    valid = jnp.broadcast_to(
        jnp.where((rank == world - 1) & (col == s_loc - 1),
                  0.0, 1.0)[None, :], (b, s_loc))
    den = jax.lax.psum(jnp.sum(valid), axis_name)
    return targets, valid, den


def _globalize(local, axis_name: Optional[str]):
    """Replicated global VALUE, purely-LOCAL grad path: the psum rides
    behind stop_gradient so the cotangent never crosses a collective
    transpose (whose scaling depends on replication tracking). Each
    device's grad is exactly its shard's contribution to the dense
    objective — callers psum grads over ``axis_name`` for replicated
    params."""
    if axis_name is None:
        return local
    return local + jax.lax.stop_gradient(
        jax.lax.psum(local, axis_name) - local)


def next_token_loss(logits, tokens, axis_name: Optional[str] = None):
    """Mean next-token softmax cross-entropy, identical between the dense
    and sequence-parallel layouts.

    Dense (``axis_name=None``): ``logits[:, :-1]`` predicts
    ``tokens[:, 1:]``; mean over B·(S-1) targets.

    Sequence-parallel (called per-shard inside ``shard_map``): each shard's
    final position predicts the FIRST token of the NEXT shard, ppermuted
    in — no shard-boundary targets are dropped, unlike a per-shard
    ``logits[:, :-1]`` vs ``tokens[:, 1:]`` loss. The last global position
    (which has no next token) is masked out and the mean is normalized by
    the global target count via ``psum``, so the value equals the dense
    objective on the gathered sequence.
    """
    from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss
    # named scope: profiler traces attribute the xentropy + masking ops
    # to the loss bucket (pyprof.capture) — metadata only
    with jax.named_scope("loss"):
        targets, valid, den = _shifted_targets(tokens, axis_name)
        losses = softmax_cross_entropy_loss(logits, targets)
        local = jnp.sum(losses * valid) / den
        return _globalize(local, axis_name)


def chunked_next_token_loss(hidden, head_params, tokens, *,
                            chunk: int = 8192,
                            axis_name: Optional[str] = None):
    """:func:`next_token_loss` without ever materializing the full
    (S, vocab) logits: the LM head matmul + softmax-xentropy run per
    sequence chunk inside a ``jax.checkpoint``-wrapped ``lax.scan`` body,
    so peak memory is O(chunk·vocab) forward AND backward (the backward
    recomputes each chunk's logits). At 128k context x 32k vocab, fp32
    logits alone are ~17 GB — past a single chip's HBM; chunking removes
    that cap.

    ``hidden``: (B, S, D) final hidden states
    (``model.apply(..., return_hidden=True)``). ``head_params``: the head
    Dense params dict ({'kernel': (D, vocab)[, 'bias': (vocab,)]}).
    Same dense/seq-parallel target shifting as :func:`next_token_loss`.
    """
    from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss
    b, s, d = hidden.shape
    targets, valid, den = _shifted_targets(tokens, axis_name)
    chunk = min(chunk, s)
    if s % chunk:
        # Pad the sequence to a whole number of chunks instead of shrinking
        # the chunk (a gcd fallback degrades to chunk=1 for prime S, turning
        # the scan into S tiny head matmuls). Padded positions carry
        # valid=0, so they contribute nothing; ``den`` above is already the
        # unpadded target count.
        pad = chunk - s % chunk
        hidden = jnp.pad(hidden, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)))
        valid = jnp.pad(valid, ((0, 0), (0, pad)))
        s = s + pad
    n = s // chunk

    hid = hidden.reshape(b, n, chunk, d).transpose(1, 0, 2, 3)
    tgt = targets.reshape(b, n, chunk).transpose(1, 0, 2)
    val = valid.reshape(b, n, chunk).transpose(1, 0, 2)
    kernel = head_params["kernel"]
    bias = head_params.get("bias")

    @jax.checkpoint
    def body(acc, xs):
        h_c, t_c, v_c = xs
        logits = h_c @ kernel.astype(h_c.dtype)
        if bias is not None:
            logits = logits + bias.astype(logits.dtype)
        losses = softmax_cross_entropy_loss(
            logits.astype(jnp.float32), t_c)
        return acc + jnp.sum(losses * v_c), None

    # scope for profiler attribution: the scan body (head matmul +
    # xentropy) is traced inside it, so its kernels land in 'loss'
    with jax.named_scope("loss"):
        num, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32),
                              (hid, tgt, val))
        return _globalize(num / den, axis_name)


def generate(model: TransformerLM, params, prompt, max_new_tokens: int,
             *, temperature: float = 0.0, rng=None, top_k: int = 0,
             top_p: float = 0.0, eos_token_id: Optional[int] = None,
             pad_token_id: int = 0, decode_max_len: int = 0):
    """Autoregressive KV-cache generation. ``prompt``: (B, S_p) int32.
    Returns (B, S_p + max_new_tokens) — the prompt with the generated
    continuation appended. ``temperature=0`` is greedy argmax; otherwise
    categorical sampling at that temperature (``rng`` required),
    optionally truncated: ``top_k`` keeps the k highest logits,
    ``top_p`` nucleus-truncates to the smallest set with cumulative
    probability ≥ p (both static-shape: a sort + threshold mask, never
    a dynamic gather). With ``eos_token_id``, sequences that emit EOS
    fill their remaining positions with ``pad_token_id`` (the scan
    shape stays static — finished sequences keep stepping but their
    outputs are masked, the standard jit-compatible early-stop).

    TPU-native decode: the prompt prefills every layer's K/V cache in
    ONE full forward (a chunked ``dynamic_update_slice`` at the running
    cache index), then each new token runs a 1-token step inside a
    ``lax.scan`` — static shapes, every step attends over the full
    ``decode_max_len`` window under the index-offset causal mask. Wrap
    in ``jax.jit`` for dispatch-free loops (examples/gpt/train_lm.py
    ``--generate`` does, and measures tokens/s).

    The reference framework has no generation/inference story (it is a
    training-utilities library); this is additive, like the model zoo
    it serves.
    """
    b, s_p = prompt.shape
    if max_new_tokens < 1:
        raise ValueError(
            f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if temperature <= 0.0 and (top_k > 0 or top_p > 0.0):
        # the greedy branch never reaches the truncation logic — silently
        # ignoring the flags would misreport what was sampled (ADVICE r4)
        raise ValueError(
            "top_k/top_p require temperature > 0 (temperature<=0 is "
            "greedy argmax, where truncation has no effect)")
    total = s_p + max_new_tokens
    max_len = decode_max_len or model.max_seq
    if total > max_len:
        raise ValueError(
            f"prompt ({s_p}) + max_new_tokens ({max_new_tokens}) "
            f"exceeds the cache ({max_len})")
    pos_table_active = (not (model.relative_bias or model.alibi)
                        if model.learned_pos_emb is None
                        else model.learned_pos_emb)
    if total > model.max_seq and pos_table_active:
        # positions past max_seq would clamp into the last learned
        # position embedding under jit — silent garbage, not an error.
        # Bias-positioned models (rel-bias/ALiBi without a position
        # table) have no such bound: length extrapolation past the
        # training max_seq is exactly their advertised capability, so
        # only decode_max_len caps them.
        raise ValueError(
            f"prompt ({s_p}) + max_new_tokens ({max_new_tokens}) "
            f"exceeds the model's position table (max_seq="
            f"{model.max_seq})")
    dec = model.clone(decode=True, decode_max_len=max_len, dropout=0.0,
                      remat=False)

    def sample(logits, key):
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(prompt.dtype)
        logits = logits.astype(jnp.float32) / temperature
        if top_k > 0 or top_p > 0.0:
            # ONE descending sort serves both truncations (the r4 code
            # sorted the 32k-entry vocab twice when both were on —
            # each sort is the dominant per-step sampling cost, see
            # BASELINE.md's sampled-decode price): top-k keeps logits
            # >= the k-th sorted entry; top-p's nucleus is computed on
            # the POST-top-k distribution (same semantics as the
            # sequential form) by masking sorted entries past k before
            # the cumulative softmax.
            srt = jnp.sort(logits, axis=-1)[..., ::-1]
            thresh = jnp.full_like(logits[..., :1], -jnp.inf)
            if top_k > 0:
                thresh = srt[..., top_k - 1][..., None]
                # VALUE-based masking, not positional: entries TIED
                # with the k-th value all survive top-k (that is what
                # `logits < kth` downstream keeps), so they must also
                # carry their mass into the nucleus softmax — a
                # positional pos<k mask would drop tied mass and move
                # the top-p cutoff on quantized/saturated logits
                srt = jnp.where(srt >= thresh, srt, -jnp.inf)
            if top_p > 0.0:
                cum = jnp.cumsum(jax.nn.softmax(srt, axis=-1), axis=-1)
                # smallest prefix with cumulative prob >= p stays: the
                # cutoff logit is the last sorted entry whose PRECEDING
                # cumulative mass is still < p
                keep = jnp.concatenate(
                    [jnp.ones_like(cum[..., :1], bool),
                     cum[..., :-1] < top_p], axis=-1)
                cutoff = jnp.min(jnp.where(keep, srt, jnp.inf), axis=-1,
                                 keepdims=True)
                thresh = jnp.maximum(thresh, cutoff)
            logits = jnp.where(logits < thresh, -jnp.inf, logits)
        return jax.random.categorical(
            key, logits, axis=-1).astype(prompt.dtype)

    if temperature > 0.0 and rng is None:
        raise ValueError("temperature > 0 requires rng")
    rng = jax.random.PRNGKey(0) if rng is None else rng

    # prefill: one forward over the whole prompt, cache written
    logits, vs = dec.apply({"params": params}, prompt,
                           mutable=["cache"])
    keys = jax.random.split(rng, max_new_tokens)
    tok0 = sample(logits[:, -1], keys[0])
    done0 = (jnp.zeros((b,), bool) if eos_token_id is None
             else tok0 == eos_token_id)

    def step(carry, xs):
        cache, tok, done = carry
        i, key = xs
        lg, v2 = dec.apply({"params": params, "cache": cache},
                           tok[:, None], pos_offset=s_p + i,
                           mutable=["cache"])
        nxt = sample(lg[:, -1], key)
        if eos_token_id is not None:
            nxt = jnp.where(done, jnp.asarray(pad_token_id, nxt.dtype),
                            nxt)
            done = done | (nxt == eos_token_id)
        return (v2["cache"], nxt, done), nxt

    # max_new - 1 steps: tok0 (position s_p) came from the prefill
    # logits, step i emits position s_p + i + 1 — no wasted final
    # forward whose sample would be discarded
    _, toks = jax.lax.scan(
        step, (vs["cache"], tok0, done0),
        (jnp.arange(max_new_tokens - 1), keys[1:]))
    gen = jnp.concatenate(
        [tok0[:, None], toks.T.astype(prompt.dtype)], axis=1)
    return jnp.concatenate([prompt, gen], axis=1)


GPTSmall = functools.partial(TransformerLM, num_layers=12, embed_dim=768,
                             num_heads=12)
GPTTiny = functools.partial(TransformerLM, num_layers=2, embed_dim=128,
                            num_heads=4)
