"""Decoder-only Transformer LM — the long-context flagship for the
framework's sequence-parallel stack (the reference has no model zoo or
distributed attention, SURVEY.md §5.7; this model exists so ring/Ulysses
attention, flash kernels, FusedLayerNorm, and fused softmax-xentropy have
an end-to-end consumer, the way examples/imagenet consumes amp+DDP).

Pre-LN blocks: x + Attn(LN(x)), x + MLP(LN(x)). Attention is
``contrib.multihead_attn.SelfMultiheadAttn`` (Pallas flash, fused
dropout); with ``seq_parallel='ring'|'ulysses'`` the model runs on
sequence shards under shard_map — every projection/LN/MLP is per-token
and stays local, only the attention communicates. Pass ``pos_offset``
(rank * local_seq) so learned position embeddings see global positions.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
import flax.linen as nn

from apex_tpu.contrib.multihead_attn import SelfMultiheadAttn
from apex_tpu.normalization import FusedLayerNorm


class Block(nn.Module):
    embed_dim: int
    num_heads: int
    mlp_ratio: int = 4
    dropout: float = 0.0
    dtype: Any = None
    seq_parallel: Optional[str] = None
    axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, *, deterministic: bool = True,
                 dropout_rng=None):
        e = self.embed_dim
        h = SelfMultiheadAttn(
            embed_dim=e, num_heads=self.num_heads, dropout=self.dropout,
            causal=True, dtype=self.dtype, seq_parallel=self.seq_parallel,
            axis_name=self.axis_name, name="attn")(
            FusedLayerNorm(normalized_shape=e, name="ln1")(x)
            .astype(x.dtype),
            deterministic=deterministic, dropout_rng=dropout_rng)
        x = x + h
        y = FusedLayerNorm(normalized_shape=e, name="ln2")(x).astype(x.dtype)
        y = nn.Dense(self.mlp_ratio * e, dtype=self.dtype, name="fc1")(y)
        y = nn.gelu(y)
        y = nn.Dense(e, dtype=self.dtype, name="fc2")(y)
        return x + y


class TransformerLM(nn.Module):
    """``TransformerLM(vocab, layers, embed_dim, heads)``; __call__ maps
    (B, S) int tokens -> (B, S, vocab) fp32 logits."""

    vocab_size: int
    num_layers: int
    embed_dim: int
    num_heads: int
    max_seq: int = 4096
    mlp_ratio: int = 4
    dropout: float = 0.0
    dtype: Any = None
    seq_parallel: Optional[str] = None
    axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, tokens, *, pos_offset=0, deterministic: bool = True,
                 dropout_rng=None):
        b, s = tokens.shape
        emb = nn.Embed(self.vocab_size, self.embed_dim,
                       dtype=self.dtype, name="tok_emb")(tokens)
        pos = pos_offset + jnp.arange(s)
        emb = emb + nn.Embed(self.max_seq, self.embed_dim,
                             dtype=self.dtype, name="pos_emb")(pos)[None]
        x = emb
        for i in range(self.num_layers):
            x = Block(self.embed_dim, self.num_heads, self.mlp_ratio,
                      self.dropout, self.dtype, self.seq_parallel,
                      self.axis_name, name=f"block_{i}")(
                x, deterministic=deterministic, dropout_rng=dropout_rng)
        x = FusedLayerNorm(normalized_shape=self.embed_dim,
                           name="ln_f")(x).astype(x.dtype)
        logits = nn.Dense(self.vocab_size, dtype=self.dtype,
                          name="head")(x)
        return logits.astype(jnp.float32)


def next_token_loss(logits, tokens, axis_name: Optional[str] = None):
    """Mean next-token softmax cross-entropy, identical between the dense
    and sequence-parallel layouts.

    Dense (``axis_name=None``): ``logits[:, :-1]`` predicts
    ``tokens[:, 1:]``; mean over B·(S-1) targets.

    Sequence-parallel (called per-shard inside ``shard_map``): each shard's
    final position predicts the FIRST token of the NEXT shard, ppermuted
    in — no shard-boundary targets are dropped, unlike a per-shard
    ``logits[:, :-1]`` vs ``tokens[:, 1:]`` loss. The last global position
    (which has no next token) is masked out and the mean is normalized by
    the global target count via ``psum``, so the value equals the dense
    objective on the gathered sequence.
    """
    from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss
    if axis_name is None:
        return jnp.mean(
            softmax_cross_entropy_loss(logits[:, :-1], tokens[:, 1:]))
    world = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    s_loc = tokens.shape[1]
    # device r receives the first token of shard r+1 (source r+1 -> dest r)
    perm = [((j + 1) % world, j) for j in range(world)]
    nxt = jax.lax.ppermute(tokens[:, :1], axis_name, perm)
    targets = jnp.concatenate([tokens[:, 1:], nxt], axis=1)   # (B, S_loc)
    losses = softmax_cross_entropy_loss(logits, targets)      # (B, S_loc)
    col = jnp.arange(s_loc)
    valid = jnp.where((rank == world - 1) & (col == s_loc - 1),
                      0.0, 1.0)[None, :]
    den = jax.lax.psum(jnp.sum(valid * jnp.ones_like(losses)), axis_name)
    local = jnp.sum(losses * valid) / den
    # Replicated global VALUE, purely-LOCAL grad path: the psum rides
    # behind stop_gradient so the cotangent never crosses a collective
    # transpose (whose scaling depends on replication tracking). Each
    # device's grad is exactly its shard's contribution to the dense
    # objective — callers psum grads over ``axis_name`` for replicated
    # params.
    return local + jax.lax.stop_gradient(
        jax.lax.psum(local, axis_name) - local)


GPTSmall = functools.partial(TransformerLM, num_layers=12, embed_dim=768,
                             num_heads=12)
GPTTiny = functools.partial(TransformerLM, num_layers=2, embed_dim=128,
                            num_heads=4)
