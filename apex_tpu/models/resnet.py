"""ResNet v1.5 in flax (NHWC, TPU-native layout) — the model behind the
reference's flagship config (examples/imagenet/main_amp.py uses torchvision
resnet; the model itself is standard, re-implemented here for TPU).

Supports swapping the norm layer for :class:`apex_tpu.parallel.SyncBatchNorm`
(the DDP+SyncBN 8-chip BASELINE config) via ``axis_name``.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import flax.linen as nn

from apex_tpu.parallel.sync_batchnorm import SyncBatchNorm

ModuleDef = Any


class ResNetBlock(nn.Module):
    filters: int
    norm: ModuleDef
    strides: Tuple[int, int] = (1, 1)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.filters, (3, 3), self.strides, padding=[(1, 1), (1, 1)],
                    use_bias=False, dtype=self.dtype)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), padding=[(1, 1), (1, 1)],
                    use_bias=False, dtype=self.dtype)(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters, (1, 1), self.strides,
                               use_bias=False, dtype=self.dtype)(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class BottleneckBlock(nn.Module):
    filters: int
    norm: ModuleDef
    strides: Tuple[int, int] = (1, 1)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.filters, (1, 1), use_bias=False,
                    dtype=self.dtype)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), self.strides,
                    padding=[(1, 1), (1, 1)], use_bias=False,
                    dtype=self.dtype)(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters * 4, (1, 1), use_bias=False,
                    dtype=self.dtype)(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters * 4, (1, 1), self.strides,
                               use_bias=False, dtype=self.dtype)(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.float32
    axis_name: Optional[str] = None   # set to sync BN stats over a mesh axis
    bn_momentum: float = 0.1

    @nn.compact
    def __call__(self, x, train: bool = True):
        # SyncBatchNorm for BOTH paths: with axis_name=None it is a local
        # fused BatchNorm (XLA-fused stats — measured faster than the
        # opt-in Pallas stats kernel inside a full train step, see
        # BASELINE.md dispatch-policy table — with torch momentum/
        # unbiased-var conventions); with an axis name, stats sync over
        # the mesh. Stats/normalization stay fp32 (keep_batchnorm_fp32)
        # while the output re-enters the bf16 compute stream via dtype.
        def norm_def(scale_init=nn.initializers.ones, name=None):
            return SyncBatchNorm(
                momentum=self.bn_momentum, axis_name=self.axis_name,
                use_running_average=not train, dtype=self.dtype,
                scale_init=scale_init, name=name)

        x = nn.Conv(self.num_filters, (7, 7), (2, 2),
                    padding=[(3, 3), (3, 3)], use_bias=False,
                    dtype=self.dtype, name="conv_init")(x)
        x = norm_def(name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, block_size in enumerate(self.stage_sizes):
            for j in range(block_size):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    self.num_filters * 2 ** i, norm=norm_def,
                    strides=strides, dtype=self.dtype)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)


ResNet18 = functools.partial(ResNet, stage_sizes=[2, 2, 2, 2],
                             block_cls=ResNetBlock)
ResNet34 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3],
                             block_cls=ResNetBlock)
ResNet50 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3],
                             block_cls=BottleneckBlock)
ResNet101 = functools.partial(ResNet, stage_sizes=[3, 4, 23, 3],
                              block_cls=BottleneckBlock)
ResNet152 = functools.partial(ResNet, stage_sizes=[3, 8, 36, 3],
                              block_cls=BottleneckBlock)
