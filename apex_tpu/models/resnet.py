"""ResNet v1.5 in flax (NHWC, TPU-native layout) — the model behind the
reference's flagship config (examples/imagenet/main_amp.py uses torchvision
resnet; the model itself is standard, re-implemented here for TPU).

Supports swapping the norm layer for :class:`apex_tpu.parallel.SyncBatchNorm`
(the DDP+SyncBN 8-chip BASELINE config) via ``axis_name``.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import flax.linen as nn

from apex_tpu.parallel.sync_batchnorm import SyncBatchNorm

ModuleDef = Any


class ResNetBlock(nn.Module):
    filters: int
    norm: ModuleDef
    strides: Tuple[int, int] = (1, 1)
    dtype: Any = jnp.float32
    # Opt-in fused conv epilogue: each conv's BN+ReLU (and the exit's
    # BN+residual-add+ReLU) runs as ONE Pallas pass instead of separate
    # memory-bound passes (ops/conv_epilogue.py). False (default) keeps
    # the exact pre-kernel op sequence.
    fused_epilogue: bool = False

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.filters, (3, 3), self.strides, padding=[(1, 1), (1, 1)],
                    use_bias=False, dtype=self.dtype)(x)
        if self.fused_epilogue:
            y = self.norm()(y, relu=True)
        else:
            y = self.norm()(y)
            y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), padding=[(1, 1), (1, 1)],
                    use_bias=False, dtype=self.dtype)(y)
        if self.fused_epilogue:
            if residual.shape != y.shape:
                residual = nn.Conv(self.filters, (1, 1), self.strides,
                                   use_bias=False, dtype=self.dtype)(residual)
                residual = self.norm(name="norm_proj")(residual)
            return self.norm(scale_init=nn.initializers.zeros)(
                y, residual=residual, relu=True)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters, (1, 1), self.strides,
                               use_bias=False, dtype=self.dtype)(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class BottleneckBlock(nn.Module):
    filters: int
    norm: ModuleDef
    strides: Tuple[int, int] = (1, 1)
    dtype: Any = jnp.float32
    fused_epilogue: bool = False    # see ResNetBlock

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.filters, (1, 1), use_bias=False,
                    dtype=self.dtype)(x)
        if self.fused_epilogue:
            y = self.norm()(y, relu=True)
        else:
            y = self.norm()(y)
            y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), self.strides,
                    padding=[(1, 1), (1, 1)], use_bias=False,
                    dtype=self.dtype)(y)
        if self.fused_epilogue:
            y = self.norm()(y, relu=True)
        else:
            y = self.norm()(y)
            y = nn.relu(y)
        y = nn.Conv(self.filters * 4, (1, 1), use_bias=False,
                    dtype=self.dtype)(y)
        if self.fused_epilogue:
            if residual.shape != y.shape:
                residual = nn.Conv(self.filters * 4, (1, 1), self.strides,
                                   use_bias=False, dtype=self.dtype)(residual)
                residual = self.norm(name="norm_proj")(residual)
            return self.norm(scale_init=nn.initializers.zeros)(
                y, residual=residual, relu=True)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters * 4, (1, 1), self.strides,
                               use_bias=False, dtype=self.dtype)(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


def space_to_depth(x: jax.Array, block: int = 2) -> jax.Array:
    """(N, H, W, C) -> (N, H/b, W/b, b*b*C), depth ordered (row-in-block,
    col-in-block, channel). The standard TPU input transform: a stride-2
    conv on a C=3 image keeps only 3 of 128 MXU lanes busy; after
    space-to-depth the stem contracts over b*b*... channels instead."""
    n, h, w, c = x.shape
    x = x.reshape(n, h // block, block, w // block, block, c)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(
        n, h // block, w // block, block * block * c)


def conv7_to_s2d_kernel(k7: jax.Array) -> jax.Array:
    """Map a (7, 7, C, O) stride-2 stem kernel to the exactly-equivalent
    (4, 4, 4C, O) kernel for the ``space_to_depth`` stem (block 2).

    out[p,q] = sum_{u,v,c} k7[u,v,c] x[2p-3+u, 2q-3+v, c]: pad the kernel
    to 8x8 with a zero top row/left column (u' = u+1, so 2p-4+u'), split
    u' = 2a+i into block index a and in-block row i, and the sum becomes a
    4x4 stride-1 conv over s2d blocks p-2..p+1 — i.e. padding (2, 1)."""
    k8 = jnp.pad(k7, ((1, 0), (1, 0), (0, 0), (0, 0)))
    c, o = k7.shape[2], k7.shape[3]
    return (k8.reshape(4, 2, 4, 2, c, o).transpose(0, 2, 1, 3, 4, 5)
            .reshape(4, 4, 4 * c, o))


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.float32
    axis_name: Optional[str] = None   # set to sync BN stats over a mesh axis
    bn_momentum: float = 0.1
    # "conv7": the reference 7x7/2 stem. "space_to_depth": the TPU MLPerf
    # stem — input space-to-depth (2x2 blocks) + an equivalent 4x4/1 conv
    # (see conv7_to_s2d_kernel for the exact weight correspondence).
    stem: str = "conv7"
    # Opt-in fused Pallas conv epilogue (BN+ReLU, and BN+residual+ReLU on
    # block exits) — ops/conv_epilogue.py; threaded to every block and
    # the stem BN. False (default) traces the exact pre-kernel program.
    fused_epilogue: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        # SyncBatchNorm for BOTH paths: with axis_name=None it is a local
        # fused BatchNorm (XLA-fused stats — measured faster than the
        # opt-in Pallas stats kernel inside a full train step, see
        # BASELINE.md dispatch-policy table — with torch momentum/
        # unbiased-var conventions); with an axis name, stats sync over
        # the mesh. Stats/normalization stay fp32 (keep_batchnorm_fp32)
        # while the output re-enters the bf16 compute stream via dtype.
        def norm_def(scale_init=nn.initializers.ones, name=None):
            return SyncBatchNorm(
                momentum=self.bn_momentum, axis_name=self.axis_name,
                use_running_average=not train, dtype=self.dtype,
                scale_init=scale_init, name=name,
                fused_epilogue=self.fused_epilogue)

        # jax.named_scope annotations ride into XLA op metadata, so
        # profiler traces (pyprof.capture) attribute kernels to stages
        # and blocks out of the box — the nvmarker wiring of the
        # reference pyprof, with zero runtime cost (metadata only)
        with jax.named_scope("stem"):
            if self.stem == "space_to_depth":
                x = space_to_depth(x, 2)
                x = nn.Conv(self.num_filters, (4, 4), (1, 1),
                            padding=[(2, 1), (2, 1)], use_bias=False,
                            dtype=self.dtype, name="conv_init")(x)
            elif self.stem == "conv7":
                x = nn.Conv(self.num_filters, (7, 7), (2, 2),
                            padding=[(3, 3), (3, 3)], use_bias=False,
                            dtype=self.dtype, name="conv_init")(x)
            else:
                raise ValueError(f"stem must be 'conv7' or "
                                 f"'space_to_depth', got {self.stem!r}")
            if self.fused_epilogue:
                x = norm_def(name="bn_init")(x, relu=True)
            else:
                x = norm_def(name="bn_init")(x)
                x = nn.relu(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2),
                            padding=((1, 1), (1, 1)))
        for i, block_size in enumerate(self.stage_sizes):
            for j in range(block_size):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                with jax.named_scope(f"stage{i + 1}/block{j}"):
                    x = self.block_cls(
                        self.num_filters * 2 ** i, norm=norm_def,
                        strides=strides, dtype=self.dtype,
                        fused_epilogue=self.fused_epilogue)(x)
        with jax.named_scope("head"):
            x = jnp.mean(x, axis=(1, 2))
            x = nn.Dense(self.num_classes, dtype=self.dtype,
                         name="head")(x)
        return x.astype(jnp.float32)


ResNet18 = functools.partial(ResNet, stage_sizes=[2, 2, 2, 2],
                             block_cls=ResNetBlock)
ResNet34 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3],
                             block_cls=ResNetBlock)
ResNet50 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3],
                             block_cls=BottleneckBlock)
ResNet101 = functools.partial(ResNet, stage_sizes=[3, 4, 23, 3],
                              block_cls=BottleneckBlock)
ResNet152 = functools.partial(ResNet, stage_sizes=[3, 8, 36, 3],
                              block_cls=BottleneckBlock)
