"""DCGAN generator/discriminator in flax (NHWC) — the models behind the
reference's multi-model/multi-loss amp example (examples/dcgan/main_amp.py:
two models, two optimizers, three backward passes per step exercising
``num_losses``/``loss_id`` amp plumbing)."""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
import flax.linen as nn


class Generator(nn.Module):
    """latent (B, 1, 1, nz) -> image (B, 64, 64, nc)."""

    nz: int = 100
    ngf: int = 64
    nc: int = 3
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, z, train: bool = True):
        bn = lambda name: nn.BatchNorm(use_running_average=not train,
                                       momentum=0.9, dtype=self.dtype,
                                       name=name)
        x = nn.ConvTranspose(self.ngf * 8, (4, 4), (1, 1), padding="VALID",
                             use_bias=False, dtype=self.dtype)(z)
        x = nn.relu(bn("bn0")(x))                        # 4x4
        x = nn.ConvTranspose(self.ngf * 4, (4, 4), (2, 2), padding="SAME",
                             use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(bn("bn1")(x))                        # 8x8
        x = nn.ConvTranspose(self.ngf * 2, (4, 4), (2, 2), padding="SAME",
                             use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(bn("bn2")(x))                        # 16x16
        x = nn.ConvTranspose(self.ngf, (4, 4), (2, 2), padding="SAME",
                             use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(bn("bn3")(x))                        # 32x32
        x = nn.ConvTranspose(self.nc, (4, 4), (2, 2), padding="SAME",
                             use_bias=False, dtype=self.dtype)(x)
        return jnp.tanh(x)                               # 64x64


class Discriminator(nn.Module):
    """image (B, 64, 64, nc) -> logit (B,)."""

    ndf: int = 64
    nc: int = 3
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        lrelu = lambda x: nn.leaky_relu(x, 0.2)
        bn = lambda name: nn.BatchNorm(use_running_average=not train,
                                       momentum=0.9, dtype=self.dtype,
                                       name=name)
        x = lrelu(nn.Conv(self.ndf, (4, 4), (2, 2), padding="SAME",
                          use_bias=False, dtype=self.dtype)(x))     # 32
        x = lrelu(bn("bn0")(nn.Conv(self.ndf * 2, (4, 4), (2, 2),
                                    padding="SAME", use_bias=False,
                                    dtype=self.dtype)(x)))          # 16
        x = lrelu(bn("bn1")(nn.Conv(self.ndf * 4, (4, 4), (2, 2),
                                    padding="SAME", use_bias=False,
                                    dtype=self.dtype)(x)))          # 8
        x = lrelu(bn("bn2")(nn.Conv(self.ndf * 8, (4, 4), (2, 2),
                                    padding="SAME", use_bias=False,
                                    dtype=self.dtype)(x)))          # 4
        x = nn.Conv(1, (4, 4), (1, 1), padding="VALID", use_bias=False,
                    dtype=self.dtype)(x)                            # 1x1
        return x.reshape(x.shape[0]).astype(jnp.float32)
