"""BERT-style transformer encoder built from the framework's own fused
pieces (FusedLayerNorm, fused MHA, fused MLP path, xentropy) — the model
behind the BASELINE "BERT-large pretrain, FusedLAMB + multi_tensor_l2norm
grad-clip, 32 chips" config. The reference ships no BERT model (apex is an
extension library); this is the canonical workload its DistributedFusedLAMB
was built for (distributed_fused_lamb.py BERT-scale docs).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import flax.linen as nn

from apex_tpu.normalization import FusedLayerNorm
from apex_tpu.contrib.multihead_attn import SelfMultiheadAttn


class TransformerLayer(nn.Module):
    hidden: int
    heads: int
    mlp_dim: int
    dropout: float = 0.0
    impl: str = "fast"
    dtype: Any = None

    @nn.compact
    def __call__(self, x, *, deterministic: bool = True):
        h = SelfMultiheadAttn(
            embed_dim=self.hidden, num_heads=self.heads, bias=True,
            dropout=self.dropout, impl=self.impl, dtype=self.dtype)(
                x, deterministic=deterministic)
        x = FusedLayerNorm(normalized_shape=self.hidden)(x + h)
        m = nn.Dense(self.mlp_dim, dtype=self.dtype)(x)
        m = nn.gelu(m)
        m = nn.Dense(self.hidden, dtype=self.dtype)(m)
        return FusedLayerNorm(normalized_shape=self.hidden)(x + m)


class BertEncoder(nn.Module):
    """Masked-LM encoder. bert-large: hidden=1024, layers=24, heads=16."""

    vocab_size: int = 30522
    hidden: int = 1024
    layers: int = 24
    heads: int = 16
    mlp_dim: int = 4096
    max_len: int = 512
    dropout: float = 0.0
    impl: str = "fast"
    dtype: Any = None

    @nn.compact
    def __call__(self, tokens, *, deterministic: bool = True):
        pos = jnp.arange(tokens.shape[1])
        x = nn.Embed(self.vocab_size, self.hidden, name="tok_emb")(tokens)
        x = x + nn.Embed(self.max_len, self.hidden, name="pos_emb")(pos)
        x = FusedLayerNorm(normalized_shape=self.hidden)(x)
        if self.dtype is not None:
            x = x.astype(self.dtype)
        for _ in range(self.layers):
            x = TransformerLayer(
                hidden=self.hidden, heads=self.heads, mlp_dim=self.mlp_dim,
                dropout=self.dropout, impl=self.impl, dtype=self.dtype)(
                    x, deterministic=deterministic)
        logits = nn.Dense(self.vocab_size, dtype=self.dtype,
                          name="mlm_head")(x)
        return logits.astype(jnp.float32)


def bert_large(**kw) -> BertEncoder:
    return BertEncoder(hidden=1024, layers=24, heads=16, mlp_dim=4096, **kw)


def bert_base(**kw) -> BertEncoder:
    return BertEncoder(hidden=768, layers=12, heads=12, mlp_dim=3072, **kw)
