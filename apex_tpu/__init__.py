"""apex_tpu — a TPU-native mixed-precision / fused-kernel / distributed training
framework with the capabilities of NVIDIA Apex (reference: /root/reference).

Built from scratch for TPU: JAX / XLA / Pallas / pjit. The reference's CUDA-era
mechanisms map onto TPU idioms:

  - ``apex.amp`` monkey-patched eager casts  -> trace-time dtype policy + function
    interposition on the jax.numpy namespace (O1/O4) and policy-driven parameter
    casting with fp32 master weights (O2/O5).
  - ``csrc/multi_tensor_*`` fused CUDA kernels -> Pallas TPU kernels over flat
    per-dtype parameter buckets (with pure-jnp fallbacks on CPU).
  - ``apex.parallel.DistributedDataParallel`` NCCL flat-bucket allreduce ->
    ``jax.lax.psum`` over a named mesh axis inside ``shard_map``/``pjit``; overlap
    is delegated to XLA's latency-hiding scheduler.
  - CUDA IPC / process groups -> mesh axis_index_groups on XLA collectives.

Reference layer map: see SURVEY.md at the repo root; top-level wiring mirrors
``apex/__init__.py:1-24`` of the reference.
"""

__version__ = "0.1.0"

from apex_tpu import _compat  # noqa: F401  (installs jax version shims)
from apex_tpu import checkpoint
from apex_tpu import ops
from apex_tpu import multi_tensor_apply
from apex_tpu import amp
from apex_tpu import optimizers
from apex_tpu import parallel
from apex_tpu import normalization
from apex_tpu import contrib
from apex_tpu import fp16_utils
from apex_tpu import mlp
from apex_tpu import rnn
from apex_tpu import reparameterization
from apex_tpu import sparsity
from apex_tpu import pyprof
from apex_tpu import telemetry
from apex_tpu import trace
from apex_tpu import tune
from apex_tpu import trainer
from apex_tpu import resilience
from apex_tpu import testing
