"""Static per-step communication accounting: bytes per collective, grouped
by mesh axis, from the program itself.

Why static: on TPU every collective a step will execute is visible in its
jaxpr at trace time — walking the equation graph (the same walker the lint
jaxpr pass uses, :mod:`apex_tpu.utils.jaxpr_walk`) yields an exact
per-step communication bill with zero runtime cost. This is the quantity
that motivates weight-update sharding in arXiv:2004.13336: whether ZeRO's
reduce-scatter + all-gather beats plain all-reduce for your model is a
bytes-per-axis comparison you can now read off before buying chip time.

Two byte figures per (axis, primitive):

  * ``bytes_in``   — payload entering the collective per device per step
    (operand bytes; for ``all_gather`` the shard each device contributes).
  * ``bytes_wire`` — estimated bytes each device moves on the
    interconnect under the standard ring algorithms:

      - all-reduce (psum/pmin/pmax)     2 (n-1)/n x bytes_in
      - reduce-scatter (psum_scatter)     (n-1)/n x bytes_in
      - all-gather                        (n-1)   x bytes_in
      - all-to-all                        (n-1)/n x bytes_in
      - ppermute / pshuffle                         bytes_in  (one hop)

    where n is the axis size — resolved from enclosing ``shard_map`` mesh
    params automatically, or passed via ``axis_sizes``. Unknown axis size
    leaves ``bytes_wire`` as None rather than guessing.

Loop handling mirrors pyprof's cost-analysis caveats: a ``lax.scan`` body
is multiplied by its static trip count; a ``lax.while_loop`` body is
counted ONCE (trip count unknowable — the result is a lower bound and the
record is flagged ``in_while=True``); both ``cond`` branches are counted
(upper bound).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from apex_tpu.utils.jaxpr_walk import (WalkContext, operand_bytes,
                                       walk_jaxpr_ctx)

# collective primitive -> wire multiplier builder (n = axis size)
_WIRE = {
    "psum": lambda n: 2.0 * (n - 1) / n,
    "pmin": lambda n: 2.0 * (n - 1) / n,
    "pmax": lambda n: 2.0 * (n - 1) / n,
    "psum_scatter": lambda n: (n - 1) / n,
    "reduce_scatter": lambda n: (n - 1) / n,
    "all_gather": lambda n: float(n - 1),
    "all_to_all": lambda n: (n - 1) / n,
    "ppermute": lambda n: 1.0,
    "pshuffle": lambda n: 1.0,
}
COLLECTIVE_PRIMS = frozenset(_WIRE)


@dataclasses.dataclass
class CommRecord:
    """Aggregate for one (axis, primitive) pair over one step."""

    axis: str
    primitive: str
    count: int = 0                    # executions per step (scan-scaled)
    bytes_in: float = 0.0             # per device per step
    bytes_wire: Optional[float] = 0.0  # None once any site lacks axis size
    in_while: bool = False            # any site inside a while body

    def to_meta(self) -> Dict[str, Any]:
        d = {"axis": self.axis, "primitive": self.primitive,
             "count": self.count}
        if self.bytes_wire is not None:
            d["bytes_wire"] = round(self.bytes_wire)
        if self.in_while:
            d["in_while"] = True
        return d


def _axis_names_of(params: dict) -> Tuple[str, ...]:
    names = params.get("axes", params.get("axis_name", ()))
    if isinstance(names, str):
        names = (names,)
    return tuple(n for n in (names or ()) if isinstance(n, str))


def _operand_bytes(eqn) -> float:
    return operand_bytes(eqn)    # jaxpr_walk: ONE byte definition


def _visit_collective(eqn, ctx: "WalkContext",
                      stats: Dict[Tuple[str, str], CommRecord]) -> None:
    prim = eqn.primitive.name
    if prim not in COLLECTIVE_PRIMS:
        return
    names = _axis_names_of(eqn.params)
    nbytes = _operand_bytes(eqn)
    # multi-axis collective: total world = product of sizes; the
    # bill is charged to each named axis with the joint world size
    # (sizes compose multiplicatively for ring cost estimation)
    world: Optional[int] = 1
    for name in names:
        n = ctx.axis_size(name)
        world = None if n is None or world is None else world * n
    # grouped collective: the ring runs within one replica
    # subset, so the effective world is the GROUP size, not the
    # axis size (and it is known even when the axis size is not
    # discoverable — adasum's pairwise levels bill as 2-member
    # all-reduces, not full-axis ones)
    groups = eqn.params.get("axis_index_groups")
    if groups is not None:
        try:
            world = len(groups[0]) or None
        except Exception:
            pass
    for name in names:
        rec = stats.setdefault(
            (name, prim), CommRecord(axis=name, primitive=prim))
        rec.count += ctx.loop_mult
        rec.bytes_in += ctx.loop_mult * nbytes
        rec.in_while = rec.in_while or ctx.in_while
        if rec.bytes_wire is not None and world and world > 0:
            rec.bytes_wire += ctx.loop_mult * nbytes * _WIRE[prim](world)
        else:
            rec.bytes_wire = None


def comm_stats(fn: Callable, *args,
               axis_sizes: Optional[Dict[str, int]] = None,
               **kwargs) -> List[CommRecord]:
    """Trace ``fn(*args, **kwargs)`` (no execution — avals suffice) and
    return per-(axis, primitive) communication records for ONE call.

    The traversal is :func:`~apex_tpu.utils.jaxpr_walk.walk_jaxpr_ctx` —
    the context walker threads the scan multipliers, while-body flags,
    and shard_map-resolved axis sizes this accounting needs (and the
    lint SPMD verifier shares the same sub-jaxpr discovery tier).

    ``axis_sizes`` pre-seeds axis-name -> size for programs whose mesh is
    not discoverable from the jaxpr (bare pmap bodies, check_entry-style
    fragments); sizes found on enclosing ``shard_map`` equations are
    picked up automatically and take precedence only where unset."""
    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    stats: Dict[Tuple[str, str], CommRecord] = {}
    seed = WalkContext(
        axis_sizes=tuple(sorted((axis_sizes or {}).items())))
    walk_jaxpr_ctx(closed.jaxpr,
                   lambda eqn, ctx: _visit_collective(eqn, ctx, stats),
                   seed)
    return sorted(stats.values(), key=lambda r: (r.axis, r.primitive))


def record_comm_stats(fn: Callable, *args,
                      axis_sizes: Optional[Dict[str, int]] = None,
                      name: str = "comm",
                      **kwargs) -> List[CommRecord]:
    """Run :func:`comm_stats` and emit one static event per record:
    ``{name}/{axis}/{primitive}_bytes`` with the wire estimate and count
    in meta. Returns the records (empty when telemetry is disabled —
    tracing is skipped entirely)."""
    from apex_tpu.telemetry import events as _ev
    from apex_tpu.telemetry.instrument import record_static
    if not _ev.enabled():
        return []
    records = comm_stats(fn, *args, axis_sizes=axis_sizes, **kwargs)
    for r in records:
        # dedup includes the byte/count payload: two DIFFERENT programs
        # sharing an (axis, primitive) pair (train + eval step) must both
        # land; only true re-traces of the same bill are collapsed
        record_static(f"{name}/{r.axis}/{r.primitive}_bytes", r.bytes_in,
                      meta=r.to_meta(),
                      dedup_key=(r.axis, r.primitive, r.bytes_in, r.count))
    return records


def format_comm(records: List[CommRecord]) -> str:
    """Human table of a comm bill (the summarize CLI's comm section)."""
    if not records:
        return "no collectives"
    lines = [f"{'axis':<10}{'collective':<16}{'count':>7}"
             f"{'bytes_in':>14}{'bytes_wire':>14}"]
    for r in records:
        wire = "?" if r.bytes_wire is None else f"{r.bytes_wire:,.0f}"
        flag = " (while: lower bound)" if r.in_while else ""
        lines.append(f"{r.axis:<10}{r.primitive:<16}{r.count:>7}"
                     f"{r.bytes_in:>14,.0f}{wire:>14}{flag}")
    return "\n".join(lines)
